"""Fault-tolerant KV-fabric transport: wire codec, lossy channel,
retry/backoff/breaker policy, and the fleet-wide chaos soak.

Coverage, one layer per block:

- codec: bit-exact round trips for page frames (fp32 AND int8 +
  scales), digest sets, and re-home records; the typed WireError
  taxonomy (truncated / corrupt / bad_version) drilled shape by shape;
  ``decode_frame`` total over seeded fuzz — nothing narrower than a
  WireError ever escapes.
- channel: a default channel is bytes-identical and order-preserving;
  a lossy channel is seed-deterministic (same seed, same fates).
- policy: the backoff+jitter formula golden, the breaker state machine
  golden (closed -> open at threshold, half-open probe, re-open/close),
  retries recover a lossy exchange, hedged reads win and are counted.
- faults: all four wire-grain points (``wire_drop`` / ``wire_corrupt``
  / ``wire_delay`` / ``peer_timeout``) drilled through a live
  Transport with exact counter accounting.
- fleet: the parity pin — a FleetRouter over a LOSSLESS channel is
  bit-identical to the in-process fleet (outputs, retirement classes,
  SyncTally count); a dead wire degrades page fetches to local
  re-prefill (``refetch_fallback`` hop, never FAILED) and re-homes
  fall back to the local copy (a lost frame can never lose a request).
- journeys: the three new hop kinds are a v1-compatible extension
  (old kinds unchanged), and the fleet simulator SKIPS-and-counts hop
  kinds newer than the build instead of refusing the dump — while
  ``validate_journey`` itself stays strict.
- chaos: the soak smoke in tier-1 (every fault point armed, invariants
  swept every step), the >=5-seed acceptance matrix @slow.

Everything runs on the shared virtual clock — sleep-free, deterministic.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import SyncTally
from paddle_tpu.obs.journey import JOURNEY_KINDS, validate_journey
from paddle_tpu.serving import (FaultInjector, FleetConfig, FleetRouter,
                                ServingConfig)
from paddle_tpu.serving.channel import (ChannelConfig, CircuitBreaker,
                                        SimChannel, Transport,
                                        TransportConfig, unit_hash)
from paddle_tpu.serving.chaos import (ChaosConfig, ChaosInvariantError,
                                      build_schedule, soak)
from paddle_tpu.serving.faults import POINTS
from paddle_tpu.serving.fleet_sim import replay_classes, simulate
from paddle_tpu.serving.kv_cache import SpilledPage
from paddle_tpu.serving.wire import (WIRE_ERROR_KINDS, RehomeRecord,
                                     WireCorruptError, WireError,
                                     WireTruncatedError,
                                     WireVersionError, decode_frame,
                                     encode_digests, encode_page,
                                     encode_rehome)
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.wire


class VirtualClock:
    """Integer-stepped fake clock shared by every replica: 1.0 s per
    read, so latency fields are exact float arithmetic."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


@pytest.fixture(scope="module")
def model():
    paddle.seed(41)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=48, dropout=0.0))
    m.eval()
    return m


_ENG = dict(max_batch=2, num_pages=20, page_size=4, max_prompt_len=8)


def _fleet(model, num_replicas=2, eng=None, injector=None, **fleet_kw):
    kw = dict(_ENG)
    kw.update(eng or {})
    cfg = FleetConfig(num_replicas=num_replicas,
                      engine=ServingConfig(**kw), **fleet_kw)
    return FleetRouter(model, cfg, clock=VirtualClock(),
                       fault_injector=injector)


def _lossless(seed=0, **kw):
    return Transport(SimChannel(ChannelConfig(seed=seed)),
                     TransportConfig(seed=seed, **kw))


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 97, (n,)).astype(np.int32)


def _page(seed=0, quantized=False):
    rng = np.random.RandomState(seed)
    shape = (2, 4, 2, 16)  # [layers, page, heads, head_dim]
    if quantized:
        k = rng.randint(-128, 128, shape).astype(np.int8)
        v = rng.randint(-128, 128, shape).astype(np.int8)
        ks = rng.rand(2, 2).astype(np.float32)
        vs = rng.rand(2, 2).astype(np.float32)
    else:
        k = rng.randn(*shape).astype(np.float32)
        v = rng.randn(*shape).astype(np.float32)
        ks = vs = None
    return SpilledPage(key=(seed, tuple(int(t) for t in
                                        rng.randint(0, 97, 4))),
                       serial=seed + 7, k=k, v=v, k_scale=ks, v_scale=vs)


# --------------------------------------------------------------- codec
@pytest.mark.parametrize("quantized", [False, True],
                         ids=["fp32", "int8"])
def test_page_frame_roundtrip_bit_exact(quantized):
    page = _page(seed=3, quantized=quantized)
    kind, got = decode_frame(encode_page(page))
    assert kind == "page"
    assert got.key == page.key and got.serial == page.serial
    for field in ("k", "v"):
        a, b = getattr(page, field), getattr(got, field)
        assert a.dtype == b.dtype and np.array_equal(a, b)
    if quantized:
        assert np.array_equal(got.k_scale, page.k_scale)
        assert np.array_equal(got.v_scale, page.v_scale)
        assert got.k.dtype == np.int8
    else:
        assert got.k_scale is None and got.v_scale is None


def test_digest_frame_roundtrip_is_canonical():
    digests = frozenset({2 ** 63 + 11, 5, 999983})
    frame = encode_digests(digests)
    kind, got = decode_frame(frame)
    assert kind == "digests" and got == digests
    # one set, one encoding: iteration order cannot leak into bytes
    assert frame == encode_digests(set(sorted(digests, reverse=True)))


@pytest.mark.parametrize("deadline", [None, 123.5])
def test_rehome_frame_roundtrip(deadline):
    prompt = _prompt(6, seed=9)
    kind, got = decode_frame(encode_rehome(41, prompt, 7, deadline,
                                           "tenant-β"))
    assert kind == "rehome" and isinstance(got, RehomeRecord)
    assert got.rid == 41 and got.max_new_tokens == 7
    assert got.deadline == deadline and got.tenant == "tenant-β"
    assert got.prompt.dtype == np.int32
    assert np.array_equal(got.prompt, prompt)


def test_wire_error_taxonomy():
    frame = encode_page(_page(seed=1))
    # truncation: envelope cut anywhere -> truncated
    with pytest.raises(WireTruncatedError) as e:
        decode_frame(frame[:5])
    assert e.value.kind == "truncated"
    with pytest.raises(WireTruncatedError):
        decode_frame(frame[:-3])
    # corruption: payload byte flip breaks the CRC
    flipped = bytearray(frame)
    flipped[len(flipped) // 2] ^= 0xA5
    with pytest.raises(WireCorruptError) as e:
        decode_frame(bytes(flipped))
    assert e.value.kind == "corrupt"
    # bytes past the declared trailer are corruption, not tolerance
    with pytest.raises(WireCorruptError):
        decode_frame(frame + b"x")
    # bad version byte / bad magic -> bad_version
    future = bytearray(frame)
    future[4] = 9
    with pytest.raises(WireVersionError) as e:
        decode_frame(bytes(future))
    assert e.value.kind == "bad_version"
    with pytest.raises(WireVersionError):
        decode_frame(b"NOPE" + frame[4:])
    # the taxonomy is closed: every raised kind is declared
    assert {"truncated", "corrupt", "bad_version"} == set(WIRE_ERROR_KINDS)
    for exc in (WireTruncatedError, WireCorruptError, WireVersionError):
        assert issubclass(exc, WireError)


def test_decode_frame_total_over_fuzz():
    # nothing narrower than WireError may escape, for ANY bytes
    rng = np.random.RandomState(7)
    frames = [encode_page(_page(2)), encode_digests({1, 2}),
              encode_rehome(1, _prompt(3), 2, None, "t")]
    for trial in range(400):
        base = frames[trial % 3]
        buf = bytearray(base)
        for _ in range(rng.randint(1, 4)):
            op = rng.randint(3)
            if op == 0 and len(buf) > 2:
                del buf[rng.randint(len(buf)):]
            elif op == 1 and buf:
                buf[rng.randint(len(buf))] ^= rng.randint(1, 256)
            else:
                buf += bytes(rng.randint(0, 256, rng.randint(1, 9),
                                         dtype=np.uint8))
        try:
            kind, _ = decode_frame(bytes(buf))
            assert kind in ("page", "digests", "rehome")
        except WireError as e:
            assert e.kind in WIRE_ERROR_KINDS


# -------------------------------------------------------------- channel
def test_default_channel_is_lossless_identity():
    ch = SimChannel()
    frames = [encode_digests({i}) for i in range(8)]
    arrivals = ch.transfer(0, frames)
    assert [d for _, d in arrivals] == frames  # bytes AND order
    assert ch.dropped == ch.corrupted == ch.duplicated \
        == ch.reordered == 0


def test_lossy_channel_is_seed_deterministic():
    cfg = ChannelConfig(seed=5, drop_rate=0.3, corrupt_rate=0.2,
                        dup_rate=0.2, reorder_rate=0.3, latency_s=0.01,
                        jitter_s=0.02)
    frames = [encode_digests({i}) for i in range(16)]
    a = [SimChannel(cfg).transfer(1, list(frames)) for _ in range(2)]
    assert a[0] == a[1]  # same seed -> same fates, byte for byte
    stats = SimChannel(cfg)
    stats.transfer(1, list(frames))
    assert stats.dropped + stats.corrupted > 0  # the rates are real


# --------------------------------------------------------------- policy
def test_backoff_golden():
    tr = _lossless(seed=11, backoff_s=0.02, backoff_max_s=0.1,
                   jitter_frac=0.5)
    for peer in (0, 3):
        for attempt in (1, 2, 3, 7):
            expect = min(
                0.02 * 2.0 ** (attempt - 1)
                * (1.0 + 0.5 * unit_hash(11, peer, attempt)), 0.1)
            assert tr.backoff_for(peer, attempt) == expect
    # jitter is per-(seed, peer, attempt): peers do not thundering-herd
    assert tr.backoff_for(0, 1) != tr.backoff_for(3, 1)


def test_breaker_state_machine_golden():
    br = CircuitBreaker(threshold=2, reset_s=1.0)
    assert br.state == "closed" and br.allow(0.0)
    assert not br.on_failure(0.0)          # 1 failure: still closed
    assert br.on_failure(0.5)              # 2nd opens
    assert br.state == "open"
    assert not br.allow(1.0) and br.blocked(1.0)
    assert br.allow(1.5)                   # past reset: half-open probe
    assert br.state == "half_open" and not br.blocked(1.5)
    assert br.on_failure(1.6)              # probe fails: re-open NOW
    assert br.state == "open"
    assert br.allow(2.7)
    assert br.on_success()                 # probe succeeds: closed
    assert br.state == "closed" and br.failures == 0


def test_retries_recover_a_lossy_exchange():
    tr = Transport(SimChannel(ChannelConfig(seed=3, drop_rate=0.3,
                                            corrupt_rate=0.1)),
                   TransportConfig(seed=3, retries=8, timeout_s=0.5,
                                   breaker_threshold=100))
    frames = [encode_digests({i}) for i in range(3)]
    ok = 0
    for _ in range(20):
        got = tr.exchange(0, frames)
        if got is not None:
            assert [v for _, v in got] == [frozenset({i})
                                           for i in range(3)]
            ok += 1
    assert ok == 20  # the retry budget rides out 40% loss
    assert tr.retries_total > 0
    assert tr.corrupt_total > 0  # corruption was seen, counted, retried


def test_hedge_wins_are_counted():
    tr = Transport(SimChannel(ChannelConfig(seed=9, drop_rate=0.3,
                                            latency_s=0.01,
                                            jitter_s=0.05)),
                   TransportConfig(seed=9, hedge=True, timeout_s=0.5,
                                   retries=4))
    frames = [encode_digests({5})]
    wins = 0
    for _ in range(40):
        got = tr.exchange(1, frames)
        assert got is not None
        wins += tr.last.hedge_win
    assert wins == tr.hedge_wins_total > 0


# ---------------------------------------------------------- fault points
def test_wire_fault_points_drilled():
    frames = [encode_digests({1})]
    # wire_drop: attempt loses every frame, retry recovers
    inj = FaultInjector().arm("wire_drop", rid=17)
    tr = _lossless(seed=1).attach(injector=inj)
    assert tr.exchange(0, frames, rid=17) is not None
    assert tr.last.retries == 1 and tr.retries_total == 1
    # wire_corrupt: typed decode failure, counted, retried
    inj = FaultInjector().arm("wire_corrupt", rid=17)
    tr = _lossless(seed=1).attach(injector=inj)
    assert tr.exchange(0, frames, rid=17) is not None
    assert tr.last.corrupt == 1 and tr.corrupt_total == 1
    # wire_delay: slow (not dead) peer -> timeout accounting
    inj = FaultInjector().arm("wire_delay", rid=17, delay_s=9.0)
    tr = _lossless(seed=1).attach(injector=inj)
    assert tr.exchange(0, frames, rid=17) is not None
    assert tr.last.timeouts == 1 and tr.timeouts_total == 1
    # peer_timeout: matched by PEER index, not request id
    inj = FaultInjector().arm("peer_timeout", rid=0)
    tr = _lossless(seed=1).attach(injector=inj)
    assert tr.exchange(0, frames, rid=17) is not None
    assert tr.last.timeouts == 1
    # exhausting the budget opens the breaker and fails the exchange
    inj = FaultInjector().arm("peer_timeout", rid=0, times=-1)
    tr = Transport(SimChannel(ChannelConfig(seed=1)),
                   TransportConfig(seed=1, retries=1,
                                   breaker_threshold=2)).attach(
                                       injector=inj)
    assert tr.exchange(0, frames) is None
    assert tr.exchange(0, frames) is None
    assert tr.peer_open(0)  # breaker open: affinity must degrade
    assert tr.exchange(0, frames) is None and tr.last.breaker_open
    assert [s for _, _, s in tr.breaker_events] == ["open"]


# ---------------------------------------------------------------- fleet
def test_lossless_wire_fleet_bit_identical_to_in_process(model):
    prompts = [_prompt(5 + i % 3, seed=i) for i in range(6)]

    def run(transport):
        fl = _fleet(model, num_replicas=2, transport=transport)
        rids = [fl.submit(p, 4) for p in prompts]
        with SyncTally() as tally:
            outs = fl.run()
        return ([outs[r] for r in rids],
                fl.retirement_class_counts(), tally.count)

    base_out, base_cls, base_tally = run(None)
    wire_out, wire_cls, wire_tally = run(_lossless(seed=7))
    for a, b in zip(base_out, wire_out):
        assert np.array_equal(a, b)  # outputs: bit-identical
    assert base_cls == wire_cls      # retirement classes: identical
    assert base_tally == wire_tally  # device syncs: identical


def test_dead_wire_page_fetch_degrades_never_fails(model):
    # a totally dead wire (every exchange dropped) must turn cross-
    # replica page fetches into local re-prefill — counted, stamped as
    # a refetch_fallback hop, and NEVER a FAILED retirement
    inj = FaultInjector()
    fl = _fleet(model, num_replicas=2, injector=inj,
                eng=dict(host_tier_bytes=1 << 20),
                transport=_lossless(seed=5), fetch_pages=True)
    warm = _prompt(8, seed=3)
    fl.submit(warm, 3)
    fl.run()                      # replica 0 is now warm + gossiped
    inj.arm("wire_drop", times=-1)  # kill the wire from here on
    rids = [fl.submit(warm, 3) for _ in range(5)]  # overflow spills
    outs = fl.run()
    assert sorted(outs) == sorted(rids)  # every request completed
    snap = fl.metrics.snapshot()
    assert snap["serving_wire_refetch_fallback_total"] > 0
    hops = {h["kind"] for rec in fl.journey_dump()
            for h in rec["hops"]}
    assert {"wire_retry", "refetch_fallback"} <= hops
    for rec in fl.journey_dump():
        validate_journey(rec)


@pytest.mark.faults
def test_rehome_rides_the_wire_and_survives_its_loss(model):
    # clean waiters on a dying replica re-home over the wire; when the
    # wire eats the frame, the LOCAL copy re-homes instead — no
    # composition of faults may lose an accepted request
    for drop in (0.0, 1.0):
        inj = FaultInjector().arm("replica_down", rid=1, step=2)
        tr = Transport(SimChannel(ChannelConfig(seed=3, drop_rate=drop)),
                       TransportConfig(seed=3))
        fl = _fleet(model, num_replicas=2, injector=inj, transport=tr)
        rids = [fl.submit(_prompt(5, seed=i), 3) for i in range(6)]
        outs = fl.run()
        retired = fl.pop_retired()
        for rid in rids:  # accounted exactly once, never lost
            assert (rid in outs) != (rid in retired)
        for rec in fl.journey_dump():
            validate_journey(rec)


def test_breaker_instants_on_their_own_trace_track(model):
    inj = FaultInjector().arm("peer_timeout", rid=0, times=-1)
    tr = Transport(SimChannel(ChannelConfig(seed=13)),
                   TransportConfig(seed=13, retries=0,
                                   breaker_threshold=1))
    fl = _fleet(model, num_replicas=2, transport=tr, injector=inj)
    fl.submit(_prompt(5, seed=1), 3)
    fl.run()
    doc = fl.export_chrome_trace()
    pid = len(fl.replicas) + 1  # the transport's own process track
    inst = [e for e in doc["traceEvents"]
            if e.get("pid") == pid and e.get("ph") == "i"]
    assert inst and all(e["s"] == "g" and
                        e["name"].startswith("breaker:")
                        for e in inst)
    assert len(inst) == len(tr.breaker_events)


# -------------------------------------------------------------- journeys
def test_new_hop_kinds_are_a_v1_extension():
    # the schema EXTENDS: new kinds appear, nothing moves or vanishes
    assert {"wire_retry", "refetch_fallback", "breaker_open"} \
        <= JOURNEY_KINDS
    assert {"enqueue", "routed", "admit", "retire",
            "shed"} <= JOURNEY_KINDS  # the v1 base is untouched


def test_fleet_sim_skips_and_counts_unknown_hop_kinds(model):
    fl = _fleet(model, num_replicas=2)
    for i in range(4):
        fl.submit(_prompt(5, seed=i), 3)
    fl.run()
    dump = fl.journey_dump()
    base = replay_classes(dump)
    # a NEWER writer minted a hop kind this build has never heard of
    dump[0] = dict(dump[0], hops=dump[0]["hops"] + [
        {"kind": "quantum_teleport", "step": 9, "t": 9.0}])
    assert replay_classes(dump) == base  # replay: skip, not refuse
    what_if = simulate(dump, replicas=2, slots=2)
    assert what_if["unknown_hops"] == 1  # ...and COUNTED, not silent
    # the strict gate itself is unchanged — tolerance lives in the
    # replayer, not in the schema validator
    with pytest.raises(ValueError, match="unknown journey hop kind"):
        validate_journey(dump[0])
    # broken grammar (not new vocabulary) still refuses the dump
    bad = [dict(dump[1], hops=dump[1]["hops"] + [{"kind": "x"}])]
    with pytest.raises(ValueError, match="missing"):
        replay_classes(bad)


# ----------------------------------------------------------------- chaos
def test_chaos_schedule_covers_every_fault_point():
    router, per = build_schedule(ChaosConfig(seed=0, num_replicas=3))
    armed = {a.point for a in router._arms}
    for inj in per:
        armed |= {a.point for a in inj._arms}
    assert armed == set(POINTS)


def test_chaos_soak_smoke(model):
    rep = soak(model, ChaosConfig(seed=0))
    assert rep["requests"] == 10
    assert sum(rep["classes"].values()) == rep["requests"]
    assert rep["goodput_tokens"] + rep["badput_tokens"] \
        == rep["tokens_total"]
    assert rep["faults_fired"]["router"] > 0
    assert rep["wire"]["retries"] > 0


def test_chaos_config_validates():
    with pytest.raises(ValueError, match="replicas"):
        ChaosConfig(num_replicas=1).validate()
    with pytest.raises(ValueError, match="requests"):
        ChaosConfig(requests=0).validate()
    assert issubclass(ChaosInvariantError, AssertionError)


@pytest.mark.slow
def test_chaos_soak_matrix(model):
    # the acceptance matrix: >=5 seeds, every POINTS entry armed, every
    # rid retired exactly once, ledger reconciled, invariants clean at
    # every step — soak() raises ChaosInvariantError otherwise
    for seed in range(5):
        rep = soak(model, ChaosConfig(seed=seed))
        assert sum(rep["classes"].values()) == rep["requests"]
        assert rep["goodput_tokens"] + rep["badput_tokens"] \
            == rep["tokens_total"]
