"""fluid.layers batch 3: the 1.x long tail (reference fluid/layers/*) —
activations, reductions, losses, resize, detection, LR decay, arrays, RNN."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

L = fluid.layers


def _t(a):
    return paddle.to_tensor(np.asarray(a, "float32"))


def test_activation_tail():
    x = _t([-2.0, -0.5, 0.5, 2.0])
    np.testing.assert_allclose(L.brelu(x, 0.0, 1.0).numpy(), [0, 0, 0.5, 1])
    assert L.leaky_relu(x, alpha=0.1).numpy()[0] == pytest.approx(-0.2)
    np.testing.assert_allclose(L.relu6(_t([7.0])).numpy(), [6.0])
    assert L.hard_sigmoid(x).numpy().min() >= 0
    assert L.soft_relu(x).numpy().min() > 0
    np.testing.assert_allclose(
        L.swish(x, beta=1.0).numpy(),
        (x.numpy() / (1 + np.exp(-x.numpy()))), rtol=1e-5)
    m = L.maxout(paddle.to_tensor(np.random.rand(1, 4, 2, 2).astype("float32")), 2)
    assert tuple(m.shape) == (1, 2, 2, 2)
    np.testing.assert_allclose(L.pow(_t([2.0]), 3).numpy(), [8.0])


def test_elementwise_and_reduce_tail():
    x, y = _t([4.0, 7.0]), _t([3.0, 2.0])
    np.testing.assert_allclose(L.elementwise_mod(x, y).numpy(), [1, 1])
    np.testing.assert_allclose(L.elementwise_floordiv(x, y).numpy(), [1, 3])
    np.testing.assert_allclose(L.elementwise_max(x, y).numpy(), [4, 7])
    np.testing.assert_allclose(L.elementwise_pow(x, y).numpy(), [64, 49])
    b = paddle.to_tensor(np.array([[True, False], [True, True]]))
    assert L.reduce_all(b).numpy() == False  # noqa: E712
    assert L.reduce_any(b).numpy() == True  # noqa: E712
    np.testing.assert_allclose(
        L.reduce_prod(_t([[2, 3], [4, 5]]), dim=1).numpy(), [6, 20])
    assert bool(L.has_nan(_t([np.nan, 1.0])).numpy())
    assert bool(L.has_inf(_t([np.inf])).numpy())
    assert not bool(L.isfinite(_t([np.inf])).numpy())


def test_comparison_and_logic():
    x, y = _t([1.0, 2.0]), _t([2.0, 2.0])
    assert list(L.less_than(x, y).numpy()) == [True, False]
    assert list(L.equal(x, y).numpy()) == [False, True]
    a = paddle.to_tensor(np.array([True, False]))
    b = paddle.to_tensor(np.array([True, True]))
    assert list(L.logical_xor(a, b).numpy()) == [False, True]


def test_tensor_tail():
    vals, ids = L.argsort(_t([3.0, 1.0, 2.0]))
    np.testing.assert_allclose(vals.numpy(), [1, 2, 3])
    assert list(ids.numpy()) == [1, 2, 0]
    assert L.eye(3).numpy().trace() == 3
    assert tuple(L.eye(2, 2, batch_shape=[4]).shape) == (4, 2, 2)
    np.testing.assert_allclose(L.reverse(_t([1, 2, 3]), 0).numpy(), [3, 2, 1])
    out = L.multiplex([_t([[1, 2]]), _t([[3, 4]])],
                      paddle.to_tensor(np.array([[1]], "int32")))
    np.testing.assert_allclose(out.numpy(), [[3, 4]])
    assert int(L.size(_t([[1, 2], [3, 4]])).numpy()) == 4
    assert int(L.rank(_t([[1.0]])).numpy()) == 2
    np.testing.assert_allclose(L.range(0, 6, 2, "int64").numpy(), [0, 2, 4])
    u, idx = L.unique(paddle.to_tensor(np.array([2, 3, 2], "int64")))
    assert sorted(u.numpy().tolist()) == [2, 3]
    padded = L.pad_constant_like(_t(np.zeros((3, 4))), _t(np.ones((2, 2))))
    assert tuple(padded.shape) == (3, 4)
    s = L.sums([_t([1.0]), _t([2.0]), _t([3.0])])
    np.testing.assert_allclose(s.numpy(), [6.0])


def test_loss_tail():
    pred = _t([[0.7, 0.3], [0.2, 0.8]])
    lbl = _t([[1.0, 0.0], [0.0, 1.0]])
    assert L.mse_loss(pred, lbl).numpy() >= 0
    assert L.square_error_cost(pred, lbl).numpy().shape == (2, 2)
    h = L.huber_loss(_t([0.1, 3.0]), _t([0.0, 0.0]), delta=1.0)
    np.testing.assert_allclose(h.numpy(), [0.005, 2.5], rtol=1e-5)
    sl = L.smooth_l1(_t([[0.1, 3.0]]), _t([[0.0, 0.0]]))
    assert sl.shape[-1] == 1
    ce = L.sigmoid_cross_entropy_with_logits(_t([[0.0, 2.0]]), lbl[:1])
    assert ce.numpy().shape == (1, 2)
    b = L.bpr_loss(_t([[0.5, 0.1, 0.4]]),
                   paddle.to_tensor(np.array([[0]], "int64")))
    assert b.numpy().shape == (1, 1)
    ts = L.teacher_student_sigmoid_loss(_t([[1.0]]), _t([[0.5]]))
    assert np.isfinite(ts.numpy()).all()
    rk = L.rank_loss(_t([[1.0]]), _t([[0.3]]), _t([[0.1]]))
    assert np.isfinite(rk.numpy()).all()
    cl = L.center_loss(_t(np.random.rand(4, 8)),
                       paddle.to_tensor(np.array([0, 1, 0, 2], "int64")),
                       num_classes=3, alpha=0.1)
    assert cl.numpy().shape == (4, 1)


def test_norm_and_similarity():
    x = _t(np.random.rand(2, 8))
    n = L.l2_normalize(x, axis=1)
    np.testing.assert_allclose(np.linalg.norm(n.numpy(), axis=1), [1, 1],
                               rtol=1e-5)
    c = L.cos_sim(x, x)
    np.testing.assert_allclose(c.numpy(), np.ones((2, 1)), rtol=1e-5)
    clipped = L.clip_by_norm(_t([3.0, 4.0]), 1.0)
    np.testing.assert_allclose(np.linalg.norm(clipped.numpy()), 1.0,
                               rtol=1e-5)


def test_resize_family():
    x = paddle.to_tensor(np.random.rand(1, 3, 8, 8).astype("float32"))
    assert tuple(L.resize_bilinear(x, [16, 16]).shape) == (1, 3, 16, 16)
    assert tuple(L.resize_nearest(x, [4, 4]).shape) == (1, 3, 4, 4)
    assert tuple(L.image_resize_short(x, 16).shape) == (1, 3, 16, 16)


def test_vision_tail():
    x = paddle.to_tensor(np.random.rand(2, 4, 4, 4).astype("float32"))
    assert tuple(L.shuffle_channel(x, 2).shape) == (2, 4, 4, 4)
    assert tuple(L.space_to_depth(x, 2).shape) == (2, 16, 2, 2)
    sc = _t(np.random.rand(4))
    out = L.affine_channel(x, scale=sc, bias=sc)
    assert tuple(out.shape) == (2, 4, 4, 4)
    cols = L.im2sequence(x, filter_size=2, stride=2)
    assert cols.shape[1] == 4  # (4/2)*(4/2) patches
    assert tuple(L.adaptive_pool2d(x, 2, "avg").shape) == (2, 4, 2, 2)


def test_detection_ops():
    boxes = _t([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]])
    iou = L.iou_similarity(boxes, boxes)
    np.testing.assert_allclose(np.asarray(iou.numpy()).diagonal(),
                               [1, 1, 1], rtol=1e-5)
    im_info = _t([[12.0, 12.0, 1.0]])
    clipped = L.box_clip(boxes, im_info)
    assert clipped.numpy().max() <= 11.0
    feat = paddle.to_tensor(np.zeros((1, 8, 4, 4), "float32"))
    img = paddle.to_tensor(np.zeros((1, 3, 64, 64), "float32"))
    pb, pv = L.prior_box(feat, img, min_sizes=[16], aspect_ratios=[1.0, 2.0],
                         flip=True)
    assert pb.shape[-1] == 4 and pv.shape == pb.shape
    an, av = L.anchor_generator(feat, [32, 64], [0.5, 1.0, 2.0],
                                [0.1, 0.1, 0.2, 0.2], [16.0, 16.0])
    assert an.shape[2] == 6
    d = _t([[0.9, 0.1], [0.2, 0.8], [0.3, 0.3]])
    mi, mv = L.bipartite_match(d)
    assert list(mi.numpy()) == [0, 1]
    scores = _t([[0.1, 0.2, 0.1], [0.9, 0.85, 0.05]])
    nmsd = L.multiclass_nms(boxes, scores, 0.3, 10, 5)
    assert nmsd.shape[-1] == 6


def test_lr_decay_constructors():
    import paddle_tpu.optimizer.lr as lr

    assert isinstance(L.noam_decay(64, 100), lr.NoamDecay)
    assert isinstance(L.exponential_decay(0.1, 100, 0.9), lr.ExponentialDecay)
    assert isinstance(L.exponential_decay(0.1, 100, 0.9, staircase=True),
                      lr.StepDecay)
    assert isinstance(L.piecewise_decay([100], [0.1, 0.01]),
                      lr.PiecewiseDecay)
    assert isinstance(L.cosine_decay(0.1, 10, 3), lr.CosineAnnealingDecay)
    assert isinstance(L.polynomial_decay(0.1, 100), lr.PolynomialDecay)
    w = L.linear_lr_warmup(0.1, 10, 0.0, 0.1)
    assert isinstance(w, lr.LinearWarmup)


def test_array_ops_and_counters():
    arr = L.create_array("float32")
    i0 = paddle.to_tensor(np.int64(0))
    L.array_write(_t([1.0, 2.0]), i0, arr)
    L.array_write(_t([3.0, 4.0]), paddle.to_tensor(np.int64(1)), arr)
    assert int(L.array_length(arr).numpy()) == 2
    np.testing.assert_allclose(L.array_read(arr, i0).numpy(), [1, 2])
    merged, sizes = L.tensor_array_to_tensor(arr, axis=0)
    assert tuple(merged.shape) == (4,)
    c1 = L.autoincreased_step_counter("t")
    c2 = L.autoincreased_step_counter("t")
    assert int(c2.numpy()) == int(c1.numpy()) + 1


def test_edit_distance_and_ctc_decode():
    a = paddle.to_tensor(np.array([[1, 2, 3, 0]], "int64"))
    b = paddle.to_tensor(np.array([[1, 3, 3, 0]], "int64"))
    d, n = L.edit_distance(a, b, normalized=False)
    assert d.numpy()[0, 0] == 1.0 and int(n.numpy()) == 1
    probs = _t(np.eye(4)[[1, 1, 0, 2]][None])  # blank=0: "1 1 _ 2" -> [1, 2]
    ids, lens = L.ctc_greedy_decoder(probs, blank=0)
    assert ids.numpy()[0, :2].tolist() == [1, 2]
    assert int(lens.numpy()[0]) == 2


@pytest.mark.slow
def test_rnn_api_tail():
    x = paddle.to_tensor(np.random.rand(2, 5, 8).astype("float32"))
    out = L.dynamic_gru(x, 16)
    assert tuple(out.shape) == (2, 5, 16)
    out, c = L.dynamic_lstm(x, 64)  # size = 4*hidden
    assert tuple(out.shape) == (2, 5, 16)
    cell = L.GRUCell(8, 16)
    o, h = L.rnn(cell, x)
    assert tuple(o.shape) == (2, 5, 16)
    hh, cc = L.lstm_unit(_t(np.random.rand(2, 8)),
                         _t(np.random.rand(2, 16)),
                         _t(np.random.rand(2, 16)))
    assert tuple(hh.shape) == (2, 16)


def test_assert_and_sampling():
    L.Assert(paddle.to_tensor(np.array([True])))
    with pytest.raises(ValueError, match="Assert"):
        L.Assert(paddle.to_tensor(np.array([False])), data=[_t([1.0])])
    ids = L.sampling_id(_t([[0.0, 1.0, 0.0]]), seed=3)
    assert int(ids.numpy()[0]) == 1
