"""OpTest batch 7: linalg family (reference test strategy SURVEY §4.1,
op_test.py protocol: eager + static paths vs numpy.linalg references,
finite-difference grad checks where the decomposition is differentiable
and well-conditioned)."""
import numpy as np

import paddle_tpu as paddle
from optest_batch_util import make_f32, make_mk

_mk = make_mk(globals(), default_atol=1e-4)
_r = np.random.RandomState(13)
_f32 = make_f32(_r)


def _spd(n, batch=()):
    """Symmetric positive-definite matrix (well-conditioned)."""
    a = _r.rand(*batch, n, n).astype("float32")
    return (a @ np.swapaxes(a, -1, -2) + n * np.eye(n, dtype="float32"))


_mk("TestCholeskyOp", paddle.linalg.cholesky,
    lambda: {"x": _spd(4)},
    lambda x: np.linalg.cholesky(x),
    grads=("x",), grad_rtol=5e-2, grad_atol=1e-3)

_mk("TestDetOp", paddle.linalg.det,
    lambda: {"x": _spd(3)},
    lambda x: np.linalg.det(x).astype("float32"),
    grads=("x",), rtol=1e-4)

_mk("TestSlogdetOp",
    lambda x: paddle.linalg.slogdet(x),
    lambda: {"x": _spd(3)},
    lambda x: np.stack(np.linalg.slogdet(x)).astype("float32"))

_mk("TestInvOp", paddle.linalg.inv,
    lambda: {"x": _spd(4)},
    lambda x: np.linalg.inv(x),
    grads=("x",), grad_rtol=5e-2, grad_atol=1e-3)

_mk("TestPinvOp", paddle.linalg.pinv,
    lambda: {"x": _f32(5, 3)},
    lambda x: np.linalg.pinv(x), rtol=1e-3)

_mk("TestSolveOp", paddle.linalg.solve,
    lambda: {"x": _spd(4), "y": _f32(4, 2)},
    lambda x, y: np.linalg.solve(x, y),
    grads=("x", "y"), grad_rtol=5e-2, grad_atol=1e-3)

_mk("TestTriangularSolveOp", paddle.linalg.triangular_solve,
    lambda: {"x": np.tril(_spd(4)).astype("float32"), "y": _f32(4, 2)},
    lambda x, y, upper: np.linalg.solve(x, y),
    attrs={"upper": False})

_mk("TestCholeskySolveOp", paddle.linalg.cholesky_solve,
    lambda: {"x": _f32(4, 2), "y": np.linalg.cholesky(_spd(4))},
    # x given L solves (L L^T) out = x
    lambda x, y, upper: np.linalg.solve(y @ y.T, x),
    attrs={"upper": False}, rtol=1e-3)

_mk("TestMatrixPowerOp", paddle.linalg.matrix_power,
    lambda: {"x": _spd(3)},
    lambda x, n: np.linalg.matrix_power(x, n),
    attrs={"n": 3}, rtol=1e-3)

_mk("TestMatrixRankOp", paddle.linalg.matrix_rank,
    lambda: {"x": (np.outer(np.arange(1, 5), np.arange(1, 6))
                   .astype("float32"))},
    lambda x: np.int64(np.linalg.matrix_rank(x)))

_mk("TestCondOp", paddle.linalg.cond,
    lambda: {"x": _spd(3)},
    lambda x: np.linalg.cond(x).astype("float32"), rtol=1e-3)

_mk("TestMultiDotOp",
    lambda a, b, c: paddle.linalg.multi_dot([a, b, c]),
    lambda: {"a": _f32(3, 4), "b": _f32(4, 5), "c": _f32(5, 2)},
    lambda a, b, c: a @ b @ c,
    grads=("a", "b", "c"), atol=1e-5)

_mk("TestCovOp", paddle.linalg.cov,
    lambda: {"x": _f32(3, 8)},
    lambda x: np.cov(x).astype("float32"), rtol=1e-4)

_mk("TestCorrcoefOp", paddle.linalg.corrcoef,
    lambda: {"x": _f32(3, 8)},
    lambda x: np.corrcoef(x).astype("float32"), rtol=1e-4)


# decompositions: verify reconstruction / invariants rather than raw factors
# (factor sign/phase conventions differ legitimately between backends — the
# reference op tests do the same for svd/qr/eigh)
def _svd_recon(x):
    return x  # U S V^H must reconstruct x


_mk("TestSvdReconstructOp",
    lambda x: (lambda usv: usv[0] @ paddle.diag(usv[1]) @ usv[2])(
        paddle.linalg.svd(x, full_matrices=False)),
    lambda: {"x": _f32(4, 3)},
    _svd_recon, rtol=1e-3)

_mk("TestQrReconstructOp",
    lambda x: (lambda qr_: qr_[0] @ qr_[1])(paddle.linalg.qr(x)),
    lambda: {"x": _f32(4, 3)},
    lambda x: x, rtol=1e-3)

_mk("TestEighEigvalsOp",
    lambda x: paddle.linalg.eigvalsh(x),
    lambda: {"x": _spd(4)},
    lambda x: np.linalg.eigvalsh(x).astype("float32"), rtol=1e-3)

# lu's packed factors need a custom pivot-aware assertion, so it gets a
# plain test instead of an _mk class
def test_lu_reconstructs():
    import paddle_tpu as paddle

    x = _spd(4)
    lu, pivots = paddle.linalg.lu(np.asarray(x))
    lu = np.asarray(lu.numpy())
    piv = np.asarray(pivots.numpy())
    L = np.tril(lu, -1) + np.eye(4, dtype=lu.dtype)
    U = np.triu(lu)
    # apply pivots (1-based LAPACK ipiv: row i swapped with row piv[i]-1)
    perm = np.arange(4)
    for i, p in enumerate(piv):
        perm[[i, p - 1]] = perm[[p - 1, i]]
    recon = np.zeros_like(x)
    recon[perm] = (L @ U)
    np.testing.assert_allclose(recon, x, rtol=1e-3, atol=1e-3)


def test_lstsq_matches_numpy():
    import paddle_tpu as paddle

    a = _f32(6, 3)
    b = _f32(6, 2)
    sol = paddle.linalg.lstsq(np.asarray(a), np.asarray(b))[0]
    ref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(np.asarray(sol.numpy()), ref,
                               rtol=1e-3, atol=1e-4)


if __name__ == "__main__":
    import unittest

    unittest.main()
