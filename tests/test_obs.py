"""paddle_tpu.obs — per-request tracing, latency histograms, timeline
export.

Four layers of coverage:

- histogram goldens: bucket-edge ownership, percentile interpolation math,
  overflow clamping, pre-seeded presence (zeros before the first sample).
- trace completeness: every terminal state (finished / cancelled-waiting /
  cancelled-running / expired / failed / shed) leaves a summarizable
  trace, and BOTH preemption modes (recompute and swap) leave resumable
  traces whose TTFT stays anchored to the first token the client saw.
- exporters: Chrome trace_event JSON schema validation (the document
  Perfetto loads), Prometheus text exposition shape.
- overhead contract: tracing off costs ONE attribute check per event site
  (pinned by counting property reads, the fault-injector pin's idiom) and
  tracing ON adds ZERO host syncs to the decode loop (SyncTally pin).

Every engine scenario runs on a virtual clock — sleep-free, deterministic
timestamps.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import SyncTally
from paddle_tpu.obs import (Histogram, RequestTrace, StepTimeline, Tracer,
                            chrome_trace, latency_table, prometheus_text)
from paddle_tpu.serving import (FaultInjector, ServingConfig, ServingEngine,
                                ServingMetrics)
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.obs


class VirtualClock:
    """Strictly increasing fake engine clock: 1 ms per read."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 0.001
        return self.t


def _toy_model():
    paddle.seed(29)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=48, dropout=0.0))
    model.eval()
    return model


def _engine(model=None, clock=None, fault_injector=None, **overrides):
    kw = dict(max_batch=2, num_pages=20, page_size=4, max_prompt_len=8)
    kw.update(overrides)
    return ServingEngine(model or _toy_model(), ServingConfig(**kw),
                         clock=clock or VirtualClock(),
                         fault_injector=fault_injector)


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 97, (n,)).astype(np.int32)


# -------------------------------------------------------------- histograms
def test_histogram_bucket_edges_golden():
    h = Histogram("h", (1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 3.0, 4.0, 8.0):
        h.observe(v)
    # bucket i owns (edges[i-1], edges[i]]: exact edge values fall LOW
    assert h.counts == [2, 1, 2, 1]
    assert h.count == 6 and h.sum == pytest.approx(18.0)
    assert h.mean == pytest.approx(3.0)


def test_histogram_percentile_interpolation_golden():
    h = Histogram("h", (10.0, 20.0, 30.0))
    for _ in range(10):
        h.observe(5.0)  # all ten samples in (0, 10]
    # rank q*count interpolated linearly inside the owning bucket
    assert h.percentile(0.50) == pytest.approx(5.0)
    assert h.percentile(0.90) == pytest.approx(9.0)
    assert h.percentile(0.99) == pytest.approx(9.9)
    assert h.percentile(1.00) == pytest.approx(10.0)
    for _ in range(10):
        h.observe(15.0)  # ten more in (10, 20]
    assert h.percentile(0.50) == pytest.approx(10.0)
    assert h.percentile(0.75) == pytest.approx(15.0)


def test_histogram_overflow_clamps_to_top_edge():
    h = Histogram("h", (1.0, 8.0))
    h.observe(1e9)
    # a runaway sample must not paint p50 as infinity
    assert h.percentile(0.5) == 8.0
    assert h.cumulative_buckets()[-1] == (float("inf"), 1)


def test_histogram_empty_and_validation():
    h = Histogram("h", (1.0, 2.0))
    assert h.percentile(0.99) == 0.0 and h.mean == 0.0
    snap = h.snapshot()
    assert snap == {"p50": 0.0, "p90": 0.0, "p99": 0.0,
                    "count": 0, "sum": 0.0, "mean": 0.0}
    with pytest.raises(ValueError):
        h.percentile(1.5)
    with pytest.raises(ValueError):
        Histogram("bad", (2.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("bad", (1.0,))


def test_histogram_cumulative_buckets_monotone():
    h = Histogram("h", (1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 9.0):
        h.observe(v)
    cums = [c for _, c in h.cumulative_buckets()]
    assert cums == sorted(cums) and cums[-1] == h.count


def test_metrics_percentile_gauges_pre_seeded():
    m = ServingMetrics()
    snap = m.snapshot()
    for hist in ("ttft_s", "tpot_s", "queue_wait_s", "e2e_s",
                 "step_duration_s", "batch_occupancy"):
        for q in ("p50", "p90", "p99"):
            assert snap[f"serving_{hist}_{q}"] == 0.0, (hist, q)
        assert snap[f"serving_{hist}_count"] == 0
    assert snap["serving_queue_depth_peak"] == 0
    assert snap["serving_page_pool_peak"] == 0


def test_metrics_observe_request_skips_none_fields():
    m = ServingMetrics()
    m.observe_request({"queue_wait": 0.5, "ttft": None, "tpot": None,
                       "e2e": 1.0})
    snap = m.snapshot()
    assert snap["serving_queue_wait_s_count"] == 1
    assert snap["serving_e2e_s_count"] == 1
    assert snap["serving_ttft_s_count"] == 0  # None skipped, not zero


# ------------------------------------------------------ trace completeness
def test_finished_trace_full_lifecycle():
    engine = _engine()
    rid = engine.add_request(_prompt(4), 4)
    engine.run()
    tr = engine.trace(rid)
    names = [e.name for e in tr.events]
    assert names == ["enqueued", "admitted", "prefill_start",
                     "prefill_end", "first_token", "retired"]
    assert tr.state == "finished" and tr.terminal
    s = tr.summary()
    assert s["state"] == "finished" and s["tokens"] == 4
    for k in ("queue_wait", "prefill_time", "ttft", "tpot", "e2e"):
        assert s[k] is not None and s[k] >= 0.0, k
    # the decomposition is internally consistent on a monotone clock
    assert s["e2e"] >= s["ttft"] >= s["queue_wait"]


def test_cancelled_while_waiting_trace_has_no_ttft():
    engine = _engine(max_batch=1)
    r1 = engine.add_request(_prompt(4), 8)
    r2 = engine.add_request(_prompt(5, seed=1), 8)
    engine.step()  # r1 occupies the only slot; r2 still queued
    assert engine.cancel(r2)
    tr = engine.trace(r2)
    assert [e.name for e in tr.events] == ["enqueued", "retired"]
    s = tr.summary()
    assert s["state"] == "cancelled"
    assert s["ttft"] is None and s["tpot"] is None \
        and s["queue_wait"] is None
    assert s["e2e"] is not None and s["e2e"] > 0.0
    engine.run()
    assert engine.trace(r1).state == "finished"


def test_cancelled_while_running_trace():
    engine = _engine()
    rid = engine.add_request(_prompt(4), 16)
    engine.step()
    engine.step()  # > 1 token generated before the cancel
    assert engine.cancel(rid)
    tr = engine.trace(rid)
    assert tr.state == "cancelled"
    assert tr.first("first_token") is not None
    s = tr.summary()
    assert s["ttft"] is not None and s["e2e"] is not None
    # TPOT is a decode-speed figure: a non-finished retirement happens at
    # an arbitrary later sweep, so it must NOT be derived from it even
    # with >= 2 tokens on record
    assert s["tokens"] > 1 and s["tpot"] is None


def test_expired_trace():
    clock = VirtualClock()
    engine = _engine(clock=clock)
    rid = engine.add_request(_prompt(4), 16, deadline_s=5.0)
    engine.step()
    clock.t += 60.0  # blow the deadline, sleep-free
    engine.step()
    tr = engine.trace(rid)
    assert tr.state == "expired"
    assert tr.summary()["e2e"] is not None


def test_failed_trace_prefill_fault():
    inj = FaultInjector().arm("prefill_fail", step=0)
    engine = _engine(fault_injector=inj)
    rid = engine.add_request(_prompt(4), 4)
    engine.run()
    tr = engine.trace(rid)
    assert tr.state == "failed"
    # the fault fires BEFORE the jitted prefill: no prefill span opened
    assert tr.first("prefill_start") is None
    assert tr.summary()["ttft"] is None


def test_shed_trace():
    engine = _engine(max_batch=1, max_waiting=1,
                     shed_policy="shed-oldest")
    engine.add_request(_prompt(4), 8)
    r2 = engine.add_request(_prompt(5, seed=1), 8)  # fills the queue
    r3 = engine.add_request(_prompt(6, seed=2), 8)  # sheds r2
    tr = engine.trace(r2)
    assert tr.state == "shed"
    assert [e.name for e in tr.events] == ["enqueued", "retired"]
    assert engine.trace(r3).state is None  # the newcomer lives


def _preemption_scenario(mode):
    # 3 usable pages of 8 tokens; r1 (4+8=12 tok -> 2 pages) and r2
    # (7+10=17 tok -> 3 pages) can't both peak: one MUST be preempted
    engine = _engine(max_batch=2, num_pages=4, page_size=8,
                     max_prompt_len=16, preemption_mode=mode)
    r1 = engine.add_request(_prompt(4), 8)
    r2 = engine.add_request(_prompt(7, seed=1), 10)
    outs = engine.run()
    assert set(outs) == {r1, r2}
    victim = next(t for t in (engine.trace(r1), engine.trace(r2))
                  if t.count("preempted"))
    return engine, victim


def test_recompute_preemption_leaves_resumable_trace():
    engine, tr = _preemption_scenario("recompute")
    assert tr.first("preempted").arg("mode") == "recompute"
    # the victim replayed from prefill: one more prefill span and one
    # more admission per preemption — one request, one trace, the whole
    # story
    n = tr.count("preempted")
    assert n >= 1
    assert tr.count("prefill_start") == n + 1
    assert tr.count("admitted") == n + 1
    assert tr.state == "finished"
    s = tr.summary()
    assert s["preemptions"] == n
    # TTFT anchors to the FIRST token the client saw, not the replay
    first_tok = tr.first("first_token")
    assert s["ttft"] == pytest.approx(
        first_tok.t - tr.first("enqueued").t)


def test_swap_preemption_leaves_resumable_trace():
    engine, tr = _preemption_scenario("swap")
    assert tr.first("preempted").arg("mode") == "swap"
    assert tr.count("swap_out") == 1 and tr.count("swap_in") == 1
    assert tr.count("resumed") == 1
    # swap keeps the generated tokens: no second prefill
    assert tr.count("prefill_start") == 1
    assert tr.state == "finished"
    assert tr.summary()["preemptions"] == 1
    snap = engine.metrics.snapshot()
    assert snap["serving_swap_outs"] == snap["serving_swap_ins"] >= 1


def test_decode_mark_cadence():
    engine = _engine(decode_mark_every=2)
    rid = engine.add_request(_prompt(4), 6)
    engine.run()
    tr = engine.trace(rid)
    marks = [e.arg("tokens") for e in tr.events
             if e.name == "decode_mark"]
    assert marks == [2, 4, 6]


def test_histograms_fed_from_traces():
    engine = _engine()
    for i in range(3):
        engine.add_request(_prompt(4, seed=i), 4)
    engine.run()
    snap = engine.metrics.snapshot()
    for hist in ("ttft_s", "tpot_s", "queue_wait_s", "e2e_s"):
        assert snap[f"serving_{hist}_count"] == 3, hist
        assert snap[f"serving_{hist}_p99"] > 0.0, hist
    assert snap["serving_step_duration_s_count"] > 0
    assert snap["serving_batch_occupancy_count"] > 0


# ----------------------------------------------------------- trace store
def test_tracer_evicts_only_terminal_traces():
    clock = VirtualClock()
    t = Tracer(clock, capacity=2)
    t.begin(1)
    t.event(1, "retired", state="finished", tokens=1)
    t.begin(2)  # live
    t.begin(3)  # over capacity: evicts rid 1 (oldest terminal)
    assert t.get(1) is None and t.evicted == 1
    assert t.get(2) is not None and t.get(3) is not None
    t.begin(4)  # all retained traces live: grows, corrupts nothing
    assert len(t) == 3 and t.evicted == 1
    # once the live burst retires, the store RECLAIMS down to capacity
    # (not one-per-insert: the high-water mark must not stick)
    for rid in (2, 3, 4):
        t.event(rid, "retired", state="finished", tokens=1)
    t.begin(5)
    assert len(t) == 2 and t.evicted == 3
    assert t.get(4) is not None and t.get(5) is not None  # newest survive


def test_tracer_ignores_unknown_rid():
    t = Tracer(VirtualClock(), capacity=2)
    t.event(99, "decode_mark")  # evicted/unknown: dropped, not raised
    assert len(t) == 0


def test_request_trace_helpers():
    tr = RequestTrace(7)
    tr.add("enqueued", 1.0)
    tr.add("decode_mark", 2.0, {"tokens": 2})
    tr.add("decode_mark", 3.0, {"tokens": 4})
    assert tr.first("decode_mark").t == 2.0
    assert tr.last("decode_mark").t == 3.0
    assert tr.count("decode_mark") == 2
    assert tr.first("missing") is None
    assert not tr.terminal


# -------------------------------------------------------------- exporters
def _chrome_doc(engine):
    doc = engine.export_chrome_trace()
    json.loads(json.dumps(doc))  # round-trips as real JSON
    return doc


def test_chrome_trace_schema():
    engine = _engine()
    rids = [engine.add_request(_prompt(4, seed=i), 4) for i in range(2)]
    engine.run()
    doc = _chrome_doc(engine)
    events = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms" and events
    for ev in events:
        assert ev["ph"] in ("X", "i", "M", "C"), ev
        assert isinstance(ev["name"], str) and ev["name"]
        assert ev["pid"] == 1
        assert isinstance(ev["tid"], int)
        if ev["ph"] != "M":
            assert ev["ts"] >= 0.0
        if ev["ph"] == "X":
            assert ev["dur"] >= 0.0
        if ev["ph"] == "i":
            # request-track instants are thread-scoped; watchdog alert
            # instants on the engine track are global
            assert ev["s"] in ("t", "g")
        if ev["ph"] == "C":
            # counter tracks: one numeric series per args key
            assert all(isinstance(v, (int, float))
                       for v in ev["args"].values()), ev
    # one named track per request + the engine loop
    threads = {ev["tid"]: ev["args"]["name"] for ev in events
               if ev["ph"] == "M" and ev["name"] == "thread_name"}
    assert threads[0] == "engine loop"
    for rid in rids:
        assert threads[rid + 1] == f"request {rid}"
    # the request phase spans and the engine step spans are all present
    span_names = {ev["name"] for ev in events if ev["ph"] == "X"}
    assert {"queued", "prefill", "decode"} <= span_names
    assert any(n in span_names for n in ("prefill+decode", "idle"))
    retired = [ev for ev in events if ev["ph"] == "i"
               and ev["name"].startswith("retired")]
    assert len(retired) == len(rids)


def test_chrome_trace_write_and_engine_track_args(tmp_path):
    engine = _engine()
    engine.add_request(_prompt(4), 3)
    engine.run()
    path = tmp_path / "trace.json"
    doc = engine.export_chrome_trace(path)
    assert json.loads(path.read_text()) == json.loads(json.dumps(doc))
    steps = [ev for ev in doc["traceEvents"]
             if ev.get("cat") == "engine" and ev["ph"] == "X"]
    assert len(steps) == len(engine.timeline)
    for ev in steps:
        for key in ("step", "batch", "prefills", "pages_in_use",
                    "queue_depth", "preemptions"):
            assert key in ev["args"], key


def test_prometheus_exposition_shape():
    engine = _engine()
    engine.add_request(_prompt(4), 4)
    engine.run()
    text = engine.metrics.prometheus()
    lines = text.strip().splitlines()
    assert "# TYPE serving_tokens_total counter" in lines
    assert "# TYPE serving_queue_depth gauge" in lines
    assert "# TYPE serving_ttft_s histogram" in lines
    # cumulative bucket series ends at +Inf == count
    inf = next(ln for ln in lines
               if ln.startswith('serving_ttft_s_bucket{le="+Inf"}'))
    count = next(ln for ln in lines if ln.startswith("serving_ttft_s_count"))
    assert inf.split()[-1] == count.split()[-1] == "1"
    # percentile mirrors are NOT double-exported as scalars
    assert not any(ln.startswith("serving_ttft_s_p50 ") for ln in lines)


def test_latency_table_renders():
    engine = _engine()
    engine.add_request(_prompt(4), 4)
    engine.run()
    table = latency_table(engine.latency_summaries())
    assert "queue_wait" in table and "ttft" in table
    assert "finished" in table


def test_chrome_trace_empty_inputs():
    doc = chrome_trace()
    assert [ev["ph"] for ev in doc["traceEvents"]] == ["M", "M"]
    assert prometheus_text({}).strip() == ""


# -------------------------------------------------------------- timeline
def test_timeline_ring_is_bounded():
    engine = _engine(timeline_capacity=4)
    engine.add_request(_prompt(4), 12)
    engine.run()
    tl = engine.timeline
    assert tl.total_steps > 4  # 12 decode steps happened...
    assert len(tl) == 4        # ...but only the newest 4 are retained
    recs = tl.records()
    assert [r.step for r in recs] == sorted(r.step for r in recs)
    assert recs[-1] is tl.last
    for r in recs:
        assert r.t_end >= r.t_start
        assert r.duration == r.t_end - r.t_start


def test_timeline_records_step_shape():
    engine = _engine()
    engine.add_request(_prompt(4), 3)
    engine.step()
    rec = engine.timeline.last
    assert rec.prefills == 1 and rec.admitted == 1 and rec.batch == 1
    assert rec.phase_mix() == "prefill+decode"
    assert rec.pages_in_use > 0
    assert rec.host_syncs is None  # debug_checks off
    engine.run()
    assert engine.timeline.last.finished == 1


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 budget; debug-checks host-sync counting stays
# pinned tier-1 by test_analysis's sync-accounting test and test_serving_tp's sync-free cert
def test_timeline_host_syncs_under_debug_checks():
    engine = _engine(debug_checks=True)
    engine.add_request(_prompt(4), 3)
    engine.step()
    # the step's syncs: the prefill first-token fetch + the decode fetch
    assert engine.timeline.last.host_syncs == 2
    engine.step()
    assert engine.timeline.last.host_syncs == 1  # decode fetch only
    with pytest.raises(ValueError):
        StepTimeline(0)


# ------------------------------------------------------ overhead contract
def test_obs_off_engine_surfaces_are_none():
    engine = _engine(enable_tracing=False)
    rid = engine.add_request(_prompt(4), 3)
    outs = engine.run()
    assert rid in outs
    assert engine.trace(rid) is None and engine.timeline is None
    assert engine.traces() == [] and engine.latency_summaries() == []
    doc = engine.export_chrome_trace()
    assert all(ev["ph"] == "M" for ev in doc["traceEvents"])
    snap = engine.metrics.snapshot()
    assert snap["serving_ttft_s_count"] == 0  # histograms ride traces


def test_obs_off_is_one_attribute_check_per_event_site():
    # the tracing analog of the fault-injector zero-overhead pin: with
    # tracing off, each event site costs exactly one read of ._tracer
    # (which is None) and nothing else
    class CountingEngine(ServingEngine):
        reads = 0

        @property
        def _tracer(self):
            CountingEngine.reads += 1
            return self.__dict__.get("_tracer_value")

        @_tracer.setter
        def _tracer(self, value):
            self.__dict__["_tracer_value"] = value

    engine = CountingEngine(_toy_model(), ServingConfig(
        max_batch=2, num_pages=20, page_size=4, max_prompt_len=8,
        enable_tracing=False), clock=VirtualClock())
    CountingEngine.reads = 0
    engine.add_request(_prompt(4), 3)
    assert CountingEngine.reads == 1  # the enqueue site
    CountingEngine.reads = 0
    engine.step()  # prefill site + decode site
    assert CountingEngine.reads == 2
    CountingEngine.reads = 0
    engine.step()  # decode site + the finish (retire) site
    assert CountingEngine.reads == 2


def test_tracing_on_adds_zero_host_syncs_to_decode_loop():
    # the acceptance pin: the SyncTally certification is UNCHANGED with
    # tracing enabled — one token fetch per step boundary, nothing else
    engine = _engine()
    assert engine.config.enable_tracing  # on by default
    for i in range(3):
        engine.add_request(_prompt(4, seed=i), 4)
    with SyncTally() as tally:
        engine.run()
    snap = engine.metrics.snapshot()
    fetches = int(snap["serving_decode_steps"]
                  + snap["serving_prefills_total"])
    assert tally.count == fetches, (tally.events, fetches)
    assert len(engine.traces()) == 3  # tracing really was on
