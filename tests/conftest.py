"""Test conftest: force an 8-device CPU mesh before jax initializes.

Mirrors the reference's device-backend test strategy (survey §4): CPU-parity
op tests + multi-device tests on a virtual mesh without real chips.
"""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle

    paddle.seed(102)
    np.random.seed(102)
    yield
