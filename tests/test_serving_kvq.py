"""Quantized paged KV cache (kv_dtype="int8") + host-memory prefix spill
tier. Pins the PR's contract end to end:

- fp32 stays the default and byte-identical (no scale leaves, same pool
  dtype, same kv_bytes_per_token math);
- int8 pools store codes + per-page-per-head scales, compile_counts are
  pinned EQUAL to fp32, the decode loop stays sync-free, and the greedy
  token streams diverge from fp32 by no more than a pinned bound on the
  tier-1 toy model (prefix-cache hit/cold parity is exact: cached pages
  hold exactly the codes a cold prefill would write);
- swap preemption and COW move codes + scales bit-exactly;
- the hlocheck artifact audits: every donated int8 pool + scale leaf is
  aliased, budgets (single-chip zero / TP 2L+1) are unchanged, and the
  quantized pool's donated/aliased HBM is < 0.3x fp32;
- the host tier: eviction spills refcount-0 indexed prefix pages (one
  batched gather per sweep), a later prefix hit restores them BIT-EXACTLY
  and counts as a prefix hit (prefill tokens saved pinned), the tier
  honors its byte bound, restore_fail retires only the affected request,
  and the spill/restore lifecycle shows up in traces + Chrome export.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import SyncTally
from paddle_tpu.analysis.hlocheck import audit_guard, run_step
from paddle_tpu.serving import (FaultInjector, HostTier, PagedCacheConfig,
                                PagedKVCache, ServingConfig, ServingEngine,
                                SpilledPage)
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.kvq


@pytest.fixture(scope="module")
def model():
    paddle.seed(1234)
    m = GPTForCausalLM(GPTConfig(vocab_size=97, hidden_size=32,
                                 num_layers=2, num_heads=2,
                                 max_seq_len=64, dropout=0.0))
    m.eval()
    return m


def _engine(model, **kw):
    cfg = dict(max_batch=2, num_pages=32, page_size=4, max_prompt_len=16)
    cfg.update(kw)
    return ServingEngine(model, ServingConfig(**cfg))


def _prompts(lens=(5, 9, 12)):
    rng = np.random.RandomState(3)
    return [rng.randint(0, 97, (n,)).astype(np.int32) for n in lens]


def _run_all(eng, prompts, new=6):
    for p in prompts:
        eng.add_request(p, new)
    outs = eng.run()
    return [outs[k] for k in sorted(outs)]


# ------------------------------------------------------------- validation
def test_kv_dtype_and_tier_validation(model):
    with pytest.raises(ValueError, match="kv_dtype"):
        _engine(model, kv_dtype="fp8")
    with pytest.raises(ValueError, match="kv_dtype"):
        PagedKVCache(PagedCacheConfig(num_layers=1, num_heads=1, head_dim=4,
                                      kv_dtype="int4"))
    with pytest.raises(ValueError, match="host_tier_bytes"):
        PagedKVCache(PagedCacheConfig(num_layers=1, num_heads=1, head_dim=4,
                                      host_tier_bytes=-1))
    # the tier spills INDEXED prefix pages: prefix caching is a hard dep
    with pytest.raises(ValueError, match="prefix"):
        _engine(model, host_tier_bytes=1 << 20,
                enable_prefix_caching=False)


def test_fp32_default_pools_unchanged(model):
    eng = _engine(model)
    for pl in eng.cache.pools:
        assert set(pl) == {"k_pool", "v_pool"}
        assert pl["k_pool"].dtype == np.float32
    assert eng.cache.cfg.kv_bytes_per_token == \
        2 * 2 * 2 * 16 * 4  # 2(kv) * layers * heads * head_dim * itemsize
    assert eng.cache.host_tier is None


def test_int8_pools_store_codes_and_scales(model):
    eng = _engine(model, kv_dtype="int8")
    for pl in eng.cache.pools:
        assert set(pl) == {"k_pool", "v_pool", "k_scale", "v_scale"}
        assert pl["k_pool"].dtype == np.int8
        assert pl["k_scale"].dtype == np.float32
        assert pl["k_scale"].shape == (32, 2)  # [num_pages, heads]
    # codes + amortized per-page scales: 4x+ under the fp32 figure
    q8 = eng.cache.cfg.kv_bytes_per_token
    assert q8 < 0.3 * (2 * 2 * 2 * 16 * 4)


# ------------------------------------------------- quality + compile pins
def test_int8_compile_counts_pinned_equal_fp32_and_sync_free(model):
    prompts = _prompts()
    e32 = _engine(model)
    o32 = _run_all(e32, prompts)
    e8 = _engine(model, kv_dtype="int8")
    for p in prompts:
        e8.add_request(p, 6)
    pre = e8.metrics.snapshot()
    with SyncTally() as tally:
        outs = e8.run()
    o8 = [outs[k] for k in sorted(outs)]
    # compile-once is quantization-blind: same guard counts, same dict
    assert e8.compile_counts == e32.compile_counts
    assert e8.compile_counts["decode"] == 1
    assert e8.cache.compile_counts == e32.cache.compile_counts
    # the sync-free certification formula is unchanged in int8 mode
    snap = e8.metrics.snapshot()
    fetches = int(snap["serving_decode_steps"] - pre["serving_decode_steps"]
                  + snap["serving_prefills_total"]
                  - pre["serving_prefills_total"])
    assert tally.count == fetches
    # greedy divergence vs fp32 bounded on the toy model: the pinned
    # threshold (mean common-prefix fraction of the full token streams)
    # is deliberately loose — measured 1.0 here, bound at 0.5
    fracs = []
    for a, b in zip(o32, o8):
        common = 0
        for x, y in zip(a, b):
            if x != y:
                break
            common += 1
        fracs.append(common / len(a))
    assert np.mean(fracs) >= 0.5, f"divergence too high: {fracs}"


def test_int8_prefix_hit_parity_exact(model):
    """Cached pages hold exactly the codes a cold prefill would write
    (same tokens, same exact-zero-masked prefix, deterministic quantizer),
    so greedy outputs are bit-identical cache-on/hit vs cache-off."""
    rng = np.random.RandomState(11)
    shared = rng.randint(0, 97, (8,)).astype(np.int32)
    prompts = [np.concatenate([shared, rng.randint(0, 97, (4,))
                               .astype(np.int32)]) for _ in range(3)]
    e_on = _engine(model, kv_dtype="int8")
    outs_on = []
    for p in prompts:  # sequential: later prompts HIT the shared pages
        rid = e_on.add_request(p, 6)
        outs_on.append(e_on.run()[rid])
    assert e_on.metrics.snapshot()["serving_prefix_hits"] >= 2
    e_off = _engine(model, kv_dtype="int8", enable_prefix_caching=False)
    for p, on in zip(prompts, outs_on):
        rid = e_off.add_request(p, 6)
        assert np.array_equal(e_off.run()[rid], on)


@pytest.mark.slow  # re-tiered 2026-08 (PR 10): tier-1 budget — the codes+scales swap payload stays tier-1-pinned by the [int8] spill/restore roundtrip (same gather/scatter jits moving the same leaves) and swap-parity by the faults suite
def test_int8_swap_preemption_bit_exact(model):
    """Swap handles carry codes + scales; a preempted int8 request resumes
    with bit-identical output to an unpreempted run."""
    prompts = _prompts(lens=(9, 10))
    ref = _run_all(_engine(model, num_pages=32, kv_dtype="int8"),
                   prompts, new=14)
    eng = _engine(model, num_pages=9, kv_dtype="int8",
                  preemption_mode="swap", debug_checks=True)
    outs = _run_all(eng, prompts, new=14)
    snap = eng.metrics.snapshot()
    assert snap["serving_swap_outs"] > 0 and snap["serving_swap_ins"] > 0
    assert all(np.array_equal(a, b) for a, b in zip(ref, outs))


@pytest.mark.slow  # re-tiered 2026-08 (PR 10): tier-1 budget — the all-leaves COW copy stays tier-1-pinned by the q8 registry cert (cow mover aliasing) + the prefix suite's COW semantics; only their composition's end-to-end parity moves to the round gate
def test_int8_cow_copies_codes_and_scales(model):
    """A fully-cached prompt admitted beside its live twin privatizes the
    last page — codes AND scales — before the one sanctioned rewrite."""
    rng = np.random.RandomState(13)
    p = rng.randint(0, 97, (8,)).astype(np.int32)  # 2 full pages
    eng = _engine(model, kv_dtype="int8", debug_checks=True)
    r1 = eng.add_request(p, 10)  # long holder: stays running
    eng.step()  # prefill r1 -> its prompt pages register in the index
    r2 = eng.add_request(p, 2)   # full hit while r1 still holds the pages
    outs = eng.run()
    assert eng.cache.cow_copies == 1
    assert np.array_equal(outs[r1][:len(outs[r2])], outs[r2])


# --------------------------------------------------- hlocheck/HBM audits
def test_q8_registry_steps_certify_and_alias_all_leaves():
    dec = run_step("engine_decode_q8")
    assert dec.collectives == () and dec.host_transfers == ()
    # 2 layers x (k_pool, v_pool, k_scale, v_scale) all donated + aliased
    assert dec.donated_leaves == 8 == dec.aliased_leaves
    gather = run_step("swap_gather_q8")
    assert gather.donated_leaves == 0 and gather.collectives == ()
    scatter = run_step("swap_scatter_q8")
    assert scatter.donated_leaves == 8 == scatter.aliased_leaves


def test_quantized_pool_hbm_under_0p3x_fp32(model):
    """The ISSUE's pinned capacity claim, read off the compiled artifact:
    on a pool-dominated config the decode step's donated (pool) bytes and
    its peak HBM both shrink below 0.3x fp32."""
    import jax.numpy as jnp

    def decode_report(kv_dtype):
        # pool-dominated on purpose: 4096 pages x 4 tokens -> the fp32
        # pool is ~8 MiB against ~120 KiB of params, so the ratio reads
        # the POOL, not the model
        eng = ServingEngine(model, ServingConfig(
            max_batch=2, num_pages=4096, page_size=4, max_prompt_len=8,
            kv_dtype=kv_dtype))
        args = (eng._p, eng.cache.pools,
                jnp.asarray(eng.cache.page_table), jnp.asarray(eng._ctx),
                jnp.asarray(eng._last_tok), jnp.asarray(eng._active),
                jnp.asarray(eng._rids), jnp.asarray(eng._gen))
        return audit_guard(eng._decode_jit, args, name=f"decode-{kv_dtype}")

    r32 = decode_report("float32")
    r8 = decode_report("int8")
    assert r8.donated_leaves == r8.aliased_leaves
    assert r8.donated_bytes < 0.3 * r32.donated_bytes
    assert r8.peak_bytes < 0.3 * r32.peak_bytes


def test_tp2_int8_decode_certifies_same_budget():
    """TP x quantization: the sharded int8 decode certifies against the
    UNCHANGED 2L+1 all-reduce budget (quantization adds no collectives)
    with every donated code + scale shard aliased."""
    rep = run_step("tp2_engine_decode_q8")
    assert rep.counts() == {"all-reduce": 5}  # 2*2 layers + 1 logits
    assert rep.donated_leaves == 8 == rep.aliased_leaves


@pytest.mark.slow  # tier-1 budget: the TP x int8 composition is pinned by
# tp2_engine_decode_q8 (budget + aliasing, tier-1 above) plus the fp32
# TP parity suite (-m tp); the full two-engine parity run gates rounds
def test_tp2_int8_outputs_bit_identical_tp1(model):
    import itertools

    from paddle_tpu.serving import scheduler as sched_mod

    prompts = _prompts()

    def run(tp):
        sched_mod._rid_counter = itertools.count(31000)
        eng = ServingEngine(model, ServingConfig(
            max_batch=2, num_pages=16, page_size=4, max_prompt_len=16,
            kv_dtype="int8", tensor_parallel=tp))
        return _run_all(eng, prompts)

    assert all(np.array_equal(a, b) for a, b in zip(run(1), run(2)))


# ------------------------------------------------------- host spill tier
_PS = 4                      # page size used by the tier tests
_SYS_TOKENS = 16             # 4 full shareable pages


def _tier_engine(model, kv_dtype="float32", tier_bytes=1 << 20, **kw):
    cfg = dict(max_batch=2, num_pages=14, page_size=_PS, max_prompt_len=32,
               kv_dtype=kv_dtype, host_tier_bytes=tier_bytes,
               debug_checks=True)
    cfg.update(kw)
    return ServingEngine(model, ServingConfig(**cfg))


def _system_prompt():
    rng = np.random.RandomState(29)
    return rng.randint(0, 97, (_SYS_TOKENS,)).astype(np.int32)


def _pressure(eng, n=2, lens=22, new=2, seed=31):
    """Cold whales that force the LRU sweep through the parked system
    pages WITHOUT oversubscribing the pool: two concurrent 6-page whales
    demand 12 of the 13 usable pages, so the allocator evicts exactly the
    oldest parked pages (the system chain) instead of preempt-thrashing."""
    rng = np.random.RandomState(seed)
    for _ in range(n):
        eng.add_request(rng.randint(0, 97, (lens,)).astype(np.int32), new)
    eng.run()


def _gather_pages(cache, pages):
    """Raw device bytes of the named pages via the jitted swap gather —
    the bit-exactness witness for the spill/restore round trip."""
    import jax.numpy as jnp

    got = cache._gather_jit(cache.pools,
                            jnp.asarray(cache._padded_idx(pages)))
    return [np.asarray(a)[:, :len(pages)].copy() for a in got]


# the fp32 variant is re-tiered 2026-08 (PR 10, tier-1 budget): the
# spill/restore movers are mode-agnostic by construction (kv_cache leaf
# maps) and the costlier [int8] variant pins the same roundtrip plus the
# scale leaves; fp32-unchanged is pinned separately
@pytest.mark.parametrize("kv_dtype", [
    pytest.param("float32", marks=pytest.mark.slow),
    # re-tiered 2026-08 (PR 20): tier-1 crossed its 870 s budget; the
    # full roundtrip now lives in the slow tier (int8_prefix_hit_parity
    # and restore_fail keep the int8 spill path hot in tier-1)
    pytest.param("int8", marks=pytest.mark.slow)])
def test_evict_spill_hit_restore_roundtrip_bit_exact(model, kv_dtype):
    """The tentpole round trip: a warm prefix's pages are captured, the
    pool is thrashed (eviction -> spill), and a re-admission restores the
    SAME bytes into fresh pages — codes and scales bit-identical — while
    counting as a prefix hit with the prefill tokens saved pinned."""
    system = _system_prompt()
    eng = _tier_engine(model, kv_dtype=kv_dtype)
    tail = np.asarray([1, 2, 3], np.int32)
    eng.add_request(np.concatenate([system, tail]), 4)
    eng.run()
    # the registered system pages, in chain order, still resident
    keys_before = dict(eng.cache._key_to_page)
    sys_pages = eng.cache.match_prefix(system)
    assert len(sys_pages) == _SYS_TOKENS // _PS
    before = _gather_pages(eng.cache, sys_pages)
    serials = [eng.cache._page_serial[p] for p in sys_pages]

    _pressure(eng)  # wipes the pool: every parked page spills
    st = eng.cache.stats()
    assert st["host_tier_pages"] > 0 and st["host_tier_spills"] >= \
        len(sys_pages)
    assert eng.cache.match_prefix(system) == []  # gone from the device

    pre = eng.metrics.snapshot()
    tail2 = np.asarray([7, 8, 9], np.int32)
    rid = eng.add_request(np.concatenate([system, tail2]), 4)
    out = eng.run()[rid]
    assert out is not None
    snap = eng.metrics.snapshot()
    # restored pages count as a prefix hit; ONLY the tail was prefilled
    assert snap["serving_prefix_hits"] - pre["serving_prefix_hits"] == 1
    assert snap["serving_prefix_tokens_saved"] \
        - pre["serving_prefix_tokens_saved"] == _SYS_TOKENS
    assert snap["serving_prefill_tokens_total"] \
        - pre["serving_prefill_tokens_total"] == len(tail2)
    assert snap["serving_host_tier_restores_total"] >= len(sys_pages)
    assert snap["serving_host_tier_hits_total"] >= 1
    # the lifecycle surfaced: this admission's trace carries the restore
    names = [e.name for e in eng.trace(rid).events]
    assert "restore" in names and \
        names.index("restore") < names.index("admitted")
    # bit-exactness: the restored pages hold the captured bytes, under
    # their ORIGINAL chain serials (descendant keys stay reachable)
    new_pages = eng.cache.match_prefix(system)
    assert len(new_pages) == len(sys_pages)
    after = _gather_pages(eng.cache, new_pages)
    for a, b in zip(before, after):
        assert a.dtype == b.dtype and np.array_equal(a, b)
    assert [eng.cache._page_serial[p] for p in new_pages] == serials
    assert keys_before.keys() >= \
        {eng.cache._page_key[p] for p in new_pages}
    eng.cache.check_invariants()


@pytest.mark.slow  # tier-1 budget: the tier-key/device-index disjointness
# invariant is swept by check_invariants under debug_checks in EVERY
# tier-1 host-tier test above; this re-registration scenario gates rounds
def test_spilled_page_outlives_generated_registration(model):
    """Registration of new device pages drops a stale tier twin: the
    device index always wins, and the invariant sweep (no key reachable
    both on device and in the tier) holds across the whole lifecycle."""
    system = _system_prompt()
    eng = _tier_engine(model)
    eng.add_request(np.concatenate([system, [1, 2, 3]]).astype(np.int32), 4)
    eng.run()
    _pressure(eng)
    tier_keys = set(eng.cache.host_tier._entries)
    assert tier_keys
    # a fresh identical prompt restores (not re-registers) — but even if
    # content re-registers through the generated span, invariants hold
    eng.add_request(np.concatenate([system, [1, 2, 3]]).astype(np.int32), 4)
    eng.run()
    eng.cache.check_invariants()


def test_kv_bytes_per_token_tracks_model_dtype():
    """The gauge reads the POOL's real itemsize: a bf16 model's fp32-path
    pools cost 2 B/elem, not a hardcoded 4 (capacity dashboards divide
    HBM by this figure)."""
    import jax.numpy as jnp

    per = 2 * 2 * 2 * 4  # 2(kv) * layers * heads * head_dim
    f32 = PagedCacheConfig(num_layers=2, num_heads=2, head_dim=4)
    bf16 = PagedCacheConfig(num_layers=2, num_heads=2, head_dim=4,
                            dtype=jnp.bfloat16)
    assert f32.kv_bytes_per_token == per * 4
    assert bf16.kv_bytes_per_token == per * 2


def test_tier_probe_does_not_reorder_lru():
    """cached_prefix_tokens is a PROBE: the scheduler's degraded-mode
    warm-waiter scan runs it every step for every waiter, so it must not
    promote never-admitted entries over genuinely warm ones — only a
    touching get() (the admit/restore path) reorders the tier LRU."""
    t = HostTier(max_bytes=100)

    def entry(i):
        return SpilledPage(key=(0, (i,)), serial=i,
                           k=np.zeros(20, np.int8), v=np.zeros(20, np.int8))

    t.put(entry(1))
    t.put(entry(2))
    assert t.get((0, (1,)), touch=False) is not None  # probe: no reorder
    t.put(entry(3))  # bound forces a drop: 1 is STILL the oldest
    assert t.get((0, (1,))) is None
    assert t.get((0, (2,))) is not None
    # a touching get promotes: now 3 is older than 2
    t.put(entry(4))
    assert t.get((0, (3,))) is None and t.get((0, (2,))) is not None


def test_host_tier_byte_bound_drops_oldest():
    t = HostTier(max_bytes=100)

    def entry(i, nbytes=40):
        return SpilledPage(key=(0, (i,)), serial=i,
                           k=np.zeros(nbytes // 2, np.int8),
                           v=np.zeros(nbytes - nbytes // 2, np.int8))

    t.put(entry(1))
    t.put(entry(2))
    assert t.bytes == 80 and len(t) == 2
    t.put(entry(3))  # 120 > 100: oldest (1) drops
    assert t.bytes == 80 and t.get((0, (1,))) is None
    assert t.get((0, (2,))) is not None
    t.put(entry(4, nbytes=200))  # larger than the whole bound: refused
    assert t.get((0, (4,))) is None and t.bytes == 80
    # replacing a key never double-counts
    t.put(entry(2))
    assert t.bytes == 80 and len(t) == 2


def test_restore_fail_retires_request_survivors_keep_serving(model):
    """The new fault point: a failed host-tier restore retires ONLY the
    re-admitted request (FAILED, error recorded, stale tier entries
    dropped); everyone else keeps serving and page accounting drains."""
    system = _system_prompt()
    inj = FaultInjector()
    eng = ServingEngine(
        model,
        ServingConfig(max_batch=2, num_pages=14, page_size=_PS,
                      max_prompt_len=32, host_tier_bytes=1 << 20,
                      debug_checks=True),
        fault_injector=inj)
    eng.add_request(np.concatenate([system, [1, 2, 3]]).astype(np.int32), 4)
    eng.run()
    _pressure(eng)
    assert len(eng.cache.host_tier) > 0
    head_key = (0, tuple(int(t) for t in system[:_PS]))
    assert head_key in eng.cache.host_tier._entries

    inj.arm("restore_fail")  # next restore, any step, any rid
    doomed = eng.add_request(
        np.concatenate([system, [7, 8, 9]]).astype(np.int32), 4)
    survivor = eng.add_request(
        np.asarray([5, 6, 7, 8, 9], np.int32), 4)
    outs = eng.run()
    assert eng.status(doomed) == "failed"
    assert "restore_fail" in str(eng.request(doomed).error)
    assert survivor in outs  # the batch kept serving
    # the stale entries the failed restore touched are gone from the tier
    # (the sweep that ran BEFORE the failure may have spilled new ones —
    # those are fine; the system chain must be dropped)
    assert head_key not in eng.cache.host_tier._entries
    assert eng.cache.cached_prefix_tokens(system) == 0
    assert any(pt == "restore_fail" and rid == doomed
               for pt, _, rid in inj.fired)
    # no leaked pages: the undone admission left the pool accounted
    eng.cache.check_invariants()
    final = eng.run()  # drains cleanly
    assert eng.cache.allocator.pages_in_use == 0 or final is not None


@pytest.mark.slow  # tier-1 budget: restore accounting (hits/saved tokens)
# is pinned tier-1 by the roundtrip test; the trace/Chrome surface of the
# same events gates rounds
def test_spill_restore_trace_events_and_chrome_instants(model):
    system = _system_prompt()
    eng = _tier_engine(model)
    eng.add_request(np.concatenate([system, [1, 2, 3]]).astype(np.int32), 4)
    eng.run()
    _pressure(eng)
    rid = eng.add_request(
        np.concatenate([system, [7, 8, 9]]).astype(np.int32), 4)
    eng.run()
    names = [e.name for e in eng.trace(rid).events]
    assert "restore" in names
    assert names.index("restore") < names.index("admitted")
    restore = eng.trace(rid).first("restore")
    assert restore.arg("pages") == _SYS_TOKENS // _PS
    # some admission in the pressure burst stamped the spills it forced
    spilled = [t for t in eng.traces()
               if any(e.name == "spill" for e in t.events)]
    assert spilled, "no admission carried a spill event"
    doc = eng.export_chrome_trace()
    phases = {(ev.get("name"), ev.get("ph")) for ev in doc["traceEvents"]}
    assert ("restore", "i") in phases and ("spill", "i") in phases


def test_host_tier_gauges_preseeded_and_fed(model):
    eng = _tier_engine(model, kv_dtype="int8")
    snap = eng.metrics.snapshot()
    for k in ("serving_kv_bytes_per_token", "serving_host_tier_pages",
              "serving_host_tier_bytes", "serving_host_tier_hits_total",
              "serving_host_tier_spills_total",
              "serving_host_tier_restores_total"):
        assert k in snap, f"{k} missing from a fresh snapshot"
    assert snap["serving_kv_bytes_per_token"] == \
        eng.cache.cfg.kv_bytes_per_token > 0
    assert snap["serving_host_tier_pages"] == 0
    # prometheus types: the _total mirrors export as counters
    text = eng.metrics.prometheus()
    assert "# TYPE serving_host_tier_spills_total counter" in text
    assert "# TYPE serving_host_tier_pages gauge" in text
