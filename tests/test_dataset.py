"""InMemoryDataset/QueueDataset tests (reference analog:
tests/unittests/test_dataset.py)."""
import numpy as np
import pytest

from paddle_tpu.distributed.fleet import InMemoryDataset, QueueDataset


@pytest.fixture
def slot_files(tmp_path):
    rs = np.random.RandomState(0)
    paths = []
    for fi in range(3):
        p = tmp_path / f"part-{fi}.txt"
        lines = []
        for i in range(20):
            ids = " ".join(f"click:{rs.randint(1, 100)}"
                           for _ in range(rs.randint(1, 4)))
            dense = ",".join(f"{v:.3f}" for v in rs.rand(2))
            lines.append(f"{i % 2} {ids} show:{rs.randint(1, 50)} f:{dense}")
        p.write_text("\n".join(lines) + "\n")
        paths.append(str(p))
    return paths


def test_in_memory_dataset_load_shuffle_batch(slot_files):
    ds = InMemoryDataset()
    ds.set_filelist(slot_files)
    ds.set_batch_size(8)
    ds.set_use_var(["click", "show"], dense_slots=["f"])
    n = ds.load_into_memory()
    assert n == 60 and ds.get_memory_data_size() == 60

    first_before = ds._records[0]
    ds.local_shuffle()

    batches = list(ds)
    assert sum(b["label"].shape[0] for b in batches) == 60
    b0 = batches[0]
    assert b0["click"].dtype == np.int64 and b0["click"].shape[0] == 8
    assert b0["show"].shape[1] >= 1
    assert b0["f"].shape == (8, 2)

    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_queue_dataset_streams_all_records(slot_files):
    ds = QueueDataset(capacity=4)
    ds.set_filelist(slot_files)
    ds.set_batch_size(7)
    ds.set_thread(2)
    ds.set_use_var(["click"], dense_slots=["f"])
    total = 0
    n_batches = 0
    for batch in ds:
        total += batch["label"].shape[0]
        n_batches += 1
        assert batch["click"].shape[0] <= 7
    assert total == 60
    assert n_batches >= 9  # 3 files x ceil(20/7)

    # second iteration works (fresh readers)
    assert sum(b["label"].shape[0] for b in ds) == 60


def test_sparse_padding_static_shape(slot_files):
    ds = InMemoryDataset()
    ds.set_filelist(slot_files[:1])
    ds.set_batch_size(20)
    ds.set_use_var(["click"])
    ds.load_into_memory()
    (batch,) = list(ds)
    # padded to max ids per instance within batch
    assert batch["click"].ndim == 2
    assert (batch["click"] >= 0).all()


def test_queue_dataset_reader_error_propagates(tmp_path):
    p = tmp_path / "ok.txt"
    p.write_text("1 click:5\n")
    ds = QueueDataset()
    ds.set_filelist([str(p), str(tmp_path / "missing.txt")])
    ds.set_batch_size(2)
    ds.set_thread(2)
    ds.set_use_var(["click"])
    with pytest.raises(FileNotFoundError):
        list(ds)  # must raise, not hang


def test_global_shuffle_exchanges_records(slot_files):
    """Two simulated workers exchange shards via the PS blob mailbox —
    no record lost, partitions disjoint."""
    import threading

    from paddle_tpu.distributed.ps import PsClient, PsServer

    server = PsServer(port=0, n_workers=2).start()
    eps = [f"127.0.0.1:{server.port}"]

    class FakeRole:
        def __init__(self, idx):
            self._i = idx

        def worker_num(self):
            return 2

        def worker_index(self):
            return self._i

    datasets = []
    for w in range(2):
        ds = InMemoryDataset()
        ds.set_filelist([slot_files[w]])  # disjoint shards per worker
        ds.set_batch_size(8)
        ds.set_use_var(["click", "show"], dense_slots=["f"])
        ds.load_into_memory()
        ds._ps_client = PsClient(eps)
        ds._role = FakeRole(w)
        datasets.append(ds)

    total_before = sum(d.get_memory_data_size() for d in datasets)
    threads = [threading.Thread(target=d.global_shuffle) for d in datasets]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    total_after = sum(d.get_memory_data_size() for d in datasets)
    assert total_after == total_before == 40
    r0 = {repr(r) for r in datasets[0]._records}
    r1 = {repr(r) for r in datasets[1]._records}
    assert not (r0 & r1)  # disjoint ownership
    for d in datasets:
        d._ps_client.close()
    server.stop()
