"""Constant folding / DCE / CSE program passes (reference: ir pass family +
Executor prune, executor.py:1358)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.static.passes import new_pass, PassManager


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _build_constexpr_program():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4], "float32")
        a = paddle.full([4], 2.0, "float32")
        b = paddle.full([4], 3.0, "float32")
        c = paddle.add(a, b)            # foldable: 5
        d = paddle.multiply(c, a)       # foldable: 10
        y = paddle.add(x, d)            # not foldable (feed input)
    return main, startup, y


def test_constant_folding_folds_transitively(static_mode):
    main, startup, y = _build_constexpr_program()
    n_before = len(main.global_block.ops)
    ctx = new_pass("constant_folding").apply(main)
    # full() evaluates at trace time; the recorded add and multiply fold
    assert ctx.attrs["constant_folding.n_folded"] == 2
    folded_types = [op.type for op in main.global_block.ops]
    assert "folded_constant" in folded_types
    exe = static.Executor()
    exe.run(startup)
    out = exe.run(main, feed={"x": np.ones(4, np.float32)}, fetch_list=[y])
    np.testing.assert_allclose(out[0], np.full(4, 11.0), rtol=1e-6)


def test_constant_folding_skips_params_and_stochastic(static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8], "float32")
        h = static.nn.fc(x, 8)  # parameter inputs — must NOT fold
        h2 = paddle.nn.functional.dropout(h, 0.5)  # stochastic — must NOT fold
    ctx = new_pass("constant_folding").apply(main)
    types = [op.type for op in main.global_block.ops]
    assert not any(t == "folded_constant" and "fc" in a.get("folded_from", "")
                   for t, a in [(op.type, op.attrs)
                                for op in main.global_block.ops])
    # program still runs and params still train-able (not frozen to consts)
    exe = static.Executor()
    exe.run(startup)
    exe.run(main, feed={"x": np.ones((4, 8), np.float32)}, fetch_list=[h2])


def test_dce_prunes_to_targets(static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4], "float32")
        kept = paddle.add(x, x)
        dead = paddle.multiply(kept, kept)      # not on the path to target
        dead2 = paddle.exp(dead)                # noqa: F841 dead chain
        target = paddle.subtract(kept, x)
    n_before = len(main.global_block.ops)
    ctx = new_pass("dead_code_elimination",
                   {"targets": [target]}).apply(main)
    assert ctx.attrs["dead_code_elimination.n_removed"] == 2
    assert len(main.global_block.ops) == n_before - 2
    exe = static.Executor()
    exe.run(startup)
    out = exe.run(main, feed={"x": np.full(4, 2.0, np.float32)},
                  fetch_list=[target])
    np.testing.assert_allclose(out[0], np.full(4, 2.0), rtol=1e-6)


def test_dce_requires_targets(static_mode):
    main, _ = static.Program(), static.Program()
    with pytest.raises(RuntimeError, match="not applicable"):
        new_pass("dead_code_elimination").apply(main)


def test_cse_dedupes_and_preserves_fetches(static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4], "float32")
        a = paddle.exp(x)
        b = paddle.exp(x)       # duplicate of a
        y = paddle.add(a, b)
    ctx = new_pass("common_subexpression_elimination").apply(main)
    assert ctx.attrs["cse.n_deduped"] == 1
    assert any(op.type == "share" for op in main.global_block.ops)
    exe = static.Executor()
    exe.run(startup)
    # both the combined output AND the deduped variable fetch correctly
    out = exe.run(main, feed={"x": np.zeros(4, np.float32)},
                  fetch_list=[y, b])
    np.testing.assert_allclose(out[0], np.full(4, 2.0), rtol=1e-6)
    np.testing.assert_allclose(out[1], np.ones(4), rtol=1e-6)


def test_cse_keeps_stochastic_ops(static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [1000], "float32")
        d1 = paddle.nn.functional.dropout(x, 0.5)
        d2 = paddle.nn.functional.dropout(x, 0.5)  # must NOT be deduped
        y = paddle.add(d1, d2)  # noqa: F841
    ctx = new_pass("common_subexpression_elimination").apply(main)
    assert ctx.attrs["cse.n_deduped"] == 0


def test_pass_manager_composition(static_mode):
    main, startup, y = _build_constexpr_program()
    pm = PassManager([
        new_pass("constant_folding"),
        new_pass("common_subexpression_elimination"),
        new_pass("dead_code_elimination", {"targets": [y]}),
    ])
    ctx = pm.apply(main)
    assert "constant_folding" in ctx.attrs["applied_passes"]
    exe = static.Executor()
    exe.run(startup)
    out = exe.run(main, feed={"x": np.ones(4, np.float32)}, fetch_list=[y])
    np.testing.assert_allclose(out[0], np.full(4, 11.0), rtol=1e-6)


def test_cse_distinguishes_closure_config(static_mode):
    """Confirmed-bug regression (code review r4): sum(x, axis=0) and
    sum(x, axis=1) record identical (type, inputs, attrs) — the closure
    fingerprint must keep them distinct."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 3], "float32")
        a = paddle.sum(x, axis=0)
        b = paddle.sum(x, axis=1)
    ctx = new_pass("common_subexpression_elimination").apply(main)
    assert ctx.attrs["cse.n_deduped"] == 0
    exe = static.Executor()
    exe.run(startup)
    feed = {"x": np.array([[1, 2, 3], [2, 3, 4]], np.float32)}
    out = exe.run(main, feed=feed, fetch_list=[a, b])
    np.testing.assert_allclose(out[0], [3, 5, 7])
    np.testing.assert_allclose(out[1], [6, 9])
    # identical config across distinct closures still dedupes
    main2, startup2 = static.Program(), static.Program()
    with static.program_guard(main2, startup2):
        x = static.data("x", [2, 3], "float32")
        c = paddle.sum(x, axis=0)
        d = paddle.sum(x, axis=0)  # noqa: F841
    ctx = new_pass("common_subexpression_elimination").apply(main2)
    assert ctx.attrs["cse.n_deduped"] == 1


def test_cse_distinguishes_folded_constants(static_mode):
    """Confirmed-miscompile regression (code review r4, round 2): two
    different folded constants carry their values in lambda DEFAULT args —
    the fingerprint must hash defaults (by array content), not just cells."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2], "float32")
        w1 = paddle.full([2], 1.0, "float32")
        w2 = paddle.full([2], 3.0, "float32")
        c1 = paddle.multiply(w1, w1)   # folds to 1
        c2 = paddle.multiply(w2, w2)   # folds to 9
        y = paddle.add(paddle.add(x, c1), c2)
    from paddle_tpu.static.passes import PassManager
    pm = PassManager([new_pass("constant_folding"),
                      new_pass("common_subexpression_elimination")])
    ctx = pm.apply(main)
    assert ctx.attrs["cse.n_deduped"] == 0  # distinct constants NOT merged
    exe = static.Executor()
    exe.run(startup)
    out = exe.run(main, feed={"x": np.zeros(2, np.float32)}, fetch_list=[y])
    np.testing.assert_allclose(out[0], np.full(2, 10.0), rtol=1e-6)


def test_static_save_falls_back_on_unexportable_program(static_mode, tmp_path):
    """Code-review r4: static.save must never crash on programs outside the
    pdmodel emitter set (scalar-operand add records a 1-input op)."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4], "float32")
        y = paddle.add(x, paddle.to_tensor(np.float32(2.0)))  # noqa: F841
    path = str(tmp_path / "m")
    static.save(main, path)  # must not raise
    assert (tmp_path / "m.pdparams").exists()
    assert (tmp_path / "m.pdmodel").exists()
