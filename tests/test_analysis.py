"""paddle_tpu.analysis — trace-time auditor + repo linter.

Three layers of coverage:

- tracecheck golden tests: the retrace explainer must name the RIGHT
  argument (and axis/dtype/static value) when a signature changes; budget
  and donation violations raise; SyncTally counts exactly the host-sync
  events and nothing else.
- serving integration: the engine's pinned ``compile_counts`` surface now
  reads off CompileGuard unchanged; ``debug_checks=True`` turns an
  unexpected decode retrace into a RetraceError naming the argument and
  runs the cache invariant sweep each step.
- lint: one fixture per rule (positive + pragma-suppressed), the repo
  self-lint at ZERO findings (the tier-1 enforcement of every fix this PR
  made), and reintroduction tests proving the linter would catch the PR 2
  ``eq`` bug and a ``time.time()`` in serving again.
"""
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import (RULES, CompileGuard, DonationViolation,
                                 RetraceError, SyncTally, SyncViolation,
                                 donation_audit, lint_paths, lint_source)
from paddle_tpu.serving import ServingConfig, ServingEngine
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.analysis

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "lint_fixtures"


# ----------------------------------------------------------- CompileGuard
def test_guard_counts_traces_not_calls():
    g = CompileGuard(lambda x: x * 2, "double", budget=2)
    for _ in range(3):
        g(jnp.zeros((4,)))
    g(jnp.zeros((8,)))
    assert g.calls == 4 and g.traces == 2 and g.retraces == 0
    assert len(g.signatures) == 2


def test_guard_budget_counts_overage_when_not_strict():
    g = CompileGuard(lambda x: x + 1, "inc", budget=1)
    g(jnp.zeros((2,)))
    g(jnp.zeros((3,)))  # over budget but unstrict: counted, not raised
    assert g.traces == 2 and g.retraces == 1


def test_retrace_explainer_names_argument_and_axis():
    g = CompileGuard(lambda lhs, rhs: lhs @ rhs, "mm", budget=1, strict=True)
    g(jnp.zeros((4, 8)), jnp.zeros((8, 2)))
    with pytest.raises(RetraceError) as ei:
        g(jnp.zeros((4, 16)), jnp.zeros((16, 2)))
    msg = str(ei.value)
    assert "'mm'" in msg and "budget of 1" in msg
    assert "lhs" in msg and "rhs" in msg
    assert "axis 1: 8 -> 16" in msg  # lhs changed on axis 1
    assert "axis 0: 8 -> 16" in msg  # rhs changed on axis 0
    # strict mode refuses BEFORE paying the recompile
    assert g.traces == 1 and g.retraces == 1


def test_retrace_explainer_names_dtype_change():
    g = CompileGuard(lambda ctx, tok: ctx + tok, "step", budget=1,
                     strict=True)
    g(jnp.zeros((4,), jnp.int32), jnp.zeros((4,), jnp.int32))
    with pytest.raises(RetraceError) as ei:
        g(jnp.zeros((4,), jnp.float32), jnp.zeros((4,), jnp.int32))
    msg = str(ei.value)
    assert "ctx" in msg and "dtype int32 -> float32" in msg
    assert "tok:" not in msg  # the unchanged argument is not blamed


def test_retrace_explainer_names_static_value():
    g = CompileGuard(lambda x, width: x[:width], "slice", budget=1,
                     strict=True, static_argnums=(1,))
    g(jnp.arange(8), 4)
    g(jnp.arange(8), 4)  # same static value: cache hit
    with pytest.raises(RetraceError) as ei:
        g(jnp.arange(8), 6)
    assert "width" in str(ei.value)
    assert "static value 4 -> 6" in str(ei.value)


def test_retrace_explainer_pytree_structure_change():
    g = CompileGuard(lambda pools: [p * 2 for p in pools], "pools",
                     budget=1, strict=True)
    g([jnp.zeros(2)])
    with pytest.raises(RetraceError) as ei:
        g([jnp.zeros(2), jnp.zeros(2)])
    assert "pytree structure changed" in str(ei.value)


def test_strict_retry_of_refused_signature_counts_one_retrace():
    # retraces counts retrace EVENTS, matching non-strict accounting: a
    # caller looping on the same refused signature is one event, N raises
    g = CompileGuard(lambda x: x + 1, "inc", budget=1, strict=True)
    g(jnp.zeros((2,)))
    for _ in range(3):
        with pytest.raises(RetraceError):
            g(jnp.zeros((5,)))
    assert g.traces == 1 and g.retraces == 1
    with pytest.raises(RetraceError):
        g(jnp.zeros((7,)))  # a DIFFERENT bad signature is a second event
    assert g.retraces == 2


def test_group_budget_catches_same_group_retrace_despite_headroom():
    # the prefill shape: aggregate budget 4 (buckets), but bucket (8,) must
    # compile ONCE — a dtype drift re-tracing it is refused even though
    # the aggregate budget has room for 3 more traces
    g = CompileGuard(lambda ids: ids * 2, "prefill", budget=4, strict=True,
                     group_by=lambda ids: tuple(ids.shape))
    g(jnp.zeros((8,), jnp.int32))
    g(jnp.zeros((16,), jnp.int32))  # a new bucket: allowed
    with pytest.raises(RetraceError) as ei:
        g(jnp.zeros((8,), jnp.float32))  # same bucket, drifted dtype
    msg = str(ei.value)
    assert "group (8,)" in msg and "dtype int32 -> float32" in msg
    assert g.traces == 2 and g.retraces == 1


def test_sync_tally_keeps_keyword_numpy_calls_working():
    with SyncTally() as t:
        out = np.asarray(a=jnp.arange(3))  # operand by keyword
        np.asarray(np.ones(2), dtype=np.float32)
    assert out.tolist() == [0, 1, 2] and t.count == 1


def test_guard_use_after_donation_raises():
    g = CompileGuard(lambda pool, i: pool.at[i].set(0.0), "scatter",
                     donate_argnums=(0,), strict=True)
    pool = jnp.ones((4, 2))
    new_pool = g(pool, jnp.asarray(1))
    with pytest.raises(DonationViolation) as ei:
        g(pool, jnp.asarray(2))  # consumed buffer referenced again
    assert "pool" in str(ei.value) and "donated" in str(ei.value)
    g(new_pool, jnp.asarray(2))  # the returned array is the live one


def test_guard_double_donation_raises():
    g = CompileGuard(lambda a, b: (a.at[0].set(1.0), b.at[0].set(2.0)),
                     "dd", donate_argnums=(0, 1), strict=True)
    x = jnp.ones((3,))
    with pytest.raises(DonationViolation) as ei:
        g(x, x)
    assert "double donation" in str(ei.value)


def test_donation_audit_reports_unused_donated_leaf():
    reports = donation_audit(lambda pool, dead: pool * 2, (0, 1),
                             jnp.ones(3), jnp.ones(4))
    assert len(reports) == 1 and "dead" in reports[0] \
        and "never consumed" in reports[0]
    assert donation_audit(lambda pool: pool * 2, (0,), jnp.ones(3)) == []


# -------------------------------------------------------------- SyncTally
def test_sync_tally_counts_sync_events_only():
    with SyncTally() as t:
        arr = jnp.arange(4)
        jnp.sum(arr)            # device compute: not a sync
        np.asarray(np.ones(2))  # host->host: not a sync
        np.asarray(arr)         # sync
        int(arr[0])             # sync
        arr[1].item()           # sync
        jax.device_get(arr)     # sync
    assert t.count == 4
    assert t.events == ["np.asarray", "int", "item", "device_get"]
    # patches removed on exit: no counting outside the region
    before = t.count
    np.asarray(jnp.zeros(2))
    assert t.count == before


def test_sync_tally_counts_tolist_and_iteration():
    """The PR 6 blind-spot fix: ``.tolist()`` is a full-array host
    materialization and iterating a device array (``for``/``list()``,
    including the __len__/__getitem__ sequence-protocol path) drives a
    per-element dispatch loop from the host — both must count. Per-element
    coercions inside a loop still count on top of the iteration event."""
    with SyncTally() as t:
        arr = jnp.arange(3)
        arr.tolist()                    # sync: full materialization
        for _ in arr:                   # sync: one event per loop
            pass
        total = sum(int(x) for x in arr)  # iter + 3 int coercions
    assert total == 3
    assert t.events == ["tolist", "iter", "iter", "int", "int", "int"], \
        t.events
    # patches removed on exit
    before = t.count
    jnp.zeros(2).tolist()
    assert t.count == before


def test_sync_tally_paused_suppresses_counting():
    """hlocheck AOT-lowers steps inside debug_checks step tallies;
    lowering materializes traced constants host-side — compile-time work
    the certification must not count. Nested pauses restore correctly."""
    from paddle_tpu.analysis import sync_tally_paused

    with SyncTally() as t:
        with sync_tally_paused():
            np.asarray(jnp.zeros(2))
            jnp.zeros(2).tolist()
        np.asarray(jnp.zeros(2))  # counting resumes after the pause
    assert t.count == 1 and t.events == ["np.asarray"]


def test_sync_tally_nests_and_enforces_allowance():
    with SyncTally() as outer:
        with SyncTally() as inner:
            np.asarray(jnp.zeros(2))
        np.asarray(jnp.zeros(2))
    assert inner.count == 1 and outer.count == 2
    with pytest.raises(SyncViolation) as ei:
        with SyncTally(allowed=1, name="decode"):
            np.asarray(jnp.zeros(2))
            np.asarray(jnp.zeros(2))
    assert "decode" in str(ei.value) and "allows 1" in str(ei.value)


# ------------------------------------------------------ serving integration
def _toy_engine(**overrides):
    paddle.seed(23)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=48, dropout=0.0))
    model.eval()
    kw = dict(max_batch=2, num_pages=20, page_size=4, max_prompt_len=8,
              debug_checks=True)
    kw.update(overrides)
    return ServingEngine(model, ServingConfig(**kw))


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 budget; the read-through property is exercised
# by every compile_counts pin across test_serving*/test_serving_tp and the demo
def test_engine_compile_counts_surface_reads_off_guards():
    engine = _toy_engine()
    rng = np.random.RandomState(0)
    for n, b in ((3, 4), (6, 3)):
        engine.add_request(rng.randint(0, 97, (n,)).astype(np.int32), b)
    engine.run()
    # the exact dict-shaped pin PR 1-3 rely on, now a CompileGuard view
    assert engine.compile_counts == {"prefill": 1, "decode": 1}
    assert engine.compile_counts["decode"] == \
        engine.guards["decode"].traces
    assert dict(engine.cache.compile_counts) == \
        {"swap_gather": 0, "swap_scatter": 0, "cow_copy": 0}


def test_engine_debug_checks_retrace_raises_naming_argument():
    engine = _toy_engine()
    rng = np.random.RandomState(1)
    engine.add_request(rng.randint(0, 97, (4,)).astype(np.int32), 3)
    engine.run()  # compiles prefill + decode once, audits clean
    # an unexpected decode retrace: ctx at the wrong width. The guard must
    # refuse it (budget 1 already spent) and blame exactly 'ctx'.
    b = engine.config.max_batch
    with pytest.raises(RetraceError) as ei:
        engine._decode_jit(
            engine._p, engine.cache.pools,
            jnp.asarray(engine.cache.page_table),
            jnp.zeros((b + 1,), jnp.int32),  # <- ctx grew an element
            jnp.asarray(engine._last_tok), jnp.asarray(engine._active),
            jnp.asarray(engine._rids), jnp.asarray(engine._gen))
    msg = str(ei.value)
    assert "'decode'" in msg and "ctx" in msg
    assert f"axis 0: {b} -> {b + 1}" in msg
    assert engine.compile_counts == {"prefill": 1, "decode": 1}


def test_engine_debug_checks_serves_correctly_and_counts_syncs():
    # debug_checks must not change behavior: outputs still match the
    # reference loop, invariants sweep clean, and the analysis metrics
    # report the per-step token fetches as the only host syncs
    engine = _toy_engine()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 97, (n,)).astype(np.int32) for n in (3, 5)]
    rids = [engine.add_request(p, 4) for p in prompts]
    outs = engine.run()
    from paddle_tpu.core.tensor import Tensor
    for rid, p in zip(rids, prompts):
        ref = np.asarray(engine.model.generate(
            Tensor(p[None]), max_new_tokens=4)._value)[0]
        np.testing.assert_array_equal(ref, outs[rid])
    snap = engine.metrics.snapshot()
    assert snap["serving_analysis_retraces_total"] == 0
    # every decode step fetches its token batch (1 sync), every prefill
    # fetches its first token (1 sync) — and NOTHING else syncs
    expected = snap["serving_decode_steps"] + snap["serving_prefills_total"]
    assert snap["serving_analysis_host_syncs_total"] == expected


def test_debug_checks_runs_donation_audit_at_first_trace():
    """PR 5 satellite: debug_checks audits each jitted step at jaxpr
    level before its FIRST trace — the engine's donated pools must all be
    consumed by the computation (a donated-but-unused buffer is a wrong
    donate_argnums). Clean audits are recorded per step name."""
    engine = _toy_engine()
    assert engine._donation_audits == {}  # nothing traced yet
    rng = np.random.RandomState(3)
    engine.add_request(rng.randint(0, 97, (4,)).astype(np.int32), 3)
    engine.run()
    assert set(engine._donation_audits) == {"prefill", "decode"}
    # the engine's donation is clean: no dead donated leaves survived to
    # raise, and no identity pass-through reports were recorded either
    assert engine._donation_audits == {"prefill": [], "decode": []}


def test_donation_audit_helper_raises_on_dead_donated_leaf():
    # the audit reads the impl and donate_argnums OFF THE GUARD, so it
    # can never desynchronize from what the jit actually donates
    engine = _toy_engine()
    bad = CompileGuard(lambda pool, dead: pool * 2, "bad_step",
                       donate_argnums=(0, 1))
    with pytest.raises(DonationViolation) as ei:
        engine._audit_donation(bad, (jnp.ones(3), jnp.ones(4)))
    msg = str(ei.value)
    assert "bad_step" in msg and "dead" in msg and "never consumed" in msg
    assert "bad_step" not in engine._donation_audits  # fatal, not recorded


def test_debug_checks_off_skips_donation_audit():
    engine = _toy_engine(debug_checks=False)
    rng = np.random.RandomState(4)
    engine.add_request(rng.randint(0, 97, (4,)).astype(np.int32), 3)
    engine.run()
    assert engine._donation_audits == {}


def test_analysis_counters_pre_seeded():
    engine = _toy_engine(debug_checks=False)
    snap = engine.metrics.snapshot()
    assert snap["serving_analysis_retraces_total"] == 0
    assert snap["serving_analysis_host_syncs_total"] == 0
    # the PT003 backfill: every counter is visible before its first event
    for k in ("tokens_total", "prefills_total", "prefill_tokens_total",
              "decode_steps", "preemptions_total"):
        assert snap["serving_" + k] == 0, k


# ------------------------------------------------------------------- lint
# fixture file -> (path the rule scope sees, {line: rule} expected)
_FIXTURE_CASES = {
    "pt001_dataclass_eq.py": ("pt001.py", {7: "PT001"}),
    "pt002_pool_loop.py": ("serving/pt002.py", {5: "PT002"}),
    "pt003_unseeded_counter.py": ("pt003.py", {18: "PT003", 21: "PT003"}),
    "pt004_wall_clock.py": ("serving/pt004.py", {6: "PT004"}),
    "pt005_hot_sync.py": ("serving/pt005.py",
                          {8: "PT005", 9: "PT005", 10: "PT005"}),
    "pt006_jit_no_donate.py": ("serving/pt006.py", {23: "PT006"}),
    "pt007_mutable_default.py": ("pt007.py", {4: "PT007", 14: "PT007"}),
    "pt008_unseeded_gauge.py": ("pt008.py",
                                {16: "PT008", 17: "PT008", 18: "PT008"}),
    "pt009_raw_jit.py": ("serving/pt009.py",
                         {13: "PT009", 15: "PT009", 18: "PT009",
                          25: "PT009", 29: "PT009"}),
    "pt010_shard_map.py": ("serving/pt010.py",
                           {6: "PT010", 7: "PT010", 13: "PT010"}),
    "pt011_uncertified_pallas.py": ("kernels/pt011.py",
                                    {7: "PT011", 11: "PT011"}),
    "pt012_unregistered_family.py": ("pt012.py",
                                     {14: "PT012", 19: "PT012",
                                      24: "PT012", 44: "PT012",
                                      55: "PT012", 61: "PT012"}),
    "pt013_direct_add_request.py": ("serving/fleet_rogue.py",
                                    {9: "PT013"}),
    "pt014_raw_wire.py": ("serving/sidechannel.py",
                          {5: "PT014", 6: "PT014", 7: "PT014",
                           8: "PT014", 12: "PT014", 16: "PT014",
                           20: "PT014"}),
    "pt015_raw_psum.py": ("serving/rogue_collective.py",
                          {6: "PT015", 7: "PT015",
                           11: "PT015", 12: "PT015"}),
    "pt016_wallclock.py": ("serving/pt016.py",
                           {13: "PT016", 18: "PT016", 22: "PT016",
                            23: "PT016", 29: "PT016"}),
    "pt017_contextless_exchange.py": ("serving/pt017.py",
                                      {9: "PT017", 14: "PT017",
                                       19: "PT017"}),
}


@pytest.mark.parametrize("fixture", sorted(_FIXTURE_CASES))
def test_lint_rule_fixture(fixture):
    """Each rule: the positive cases fire at the expected lines, the
    pragma-suppressed twin of the same defect stays quiet, clean code
    stays quiet."""
    as_path, expected = _FIXTURE_CASES[fixture]
    src = (FIXTURES / fixture).read_text()
    findings = lint_source(src, as_path)
    assert {(f.line, f.rule) for f in findings} == set(expected.items()), \
        [str(f) for f in findings]
    assert "lint: disable" not in "".join(
        src.splitlines()[f.line - 1] for f in findings)


def test_lint_rule_table_is_complete():
    assert sorted(RULES) == [f"PT00{i}" for i in range(1, 10)] + [
        "PT010", "PT011", "PT012", "PT013", "PT014", "PT015", "PT016",
        "PT017"]
    for code, rule in RULES.items():
        assert rule.doc and rule.code == code


def test_serving_scoped_rules_do_not_fire_outside_serving():
    src = (FIXTURES / "pt004_wall_clock.py").read_text()
    assert lint_source(src, "io/dataloader_helper.py") == []


def test_allowlist_exempts_matching_paths():
    src = (FIXTURES / "pt004_wall_clock.py").read_text()
    assert lint_source(src, "serving/legacy.py",
                       allowlist={"legacy": {"PT004"}}) == []
    assert lint_source(src, "serving/fresh.py",
                       allowlist={"legacy": {"PT004"}}) != []


def test_repo_self_lint_zero_findings():
    """The tier-1 enforcement: every invariant the linter encodes holds
    over paddle_tpu/ itself. A regression in any fixed violation (the
    SwapHandle eq, the unseeded counters, a stray sync in step()) fails
    here, forever."""
    findings = lint_paths([REPO / "paddle_tpu"])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_tests_and_examples_lint_zero_nonfixture_findings():
    """The PR 5 widening: the default sweep also covers tests/ and
    examples/ — a serving contract regression (mutable default, unseeded
    stat, array-field dataclass) hides in a test helper as easily as in
    the package. The lint fixtures' INTENTIONAL positives are exempted
    via the ALLOWLIST (a pragma inside a fixture would defeat the
    fixture), so the pin is zero NON-fixture findings."""
    findings = lint_paths([REPO / "tests", REPO / "examples"])
    assert findings == [], "\n".join(str(f) for f in findings)
    # the allowlist is doing real work: without it the fixtures DO fire
    fixture_findings = lint_paths([REPO / "tests" / "lint_fixtures"],
                                  allowlist={})
    assert fixture_findings, "fixture positives vanished — dead fixtures"


def test_self_lint_catches_reintroduced_unseeded_gauge():
    """Deliberately strip a gauge from metrics._SEEDED: PT008 must fail
    the way PT003 would for a counter."""
    path = REPO / "paddle_tpu" / "serving" / "metrics.py"
    src = path.read_text()
    bad = src.replace('"queue_depth_peak", "page_pool_peak")',
                      '"queue_depth_peak",)')
    assert bad != src, "metrics.py no longer seeds the peak gauges"
    findings = lint_source(bad, "paddle_tpu/serving/metrics.py")
    assert any(f.rule == "PT008" and "page_pool_peak" in f.message
               for f in findings)


def test_self_lint_catches_reintroduced_pr2_eq_bug():
    """Deliberately strip SwapHandle's eq=False: the linter must fail the
    way it would have failed PR 2's review."""
    path = REPO / "paddle_tpu" / "serving" / "kv_cache.py"
    src = path.read_text()
    bad = src.replace("@dataclass(eq=False)  # ndarray fields: identity "
                      "semantics (lint rule PT001)", "@dataclass")
    assert bad != src, "kv_cache.py no longer carries the PT001 fix marker"
    findings = lint_source(bad, "paddle_tpu/serving/kv_cache.py")
    assert any(f.rule == "PT001" and "SwapHandle" in f.message
               for f in findings)


def test_self_lint_catches_reintroduced_raw_jit():
    """Deliberately route the engine's decode step through a raw jax.jit
    instead of its CompileGuard: PT009 must fire — an unregistered step is
    invisible to the compile budgets AND the hlocheck artifact audits."""
    path = REPO / "paddle_tpu" / "serving" / "engine.py"
    src = path.read_text()
    bad = src.replace("self._decode_jit = CompileGuard(",
                      "self._decode_jit = jax.jit(")
    assert bad != src, "engine.py no longer guards the decode step"
    findings = lint_source(bad, "paddle_tpu/serving/engine.py")
    assert any(f.rule == "PT009" and "CompileGuard" in f.message
               for f in findings)
    # the guarded original is clean: the guard IS the sanctioned route
    assert not any(f.rule == "PT009"
                   for f in lint_source(src, "paddle_tpu/serving/engine.py"))


def test_self_lint_catches_reintroduced_rogue_shard_map():
    """Deliberately give the engine its own shard_map import (the way a
    quick hack would shard a step without declaring its budget): PT010
    must fire — an unregistered sharded step can acquire implicit
    resharding collectives no hlocheck audit ever counts. The sanctioned
    serving/tp.py entry point (registered tp2_engine_* steps) stays
    clean under its pragma."""
    path = REPO / "paddle_tpu" / "serving" / "engine.py"
    src = path.read_text()
    bad = src.replace(
        "from ..analysis import hlocheck",
        "from ..analysis import hlocheck\n"
        "from jax.experimental.shard_map import shard_map")
    assert bad != src
    findings = lint_source(bad, "paddle_tpu/serving/engine.py")
    assert any(f.rule == "PT010" and "hlocheck registry" in f.message
               for f in findings)
    tp_src = (REPO / "paddle_tpu" / "serving" / "tp.py").read_text()
    assert "lint: disable=PT010" in tp_src
    assert not any(f.rule == "PT010"
                   for f in lint_source(tp_src,
                                        "paddle_tpu/serving/tp.py"))


def test_self_lint_catches_uncertified_pallas_kernel():
    """Deliberately strip fused_layernorm's KERNELCHECK_CERTS declaration:
    PT011 must fire on every pallas_call — an uncertified kernel ships
    with no VMEM budget, tiling lint, race proof, or roofline contract.
    The declared original stays clean."""
    path = REPO / "paddle_tpu" / "kernels" / "fused_layernorm.py"
    src = path.read_text()
    bad = "\n".join(line for line in src.splitlines()
                    if not line.startswith("KERNELCHECK_CERTS"))
    assert bad != src, "fused_layernorm.py no longer declares its certs"
    findings = lint_source(bad, "paddle_tpu/kernels/fused_layernorm.py")
    assert any(f.rule == "PT011" and "kernelcheck" in f.message
               for f in findings)
    assert not any(f.rule == "PT011" for f in lint_source(
        src, "paddle_tpu/kernels/fused_layernorm.py"))
    # the annotated declaration form sanctions the module just the same
    ann = src.replace("KERNELCHECK_CERTS = ",
                      "KERNELCHECK_CERTS: tuple = ")
    assert ann != src
    assert not any(f.rule == "PT011" for f in lint_source(
        ann, "paddle_tpu/kernels/fused_layernorm.py"))


def test_self_lint_catches_unregistered_stat_family():
    """Deliberately strip the alerts family from metrics._FAMILIES: PT012
    must fire at the on_alert stat_add — a formatted family name
    PT003/PT008 can't resolve would otherwise ship with no pre-seeded
    members. The declared original stays clean."""
    path = REPO / "paddle_tpu" / "serving" / "metrics.py"
    src = path.read_text()
    bad = "\n".join(line for line in src.splitlines()
                    if '"alerts_total": "rule",' not in line)
    assert bad != src, "metrics.py no longer declares the alerts family"
    findings = lint_source(bad, "paddle_tpu/serving/metrics.py")
    assert any(f.rule == "PT012" and "alerts_total" in f.message
               for f in findings)
    assert not any(f.rule in ("PT003", "PT008", "PT012")
                   for f in lint_source(
                       src, "paddle_tpu/serving/metrics.py"))


def test_self_lint_catches_unregistered_multilabel_family():
    """Deliberately strip the multi-label tenant_retired_total family
    from metrics._FAMILIES: PT012 must fire at the on_tenant_retire
    stat_add — the ``base{tenant=,class=}`` shape must not dodge the
    registry — and reordering the write's label keys against the
    declaration must fire the key-mismatch arm (keys are part of the
    registry key the seeding created)."""
    path = REPO / "paddle_tpu" / "serving" / "metrics.py"
    src = path.read_text()
    marker = '"tenant_retired_total": ("tenant", "class"),'
    bad = "\n".join(line for line in src.splitlines()
                    if marker not in line)
    assert bad != src, "metrics.py no longer declares the tenant grid"
    findings = lint_source(bad, "paddle_tpu/serving/metrics.py")
    assert any(f.rule == "PT012" and "tenant_retired_total" in f.message
               for f in findings)
    # a write whose label ORDER disagrees with the declaration fires too
    swapped = src.replace(
        "tenant_retired_total{{tenant={tenant},class={cls}}}",
        "tenant_retired_total{{class={cls},tenant={tenant}}}")
    assert swapped != src
    findings = lint_source(swapped, "paddle_tpu/serving/metrics.py")
    assert any(f.rule == "PT012" and "label keys" in f.message
               for f in findings)
    assert not any(f.rule == "PT012" for f in lint_source(
        src, "paddle_tpu/serving/metrics.py"))


def test_self_lint_catches_unsanctioned_fleet_dispatch():
    """Deliberately strip the pragma off the fleet router's one
    sanctioned add_request site: PT013 must fire — a fleet dispatch
    outside the weighted admission path is the bypass the rule exists
    to close. The pragma'd original stays clean, and the pragma must
    actually exist (a silently deleted site would pass vacuously)."""
    path = REPO / "paddle_tpu" / "serving" / "fleet.py"
    src = path.read_text()
    assert "# lint: disable=PT013" in src, \
        "fleet.py lost its sanctioned dispatch pragma"
    bad = src.replace("  # lint: disable=PT013", "")
    assert bad != src
    findings = lint_source(bad, "paddle_tpu/serving/fleet.py")
    assert any(f.rule == "PT013" and "admission" in f.message
               for f in findings)
    assert not any(f.rule == "PT013" for f in lint_source(
        src, "paddle_tpu/serving/fleet.py"))


def test_self_lint_pt014_gate_is_the_filename():
    """serving/wire.py is the ONE sanctioned struct user: the very same
    codec source linted under any other serving filename fires PT014 —
    the gate is the filename, so moving frame-packing bytes out of
    wire.py (a second codec, a 'quick' side channel) reintroduces the
    raw-struct finding. The real wire.py stays clean, and it genuinely
    exercises the gate (it must actually use struct)."""
    path = REPO / "paddle_tpu" / "serving" / "wire.py"
    src = path.read_text()
    assert "struct" in src, "wire.py no longer packs with struct?"
    assert lint_source(src, "paddle_tpu/serving/wire.py") == []
    findings = lint_source(src, "paddle_tpu/serving/wire2.py")
    assert any(f.rule == "PT014" for f in findings)


def test_self_lint_pt015_gate_is_the_filename():
    """serving/tp.py is the ONE sanctioned psum user: the very same
    module linted under any other serving filename fires PT015 — moving
    a collective out of tp.py (a 'quick' raw reduction beside the
    budgeted wrappers) reintroduces the unbudgeted-psum finding. The
    real tp.py stays clean, and it genuinely exercises the gate (it must
    actually call lax.psum — quantized_psum does)."""
    path = REPO / "paddle_tpu" / "serving" / "tp.py"
    src = path.read_text()
    assert "lax.psum" in src, "tp.py no longer reduces with lax.psum?"
    assert not any(f.rule == "PT015" for f in lint_source(
        src, "paddle_tpu/serving/tp.py"))
    findings = lint_source(src, "paddle_tpu/serving/tp_rogue.py")
    assert any(f.rule == "PT015" for f in findings)
    # and a raw psum pasted into any other serving module fires too —
    # the strip-reintroduction direction: engine.py grows a psum, PT015
    # catches it at the line
    eng = (REPO / "paddle_tpu" / "serving" / "engine.py").read_text()
    bad = eng + "\n\ndef _rogue(x):\n    import jax\n" \
                "    return jax.lax.psum(x, 'tp')\n"
    findings = lint_source(bad, "paddle_tpu/serving/engine.py")
    assert any(f.rule == "PT015" and "tp.py" in f.message
               for f in findings)
    assert not any(f.rule == "PT015" for f in lint_source(
        eng, "paddle_tpu/serving/engine.py"))


def test_self_lint_catches_reintroduced_wall_clock():
    path = REPO / "paddle_tpu" / "serving" / "engine.py"
    src = path.read_text()
    bad = src.replace("self._clock = clock or time.monotonic",
                      "self._clock = clock or (lambda: time.time())")
    assert bad != src
    findings = lint_source(bad, "paddle_tpu/serving/engine.py")
    assert any(f.rule == "PT004" for f in findings)


def test_self_lint_pt016_determinism_fence():
    """PT016's two strip-reintroduction directions. (1) chaos.py's RNG is
    sanctioned ONLY because it is seeded: stripping the seed argument
    from its RandomState fires. (2) the clock gate is the filename:
    engine.py's pluggable-clock default (`clock or time.monotonic`) is
    the one sanctioned wall-clock binding — the very same module linted
    under any other serving filename fires, so moving the clock binding
    out of engine.py reintroduces the finding."""
    chaos = (REPO / "paddle_tpu" / "serving" / "chaos.py").read_text()
    assert "np.random.RandomState(cfg.seed)" in chaos, \
        "chaos.py no longer seeds its RNG this way?"
    assert not any(f.rule == "PT016" for f in lint_source(
        chaos, "paddle_tpu/serving/chaos.py"))
    unseeded = chaos.replace("np.random.RandomState(cfg.seed)",
                             "np.random.RandomState()")
    findings = lint_source(unseeded, "paddle_tpu/serving/chaos.py")
    assert any(f.rule == "PT016" and "seed" in f.message
               for f in findings)

    eng = (REPO / "paddle_tpu" / "serving" / "engine.py").read_text()
    assert "clock or time.monotonic" in eng
    assert not any(f.rule == "PT016" for f in lint_source(
        eng, "paddle_tpu/serving/engine.py"))
    findings = lint_source(eng, "paddle_tpu/serving/scheduler.py")
    assert any(f.rule == "PT016" and "monotonic" in f.message
               for f in findings)


def test_self_lint_pt017_contextless_exchange():
    """PT017 strip-reintroduction: fleet.py's gossip exchange carries an
    EXPLICIT ``rid=None`` — that spelling is the sanctioning. Stripping
    it (the natural refactor slip: "gossip has no request, drop the
    keyword") reintroduces the finding on the very call the rule was
    written for."""
    fleet = (REPO / "paddle_tpu" / "serving" / "fleet.py").read_text()
    assert "step=self._step_idx, rid=None, span=sid" in fleet, \
        "fleet.py's gossip exchange no longer spells rid=None this way?"
    assert not any(f.rule == "PT017" for f in lint_source(
        fleet, "paddle_tpu/serving/fleet.py"))
    stripped = fleet.replace("step=self._step_idx, rid=None, span=sid",
                             "step=self._step_idx, span=sid")
    findings = lint_source(stripped, "paddle_tpu/serving/fleet.py")
    assert any(f.rule == "PT017" and "rid" in f.message
               for f in findings)


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; --durations top mover
def test_lint_cli_exit_codes_and_filters(tmp_path):
    clean = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "paddle_tpu/"],
        cwd=REPO, capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "0 findings" in clean.stdout

    bad = tmp_path / "serving" / "dirty.py"
    bad.parent.mkdir()
    bad.write_text("import time\n\n\ndef step(self, q=[]):\n"
                   "    return time.time()\n")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 1
    assert "PT004" in r.stdout and "PT007" in r.stdout

    only = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", str(tmp_path),
         "--rule", "PT007"],
        cwd=REPO, capture_output=True, text=True)
    assert only.returncode == 1
    assert "PT007" in only.stdout and "PT004" not in only.stdout

    r2 = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", str(tmp_path),
         "--path", "nonexistent-substring"],
        cwd=REPO, capture_output=True, text=True)
    assert r2.returncode == 0

    unknown = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--rule", "PT999"],
        cwd=REPO, capture_output=True, text=True)
    assert unknown.returncode == 2


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; --durations top mover
def test_lint_cli_default_sweep_covers_tests_and_examples():
    """No-path invocation lints the package + tests/ + examples/ (clean
    because fixtures are allowlisted); --include overrides the extra
    trees."""
    clean = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis"],
        cwd=REPO, capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "0 findings" in clean.stdout

    # the default sweep actually REACHES tests/: a transient dirty helper
    # dropped there is found by the no-path invocation...
    probe = REPO / "tests" / "_lint_probe_tmp_do_not_commit.py"
    probe.write_text("def helper(q=[]):\n    return q\n")
    try:
        dirty = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis"],
            cwd=REPO, capture_output=True, text=True)
        assert dirty.returncode == 1 and "PT007" in dirty.stdout
        # ...and --include overrides the extra trees away again
        narrowed = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis",
             "--include", "examples"],
            cwd=REPO, capture_output=True, text=True)
        assert narrowed.returncode == 0, narrowed.stdout + narrowed.stderr
    finally:
        probe.unlink()


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; --durations top mover
def test_tools_lint_entry_point():
    r = subprocess.run([sys.executable, str(REPO / "tools" / "lint.py")],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 findings" in r.stdout
