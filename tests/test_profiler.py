"""Profiler / host tracer tests (reference analog:
tests/unittests/test_profiler.py, new_profiler tests)."""
import json
import time

import paddle_tpu as paddle
from paddle_tpu import profiler


def test_record_event_ring_buffer_and_chrome_export(tmp_path):
    tr = profiler.host_tracer()
    tr.clear()
    with profiler.RecordEvent("step"):
        with profiler.RecordEvent("forward"):
            time.sleep(0.001)
        with profiler.RecordEvent("backward"):
            pass
    assert tr.count() == 3
    path = str(tmp_path / "trace.json")
    n = tr.export_chrome_trace(path)
    assert n == 3
    with open(path) as f:
        doc = json.load(f)
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"step", "forward", "backward"} <= names
    fw = next(e for e in doc["traceEvents"] if e.get("name") == "forward")
    assert fw["dur"] >= 1000.0  # >= 1ms in us units


def test_ring_buffer_overwrites_oldest():
    from paddle_tpu.profiler import _HostTracer

    tr = _HostTracer(capacity=4)
    for i in range(10):
        tr.record(f"e{i}", i * 100, 10, 1)
    assert tr.count() == 4


def test_profiler_timer_summary():
    prof = paddle.profiler.Profiler(timer_only=True)
    prof.start()
    for _ in range(3):
        time.sleep(0.002)
        prof.step()
    prof.stop()
    s = prof.summary()
    assert "steps: 3" in s


def test_benchmark_ips():
    b = paddle.profiler.benchmark()
    b.begin()
    for _ in range(5):
        time.sleep(0.001)
        b.step(num_samples=32)
    rep = b.report()
    assert rep["steps"] == 5 and rep["ips"] > 0


def test_chrome_export_escapes_control_chars(tmp_path):
    tr = profiler.host_tracer()
    tr.clear()
    tr.record("step\n1\t\"x\"", 0, 100, 1)
    path = str(tmp_path / "esc.json")
    tr.export_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)  # must parse despite control chars in the name
    names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert names == ['step\n1\t"x"']


def test_profiler_statistics_and_result_roundtrip(tmp_path):
    """SortedKeys / export_protobuf / load_profiler_result / summary
    (reference: profiler_statistic.py:35, profiler.py:209, utils.py:128)."""
    import time as _time

    import paddle_tpu.profiler as profiler

    profiler.host_tracer().clear()
    for _ in range(3):
        with profiler.RecordEvent("stat_op_a"):
            _time.sleep(0.002)
    with profiler.RecordEvent("stat_op_b"):
        _time.sleep(0.001)
    handler = profiler.export_protobuf(str(tmp_path), worker_name="w0")
    path = handler()
    assert path.endswith("w0.paddle_trace.pb")
    res = profiler.load_profiler_result(path)
    stats = res.per_name_stats()
    assert stats["stat_op_a"]["calls"] == 3
    assert stats["stat_op_a"]["total_ns"] > stats["stat_op_b"]["total_ns"]
    table = profiler.summary(res, sorted_by=profiler.SortedKeys.CPUTotal)
    first_data_row = table.splitlines()[1]
    assert "stat_op_a" in first_data_row  # sorted by total desc
