"""examples/ must keep running end-to-end (each asserts its own learning/
round-trip invariants internally)."""
import os
import subprocess
import sys

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


def _run(script, extra_env=None, timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = ""  # drop the axon sitecustomize: examples pin CPU
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", script)],
        env=env, cwd=_ROOT, timeout=timeout,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


@pytest.mark.slow
@pytest.mark.parametrize("script,extra", [
    ("train_gpt.py", None),
    ("static_train_export.py", None),
    ("fleet_hybrid.py",
     {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}),
    ("fluid_legacy.py", None),
    ("auto_parallel_plan.py",
     {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}),
    ("serving_demo.py", None),
])
def test_example_runs(script, extra):
    proc = _run(script, extra)
    assert proc.returncode == 0, proc.stdout.decode()[-2000:]
