"""Serving resilience: deadlines, cancellation, backpressure, swap
preemption, and the fault-injection harness (paddle_tpu/serving/faults.py).

Everything is deterministic — the engine clock is a manually-held fake and
time only advances through ``slow_step`` fault skew; no sleeps anywhere.
The page-accounting invariant every scenario ends on: ``pages_in_use``
returns to 0 once the engine drains, whatever was cancelled, expired,
shed, swapped, or failed along the way.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.serving import (EngineOverloaded, FaultInjector,
                                InjectedFault, ServingConfig, ServingEngine)
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.faults


class FakeClock:
    """Engine time that only moves when the test (or a slow_step fault via
    the engine's skew) says so."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _toy_model(seed=11):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=48, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _reference(model, prompt, budget):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=budget)
    return np.asarray(out._value)[0]


def _prompts(seed, lens):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 97, (n,)).astype(np.int32) for n in lens]


# ------------------------------------------------------------- faults unit
def test_injector_arm_validation_and_matching():
    inj = FaultInjector()
    with pytest.raises(ValueError):
        inj.arm("bogus_point")
    with pytest.raises(ValueError):
        inj.arm("decode_fail", times=0)
    inj.arm("decode_fail", step=3, rid=7).arm("slow_step", delay_s=2.5)
    assert inj.hit("decode_fail", step=2, rid=7) is None  # wrong step
    assert inj.hit("decode_fail", step=3, rid=8) is None  # wrong rid
    assert inj.hit("decode_fail", step=3, rid=7) is not None
    assert inj.hit("decode_fail", step=3, rid=7) is None  # consumed
    # wildcard step, unlimited firings
    inj.arm("pool_exhausted", times=-1)
    assert inj.hit("pool_exhausted", step=0) is not None
    assert inj.hit("pool_exhausted", step=99) is not None
    assert inj.hit("slow_step", step=5).delay_s == 2.5
    assert ("decode_fail", 3, 7) in inj.fired


# ------------------------------------------------- deadlines & cancellation
def test_deadline_expiry_under_pool_pressure():
    # r1 holds the whole 3-page pool, so r2 waits head-of-line; an injected
    # 10s stall (slow_step skew — time never really passes) blows r2's 5s
    # deadline while it is still queued
    model = _toy_model()
    clock = FakeClock()
    inj = FaultInjector().arm("slow_step", step=2, delay_s=10.0)
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=4, page_size=4, max_prompt_len=8),
        clock=clock, fault_injector=inj)
    p1, p2 = _prompts(0, (6, 4))
    r1 = engine.add_request(p1, 6)
    r2 = engine.add_request(p2, 4, deadline_s=5.0)
    outs = engine.run()
    assert set(outs) == {r1}
    np.testing.assert_array_equal(_reference(model, p1, 6), outs[r1])
    assert engine.status(r2) == "expired"
    assert engine.metrics.snapshot()["serving_expired"] == 1
    assert engine.cache.allocator.pages_in_use == 0
    assert inj.fired == [("slow_step", 2, None)]


def test_deadline_expires_running_request_and_frees_pages():
    model = _toy_model()
    clock = FakeClock()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8),
        clock=clock)
    p1, p2 = _prompts(1, (5, 4))
    r1 = engine.add_request(p1, 12, deadline_s=3.0)
    r2 = engine.add_request(p2, 4)
    engine.step()  # both admitted and decoding
    assert engine.status(r1) == "running"
    used_mid = engine.cache.allocator.pages_in_use
    clock.advance(5.0)  # past r1's deadline, mid-generation
    engine.step()
    assert engine.status(r1) == "expired"
    assert engine.cache.allocator.pages_in_use < used_mid
    outs = engine.run()
    assert set(outs) == {r2}
    np.testing.assert_array_equal(_reference(model, p2, 4), outs[r2])
    assert engine.cache.allocator.pages_in_use == 0


def test_cancel_while_running_frees_pages():
    model = _toy_model()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8))
    p1, p2, p3 = _prompts(2, (5, 4, 3))
    r1 = engine.add_request(p1, 10)
    r2 = engine.add_request(p2, 4)
    engine.step()
    used_both = engine.cache.allocator.pages_in_use
    assert engine.cancel(r1)
    assert engine.cache.allocator.pages_in_use < used_both
    assert engine.status(r1) == "cancelled"
    assert not engine.cancel(r1)       # already terminal
    assert not engine.cancel(424242)   # unknown
    r3 = engine.add_request(p3, 3)
    assert engine.cancel(r3)  # cancel straight out of the waiting queue
    outs = engine.run()
    assert set(outs) == {r2}
    np.testing.assert_array_equal(_reference(model, p2, 4), outs[r2])
    assert engine.cache.allocator.pages_in_use == 0
    assert engine.metrics.snapshot()["serving_cancelled"] == 2
    assert set(engine.pop_retired()) == {r1, r3}


# ------------------------------------------------------------- backpressure
def test_full_queue_rejects_deterministically():
    model = _toy_model()
    engine = ServingEngine(model, ServingConfig(
        max_batch=1, num_pages=24, page_size=4, max_prompt_len=8,
        max_waiting=1, shed_policy="reject"))
    p1, p2, p3 = _prompts(3, (4, 4, 4))
    r1 = engine.add_request(p1, 4)
    engine.step()  # r1 takes the lone slot
    r2 = engine.add_request(p2, 4)  # fills the queue
    with pytest.raises(EngineOverloaded):
        engine.add_request(p3, 4)
    assert engine.metrics.snapshot()["serving_rejected"] == 1
    outs = engine.run()
    assert set(outs) == {r1, r2}
    assert engine.cache.allocator.pages_in_use == 0


def test_shed_oldest_keeps_fifo_order_for_survivors():
    model = _toy_model()
    engine = ServingEngine(model, ServingConfig(
        max_batch=1, num_pages=24, page_size=4, max_prompt_len=8,
        max_waiting=2, shed_policy="shed-oldest"))
    prompts = _prompts(4, (4, 5, 3, 6))
    r1 = engine.add_request(prompts[0], 4)
    engine.step()  # r1 running; the queue is for r2..r4
    r2 = engine.add_request(prompts[1], 4)
    r3 = engine.add_request(prompts[2], 4)
    r4 = engine.add_request(prompts[3], 4)  # queue full -> sheds r2
    assert engine.status(r2) == "shed"
    assert engine.metrics.snapshot()["serving_shed"] == 1
    order = []
    while not engine.scheduler.all_done:
        order.extend(engine.step())
    assert order == [r1, r3, r4], "survivors must finish in arrival order"
    for rid, i in ((r1, 0), (r3, 2), (r4, 3)):
        np.testing.assert_array_equal(
            _reference(model, prompts[i], 4), engine.result(rid))
    assert engine.cache.allocator.pages_in_use == 0


def test_shed_oldest_never_sheds_a_preemption_victim():
    # a preempted request requeues at the FRONT of the waiting queue — it is
    # not the "oldest waiter", it is admitted work in flight. shed-oldest
    # must shed the longest-waiting NEWCOMER instead, and reject outright
    # when the queue holds only preemption victims.
    model = _toy_model()
    prompts = _prompts(11, (4, 5, 3, 4))
    inj = FaultInjector().arm("pool_exhausted", step=2)
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8,
        max_waiting=1, shed_policy="shed-oldest"), fault_injector=inj)
    r1 = engine.add_request(prompts[0], 6)
    engine.step()  # admit r1 before r2 queues (max_waiting=1)
    r2 = engine.add_request(prompts[1], 6)
    engine.step(); engine.step()  # step 2 preempts one running request
    victim = [r for r in (r1, r2) if engine.status(r) == "waiting"]
    assert len(victim) == 1, "pool_exhausted must have preempted one request"
    # queue == [victim] and max_waiting=1: full of in-flight work only
    with pytest.raises(EngineOverloaded):
        engine.add_request(prompts[2], 3)
    assert engine.metrics.snapshot()["serving_rejected"] == 1
    outs = engine.run()  # the victim is never lost
    assert set(outs) == {r1, r2}
    assert engine.cache.allocator.pages_in_use == 0


def test_shed_oldest_skips_victim_and_sheds_oldest_newcomer():
    model = _toy_model()
    prompts = _prompts(12, (4, 5, 3, 4))
    inj = FaultInjector().arm("pool_exhausted", step=2)
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8,
        max_waiting=2, shed_policy="shed-oldest"), fault_injector=inj)
    r1 = engine.add_request(prompts[0], 6)
    r2 = engine.add_request(prompts[1], 6)
    engine.step(); engine.step(); engine.step()  # step 2 preempts one
    r3 = engine.add_request(prompts[2], 3)  # queue: [victim, r3]
    r4 = engine.add_request(prompts[3], 3)  # full -> sheds r3, NOT victim
    assert engine.status(r3) == "shed"
    outs = engine.run()
    assert set(outs) == {r1, r2, r4}
    assert engine.cache.allocator.pages_in_use == 0


# ---------------------------------------------------------- swap preemption
@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 budget; swap-vs-recompute parity stays pinned
# tier-1 by test_serving_tp's preemption-parity pair (both modes, TP=1 reference engines included)
# and test_serving's swap suite
def test_swap_preempt_parity_with_recompute():
    model = _toy_model(seed=13)
    prompts = _prompts(5, (6, 5, 4))
    budgets = [10, 9, 8]

    def drive(mode):
        engine = ServingEngine(model, ServingConfig(
            max_batch=3, num_pages=8, page_size=4, max_prompt_len=8,
            preemption_mode=mode))
        rids = [engine.add_request(p, b) for p, b in zip(prompts, budgets)]
        outs = engine.run()
        # snapshot before the next engine resets the process-wide registry
        return engine, rids, outs, engine.metrics.snapshot()

    eng_r, rids_r, outs_r, snap_r = drive("recompute")
    eng_s, rids_s, outs_s, snap_s = drive("swap")
    assert eng_r.scheduler.preemption_count > 0
    assert eng_s.scheduler.preemption_count > 0
    for i, (rr, rs) in enumerate(zip(rids_r, rids_s)):
        ref = _reference(model, prompts[i], budgets[i])
        np.testing.assert_array_equal(ref, outs_r[rr])
        np.testing.assert_array_equal(ref, outs_s[rs])
    assert snap_s["serving_swap_outs"] > 0
    assert snap_s["serving_swap_ins"] == snap_s["serving_swap_outs"]
    # swap keeps generated tokens: every request prefills exactly once,
    # while recompute re-prefills its preemption victims
    assert snap_s["serving_prefills_total"] == len(prompts)
    assert snap_r["serving_prefills_total"] > len(prompts)
    # host<->device swaps never change pool shapes: still one trace each
    assert eng_s.compile_counts == {"prefill": 1, "decode": 1}
    assert eng_s.cache.allocator.pages_in_use == 0
    assert eng_r.cache.allocator.pages_in_use == 0


# ---------------------------------------------------------- injected faults
def test_decode_fail_isolates_the_failed_request():
    model = _toy_model()
    prompts = _prompts(6, (5, 4, 6))
    budgets = [6, 8, 5]
    inj = FaultInjector()
    engine = ServingEngine(model, ServingConfig(
        max_batch=3, num_pages=24, page_size=4, max_prompt_len=8),
        fault_injector=inj)
    rids = [engine.add_request(p, b) for p, b in zip(prompts, budgets)]
    inj.arm("decode_fail", step=2, rid=rids[1])
    outs = engine.run()
    assert set(outs) == {rids[0], rids[2]}, "non-faulted requests finish"
    for i in (0, 2):
        np.testing.assert_array_equal(
            _reference(model, prompts[i], budgets[i]), outs[rids[i]])
    assert engine.status(rids[1]) == "failed"
    err = engine.request(rids[1]).error
    assert isinstance(err, InjectedFault) and "decode_fail" in str(err)
    assert engine.metrics.snapshot()["serving_failed"] == 1
    assert engine.cache.allocator.pages_in_use == 0, \
        "a faulted step must not corrupt page accounting"


def test_verify_fail_retires_mid_speculation_and_survivors_keep_serving():
    # speculative decoding (ServingConfig(spec=)): the verify_fail point
    # is consulted before the verify dispatch — the faulted request
    # retires FAILED with its pages (including the K-token speculative
    # over-reservation the scheduler grew for this very step) draining,
    # the stateless draft proposer needs no cleanup, and the survivors
    # verify this same step with exact output parity
    from paddle_tpu.serving import SpecConfig

    model = _toy_model()
    prompts = _prompts(11, (5, 4, 6))
    budgets = [6, 8, 5]
    inj = FaultInjector()
    engine = ServingEngine(model, ServingConfig(
        max_batch=3, num_pages=24, page_size=4, max_prompt_len=8,
        spec=SpecConfig(method="ngram", depth=4)), fault_injector=inj)
    rids = [engine.add_request(p, b) for p, b in zip(prompts, budgets)]
    # step 1: rids[1] (budget 8) is certainly still mid-speculation — one
    # verify step emits at most depth + 1 = 5 tokens
    inj.arm("verify_fail", step=1, rid=rids[1])
    outs = engine.run()
    assert set(outs) == {rids[0], rids[2]}, "non-faulted requests finish"
    for i in (0, 2):
        np.testing.assert_array_equal(
            _reference(model, prompts[i], budgets[i]), outs[rids[i]])
    assert engine.status(rids[1]) == "failed"
    err = engine.request(rids[1]).error
    assert isinstance(err, InjectedFault) and "verify_fail" in str(err)
    assert engine.metrics.snapshot()["serving_failed"] == 1
    assert engine.compile_counts["verify"] == 1
    assert engine.cache.allocator.pages_in_use == 0, \
        "a faulted verify must not corrupt page accounting"


def test_prefill_fail_undoes_admission_only_for_the_victim():
    model = _toy_model()
    prompts = _prompts(7, (5, 4))
    inj = FaultInjector()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8),
        fault_injector=inj)
    r1 = engine.add_request(prompts[0], 5)
    r2 = engine.add_request(prompts[1], 4)
    inj.arm("prefill_fail", rid=r1)
    outs = engine.run()
    assert set(outs) == {r2}
    np.testing.assert_array_equal(
        _reference(model, prompts[1], 4), outs[r2])
    assert engine.status(r1) == "failed"
    assert isinstance(engine.request(r1).error, InjectedFault)
    assert engine.cache.allocator.pages_in_use == 0


def test_chunk_fail_retires_mid_prefill_and_survivors_keep_serving():
    # chunked prefill: the whale fails on its SECOND chunk (step 1), after
    # one chunk of its prompt KV is already resident — the partial prefill
    # must drain with the retirement while the short survivor (admitted
    # the same step, decoding by then) finishes with exact parity
    model = _toy_model()
    whale, short = _prompts(15, (20, 5))
    inj = FaultInjector()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=24,
        chunk_size=8), fault_injector=inj)
    r1 = engine.add_request(whale, 6)
    r2 = engine.add_request(short, 4)
    inj.arm("chunk_fail", step=1, rid=r1)
    outs = engine.run()
    assert set(outs) == {r2}, "only the non-faulted request finishes"
    np.testing.assert_array_equal(_reference(model, short, 4), outs[r2])
    assert engine.status(r1) == "failed"
    err = engine.request(r1).error
    assert isinstance(err, InjectedFault) and "chunk_fail" in str(err)
    assert inj.fired == [("chunk_fail", 1, r1)]
    snap = engine.metrics.snapshot()
    assert snap["serving_failed"] == 1
    # exactly one chunk ran before the fault; no prefill ever completed
    # for the whale (prefills_total counts only the survivor's)
    assert snap["serving_prefill_chunks_total"] == 2  # whale's 1st + short
    assert snap["serving_prefills_total"] == 1
    assert engine.cache.allocator.pages_in_use == 0, \
        "a mid-prefill failure must not leak the partial prompt's pages"


@pytest.mark.slow  # re-tiered 2026-08 (PR 20): tier-1 crossed its 870 s
# budget; the budget-drain preemption test keeps the victim-resume path
# hot in tier-1
def test_pool_exhausted_injection_forces_preemption():
    # the pool is actually ample — the injector simulates it running dry,
    # and the victim-policy preemption must still converge to full parity
    model = _toy_model()
    prompts = _prompts(8, (5, 4))
    budgets = [8, 7]
    inj = FaultInjector().arm("pool_exhausted", step=3)
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8),
        fault_injector=inj)
    rids = [engine.add_request(p, b) for p, b in zip(prompts, budgets)]
    outs = engine.run()
    assert engine.scheduler.preemption_count >= 1
    assert inj.fired == [("pool_exhausted", 3, None)]
    for i, rid in enumerate(rids):
        np.testing.assert_array_equal(
            _reference(model, prompts[i], budgets[i]), outs[rid])
    assert engine.cache.allocator.pages_in_use == 0


# ----------------------------------------------------- run() budget + drain
def test_run_budget_pauses_admission_and_drains_gracefully():
    model = _toy_model()
    clock = FakeClock()
    inj = FaultInjector().arm("slow_step", times=-1, delay_s=2.0)
    engine = ServingEngine(model, ServingConfig(
        max_batch=1, num_pages=24, page_size=4, max_prompt_len=8),
        clock=clock, fault_injector=inj)
    p1, p2 = _prompts(9, (4, 5))
    r1 = engine.add_request(p1, 6)
    r2 = engine.add_request(p2, 3)
    outs = engine.run(budget_s=3.0)
    # every virtual step costs 2s: the budget elapses mid-r1, which drains
    # to completion; r2 is never admitted and stays queued — no exception
    assert set(outs) == {r1}
    np.testing.assert_array_equal(_reference(model, p1, 6), outs[r1])
    assert engine.status(r2) == "waiting"
    assert not engine.admit_paused, "drain must re-enable admission"
    outs2 = engine.run()  # a later call serves the carried-over queue
    assert set(outs2) == {r2}
    np.testing.assert_array_equal(_reference(model, p2, 3), outs2[r2])
    assert engine.cache.allocator.pages_in_use == 0


def test_budget_drain_still_resumes_preemption_victims():
    # the budget pauses NEWCOMER admission only: a request preempted after
    # the budget elapsed is in-flight work and must drain to completion,
    # not sit abandoned in the queue (in recompute mode it would also have
    # lost every generated token)
    model = _toy_model()
    clock = FakeClock()
    inj = FaultInjector().arm("slow_step", times=-1, delay_s=2.0)
    for mode in ("recompute", "swap"):
        # 4 usable pages; the two requests need 4+4=8 at peak -> guaranteed
        # preemption mid-decode, well after the 1s budget elapsed at step 0
        engine = ServingEngine(model, ServingConfig(
            max_batch=2, num_pages=5, page_size=4, max_prompt_len=8,
            preemption_mode=mode), clock=clock, fault_injector=inj)
        p1, p2 = _prompts(14, (6, 5))
        r1 = engine.add_request(p1, 8)
        r2 = engine.add_request(p2, 8)
        outs = engine.run(budget_s=1.0)
        assert set(outs) == {r1, r2}, \
            f"{mode}: a preempted in-flight request was abandoned by drain"
        assert engine.scheduler.preemption_count > 0, "setup must preempt"
        np.testing.assert_array_equal(_reference(model, p1, 8), outs[r1])
        np.testing.assert_array_equal(_reference(model, p2, 8), outs[r2])
        assert engine.cache.allocator.pages_in_use == 0


def test_run_honors_and_preserves_caller_set_admit_pause():
    # admit_paused is a documented caller knob: run() must drain in-flight
    # work, leave the queue untouched, and NOT flip the flag back on exit
    model = _toy_model()
    engine = ServingEngine(model, ServingConfig(
        max_batch=1, num_pages=24, page_size=4, max_prompt_len=8))
    p1, p2 = _prompts(13, (4, 5))
    r1 = engine.add_request(p1, 4)
    engine.step()  # r1 takes the lone slot
    r2 = engine.add_request(p2, 3)
    engine.admit_paused = True
    outs = engine.run()  # drains r1, returns instead of spinning on r2
    assert set(outs) == {r1}
    assert engine.status(r2) == "waiting"
    assert engine.admit_paused, "run() must not clobber the caller's pause"
    engine.admit_paused = False
    outs2 = engine.run()
    assert set(outs2) == {r2}
    np.testing.assert_array_equal(_reference(model, p2, 3), outs2[r2])
    assert engine.cache.allocator.pages_in_use == 0


# ----------------------------------------------------- zero-overhead default
def test_default_path_is_one_injector_lookup_per_step():
    # the engine may consult the injector exactly ONCE per step; with none
    # installed the whole harness must cost one attribute read + None check
    class CountingEngine(ServingEngine):
        reads = 0

        @property
        def _fault_injector(self):
            CountingEngine.reads += 1
            return self.__dict__.get("_fault_injector_value")

        @_fault_injector.setter
        def _fault_injector(self, value):
            self.__dict__["_fault_injector_value"] = value

    model = _toy_model()
    engine = CountingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8))
    engine.add_request(_prompts(10, (4,))[0], 3)
    CountingEngine.reads = 0
    engine.step()
    assert CountingEngine.reads == 1
    engine.step()
    assert CountingEngine.reads == 2
