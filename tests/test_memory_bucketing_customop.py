"""Tests for the round-3 gap closures: allocator stats surface (survey #5),
bucketing/padding dynamic-shape policy (hard-part #2 / LoD analog, #30),
and out-of-tree custom op registration (#15).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


# ------------------------------------------------------------ memory surface
@pytest.mark.slow
def test_memory_stats_surface():
    from paddle_tpu.core import memory

    s = memory.memory_stats()
    assert isinstance(s, dict)  # CPU backend may report {} — shape, not values
    assert memory.memory_allocated() >= 0
    assert memory.max_memory_allocated() >= memory.memory_allocated() or \
        memory.max_memory_allocated() == 0
    with pytest.raises(ValueError):
        memory.set_memory_fraction(1.5)
    import os

    memory.set_memory_fraction(0.5)
    assert os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.5"
    memory.set_preallocate(False)
    assert os.environ["XLA_PYTHON_CLIENT_PREALLOCATE"] == "false"
    memory.empty_cache()  # must not raise


# ---------------------------------------------------------------- bucketing
def test_bucket_boundaries_and_padding():
    from paddle_tpu.io import bucket_boundaries, pad_sequence_batch, pad_to_bucket

    b = bucket_boundaries(100, scheme="pow2", min_len=16)
    assert b == [16, 32, 64, 100]
    arr, n = pad_to_bucket(np.arange(20), b)
    assert arr.shape == (32,) and n == 20 and arr[20:].sum() == 0
    with pytest.raises(ValueError):
        pad_to_bucket(np.arange(200), b)

    batch, lengths = pad_sequence_batch(
        [np.ones(5), np.ones(9)], boundaries=b, pad_value=0)
    assert batch.shape == (2, 16)
    assert list(lengths) == [5, 9]


def test_length_bucket_sampler_bounds_shapes():
    """Every batch pads to ONE boundary; total distinct padded shapes <=
    ladder size (the compile-count bound that replaces LoD)."""
    from paddle_tpu.io import Dataset, LengthBucketSampler, bucket_boundaries

    class Var(Dataset):
        def __init__(self):
            rng = np.random.RandomState(0)
            self.lens = rng.randint(3, 90, size=40)

        def __len__(self):
            return 40

        def __getitem__(self, i):
            return np.arange(self.lens[i])

    ds = Var()
    ladder = bucket_boundaries(96, scheme="pow2", min_len=8)
    sampler = LengthBucketSampler(ds, lambda d, i: d.lens[i], ladder,
                                  batch_size=4)
    seen = set()
    count = 0
    for batch in sampler:
        bucket = sampler.bucket_of(batch)
        for i in batch:
            assert ds.lens[i] <= bucket
        seen.add(bucket)
        count += len(batch)
    assert count == 40  # every sample appears exactly once
    assert seen <= set(ladder)
    assert len(sampler) >= len(seen)


# ---------------------------------------------------------------- custom ops
def test_register_custom_op_with_grad():
    import jax.numpy as jnp

    from paddle_tpu.utils.custom_op import (
        CustomOpError, get_op, register_op, registered_ops)

    def swish_beta2(x):
        return x / (1 + jnp.exp(-2.0 * x))

    def swish_bwd(inputs, g):
        (x,) = inputs
        s = 1 / (1 + jnp.exp(-2.0 * x))
        return (g * (s + 2.0 * x * s * (1 - s)),)

    op = register_op("swish2", swish_beta2, backward=swish_bwd)
    assert "swish2" in registered_ops()
    assert get_op("swish2") is op
    with pytest.raises(CustomOpError):
        register_op("swish2", swish_beta2)

    x = paddle.to_tensor(np.linspace(-2, 2, 7).astype(np.float32),
                         stop_gradient=False)
    y = op(x)
    np.testing.assert_allclose(
        y.numpy(), x.numpy() / (1 + np.exp(-2 * x.numpy())), rtol=1e-6)
    y.sum().backward()
    # custom VJP matches finite differences
    eps = 1e-3
    num = ((x.numpy() + eps) / (1 + np.exp(-2 * (x.numpy() + eps))) -
           (x.numpy() - eps) / (1 + np.exp(-2 * (x.numpy() - eps)))) / (2 * eps)
    np.testing.assert_allclose(x.grad.numpy(), num, rtol=1e-3, atol=1e-4)


def test_custom_op_in_static_program():
    from paddle_tpu import static
    from paddle_tpu.utils.custom_op import register_op

    import jax.numpy as jnp

    op = register_op("double_plus", lambda a, b: 2.0 * a + b, override=True)
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [3], "float32")
            out = op(x, x)
        names = [o.type for o in main.all_ops()]
        assert "double_plus" in names, names
        exe = static.Executor()
        res = exe.run(main, feed={"x": np.ones(3, np.float32)},
                      fetch_list=[out])
        np.testing.assert_allclose(res[0], 3.0)
    finally:
        paddle.disable_static()
