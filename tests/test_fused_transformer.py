"""incubate.nn fused transformer layers (reference incubate/nn/layer/
fused_transformer.py) — validated against an INDEPENDENT composition of
standard ops."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import incubate
from paddle_tpu.incubate.nn import (
    FusedFeedForward,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)
from paddle_tpu.incubate.nn.functional import (
    fused_feedforward,
    fused_multi_head_attention,
)


def _ln(x, scale, bias, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * scale + bias


def _ref_mha(x, wqkv, bqkv, wlin, blin, ln_s, ln_b, pre_s, pre_b,
             pre_layer_norm, mask=None):
    b, s, d = x.shape
    _, n, h, _ = wqkv.shape
    src = _ln(x, pre_s, pre_b) if pre_layer_norm else x
    qkv = np.einsum("bsd,tnhd->tbnsh", src, wqkv) + bqkv[:, None, :, None, :]
    q, k, v = qkv
    logits = np.einsum("bnsh,bnth->bnst", q, k) / np.sqrt(h)
    if mask is not None:
        logits = logits + mask
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    ctx = np.einsum("bnst,bnth->bnsh", probs, v)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, n * h)
    out = x + (ctx @ wlin + blin)
    return out if pre_layer_norm else _ln(out, ln_s, ln_b)


@pytest.mark.parametrize("pre", [False, True])
def test_fused_mha_matches_reference_composition(pre):
    rng = np.random.RandomState(0)
    d, n = 16, 2
    x = rng.randn(2, 5, d).astype(np.float32)
    wqkv = (rng.randn(3, n, d // n, d) * 0.2).astype(np.float32)
    bqkv = (rng.randn(3, n, d // n) * 0.1).astype(np.float32)
    wlin = (rng.randn(d, d) * 0.2).astype(np.float32)
    blin = (rng.randn(d) * 0.1).astype(np.float32)
    ln_s = rng.rand(d).astype(np.float32) + 0.5
    ln_b = (rng.randn(d) * 0.1).astype(np.float32)
    pre_s = rng.rand(d).astype(np.float32) + 0.5
    pre_b = (rng.randn(d) * 0.1).astype(np.float32)

    out = fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(wqkv), paddle.to_tensor(wlin),
        pre_layer_norm=pre, pre_ln_scale=paddle.to_tensor(pre_s),
        pre_ln_bias=paddle.to_tensor(pre_b), ln_scale=paddle.to_tensor(ln_s),
        ln_bias=paddle.to_tensor(ln_b), qkv_bias=paddle.to_tensor(bqkv),
        linear_bias=paddle.to_tensor(blin), dropout_rate=0.0,
        attn_dropout_rate=0.0, training=False)
    ref = _ref_mha(x, wqkv, bqkv, wlin, blin, ln_s, ln_b, pre_s, pre_b, pre)
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=2e-4,
                               atol=2e-5)


def test_fused_mha_attn_mask():
    rng = np.random.RandomState(1)
    d, n, s = 8, 2, 4
    x = rng.randn(1, s, d).astype(np.float32)
    wqkv = (rng.randn(3, n, d // n, d) * 0.3).astype(np.float32)
    wlin = np.eye(d, dtype=np.float32)
    ln_s, ln_b = np.ones(d, np.float32), np.zeros(d, np.float32)
    # causal additive mask [1, n, s, s]
    mask = np.triu(np.full((s, s), -1e9, np.float32), 1)[None, None]
    out = fused_multi_head_attention(
        paddle.to_tensor(x), paddle.to_tensor(wqkv), paddle.to_tensor(wlin),
        ln_scale=paddle.to_tensor(ln_s), ln_bias=paddle.to_tensor(ln_b),
        attn_mask=paddle.to_tensor(mask), dropout_rate=0.0,
        attn_dropout_rate=0.0, training=False)
    ref = _ref_mha(x, wqkv, np.zeros((3, n, d // n), np.float32), wlin,
                   np.zeros(d, np.float32), ln_s, ln_b, ln_s, ln_b, False,
                   mask=mask)
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=2e-4,
                               atol=2e-5)


@pytest.mark.parametrize("pre", [False, True])
def test_fused_feedforward_matches_composition(pre):
    rng = np.random.RandomState(2)
    d, dff = 12, 24
    x = rng.randn(2, 3, d).astype(np.float32)
    w1 = (rng.randn(d, dff) * 0.3).astype(np.float32)
    b1 = (rng.randn(dff) * 0.1).astype(np.float32)
    w2 = (rng.randn(dff, d) * 0.3).astype(np.float32)
    b2 = (rng.randn(d) * 0.1).astype(np.float32)
    s1 = rng.rand(d).astype(np.float32) + 0.5
    c1 = (rng.randn(d) * 0.1).astype(np.float32)
    s2 = rng.rand(d).astype(np.float32) + 0.5
    c2 = (rng.randn(d) * 0.1).astype(np.float32)
    out = fused_feedforward(
        paddle.to_tensor(x), paddle.to_tensor(w1), paddle.to_tensor(w2),
        linear1_bias=paddle.to_tensor(b1), linear2_bias=paddle.to_tensor(b2),
        ln1_scale=paddle.to_tensor(s1), ln1_bias=paddle.to_tensor(c1),
        ln2_scale=paddle.to_tensor(s2), ln2_bias=paddle.to_tensor(c2),
        dropout1_rate=0.0, dropout2_rate=0.0, activation="relu",
        pre_layer_norm=pre, training=False)
    src = _ln(x, s1, c1) if pre else x
    mid = np.maximum(src @ w1 + b1, 0.0) @ w2 + b2
    ref = x + mid if pre else _ln(x + mid, s2, c2)
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=2e-4,
                               atol=2e-5)


@pytest.mark.slow
def test_fused_encoder_layer_trains():
    paddle.seed(4)
    enc = FusedTransformerEncoderLayer(16, 2, 32, dropout_rate=0.0)
    opt = paddle.optimizer.AdamW(1e-3, parameters=enc.parameters())
    x = paddle.to_tensor(np.random.RandomState(3).randn(2, 6, 16)
                         .astype(np.float32))
    tgt = paddle.to_tensor(np.random.RandomState(4).randn(2, 6, 16)
                           .astype(np.float32))
    losses = []
    for _ in range(8):
        loss = ((enc(x) - tgt) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
    assert len(list(enc.parameters())) == 16  # 8 MHA + 8 FFN


@pytest.mark.slow
def test_fused_multi_transformer_stack():
    mt = FusedMultiTransformer(16, 2, 32, num_layers=3)
    mt.eval()
    x = paddle.to_tensor(np.random.RandomState(5).randn(2, 4, 16)
                         .astype(np.float32))
    out = mt(x)
    assert out.shape == [2, 4, 16]
    assert np.isfinite(np.asarray(out._value)).all()
    assert len(list(mt.parameters())) == 36  # 12 groups x 3 layers


@pytest.mark.slow
def test_fused_multi_transformer_kv_cache_decode_parity():
    """Incremental decoding with caches must reproduce the full causal
    forward position for position (the generation-serving contract)."""
    paddle.seed(13)
    mt = FusedMultiTransformer(16, 2, 32, num_layers=2)
    mt.eval()
    rng = np.random.RandomState(11)
    S = 5
    x = rng.randn(1, S, 16).astype(np.float32)
    causal = np.triu(np.full((S, S), -1e9, np.float32), 1)[None, None]
    full = np.asarray(mt(paddle.to_tensor(x),
                         attn_mask=paddle.to_tensor(causal))._value)

    # prefill on the first 2 tokens (NO mask: cached path is causal by
    # default, incl. within the chunk), then decode 3 tokens one at a time
    out, caches = mt(paddle.to_tensor(x[:, :2]), caches=[])
    steps = [np.asarray(out._value)]
    assert caches[0].shape[3] == 2  # prefix length cached per layer
    assert caches[0].stop_gradient  # detached: no vjp chain across steps
    for t in range(2, S):
        out, caches = mt(paddle.to_tensor(x[:, t:t + 1]), caches=caches)
        steps.append(np.asarray(out._value))
    incremental = np.concatenate(steps, axis=1)
    np.testing.assert_allclose(incremental, full, rtol=2e-4, atol=2e-5)
    assert caches[0].shape[3] == S

    # multi-token CHUNK decode (s_new=3 after a 2-token prefix) must stay
    # intra-chunk causal too
    out2, caches2 = mt(paddle.to_tensor(x[:, :2]), caches=[])
    chunk, caches2 = mt(paddle.to_tensor(x[:, 2:]), caches=caches2)
    np.testing.assert_allclose(np.asarray(chunk._value), full[:, 2:],
                               rtol=2e-4, atol=2e-5)

    # reference-style preallocated cache + mismatched time_step: loud error
    import jax.numpy as jnp
    bad = [paddle.Tensor(jnp.zeros((2, 1, 2, 64, 8), jnp.float32))
           for _ in range(2)]
    with pytest.raises(ValueError, match="time_step"):
        mt(paddle.to_tensor(x[:, :1]), caches=bad, time_step=3)


def test_incubate_nn_all_matches_reference():
    ref_all = {"FusedMultiHeadAttention", "FusedFeedForward",
               "FusedTransformerEncoderLayer", "FusedMultiTransformer"}
    assert ref_all <= set(dir(incubate.nn))
