"""Distribution transforms / TransformedDistribution / Independent tests
(reference: python/paddle/distribution/{transform,transformed_distribution,
independent}.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distribution import (
    AffineTransform,
    ChainTransform,
    ExpTransform,
    Independent,
    Normal,
    PowerTransform,
    SigmoidTransform,
    StickBreakingTransform,
    TanhTransform,
    TransformedDistribution,
)


def test_lognormal_via_transformed_distribution():
    mu, sig = 0.3, 0.7
    ln = TransformedDistribution(Normal(mu, sig), [ExpTransform()])
    y = np.array([0.5, 1.0, 2.5])
    lp = np.asarray(ln.log_prob(Tensor(y))._value)
    ref = -np.log(y * sig * np.sqrt(2 * np.pi)) - (np.log(y) - mu) ** 2 / (2 * sig**2)
    np.testing.assert_allclose(lp, ref, rtol=1e-5)
    s = ln.sample((1000,))
    assert (np.asarray(s._value) > 0).all()  # support of a log-normal


@pytest.mark.parametrize("t,x", [
    (AffineTransform(1.5, -2.0), np.array([0.3, -0.7])),
    (ExpTransform(), np.array([0.1, 1.2])),
    (SigmoidTransform(), np.array([-1.0, 2.0])),
    (TanhTransform(), np.array([0.4, -0.9])),
    (PowerTransform(3.0), np.array([0.5, 1.4])),
])
def test_transform_roundtrip_and_numeric_jacobian(t, x):
    y = np.asarray(t.forward(Tensor(x))._value)
    np.testing.assert_allclose(np.asarray(t.inverse(Tensor(y))._value), x,
                               rtol=1e-4)
    eps = 1e-5
    num = np.log(np.abs(
        (np.asarray(t.forward(Tensor(x + eps))._value)
         - np.asarray(t.forward(Tensor(x - eps))._value)) / (2 * eps)))
    np.testing.assert_allclose(
        np.asarray(t.forward_log_det_jacobian(Tensor(x))._value), num,
        rtol=1e-3, atol=1e-5)
    # inverse_log_det is the negation at the mapped point
    np.testing.assert_allclose(
        np.asarray(t.inverse_log_det_jacobian(Tensor(y))._value), -num,
        rtol=1e-3, atol=1e-5)


def test_chain_transform_composes():
    ch = ChainTransform([AffineTransform(0.5, 2.0), TanhTransform()])
    x = np.array([0.1, -0.3])
    y = np.asarray(ch.forward(Tensor(x))._value)
    np.testing.assert_allclose(np.asarray(ch.inverse(Tensor(y))._value), x,
                               rtol=1e-5)
    num = np.log(np.abs(2.0 * (1 - np.tanh(0.5 + 2 * x) ** 2)))
    np.testing.assert_allclose(
        np.asarray(ch.forward_log_det_jacobian(Tensor(x))._value), num,
        rtol=1e-5)


def test_stick_breaking_simplex():
    sb = StickBreakingTransform()
    x = np.random.RandomState(0).randn(5, 3)
    simplex = np.asarray(sb.forward(Tensor(x))._value)
    assert simplex.shape == (5, 4)
    np.testing.assert_allclose(simplex.sum(-1), 1.0, rtol=1e-5)
    assert (simplex > 0).all()
    np.testing.assert_allclose(np.asarray(sb.inverse(Tensor(simplex))._value),
                               x, rtol=1e-4)


@pytest.mark.slow
def test_kl_divergence_closed_forms_vs_monte_carlo():
    """New KL pairs validated against Monte-Carlo estimates (reference kl.py
    register table)."""
    from paddle_tpu.distribution import (Bernoulli, Beta, Dirichlet,
                                         kl_divergence)

    paddle.seed(1234)  # the MC draws consume the global key stream

    def mc_kl(p, q, n=200_000):
        s = np.asarray(p.sample((n,))._value)
        lp = np.asarray(p.log_prob(Tensor(s))._value)
        lq = np.asarray(q.log_prob(Tensor(s))._value)
        d = lp - lq
        return d.reshape(n, -1).sum(-1).mean() if d.ndim > 1 else d.mean()

    pairs = [
        (Bernoulli(0.3), Bernoulli(0.7)),
        (Beta(2.0, 3.0), Beta(4.0, 1.5)),
    ]
    for p, q in pairs:
        kl = float(np.asarray(kl_divergence(p, q)._value))
        est = mc_kl(p, q)
        assert kl == pytest.approx(est, rel=0.05), (type(p).__name__, kl, est)
        assert kl > 0

    # Dirichlet KL: identical distributions -> 0; known asymmetry positive
    d1 = Dirichlet(np.array([2.0, 3.0, 4.0]))
    d2 = Dirichlet(np.array([1.0, 1.0, 1.0]))
    assert float(np.asarray(kl_divergence(d1, d1)._value)) == pytest.approx(0, abs=1e-6)
    assert float(np.asarray(kl_divergence(d1, d2)._value)) > 0

    from paddle_tpu.distribution import Uniform

    u_in = kl_divergence(Uniform(0.2, 0.6), Uniform(0.0, 1.0))
    assert float(np.asarray(u_in._value)) == pytest.approx(np.log(1.0 / 0.4), rel=1e-5)
    u_out = kl_divergence(Uniform(0.0, 2.0), Uniform(0.0, 1.0))
    assert np.isinf(float(np.asarray(u_out._value)))
    # degenerate q: true KL is infinite, not a clipped finite value
    b_inf = kl_divergence(Bernoulli(0.5), Bernoulli(0.0))
    assert np.isinf(float(np.asarray(b_inf._value)))
    # identical degenerate distributions: KL is 0, not inf (q only lacks
    # support where p also puts no mass)
    for v in (0.0, 1.0):
        b_same = kl_divergence(Bernoulli(v), Bernoulli(v))
        assert float(np.asarray(b_same._value)) == pytest.approx(0.0, abs=1e-5)
    # p degenerate at the outcome q still covers: finite
    b_fin = kl_divergence(Bernoulli(0.0), Bernoulli(0.5))
    assert np.isfinite(float(np.asarray(b_fin._value)))


def test_independent_sums_event_dims():
    base = Normal(np.zeros(4, np.float32), np.ones(4, np.float32))
    ind = Independent(base, 1)
    v = np.zeros(4, np.float32)
    lp = float(np.asarray(ind.log_prob(Tensor(v))._value))
    per = float(np.asarray(base.log_prob(Tensor(v))._value).reshape(-1)[0])
    assert lp == pytest.approx(4 * per, rel=1e-5)
    ent = float(np.asarray(ind.entropy()._value))
    per_e = float(np.asarray(base.entropy()._value).reshape(-1)[0])
    assert ent == pytest.approx(4 * per_e, rel=1e-5)
