"""Distribution transforms / TransformedDistribution / Independent tests
(reference: python/paddle/distribution/{transform,transformed_distribution,
independent}.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distribution import (
    AffineTransform,
    ChainTransform,
    ExpTransform,
    Independent,
    Normal,
    PowerTransform,
    SigmoidTransform,
    StickBreakingTransform,
    TanhTransform,
    TransformedDistribution,
)


def test_lognormal_via_transformed_distribution():
    mu, sig = 0.3, 0.7
    ln = TransformedDistribution(Normal(mu, sig), [ExpTransform()])
    y = np.array([0.5, 1.0, 2.5])
    lp = np.asarray(ln.log_prob(Tensor(y))._value)
    ref = -np.log(y * sig * np.sqrt(2 * np.pi)) - (np.log(y) - mu) ** 2 / (2 * sig**2)
    np.testing.assert_allclose(lp, ref, rtol=1e-5)
    s = ln.sample((1000,))
    assert (np.asarray(s._value) > 0).all()  # support of a log-normal


@pytest.mark.parametrize("t,x", [
    (AffineTransform(1.5, -2.0), np.array([0.3, -0.7])),
    (ExpTransform(), np.array([0.1, 1.2])),
    (SigmoidTransform(), np.array([-1.0, 2.0])),
    (TanhTransform(), np.array([0.4, -0.9])),
    (PowerTransform(3.0), np.array([0.5, 1.4])),
])
def test_transform_roundtrip_and_numeric_jacobian(t, x):
    y = np.asarray(t.forward(Tensor(x))._value)
    np.testing.assert_allclose(np.asarray(t.inverse(Tensor(y))._value), x,
                               rtol=1e-4)
    eps = 1e-5
    num = np.log(np.abs(
        (np.asarray(t.forward(Tensor(x + eps))._value)
         - np.asarray(t.forward(Tensor(x - eps))._value)) / (2 * eps)))
    np.testing.assert_allclose(
        np.asarray(t.forward_log_det_jacobian(Tensor(x))._value), num,
        rtol=1e-3, atol=1e-5)
    # inverse_log_det is the negation at the mapped point
    np.testing.assert_allclose(
        np.asarray(t.inverse_log_det_jacobian(Tensor(y))._value), -num,
        rtol=1e-3, atol=1e-5)


def test_chain_transform_composes():
    ch = ChainTransform([AffineTransform(0.5, 2.0), TanhTransform()])
    x = np.array([0.1, -0.3])
    y = np.asarray(ch.forward(Tensor(x))._value)
    np.testing.assert_allclose(np.asarray(ch.inverse(Tensor(y))._value), x,
                               rtol=1e-5)
    num = np.log(np.abs(2.0 * (1 - np.tanh(0.5 + 2 * x) ** 2)))
    np.testing.assert_allclose(
        np.asarray(ch.forward_log_det_jacobian(Tensor(x))._value), num,
        rtol=1e-5)


def test_stick_breaking_simplex():
    sb = StickBreakingTransform()
    x = np.random.RandomState(0).randn(5, 3)
    simplex = np.asarray(sb.forward(Tensor(x))._value)
    assert simplex.shape == (5, 4)
    np.testing.assert_allclose(simplex.sum(-1), 1.0, rtol=1e-5)
    assert (simplex > 0).all()
    np.testing.assert_allclose(np.asarray(sb.inverse(Tensor(simplex))._value),
                               x, rtol=1e-4)


def test_independent_sums_event_dims():
    base = Normal(np.zeros(4, np.float32), np.ones(4, np.float32))
    ind = Independent(base, 1)
    v = np.zeros(4, np.float32)
    lp = float(np.asarray(ind.log_prob(Tensor(v))._value))
    per = float(np.asarray(base.log_prob(Tensor(v))._value).reshape(-1)[0])
    assert lp == pytest.approx(4 * per, rel=1e-5)
    ent = float(np.asarray(ind.entropy()._value))
    per_e = float(np.asarray(base.entropy()._value).reshape(-1)[0])
    assert ent == pytest.approx(4 * per_e, rel=1e-5)
