"""Transformer MT family: training convergence on a copy task + beam decode
(reference pattern: test_transformer_api.py drives nn.Transformer end to end)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.text import TransformerMT, TransformerMTConfig

# the copy-task fixture trains ~120 eager steps; round-gate tier only
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def copy_task_model():
    """Fit a tiny MT model on a FIXED set of copy sequences (overfit regime —
    verified to reach ~0.1 CE; full copy generalization needs more steps than
    a unit test affords) and give beam search something meaningful to decode."""
    paddle.seed(42)
    cfg = TransformerMTConfig(
        src_vocab_size=20, tgt_vocab_size=20, d_model=32, nhead=4,
        num_encoder_layers=1, num_decoder_layers=1, dim_feedforward=64,
        dropout=0.0, max_length=24, bos_id=0, eos_id=1, pad_id=2,
        label_smooth_eps=0.1)
    m = TransformerMT(cfg)
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
    rng = np.random.RandomState(0)
    toks = rng.randint(3, 20, (8, 5)).astype("int32")
    src = Tensor(toks)
    tgt_in = Tensor(np.concatenate(
        [np.full((8, 1), 0, "int32"), toks], axis=1))  # bos + toks
    labels = Tensor(np.concatenate(
        [toks, np.full((8, 1), 1, "int32")], axis=1))  # toks + eos

    losses = []
    for i in range(120):
        loss = m(src, tgt_in, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return m, losses, toks


def test_copy_task_loss_decreases(copy_task_model):
    _, losses, _ = copy_task_model
    assert losses[-1] < 1.0, (losses[0], losses[-1])
    assert losses[-1] < losses[0] * 0.3


def test_greedy_logits_match_teacher_forcing(copy_task_model):
    m, _, _ = copy_task_model
    m.eval()
    rng = np.random.RandomState(1)
    src = Tensor(rng.randint(3, 20, (2, 5)).astype("int32"))
    tgt_in = Tensor(np.full((2, 1), 0, "int32"))
    logits = m(src, tgt_in)
    assert list(logits.shape) == [2, 1, 20]


def test_beam_translate_copies_source(copy_task_model):
    m, _, toks = copy_task_model
    m.eval()
    toks = toks[:3]
    out = np.asarray(m.translate(Tensor(toks), beam_size=3,
                                 max_len=10)._value)
    # the overfit copy model (teacher-forcing argmax acc ~96% at this size)
    # must terminate every row with eos and reproduce the large majority of
    # source tokens — exact copy of every row would be flaky at d_model=32
    matched = total = 0
    for b in range(3):
        seq = out[b]
        got = seq[seq != 2]  # strip pad
        assert got[-1] == 1, f"row {b} missing eos: {seq}"
        body = got[:-1]
        n = min(len(body), len(toks[b]))
        matched += (body[:n] == toks[b][:n]).sum()
        total += len(toks[b])
    assert matched / total >= 0.8, (matched, total, out)


def test_beam_search_shapes_and_lengths(copy_task_model):
    m, _, _ = copy_task_model
    m.eval()
    rng = np.random.RandomState(3)
    src = Tensor(rng.randint(3, 20, (2, 4)).astype("int32"))
    out, lengths = m.beam_search(src, beam_size=4, max_len=9)
    assert list(out.shape) == [2, 9, 4]
    L = np.asarray(lengths._value)
    assert L.shape == (2, 4)
    assert (L >= 1).all() and (L <= 9).all()


def test_sinusoid_table_properties():
    from paddle_tpu.text import sinusoid_position_encoding

    pe = np.asarray(sinusoid_position_encoding(16, 8))
    assert pe.shape == (16, 8)
    # position 0: sin terms 0, cos terms 1
    np.testing.assert_allclose(pe[0, 0::2], 0.0, atol=1e-6)
    np.testing.assert_allclose(pe[0, 1::2], 1.0, atol=1e-6)
    with pytest.raises(ValueError):
        sinusoid_position_encoding(4, 7)
