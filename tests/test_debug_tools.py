"""NaN/Inf checker, flags, monitor tests (reference analog:
tests/unittests/test_nan_inf.py, platform/monitor_test)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import monitor


def test_check_nan_inf_flag():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0]))
        with pytest.raises(FloatingPointError) as ei:
            paddle.log(x * 0.0 - 1.0)  # log(-1) = nan
        assert "nan" in str(ei.value)
        # divide by zero -> inf
        with pytest.raises(FloatingPointError):
            paddle.divide(paddle.to_tensor(np.array([1.0])),
                          paddle.to_tensor(np.array([0.0])))
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
    # flag off: no error
    out = paddle.log(paddle.to_tensor(np.array([-1.0])))
    assert np.isnan(out.numpy()).all()


def test_flags_roundtrip_and_env_coercion():
    paddle.set_flags({"FLAGS_eager_delete_tensor_gb": "2.5"})
    assert paddle.get_flags("FLAGS_eager_delete_tensor_gb")[
        "FLAGS_eager_delete_tensor_gb"] == 2.5
    paddle.set_flags({"FLAGS_check_nan_inf": "true"})
    assert paddle.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is True
    paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_monitor_stats():
    monitor.stat_reset()
    monitor.stat_add("reader_queue_size", 5)
    monitor.stat_add("reader_queue_size", 3)
    assert monitor.stat_get("reader_queue_size") == 8
    with monitor.StatTimer("step_time"):
        pass
    assert monitor.stat_get("step_time_count") == 1
    assert "step_time" in monitor.all_stats()
    monitor.stat_reset("reader_queue_size")
    assert monitor.stat_get("reader_queue_size") == 0


def test_check_nan_inf_safe_under_jit():
    """The eager nan scanner must not break tracing (jit.save/to_static)."""
    from paddle_tpu import nn, static

    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        net = nn.Linear(3, 2)
        traced = paddle.jit.to_static(
            net, input_spec=[static.InputSpec([2, 3], "float32")])
        out = traced(paddle.to_tensor(np.ones((2, 3), "float32")))
        assert tuple(out.shape) == (2, 2)
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
