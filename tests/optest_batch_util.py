"""Shared declaration helper for the OpTest batch files.

One place for the subclass factory (was copy-pasted per batch with drifting
default tolerances). Each batch calls `make_mk(globals())` once and gets an
`_mk` bound to its own module namespace, optionally overriding the batch's
default tolerances.
"""
import numpy as np

from paddle_tpu.utils.op_test import OpTest


def make_mk(module_globals, *, default_atol=1e-6, default_grad_rtol=1e-2,
            default_grad_atol=1e-4):
    """Return an `_mk(name, op, inputs_fn, ref, ...)` that declares one
    OpTest subclass into `module_globals` (keeps the reference subclass
    protocol while letting a batch state each op in one place)."""

    def _mk(name, op, inputs_fn, ref, attrs=None, grads=(), rtol=None,
            atol=default_atol, check_static=True,
            grad_rtol=default_grad_rtol, grad_atol=default_grad_atol):
        def setUp(self):
            self.op = op
            self.inputs = inputs_fn()
            self.attrs = dict(attrs or {})
            self.ref = ref

        body = {"setUp": setUp}

        def test_output(self):
            self.check_output(rtol=rtol, atol=atol,
                              check_static=check_static)

        body["test_output"] = test_output
        if grads:
            def test_grad(self):
                self.check_grad(list(grads), rtol=grad_rtol, atol=grad_atol)

            body["test_grad"] = test_grad
        cls = type(name, (OpTest,), body)
        module_globals[name] = cls
        return cls

    return _mk


def make_f32(rng: np.random.RandomState):
    def _f32(*shape, lo=-1.0, hi=1.0):
        return (rng.rand(*shape) * (hi - lo) + lo).astype("float32")

    return _f32
