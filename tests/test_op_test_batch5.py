"""OpTest batch 5: conv 1d/3d + transpose variants, pool 1d/3d, interpolate
modes, grid_sample, unfold/pixel ops (VERDICT r4 ask #4 — reference
conv/interp op tests, SURVEY §4.1). Numpy references are direct loop
implementations, independent of the jax lowerings."""
import numpy as np

import paddle_tpu.nn.functional as F
from optest_batch_util import make_mk


_mk = make_mk(globals(), default_atol=1e-5, default_grad_atol=1e-3)


_r = np.random.RandomState(3)


def _f32(*shape):
    return (_r.rand(*shape).astype("float32") - 0.5)


# ------------------------------------------------------------ numpy conv refs
def _np_conv(x, w, stride, pad, dilation, groups):
    """N-d direct convolution, NC<spatial> / OI<spatial> layouts."""
    nd = x.ndim - 2
    stride = [stride] * nd if np.isscalar(stride) else list(stride)
    pad = [pad] * nd if np.isscalar(pad) else list(pad)
    dilation = [dilation] * nd if np.isscalar(dilation) else list(dilation)
    x = np.pad(x, [(0, 0), (0, 0)] + [(p, p) for p in pad])
    n, cin = x.shape[:2]
    cout = w.shape[0]
    ksp = w.shape[2:]
    eff = [d * (k - 1) + 1 for k, d in zip(ksp, dilation)]
    osp = [(s - e) // st + 1 for s, e, st in zip(x.shape[2:], eff, stride)]
    out = np.zeros([n, cout] + osp, np.float64)
    cin_g = cin // groups
    cout_g = cout // groups
    for pos in np.ndindex(*osp):
        sl = tuple(builtins_slice(p * st, p * st + e, d)
                   for p, st, e, d in zip(pos, stride, eff, dilation))
        patch = x[(slice(None), slice(None)) + sl]  # [n, cin, *k]
        n_b = patch.shape[0]
        for g in range(groups):
            pg = patch[:, g * cin_g:(g + 1) * cin_g].reshape(n_b, -1)
            wg = w[g * cout_g:(g + 1) * cout_g].reshape(cout_g, -1)
            out[(slice(None),
                 slice(g * cout_g, (g + 1) * cout_g)) + pos] = pg @ wg.T
    return out.astype(np.float32)


def builtins_slice(start, stop, step):
    return slice(start, stop, step)


def _np_conv_transpose(x, w, stride, pad, nd):
    """Gradient-of-conv view: scatter each input pixel into the output.
    w layout: [cin, cout, *k] (paddle IOHW convention)."""
    stride = [stride] * nd if np.isscalar(stride) else list(stride)
    pad = [pad] * nd if np.isscalar(pad) else list(pad)
    n, cin = x.shape[:2]
    cout = w.shape[1]
    ksp = list(w.shape[2:])
    isp = list(x.shape[2:])
    osp = [(i - 1) * st + k - 2 * p
           for i, st, k, p in zip(isp, stride, ksp, pad)]
    full = [o + 2 * p for o, p in zip(osp, pad)]
    out = np.zeros([n, cout] + full, np.float64)
    for pos in np.ndindex(*isp):
        v = x[(slice(None), slice(None)) + pos]  # [n, cin]
        contrib = np.einsum("nc,co...->no...", v, w)
        sl = tuple(slice(p * st, p * st + k)
                   for p, st, k in zip(pos, stride, ksp))
        out[(slice(None), slice(None)) + sl] += contrib
    sl = tuple(slice(p, p + o) for p, o in zip(pad, osp))
    return out[(slice(None), slice(None)) + sl].astype(np.float32)


# ---------------------------------------------------------------- conv family
_mk("TestConv1dOp", F.conv1d,
    lambda: {"x": _f32(2, 3, 12), "weight": _f32(5, 3, 3)},
    lambda x, weight, stride, padding: _np_conv(x, weight, [stride],
                                                [padding], [1], 1),
    attrs={"stride": 2, "padding": 1}, grads=("x", "weight"))

_mk("TestConv1dDilatedOp", F.conv1d,
    lambda: {"x": _f32(1, 2, 14), "weight": _f32(4, 2, 3)},
    lambda x, weight, dilation: _np_conv(x, weight, [1], [0], [dilation], 1),
    attrs={"dilation": 2}, grads=("x",))

_mk("TestConv2dGroupsOp", F.conv2d,
    lambda: {"x": _f32(2, 4, 8, 8), "weight": _f32(6, 2, 3, 3)},
    lambda x, weight, groups, padding: _np_conv(x, weight, [1, 1],
                                                [padding, padding], [1, 1],
                                                groups),
    attrs={"groups": 2, "padding": 1}, grads=("x", "weight"))

_mk("TestDepthwiseConv2dOp", F.conv2d,
    lambda: {"x": _f32(1, 4, 7, 7), "weight": _f32(4, 1, 3, 3)},
    lambda x, weight, groups: _np_conv(x, weight, [1, 1], [0, 0], [1, 1],
                                       groups),
    attrs={"groups": 4}, grads=("x",))

_mk("TestConv2dDilatedStridedOp", F.conv2d,
    lambda: {"x": _f32(1, 2, 11, 11), "weight": _f32(3, 2, 3, 3)},
    lambda x, weight, stride, dilation: _np_conv(
        x, weight, [stride, stride], [0, 0], [dilation, dilation], 1),
    attrs={"stride": 2, "dilation": 2}, grads=("x",))

_mk("TestConv3dOp", F.conv3d,
    lambda: {"x": _f32(1, 2, 6, 6, 6), "weight": _f32(4, 2, 3, 3, 3)},
    lambda x, weight, padding: _np_conv(x, weight, [1, 1, 1],
                                        [padding] * 3, [1, 1, 1], 1),
    attrs={"padding": 1}, grads=("x", "weight"))

_mk("TestConv1dTransposeOp", F.conv1d_transpose,
    lambda: {"x": _f32(2, 3, 6), "weight": _f32(3, 4, 3)},
    lambda x, weight, stride, padding: _np_conv_transpose(
        x, weight, stride, padding, 1),
    attrs={"stride": 2, "padding": 1}, grads=("x", "weight"))

_mk("TestConv2dTransposeOp", F.conv2d_transpose,
    lambda: {"x": _f32(1, 3, 5, 5), "weight": _f32(3, 4, 3, 3)},
    lambda x, weight, stride: _np_conv_transpose(x, weight, stride, 0, 2),
    attrs={"stride": 2}, grads=("x", "weight"))

_mk("TestConv2dTransposePaddedOp", F.conv2d_transpose,
    lambda: {"x": _f32(1, 2, 4, 4), "weight": _f32(2, 3, 3, 3)},
    lambda x, weight, padding: _np_conv_transpose(x, weight, 1, padding, 2),
    attrs={"padding": 1}, grads=("x",))

_mk("TestConv3dTransposeOp", F.conv3d_transpose,
    lambda: {"x": _f32(1, 2, 3, 3, 3), "weight": _f32(2, 3, 2, 2, 2)},
    lambda x, weight, stride: _np_conv_transpose(x, weight, stride, 0, 3),
    attrs={"stride": 2}, grads=("x",))


# ---------------------------------------------------------------- pool family
def _np_pool(x, k, stride, pad, ptype, nd, exclusive=True):
    k = [k] * nd if np.isscalar(k) else list(k)
    stride = k if stride is None else (
        [stride] * nd if np.isscalar(stride) else list(stride))
    pad = [pad] * nd if np.isscalar(pad) else list(pad)
    fill = -np.inf if ptype == "max" else 0.0
    xp = np.pad(x, [(0, 0), (0, 0)] + [(p, p) for p in pad],
                constant_values=fill)
    osp = [(s - kk) // st + 1 for s, kk, st in zip(xp.shape[2:], k, stride)]
    out = np.zeros(list(x.shape[:2]) + osp, np.float64)
    for pos in np.ndindex(*osp):
        sl = tuple(slice(p * st, p * st + kk)
                   for p, st, kk in zip(pos, stride, k))
        patch = xp[(slice(None), slice(None)) + sl]
        axes = tuple(range(2, 2 + nd))
        if ptype == "max":
            out[(slice(None), slice(None)) + pos] = patch.max(axes)
        elif exclusive:
            cnt = np.ones_like(xp[:1, :1])
            cnt_patch = np.pad(np.ones_like(x[:1, :1]),
                               [(0, 0), (0, 0)] + [(p, p) for p in pad])[
                (slice(None), slice(None)) + sl]
            out[(slice(None), slice(None)) + pos] = (
                patch.sum(axes) / cnt_patch.sum(axes))
        else:
            out[(slice(None), slice(None)) + pos] = patch.mean(axes)
    return out.astype(np.float32)


_mk("TestAvgPool1dOp", F.avg_pool1d,
    lambda: {"x": _f32(2, 3, 10)},
    lambda x, kernel_size, stride: _np_pool(x, kernel_size, stride, 0,
                                            "avg", 1),
    attrs={"kernel_size": 3, "stride": 2}, grads=("x",))

_mk("TestMaxPool1dOp", F.max_pool1d,
    lambda: {"x": _f32(2, 3, 9)},
    lambda x, kernel_size: _np_pool(x, kernel_size, None, 0, "max", 1),
    attrs={"kernel_size": 3}, grads=("x",))

_mk("TestAvgPool3dOp", F.avg_pool3d,
    lambda: {"x": _f32(1, 2, 6, 6, 6)},
    lambda x, kernel_size: _np_pool(x, kernel_size, None, 0, "avg", 3),
    attrs={"kernel_size": 2}, grads=("x",))

_mk("TestMaxPool3dOp", F.max_pool3d,
    lambda: {"x": _f32(1, 2, 6, 6, 6)},
    lambda x, kernel_size, stride: _np_pool(x, kernel_size, stride, 0,
                                            "max", 3),
    attrs={"kernel_size": 2, "stride": 2}, grads=("x",))

_mk("TestAvgPool2dPaddedOp", F.avg_pool2d,
    lambda: {"x": _f32(1, 2, 6, 6)},
    lambda x, kernel_size, padding, exclusive: _np_pool(
        x, kernel_size, None, padding, "avg", 2, exclusive=exclusive),
    attrs={"kernel_size": 2, "padding": 1, "exclusive": True},
    grads=("x",))

_mk("TestAdaptiveAvgPool1dOp", F.adaptive_avg_pool1d,
    lambda: {"x": _f32(2, 3, 12)},
    lambda x, output_size: x.reshape(2, 3, output_size,
                                     12 // output_size).mean(-1),
    attrs={"output_size": 4}, grads=("x",))

_mk("TestAdaptiveAvgPool3dOp", F.adaptive_avg_pool3d,
    lambda: {"x": _f32(1, 2, 4, 4, 4)},
    lambda x, output_size: x.reshape(1, 2, 2, 2, 2, 2, 2, 2)
    .mean(axis=(3, 5, 7)),
    attrs={"output_size": 2}, grads=("x",))


# ------------------------------------------------------------ interpolate
def _np_interp_nearest(x, oh, ow):
    n, c, h, w = x.shape
    ih = (np.arange(oh) * (h / oh)).astype(np.int64)
    iw = (np.arange(ow) * (w / ow)).astype(np.int64)
    return x[:, :, ih][:, :, :, iw]


def _np_interp_bilinear(x, oh, ow, align_corners):
    n, c, h, w = x.shape
    if align_corners:
        ys = np.linspace(0, h - 1, oh)
        xs = np.linspace(0, w - 1, ow)
    else:
        ys = np.maximum((np.arange(oh) + 0.5) * h / oh - 0.5, 0)
        xs = np.maximum((np.arange(ow) + 0.5) * w / ow - 0.5, 0)
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, None, :, None]
    wx = (xs - x0)[None, None, None, :]
    a = x[:, :, y0][:, :, :, x0]
    b = x[:, :, y0][:, :, :, x1]
    cc = x[:, :, y1][:, :, :, x0]
    d = x[:, :, y1][:, :, :, x1]
    return (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
            + cc * wy * (1 - wx) + d * wy * wx).astype(np.float32)


_mk("TestInterpNearestOp", F.interpolate,
    lambda: {"x": _f32(2, 3, 4, 4)},
    lambda x, size, mode: _np_interp_nearest(x, *size),
    attrs={"size": [8, 8], "mode": "nearest"}, grads=("x",))

_mk("TestInterpBilinearOp", F.interpolate,
    lambda: {"x": _f32(1, 2, 4, 5)},
    lambda x, size, mode, align_corners: _np_interp_bilinear(
        x, size[0], size[1], align_corners),
    attrs={"size": [8, 10], "mode": "bilinear", "align_corners": False},
    rtol=1e-4, grads=("x",))

_mk("TestInterpBilinearAlignOp", F.interpolate,
    lambda: {"x": _f32(1, 2, 4, 4)},
    lambda x, size, mode, align_corners: _np_interp_bilinear(
        x, size[0], size[1], align_corners),
    attrs={"size": [7, 7], "mode": "bilinear", "align_corners": True},
    rtol=1e-4)

_mk("TestInterpAreaOp", F.interpolate,
    lambda: {"x": _f32(1, 2, 8, 8)},
    lambda x, size, mode: x.reshape(1, 2, 4, 2, 4, 2).mean(axis=(3, 5)),
    attrs={"size": [4, 4], "mode": "area"}, grads=("x",))


# ------------------------------------------------------------ grid_sample
def _np_grid_sample_bilinear(x, grid, align_corners):
    n, c, h, w = x.shape
    gh, gw = grid.shape[1:3]
    out = np.zeros((n, c, gh, gw), np.float64)
    for b in range(n):
        for i in range(gh):
            for j in range(gw):
                gx, gy = grid[b, i, j]
                if align_corners:
                    fx = (gx + 1) / 2 * (w - 1)
                    fy = (gy + 1) / 2 * (h - 1)
                else:
                    fx = ((gx + 1) * w - 1) / 2
                    fy = ((gy + 1) * h - 1) / 2
                x0, y0 = int(np.floor(fx)), int(np.floor(fy))
                for dy in (0, 1):
                    for dx in (0, 1):
                        xi, yi = x0 + dx, y0 + dy
                        wgt = ((1 - abs(fx - xi)) * (1 - abs(fy - yi)))
                        if 0 <= xi < w and 0 <= yi < h and wgt > 0:
                            out[b, :, i, j] += wgt * x[b, :, yi, xi]
    return out.astype(np.float32)


_mk("TestGridSampleOp", F.grid_sample,
    lambda: {"x": _f32(1, 2, 5, 5),
             "grid": (_r.rand(1, 3, 4, 2).astype("float32") * 1.6 - 0.8)},
    lambda x, grid, align_corners: _np_grid_sample_bilinear(
        x, grid, align_corners),
    attrs={"align_corners": True}, rtol=1e-4, grads=("x",))

_mk("TestGridSampleUnalignedOp", F.grid_sample,
    lambda: {"x": _f32(1, 2, 4, 4),
             "grid": (_r.rand(1, 3, 3, 2).astype("float32") * 1.2 - 0.6)},
    lambda x, grid, align_corners: _np_grid_sample_bilinear(
        x, grid, align_corners),
    attrs={"align_corners": False}, rtol=1e-4)


# ------------------------------------------------------- patch/pixel ops
def _np_unfold(x, k, stride):
    n, c, h, w = x.shape
    oh = (h - k) // stride + 1
    ow = (w - k) // stride + 1
    cols = np.zeros((n, c * k * k, oh * ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i * stride:i * stride + k,
                      j * stride:j * stride + k]
            cols[:, :, i * ow + j] = patch.reshape(n, -1)
    return cols


_mk("TestUnfoldOp", F.unfold,
    lambda: {"x": _f32(2, 3, 6, 6)},
    lambda x, kernel_sizes, strides: _np_unfold(x, kernel_sizes, strides),
    attrs={"kernel_sizes": 2, "strides": 2}, grads=("x",))

_mk("TestPixelShuffleOp", F.pixel_shuffle,
    lambda: {"x": _f32(1, 8, 3, 3)},
    lambda x, upscale_factor: _np_pixel_shuffle(x, upscale_factor),
    attrs={"upscale_factor": 2}, grads=("x",))


def _np_pixel_shuffle(x, r):
    n, c, h, w = x.shape
    oc = c // (r * r)
    return (x.reshape(n, oc, r, r, h, w)
            .transpose(0, 1, 4, 2, 5, 3)
            .reshape(n, oc, h * r, w * r))


_mk("TestPixelUnshuffleOp", F.pixel_unshuffle,
    lambda: {"x": _f32(1, 2, 6, 6)},
    lambda x, downscale_factor: _np_pixel_unshuffle(x, downscale_factor),
    attrs={"downscale_factor": 3}, grads=("x",))


def _np_pixel_unshuffle(x, r):
    n, c, h, w = x.shape
    return (x.reshape(n, c, h // r, r, w // r, r)
            .transpose(0, 1, 3, 5, 2, 4)
            .reshape(n, c * r * r, h // r, w // r))


_mk("TestChannelShuffleOp", F.channel_shuffle,
    lambda: {"x": _f32(1, 6, 4, 4)},
    lambda x, groups: x.reshape(1, groups, 2, 4, 4)
    .transpose(0, 2, 1, 3, 4).reshape(1, 6, 4, 4),
    attrs={"groups": 3}, grads=("x",))


# review-finding regressions: coordinate conventions + layouts
_mk("TestInterpNearestNonIntegerScaleOp", F.interpolate,
    # 3 -> 2: reference floor(i*in/out) picks [0, 1]; a half-pixel
    # convention would pick [0, 2]
    lambda: {"x": np.arange(6, dtype=np.float32).reshape(1, 2, 3)},
    lambda x, size, mode, data_format: x[:, :, [0, 1]],
    attrs={"size": [2], "mode": "nearest", "data_format": "NCL"})

_mk("TestInterpAlignMode1Op", F.interpolate,
    # align_mode=1: src = i*in/out (asymmetric), NOT half-pixel
    lambda: {"x": np.arange(4, dtype=np.float32).reshape(1, 1, 4)},
    lambda x, size, mode, align_mode, data_format: np.array(
        [[[0.0, 4 / 8, 8 / 8, 12 / 8, 16 / 8, 20 / 8, 24 / 8, 3.0]]],
        np.float32),
    attrs={"size": [8], "mode": "linear", "align_mode": 1,
           "data_format": "NCL"}, rtol=1e-5)

_mk("TestInterpNHWCOp", F.interpolate,
    lambda: {"x": _f32(2, 4, 4, 3)},
    lambda x, size, mode, data_format: np.moveaxis(_np_interp_nearest_f(
        np.moveaxis(x, -1, 1), 8, 8), 1, -1),
    attrs={"size": [8, 8], "mode": "nearest", "data_format": "NHWC"})


def _np_interp_nearest_f(x, oh, ow):
    h, w = x.shape[2], x.shape[3]
    ih = np.floor(np.arange(oh) * (h / oh)).astype(np.int64)
    iw = np.floor(np.arange(ow) * (w / ow)).astype(np.int64)
    return x[:, :, ih][:, :, :, iw]
