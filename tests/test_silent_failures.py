"""Regression tests for the round-2 'silent failure trio' (VERDICT r2 item 7):
each test fails on the old behavior.

1. maybe_shard / Tensor.to no longer swallow exceptions.
2. build_hybrid_step(recompute=True) actually rematerializes (and rejects a
   config that matches nothing).
3. ParallelCrossEntropy uses the vocab-parallel kernel, verified with
   logits-sharded parity on both the shard_map and GSPMD paths.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


def test_maybe_shard_raises_on_bad_spec():
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed.fleet.hybrid_train import maybe_shard, mesh_scope

    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2), ("dp", "mp"))
    t = Tensor(np.random.rand(4, 8).astype(np.float32))
    with mesh_scope(mesh):
        # rank-mismatched spec: must raise, not silently return unsharded
        with pytest.raises(Exception):
            maybe_shard(t, spec=P(None, None, "mp"))
        # valid spec still works
        out = maybe_shard(t, last_dim_axis="mp")
        assert out.shape == t.shape


def test_tensor_to_rejects_garbage():
    t = Tensor(np.ones((2, 2), np.float32))
    assert "16" in str(t.to("bfloat16").dtype)
    assert tuple(t.to("cpu").shape) == (2, 2)  # placement no-op, not an error
    assert tuple(t.to(paddle.CPUPlace()).shape) == (2, 2)
    with pytest.raises(Exception):
        t.to("definitely_not_a_dtype_or_place")


class _Blocky(nn.Layer):
    def __init__(self):
        super().__init__()
        self.emb = nn.Linear(8, 16)
        self.blocks = nn.LayerList([nn.Linear(16, 16) for _ in range(3)])
        self.head = nn.Linear(16, 4)

    def forward(self, x):
        x = self.emb(x)
        for b in self.blocks:
            x = nn.functional.relu(b(x))
        return self.head(x)


def test_hybrid_step_recompute_applies():
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.distributed.fleet.hybrid_train import build_hybrid_step

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("dp",))
    model = _Blocky()
    opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()
    init_fn, step_fn, shard_batch = build_hybrid_step(
        model, opt, loss_fn, mesh, recompute=True
    )
    # every LayerList child got wrapped
    assert all(getattr(b, "_recompute_wrapped", False) for b in model.blocks)
    state = init_fn()
    x = np.random.rand(4, 8).astype(np.float32)
    y = np.random.randint(0, 4, (4,))
    import jax.numpy as jnp

    key = paddle.core.rng.next_rng_key() if hasattr(paddle.core, "rng") else None
    from paddle_tpu.core import rng as rng_mod

    loss, state = step_fn(state, rng_mod.next_rng_key(),
                          jnp.float32(0.1), shard_batch([x]), shard_batch([y]))
    assert np.isfinite(float(loss))


def test_hybrid_step_recompute_rejects_empty_match():
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.distributed.fleet.hybrid_train import build_hybrid_step

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("dp",))
    model = _Blocky()
    opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
    with pytest.raises(ValueError, match="recompute"):
        build_hybrid_step(model, opt, nn.CrossEntropyLoss(), mesh,
                          recompute=True,
                          recompute_configs={"checkpoints": ["no_such_layer"]})


def test_parallel_cross_entropy_shard_map_parity():
    """Logits-sharded CE inside shard_map == dense CE (reference pattern:
    test_collective_base.py multi-rank numeric checks)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed.fleet.meta_parallel import ParallelCrossEntropy

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("mp",))
    b, v = 6, 32
    logits = np.random.randn(b, v).astype(np.float32)
    labels = np.random.randint(0, v, (b,))
    layer = ParallelCrossEntropy()

    def f(lg, lb):
        from paddle_tpu.core import tape

        with tape.no_grad():
            return layer(Tensor(lg), Tensor(lb))._value

    fm = jax.jit(jax.shard_map(f, mesh=mesh,
                               in_specs=(P(None, "mp"), P()), out_specs=P()))
    loss = np.asarray(fm(jnp.asarray(logits), jnp.asarray(labels)))
    ref = -np.log(np.exp(logits)[np.arange(b), labels] / np.exp(logits).sum(-1))
    assert np.allclose(loss, ref, rtol=1e-4)


def test_parallel_cross_entropy_gspmd_parity():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.distributed.fleet.hybrid_train import mesh_scope
    from paddle_tpu.distributed.fleet.meta_parallel import ParallelCrossEntropy

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("mp",))
    b, v = 6, 32
    logits = np.random.randn(b, v).astype(np.float32)
    labels = np.random.randint(0, v, (b,))
    layer = ParallelCrossEntropy()

    def f(lg, lb):
        from paddle_tpu.core import tape

        with tape.no_grad(), mesh_scope(mesh):
            return layer(Tensor(lg), Tensor(lb))._value

    fj = jax.jit(f, in_shardings=(NamedSharding(mesh, P(None, "mp")),
                                  NamedSharding(mesh, P())))
    loss = np.asarray(fj(jnp.asarray(logits), jnp.asarray(labels)))
    ref = -np.log(np.exp(logits)[np.arange(b), labels] / np.exp(logits).sum(-1))
    assert np.allclose(loss, ref, rtol=1e-4)


def test_parallel_cross_entropy_ignore_index():
    """label == ignore_index rows contribute exactly zero loss on both paths."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed.fleet.meta_parallel import ParallelCrossEntropy

    b, v = 4, 32
    logits = np.random.randn(b, v).astype(np.float32)
    labels = np.array([3, -100, 7, -100])
    layer = ParallelCrossEntropy()
    from paddle_tpu.core import tape

    with tape.no_grad():
        loss = np.asarray(layer(Tensor(logits), Tensor(labels.astype(np.int32)))._value)
    assert loss[1] == 0.0 and loss[3] == 0.0
    assert loss[0] > 0.0 and loss[2] > 0.0

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("mp",))

    def f(lg, lb):
        with tape.no_grad():
            return layer(Tensor(lg), Tensor(lb))._value

    fm = jax.jit(jax.shard_map(f, mesh=mesh,
                               in_specs=(P(None, "mp"), P()), out_specs=P()))
    loss_mp = np.asarray(fm(jnp.asarray(logits), jnp.asarray(labels.astype(np.int32))))
    assert loss_mp[1] == 0.0 and loss_mp[3] == 0.0
    assert np.allclose(loss_mp, loss, rtol=1e-4)


def test_linear_cross_entropy_fused_parity():
    """The chunked head+CE kernel (bench/GPT loss path) == naive matmul+CE."""
    import paddle_tpu.nn.functional as F
    import paddle_tpu.tensor_ops.math as M

    rng = np.random.RandomState(3)
    h = Tensor(rng.randn(2, 9, 16).astype(np.float32))
    w = Tensor(rng.randn(16, 33).astype(np.float32))
    lab = rng.randint(0, 33, (2, 9))
    lab[0, 2] = -100  # ignore_index position
    lab_t = Tensor(lab.astype(np.int32))
    fused = float(F.linear_cross_entropy(h, w, lab_t, chunk_size=4))
    naive = float(F.cross_entropy(
        M.matmul(h, w).reshape([-1, 33]), Tensor(lab.reshape(-1).astype(np.int32))
    ))
    assert abs(fused - naive) < 1e-5
