"""Real-format .pdmodel/.pdiparams EXPORT (static/pdmodel_export.py) —
round-tripped through the independent ProgramDesc wire parser + executor in
inference/pdmodel.py (itself validated against genuine Paddle fixtures in
test_pdmodel_interop.py). Closes the artifact-interop loop both directions:
reference → paddle_tpu (load) and paddle_tpu → reference (export)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _export_and_reload(tmp_path, main, startup, feeds, fetches, feed_dict):
    exe = static.Executor()
    exe.run(startup)
    want = exe.run(main, feed=feed_dict, fetch_list=fetches)

    prefix = str(tmp_path / "model")
    out = static.save_inference_model(prefix, feeds, fetches,
                                      program=main, program_format="pdmodel")
    assert out.endswith(".pdmodel")
    # file must be raw protobuf, not pickle
    with open(prefix + ".pdmodel", "rb") as f:
        head = f.read(1)
    assert head == b"\x0a"  # field 1 LEN — ProgramDesc.blocks

    prog, feed_names, fetch_names = static.load_inference_model(prefix)
    got = prog._exported_call(feed_dict)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-5, atol=1e-5)
    return prog


def test_export_lenet_style_conv_net(tmp_path, static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 1, 28, 28], "float32")
        net_out = paddle.nn.functional.conv2d(
            x, paddle.to_tensor(
                np.random.randn(6, 1, 5, 5).astype("float32") * 0.1),
            bias=paddle.to_tensor(np.zeros(6, "float32")), padding=2)
        net_out = paddle.nn.functional.relu(net_out)
        net_out = paddle.nn.functional.max_pool2d(net_out, 2, 2)
        net_out = paddle.flatten(net_out, 1)
        w = paddle.to_tensor(
            np.random.randn(6 * 14 * 14, 10).astype("float32") * 0.05)
        b = paddle.to_tensor(np.zeros(10, "float32"))
        logits = paddle.nn.functional.linear(net_out, w, b)
        probs = paddle.nn.functional.softmax(logits, axis=-1)
    feed = {"x": np.random.rand(2, 1, 28, 28).astype("float32")}
    prog = _export_and_reload(tmp_path, main, startup, [x], [probs], feed)
    # persistable params made it into the .pdiparams stream
    assert len(prog._prog.param_names) == 4


def test_export_transformer_style_block(tmp_path, static_mode):
    d = 16
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = static.data("ids", [2, 8], "int64")
        table = paddle.to_tensor(np.random.randn(50, d).astype("float32") * 0.1)
        h = paddle.nn.functional.embedding(ids, table)
        g = paddle.to_tensor(np.ones(d, "float32"))
        beta = paddle.to_tensor(np.zeros(d, "float32"))
        h = paddle.nn.functional.layer_norm(h, [d], weight=g, bias=beta)
        wq = paddle.to_tensor(np.random.randn(d, d).astype("float32") * 0.1)
        q = paddle.matmul(h, wq)
        att = paddle.matmul(q, q, transpose_y=True)
        att = paddle.nn.functional.softmax(
            paddle.scale(att, scale=1.0 / np.sqrt(d)), axis=-1)
        ctxv = paddle.matmul(att, h)
        out = paddle.add(h, ctxv)
        out = paddle.nn.functional.gelu(out)
    feed = {"ids": np.random.randint(0, 50, (2, 8)).astype("int64")}
    _export_and_reload(tmp_path, main, startup, [ids], [out], feed)


def test_export_batch_norm_and_transpose(tmp_path, static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 3, 8, 8], "float32")
        rm = paddle.to_tensor(np.zeros(3, "float32"))
        rv = paddle.to_tensor(np.ones(3, "float32"))
        sc = paddle.to_tensor(np.random.rand(3).astype("float32") + 0.5)
        bi = paddle.to_tensor(np.random.randn(3).astype("float32"))
        h = paddle.nn.functional.batch_norm(x, rm, rv, sc, bi, training=False)
        h = paddle.transpose(h, [0, 2, 3, 1])
        h = paddle.reshape(h, [2, 8 * 8 * 3])
    feed = {"x": np.random.rand(2, 3, 8, 8).astype("float32")}
    _export_and_reload(tmp_path, main, startup, [x], [h], feed)


def test_export_unmapped_op_raises(tmp_path, static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4], "float32")
        y = paddle.erf(x)  # no pdmodel emitter
    with pytest.raises(NotImplementedError, match="StableHLO"):
        static.save_inference_model(str(tmp_path / "m"), [x], [y],
                                    program=main, program_format="pdmodel")


def test_serialize_program_is_parseable(static_mode):
    from paddle_tpu.inference.pdmodel import parse_program_desc

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 4], "float32")
        y = paddle.nn.functional.relu(paddle.matmul(x, x))
    blob = static.serialize_program(main, feed_vars=[x], fetch_vars=[y])
    desc = parse_program_desc(blob)
    ops = [op["type"] for op in desc["blocks"][0]["ops"]]
    assert ops == ["feed", "matmul_v2", "relu", "fetch"]
    # attrs survive the wire round-trip
    mm = desc["blocks"][0]["ops"][1]
    assert mm["attrs"]["trans_x"] is False or mm["attrs"]["trans_x"] == 0


def test_export_negative_padding_idx_and_pair_paddings(tmp_path, static_mode):
    """Code-review r4 regressions: padding_idx=-1 must mean 'last vocab row'
    (not the kNoPadding sentinel) after export, and pair-list conv paddings
    must flatten instead of crashing."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = static.data("ids", [2, 4], "int64")
        table = paddle.to_tensor(np.random.randn(10, 8).astype("float32"))
        emb = paddle.nn.functional.embedding(ids, table, padding_idx=-1)
        x = static.data("x", [1, 2, 8, 8], "float32")
        w = paddle.to_tensor(np.random.randn(2, 2, 3, 3).astype("float32"))
        conv = paddle.nn.functional.conv2d(x, w, padding=[(1, 2), (0, 1)])
    feed = {"ids": np.array([[0, 9, 3, 9], [9, 1, 2, 4]], np.int64),
            "x": np.random.rand(1, 2, 8, 8).astype("float32")}
    exe = static.Executor()
    exe.run(startup)
    want_emb = exe.run(main, feed=feed, fetch_list=[emb])[0]
    # rows with id 9 (== vocab-1 == normalized -1) are zeroed in-framework
    assert np.allclose(want_emb[0, 1], 0) and np.allclose(want_emb[1, 0], 0)
    # recorded attr is the normalized non-negative index
    emb_ops = [op for op in main.global_block.ops if op.type == "embedding"]
    assert emb_ops[0].attrs["padding_idx"] == 9
    # pair paddings export without crashing and conv op carries 4-int form
    prefix = str(tmp_path / "m")
    static.save_inference_model(prefix, [ids, x], [conv],
                                program=main, program_format="pdmodel")
    from paddle_tpu.inference.pdmodel import parse_program_desc

    with open(prefix + ".pdmodel", "rb") as f:
        desc = parse_program_desc(f.read())
    conv_descs = [o for o in desc["blocks"][0]["ops"] if o["type"] == "conv2d"]
    assert conv_descs[0]["attrs"]["paddings"] == [1, 2, 0, 1]


def test_convert_to_mixed_precision_roundtrip(tmp_path, static_mode):
    """inference.convert_to_mixed_precision rewrites the .pdiparams stream
    to the requested dtype and rejects unsupported requests loudly."""
    import ml_dtypes

    from paddle_tpu import inference
    from paddle_tpu.framework.io import _read_lod_tensor

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 4], "float32")
        w = paddle.to_tensor(np.random.randn(4, 3).astype("float32"))
        y = paddle.matmul(x, w)
    prefix = str(tmp_path / "m")
    static.save_inference_model(prefix, [x], [y], program=main,
                                program_format="pdmodel")
    inference.convert_to_mixed_precision(
        prefix + ".pdmodel", prefix + ".pdiparams",
        prefix + "_bf16.pdmodel", prefix + "_bf16.pdiparams",
        mixed_precision="bfloat16")
    import io as _io

    data = open(prefix + "_bf16.pdiparams", "rb").read()
    arr, _ = _read_lod_tensor(_io.BytesIO(data))
    assert arr.dtype == ml_dtypes.bfloat16
    # fp16 spelling works; bogus dtype and black_list are loud
    inference.convert_to_mixed_precision(
        prefix + ".pdmodel", prefix + ".pdiparams",
        prefix + "_f16.pdmodel", prefix + "_f16.pdiparams",
        mixed_precision="fp16")
    with pytest.raises(ValueError, match="mixed_precision"):
        inference.convert_to_mixed_precision(
            prefix + ".pdmodel", prefix + ".pdiparams", "/tmp/x", "/tmp/y",
            mixed_precision="int3")
    with pytest.raises(NotImplementedError, match="black_list"):
        inference.convert_to_mixed_precision(
            prefix + ".pdmodel", prefix + ".pdiparams", "/tmp/x", "/tmp/y",
            black_list={"softmax"})
