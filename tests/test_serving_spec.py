"""Speculative decoding (paddle_tpu/serving/spec.py + the engine's verify
step): bit-identical outputs speculation on vs off — greedy AND sampling,
both proposer methods — with one compiled verify program per configured
depth, the sync-free certification formula unchanged, exact page
accounting after partial accepts, preemption replay in both modes, and
the prefix cache registering only accepted spans.

The parity guarantee under test is structural, not statistical: every
token the verify step emits is the TARGET's own token (argmax or the
(seed, rid, token_idx)-fold sample) at the identical context — acceptance
only decides how many of them one step emits — so parity must hold at ANY
acceptance rate, including zero.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis.tracecheck import SyncTally
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.serving import (FaultInjector, ServingConfig, ServingEngine,
                                SpecConfig)
from paddle_tpu.serving.spec import accept_counts, propose_ngram
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.spec


def _toy_model(seed=11, vocab=97):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=48, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _reference(model, prompt, budget):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=budget)
    return np.asarray(out._value)[0]


def _draft_cfg(vocab=97):
    return GPTConfig(vocab_size=vocab, hidden_size=16, num_layers=1,
                     num_heads=2, max_seq_len=16, dropout=0.0)


def _spec(method, depth, vocab=97, **kw):
    if method == "draft":
        kw.setdefault("draft", _draft_cfg(vocab))
        kw.setdefault("window", 4)
    return SpecConfig(method=method, depth=depth, **kw)


def _prompts(rng, lens, vocab=97):
    return [rng.randint(0, vocab, (n,)).astype(np.int32) for n in lens]


def _engine(model, spec, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("num_pages", 24)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_prompt_len", 8)
    return ServingEngine(model, ServingConfig(spec=spec, **kw))


# ------------------------------------------------------------------ parity
@pytest.mark.parametrize("method", [
    "ngram",
    # re-tiered 2026-08 (PR 20): tier-1 crossed its 870 s budget; the
    # ngram variant keeps the verify-program pin hot in tier-1
    pytest.param("draft", marks=pytest.mark.slow)])
def test_greedy_parity_and_one_verify_program_per_depth(method):
    """The acceptance pin: greedy outputs bit-identical speculation on vs
    off for K in {1, 2, 4} and both proposer methods, with exactly ONE
    verify program compiled per configured depth (debug_checks strict —
    a retrace would raise, not just count)."""
    model = _toy_model()
    rng = np.random.RandomState(0)
    prompts = _prompts(rng, (3, 7))
    budgets = [6, 8]
    refs = [_reference(model, p, b) for p, b in zip(prompts, budgets)]
    for depth in (1, 2, 4):
        engine = _engine(model, _spec(method, depth), debug_checks=True)
        rids = [engine.add_request(p, b)
                for p, b in zip(prompts, budgets)]
        outs = engine.run()
        for i, rid in enumerate(rids):
            np.testing.assert_array_equal(
                refs[i], outs[rid],
                err_msg=f"{method} K={depth} request {i} diverged")
        assert engine.compile_counts == \
            {"prefill": 1, "decode": 0, "verify": 1}, \
            (method, depth, engine.compile_counts)
        assert engine.cache.allocator.pages_in_use == 0


# the draft variant is round-gated at birth (tier-1 budget): sampling
# parity is proposer-agnostic — the accept rule compares TARGET tokens
# only — and the draft path stays tier-1-pinned by the greedy parity
# matrix above; the ngram variant keeps the fold rule itself tier-1
@pytest.mark.parametrize("method", [
    "ngram", pytest.param("draft", marks=pytest.mark.slow)])
def test_sampling_parity_via_prng_fold(method):
    """Sampled outputs bit-identical spec-on vs spec-off: the verify step
    draws the target's token at position gen+j under the SAME
    (seed, rid, token_idx) fold sequential decoding uses, so rejection
    never resamples a different stream."""
    model = _toy_model()
    rng = np.random.RandomState(1)
    prompts = _prompts(rng, (3, 6, 5))
    budgets = [6, 7, 5]

    def drive(spec):
        engine = _engine(model, spec, do_sample=True, temperature=0.8,
                         top_k=12, seed=5)
        rids = [engine.add_request(p, b)
                for p, b in zip(prompts, budgets)]
        outs = engine.run()
        return [outs[r] for r in rids]

    # rid-aligned runs: the PRNG stream is keyed by rid, so both engines
    # must see identical rids for identical requests
    import itertools

    import paddle_tpu.serving.scheduler as sched
    base = next(sched._rid_counter)
    sched._rid_counter = itertools.count(base + 100)
    off = drive(None)
    sched._rid_counter = itertools.count(base + 100)
    on = drive(_spec(method, 4))
    for i, (a, b) in enumerate(zip(off, on)):
        np.testing.assert_array_equal(a, b,
                                      err_msg=f"{method} request {i}")


@pytest.mark.slow  # round-gated at birth (tier-1 budget): eos/budget termination rides _maybe_finish, shared verbatim with plain decode and pinned per-token by the tier-1 parity matrix (whose budgets terminate every request)
def test_eos_respected_mid_acceptance():
    """A request whose eos lands inside an accepted span stops there —
    tokens past eos are discarded exactly as sequential decode never
    would have produced them."""
    model = _toy_model()
    rng = np.random.RandomState(2)
    prompt = _prompts(rng, (5,))[0]
    ref = _reference(model, prompt, 12)
    eos = int(ref[len(prompt) + 3])  # force a stop a few tokens in
    engine = _engine(model, _spec("ngram", 4), eos_token_id=eos,
                     max_prompt_len=8)
    rid = engine.add_request(prompt, 12)
    out = engine.run()[rid]
    # output ends at the FIRST occurrence of eos in the greedy stream
    stop = np.nonzero(ref[len(prompt):] == eos)[0][0]
    np.testing.assert_array_equal(out, ref[:len(prompt) + stop + 1])
    assert engine.cache.allocator.pages_in_use == 0


# --------------------------------------------------------- propose / accept
def test_accept_counts_golden():
    import jax.numpy as jnp

    cand = jnp.asarray([[5, 7, 9], [5, 7, 9], [1, 2, 3], [5, 9, 7]])
    target = jnp.asarray([[5, 7, 9, 4],   # all accepted
                          [5, 7, 8, 4],   # first two
                          [9, 9, 9, 9],   # none
                          [5, 7, 7, 4]])  # stop at the first mismatch,
    got = np.asarray(accept_counts(cand, target))  # later re-match ignored
    np.testing.assert_array_equal(got, [3, 2, 0, 1])


def test_ngram_proposer_golden():
    import jax.numpy as jnp

    hist = np.zeros((3, 16), np.int32)
    # row 0: ... 5 7 [1 2 3] ... 5 7 -> proposes 1 2 3
    hist[0, :10] = [9, 5, 7, 1, 2, 3, 4, 9, 5, 7]
    # row 1: no earlier occurrence of its tail bigram
    hist[1, :6] = [1, 2, 3, 4, 5, 6]
    # row 2: [5 7] [5 7] — the match overlaps the tail and its
    # continuation runs off the known tokens -> tail padded
    hist[2, :4] = [5, 7, 5, 7]
    known = jnp.asarray([10, 6, 4], jnp.int32)
    got = np.asarray(propose_ngram(jnp.asarray(hist), known, 3, 2,
                                   pad_id=0))
    np.testing.assert_array_equal(got[0], [1, 2, 3])
    np.testing.assert_array_equal(got[1], [0, 0, 0])
    np.testing.assert_array_equal(got[2], [5, 7, 0])


def test_acceptance_fires_on_repetitive_traffic_and_obs_surfaces():
    """A deterministic nonzero-acceptance run: tiny vocab makes the
    greedy target fall into short cycles, which the n-gram proposer then
    predicts — proposed counts are exact (K per active slot per verify
    step), the acceptance surfaces move, and the obs plumbing agrees
    end to end: every verify step stamps a ``spec_verify`` lifecycle
    event (proposed/accepted args) that exports as a Chrome instant, and
    ``StepRecord.accepted`` sums to the accepted-tokens counter."""
    model = _toy_model(seed=3, vocab=5)
    engine = _engine(model, _spec("ngram", 4, vocab=5), max_batch=1,
                     num_pages=16)
    rid = engine.add_request(np.asarray([1, 2, 3], np.int32), 24)
    out = engine.run()[rid]
    np.testing.assert_array_equal(
        out, _reference(model, np.asarray([1, 2, 3], np.int32), 24))
    snap = engine.metrics.snapshot()
    steps = snap["serving_decode_steps"]
    accepted = snap["serving_spec_accepted_tokens_total"]
    assert snap["serving_spec_proposed_tokens_total"] == 4 * steps
    assert accepted > 0, \
        "a 5-token vocab greedy stream must cycle within 24 tokens"
    # 23 post-prefill tokens; each verify step emits 1 + its accepted
    # count (the final step may discard acceptance past the budget), so
    # acceptance is exactly the steps saved, up to that final discard
    assert 23 - accepted <= steps < 23, (steps, accepted)
    assert snap["serving_spec_acceptance_rate"] == pytest.approx(
        accepted / (4 * steps))
    evs = [e for e in engine.trace(rid).events if e.name == "spec_verify"]
    assert len(evs) == steps, "one spec_verify event per verify step"
    assert sum(e.arg("accepted") for e in evs) == accepted
    assert all(e.arg("proposed") == 4 for e in evs)
    assert sum(r.accepted for r in engine.timeline.records()) == accepted
    doc = engine.export_chrome_trace()
    assert any(e.get("name") == "spec_verify" and e.get("ph") == "i"
               for e in doc["traceEvents"])


# ------------------------------------------------- pages, sync, preemption
def test_page_accounting_exact_after_partial_accepts():
    """After every step, each decoding slot holds EXACTLY the pages its
    resident tokens need — the speculative over-reservation (K extra
    slots) must have shrunk back the moment the accept count was known —
    and the structural invariant sweep passes throughout (debug_checks
    runs check_invariants at every step boundary)."""
    model = _toy_model()
    rng = np.random.RandomState(3)
    prompts = _prompts(rng, (3, 7))
    engine = _engine(model, _spec("ngram", 4), debug_checks=True)
    rids = [engine.add_request(p, b) for p, b in zip(prompts, (9, 8))]
    seen_rest = 0
    while not engine.scheduler.all_done:
        engine.step()
        for slot, req in engine.scheduler.running.items():
            if req.state != "running":
                continue
            held = len(engine.cache._slot_pages[slot])
            res = req.tokens_resident
            # exact at-rest bound: pages cover the written KV (res - 1
            # positions) and at most the pending token's slot — a full
            # accept never shrinks (its reservation was fully consumed),
            # a partial accept shrinks to pages_for(res) exactly; the
            # speculative K-token reservation must be gone either way
            assert engine.cache.pages_for(res - 1) <= held \
                <= engine.cache.pages_for(res), (slot, held, res)
            seen_rest += 1
    assert seen_rest > 0
    assert engine.cache.allocator.pages_in_use == 0
    outs = engine.pop_finished()
    for i, rid in enumerate(rids):
        np.testing.assert_array_equal(
            _reference(model, prompts[i], (9, 8)[i]), outs[rid])


def test_sync_free_certification_with_speculation_on():
    # the acceptance pin: ONE host fetch per engine step — the packed
    # (targets, accept count) array is the decode token fetch renamed, so
    # the SyncTally formula (decode steps + completed prefills) is
    # byte-identical with speculation on
    model = _toy_model()
    rng = np.random.RandomState(4)
    engine = _engine(model, _spec("ngram", 4))
    for p, b in zip(_prompts(rng, (3, 7, 5)), (6, 8, 5)):
        engine.add_request(p, b)
    pre = engine.metrics.snapshot()
    with SyncTally() as tally:
        engine.run()
    snap = engine.metrics.snapshot()
    fetches = int(snap["serving_decode_steps"] - pre["serving_decode_steps"]
                  + snap["serving_prefills_total"]
                  - pre["serving_prefills_total"])
    assert tally.count == fetches, (tally.count, fetches,
                                    tally.events[:20])
    assert snap["serving_analysis_retraces_total"] == 0


# the swap variant is round-gated at birth (tier-1 budget): the swap
# restore path is sharding/content-blind and stays tier-1-pinned by the
# faults suite's swap-parity scenario and the kvq bit-exact swap round
# trip; the spec-specific claim (history rebuild + replay) is pinned by
# the recompute variant
@pytest.mark.parametrize("mode", [
    "recompute", pytest.param("swap", marks=pytest.mark.slow)])
def test_preemption_replay_mid_speculation(mode):
    """Pool pressure preempts a request mid-speculation; the replay —
    full re-prefill under recompute, restored pages + rebuilt history
    under swap — reproduces the exact token stream (proposals are a pure
    function of the token history, emitted tokens of the target)."""
    model = _toy_model(seed=13)
    rng = np.random.RandomState(1)
    prompts = _prompts(rng, (6, 5, 4))
    budgets = [10, 9, 8]
    engine = _engine(model, _spec("ngram", 2), max_batch=3, num_pages=10,
                     preemption_mode=mode)
    rids = [engine.add_request(p, b) for p, b in zip(prompts, budgets)]
    outs = engine.run()
    assert engine.scheduler.preemption_count > 0, \
        "the pool must be small enough to force preemption"
    for i, rid in enumerate(rids):
        np.testing.assert_array_equal(
            _reference(model, prompts[i], budgets[i]), outs[rid],
            err_msg=f"{mode} request {i}")
    assert engine.cache.allocator.pages_in_use == 0


# ------------------------------------------------- cache / quantized / tp
def test_prefix_cache_registers_only_accepted_spans():
    """The pages a finished speculative request indexes hold EXACTLY its
    emitted tokens — rejected candidates' garbage KV is never registered
    (an identical follow-up prompt walks the full chain and serves from
    cache, bit-identically)."""
    model = _toy_model()
    rng = np.random.RandomState(5)
    prompt = _prompts(rng, (16,))[0]
    engine = _engine(model, _spec("ngram", 4), max_prompt_len=24,
                     num_pages=32, debug_checks=True)
    r1 = engine.add_request(prompt, 6)
    out1 = engine.run()[r1]
    # the registered chain covers every full page of output[:-1] (the
    # resident span) and nothing else — a garbage registration would
    # break the exact-match walk
    pages = engine.cache.match_prefix(out1)
    assert len(pages) == (len(out1) - 1) // 4
    r2 = engine.add_request(prompt, 6)
    out2 = engine.run()[r2]
    np.testing.assert_array_equal(out1, out2)
    snap = engine.metrics.snapshot()
    assert snap["serving_prefix_hits"] == 1
    assert snap["serving_prefix_tokens_saved"] >= 12


@pytest.mark.slow  # round-gated at birth (tier-1 budget): the int8 write/gather machinery is pinned by the kvq suite and the spec machinery by every tier-1 test here; this pins only their composition's bounded-divergence contract
def test_int8_pool_composes_with_speculation():
    """kv_dtype="int8" + speculation serves correctly (invariants, page
    drain, zero retraces). Bitwise spec-on/off parity is NOT promised
    here: rejected candidates' scatters can grow a page's monotone absmax
    scale, which is the same bounded-quality contract PR 9 pinned —
    pinned the same way (common greedy prefix vs the non-speculative int8
    engine)."""
    model = _toy_model()
    rng = np.random.RandomState(6)
    prompts = _prompts(rng, (3, 7))
    budgets = [8, 8]

    def drive(spec):
        engine = _engine(model, spec, kv_dtype="int8", debug_checks=True)
        rids = [engine.add_request(p, b)
                for p, b in zip(prompts, budgets)]
        outs = engine.run()
        assert engine.cache.allocator.pages_in_use == 0
        snap = engine.metrics.snapshot()
        assert snap["serving_analysis_retraces_total"] == 0
        return [outs[r] for r in rids]

    off = drive(None)
    on = drive(_spec("ngram", 4))

    def common(a, b):
        n = min(len(a), len(b))
        eq = np.nonzero(np.asarray(a[:n]) != np.asarray(b[:n]))[0]
        return (eq[0] if len(eq) else n) / n

    assert np.mean([common(a, b) for a, b in zip(off, on)]) >= 0.5


def test_registry_verify_spec_certifies():
    """The hlocheck registry step: the whole propose + K+1 verify +
    accept program compiles with zero collectives, zero host transfers,
    and every donated pool leaf aliased."""
    from paddle_tpu.analysis.hlocheck import run_step

    rep = run_step("engine_verify_spec")
    assert rep.collectives == () and rep.host_transfers == ()
    assert rep.donated_leaves == 4 == rep.aliased_leaves


@pytest.mark.slow  # re-tiered at birth: the single-chip cert + the engine TP suite already pin the sharded machinery; this re-lowers a 2-device mesh program
def test_registry_tp2_verify_spec_certifies():
    """Tensor parallelism composes: the sharded verify step certifies at
    the target's own 2*num_layers + 1 all-reduce budget — the in-jit
    proposer adds ZERO collectives."""
    from paddle_tpu.analysis.hlocheck import run_step

    rep = run_step("tp2_engine_verify_spec")
    assert rep.counts() == {"all-reduce": 2 * 2 + 1}, rep.counts()


# ---------------------------------------------------- faults / validation
@pytest.mark.slow  # round-gated at birth (tier-1 budget): the identical scenario runs tier-1 in tests/test_serving_faults.py::test_verify_fail_retires_mid_speculation_and_survivors_keep_serving
def test_verify_fail_isolates_the_failed_request():
    """The verify_fail fault point: the faulted request retires FAILED
    before the verify dispatch — its pages (speculative over-reservation
    included) drain — and the survivors keep serving bit-identically."""
    model = _toy_model()
    rng = np.random.RandomState(7)
    prompts = _prompts(rng, (3, 5))
    inj = FaultInjector()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8,
        spec=SpecConfig(method="ngram", depth=4)), fault_injector=inj)
    r0 = engine.add_request(prompts[0], 8)
    r1 = engine.add_request(prompts[1], 8)
    # step 1: r0 (budget 8) is certainly still mid-speculation — one
    # verify step emits at most depth + 1 = 5 tokens
    inj.arm("verify_fail", rid=r0, step=1)
    outs = engine.run()
    assert engine.status(r0) == "failed"
    assert isinstance(engine.request(r0).error, Exception)
    assert r0 not in outs
    np.testing.assert_array_equal(_reference(model, prompts[1], 8),
                                  outs[r1])
    assert engine.cache.allocator.pages_in_use == 0
    assert engine.metrics.snapshot()["serving_failed"] == 1


def test_spec_validation_errors():
    model = _toy_model()
    with pytest.raises(ValueError, match="method"):
        _engine(model, SpecConfig(method="oracle"))
    with pytest.raises(ValueError, match="depth"):
        _engine(model, SpecConfig(method="ngram", depth=0))
    with pytest.raises(ValueError, match="ngram"):
        _engine(model, SpecConfig(method="ngram", ngram=0))
    with pytest.raises(ValueError, match="spec.draft"):
        _engine(model, SpecConfig(method="draft"))
    with pytest.raises(ValueError, match="vocab"):
        _engine(model, SpecConfig(method="draft",
                                  draft=_draft_cfg(vocab=31)))
    with pytest.raises(ValueError, match="window"):
        _engine(model, SpecConfig(method="draft", draft=_draft_cfg(),
                                  window=0))
    with pytest.raises(ValueError, match="max_seq_len"):
        _engine(model, SpecConfig(method="draft", draft=_draft_cfg(),
                                  window=16))
    with pytest.raises(ValueError, match="draft_model"):
        ServingEngine(model, ServingConfig(), draft_model=model)
    # the decode reserve is part of the admission bound: a request whose
    # prompt + budget + K can never fit is rejected up front
    engine = _engine(model, _spec("ngram", 4), num_pages=40,
                     page_size=4, max_prompt_len=8)
    cap = engine.cache.cfg.max_tokens_per_seq
    with pytest.raises(ValueError, match="reserve"):
        engine.add_request(np.arange(1, 8, dtype=np.int32), cap - 8)


@pytest.mark.slow  # round-gated at birth (tier-1 budget): the draft proposer path itself is tier-1-pinned by the greedy parity matrix; this pins only the prebuilt-model override plumbing (validated cheaply in test_spec_validation_errors too)
def test_prebuilt_draft_model_is_used():
    """ServingEngine(draft_model=) wins over building from spec.draft —
    parity holds with any draft (acceptance-only machinery)."""
    model = _toy_model()
    paddle.seed(29)
    draft = GPTForCausalLM(_draft_cfg())
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8,
        spec=SpecConfig(method="draft", depth=2, window=4)),
        draft_model=draft)
    assert engine._draft is draft
    p = np.asarray([3, 5, 7], np.int32)
    rid = engine.add_request(p, 6)
    np.testing.assert_array_equal(_reference(model, p, 6),
                                  engine.run()[rid])


# -------------------------------------------------------------- obs pins
def test_spec_gauges_pre_seeded_and_depth_published():
    model = _toy_model()
    engine = _engine(model, None)  # speculation OFF
    snap = engine.metrics.snapshot()
    for k in ("spec_depth", "spec_proposed_tokens_total",
              "spec_accepted_tokens_total", "spec_acceptance_rate"):
        assert snap["serving_" + k] == 0, k
    engine2 = _engine(model, _spec("ngram", 4))
    assert engine2.metrics.snapshot()["serving_spec_depth"] == 4
    # prometheus types the counters
    text = engine2.metrics.prometheus()
    assert "# TYPE serving_spec_proposed_tokens_total counter" in text
    assert "# TYPE serving_spec_accepted_tokens_total counter" in text
