"""Reference checkpoint interop (VERDICT r3 item 5).

Real PaddlePaddle `.pdparams` files are plain pickles of
{structured_name: ndarray, "StructuredToParameterName@@": name_table}
(reference framework/io.py:760 _legacy_save). The fixtures below are built
byte-for-byte in that layout WITHOUT our writer, so load-side interop is
tested against the real format, not against our own serialization.
"""
import io
import pickle
import struct

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.framework.io import (
    load_binary_tensor,
    load_binary_vars,
    save_binary_tensor,
)


def _reference_style_pdparams(tmp_path, arrays):
    """Byte-layout of real paddle.save(layer.state_dict(), ...)."""
    saved = dict(arrays)
    saved["StructuredToParameterName@@"] = {
        k: f"linear_0.{k[0]}_0" for k in arrays}
    p = tmp_path / "ref_model.pdparams"
    with open(p, "wb") as f:
        pickle.dump(saved, f, protocol=2)  # real paddle defaults protocol=2
    return str(p)


def test_load_reference_format_pdparams_into_model(tmp_path):
    rng = np.random.RandomState(0)
    w = rng.randn(4, 3).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    path = _reference_style_pdparams(tmp_path, {"weight": w, "bias": b})

    sd = paddle.load(path)
    assert set(sd) == {"weight", "bias"}  # name table stripped
    m = nn.Linear(4, 3)
    m.set_state_dict(sd)
    np.testing.assert_array_equal(m.weight.numpy(), w)
    x = np.ones((2, 4), np.float32)
    np.testing.assert_allclose(
        np.asarray(m(paddle.to_tensor(x))._value), x @ w + b, rtol=1e-6)


def test_load_reference_pdopt_with_lr_scheduler_entry(tmp_path):
    moment = np.arange(6, dtype=np.float32).reshape(2, 3)
    saved = {"linear_0.w_0_moment1_0": moment,
             "LR_Scheduler": {"last_epoch": 3, "last_lr": 0.01},
             "StructuredToParameterName@@": {}}
    p = tmp_path / "adam.pdopt"
    with open(p, "wb") as f:
        pickle.dump(saved, f, protocol=2)
    sd = paddle.load(str(p))
    np.testing.assert_array_equal(sd["linear_0.w_0_moment1_0"].numpy(), moment)
    assert sd["LR_Scheduler"]["last_epoch"] == 3


def test_load_reference_big_param_slices(tmp_path):
    """UnpackBigParamInfor@@ re-merge (reference fluid/io.py:1804)."""
    full = np.arange(12, dtype=np.float32).reshape(3, 4)
    flat = full.flatten()
    saved = {
        "w@@.0": flat[:7], "w@@.1": flat[7:],
        "UnpackBigParamInfor@@": {
            "w": {"OriginShape": (3, 4), "slices": ["w@@.0", "w@@.1"]}},
        "StructuredToParameterName@@": {"w": "linear_0.w_0"},
    }
    p = tmp_path / "big.pdparams"
    with open(p, "wb") as f:
        pickle.dump(saved, f, protocol=2)
    sd = paddle.load(str(p), return_numpy=True)
    np.testing.assert_array_equal(sd["w"], full)


def test_load_reference_reduce_tuple_tensor(tmp_path):
    """Nested pickles from real paddle represent tensors as (name, ndarray)
    reduce-tuples (reference io.py:243 reduce_varbase)."""
    obj = {"model": {"w": ("linear_0.w_0", np.ones((2, 2), np.float32))},
           "epoch": 7}
    p = tmp_path / "nested.pd"
    with open(p, "wb") as f:
        pickle.dump(obj, f, protocol=2)
    back = paddle.load(str(p))
    assert back["epoch"] == 7
    t = back["model"]["w"]
    assert t.name == "linear_0.w_0"
    np.testing.assert_array_equal(t.numpy(), np.ones((2, 2), np.float32))


def test_export_is_loadable_without_paddle_tpu(tmp_path):
    """Our .pdparams must be a PLAIN pickle (dict of ndarrays + name table):
    exactly what real paddle.load parses — no custom classes."""
    m = nn.Linear(5, 2)
    p = str(tmp_path / "ours.pdparams")
    paddle.save(m.state_dict(), p)
    with open(p, "rb") as f:
        raw = pickle.load(f)  # would raise if custom classes were pickled
    assert "StructuredToParameterName@@" in raw
    tensors = {k: v for k, v in raw.items()
               if k != "StructuredToParameterName@@"}
    assert all(type(v) is np.ndarray for v in tensors.values())
    assert set(tensors) == set(m.state_dict())


def test_roundtrip_reference_format(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    p = str(tmp_path / "seq.pdparams")
    paddle.save(m.state_dict(), p)
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict(paddle.load(p))
    x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
    np.testing.assert_allclose(np.asarray(m(x)._value),
                               np.asarray(m2(x)._value), rtol=1e-6)


# ------------------------------------------------------- binary var stream
def test_binary_lod_tensor_golden_bytes():
    """Hand-assembled stream per lod_tensor.cc:191/tensor_util.cc:1004:
    u32 0 | u64 lod_level=0 | u32 0 | i32 desc_len | desc | raw fp32."""
    arr = np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)
    desc = b"\x08\x05" + b"\x10\x02" + b"\x10\x02"  # FP32, dims [2,2]
    golden = (struct.pack("<I", 0) + struct.pack("<Q", 0)
              + struct.pack("<I", 0) + struct.pack("<i", len(desc)) + desc
              + arr.tobytes())
    got = load_binary_tensor(io.BytesIO(golden))
    np.testing.assert_array_equal(got, arr)

    # our writer must emit the identical byte stream
    buf = io.BytesIO()
    save_binary_tensor(buf, arr)
    assert buf.getvalue() == golden


@pytest.mark.parametrize("dtype", ["float32", "float64", "int64", "int32",
                                   "float16", "uint8", "bool"])
def test_binary_tensor_dtype_roundtrip(tmp_path, dtype):
    rng = np.random.RandomState(1)
    arr = (rng.rand(3, 5) * 10).astype(dtype)
    p = str(tmp_path / f"var_{dtype}")
    save_binary_tensor(p, arr)
    np.testing.assert_array_equal(load_binary_tensor(p), arr)


def test_binary_combined_params_file(tmp_path):
    """__params__-style combined file: concatenated streams read in order."""
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    b = np.arange(4, dtype=np.int64)
    p = str(tmp_path / "__params__")
    with open(p, "wb") as f:
        save_binary_tensor(f, a)
        save_binary_tensor(f, b)
    out = load_binary_vars(p, ["a", "b"])
    np.testing.assert_array_equal(out["a"], a)
    np.testing.assert_array_equal(out["b"], b)


def test_save_use_binary_format_and_sniffing_load(tmp_path):
    t = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
    p = str(tmp_path / "w.pdtensor")
    paddle.save(t, p, use_binary_format=True)
    back = paddle.load(p)  # sniffs non-pickle -> LoDTensor stream
    np.testing.assert_array_equal(back.numpy(), t.numpy())


def test_nested_name_table_shaped_key_preserved(tmp_path):
    """The name table is root-level metadata only: an identically-named key
    inside a NESTED dict is user data and must survive the load."""
    obj = {"outer": {"StructuredToParameterName@@": {"w": "w0"}, "x": 1}}
    p = tmp_path / "nested_table.pd"
    with open(p, "wb") as f:
        pickle.dump(obj, f, protocol=2)
    back = paddle.load(str(p))
    assert back["outer"]["StructuredToParameterName@@"] == {"w": "w0"}
    assert back["outer"]["x"] == 1


def test_save_rejects_unreadable_protocols(tmp_path):
    with pytest.raises(ValueError, match="protocol"):
        paddle.save({"a": paddle.ones([2])}, str(tmp_path / "x.pd"), protocol=1)
    with pytest.raises(ValueError, match="protocol"):
        paddle.save({"a": paddle.ones([2])}, str(tmp_path / "x.pd"), protocol=5)


def test_bf16_state_dict_is_portable(tmp_path):
    """bf16 params export as fp32 ndarrays (loadable without ml_dtypes) and
    cast back to bf16 by set_state_dict."""
    m = nn.Linear(4, 4)
    m.to(dtype="bfloat16")
    p = str(tmp_path / "bf16.pdparams")
    paddle.save(m.state_dict(), p)
    with open(p, "rb") as f:
        raw = pickle.load(f)
    assert raw["weight"].dtype == np.float32
    m2 = nn.Linear(4, 4)
    m2.to(dtype="bfloat16")
    m2.set_state_dict(paddle.load(p))
    assert m2.weight.dtype == "bfloat16"
    np.testing.assert_array_equal(
        np.asarray(m2.weight.numpy(), np.float32),
        np.asarray(m.weight.numpy(), np.float32))


def test_old_private_format_still_loads(tmp_path):
    """Round-1/2 checkpoints pickled _TensorPayload objects."""
    from paddle_tpu.framework.io import _TensorPayload

    p = str(tmp_path / "old.pd")
    with open(p, "wb") as f:
        pickle.dump({"x": _TensorPayload(np.ones(3, np.float32))}, f)
    back = paddle.load(p)
    np.testing.assert_array_equal(back["x"].numpy(), np.ones(3, np.float32))


@pytest.mark.slow
def test_gpt_checkpoint_reference_format(tmp_path):
    """End-to-end: GPT weights exported in the reference layout reload into a
    fresh model with identical logits."""
    from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(11)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                    max_seq_len=32, dropout=0.0)
    m = GPTForCausalLM(cfg)
    p = str(tmp_path / "gpt.pdparams")
    paddle.save(m.state_dict(), p)
    paddle.seed(12)
    m2 = GPTForCausalLM(cfg)
    m2.set_state_dict(paddle.load(p))
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype(np.int32))
    m.eval(), m2.eval()
    np.testing.assert_allclose(np.asarray(m(ids)._value),
                               np.asarray(m2(ids)._value), rtol=1e-6)
