"""Eager autograd tape tests (reference: eager-mode grad checks; the analytic-vs-
finite-difference method of op_test.py check_grad)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_and_accumulate():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * 3).sum()
    y.backward()
    z = (x * 2).sum()
    z.backward()
    assert np.allclose(x.grad.numpy(), [5.0, 5.0])  # 3 + 2 accumulated
    x.clear_grad()
    assert x.grad is None


def test_matmul_grad_matches_fd():
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(4, 2).astype(np.float32)
    ta = paddle.to_tensor(a, stop_gradient=False)
    tb = paddle.to_tensor(b, stop_gradient=False)
    loss = (ta @ tb).sum()
    loss.backward()
    # analytic: dL/da = ones @ b.T
    assert np.allclose(ta.grad.numpy(), np.ones((3, 2)) @ b.T, rtol=1e-5)
    assert np.allclose(tb.grad.numpy(), a.T @ np.ones((3, 2)), rtol=1e-5)


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    assert np.allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    d = (x * 2).detach()
    assert d.stop_gradient
    z = (x + d).sum()
    z.backward()
    assert np.allclose(x.grad.numpy(), [1.0, 1.0])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 5
    assert y._tape_node is None


def test_branching_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    a = x * 3
    b = x * 4
    y = (a + b).sum()
    y.backward()
    assert np.allclose(x.grad.numpy(), [7.0])


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.random.rand(4, 6).astype(np.float32), stop_gradient=False)
    v, i = paddle.topk(x, 2, axis=1)
    v.sum().backward()
    g = x.grad.numpy()
    assert g.sum() == pytest.approx(8.0)  # 2 per row * 4 rows
    assert ((g == 0) | (g == 1)).all()


def test_paddle_grad_api():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * x
    (g,) = paddle.grad(y, x)
    assert np.allclose(g.numpy(), [6.0])


def test_softmax_cross_entropy_grad():
    logits = paddle.to_tensor(np.random.rand(5, 10).astype(np.float32), stop_gradient=False)
    labels = paddle.to_tensor(np.random.randint(0, 10, (5,)))
    loss = paddle.nn.functional.cross_entropy(logits, labels)
    loss.backward()
    g = logits.grad.numpy()
    # gradient rows sum to zero (softmax CE property)
    assert np.allclose(g.sum(axis=1), 0.0, atol=1e-5)


def test_retain_graph():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).sum()
    y.backward(retain_graph=True)
    y.backward(retain_graph=False)
    assert np.allclose(x.grad.numpy(), [4.0])


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    assert np.allclose(y.numpy(), [6.0])
    assert np.allclose(x.grad.numpy(), [2.0])


def test_higher_shape_broadcast_grad():
    x = paddle.to_tensor(np.random.rand(3, 1).astype(np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.random.rand(1, 4).astype(np.float32), stop_gradient=False)
    y = (x + b).sum()
    y.backward()
    assert x.grad.shape == [3, 1]
    assert np.allclose(x.grad.numpy(), 4.0)
    assert b.grad.shape == [1, 4]
    assert np.allclose(b.grad.numpy(), 3.0)
