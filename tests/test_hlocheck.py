"""paddle_tpu.analysis.hlocheck — the compiled-artifact auditor.

Four layers of coverage:

- parsing: byte volumes off HLO result types, census over real compiled
  text (collectives classified with payload bytes, host callbacks
  flagged, -done halves not double-counted).
- budgets: a declared CollectiveBudget passes, the zero (single-chip)
  budget raises NAMING the op; byte caps and host-transfer floors raise.
- aliasing: donated-and-consumed pools verified against XLA's
  input_output_alias table; an unaliasable donation raises naming the
  leaf (the compiled proof behind PT006).
- integration: the ACCEPTANCE GATES — engine prefill+decode pass under
  debug_checks (zero collectives, zero host transfers, all donations
  aliased, serving_hlo_* metrics live), and the toy 8-device shard_map
  step certifies against a budget of exactly one all-reduce while the
  over-budget variant raises (the registry + CLI share all of it).
"""
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import hlocheck
from paddle_tpu.analysis.hlocheck import (REGISTRY, SINGLE_CHIP,
                                          AliasingViolation,
                                          CollectiveBudget,
                                          CollectiveBudgetError,
                                          HostTransferError, audit, census,
                                          run_step)
from paddle_tpu.serving import ServingConfig, ServingEngine
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.hlocheck

REPO = pathlib.Path(__file__).resolve().parent.parent


# ------------------------------------------------------------------ parsing
def test_type_bytes_parser():
    tb = hlocheck._type_bytes
    assert tb("f32[4,8]{1,0}") == 128
    assert tb("bf16[2,2]{1,0}") == 8
    assert tb("(f32[4]{0}, bf16[2,2]{1,0})") == 24
    assert tb("f32[]") == 4       # scalar
    assert tb("s8[3]{0}") == 3
    assert tb("u32[2]{0}") == 8
    assert tb("pred[5]{0}") == 5
    # sub-byte dtypes pack: an int4 quantized collective (the EQuARX-style
    # payload these volumes baseline) is NOT charged a byte per element
    assert tb("s4[1024]{0}") == 512
    assert tb("u2[5]{0}") == 2    # ceil(10 bits / 8)


def test_census_classifies_and_skips_done_halves():
    text = """
  %all-reduce.1 = f32[4,8]{1,0} all-reduce(f32[4,8]{1,0} %x), channel_id=1
  %ag-start = (f32[2]{0}, f32[16]{0}) all-gather-start(f32[2]{0} %y)
  %ag-done = f32[16]{0} all-gather-done((f32[2]{0}, f32[16]{0}) %ag-start)
  %arc = (f32[2]{0}, f32[4]{0}, f32[2]{0}, f32[4]{0}) all-reduce-start(f32[2]{0} %c0, f32[4]{0} %c1), channel_id=3
  %rs = f32[2]{0} reduce-scatter(f32[16]{0} %z), dimensions={0}
  %cp = f32[4]{0} collective-permute(f32[4]{0} %w)
  %cc = () custom-call(f32[] %v), custom_call_target="xla_python_cpu_callback"
  %mm = f32[4,4]{1,0} custom-call(f32[4,4]{1,0} %a), custom_call_target="__onednn$matmul"
  %send.1 = (f32[2]{0}, u32[], token[]) send(f32[2]{0} %s, token[] %t), channel_id=2, is_host_transfer=true
  %infeed.1 = (f32[3]{0}, token[]) infeed(token[] %t2)
"""
    colls, hosts = census(text)
    kinds = sorted(c.kind for c in colls)
    assert kinds == ["all-gather", "all-reduce", "all-reduce",
                     "collective-permute", "reduce-scatter"]
    ar = next(c for c in colls if c.instr == "all-reduce.1")
    assert ar.nbytes == 128
    # the -start counts once and charges only its RESULT buffer(s) (64 B
    # for the f32[16] gather, not the (operand, result) tuple's 72), so
    # byte caps hold whether XLA compiles the sync or async form; a
    # combiner-merged variadic -start charges its whole result half
    # (24 B = f32[2] + f32[4], not just the last element); -done never
    ag = next(c for c in colls if c.kind == "all-gather")
    assert ag.nbytes == 64
    arc = next(c for c in colls if c.instr == "arc")
    assert arc.nbytes == 24
    # host transfers: the python callback, the host send, the infeed —
    # NOT the oneDNN matmul custom-call
    assert sorted(h.kind for h in hosts) == ["custom-call", "infeed", "send"]
    cb = next(h for h in hosts if h.kind == "custom-call")
    assert cb.detail == "xla_python_cpu_callback"


# ---------------------------------------------------- budgets on real steps
@pytest.fixture(scope="module")
def tp8_report():
    """The toy tensor-parallel shard_map step, audited ONCE for the whole
    module (enforced against its own declared budget inside run_step)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest 8-device CPU mesh")
    return run_step("tp8_decode")


def test_tp8_certifies_against_declared_budget(tp8_report):
    """THE acceptance gate for the sharded-serving arc: the Megatron-split
    step compiles to exactly its declared collective — one all-reduce of
    the [B, H] partials — and nothing else (no implicit resharding
    all-gathers, no host transfers)."""
    assert tp8_report.counts() == {"all-reduce": 1}
    assert tp8_report.collective_bytes == \
        hlocheck._TP8_BATCH * hlocheck._TP8_HIDDEN * 4
    assert tp8_report.host_transfers == ()
    assert tp8_report.flops > 0 and tp8_report.peak_bytes > 0
    # re-enforcing the declared budget is idempotent (pure over the report)
    tp8_report.enforce(CollectiveBudget(
        all_reduce=1,
        max_collective_bytes=tp8_report.collective_bytes))


def test_tp8_over_budget_raises_naming_the_op(tp8_report):
    """The over-budget variant: the SAME compiled step held to the
    single-chip (zero) budget must raise naming the op, its count, and
    its payload."""
    with pytest.raises(CollectiveBudgetError) as ei:
        tp8_report.enforce(SINGLE_CHIP)
    msg = str(ei.value)
    assert "all-reduce" in msg and "budget of 0" in msg
    assert "128 B" in msg            # the payload volume
    assert "%all-reduce" in msg      # the offending HLO instruction


def test_tp8_byte_cap_raises(tp8_report):
    with pytest.raises(CollectiveBudgetError) as ei:
        tp8_report.enforce(CollectiveBudget(all_reduce=1,
                                            max_collective_bytes=64))
    assert "exceeds the declared cap of 64" in str(ei.value)


def test_single_device_step_has_no_collectives():
    r = audit(lambda x, y: x @ y,
              (jnp.ones((4, 8), jnp.float32), jnp.ones((8, 2), jnp.float32)),
              budget=SINGLE_CHIP)
    assert r.collectives == () and r.host_transfers == ()
    assert r.flops > 0 and r.peak_bytes > 0


def test_host_callback_flagged_and_budgeted():
    def f(x):
        y = jax.pure_callback(lambda a: np.asarray(a) * 2,
                              jax.ShapeDtypeStruct((4,), jnp.float32), x)
        return y + 1

    r = audit(f, (jnp.ones((4,), jnp.float32),))
    assert len(r.host_transfers) == 1
    assert "callback" in r.host_transfers[0].detail
    with pytest.raises(HostTransferError) as ei:
        r.enforce(SINGLE_CHIP)
    assert "callback" in str(ei.value)
    r.enforce(CollectiveBudget(host_transfers=1))  # sanctioned: passes


# ---------------------------------------------------------------- aliasing
def test_donated_pools_verified_aliased():
    def scatter(pools, x):
        return [{"k": p["k"].at[0].set(x), "v": p["v"].at[0].set(x)}
                for p in pools]

    pools = [{"k": jnp.ones((4, 2), jnp.float32),
              "v": jnp.ones((4, 2), jnp.float32)} for _ in range(2)]
    r = audit(scatter, (pools, jnp.ones((2,), jnp.float32)),
              donate_argnums=(0,), budget=SINGLE_CHIP)
    assert r.donated_leaves == 4 == r.aliased_leaves
    assert r.unaliased == () and r.alias_bytes == r.donated_bytes > 0


def test_unaliasable_donation_raises_naming_leaf():
    """XLA cannot alias a donated buffer into a smaller output — the
    compiled artifact has NO alias entry for it, and the audit must say
    which leaf lost its donation (the silent-2x-HBM failure mode)."""
    r = audit(lambda pool: pool[0] * 2,
              (jnp.ones((8, 4), jnp.float32),), donate_argnums=(0,))
    assert r.donated_leaves == 1 and r.aliased_leaves == 0
    with pytest.raises(AliasingViolation) as ei:
        r.enforce(SINGLE_CHIP)
    msg = str(ei.value)
    assert "pool" in msg and "TWO copies" in msg


# ------------------------------------------------------- engine integration
def _toy_engine(**overrides):
    paddle.seed(23)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=48, dropout=0.0))
    model.eval()
    kw = dict(max_batch=2, num_pages=24, page_size=4, max_prompt_len=16,
              debug_checks=True)
    kw.update(overrides)
    return ServingEngine(model, ServingConfig(**kw))


def test_engine_steps_pass_hlocheck_under_debug_checks():
    """The single-chip acceptance gate: every compiled program (one per
    prefill bucket + decode) audits clean — zero collectives, zero host
    transfers, every donated pool leaf aliased — and the roll-up lands in
    the serving_hlo_* metrics."""
    engine = _toy_engine()
    snap0 = engine.metrics.snapshot()
    for k in ("serving_hlo_collective_ops", "serving_hlo_host_transfers",
              "serving_hlo_peak_hbm_bytes", "serving_hlo_flops_per_step"):
        assert snap0[k] == 0, k  # pre-seeded: visible before any audit
    assert engine.hlo_audits == {}
    rng = np.random.RandomState(0)
    for n, b in ((3, 4), (12, 3)):  # spans both pad buckets [8, 16]
        engine.add_request(rng.randint(0, 97, (n,)).astype(np.int32), b)
    engine.run()
    audits = engine.hlo_audits
    assert set(audits) == {"prefill[8]", "prefill[16]", "decode"}
    for name, r in audits.items():
        assert r.collectives == (), name
        assert r.host_transfers == (), name
        assert r.donated_leaves == 4 == r.aliased_leaves, name  # 2 layers k+v
        assert r.unaliased == (), name
        assert r.flops > 0 and r.peak_bytes > 0, name
    snap = engine.metrics.snapshot()
    assert snap["serving_hlo_collective_ops"] == 0
    assert snap["serving_hlo_host_transfers"] == 0
    assert snap["serving_hlo_peak_hbm_bytes"] == \
        max(r.peak_bytes for r in audits.values())
    assert snap["serving_hlo_flops_per_step"] == \
        max(r.flops for r in audits.values())
    # the audits did not disturb the PR 4/5 certifications
    assert snap["serving_analysis_retraces_total"] == 0
    expected = snap["serving_decode_steps"] + snap["serving_prefills_total"]
    assert snap["serving_analysis_host_syncs_total"] == expected


def test_engine_audits_once_per_compiled_program():
    """The cost contract: one hlocheck audit per compiled program, not per
    step — a second same-bucket prefill or later decode steps add no new
    reports (and compile_counts pins the real trace counts unchanged)."""
    engine = _toy_engine(max_prompt_len=8)
    rng = np.random.RandomState(1)
    for n in (3, 4, 5):
        engine.add_request(rng.randint(0, 97, (n,)).astype(np.int32), 3)
    engine.run()
    assert set(engine.hlo_audits) == {"prefill[8]", "decode"}
    assert engine.compile_counts == {"prefill": 1, "decode": 1}
    snap = engine.metrics.snapshot()
    assert snap["serving_hlo_collective_ops"] == 0


def test_debug_checks_off_skips_hlo_audit():
    engine = _toy_engine(debug_checks=False, max_prompt_len=8)
    rng = np.random.RandomState(2)
    engine.add_request(rng.randint(0, 97, (4,)).astype(np.int32), 3)
    engine.run()
    assert engine.hlo_audits == {}


# ----------------------------------------------------------- registry + CLI
def test_registry_cache_steps_audit_clean():
    gather = run_step("swap_gather")
    assert gather.donated_leaves == 0 and gather.collectives == ()
    scatter = run_step("swap_scatter")
    assert scatter.donated_leaves == 4 == scatter.aliased_leaves
    cow = run_step("cow_copy")
    assert cow.donated_leaves == 4 == cow.aliased_leaves


def test_run_step_unknown_name_raises():
    with pytest.raises(KeyError) as ei:
        run_step("nonexistent")
    assert "tp8_decode" in str(ei.value)  # the error lists the registry


def test_registry_names_are_stable():
    assert set(REGISTRY) == {"swap_gather", "swap_scatter", "cow_copy",
                             "engine_prefill", "engine_prefill_chunk",
                             "engine_decode", "engine_verify_spec",
                             "tp8_decode",
                             "tp2_engine_prefill",
                             "tp2_engine_prefill_chunk",
                             "tp2_engine_decode",
                             "tp2_engine_verify_spec", "tp2_swap_gather",
                             "tp2_swap_scatter", "tp2_cow_copy",
                             "engine_decode_q8", "swap_gather_q8",
                             "swap_scatter_q8", "tp2_engine_decode_q8",
                             "tp2_engine_decode_qlogits"}
    assert REGISTRY["tp8_decode"].min_devices == 8
    assert all(REGISTRY[n].min_devices == 2 for n in REGISTRY
               if n.startswith("tp2_"))


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; the tp8/tp2 certifications stay tier-1 in-process (run_step), only the CLI subprocess plumbing moves
def test_cli_hlo_step_and_exit_codes():
    """`python -m paddle_tpu.analysis --hlo` shares the entry point with
    the lint CLI: clean steps exit 0 with a census summary, unknown steps
    exit 2. The tp8 certification runs on the forced 8-device CPU mesh
    (the suite's own conftest environment, inherited by the child)."""
    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
           "PATH": "/usr/bin:/bin"}
    import os
    env = {**os.environ, **env}
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--hlo",
         "--step", "tp8_decode", "--step", "swap_gather"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "all-reducex1" in r.stdout and "within budget" in r.stdout

    unknown = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--hlo",
         "--step", "nope"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert unknown.returncode == 2
    assert "unknown step" in unknown.stdout

    listing = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--hlo",
         "--list-steps"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert listing.returncode == 0
    for name in REGISTRY:
        assert name in listing.stdout


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; the tp8/tp2 certifications stay tier-1 in-process (run_step), only the CLI subprocess plumbing moves
def test_cli_respawned_child_never_respawns_again():
    """The recursion guard: a respawned child that STILL sees too few
    devices (forced flag didn't take) must report an execution error and
    exit 1 — never spawn a grandchild."""
    import os
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           hlocheck._CHILD_ENV: "1"}
    env.pop("XLA_FLAGS", None)  # 1 device: the forced mesh "didn't take"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--hlo",
         "--step", "tp8_decode"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "did not take effect" in r.stdout
    assert "re-running" not in r.stdout  # no grandchild spawned
