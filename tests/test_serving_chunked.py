"""Chunked prefill + SLO-adaptive admission (serving/engine.py chunk
phase, serving/slo.py controller).

The contracts pinned here, in the order the ISSUE names them:

- **Bit-identical outputs** chunked vs. unchunked — greedy, across chunk
  sizes (including one that doesn't divide the prompt), through a
  prefix-cache hit, and under sampling (the per-request PRNG key scheme
  is position-keyed, so chunk boundaries cannot shift any stream).
- **Compile-count stability**: chunks pad into the existing bucket set,
  so the bucket set stays the ONLY source of prefill compiles — no new
  trace per chunk size or chunk count, pinned via ``compile_counts``.
- **Mid-prefill preemption**: a recompute victim replays its chunks from
  scratch, a swap victim resumes exactly where it left (chunk counters
  prove no rework) — both bit-identical.
- **SLO controller**: windowed-p99 AIMD over chunks-per-step, unit-level
  goldens plus an engine integration on a ticking virtual clock; the
  degraded mode's warm-prefix admission preference at scheduler level.
- **Sync-free certification unchanged**: intermediate chunks never fetch
  their token, so SyncTally == decode steps + COMPLETED prefills with
  chunking and the controller both ON.
- Obs: ``prefill_chunk`` lifecycle events, chunk spans in the Chrome
  export, pre-seeded chunk gauges; hlocheck: the chunk-shaped call is a
  registered, clean step.
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import SyncTally
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.serving import (FaultInjector, PagedCacheConfig,
                                PagedKVCache, Request, Scheduler,
                                ServingConfig, ServingEngine, ServingMetrics,
                                SLOConfig, SLOController)
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.chunked


class TickClock:
    """Strictly increasing engine clock: 10 ms per read — step durations
    become a deterministic function of how much host work a step did."""

    def __init__(self, tick=0.01):
        self.t = 0.0
        self.tick = tick

    def __call__(self):
        self.t += self.tick
        return self.t


def _toy_model(seed=11, max_seq_len=64):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=max_seq_len, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _reference(model, prompt, budget):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=budget)
    return np.asarray(out._value)[0]


def _prompts(seed, lens):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 97, (n,)).astype(np.int32) for n in lens]


def _engine(model, chunk_size=8, **overrides):
    kw = dict(max_batch=3, num_pages=32, page_size=4, max_prompt_len=24,
              chunk_size=chunk_size)
    kw.update(overrides)
    return ServingEngine(model, ServingConfig(**kw))


# ---------------------------------------------------------------- parity
@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 budget; chunked parity + the one-program-per-bucket
# pin stay tier-1 via test_no_new_trace_per_chunk_count, the chunk-8 parity/sampling/prefix tests,
# and test_serving_tp's chunked compile_counts pin
def test_greedy_parity_across_chunk_sizes_and_compile_stability():
    model = _toy_model()
    prompts = _prompts(0, (20, 4, 13, 7))
    budgets = [6, 8, 5, 7]
    refs = [_reference(model, p, b) for p, b in zip(prompts, budgets)]

    traces = {}
    for chunk in (0, 4, 8, 16):
        engine = _engine(model, chunk_size=chunk)
        rids = [engine.add_request(p, b)
                for p, b in zip(prompts, budgets)]
        outs = engine.run()
        for i, rid in enumerate(rids):
            np.testing.assert_array_equal(
                refs[i], outs[rid],
                err_msg=f"chunk_size={chunk}: request {i} diverged")
        traces[chunk] = engine.compile_counts
        assert engine.cache.allocator.pages_in_use == 0
    # the bucket set is the only source of prefill compiles: chunk size 4
    # and 8 route every chunk through bucket 8 (ONE prefill program);
    # chunk 16 uses buckets {8, 16}; unchunked spans all three buckets of
    # max_prompt_len=24. Chunking never ADDS a program.
    assert traces[4] == {"prefill": 1, "decode": 1}
    assert traces[8] == {"prefill": 1, "decode": 1}
    assert traces[16] == {"prefill": 2, "decode": 1}
    assert traces[0] == {"prefill": 3, "decode": 1}


def test_no_new_trace_per_chunk_count():
    # the SAME engine serves prompts needing 1, 2, and 3 chunks: trace
    # count must not move after the first chunk compiles its bucket
    model = _toy_model()
    engine = _engine(model, chunk_size=8)
    for n, budget in ((5, 3), (13, 3), (20, 3)):
        rid = engine.add_request(_prompts(n, (n,))[0], budget)
        engine.run()
        assert engine.compile_counts == {"prefill": 1, "decode": 1}, \
            f"prompt of {n} tokens retraced the prefill"


def test_parity_on_prefix_cache_hit_chunked():
    # the second request's cached whole-page prefix is mapped by refcount
    # and only its uncached tail streams through chunks — bit-identical
    model = _toy_model()
    system = _prompts(2, (16,))[0]  # 4 whole pages of 4
    tails = _prompts(3, (7, 5))
    prompts = [np.concatenate([system, t]).astype(np.int32) for t in tails]
    refs = [_reference(model, p, 5) for p in prompts]

    engine = _engine(model, chunk_size=4)
    outs = {}
    for p in prompts:  # sequential so the second hits the first's pages
        rid = engine.add_request(p, 5)
        outs[rid] = engine.run()[rid]
    for (rid, out), ref in zip(sorted(outs.items()), refs):
        np.testing.assert_array_equal(ref, out)
    snap = engine.metrics.snapshot()
    assert snap["serving_prefix_hits"] == 1
    assert snap["serving_prefix_tokens_saved"] >= 16
    tr = engine.trace(max(outs))
    # the hit request chunked ONLY its tail: ceil(7/4) = 2 chunks, each
    # starting at or past the cached 16 tokens
    chunk_starts = [e.arg("start") for e in tr.events
                    if e.name == "prefill_chunk"]
    assert len(chunk_starts) == 2 and min(chunk_starts) >= 16


def test_sampling_parity_chunked_vs_unchunked():
    # PRNG keys fold (seed, rid, token index) — pure position identity —
    # so chunk boundaries cannot resample any request's stream
    from paddle_tpu.serving import scheduler as sched_mod

    model = _toy_model(seed=23)
    prompts = _prompts(4, (18, 6, 11))
    budgets = [7, 6, 5]

    def drive(chunk):
        sched_mod._rid_counter = itertools.count(7000)  # align rids
        engine = _engine(model, chunk_size=chunk, do_sample=True,
                         temperature=0.8, top_k=20, seed=5)
        rids = [engine.add_request(p, b)
                for p, b in zip(prompts, budgets)]
        return rids, engine.run()

    saved = sched_mod._rid_counter
    try:
        rids_a, outs_a = drive(0)
        rids_b, outs_b = drive(8)
    finally:
        sched_mod._rid_counter = saved
    assert rids_a == rids_b
    for ra, rb in zip(rids_a, rids_b):
        np.testing.assert_array_equal(
            outs_a[ra], outs_b[rb],
            err_msg="chunked prefill resampled a different stream")


# ------------------------------------------------- mid-prefill preemption
@pytest.mark.parametrize("mode", ["recompute", "swap"])
def test_mid_prefill_preemption_parity(mode):
    # pool_exhausted at step 1: the whale is mid-prefill (one chunk
    # resident) and is the only candidate — it IS the victim. Recompute
    # replays its chunks from scratch; swap restores the partial KV and
    # continues exactly where it left (chunk events prove no rework).
    model = _toy_model()
    whale = _prompts(5, (20,))[0]
    ref = _reference(model, whale, 6)
    inj = FaultInjector().arm("pool_exhausted", step=1)
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=32, page_size=4, max_prompt_len=24,
        chunk_size=8, preemption_mode=mode), fault_injector=inj)
    rid = engine.add_request(whale, 6)
    outs = engine.run()
    np.testing.assert_array_equal(ref, outs[rid])
    tr = engine.trace(rid)
    assert tr.count("preempted") == 1
    chunks = [(e.arg("start"), e.arg("tokens")) for e in tr.events
              if e.name == "prefill_chunk"]
    snap = engine.metrics.snapshot()
    if mode == "recompute":
        # 2 chunks before the preemption (steps 0-1), then a full replay
        assert chunks == [(0, 8), (8, 8), (0, 8), (8, 8), (16, 4)]
        assert tr.count("prefill_start") == 2  # the replay's second span
    else:
        # swap: the restored pages hold the first two chunks' KV — the
        # prefill CONTINUES at token 16, no chunk is ever recomputed
        assert chunks == [(0, 8), (8, 8), (16, 4)]
        assert tr.count("prefill_start") == 1
        assert tr.count("swap_out") == 1 and tr.count("swap_in") == 1
        assert snap["serving_swap_ins"] == snap["serving_swap_outs"] == 1
    assert snap["serving_prefill_chunks_total"] == len(chunks)
    assert engine.cache.allocator.pages_in_use == 0


def test_cancel_and_deadline_mid_prefill_drain_pages():
    model = _toy_model()
    whale1, whale2, short = _prompts(6, (20, 18, 4))

    # cancel while PREFILLING
    engine = _engine(model, chunk_size=4, max_batch=2)
    r1 = engine.add_request(whale1, 4)
    r2 = engine.add_request(short, 4)
    engine.step()  # one chunk of the whale; the short one completes
    assert engine.status(r1) == "prefilling"
    assert engine.cancel(r1)
    assert engine.status(r1) == "cancelled"
    outs = engine.run()
    assert set(outs) == {r2}
    np.testing.assert_array_equal(_reference(model, short, 4), outs[r2])
    assert engine.cache.allocator.pages_in_use == 0

    # deadline expiry while PREFILLING (virtual clock)
    class Held:
        t = 0.0

        def __call__(self):
            return Held.t

    engine2 = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=32, page_size=4, max_prompt_len=24,
        chunk_size=4), clock=Held())
    r3 = engine2.add_request(whale2, 4, deadline_s=5.0)
    engine2.step()
    assert engine2.status(r3) == "prefilling"
    Held.t = 60.0
    engine2.step()
    assert engine2.status(r3) == "expired"
    assert engine2.cache.allocator.pages_in_use == 0


# ------------------------------------------------------- SLO controller
def _fed_metrics(step_s=0.0, tpot_s=0.0, n=8):
    m = ServingMetrics()
    for _ in range(n):
        if step_s:
            m.hists["step_duration_s"].observe(step_s)
        if tpot_s:
            m.hists["tpot_s"].observe(tpot_s)
    return m


def test_slo_config_validation():
    m = ServingMetrics()
    with pytest.raises(ValueError, match="at least one"):
        SLOController(SLOConfig(), m, 4)
    with pytest.raises(ValueError, match="window_steps"):
        SLOController(SLOConfig(ttft_p99_s=1.0, window_steps=0), m, 4)
    with pytest.raises(ValueError, match="min_chunks"):
        SLOController(SLOConfig(ttft_p99_s=1.0, min_chunks_per_step=0),
                      m, 4)
    with pytest.raises(ValueError, match="step_budget_frac"):
        SLOController(SLOConfig(ttft_p99_s=1.0, step_budget_frac=0.0),
                      m, 4)
    with pytest.raises(ValueError, match="max_chunks"):
        # a negative cap would slice prefilling[:-1] and hang the engine
        SLOController(SLOConfig(ttft_p99_s=1.0, max_chunks_per_step=-1),
                      m, 4)
    model = _toy_model()
    with pytest.raises(ValueError, match="chunk_size"):
        ServingEngine(model, ServingConfig(
            max_prompt_len=8, slo=SLOConfig(ttft_p99_s=1.0)))
    with pytest.raises(ValueError, match="enable_tracing"):
        ServingEngine(model, ServingConfig(
            max_prompt_len=8, chunk_size=4, enable_tracing=False,
            slo=SLOConfig(ttft_p99_s=1.0)))
    with pytest.raises(ValueError, match="chunk_size"):
        ServingEngine(model, ServingConfig(max_prompt_len=8, chunk_size=-1))
    with pytest.raises(ValueError, match="chunk_size"):
        ServingEngine(model, ServingConfig(max_prompt_len=8, chunk_size=9))


def test_slo_controller_aimd_golden():
    # breach -> multiplicative decrease (halve, floored); healthy ->
    # additive increase (+1, capped); degraded holds until fully recovered
    cfg = SLOConfig(tpot_p99_s=0.05, window_steps=4)
    m = _fed_metrics()  # empty: the construction-time mark sees zeros
    ctl = SLOController(cfg, m, default_max_chunks=8)
    assert ctl.chunk_limit == 8 and not ctl.degraded

    def window(tpot):
        for _ in range(4):
            if tpot:
                m.hists["tpot_s"].observe(tpot)
            change = ctl.on_step()
        return change

    assert window(0.2) == (8, 4) and ctl.degraded  # breach: halve
    assert window(0.2) == (4, 2) and ctl.throttles == 2
    assert window(0.2) == (2, 1)
    assert window(0.2) is None          # floored at min: no change
    assert ctl.chunk_limit == 1 and ctl.throttles == 3
    assert "tpot_p99" in ctl.last_breach[0]
    # recovery: +1 per clean window, degraded until back at the cap
    assert window(0.001) == (1, 2) and ctl.degraded
    for expect in (3, 4, 5, 6, 7):
        assert window(None) == (expect - 1, expect) and ctl.degraded
    assert window(None) == (7, 8) and not ctl.degraded
    assert window(None) is None  # capped
    # an empty window is NOT a breach and still recovers — but here we're
    # at the cap already, so nothing moves
    assert ctl.chunk_limit == 8 and ctl.evaluations == 12


def test_slo_ttft_step_budget_breach():
    # the TTFT target is enforced through its step-duration proxy:
    # p99(step) must stay under ttft_p99_s * step_budget_frac
    cfg = SLOConfig(ttft_p99_s=1.0, step_budget_frac=0.25, window_steps=2)
    m = ServingMetrics()
    ctl = SLOController(cfg, m, default_max_chunks=4)
    m.hists["step_duration_s"].observe(0.2)  # under the 0.25 budget
    m.hists["step_duration_s"].observe(0.2)
    ctl.on_step()
    assert ctl.on_step() is None and not ctl.degraded
    m.hists["step_duration_s"].observe(0.6)  # over budget
    m.hists["step_duration_s"].observe(0.6)
    ctl.on_step()
    assert ctl.on_step() == (4, 2) and ctl.degraded
    assert "step_duration_p99" in ctl.last_breach[0]


def test_slo_engine_integration_throttles_and_stays_correct():
    # ticking clock: every step has a real (virtual) duration, and a
    # microscopic TTFT target guarantees every window breaches — the
    # controller must throttle to the floor while outputs stay exact
    model = _toy_model()
    prompts = _prompts(7, (20, 13, 4, 18))
    budgets = [5, 6, 7, 4]
    refs = [_reference(model, p, b) for p, b in zip(prompts, budgets)]
    engine = ServingEngine(model, ServingConfig(
        max_batch=3, num_pages=32, page_size=4, max_prompt_len=24,
        chunk_size=4, slo=SLOConfig(ttft_p99_s=1e-6, window_steps=2)),
        clock=TickClock())
    rids = [engine.add_request(p, b) for p, b in zip(prompts, budgets)]
    outs = engine.run()
    for i, rid in enumerate(rids):
        np.testing.assert_array_equal(refs[i], outs[rid])
    snap = engine.metrics.snapshot()
    assert engine._slo.chunk_limit == 1, "every window breached: floor"
    assert snap["serving_chunk_limit"] == 1
    assert snap["serving_slo_throttles_total"] >= 1
    assert engine._slo.degraded


def test_prefer_cached_admission_prefers_warm_waiters():
    # scheduler-level: with prefer_cached the warm waiter (indexed prefix)
    # jumps the cold head; default admit() stays strictly FIFO; a
    # preemption victim at the front always outranks the preference
    cache = PagedKVCache(PagedCacheConfig(
        num_layers=1, num_heads=1, head_dim=4, num_pages=16, page_size=4,
        max_batch=2, pages_per_seq=4))
    warm_prefix = np.arange(8, dtype=np.int32)
    assert cache.admit(0, 8, tokens=warm_prefix)
    cache.register_prefix(0, warm_prefix)
    cache.release(0)  # pages park reclaimable, stay indexed

    cold = Request(prompt=np.arange(100, 108, dtype=np.int32),
                   max_new_tokens=2)
    warm = Request(prompt=np.concatenate(
        [warm_prefix, np.asarray([9], np.int32)]), max_new_tokens=2)
    s = Scheduler(cache, max_batch=1)
    s.add(cold)
    s.add(warm)
    admitted = s.admit(prefer_cached=True)
    assert [r.rid for r in admitted] == [warm.rid], \
        "degraded mode must admit the warm-prefix waiter first"
    assert warm.cached_tokens == 8
    assert list(s.waiting) == [cold]  # identity-removed mid-queue

    # default admission is untouched FIFO (slot 0 freed for the new
    # scheduler — the warm pages park reclaimable and stay indexed)
    cache.release(0)
    s2 = Scheduler(cache, max_batch=1)
    cold2 = Request(prompt=np.arange(200, 208, dtype=np.int32),
                    max_new_tokens=2)
    warm2 = Request(prompt=np.concatenate(
        [warm_prefix, np.asarray([10], np.int32)]), max_new_tokens=2)
    s2.add(cold2)
    s2.add(warm2)
    assert [r.rid for r in s2.admit()] == [cold2.rid]

    # cold waiters NEVER reorder among themselves: prefer_cached is a
    # warm-prefix preference, not shortest-job-first — a long cold head
    # keeps its turn against a shorter cold newcomer
    cache.release(0)
    s_cold = Scheduler(cache, max_batch=1)
    long_cold = Request(prompt=np.arange(400, 412, dtype=np.int32),
                        max_new_tokens=2)
    short_cold = Request(prompt=np.arange(500, 503, dtype=np.int32),
                         max_new_tokens=2)
    s_cold.add(long_cold)
    s_cold.add(short_cold)
    assert [r.rid for r in s_cold.admit(prefer_cached=True)] == \
        [long_cold.rid]

    # a front-queued victim outranks the warm preference
    cache.release(0)
    s3 = Scheduler(cache, max_batch=1)
    victim = Request(prompt=np.arange(300, 306, dtype=np.int32),
                     max_new_tokens=2)
    victim.preemptions = 1
    warm3 = Request(prompt=np.concatenate(
        [warm_prefix, np.asarray([11], np.int32)]), max_new_tokens=2)
    s3.waiting.appendleft(warm3)
    warm3.state = "waiting"
    s3.waiting.appendleft(victim)
    victim.state = "waiting"
    assert [r.rid for r in s3.admit(prefer_cached=True)] == [victim.rid]


def test_prefer_cached_head_skip_bound():
    # a cold head skipped HEAD_SKIP_LIMIT consecutive times by warm
    # waiters is force-admitted next — sustained warm traffic cannot
    # starve a cold whale indefinitely
    cache = PagedKVCache(PagedCacheConfig(
        num_layers=1, num_heads=1, head_dim=4, num_pages=16, page_size=4,
        max_batch=1, pages_per_seq=4))
    warm_prefix = np.arange(8, dtype=np.int32)
    assert cache.admit(0, 8, tokens=warm_prefix)
    cache.register_prefix(0, warm_prefix)
    cache.release(0)
    s = Scheduler(cache, max_batch=1)
    cold_head = Request(prompt=np.arange(100, 112, dtype=np.int32),
                        max_new_tokens=2)
    s.add(cold_head)
    skips = 0
    for i in range(s.HEAD_SKIP_LIMIT + 1):
        warm = Request(prompt=np.concatenate(
            [warm_prefix, np.asarray([i], np.int32)]), max_new_tokens=2)
        s.add(warm)
        (req,) = s.admit(prefer_cached=True)
        if req is cold_head:
            break
        skips += 1
        assert req is warm
        s.finish(warm)
    else:
        raise AssertionError("cold head never admitted")
    assert skips == s.HEAD_SKIP_LIMIT


def test_swap_mid_prefill_keeps_prefix_hit_accounting():
    # the swap restore zeroes cached_tokens (restored pages are not an
    # admission-time hit), but the prefill ATTEMPT's cache hit must still
    # be credited when the final chunk completes
    model = _toy_model()
    system = _prompts(20, (16,))[0]  # 4 whole pages of 4
    tail = _prompts(21, (8,))[0]
    warm_whale = np.concatenate([system, tail]).astype(np.int32)
    ref = _reference(model, warm_whale, 4)

    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=32, page_size=4, max_prompt_len=24,
        chunk_size=4, preemption_mode="swap"))
    seed_rid = engine.add_request(system.copy(), 2)  # indexes the system pages
    engine.run()
    inj_free = engine.metrics.snapshot()
    w = engine.add_request(warm_whale, 4)
    engine.step()  # hit mapped, first tail chunk resident
    assert engine.status(w) == "prefilling"
    victim = engine._requests[w]
    engine.scheduler.preempt(victim)  # swap out mid-prefill (the slot's
    # engine arrays were never activated — nothing to clear)
    outs = engine.run()
    np.testing.assert_array_equal(ref, outs[w])
    snap = engine.metrics.snapshot()
    hits = snap["serving_prefix_hits"] - inj_free["serving_prefix_hits"]
    saved = (snap["serving_prefix_tokens_saved"]
             - inj_free["serving_prefix_tokens_saved"])
    assert hits == 1, "the swap-interrupted attempt's hit must count"
    assert saved == 16
    tr = engine.trace(w)
    assert tr.count("swap_in") == 1
    # prefill_end reports only the tokens this attempt actually computed
    assert tr.first("prefill_end").arg("tokens") == 8


# --------------------------------------------------- certification + obs
def test_sync_free_certification_unchanged_with_chunking_and_slo():
    # the acceptance pin: intermediate chunks never fetch their sampled
    # token, so the SyncTally formula is BYTE-IDENTICAL to the unchunked
    # engine's — one fetch per decode step + one per COMPLETED prefill —
    # with chunking and the controller both ON
    model = _toy_model()
    engine = ServingEngine(model, ServingConfig(
        max_batch=3, num_pages=32, page_size=4, max_prompt_len=24,
        chunk_size=8, slo=SLOConfig(ttft_p99_s=100.0, tpot_p99_s=100.0,
                                    window_steps=4)), clock=TickClock())
    for p, b in zip(_prompts(8, (20, 4, 13)), (5, 6, 4)):
        engine.add_request(p, b)
    pre = engine.metrics.snapshot()
    with SyncTally() as tally:
        engine.run()
    snap = engine.metrics.snapshot()
    fetches = int(snap["serving_decode_steps"] - pre["serving_decode_steps"]
                  + snap["serving_prefills_total"]
                  - pre["serving_prefills_total"])
    assert tally.count == fetches, (tally.count, fetches,
                                    tally.events[:20])
    assert snap["serving_prefill_chunks_total"] > \
        snap["serving_prefills_total"], "chunking really was on"
    assert snap["serving_analysis_retraces_total"] == 0


def test_chunk_gauges_pre_seeded_and_chunk_limit_published():
    model = _toy_model()
    engine = _engine(model, chunk_size=0)  # chunking off
    snap = engine.metrics.snapshot()
    for k in ("prefill_chunks_total", "chunk_limit",
              "slo_throttles_total"):
        assert snap["serving_" + k] == 0, k
    engine2 = ServingEngine(model, ServingConfig(
        max_batch=3, num_pages=32, page_size=4, max_prompt_len=24,
        chunk_size=8, slo=SLOConfig(ttft_p99_s=10.0)))
    # the controller's initial limit is published at construction
    assert engine2.metrics.snapshot()["serving_chunk_limit"] == 3


def test_chunk_trace_events_and_chrome_export():
    model = _toy_model()
    engine = _engine(model, chunk_size=8)
    whale = _prompts(9, (20,))[0]
    rid = engine.add_request(whale, 4)
    engine.run()
    tr = engine.trace(rid)
    chunk_evs = [e for e in tr.events if e.name == "prefill_chunk"]
    assert [(e.arg("start"), e.arg("tokens")) for e in chunk_evs] == \
        [(0, 8), (8, 8), (16, 4)]
    assert [e.arg("final") for e in chunk_evs] == [False, False, True]
    assert chunk_evs[0].arg("bucket") == 8
    s = tr.summary()
    assert s["prefill_chunks"] == 3 and s["state"] == "finished"
    # TTFT anchoring unchanged: first_token only exists after the final
    # chunk, and prefill_time spans the whole chunked prefill
    assert tr.first("first_token").t >= chunk_evs[-1].t
    assert s["ttft"] is not None and s["prefill_time"] is not None
    # chrome export: chunk spans + instants on the request track, chunk
    # counts on the engine track
    doc = engine.export_chrome_trace()
    spans = [ev for ev in doc["traceEvents"]
             if ev["ph"] == "X" and ev["name"] == "prefill_chunk"]
    assert len(spans) == 3
    instants = [ev for ev in doc["traceEvents"]
                if ev["ph"] == "i" and ev["name"] == "prefill_chunk"]
    assert len(instants) == 3 and instants[0]["args"]["tokens"] == 8
    engine_steps = [ev for ev in doc["traceEvents"]
                    if ev.get("cat") == "engine" and ev["ph"] == "X"]
    assert sum(ev["args"]["chunks"] for ev in engine_steps) == 3


def test_timeline_records_chunks_and_phase_mix():
    model = _toy_model()
    engine = _engine(model, chunk_size=8, max_batch=1)
    engine.add_request(_prompts(10, (20,))[0], 3)
    engine.step()
    rec = engine.timeline.last
    # first step: one chunk advanced, nothing decoding yet
    assert rec.chunks == 1 and rec.prefills == 0 and rec.batch == 0
    assert rec.phase_mix() == "prefill"
    engine.step()
    engine.step()  # final chunk completes -> first token + decode
    rec = engine.timeline.last
    assert rec.prefills == 1 and rec.batch == 1
    assert rec.phase_mix() == "prefill+decode"


def test_chunked_debug_checks_audits_chunk_program_clean():
    # the chunk phase routes through the same _audit_step hook: under
    # debug_checks the chunk bucket's compiled program is hlo-audited at
    # its first trace, and the registered chunk-shaped step is clean
    from paddle_tpu.analysis import hlocheck

    model = _toy_model()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=32, page_size=4, max_prompt_len=24,
        chunk_size=8, debug_checks=True))
    rid = engine.add_request(_prompts(12, (20,))[0], 3)
    engine.run()
    assert set(engine.hlo_audits) == {"prefill[8]", "decode"}
    for name, rep in engine.hlo_audits.items():
        assert not rep.collectives and not rep.host_transfers, name
        assert rep.aliased_leaves == rep.donated_leaves and not rep.unaliased

    report = hlocheck.run_step("engine_prefill_chunk")
    assert not report.collectives and not report.host_transfers
