"""OpTest batch 4: index/scatter family, sort family, pad variants, reduce
tail, manipulation tail (VERDICT r4 ask #4 — reference test strategy
SURVEY §4.1, op_test.py protocol: eager + static paths vs numpy reference,
finite-difference grad checks where differentiable)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from optest_batch_util import make_mk


_mk = make_mk(globals())


_r = np.random.RandomState(7)


def _f32(*shape, lo=-1.0, hi=1.0):
    return (_r.rand(*shape) * (hi - lo) + lo).astype("float32")


# ------------------------------------------------------------ index family
_mk("TestGatherOp", paddle.gather,
    lambda: {"x": _f32(8, 4), "index": np.array([0, 3, 5], np.int64)},
    lambda x, index: x[index],
    grads=("x",))

_mk("TestGatherAxisOp", paddle.gather,
    lambda: {"x": _f32(4, 6), "index": np.array([1, 3], np.int64)},
    lambda x, index, axis: np.take(x, index, axis=1),
    attrs={"axis": 1}, grads=("x",))

_mk("TestGatherNdOp", paddle.gather_nd,
    lambda: {"x": _f32(4, 5, 6),
             "index": np.array([[0, 1], [2, 3]], np.int64)},
    lambda x, index: x[tuple(index.T)],
    grads=("x",))

_mk("TestScatterOp", paddle.scatter,
    lambda: {"x": _f32(6, 3), "index": np.array([1, 4], np.int64),
             "updates": _f32(2, 3)},
    lambda x, index, updates: _np_scatter(x, index, updates, overwrite=True),
    grads=("x", "updates"))


def _np_scatter(x, index, updates, overwrite=True):
    out = x.copy()
    if overwrite:
        out[index] = updates
    else:
        out[index] = 0
        np.add.at(out, index, updates)
    return out


_mk("TestScatterAddOp", paddle.scatter,
    lambda: {"x": _f32(6, 3), "index": np.array([2, 2, 0], np.int64),
             "updates": _f32(3, 3)},
    lambda x, index, updates, overwrite: _np_scatter(x, index, updates,
                                                     overwrite=overwrite),
    attrs={"overwrite": False}, grads=("x", "updates"))

_mk("TestScatterNdAddOp", paddle.scatter_nd_add,
    lambda: {"x": _f32(5, 4), "index": np.array([[1], [3], [1]], np.int64),
             "updates": _f32(3, 4)},
    lambda x, index, updates: _np_scatter_nd_add(x, index, updates),
    grads=("x", "updates"))


def _np_scatter_nd_add(x, index, updates):
    out = x.copy()
    np.add.at(out, tuple(index.T), updates)
    return out


_mk("TestIndexSelectOp", paddle.index_select,
    lambda: {"x": _f32(5, 6), "index": np.array([0, 2, 2], np.int64)},
    lambda x, index, axis: np.take(x, index, axis=axis),
    attrs={"axis": 1}, grads=("x",))

_mk("TestIndexSampleOp", paddle.index_sample,
    lambda: {"x": _f32(3, 8),
             "index": _r.randint(0, 8, (3, 4)).astype(np.int64)},
    lambda x, index: np.take_along_axis(x, index, axis=1),
    grads=("x",))

_mk("TestTakeAlongAxisOp", paddle.take_along_axis,
    lambda: {"arr": _f32(4, 5),
             "indices": _r.randint(0, 4, (2, 5)).astype(np.int64)},
    lambda arr, indices, axis: np.take_along_axis(arr, indices, axis=axis),
    attrs={"axis": 0}, grads=("arr",))

_mk("TestPutAlongAxisOp", paddle.put_along_axis,
    lambda: {"arr": _f32(4, 5),
             "indices": _r.randint(0, 4, (1, 5)).astype(np.int64),
             "values": _f32(1, 5)},
    lambda arr, indices, values, axis: _np_put_along(arr, indices, values,
                                                     axis),
    attrs={"axis": 0}, grads=("arr",))


def _np_put_along(arr, indices, values, axis):
    out = arr.copy()
    np.put_along_axis(out, indices, values, axis=axis)
    return out


_mk("TestRollOp", paddle.roll,
    lambda: {"x": _f32(4, 6)},
    lambda x, shifts, axis: np.roll(x, shifts, axis=axis),
    attrs={"shifts": 2, "axis": 1}, grads=("x",))

_mk("TestFlipOp", paddle.flip,
    lambda: {"x": _f32(3, 4, 2)},
    lambda x, axis: np.flip(x, axis=tuple(axis)),
    attrs={"axis": [0, 2]}, grads=("x",))

_mk("TestRepeatInterleaveOp", paddle.repeat_interleave,
    lambda: {"x": _f32(3, 4)},
    lambda x, repeats, axis: np.repeat(x, repeats, axis=axis),
    attrs={"repeats": 3, "axis": 1}, grads=("x",))


# ------------------------------------------------------------- sort family
_mk("TestSortOp", paddle.sort,
    # well-separated values: finite differences across a sort crossing
    # would compare against the wrong permutation
    lambda: {"x": _r.permutation(np.linspace(-1, 1, 35))
             .reshape(5, 7).astype("float32")},
    lambda x, axis: np.sort(x, axis=axis),
    attrs={"axis": 1}, grads=("x",))

_mk("TestSortDescendingOp", paddle.sort,
    lambda: {"x": _f32(6, 5)},
    lambda x, axis, descending: -np.sort(-x, axis=axis),
    attrs={"axis": 0, "descending": True})

_mk("TestArgsortOp", paddle.argsort,
    lambda: {"x": _f32(4, 9)},
    lambda x, axis: np.argsort(x, axis=axis, kind="stable"),
    attrs={"axis": 1})

_mk("TestArgmaxOp", paddle.argmax,
    lambda: {"x": _f32(5, 8)},
    lambda x, axis: np.argmax(x, axis=axis),
    attrs={"axis": 1})

_mk("TestArgminOp", paddle.argmin,
    lambda: {"x": _f32(5, 8)},
    lambda x, axis: np.argmin(x, axis=axis),
    attrs={"axis": 0})


def _np_topk(x, k, axis=-1):
    idx = np.argsort(-x, axis=axis, kind="stable")
    idx = np.take(idx, np.arange(k), axis=axis)
    return np.take_along_axis(x, idx, axis=axis), idx


_mk("TestTopkOp", paddle.topk,
    lambda: {"x": _f32(4, 10)},
    lambda x, k: _np_topk(x, k),
    attrs={"k": 3}, grads=("x",))

_mk("TestKthvalueOp", paddle.kthvalue,
    lambda: {"x": _f32(3, 7)},
    lambda x, k: (np.sort(x, axis=-1)[..., k - 1],
                  np.argsort(x, axis=-1, kind="stable")[..., k - 1]),
    attrs={"k": 2})

_mk("TestMedianOp", paddle.median,
    lambda: {"x": _f32(3, 5)},
    lambda x, axis: np.median(x, axis=axis),
    attrs={"axis": 1})


# -------------------------------------------------------------- pad family
_mk("TestPad2dConstantOp", F.pad,
    lambda: {"x": _f32(2, 3, 4, 5)},
    lambda x, pad, mode, value: np.pad(
        x, ((0, 0), (0, 0), (pad[2], pad[3]), (pad[0], pad[1])),
        constant_values=value),
    attrs={"pad": [1, 2, 1, 0], "mode": "constant", "value": 0.5},
    grads=("x",))

_mk("TestPad2dReflectOp", F.pad,
    lambda: {"x": _f32(1, 2, 5, 6)},
    lambda x, pad, mode: np.pad(
        x, ((0, 0), (0, 0), (pad[2], pad[3]), (pad[0], pad[1])),
        mode="reflect"),
    attrs={"pad": [2, 1, 1, 2], "mode": "reflect"}, grads=("x",))

_mk("TestPad2dReplicateOp", F.pad,
    lambda: {"x": _f32(1, 2, 4, 4)},
    lambda x, pad, mode: np.pad(
        x, ((0, 0), (0, 0), (pad[2], pad[3]), (pad[0], pad[1])),
        mode="edge"),
    attrs={"pad": [1, 1, 2, 0], "mode": "replicate"}, grads=("x",))

_mk("TestPad2dCircularOp", F.pad,
    lambda: {"x": _f32(1, 1, 4, 5)},
    lambda x, pad, mode: np.pad(
        x, ((0, 0), (0, 0), (pad[2], pad[3]), (pad[0], pad[1])),
        mode="wrap"),
    attrs={"pad": [1, 2, 1, 1], "mode": "circular"})

_mk("TestPad3dOp", F.pad,
    lambda: {"x": _f32(1, 2, 3, 4, 5)},
    lambda x, pad: np.pad(
        x, ((0, 0), (0, 0), (pad[4], pad[5]), (pad[2], pad[3]),
            (pad[0], pad[1]))),
    attrs={"pad": [1, 1, 0, 2, 1, 0]}, grads=("x",))

_mk("TestPad1dOp", F.pad,
    lambda: {"x": _f32(2, 3, 6)},
    lambda x, pad, data_format: np.pad(
        x, ((0, 0), (0, 0), (pad[0], pad[1]))),
    attrs={"pad": [2, 1], "data_format": "NCL"}, grads=("x",))


# ------------------------------------------------------------- reduce tail
_mk("TestReduceMaxOp", paddle.max,
    lambda: {"x": _f32(4, 6)},
    lambda x, axis: np.max(x, axis=axis), attrs={"axis": 1})

_mk("TestReduceMinOp", paddle.min,
    lambda: {"x": _f32(4, 6)},
    lambda x, axis, keepdim: np.min(x, axis=axis, keepdims=True),
    attrs={"axis": 0, "keepdim": True})

_mk("TestReduceProdOp", paddle.prod,
    lambda: {"x": _f32(3, 5, lo=0.5, hi=1.5)},
    lambda x, axis: np.prod(x, axis=axis),
    attrs={"axis": 1}, grads=("x",))

_mk("TestReduceAllOp", paddle.all,
    lambda: {"x": _r.rand(4, 5) > 0.3},
    lambda x, axis: np.all(x, axis=axis), attrs={"axis": 1})

_mk("TestReduceAnyOp", paddle.any,
    lambda: {"x": _r.rand(4, 5) > 0.7},
    lambda x, axis: np.any(x, axis=axis), attrs={"axis": 0})

_mk("TestAmaxOp", paddle.amax,
    lambda: {"x": _f32(3, 6)},
    lambda x, axis: np.max(x, axis=axis), attrs={"axis": -1})

_mk("TestAminOp", paddle.amin,
    lambda: {"x": _f32(3, 6)},
    lambda x, axis: np.min(x, axis=axis), attrs={"axis": -1})

_mk("TestNansumOp", paddle.nansum,
    lambda: {"x": np.where(_r.rand(4, 5) > 0.8, np.nan,
                           _r.rand(4, 5)).astype("float32")},
    lambda x, axis: np.nansum(x, axis=axis), attrs={"axis": 1})

_mk("TestLogsumexpAxesOp", paddle.logsumexp,
    lambda: {"x": _f32(3, 4, 5)},
    lambda x, axis: np.log(np.sum(np.exp(x), axis=tuple(axis))),
    attrs={"axis": [0, 2]}, grads=("x",))


# ------------------------------------------------------- search/count family
_mk("TestSearchsortedOp", paddle.searchsorted,
    lambda: {"sorted_sequence": np.sort(_f32(10)),
             "values": _f32(6)},
    lambda sorted_sequence, values: np.searchsorted(sorted_sequence, values))

_mk("TestBincountOp", paddle.bincount,
    lambda: {"x": _r.randint(0, 6, (20,)).astype(np.int64)},
    lambda x, minlength: np.bincount(x, minlength=minlength),
    attrs={"minlength": 8}, check_static=False)  # host-side op (dynamic len)

_mk("TestModeOp", paddle.mode,
    lambda: {"x": _r.randint(0, 3, (4, 9)).astype(np.float32)},
    lambda x: _np_mode(x))  # largest tied value, last occurrence


def _np_mode(x):
    vals = np.zeros(x.shape[0], x.dtype)
    idx = np.zeros(x.shape[0], np.int64)
    for i, row in enumerate(x):
        u, c = np.unique(row, return_counts=True)
        # paddle mode: the most frequent value; tie -> the LARGEST value,
        # index -> its LAST occurrence
        best = u[c == c.max()].max()
        vals[i] = best
        idx[i] = np.where(row == best)[0][-1]
    return vals, idx


_mk("TestDiffOp", paddle.diff,
    lambda: {"x": _f32(4, 7)},
    lambda x, axis: np.diff(x, axis=axis),
    attrs={"axis": 1}, grads=("x",))

_mk("TestRot90Op", paddle.rot90,
    lambda: {"x": _f32(3, 4, 2)},
    lambda x, k, axes: np.rot90(x, k=k, axes=tuple(axes)),
    attrs={"k": 1, "axes": [0, 1]}, grads=("x",))

_mk("TestTensordotOp", paddle.tensordot,
    lambda: {"x": _f32(3, 4, 5), "y": _f32(4, 5, 6)},
    lambda x, y, axes: np.tensordot(x, y, axes=axes),
    attrs={"axes": 2}, grads=("x", "y"))

_mk("TestErfinvOp", paddle.erfinv,
    lambda: {"x": _f32(12, lo=-0.9, hi=0.9)},
    lambda x: _np_erfinv(x), rtol=1e-4, grads=("x",))


def _np_erfinv(x):
    from scipy.special import erfinv as _e

    return _e(x).astype(np.float32)


_mk("TestExpm1Op", paddle.expm1,
    lambda: {"x": _f32(10)},
    lambda x: np.expm1(x), grads=("x",))

_mk("TestRsqrtOp", paddle.rsqrt,
    lambda: {"x": _f32(10, lo=0.5, hi=2.0)},
    lambda x: 1.0 / np.sqrt(x), grads=("x",))

_mk("TestTruncOp", paddle.trunc,
    lambda: {"x": _f32(10, lo=-3, hi=3)},
    lambda x: np.trunc(x))

_mk("TestFracOp", paddle.frac,
    lambda: {"x": _f32(10, lo=-3, hi=3)},
    lambda x: x - np.trunc(x))

_mk("TestLogitOp", paddle.logit,
    lambda: {"x": _f32(10, lo=0.1, hi=0.9)},
    lambda x: np.log(x / (1 - x)), grads=("x",), rtol=1e-4)

_mk("TestHeavisideOp", paddle.heaviside,
    lambda: {"x": _f32(10, lo=-2, hi=2), "y": _f32(10)},
    lambda x, y: np.heaviside(x, y))

# x/y separated by >> fd-delta: a min/max crossing inside the finite
# difference makes the numeric gradient meaningless
_mk("TestFmaxOp", paddle.fmax,
    lambda: {"x": _f32(8), "y": _f32(8) + np.tile([0.5, -0.5], 4)
             .astype("float32")},
    lambda x, y: np.fmax(x, y), grads=("x", "y"))

_mk("TestFminOp", paddle.fmin,
    lambda: {"x": _f32(8), "y": _f32(8) + np.tile([0.7, -0.7], 4)
             .astype("float32")},
    lambda x, y: np.fmin(x, y), grads=("x", "y"))

_mk("TestMoveaxisOp", paddle.moveaxis,
    lambda: {"x": _f32(2, 3, 4)},
    lambda x, source, destination: np.moveaxis(x, source, destination),
    attrs={"source": 0, "destination": 2}, grads=("x",))

_mk("TestRad2degOp", paddle.rad2deg,
    lambda: {"x": _f32(8, lo=-3.14, hi=3.14)},
    lambda x: np.rad2deg(x).astype(np.float32))

_mk("TestDeg2radOp", paddle.deg2rad,
    lambda: {"x": _f32(8, lo=-180, hi=180)},
    lambda x: np.deg2rad(x).astype(np.float32))


# -------------------------------------------------------- manipulation tail
_mk("TestDiagOp", paddle.diag,
    lambda: {"x": _f32(5)},
    lambda x: np.diag(x), grads=("x",))

_mk("TestDiagonalOp", paddle.diagonal,
    lambda: {"x": _f32(4, 5)},
    lambda x, offset: np.diagonal(x, offset=offset),
    attrs={"offset": 1}, grads=("x",))

_mk("TestTraceOp", paddle.trace,
    lambda: {"x": _f32(4, 4)},
    lambda x: np.trace(x), grads=("x",))

_mk("TestKronOp", paddle.kron,
    lambda: {"x": _f32(2, 3), "y": _f32(3, 2)},
    lambda x, y: np.kron(x, y), grads=("x", "y"))

_mk("TestBroadcastToOp", paddle.broadcast_to,
    lambda: {"x": _f32(1, 4)},
    lambda x, shape: np.broadcast_to(x, shape),
    attrs={"shape": [3, 4]}, grads=("x",))

_mk("TestUnbindOp", paddle.unbind,
    lambda: {"x": _f32(3, 4)},
    lambda x, axis: [x[i] for i in range(3)],
    attrs={"axis": 0}, grads=("x",))

_mk("TestChunkOp", paddle.chunk,
    lambda: {"x": _f32(6, 4)},
    lambda x, chunks, axis: np.split(x, 3, axis=0),
    attrs={"chunks": 3, "axis": 0}, grads=("x",))

_mk("TestMaskedSelectStaticShape", None, lambda: {}, None)
del TestMaskedSelectStaticShape  # dynamic-shape op: covered in test_tensor

_mk("TestLerpOp", paddle.lerp,
    lambda: {"x": _f32(4, 3), "y": _f32(4, 3), "weight": _f32(4, 3,
                                                              lo=0, hi=1)},
    lambda x, y, weight: x + weight * (y - x),
    grads=("x", "y", "weight"))

_mk("TestAddmmOp", paddle.addmm,
    lambda: {"input": _f32(3, 4), "x": _f32(3, 5), "y": _f32(5, 4)},
    lambda input, x, y, beta, alpha: beta * input + alpha * (x @ y),
    attrs={"beta": 0.5, "alpha": 2.0}, grads=("input", "x", "y"))

_mk("TestOuterOp", paddle.outer,
    lambda: {"x": _f32(4), "y": _f32(6)},
    lambda x, y: np.outer(x, y), grads=("x", "y"))

_mk("TestCrossOp", paddle.cross,
    lambda: {"x": _f32(5, 3), "y": _f32(5, 3)},
    lambda x, y, axis: np.cross(x, y, axis=axis),
    attrs={"axis": 1}, grads=("x", "y"))

_mk("TestDotOp", paddle.dot,
    lambda: {"x": _f32(4, 7), "y": _f32(4, 7)},
    lambda x, y: np.sum(x * y, axis=-1), grads=("x", "y"))

_mk("TestBmmOp", paddle.bmm,
    lambda: {"x": _f32(3, 4, 5), "y": _f32(3, 5, 2)},
    lambda x, y: np.matmul(x, y), grads=("x", "y"))


_mk("TestModeIntDtypeOp", paddle.mode,
    # review finding: int input must keep its dtype (no -inf promotion)
    lambda: {"x": _r.randint(0, 4, (3, 8)).astype(np.int64)},
    lambda x: _np_mode(x))
