"""Top-level module parity: compat, sysconfig, callbacks, hub, reader,
dataset, cost_model, _C_ops (reference: python/paddle/{compat,sysconfig,
callbacks,hub}.py, reader/decorator.py, dataset/, cost_model/, _C_ops.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


# ---------- compat ----------

def test_compat_to_text_and_bytes_nested():
    assert paddle.compat.to_text(b"abc") == "abc"
    assert paddle.compat.to_text([b"a", b"b"]) == ["a", "b"]
    assert paddle.compat.to_text({b"k": b"v"}) == {"k": "v"}
    s = {b"x", b"y"}
    out = paddle.compat.to_text(s, inplace=True)
    assert out is s and s == {"x", "y"}
    assert paddle.compat.to_bytes("abc") == b"abc"
    assert paddle.compat.to_bytes(["a", "b"]) == [b"a", b"b"]


def test_compat_round_half_away_from_zero():
    assert paddle.compat.round(0.5) == 1.0
    assert paddle.compat.round(-0.5) == -1.0
    assert paddle.compat.round(2.675, 2) == 2.68
    assert paddle.compat.round(0.0) == 0.0
    assert paddle.compat.floor_division(7, 2) == 3
    assert paddle.compat.get_exception_message(ValueError("boom")) == "boom"


# ---------- sysconfig ----------

def test_sysconfig_paths():
    inc = paddle.sysconfig.get_include()
    assert os.path.isdir(inc) and any(
        f.endswith(".cc") for f in os.listdir(inc)
    )
    lib = paddle.sysconfig.get_lib()
    assert os.path.isdir(lib)


# ---------- callbacks / hub ----------

def test_callbacks_reexports():
    for name in ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
                 "LRScheduler", "EarlyStopping", "ReduceLROnPlateau"]:
        assert hasattr(paddle.callbacks, name)


def test_hub_local_roundtrip(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "dependencies = ['numpy']\n"
        "def tiny_model(scale=2):\n"
        "    'build a tiny model'\n"
        "    return {'scale': scale}\n"
    )
    names = paddle.hub.list(str(tmp_path), source="local")
    assert "tiny_model" in names
    assert "tiny" in paddle.hub.help(str(tmp_path), "tiny_model", source="local")
    out = paddle.hub.load(str(tmp_path), "tiny_model", source="local", scale=5)
    assert out == {"scale": 5}


def test_hub_network_sources_gated(tmp_path):
    with pytest.raises(RuntimeError, match="network"):
        paddle.hub.load("owner/repo:main", "m", source="github")
    with pytest.raises(ValueError):
        paddle.hub.list("x", source="ftp")


# ---------- reader ----------

def _ten():
    def r():
        for i in range(10):
            yield i

    return r


def test_reader_basic_decorators():
    assert list(paddle.reader.cache(_ten())()) == list(range(10))
    assert list(paddle.reader.firstn(_ten(), 3)()) == [0, 1, 2]
    assert sorted(paddle.reader.shuffle(_ten(), 4)()) == list(range(10))
    assert list(paddle.reader.chain(_ten(), _ten())()) == list(range(10)) * 2
    assert list(paddle.reader.map_readers(lambda a, b: a + b, _ten(), _ten())()) \
        == [2 * i for i in range(10)]
    assert list(paddle.reader.buffered(_ten(), 2)()) == list(range(10))


def test_reader_compose_alignment():
    composed = paddle.reader.compose(_ten(), _ten())
    assert list(composed()) == [(i, i) for i in range(10)]

    def five():
        for i in range(5):
            yield i

    with pytest.raises(paddle.reader.ComposeNotAligned):
        list(paddle.reader.compose(_ten(), five)())
    # check_alignment=False truncates to the shortest reader
    out = list(paddle.reader.compose(_ten(), five, check_alignment=False)())
    assert len(out) == 5


def test_reader_xmap_ordered_and_unordered():
    mapped = paddle.reader.xmap_readers(lambda x: x * 2, _ten(), 4, 8, order=True)
    assert list(mapped()) == [2 * i for i in range(10)]
    mapped = paddle.reader.xmap_readers(lambda x: x * 2, _ten(), 4, 8)
    assert sorted(mapped()) == [2 * i for i in range(10)]


@pytest.mark.slow
def test_reader_multiprocess():
    out = sorted(paddle.reader.multiprocess_reader(
        [_ten(), _ten()], use_pipe=False)())
    assert out == sorted(list(range(10)) * 2)


# ---------- dataset ----------

def test_dataset_mnist_reader_protocol():
    r = paddle.dataset.mnist.train()
    img, label = next(iter(r()))
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert isinstance(label, int)


def test_dataset_uci_and_cifar_and_imdb():
    feat, price = next(iter(paddle.dataset.uci_housing.train()()))
    assert feat.shape == (13,) and price.shape == (1,)
    img, label = next(iter(paddle.dataset.cifar.train10()()))
    assert img.shape == (3072,) and 0 <= label < 10
    doc, sentiment = next(iter(paddle.dataset.imdb.train(
        paddle.dataset.imdb.word_dict())()))
    assert isinstance(doc, list) and sentiment in (0, 1)


def test_dataset_imikolov_ngram_and_seq():
    w = paddle.dataset.imikolov.build_dict()
    gram = next(iter(paddle.dataset.imikolov.train(w, 4)()))
    assert len(gram) == 4
    src, trg = next(iter(paddle.dataset.imikolov.train(
        w, 4, paddle.dataset.imikolov.DataType.SEQ)()))
    assert src[1:] == trg[:-1]


def test_dataset_wmt_and_movielens_and_batch():
    src, tin, tout = next(iter(paddle.dataset.wmt14.train(1000)()))
    assert tin[1:] == tout[:-1]
    item = next(iter(paddle.dataset.movielens.train()()))
    assert len(item) == 8 and paddle.dataset.movielens.max_user_id() == 6040
    # reader protocol composes with paddle.batch
    batched = paddle.batch(paddle.dataset.mnist.train(), batch_size=4)
    first = next(iter(batched()))
    assert len(first) == 4


def test_dataset_download_gated(tmp_path):
    with pytest.raises(RuntimeError, match="egress"):
        paddle.dataset.common.download("http://x/y.tar", "mod", None)
    p = os.path.join(paddle.dataset.common.DATA_HOME, "mod2")
    os.makedirs(p, exist_ok=True)
    fn = os.path.join(p, "y.tar")
    with open(fn, "wb") as f:
        f.write(b"data")
    try:
        assert paddle.dataset.common.download("http://x/y.tar", "mod2",
                                              paddle.dataset.common.md5file(fn)) == fn
    finally:
        os.remove(fn)


# ---------- cost_model ----------

def test_cost_model_static_table_and_estimate():
    cm = paddle.cost_model.CostModel()
    data = cm.static_cost_data()
    assert len(data) >= 15
    t = cm.get_static_op_time("matmul")
    assert t["op_time"] > 0
    t_bwd = cm.get_static_op_time("softmax", forward=False)
    assert t_bwd["op_time"] > 0
    est = paddle.cost_model.CostModel.estimate_time_s(1e12, 1e9)
    assert est > 0


def test_cost_model_profile_measure():
    cm = paddle.cost_model.CostModel()
    startup, main = cm.build_program()
    cost = cm.profile_measure(startup, main)
    paddle.disable_static()
    assert cost["wall_time_s"] > 0
    assert cost.get("flops", 0) > 0  # XLA cost analysis reached


# ---------- _C_ops ----------

def test_c_ops_legacy_attr_convention():
    x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
    out = paddle._C_ops.matmul_v2(x, y, "trans_x", False, "trans_y", False)
    np.testing.assert_allclose(out.numpy(), x.numpy() @ y.numpy(), rtol=1e-5)
    out_t = paddle._C_ops.matmul_v2(x, y, "trans_x", True, "trans_y", True)
    np.testing.assert_allclose(out_t.numpy(), x.numpy().T @ y.numpy().T,
                               rtol=1e-5)
    s = paddle._C_ops.scale(x, "scale", 2.0, "bias", 1.0)
    np.testing.assert_allclose(s.numpy(), x.numpy() * 2 + 1, rtol=1e-5)
    r, _ = paddle._C_ops.reshape2(x, "shape", [8, 4])
    assert tuple(r.shape) == (8, 4)
    sm = paddle._C_ops.softmax(x, "axis", -1)
    np.testing.assert_allclose(sm.numpy().sum(-1), np.ones(4), rtol=1e-5)


def test_c_ops_final_state_and_missing():
    x = paddle.to_tensor(np.random.rand(3, 3).astype(np.float32))
    out = paddle._C_ops.final_state_relu(x)
    assert out.numpy().min() >= 0
    with pytest.raises(AttributeError, match="functional"):
        paddle._C_ops.definitely_not_an_op(x)
