"""Sparse tensor + SelectedRows tests (VERDICT r3 item 6).

Reference analogs: python/paddle/sparse/ ops, phi/core/selected_rows.h, the
embedding is_sparse=True -> SelectedRows W@GRAD path, and the sgd/adam
SelectedRows kernels (lazy row updates).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, sparse
from paddle_tpu.core.selected_rows import SelectedRows


def _coo(dense):
    idx = np.argwhere(dense != 0)
    vals = dense[dense != 0]
    return sparse.sparse_coo_tensor(idx.T, vals, dense.shape)


def test_coo_tensor_is_lazy():
    """Construction must NOT densify (old ctor called .todense())."""
    dense = np.zeros((1000, 1000), np.float32)
    dense[3, 7] = 2.0
    dense[500, 1] = -1.0
    t = _coo(dense)
    from jax.experimental.sparse import BCOO

    assert isinstance(t._value, BCOO), "constructor densified the COO tensor"
    assert t.nnz() == 2
    assert t._value.data.nbytes + t._value.indices.nbytes < 100  # no 4MB dense
    np.testing.assert_allclose(t.to_dense().numpy(), dense)


@pytest.mark.slow
def test_coo_matmul_and_ops():
    rng = np.random.RandomState(0)
    dense = np.where(rng.rand(16, 8) > 0.7, rng.randn(16, 8), 0).astype(np.float32)
    y = rng.randn(8, 4).astype(np.float32)
    t = _coo(dense)
    np.testing.assert_allclose(sparse.matmul(t, y).numpy(), dense @ y, rtol=1e-5)
    s = sparse.add(t, t)
    np.testing.assert_allclose(s.to_dense().numpy(), 2 * dense, rtol=1e-6)
    r = sparse.relu(_coo(-dense))
    np.testing.assert_allclose(r.to_dense().numpy(), np.maximum(-dense, 0), rtol=1e-6)
    m = sparse.multiply(t, t)
    np.testing.assert_allclose(m.to_dense().numpy(), dense * dense, rtol=1e-5)


def test_csr_roundtrip():
    dense = np.array([[1, 0, 2], [0, 0, 3]], np.float32)
    crows, cols, vals = [0, 2, 3], [0, 2, 2], [1.0, 2.0, 3.0]
    t = sparse.sparse_csr_tensor(crows, cols, vals, [2, 3])
    np.testing.assert_allclose(t.to_dense().numpy(), dense)


def test_selected_rows_merge_and_dense():
    sr = SelectedRows([2, 0, 2], np.array([[1., 1.], [2., 2.], [3., 3.]]), height=4)
    m = sr.merged()
    assert m.rows.shape[0] == 2
    dense = np.asarray(sr.to_dense())
    np.testing.assert_allclose(dense[2], [4., 4.])
    np.testing.assert_allclose(dense[0], [2., 2.])
    np.testing.assert_allclose(dense[1], [0., 0.])
    # SR + SR concat; SR + dense -> dense
    both = sr + sr
    assert isinstance(both, SelectedRows) and both.rows.shape[0] == 6
    summed = sr + np.ones((4, 2), np.float32)
    np.testing.assert_allclose(np.asarray(summed)[2], [5., 5.])


def test_sparse_embedding_grad_never_dense():
    """The VERDICT memory assertion: with sparse=True, no [vocab, hidden]
    dense gradient materializes — W@GRAD is a SelectedRows over the looked-up
    rows only."""
    vocab, hidden = 50_000, 64
    emb = nn.Embedding(vocab, hidden, sparse=True)
    ids = paddle.to_tensor(np.array([[5, 9, 5], [100, 9, 7]], np.int64))
    out = emb(ids)
    loss = out.sum()
    loss.backward()
    g = emb.weight.grad._value
    assert isinstance(g, SelectedRows), type(g)
    assert g.value.shape == (6, hidden)
    # the sparse grad is ~4 orders of magnitude smaller than the dense one
    assert g.nbytes < vocab * hidden * 4 / 1000
    np.testing.assert_array_equal(np.sort(np.asarray(g.rows)),
                                  [5, 5, 7, 9, 9, 100])


@pytest.mark.parametrize("opt_cls", ["SGD", "Adam"])
def test_sparse_embedding_training_matches_dense(opt_cls):
    """Lazy sparse update == dense update on the same data (small vocab)."""
    def run(sparse_flag):
        paddle.seed(123)
        emb = nn.Embedding(50, 8, sparse=sparse_flag)
        fc = nn.Linear(8, 4)
        opt = getattr(paddle.optimizer, opt_cls)(
            0.1, parameters=list(emb.parameters()) + list(fc.parameters()))
        ids = paddle.to_tensor(np.array([[1, 2], [3, 1]], np.int64))
        lab = paddle.to_tensor(np.array([0, 3], np.int64))
        losses = []
        for _ in range(5):
            logits = fc(emb(ids).mean(axis=1))
            loss = nn.functional.cross_entropy(logits, lab)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return losses, emb.weight.numpy()

    dense_losses, dense_w = run(False)
    sparse_losses, sparse_w = run(True)
    assert dense_losses == pytest.approx(sparse_losses, rel=1e-5)
    np.testing.assert_allclose(dense_w, sparse_w, rtol=1e-5, atol=1e-6)


def test_sparse_embedding_static_build_falls_back_dense():
    """Under static program build the sparse path must NOT fire — the op is
    recorded densely (regression: the gate used to crash on Variable avals)."""
    from paddle_tpu import static

    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 3], "int64")
            emb = nn.Embedding(10, 4, sparse=True)
            out = emb(x)
        exe = static.Executor()
        res = exe.run(main, feed={"x": np.zeros((2, 3), np.int64)},
                      fetch_list=[out])
        assert res[0].shape == (2, 3, 4)
    finally:
        paddle.disable_static()


def test_sparse_embedding_nonleaf_weight_falls_back_dense():
    """An op-derived (non-leaf) weight cannot carry a SelectedRows ct through
    an upstream vjp — the gate must fall back to the dense path."""
    emb = nn.Embedding(12, 4, sparse=True)
    scaled = emb.weight * 2.0  # non-leaf
    out = nn.functional.embedding(
        paddle.to_tensor(np.array([1, 3], np.int64)), scaled, sparse=True)
    out.sum().backward()
    g = emb.weight.grad._value
    assert not isinstance(g, SelectedRows)  # dense chain-rule grad
    dense = np.asarray(g)
    np.testing.assert_allclose(dense[1], 2.0)


def test_sparse_grad_accumulates_across_backwards():
    emb = nn.Embedding(20, 4, sparse=True)
    ids = paddle.to_tensor(np.array([1, 2], np.int64))
    emb(ids).sum().backward()
    emb(ids).sum().backward()
    g = emb.weight.grad._value
    assert isinstance(g, SelectedRows)
    dense = np.asarray(g.to_dense())
    np.testing.assert_allclose(dense[1], 2.0)
    np.testing.assert_allclose(dense[3], 0.0)
