"""API-parity batch tests: ops added to close the reference __all__ audit
(root / nn / nn.functional / sparse). Numeric ground truth is torch (CPU)
where available — the same oracle the reference tests use for new kernels."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import functional as F

torch = pytest.importorskip("torch")
import torch.nn.functional as TF  # noqa: E402


def _t(x):
    return paddle.to_tensor(x)


def test_root_surface_complete():
    import ast

    tree = ast.parse(open("/root/reference/python/paddle/__init__.py").read())
    names = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if getattr(tgt, "id", None) == "__all__":
                    names = [e.value for e in node.value.elts
                             if isinstance(e, ast.Constant)]
    missing = [n for n in names if not hasattr(paddle, n)]
    assert missing == [], missing


def test_math_parity_ops():
    rng = np.random.RandomState(0)
    x = rng.rand(3, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(paddle.trace(_t(x))._value),
                               np.trace(x), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(paddle.lgamma(_t(x))._value),
                               torch.lgamma(torch.tensor(x)).numpy(), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(paddle.digamma(_t(x))._value),
                               torch.digamma(torch.tensor(x)).numpy(), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(paddle.erfinv(_t(x * 0.9))._value),
                               torch.erfinv(torch.tensor(x * 0.9)).numpy(),
                               rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(paddle.quantile(_t(x), 0.3, axis=1)._value),
        np.quantile(x, 0.3, axis=1), rtol=1e-5)
    a = rng.randint(1, 50, (10,))
    b = rng.randint(1, 50, (10,))
    np.testing.assert_array_equal(np.asarray(paddle.gcd(_t(a), _t(b))._value),
                                  np.gcd(a, b))
    m = rng.rand(3, 5).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(paddle.addmm(_t(x @ np.zeros((4, 5), np.float32)), _t(x),
                                _t(rng.rand(4, 5).astype(np.float32)),
                                beta=0.5, alpha=2.0)._value).shape, (3, 5))
    del m


def test_renorm_caps_subtensor_norms():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 6).astype(np.float32) * 10
    out = np.asarray(paddle.renorm(_t(x), p=2.0, axis=0, max_norm=1.0)._value)
    norms = np.linalg.norm(out, axis=1)
    assert (norms <= 1.0 + 1e-4).all()


def test_manipulation_parity_ops():
    rng = np.random.RandomState(2)
    x = rng.randn(3, 4, 5).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(paddle.diagonal(_t(x), offset=1, axis1=1, axis2=2)._value),
        np.diagonal(x, offset=1, axis1=1, axis2=2))
    outs = paddle.broadcast_tensors([_t(np.ones((1, 4))), _t(np.ones((3, 1)))])
    assert [list(o.shape) for o in outs] == [[3, 4], [3, 4]]
    u, inv, cnt = paddle.unique_consecutive(
        _t(np.array([1, 1, 2, 2, 2, 3, 1])), return_inverse=True,
        return_counts=True)
    np.testing.assert_array_equal(np.asarray(u._value), [1, 2, 3, 1])
    np.testing.assert_array_equal(np.asarray(cnt._value), [2, 3, 1, 1])
    # shard_index maps global ids into the shard or ignore_value
    out = paddle.shard_index(_t(np.array([1, 5, 9])), index_num=12, nshards=3,
                             shard_id=1)
    np.testing.assert_array_equal(np.asarray(out._value), [-1, 1, -1])
    # scatter_nd accumulates duplicates
    out = paddle.scatter_nd(_t(np.array([[1], [1], [3]])),
                            _t(np.array([1.0, 2.0, 4.0], np.float32)), [5])
    np.testing.assert_allclose(np.asarray(out._value), [0, 3, 0, 4, 0])


def test_pool3d_and_unpool_match_torch():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 8, 8, 8).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(F.max_pool3d(_t(x), 2)._value),
        TF.max_pool3d(torch.tensor(x), 2).numpy())
    np.testing.assert_allclose(
        np.asarray(F.avg_pool3d(_t(x), 2)._value),
        TF.avg_pool3d(torch.tensor(x), 2).numpy(), rtol=1e-5, atol=1e-6)
    x2 = rng.randn(2, 3, 8, 8).astype(np.float32)
    out, idx = F.max_pool2d(_t(x2), 2, return_mask=True)
    t_out, t_idx = TF.max_pool2d(torch.tensor(x2), 2, return_indices=True)
    np.testing.assert_allclose(np.asarray(out._value), t_out.numpy())
    np.testing.assert_array_equal(np.asarray(idx._value), t_idx.numpy())
    un = F.max_unpool2d(out, idx, 2)
    np.testing.assert_allclose(np.asarray(un._value),
                               TF.max_unpool2d(t_out, t_idx, 2).numpy())


def test_conv_transpose_1d_3d_match_torch():
    rng = np.random.RandomState(4)
    x = rng.randn(2, 4, 9).astype(np.float32)
    w = rng.randn(4, 3, 5).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(F.conv1d_transpose(_t(x), _t(w), stride=2, padding=1)._value),
        torch.conv_transpose1d(torch.tensor(x), torch.tensor(w), stride=2,
                               padding=1).numpy(), rtol=2e-4, atol=1e-4)
    x3 = rng.randn(1, 4, 5, 6, 7).astype(np.float32)
    w3 = rng.randn(4, 2, 3, 3, 3).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(F.conv3d_transpose(_t(x3), _t(w3), stride=2, padding=1)._value),
        torch.conv_transpose3d(torch.tensor(x3), torch.tensor(w3), stride=2,
                               padding=1).numpy(), rtol=2e-4, atol=1e-4)


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; --durations top mover
def test_ctc_loss_matches_torch_fwd_and_grad():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(5)
    T, B, C, L = 12, 3, 6, 4
    logits = rng.randn(T, B, C).astype(np.float32)
    labels = rng.randint(1, C, (B, L)).astype(np.int32)
    in_len = np.array([12, 10, 8], np.int32)
    lab_len = np.array([4, 3, 2], np.int32)
    mine = F.ctc_loss(_t(logits), _t(labels), _t(in_len), _t(lab_len),
                      blank=0, reduction="none")
    ref = TF.ctc_loss(torch.log_softmax(torch.tensor(logits), -1),
                      torch.tensor(labels.astype(np.int64)),
                      torch.tensor(in_len.astype(np.int64)),
                      torch.tensor(lab_len.astype(np.int64)),
                      blank=0, reduction="none")
    np.testing.assert_allclose(np.asarray(mine._value), ref.numpy(), rtol=1e-4)

    g = jax.grad(lambda lg: F.ctc_loss(
        Tensor(lg), _t(labels), _t(in_len), _t(lab_len),
        reduction="mean")._value)(jnp.asarray(logits))
    tt = torch.tensor(logits, requires_grad=True)
    TF.ctc_loss(torch.log_softmax(tt, -1),
                torch.tensor(labels.astype(np.int64)),
                torch.tensor(in_len.astype(np.int64)),
                torch.tensor(lab_len.astype(np.int64)),
                blank=0, reduction="mean").backward()
    np.testing.assert_allclose(np.asarray(g), tt.grad.numpy(), rtol=1e-3,
                               atol=1e-5)


def test_affine_grid_and_shuffles_match_torch():
    rng = np.random.RandomState(6)
    theta = rng.randn(2, 2, 3).astype(np.float32)
    for ac in (True, False):
        np.testing.assert_allclose(
            np.asarray(F.affine_grid(_t(theta), [2, 3, 5, 7],
                                     align_corners=ac)._value),
            TF.affine_grid(torch.tensor(theta), [2, 3, 5, 7],
                           align_corners=ac).numpy(), rtol=1e-4, atol=1e-5)
    x = rng.randn(1, 4, 6, 6).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(F.pixel_unshuffle(_t(x), 2)._value),
        TF.pixel_unshuffle(torch.tensor(x), 2).numpy())
    np.testing.assert_allclose(
        np.asarray(F.channel_shuffle(_t(x), 2)._value),
        TF.channel_shuffle(torch.tensor(x), 2).numpy())
    cols = rng.randn(2, 3 * 4, 9).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(F.fold(_t(cols), (4, 4), (2, 2))._value),
        TF.fold(torch.tensor(cols), (4, 4), (2, 2)).numpy(), rtol=1e-5)


def test_small_losses():
    rng = np.random.RandomState(7)
    p = rng.rand(4, 1).astype(np.float32)
    y = (rng.rand(4, 1) > 0.5).astype(np.float32)
    ll = np.asarray(F.log_loss(_t(p), _t(y))._value)
    assert ll.shape == (4, 1) and (ll >= 0).all()

    z = rng.randn(5, 3).astype(np.float32)
    t = (rng.rand(5, 3) > 0.5).astype(np.float32)
    mine = float(np.asarray(F.sigmoid_focal_loss(_t(z), _t(t),
                                                 reduction="sum")._value))
    # torch's sigmoid_focal_loss lives in torchvision; verify against a
    # hand-rolled reference instead
    pt = 1 / (1 + np.exp(-z))
    ce = -(t * np.log(pt) + (1 - t) * np.log(1 - pt))
    ptt = pt * t + (1 - pt) * (1 - t)
    at = 0.25 * t + 0.75 * (1 - t)
    ref = (at * (1 - ptt) ** 2 * ce).sum()
    np.testing.assert_allclose(mine, ref, rtol=1e-4)

    x = rng.randn(4, 8).astype(np.float32)
    lab = rng.randint(0, 6, (4,)).astype(np.int64)
    hs = nn.HSigmoidLoss(8, 6)
    out = hs(_t(x), _t(lab))
    assert list(out.shape) == [4, 1]
    assert np.isfinite(np.asarray(out._value)).all()

    d = nn.PairwiseDistance(p=2.0)
    a, b = rng.randn(3, 5).astype(np.float32), rng.randn(3, 5).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(d(_t(a), _t(b))._value),
        torch.pairwise_distance(torch.tensor(a), torch.tensor(b)).numpy(),
        rtol=1e-4)


def test_margin_cross_entropy_reduces_to_ce_without_margin():
    rng = np.random.RandomState(8)
    cos = np.clip(rng.randn(4, 10).astype(np.float32) * 0.3, -1, 1)
    y = rng.randint(0, 10, (4,)).astype(np.int64)
    loss = F.margin_cross_entropy(_t(cos), _t(y), margin1=1.0, margin2=0.0,
                                  margin3=0.0, scale=1.0, reduction="mean")
    ref = TF.cross_entropy(torch.tensor(cos), torch.tensor(y)).numpy()
    np.testing.assert_allclose(float(np.asarray(loss._value)), ref, rtol=1e-5)


def test_class_center_sample():
    y = _t(np.array([3, 7, 3, 1], np.int64))
    remapped, sampled = F.class_center_sample(y, num_classes=20, num_samples=8)
    s = np.asarray(sampled._value)
    r = np.asarray(remapped._value)
    assert len(s) == 8 and set([1, 3, 7]) <= set(s.tolist())
    np.testing.assert_array_equal(s[r], [3, 7, 3, 1])


def test_rnn_family():
    paddle.seed(0)
    cell = nn.SimpleRNNCell(4, 6)
    rnn = nn.RNN(cell)
    x = _t(np.random.RandomState(9).randn(2, 5, 4).astype(np.float32))
    y, h = rnn(x)
    assert list(y.shape) == [2, 5, 6] and list(h.shape) == [2, 6]
    bi = nn.BiRNN(nn.GRUCell(4, 6), nn.GRUCell(4, 6))
    yb, (hf, hb) = bi(x)
    assert list(yb.shape) == [2, 5, 12]
    # masked outputs past sequence_length are zero
    y2, _ = rnn(x, sequence_length=_t(np.array([3, 5])))
    assert np.allclose(np.asarray(y2._value)[0, 3:], 0)
    assert not np.allclose(np.asarray(y2._value)[1, 4], 0)


def test_layers_wrap_functionals():
    rng = np.random.RandomState(10)
    x3 = _t(rng.randn(1, 2, 4, 4, 4).astype(np.float32))
    assert list(nn.MaxPool3D(2)(x3).shape) == [1, 2, 2, 2, 2]
    assert list(nn.AvgPool3D(2)(x3).shape) == [1, 2, 2, 2, 2]
    assert list(nn.AdaptiveAvgPool3D(2)(x3).shape) == [1, 2, 2, 2, 2]
    assert list(nn.AdaptiveMaxPool3D(2)(x3).shape) == [1, 2, 2, 2, 2]
    x1 = _t(rng.randn(1, 2, 9).astype(np.float32))
    assert list(nn.AdaptiveMaxPool1D(3)(x1).shape) == [1, 2, 3]
    assert list(nn.Conv1DTranspose(2, 3, 3)(x1).shape)[1] == 3
    assert list(nn.Conv3DTranspose(2, 3, 3)(x3).shape)[1] == 3
    x = _t(rng.randn(1, 4, 6, 6).astype(np.float32))
    assert list(nn.ChannelShuffle(2)(x).shape) == [1, 4, 6, 6]
    assert list(nn.PixelUnshuffle(2)(x).shape) == [1, 16, 3, 3]
    assert list(nn.ZeroPad2D([1, 2, 3, 4])(x).shape) == [1, 4, 13, 9]
    assert list(nn.Softmax2D()(x).shape) == [1, 4, 6, 6]
    out = nn.ThresholdedReLU(0.5)(x)
    v = np.asarray(out._value)
    assert ((v == 0) | (v > 0.5)).all()


@pytest.mark.slow
def test_sparse_layers():
    import paddle_tpu.sparse as sp

    d = np.zeros((1, 4, 4, 4, 2), np.float32)
    d[0, 1, 1, 1] = [1.0, -2.0]
    d[0, 2, 3, 0] = [3.0, 4.0]
    idx = np.stack(np.nonzero(d))
    x = sp.sparse_coo_tensor(idx, d[np.nonzero(d)], d.shape)
    y = sp.SubmConv3D(2, 5, 3)(x)
    assert y.shape == [1, 4, 4, 4, 5]
    # submanifold: support restricted to input sites (2 sites x 5 channels max)
    assert y.nnz() <= 10
    z = sp.MaxPool3D(2)(x)
    assert z.shape == [1, 2, 2, 2, 2]
    w = sp.BatchNorm(2)(x)
    assert w.nnz() == 4
    assert np.isfinite(np.asarray(w.values().numpy())).all()


def test_flops_counts_conv_and_linear():
    net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                        nn.Flatten(), nn.Linear(8 * 32 * 32, 10))
    n = paddle.flops(net, [1, 3, 32, 32])
    # reference convention: MACs without doubling for conv/linear
    # (dynamic_flops.py count_convNd/count_linear), elementwise for ReLU
    expected = 8 * 32 * 32 * 27 + 8 * 32 * 32 + 8192 * 10
    assert n == expected, (n, expected)


def test_conv2d_transpose_output_padding_matches_torch():
    rng = np.random.RandomState(11)
    x = rng.randn(2, 4, 7, 7).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    for s, p, op in [(2, 1, 0), (2, 1, 1), (3, 0, 2)]:
        mine = np.asarray(F.conv2d_transpose(
            _t(x), _t(w), stride=s, padding=p, output_padding=op)._value)
        ref = torch.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                     stride=s, padding=p,
                                     output_padding=op).numpy()
        assert mine.shape == ref.shape
        np.testing.assert_allclose(mine, ref, rtol=2e-4, atol=1e-4)


def test_adaptive_max_pool_return_mask_matches_torch():
    rng = np.random.RandomState(12)
    xa = rng.randn(2, 3, 10).astype(np.float32)
    o, i = F.adaptive_max_pool1d(_t(xa), 4, return_mask=True)
    to, ti = TF.adaptive_max_pool1d(torch.tensor(xa), 4, return_indices=True)
    np.testing.assert_allclose(np.asarray(o._value), to.numpy())
    np.testing.assert_array_equal(np.asarray(i._value), ti.numpy())
    x3 = rng.randn(1, 2, 6, 6, 6).astype(np.float32)
    o3, i3 = F.adaptive_max_pool3d(_t(x3), 3, return_mask=True)
    to3, ti3 = TF.adaptive_max_pool3d(torch.tensor(x3), 3, return_indices=True)
    np.testing.assert_allclose(np.asarray(o3._value), to3.numpy())
    np.testing.assert_array_equal(np.asarray(i3._value), ti3.numpy())


def test_reverse_rnn_masks_padded_steps():
    """Backward RNN over a padded batch must equal a per-row reverse over
    each row's valid prefix (pad steps must not pollute state)."""
    paddle.seed(13)
    cell = nn.SimpleRNNCell(3, 5)
    r = nn.RNN(cell, is_reverse=True)
    rng = np.random.RandomState(13)
    xx = rng.randn(2, 4, 3).astype(np.float32)
    y, st = r(_t(xx), sequence_length=_t(np.array([2, 4])))
    y_ref, st_ref = r(_t(xx[0:1, :2]))
    np.testing.assert_allclose(np.asarray(y._value)[0, :2],
                               np.asarray(y_ref._value)[0], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(st._value)[0],
                               np.asarray(st_ref._value)[0], rtol=1e-5)
    assert np.allclose(np.asarray(y._value)[0, 2:], 0)


def test_pool_mask_grad_flows_through_values():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(14)
    x = rng.randn(1, 1, 4, 4).astype(np.float32)

    def loss(a):
        out, idx = F.max_pool2d(Tensor(a), 2, return_mask=True)
        return jnp.sum(out._value ** 2)

    g = jax.grad(loss)(jnp.asarray(x))
    # gradient lands exactly on the 4 window maxima
    assert int((np.asarray(g) != 0).sum()) == 4


def test_inplace_ops_mutate():
    t = _t(np.array([0.5], np.float32))
    r = paddle.tanh_(t)
    assert r is t
    np.testing.assert_allclose(t.numpy(), np.tanh(0.5), rtol=1e-6)


def test_inplace_ops_have_correct_gradients():
    """Regression: in-place ops must graft the op's autograd node, not just
    rebind the buffer (which silently made them identity in backward)."""
    import paddle_tpu.tensor_ops.math as M

    x = _t(np.array([1., 4.], np.float32))
    x.stop_gradient = False
    paddle.sqrt_(x)
    paddle.exp_(x)
    x.sum().backward()
    ref = np.exp(np.sqrt([1., 4.])) * 0.5 / np.sqrt([1., 4.])
    np.testing.assert_allclose(np.asarray(x.grad._value), ref, rtol=1e-5)

    a = _t(np.array([1., 2.], np.float32))
    a.stop_gradient = False
    b = _t(np.array([3., 4.], np.float32))
    b.stop_gradient = False
    c = a * 2
    M.add_(c, b)
    c.sum().backward()
    np.testing.assert_allclose(np.asarray(a.grad._value), [2., 2.])
    np.testing.assert_allclose(np.asarray(b.grad._value), [1., 1.])

    w = _t(np.array([0.5], np.float32))
    w.stop_gradient = False
    h = w * 3
    paddle.tanh_(h)
    (h * 5).backward()
    ref = 5 * (1 - np.tanh(1.5) ** 2) * 3
    np.testing.assert_allclose(np.asarray(w.grad._value), [ref], rtol=1e-5)


def test_lu_unpack_batched_and_flags():
    rng = np.random.RandomState(15)
    x = _t(rng.randn(3, 4, 4).astype(np.float32) + 4 * np.eye(4, dtype=np.float32))
    lu_d, piv = paddle.lu(x)
    P, L, U = paddle.linalg.lu_unpack(lu_d, piv)
    rec = np.asarray(P._value) @ np.asarray(L._value) @ np.asarray(U._value)
    np.testing.assert_allclose(rec, np.asarray(x._value), rtol=1e-4, atol=1e-5)
    P2, _, _ = paddle.linalg.lu_unpack(lu_d, piv, unpack_pivots=False)
    assert P2 is None
    P3, L3, _ = paddle.linalg.lu_unpack(lu_d, piv, unpack_ludata=False)
    assert L3 is None and P3 is not None
