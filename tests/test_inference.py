"""Inference/deployment stack tests.

Reference test analog: python/paddle/fluid/tests/unittests/test_inference_api.py
+ save/load_inference_model tests — save a trained static program, reload it in
a fresh "process" (new objects), check outputs match.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, nn, static


def _build_program():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        paddle.enable_static()
        try:
            x = static.data("x", [4, 8], "float32")
            lin = nn.Linear(8, 3)
            y = lin(x)
            out = paddle.nn.functional.softmax(y)
        finally:
            paddle.disable_static()
    return main, x, out


def test_save_load_inference_model(tmp_path):
    main, x, out = _build_program()
    exe = static.Executor()
    prefix = str(tmp_path / "model" / "m")
    static.save_inference_model(prefix, [x], [out], exe, program=main)

    xv = np.random.RandomState(0).randn(4, 8).astype("float32")
    with static.program_guard(main):
        ref = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]

    prog, feed_names, fetch_names = static.load_inference_model(prefix, exe)
    assert feed_names == ["x"]
    got = exe.run(prog, feed={"x": xv})[0]
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-5)


def test_predictor_zero_copy(tmp_path):
    main, x, out = _build_program()
    exe = static.Executor()
    prefix = str(tmp_path / "m")
    static.save_inference_model(prefix, [x], [out], exe, program=main)

    config = inference.Config(prefix)
    predictor = inference.create_predictor(config)
    names = predictor.get_input_names()
    assert names == ["x"]
    xv = np.random.RandomState(1).randn(4, 8).astype("float32")
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(xv)
    predictor.run()
    oh = predictor.get_output_handle(predictor.get_output_names()[0])
    got = oh.copy_to_cpu()
    assert got.shape == (4, 3)
    np.testing.assert_allclose(got.sum(axis=-1), np.ones(4), rtol=1e-5)

    # batch API
    outs = predictor.run([xv])
    np.testing.assert_allclose(outs[0], got, rtol=1e-6)


def test_predictor_dynamic_batch(tmp_path):
    """Batch sizes other than the exported one are served by pad/chunk — the
    TPU static-shape policy for dynamic serving batch."""
    main, x, out = _build_program()  # exported at batch 4
    exe = static.Executor()
    prefix = str(tmp_path / "m")
    static.save_inference_model(prefix, [x], [out], exe, program=main)

    predictor = inference.create_predictor(inference.Config(prefix))
    rng = np.random.RandomState(2)
    for b in (1, 3, 4, 7, 10):  # smaller, exact, and multi-chunk batches
        xv = rng.randn(b, 8).astype("float32")
        outs = predictor.run([xv])
        assert outs[0].shape == (b, 3), (b, outs[0].shape)
        np.testing.assert_allclose(outs[0].sum(-1), np.ones(b), rtol=1e-5)
        ref = predictor.run([np.pad(xv, [(0, (-b) % 4), (0, 0)])])[0][:b] \
            if b % 4 else predictor.run([xv])[0]
        np.testing.assert_allclose(outs[0], ref, rtol=1e-5)


def test_predictor_pool_shares_model(tmp_path):
    main, x, out = _build_program()
    exe = static.Executor()
    prefix = str(tmp_path / "m")
    static.save_inference_model(prefix, [x], [out], exe, program=main)

    pool = inference.PredictorPool(inference.Config(prefix), size=3)
    assert len(pool) == 3
    xv = np.random.RandomState(3).randn(4, 8).astype("float32")
    outs = [pool.retrieve(i).run([xv])[0] for i in range(3)]
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)
    np.testing.assert_allclose(outs[0], outs[2], rtol=1e-6)
    # handles are independent (zero-copy state not shared across pool members)
    pool.retrieve(0).get_input_handle("x").copy_from_cpu(xv * 2)
    np.testing.assert_allclose(
        np.asarray(pool.retrieve(1).get_input_handle("x")._value), xv,
        rtol=1e-6)


def test_jit_save_load_translated_layer(tmp_path):
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(6, 4)

        def forward(self, x):
            return paddle.tanh(self.fc(x))

    net = Net()
    net.eval()
    xv = paddle.to_tensor(np.random.RandomState(2).randn(2, 6).astype("float32"))
    ref = net(xv).numpy()

    path = str(tmp_path / "net")
    paddle.jit.save(net, path, input_spec=[static.InputSpec([2, 6], "float32")])
    loaded = paddle.jit.load(path)
    got = loaded(xv).numpy()
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-5)

    with pytest.raises(RuntimeError):
        loaded.train()


def test_predictor_opens_jit_artifact(tmp_path):
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x):
            return self.fc(x)

    net = Net()
    net.eval()
    path = str(tmp_path / "jitnet")
    paddle.jit.save(net, path, input_spec=[static.InputSpec([3, 4], "float32")])
    pred = inference.create_predictor(inference.Config(path))
    xv = np.random.RandomState(4).randn(3, 4).astype("float32")
    outs = pred.run([xv])
    ref = net(paddle.to_tensor(xv)).numpy()
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)
