"""Auto-parallel planning: cluster model, rank mapper, partition cost model,
and the planner decision test the round-4 verdict asked for — two model
shapes (wide-FFN vs long-seq) where the chosen splits DIFFER and the choice
beats the naive all-dp spec in MEASURED step time on the 8-device mesh.

Reference pattern: auto_parallel/cluster.py + mapper.py + cost_model.py and
the planner unittests (test_auto_parallel_cluster.py / test_auto_parallel_
mapper.py) — restated as decision quality instead of attribute plumbing.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.auto_parallel.cluster import (
    Cluster, cpu_test_cluster)
from paddle_tpu.distributed.auto_parallel.cost_model import (
    ModelDesc, estimate_partition, partition_comm_volumes)
from paddle_tpu.distributed.auto_parallel.mapper import map_mesh
from paddle_tpu.distributed.auto_parallel.planner import plan_parallel


def test_cluster_json_roundtrip_and_links():
    c = Cluster(accelerator_type="v5p", n_hosts=4, chips_per_host=4,
                dcn_bandwidth=50e9)
    c2 = Cluster.from_json(c.to_json())
    assert c2 == c
    assert c2.n_chips == 16
    # ranks 0..3 share host 0 (ICI); rank 4 is host 1 (DCN)
    assert c2.same_host(0, 3) and not c2.same_host(3, 4)
    assert c2.bandwidth(0, 3) == c2.device("ici_bandwidth")
    assert c2.bandwidth(0, 4) < c2.bandwidth(0, 3)
    # a 4-wide group strided 1 fits a host -> ici; strided 4 spans hosts
    assert c2.axis_medium(4, 1) == "ici"
    assert c2.axis_medium(4, 4) == "dcn"
    # reference-schema JSON (machines/devices) parses
    ref_json = ('{"machines": [{"hostname": "a", "devices": '
                '[{"type": "V5P"}, {"type": "V5P"}]}]}')
    c3 = Cluster.from_json(ref_json)
    assert c3.n_hosts == 1 and c3.chips_per_host == 2


def test_mapper_places_heaviest_axis_on_ici():
    """mapper.py analog: the axis moving the most bytes must vary fastest
    (contiguous ranks -> one host's ICI); the lightest spans hosts."""
    c = Cluster(accelerator_type="v5p", n_hosts=2, chips_per_host=4)
    ids, placement = map_mesh(
        c, {"dp": 2, "mp": 4},
        comm_bytes={"dp": 1e6, "mp": 1e9})
    assert ids.shape == (2, 4)
    # mp groups = rows of ids -> must be host-contiguous runs
    for row in ids:
        assert c.host_of(row[0]) == c.host_of(row[-1])
        assert list(row) == list(range(row[0], row[0] + 4))
    assert placement == {"dp": "dcn", "mp": "ici"}
    # volumes flipped -> dp rides ICI instead
    ids2, placement2 = map_mesh(
        c, {"dp": 2, "mp": 4}, comm_bytes={"dp": 1e9, "mp": 1e3})
    assert placement2["dp"] == "ici"


def test_comm_volume_model_directions():
    """partition_comm_volumes: dp cost scales with params, mp/sp with
    activations — the fact the planner's decisions rest on."""
    wide = ModelDesc(n_params=50_000_000, layers=2, hidden=1024, heads=8,
                     seq=32, batch=8)
    lng = ModelDesc(n_params=1_000_000, layers=2, hidden=128, heads=8,
                    seq=4096, batch=2)
    vw = partition_comm_volumes(wide, dp=8, sp=1, sh=1, mp=1)
    assert vw["dp"]["bytes"] == wide.param_bytes
    vw_mp = partition_comm_volumes(wide, dp=2, sp=1, sh=1, mp=4)
    # wide-FFN: per-step mp activation traffic << dp grad traffic
    assert (vw_mp["mp"]["bytes"] * vw_mp["mp"]["count"]
            < 0.1 * vw["dp"]["bytes"])
    vl_mp = partition_comm_volumes(lng, dp=2, sp=1, sh=1, mp=4)
    # long-seq: mp's activation all-reduces dwarf the tiny grad sync
    assert (vl_mp["mp"]["bytes"] * vl_mp["mp"]["count"]
            > vl_mp["dp"]["bytes"])


def test_planner_decisions_differ_by_model_shape():
    wide = ModelDesc(n_params=8_400_000, layers=2, hidden=512, heads=8,
                     seq=32, batch=8)
    lng = ModelDesc(n_params=1_600_000, layers=2, hidden=128, heads=8,
                    seq=2048, batch=2)
    pw = plan_parallel(8, wide, cpu_test_cluster(8))
    pl = plan_parallel(8, lng, cpu_test_cluster(8))
    # wide-FFN: tensor parallel, no sequence parallel
    assert pw.mp > 1 and pw.sp == 1
    # long-seq small-batch: batch caps dp at 2; sequence parallelism engaged
    assert pl.sp > 1 and pl.dp <= 2
    assert pw.axis_sizes != pl.axis_sizes
    # both out-score the naive all-dp candidate of the same search
    for plan, model in ((pw, wide), (pl, lng)):
        naive = [c for c in plan.candidates
                 if c["sp"] == c["sharding"] == c["mp"] == 1]
        if naive:  # all-dp exists only when batch % n_devices == 0
            assert plan.time < naive[0]["time"]
    # the breakdown names every axis's collective (the inspectable 'why')
    assert set(pw.comm_volumes) == {"dp", "sharding", "mp", "sp"}


def test_planner_always_returns_valid_plan():
    """Property sweep: over random model shapes and device counts, every
    plan factors n_devices exactly, satisfies the divisibility contract,
    and carries a full breakdown — or raises ValueError (never crashes)."""
    rng = np.random.RandomState(0)
    for _ in range(60):
        n = int(2 ** rng.randint(0, 7))
        model = ModelDesc(
            n_params=int(10 ** rng.uniform(4, 9)),
            layers=int(rng.randint(1, 48)),
            hidden=int(2 ** rng.randint(4, 13)),
            heads=int(2 ** rng.randint(0, 6)),
            seq=int(2 ** rng.randint(0, 14)),
            batch=int(2 ** rng.randint(0, 10)))
        try:
            plan = plan_parallel(n, model, cpu_test_cluster(max(n, 1)))
        except ValueError:
            continue  # indivisible shapes refuse loudly — acceptable
        assert plan.dp * plan.sp * plan.sharding * plan.mp == n, \
            (n, plan.axis_sizes)
        assert model.batch % (plan.dp * plan.sharding) == 0
        assert model.seq % plan.sp == 0 and model.hidden % plan.mp == 0
        if model.heads:
            assert model.heads % plan.sp == 0
            assert model.heads % plan.mp == 0
        assert plan.time > 0 and plan.per_chip_bytes > 0
        assert set(plan.comm_volumes) == {"dp", "sharding", "mp", "sp"}


def test_planner_memory_forces_sharding_at_scale():
    """6.7B on v5p-64: all-dp replication (~116 GB/chip) cannot fit 95 GB
    HBM; the plan must split params and fit the budget."""
    big = ModelDesc(n_params=6_700_000_000, layers=32, hidden=4096, heads=32,
                    seq=2048, batch=64)
    cl = Cluster(accelerator_type="v5p", n_hosts=16, chips_per_host=4)
    naive = estimate_partition(big, 64, 1, 1, 1, cl.to_cluster_spec())
    assert naive["per_chip_bytes"] > cl.device("hbm_bytes")
    plan = plan_parallel(64, big, cl)
    assert plan.mp * plan.sharding > 1
    assert plan.per_chip_bytes <= cl.device("hbm_bytes") * 0.6


class _WideFFN(nn.Layer):
    """One megatron column->row FFN block + small head: params >> acts."""

    def __init__(self, d=512, ffn=4096, classes=16):
        super().__init__()
        from paddle_tpu.distributed.fleet import (ColumnParallelLinear,
                                                  RowParallelLinear)

        self.col = ColumnParallelLinear(d, ffn, gather_output=False)
        self.row = RowParallelLinear(ffn, d, input_is_parallel=True)
        self.head = nn.Linear(d, classes)

    def forward(self, x):
        return self.head(self.row(nn.functional.relu(self.col(x))))


def _median_step_time(step_fn, state, xs, ys, lr, warmup=2, reps=5):
    import jax

    key = jax.random.key(0)
    for i in range(warmup):
        loss, state = step_fn(state, jax.random.fold_in(key, i), lr, xs, ys)
    float(np.asarray(loss))
    times = []
    for i in range(reps):
        t0 = time.perf_counter()
        loss, state = step_fn(state, jax.random.fold_in(key, i), lr, xs, ys)
        float(np.asarray(loss))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


@pytest.mark.slow
def test_planner_choice_beats_all_dp_measured_wide_ffn():
    """The verdict's bar: the planner picks a non-trivial split (mp-heavy)
    for the wide-FFN shape and that choice BEATS all-dp in measured step
    time on the 8-device mesh — grad all-reduce of 17 MB params vs tiny
    activation all-reduces."""
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.distributed.fleet.hybrid_train import build_hybrid_step

    wide = ModelDesc(n_params=4_300_000, layers=1, hidden=512, heads=0,
                     seq=1, batch=8)
    plan = plan_parallel(8, wide, cpu_test_cluster(8))
    assert plan.mp > 1, f"planner chose {plan.axis_sizes}; expected mp>1"

    def build(mesh_shape):
        paddle.seed(0)
        model = _WideFFN()
        opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(mesh_shape),
                    ("dp", "sharding", "mp"))
        loss_fn = lambda out, y: nn.functional.cross_entropy(out, y)  # noqa: E731
        init_fn, step_fn, shard_batch = build_hybrid_step(
            model, opt, loss_fn, mesh)
        return init_fn(), step_fn, shard_batch

    rng = np.random.RandomState(0)
    xs = rng.rand(8, 512).astype(np.float32)
    ys = rng.randint(0, 16, (8,)).astype(np.int64)

    state_p, step_p, shard_p = build((plan.dp, plan.sharding, plan.mp))
    t_plan = _median_step_time(
        step_p, state_p, shard_p([xs]), shard_p([ys]), 1e-3)
    state_d, step_d, shard_d = build((8, 1, 1))
    t_dp = _median_step_time(
        step_d, state_d, shard_d([xs]), shard_d([ys]), 1e-3)
    assert t_plan < t_dp, (
        f"planner {plan.axis_sizes}: {t_plan*1e3:.1f}ms vs all-dp "
        f"{t_dp*1e3:.1f}ms — choice did not win")


@pytest.mark.slow
def test_planner_choice_beats_naive_measured_long_seq():
    """Long-seq small-batch: all-dp cannot use 8 chips (batch 2); the
    planner engages sp. Measured: its best dp x sp layout beats the naive
    max-dp spec (dp=2, 4x the per-chip sequence work)."""
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.distributed.sequence_parallel import (
        build_context_parallel_step)
    from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

    lng = ModelDesc(n_params=1_600_000, layers=2, hidden=128, heads=8,
                    seq=2048, batch=2)
    plan = plan_parallel(8, lng, cpu_test_cluster(8))
    assert plan.sp > 1
    # best dp x sp-only candidate (the context-parallel runner's axes)
    dpsp = min((c for c in plan.candidates
                if c["sharding"] == 1 and c["mp"] == 1),
               key=lambda c: c["t_eff"])
    assert dpsp["sp"] > 1

    cfg = GPTConfig(vocab_size=128, hidden_size=128, num_layers=2,
                    num_heads=8, max_seq_len=2048, dropout=0.0,
                    tie_word_embeddings=False)

    def build(dp, sp):
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        devs = np.array(jax.devices()[: dp * sp]).reshape(dp, sp)
        mesh = Mesh(devs, ("dp", "sp"))
        loss_fn = lambda logits, labels: nn.functional.cross_entropy(  # noqa: E731
            logits.reshape([-1, 128]), labels.reshape([-1]))
        init_fn, step_fn, shard_batch = build_context_parallel_step(
            model, opt, loss_fn, mesh)
        return init_fn(), step_fn, shard_batch

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (2, 2048)).astype(np.int64)
    labels = rng.randint(0, 128, (2, 2048)).astype(np.int64)

    state_p, step_p, shard_p = build(dpsp["dp"], dpsp["sp"])
    t_plan = _median_step_time(
        step_p, state_p, shard_p([ids]), shard_p([labels]), 0.1, reps=3)
    state_n, step_n, shard_n = build(2, 1)
    t_naive = _median_step_time(
        step_n, state_n, shard_n([ids]), shard_n([labels]), 0.1, reps=3)
    assert t_plan < t_naive, (
        f"planner dp{dpsp['dp']}xsp{dpsp['sp']}: {t_plan*1e3:.1f}ms vs "
        f"naive dp2: {t_naive*1e3:.1f}ms")
