"""Real .pdmodel/.pdiparams inference-model interop.

The fixture writer below encodes ProgramDesc bytes strictly per the
published framework.proto field numbers (ProgramDesc.blocks=1;
BlockDesc idx=1/parent=2/vars=3/ops=4; OpDesc inputs=1/outputs=2/type=3/
attrs=4; VarDesc name=1/type=2/persistable=3) — the same layout real
`paddle.static.save_inference_model` emits — so the loader is tested
against the FORMAT, not against its own serializer.
"""
import io
import struct

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.io import save_binary_tensor
from paddle_tpu.inference.pdmodel import PdModelProgram, parse_program_desc


# ------------------------------------------------- minimal proto ENCODER
def _varint(n: int) -> bytes:
    n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | 0x80 if n else b)
        if not n:
            return bytes(out)


def _tag(field, wire):
    return _varint(field << 3 | wire)


def _len_field(field, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _vint_field(field, v: int) -> bytes:
    return _tag(field, 0) + _varint(v)


def _f32_field(field, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _attr(name, atype, value) -> bytes:
    out = _len_field(1, name.encode()) + _vint_field(2, atype)
    if atype == 0:  # INT
        out += _vint_field(3, value & ((1 << 64) - 1))
    elif atype == 1:  # FLOAT
        out += _f32_field(4, value)
    elif atype == 2:  # STRING
        out += _len_field(5, value.encode())
    elif atype == 3:  # INTS (unpacked, like the C++ writer)
        for v in value:
            out += _vint_field(6, v & ((1 << 64) - 1))
    elif atype == 6:  # BOOLEAN
        out += _vint_field(10, int(value))
    return out


def _op_var(param, args) -> bytes:
    out = _len_field(1, param.encode())
    for a in args:
        out += _len_field(2, a.encode())
    return out


def _op(op_type, inputs, outputs, attrs=()) -> bytes:
    out = b""
    for p, a in inputs:
        out += _len_field(1, _op_var(p, a))
    for p, a in outputs:
        out += _len_field(2, _op_var(p, a))
    out += _len_field(3, op_type.encode())
    for name, atype, val in attrs:
        out += _len_field(4, _attr(name, atype, val))
    return out


def _tensor_desc(dtype_code, dims) -> bytes:
    out = _vint_field(1, dtype_code)
    for d in dims:
        out += _vint_field(2, d & ((1 << 64) - 1))
    return out


def _var(name, dims, persistable, dtype_code=5, vtype=7) -> bytes:
    lod = _len_field(1, _tensor_desc(dtype_code, dims))
    vt = _vint_field(1, vtype) + _len_field(3, lod)
    out = _len_field(1, name.encode()) + _len_field(2, vt)
    if persistable:
        out += _vint_field(3, 1)
    return out


def _block(var_blobs, op_blobs) -> bytes:
    out = _vint_field(1, 0) + _vint_field(2, 0)
    for v in var_blobs:
        out += _len_field(3, v)
    for o in op_blobs:
        out += _len_field(4, o)
    return out


def _program(block_blob) -> bytes:
    return _len_field(1, block_blob)


def _mlp_fixture(tmp_path, seed=0):
    rng = np.random.RandomState(seed)
    w1 = rng.randn(8, 16).astype(np.float32) * 0.3
    b1 = rng.randn(16).astype(np.float32) * 0.1
    w2 = rng.randn(16, 4).astype(np.float32) * 0.3
    b2 = rng.randn(4).astype(np.float32) * 0.1

    vars_ = [
        _var("feed", [], False, vtype=9),
        _var("fetch", [], False, vtype=10),
        _var("x", [-1, 8], False),
        _var("fc1.w", list(w1.shape), True),
        _var("fc1.b", list(b1.shape), True),
        _var("fc2.w", list(w2.shape), True),
        _var("fc2.b", list(b2.shape), True),
        _var("h0", [-1, 16], False), _var("h1", [-1, 16], False),
        _var("h2", [-1, 16], False), _var("h3", [-1, 4], False),
        _var("h4", [-1, 4], False), _var("out", [-1, 4], False),
    ]
    ops = [
        _op("feed", [("X", ["feed"])], [("Out", ["x"])], [("col", 0, 0)]),
        _op("mul", [("X", ["x"]), ("Y", ["fc1.w"])], [("Out", ["h0"])],
            [("x_num_col_dims", 0, 1), ("y_num_col_dims", 0, 1)]),
        _op("elementwise_add", [("X", ["h0"]), ("Y", ["fc1.b"])],
            [("Out", ["h1"])], [("axis", 0, (1 << 64) - 1)]),  # axis=-1
        _op("relu", [("X", ["h1"])], [("Out", ["h2"])]),
        _op("mul", [("X", ["h2"]), ("Y", ["fc2.w"])], [("Out", ["h3"])]),
        _op("elementwise_add", [("X", ["h3"]), ("Y", ["fc2.b"])],
            [("Out", ["h4"])]),
        _op("softmax", [("X", ["h4"])], [("Out", ["out"])],
            [("axis", 0, (1 << 64) - 1)]),
        _op("fetch", [("X", ["out"])], [("Out", ["fetch"])], [("col", 0, 0)]),
    ]
    prog = _program(_block(vars_, ops))
    prefix = str(tmp_path / "mlp")
    with open(prefix + ".pdmodel", "wb") as f:
        f.write(prog)
    # .pdiparams: persistable vars' LoDTensor streams, SORTED name order
    params = {"fc1.w": w1, "fc1.b": b1, "fc2.w": w2, "fc2.b": b2}
    with open(prefix + ".pdiparams", "wb") as f:
        for name in sorted(params):
            save_binary_tensor(f, params[name])
    return prefix, params


def test_parse_program_desc_structure(tmp_path):
    prefix, _ = _mlp_fixture(tmp_path)
    with open(prefix + ".pdmodel", "rb") as f:
        desc = parse_program_desc(f.read())
    block = desc["blocks"][0]
    assert [op["type"] for op in block["ops"]] == [
        "feed", "mul", "elementwise_add", "relu", "mul", "elementwise_add",
        "softmax", "fetch"]
    assert block["vars"]["fc1.w"]["persistable"]
    assert block["vars"]["fc1.w"]["type"]["shape"] == [8, 16]
    assert block["vars"]["x"]["type"]["shape"] == [-1, 8]
    mul0 = block["ops"][1]
    assert mul0["inputs"]["X"] == ["x"] and mul0["inputs"]["Y"] == ["fc1.w"]
    assert mul0["attrs"]["x_num_col_dims"] == 1


def test_pdmodel_mlp_runs_and_matches_numpy(tmp_path):
    from paddle_tpu.inference.pdmodel import load_pdmodel

    prefix, p = _mlp_fixture(tmp_path)
    prog = load_pdmodel(prefix)
    assert prog.feed_names == ["x"] and prog.fetch_names == ["out"]
    x = np.random.RandomState(1).rand(5, 8).astype(np.float32)
    (out,) = prog.run({"x": x})
    h = np.maximum(x @ p["fc1.w"] + p["fc1.b"], 0.0)
    logits = h @ p["fc2.w"] + p["fc2.b"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    ref = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)


def test_pdmodel_inference_passes(tmp_path):
    """Analysis passes on loaded programs (reference analysis_predictor's
    pass-then-run contract): inference-identity dropout and scale(1,0)/
    assign fold to aliases, unread ops prune, numerics identical with
    ir_optim on/off."""
    from paddle_tpu.inference.pdmodel import load_pdmodel

    rng = np.random.RandomState(7)
    w = rng.randn(8, 4).astype(np.float32) * 0.3

    vars_ = [
        _var("feed", [], False, vtype=9),
        _var("fetch", [], False, vtype=10),
        _var("x", [-1, 8], False),
        _var("w", list(w.shape), True),
        _var("d0", [-1, 8], False), _var("m0", [-1, 8], False),
        _var("h0", [-1, 4], False), _var("s0", [-1, 4], False),
        _var("a0", [-1, 4], False), _var("dead", [-1, 4], False),
        _var("out", [-1, 4], False),
    ]
    ops = [
        _op("feed", [("X", ["feed"])], [("Out", ["x"])], [("col", 0, 0)]),
        _op("dropout", [("X", ["x"])], [("Out", ["d0"]), ("Mask", ["m0"])],
            [("dropout_prob", 1, 0.3),
             ("dropout_implementation", 2, "upscale_in_train"),
             ("is_test", 6, True)]),
        _op("mul", [("X", ["d0"]), ("Y", ["w"])], [("Out", ["h0"])]),
        _op("scale", [("X", ["h0"])], [("Out", ["s0"])],
            [("scale", 1, 1.0), ("bias", 1, 0.0)]),
        _op("assign", [("X", ["s0"])], [("Out", ["a0"])]),
        _op("relu", [("X", ["h0"])], [("Out", ["dead"])]),  # unread
        _op("softmax", [("X", ["a0"])], [("Out", ["out"])],
            [("axis", 0, (1 << 64) - 1)]),
        _op("fetch", [("X", ["out"])], [("Out", ["fetch"])], [("col", 0, 0)]),
    ]
    prefix = str(tmp_path / "passes")
    with open(prefix + ".pdmodel", "wb") as f:
        f.write(_program(_block(vars_, ops)))
    with open(prefix + ".pdiparams", "wb") as f:
        save_binary_tensor(f, w)

    opt = load_pdmodel(prefix, ir_optim=True)
    raw = load_pdmodel(prefix, ir_optim=False)
    assert opt.pass_stats["delete_dropout"] == 1
    assert opt.pass_stats["identity_scale"] == 2  # scale(1,0) + assign
    assert opt.pass_stats["pruned"] == 1
    assert len(opt.ops) == len(raw.ops) - 4
    x = rng.rand(5, 8).astype(np.float32)
    (o1,) = opt.run({"x": x})
    (o2,) = raw.run({"x": x})
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=1e-6)

    # control-flow programs are conservatively skipped
    from paddle_tpu.inference.pdmodel import apply_inference_passes

    cf_ops = [{"type": "while", "inputs": {"X": ["a"]},
               "outputs": {"Out": ["b"]}, "attrs": {}}]
    same, fetch, stats = apply_inference_passes(cf_ops, ["b"])
    assert same is cf_ops and stats.get("skipped")

    # in-place var-name reuse (Paddle inference inplace passes emit it):
    # folding assign(x->y) then rewriting x would change add(y, x) to
    # add(x, x) — the passes must refuse the whole program
    reuse_ops = [
        {"type": "assign", "inputs": {"X": ["x"]},
         "outputs": {"Out": ["y"]}, "attrs": {}},
        {"type": "relu", "inputs": {"X": ["x"]},
         "outputs": {"Out": ["x"]}, "attrs": {}},
        {"type": "elementwise_add", "inputs": {"X": ["y"], "Y": ["x"]},
         "outputs": {"Out": ["out"]}, "attrs": {}},
    ]
    same2, fetch2, stats2 = apply_inference_passes(
        reuse_ops, ["out"], live_names={"x"})
    assert same2 is reuse_ops and stats2.get("skipped") == \
        "in-place var-name reuse"
    # a feed overwritten before any read is also reuse
    feed_clobber = [{"type": "relu", "inputs": {"X": ["z"]},
                     "outputs": {"Out": ["x"]}, "attrs": {}}]
    _, _, stats3 = apply_inference_passes(
        feed_clobber, ["x"], live_names={"x", "z"})
    assert stats3.get("skipped") == "in-place var-name reuse"


def test_pdmodel_conv_bn_fold(tmp_path):
    """conv_bn_fuse_pass analog: an inference-mode conv2d->batch_norm pair
    folds the BN affine into the filter + one bias add; numerics identical
    to the unoptimized program."""
    from paddle_tpu.inference.pdmodel import load_pdmodel

    rng = np.random.RandomState(9)
    w = (rng.randn(6, 3, 3, 3) * 0.2).astype(np.float32)
    gamma = (rng.rand(6) + 0.5).astype(np.float32)
    beta = (rng.randn(6) * 0.1).astype(np.float32)
    mean = (rng.randn(6) * 0.1).astype(np.float32)
    var = (rng.rand(6) + 0.5).astype(np.float32)

    vars_ = [
        _var("feed", [], False, vtype=9),
        _var("fetch", [], False, vtype=10),
        _var("x", [-1, 3, 8, 8], False),
        _var("w", list(w.shape), True),
        _var("bn.g", [6], True), _var("bn.b", [6], True),
        _var("bn.m", [6], True), _var("bn.v", [6], True),
        _var("c0", [-1, 6, 8, 8], False), _var("b0", [-1, 6, 8, 8], False),
        _var("out", [-1, 6, 8, 8], False),
    ]
    ops = [
        _op("feed", [("X", ["feed"])], [("Out", ["x"])], [("col", 0, 0)]),
        _op("conv2d", [("Input", ["x"]), ("Filter", ["w"])],
            [("Output", ["c0"])],
            [("strides", 3, [1, 1]), ("paddings", 3, [1, 1]),
             ("dilations", 3, [1, 1]), ("groups", 0, 1)]),
        _op("batch_norm",
            [("X", ["c0"]), ("Scale", ["bn.g"]), ("Bias", ["bn.b"]),
             ("Mean", ["bn.m"]), ("Variance", ["bn.v"])],
            [("Y", ["b0"])], [("epsilon", 1, 1e-5), ("is_test", 6, True)]),
        _op("relu", [("X", ["b0"])], [("Out", ["out"])]),
        _op("fetch", [("X", ["out"])], [("Out", ["fetch"])], [("col", 0, 0)]),
    ]
    prefix = str(tmp_path / "convbn")
    with open(prefix + ".pdmodel", "wb") as f:
        f.write(_program(_block(vars_, ops)))
    params = {"bn.b": beta, "bn.g": gamma, "bn.m": mean, "bn.v": var, "w": w}
    with open(prefix + ".pdiparams", "wb") as f:
        for name in sorted(params):
            save_binary_tensor(f, params[name])

    opt = load_pdmodel(prefix, ir_optim=True)
    raw = load_pdmodel(prefix, ir_optim=False)
    assert opt.pass_stats.get("conv_bn_fuse") == 1
    assert not any(op["type"] == "batch_norm" for op in opt.ops)
    x = rng.rand(2, 3, 8, 8).astype(np.float32)
    (o1,) = opt.run({"x": x})
    (o2,) = raw.run({"x": x})
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=1e-4, atol=1e-5)


def test_pdmodel_export_refuses_disconnected_fetch(tmp_path):
    """save_inference_model called outside the program_guard that built the
    net exports the EMPTY default program — the exporter must refuse (the
    artifact would load fine and fail at first run)."""
    from paddle_tpu import nn, static

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("dx", [2, 4], "float32")
            y = nn.functional.relu(nn.Linear(4, 3)(x))
            exe = static.Executor()
            exe.run(startup)
        # OUTSIDE the guard: default program does not contain the graph
        with pytest.raises(ValueError, match="not produced by any exported"):
            static.save_inference_model(str(tmp_path / "oops"), [x], [y],
                                        exe, program_format="pdmodel")
    finally:
        paddle.disable_static()


def test_pdmodel_cnn_ops_match_torch(tmp_path):
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(3)
    w = rng.randn(6, 3, 3, 3).astype(np.float32) * 0.2
    scale = rng.rand(6).astype(np.float32) + 0.5
    bias = rng.randn(6).astype(np.float32) * 0.1
    mean = rng.randn(6).astype(np.float32) * 0.1
    var = rng.rand(6).astype(np.float32) + 0.5

    vars_ = [
        _var("feed", [], False, vtype=9),
        _var("fetch", [], False, vtype=10),
        _var("img", [-1, 3, 8, 8], False),
        _var("conv.w", list(w.shape), True),
        _var("bn.s", [6], True), _var("bn.b", [6], True),
        _var("bn.m", [6], True), _var("bn.v", [6], True),
        _var("c0", [-1, 6, 8, 8], False), _var("c1", [-1, 6, 8, 8], False),
        _var("c2", [-1, 6, 8, 8], False), _var("c3", [-1, 6, 4, 4], False),
    ]
    ops = [
        _op("feed", [("X", ["feed"])], [("Out", ["img"])], [("col", 0, 0)]),
        _op("conv2d", [("Input", ["img"]), ("Filter", ["conv.w"])],
            [("Output", ["c0"])],
            [("strides", 3, [1, 1]), ("paddings", 3, [1, 1]),
             ("dilations", 3, [1, 1]), ("groups", 0, 1)]),
        _op("batch_norm",
            [("X", ["c0"]), ("Scale", ["bn.s"]), ("Bias", ["bn.b"]),
             ("Mean", ["bn.m"]), ("Variance", ["bn.v"])],
            [("Y", ["c1"])], [("epsilon", 1, 1e-5), ("is_test", 6, True)]),
        _op("relu", [("X", ["c1"])], [("Out", ["c2"])]),
        _op("pool2d", [("X", ["c2"])], [("Out", ["c3"])],
            [("pooling_type", 2, "max"), ("ksize", 3, [2, 2]),
             ("strides", 3, [2, 2]), ("paddings", 3, [0, 0])]),
        _op("fetch", [("X", ["c3"])], [("Out", ["fetch"])], [("col", 0, 0)]),
    ]
    prefix = str(tmp_path / "cnn")
    with open(prefix + ".pdmodel", "wb") as f:
        f.write(_program(_block(vars_, ops)))
    params = {"conv.w": w, "bn.s": scale, "bn.b": bias, "bn.m": mean,
              "bn.v": var}
    with open(prefix + ".pdiparams", "wb") as f:
        for name in sorted(params):
            save_binary_tensor(f, params[name])

    from paddle_tpu.inference.pdmodel import load_pdmodel

    prog = load_pdmodel(prefix)
    img = rng.rand(2, 3, 8, 8).astype(np.float32)
    (out,) = prog.run({"img": img})

    with torch.no_grad():
        t = torch.conv2d(torch.tensor(img), torch.tensor(w), padding=1)
        t = torch.nn.functional.batch_norm(
            t, torch.tensor(mean), torch.tensor(var), torch.tensor(scale),
            torch.tensor(bias), training=False, eps=1e-5)
        t = torch.relu(t)
        t = torch.nn.functional.max_pool2d(t, 2, 2)
    np.testing.assert_allclose(np.asarray(out), t.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_pdmodel_unknown_op_raises_loudly(tmp_path):
    vars_ = [_var("feed", [], False, vtype=9),
             _var("fetch", [], False, vtype=10),
             _var("x", [-1, 4], False), _var("y", [-1, 4], False)]
    ops = [
        _op("feed", [("X", ["feed"])], [("Out", ["x"])], [("col", 0, 0)]),
        _op("some_custom_op", [("X", ["x"])], [("Out", ["y"])]),
        _op("fetch", [("X", ["y"])], [("Out", ["fetch"])], [("col", 0, 0)]),
    ]
    prefix = str(tmp_path / "custom")
    with open(prefix + ".pdmodel", "wb") as f:
        f.write(_program(_block(vars_, ops)))
    from paddle_tpu.inference.pdmodel import load_pdmodel

    prog = load_pdmodel(prefix)
    with pytest.raises(NotImplementedError, match="some_custom_op"):
        prog.run({"x": np.zeros((1, 4), np.float32)})


def test_predictor_serves_real_pdmodel(tmp_path):
    """paddle_infer-style Config/Predictor over a REAL-format model."""
    from paddle_tpu import inference

    prefix, p = _mlp_fixture(tmp_path)
    config = inference.Config(prefix + ".pdmodel", prefix + ".pdiparams")
    predictor = inference.Predictor(config)
    assert predictor.get_input_names() == ["x"]
    x = np.random.RandomState(4).rand(7, 8).astype(np.float32)
    (out,) = predictor.run([x])
    h = np.maximum(x @ p["fc1.w"] + p["fc1.b"], 0.0)
    logits = h @ p["fc2.w"] + p["fc2.b"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(-1, keepdims=True),
                               rtol=1e-5, atol=1e-6)


def test_pdmodel_transformer_block_matches_numpy(tmp_path):
    """A BERT-style self-attention block in the real op vocabulary
    (lookup_table_v2, matmul_v2, reshape2/transpose2, scale, softmax,
    elementwise_add residual, layer_norm, gelu) — the exported-transformer
    op path end to end."""
    rng = np.random.RandomState(7)
    V, H, NH, HD, S = 32, 16, 2, 8, 6
    emb = rng.randn(V, H).astype(np.float32) * 0.2
    wq = rng.randn(H, H).astype(np.float32) * 0.2
    wk = rng.randn(H, H).astype(np.float32) * 0.2
    wv = rng.randn(H, H).astype(np.float32) * 0.2
    wo = rng.randn(H, H).astype(np.float32) * 0.2
    ln_s = rng.rand(H).astype(np.float32) + 0.5
    ln_b = rng.randn(H).astype(np.float32) * 0.1

    def mm(x, y):  # matmul_v2
        return _op("matmul_v2", [("X", [x]), ("Y", [y])],
                   [("Out", [f"_{x}_{y}"])]), f"_{x}_{y}"

    vars_ = [_var("feed", [], False, vtype=9),
             _var("fetch", [], False, vtype=10),
             _var("ids", [-1, S], False, dtype_code=3),
             _var("emb.w", [V, H], True), _var("wq", [H, H], True),
             _var("wk", [H, H], True), _var("wv", [H, H], True),
             _var("wo", [H, H], True), _var("ln.s", [H], True),
             _var("ln.b", [H], True)]
    names = set()

    def v(name):
        if name not in names:
            names.add(name)
            vars_.append(_var(name, [-1], False))
        return name

    ops = [_op("feed", [("X", ["feed"])], [("Out", ["ids"])],
               [("col", 0, 0)]),
           _op("lookup_table_v2", [("W", ["emb.w"]), ("Ids", ["ids"])],
               [("Out", [v("x")])])]

    def add_mm(x, y, out):
        ops.append(_op("matmul_v2", [("X", [x]), ("Y", [y])],
                       [("Out", [v(out)])]))

    def add(op_type, ins, out, attrs=(), out_param="Out"):
        ops.append(_op(op_type, ins, [(out_param, [v(out)])], attrs))

    add_mm("x", "wq", "q")
    add_mm("x", "wk", "k")
    add_mm("x", "wv", "vv")
    # [B,S,H] -> [B,S,NH,HD] -> [B,NH,S,HD]
    for t in ("q", "k", "vv"):
        add("reshape2", [("X", [t])], f"{t}_r",
            [("shape", 3, [0, S, NH, HD])])
        add("transpose2", [("X", [f"{t}_r"])], f"{t}_t",
            [("axis", 3, [0, 2, 1, 3])])
    add("transpose2", [("X", ["k_t"])], "k_tt",
        [("axis", 3, [0, 1, 3, 2])])
    add("matmul_v2", [("X", ["q_t"]), ("Y", ["k_tt"])], "logits")
    add("scale", [("X", ["logits"])], "logits_s",
        [("scale", 1, 1.0 / np.sqrt(HD)), ("bias", 1, 0.0)])
    add("softmax", [("X", ["logits_s"])], "probs",
        [("axis", 0, (1 << 64) - 1)])
    add("matmul_v2", [("X", ["probs"]), ("Y", ["vv_t"])], "ctx")
    add("transpose2", [("X", ["ctx"])], "ctx_t", [("axis", 3, [0, 2, 1, 3])])
    add("reshape2", [("X", ["ctx_t"])], "ctx_r", [("shape", 3, [0, S, H])])
    add_mm("ctx_r", "wo", "attn_out")
    add("elementwise_add", [("X", ["x"]), ("Y", ["attn_out"])], "resid")
    ops.append(_op("layer_norm",
                   [("X", ["resid"]), ("Scale", ["ln.s"]),
                    ("Bias", ["ln.b"])], [("Y", [v("normed")])],
                   [("begin_norm_axis", 0, 2), ("epsilon", 1, 1e-5)]))
    add("gelu", [("X", ["normed"])], "out", [("approximate", 6, False)])
    ops.append(_op("fetch", [("X", ["out"])], [("Out", ["fetch"])],
                   [("col", 0, 0)]))

    prefix = str(tmp_path / "bertblock")
    with open(prefix + ".pdmodel", "wb") as f:
        f.write(_program(_block(vars_, ops)))
    params = {"emb.w": emb, "wq": wq, "wk": wk, "wv": wv, "wo": wo,
              "ln.s": ln_s, "ln.b": ln_b}
    with open(prefix + ".pdiparams", "wb") as f:
        for name in sorted(params):
            save_binary_tensor(f, params[name])

    from paddle_tpu.inference.pdmodel import load_pdmodel

    prog = load_pdmodel(prefix)
    ids = rng.randint(0, V, (2, S)).astype(np.int64)
    (out,) = prog.run({"ids": ids})

    # numpy reference
    x = emb[ids]
    q = (x @ wq).reshape(2, S, NH, HD).transpose(0, 2, 1, 3)
    k = (x @ wk).reshape(2, S, NH, HD).transpose(0, 2, 1, 3)
    vv = (x @ wv).reshape(2, S, NH, HD).transpose(0, 2, 1, 3)
    logits = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(HD)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    ctx = (probs @ vv).transpose(0, 2, 1, 3).reshape(2, S, H)
    resid = x + ctx @ wo
    mu = resid.mean(-1, keepdims=True)
    var = resid.var(-1, keepdims=True)
    normed = (resid - mu) / np.sqrt(var + 1e-5) * ln_s + ln_b
    from scipy.stats import norm as _norm  # exact gelu via erf
    ref = normed * _norm.cdf(normed)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_pdmodel_extended_ops(tmp_path):
    """split (multi-output), expand_v2, interp resize, where/compare."""
    rng = np.random.RandomState(9)
    vars_ = [_var("feed", [], False, vtype=9),
             _var("fetch", [], False, vtype=10),
             _var("x", [-1, 4, 4, 4], False),
             _var("s0", [-1], False), _var("s1", [-1], False),
             _var("up", [-1], False), _var("out", [-1], False)]
    ops = [
        _op("feed", [("X", ["feed"])], [("Out", ["x"])], [("col", 0, 0)]),
        _op("split", [("X", ["x"])], [("Out", ["s0", "s1"])],
            [("axis", 0, 1), ("num", 0, 2)]),
        _op("nearest_interp_v2", [("X", ["s0"])], [("Out", ["up"])],
            [("out_h", 0, 8), ("out_w", 0, 8)]),
        _op("reduce_mean", [("X", ["up"])], [("Out", ["out"])],
            [("dim", 3, [1, 2, 3]), ("keep_dim", 6, False)]),
        _op("fetch", [("X", ["out"])], [("Out", ["fetch"])], [("col", 0, 0)]),
    ]
    prefix = str(tmp_path / "ext")
    with open(prefix + ".pdmodel", "wb") as f:
        f.write(_program(_block(vars_, ops)))
    from paddle_tpu.inference.pdmodel import load_pdmodel

    prog = load_pdmodel(prefix)
    x = rng.rand(2, 4, 4, 4).astype(np.float32)
    (out,) = prog.run({"x": x})
    # nearest 2x upsample of the first channel-half preserves the mean
    np.testing.assert_allclose(np.asarray(out), x[:, :2].mean(axis=(1, 2, 3)),
                               rtol=1e-5)


def test_jit_load_serves_real_pdmodel(tmp_path):
    prefix, p = _mlp_fixture(tmp_path)
    layer = paddle.jit.load(prefix)
    x = np.random.RandomState(6).rand(2, 8).astype(np.float32)
    out = layer(paddle.to_tensor(x))
    h = np.maximum(x @ p["fc1.w"] + p["fc1.b"], 0.0)
    logits = h @ p["fc2.w"] + p["fc2.b"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    np.testing.assert_allclose(out.numpy(), e / e.sum(-1, keepdims=True),
                               rtol=1e-5, atol=1e-6)
    with pytest.raises(RuntimeError, match="inference program"):
        layer.train()


def test_static_io_load_inference_model_sniffs_pdmodel(tmp_path):
    """paddle.static.load_inference_model on a REAL-format model."""
    prefix, p = _mlp_fixture(tmp_path)
    paddle.enable_static()
    try:
        prog, feeds, fetches = paddle.static.load_inference_model(prefix)
        assert feeds == ["x"] and fetches == ["out"]
        exe = paddle.static.Executor()
        x = np.random.RandomState(2).rand(3, 8).astype(np.float32)
        (out,) = exe.run(prog, feed={"x": x}, fetch_list=fetches)
        h = np.maximum(x @ p["fc1.w"] + p["fc1.b"], 0.0)
        logits = h @ p["fc2.w"] + p["fc2.b"]
        e = np.exp(logits - logits.max(-1, keepdims=True))
        np.testing.assert_allclose(out, e / e.sum(-1, keepdims=True),
                                   rtol=1e-5, atol=1e-6)
    finally:
        paddle.disable_static()
