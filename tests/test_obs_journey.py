"""Per-tenant SLO observability: request journeys, the goodput/badput
ledger, and the slo_burn burn-rate watchdog.

Five layers of coverage:

- journey exactness: the hop sequence (enqueue → admit → chunks →
  decode/verify → preempt/swap → retire) with engine-step refs on a
  virtual clock, across the swap + recompute preemption paths and the
  retire-before-admit terminals (shed/expired/cancelled), plus the wire
  round-trip through ``validate_journey`` and the hop-cap bound.
- ledger classification: all 7 terminal classes deterministically, and
  the acceptance pin — per-tenant goodput + badput token totals
  reconcile EXACTLY with ``serving_tokens_total`` once every request
  has retired (recompute-replayed tokens counted on both sides).
- slo_burn: fires exactly once per onset (unit-level synthetic feeds
  and a live engine with an unmeetable target), re-arms on a healthy
  window, and never fires on a clean run.
- invariants: the SyncTally certification formula (decode_steps +
  prefills) and ``compile_counts`` are byte-identical with tenants +
  journeys + watchdogs ON, and outputs are bit-identical tenants-on vs
  off; obs-off surfaces return None rather than raising.
- surfaces: families pre-seeded (incl. the multi-label retirement
  grid), the sorted/escaped Prometheus label renderer scrape-parses on
  the live and dump paths, flight-record v2 validates with v1
  back-compat, Chrome tenant tracks, CLI exit codes.

Everything runs on a virtual clock — sleep-free, deterministic.
"""
import json
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import SyncTally
from paddle_tpu.obs import (FLIGHT_RECORD_SCHEMA, FLIGHT_RECORD_SCHEMA_V1,
                            JOURNEY_SCHEMA, JourneyBook, TenantLedger,
                            TenantSLO, Watchdog, WatchdogConfig,
                            prometheus_text, tenant_table,
                            validate_flight_record, validate_journey)
from paddle_tpu.obs.__main__ import main as obs_main
from paddle_tpu.obs.tenant import CLASSES, check_tenant_name
from paddle_tpu.obs.timeline import StepRecord
from paddle_tpu.serving import (FaultInjector, ServingConfig, ServingEngine,
                                SpecConfig)
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.journey


class VirtualClock:
    """Integer-stepped fake engine clock: 1.0 s per read, so latency
    fields are EXACT float arithmetic."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


@pytest.fixture(scope="module")
def model():
    paddle.seed(37)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=48, dropout=0.0))
    m.eval()
    return m


def _engine(model, clock=None, fault_injector=None, **overrides):
    kw = dict(max_batch=2, num_pages=20, page_size=4, max_prompt_len=8)
    kw.update(overrides)
    return ServingEngine(model, ServingConfig(**kw),
                         clock=clock or VirtualClock(),
                         fault_injector=fault_injector)


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 97, (n,)).astype(np.int32)


def _kinds(journey):
    return [h["kind"] for h in journey.hops]


# ---------------------------------------------------------------- journeys
def test_journey_golden_chunked_prefill(model):
    engine = _engine(model, chunk_size=2)
    rid = engine.add_request(_prompt(5), 3, tenant="interactive")
    engine.run()
    j = engine.journey(rid)
    assert j.tenant == "interactive" and j.state == "finished"
    # 5 prompt tokens at chunk_size=2: chunks of 2, 2, 1 — one per step
    assert _kinds(j) == ["enqueue", "admit", "prefill_start",
                         "prefill_chunk", "prefill_chunk", "prefill_chunk",
                         "prefill_end", "first_token", "retire"]
    chunks = [h for h in j.hops if h["kind"] == "prefill_chunk"]
    assert [c["tokens"] for c in chunks] == [2, 2, 1]
    assert [c["final"] for c in chunks] == [False, False, True]
    assert [c["start"] for c in chunks] == [0, 2, 4]
    # step refs: one chunk per engine step, consecutive
    steps = [c["step"] for c in chunks]
    assert steps == [steps[0], steps[0] + 1, steps[0] + 2]
    # hop timestamps are the engine clock, monotonic
    ts = [h["t"] for h in j.hops]
    assert ts == sorted(ts)
    w = validate_journey(j.to_wire())
    assert w["tokens"] == 3 and w["tpot_s"] is not None
    assert w["queue_delay_s"] == j.admitted_t - j.enqueued_t
    assert w["ttft_s"] == j.first_token_t - j.enqueued_t


def test_journey_swap_preemption_path(model):
    inj = FaultInjector().arm("pool_exhausted", step=2)
    engine = _engine(model, preemption_mode="swap", fault_injector=inj,
                     max_batch=2)
    rids = [engine.add_request(_prompt(5, seed=i), 6) for i in range(2)]
    engine.run()
    victims = [engine.journey(r) for r in rids]
    swapped = next(j for j in victims if j.preemptions)
    kinds = _kinds(swapped)
    # the swap round trip is visible with its step refs: preempt +
    # swap_out at the eviction step, swap_in + resume at re-admission
    for kind in ("preempt", "swap_out", "swap_in", "resume"):
        assert kind in kinds, (kind, kinds)
    out_hop = next(h for h in swapped.hops if h["kind"] == "swap_out")
    in_hop = next(h for h in swapped.hops if h["kind"] == "swap_in")
    assert in_hop["step"] > out_hop["step"]
    assert out_hop["pages"] > 0
    assert swapped.state == "finished"
    validate_journey(swapped.to_wire())


def test_journey_recompute_preemption_path(model):
    inj = FaultInjector().arm("pool_exhausted", step=2)
    engine = _engine(model, fault_injector=inj, max_batch=2)
    rids = [engine.add_request(_prompt(5, seed=i), 6) for i in range(2)]
    engine.run()
    victim = next(j for j in (engine.journey(r) for r in rids)
                  if j.preemptions)
    kinds = _kinds(victim)
    # recompute replays from prefill: a second prefill_start after the
    # preempt hop, no swap hops anywhere
    assert kinds.count("prefill_start") == 2
    assert "swap_out" not in kinds and "swap_in" not in kinds
    assert kinds.index("preempt") < len(kinds) - 1 - \
        kinds[::-1].index("prefill_start")
    w = victim.to_wire()
    assert w["preemptions"] == 1
    validate_journey(w)


def test_journeys_for_retire_before_admit_terminals(model):
    engine = _engine(model, max_waiting=1, shed_policy="shed-oldest")
    engine.admit_paused = True
    r_shed = engine.add_request(_prompt(4, seed=0), 4)
    r_kept = engine.add_request(_prompt(4, seed=1), 4)  # sheds r_shed
    r_cancel = None
    engine.cancel(r_kept)
    r_cancel = r_kept
    # an already-expired deadline retires at the next step boundary
    engine.admit_paused = False
    r_expired = engine.add_request(_prompt(4, seed=2), 4, deadline_s=0.0)
    engine.step()
    for rid, state in ((r_shed, "shed"), (r_cancel, "cancelled"),
                       (r_expired, "expired")):
        j = engine.journey(rid)
        assert j.state == state, (rid, state, j)
        w = validate_journey(j.to_wire())
        # never admitted: no admit hop, no queue delay, no TTFT
        assert "admit" not in _kinds(j)
        assert w["queue_delay_s"] is None and w["ttft_s"] is None
        assert w["tpot_s"] is None and w["tokens"] == 0
        assert w["e2e_s"] is not None  # enqueue -> retire is real


def test_journey_verify_hops_carry_accepted_counts(model):
    engine = _engine(model, max_prompt_len=16, num_pages=24,
                     spec=SpecConfig(method="ngram", depth=2))
    prompt = np.array([5, 6, 7, 5, 6, 7, 5, 6], np.int32)
    rid = engine.add_request(prompt, 6)
    engine.run()
    j = engine.journey(rid)
    verifies = [h for h in j.hops if h["kind"] == "verify"]
    assert verifies, _kinds(j)
    assert all(v["proposed"] == 2 and 0 <= v["accepted"] <= 2
               for v in verifies)
    # verify hops ride the decode steps: strictly increasing step refs
    steps = [v["step"] for v in verifies]
    assert steps == sorted(steps) and len(set(steps)) == len(steps)
    validate_journey(j.to_wire())


def test_journey_wire_roundtrip_and_schema_gate(model):
    engine = _engine(model)
    rid = engine.add_request(_prompt(5), 4)
    engine.run()
    w = engine.journey(rid).to_wire()
    assert w["schema"] == JOURNEY_SCHEMA
    loaded = json.loads(json.dumps(w))
    assert validate_journey(loaded) == loaded and loaded == w
    with pytest.raises(ValueError, match="schema"):
        validate_journey(dict(w, schema="nope"))
    with pytest.raises(ValueError, match="missing key"):
        validate_journey({k: v for k, v in w.items() if k != "hops"})
    with pytest.raises(ValueError, match="hop kind"):
        validate_journey(dict(w, hops=[{"kind": "warp", "step": 0,
                                        "t": 0.0}]))
    with pytest.raises(ValueError, match="dict"):
        validate_journey([w])


def test_journey_hop_cap_bounds_but_keeps_retire():
    book = JourneyBook(lambda: 0, max_hops=8)
    book.begin(1, "default")
    book.on_event(1, "enqueued", 0.0, None)
    for i in range(20):
        book.on_event(1, "decode_mark", float(i), {"tokens": i})
    book.on_event(1, "retired", 21.0, {"state": "finished", "tokens": 20})
    j = book.get(1)
    assert len(j.hops) == 9  # 8 capped + the always-kept retire
    assert j.hops[-1]["kind"] == "retire"
    assert j.dropped_hops == 13
    w = validate_journey(j.to_wire())
    assert w["dropped_hops"] == 13


def test_journey_book_evicts_oldest_terminal_only():
    book = JourneyBook(lambda: 0, capacity=2)
    for rid in (1, 2):
        book.begin(rid, "default")
        book.on_event(rid, "retired", 1.0, {"state": "finished",
                                            "tokens": 0})
    book.begin(3, "default")  # at capacity: evicts rid 1 (terminal)
    assert book.get(1) is None and book.get(2) is not None
    assert book.evicted == 1


# ------------------------------------------------------------------ ledger
def test_ledger_classification_goldens():
    slo = TenantSLO(ttft_p99_s=1.0, tpot_p99_s=0.1)
    led = TenantLedger({"t": slo})
    assert led.classify("t", "finished", ttft=0.5, tpot=0.05) == "in_slo"
    assert led.classify("t", "finished", ttft=2.0, tpot=0.05) == "ttft_late"
    assert led.classify("t", "finished", ttft=0.5, tpot=0.5) == "tpot_late"
    for state in ("shed", "expired", "cancelled", "failed"):
        assert led.classify("t", state, ttft=None, tpot=None) == state
    # no declared SLO (incl. the implicit default tenant): finished is
    # in_slo regardless of latency
    assert led.classify("default", "finished", ttft=9e9, tpot=9e9) \
        == "in_slo"
    with pytest.raises(ValueError, match="unknown terminal state"):
        led.classify("t", "vaporized", None, None)
    # accrual: one class per retirement, tokens land exactly once
    led.on_retire("t", "finished", ttft=0.5, tpot=0.05, tokens=10)
    led.on_retire("t", "finished", ttft=2.0, tpot=0.05, tokens=4)
    led.on_retire("t", "cancelled", ttft=None, tpot=None, tokens=3)
    tokens = led.token_totals()["t"]
    assert tokens["in_slo"] == 10 and tokens["ttft_late"] == 4
    assert tokens["cancelled"] == 3
    assert led.burn_totals()["t"] == (1, 3)  # cancelled isn't a violation


def test_engine_ledger_tokens_reconcile_exactly(model):
    # the acceptance pin: goodput + badput tokens across every tenant ==
    # serving_tokens_total, with a recompute preemption in the mix (the
    # replayed tokens are counted by BOTH sides) and cancelled/failed/
    # expired retirements contributing their emitted spans to badput
    inj = FaultInjector().arm("pool_exhausted", step=2) \
        .arm("decode_fail", step=5)
    engine = _engine(model, fault_injector=inj, max_batch=2,
                     tenants={"interactive": TenantSLO(1e6, 1e6)})
    rids = [engine.add_request(_prompt(5, seed=i), 6,
                               tenant="interactive" if i % 2 else "default")
            for i in range(3)]
    engine.run()
    states = {engine.status(r) for r in rids}
    assert states == {"finished", "failed"}, states
    for r in rids:  # every terminal state exports a validate-clean dict
        w = validate_journey(engine.journey(r).to_wire())
        assert w["state"] == engine.status(r)
    snap = engine.metrics.snapshot()
    ledger_total = sum(sum(book.values())
                       for book in engine._tenants.token_totals().values())
    assert ledger_total == snap["serving_tokens_total"], \
        (engine._tenants.token_totals(), snap["serving_tokens_total"])
    good = sum(v for k, v in snap.items()
               if k.startswith("serving_tenant_goodput_tokens_total"))
    bad = sum(v for k, v in snap.items()
              if k.startswith("serving_tenant_badput_tokens_total"))
    assert good + bad == snap["serving_tokens_total"]
    # the retirement grid counts every request exactly once
    retired = sum(v for k, v in snap.items()
                  if k.startswith("serving_tenant_retired_total"))
    assert retired == len(rids)


def test_engine_ttft_and_tpot_late_classes(model):
    clock = VirtualClock()
    engine = _engine(model, clock=clock, tenants={
        "tight_ttft": TenantSLO(ttft_p99_s=1e-9, tpot_p99_s=1e6),
        "tight_tpot": TenantSLO(ttft_p99_s=1e6, tpot_p99_s=1e-9)})
    r1 = engine.add_request(_prompt(5, seed=0), 4, tenant="tight_ttft")
    r2 = engine.add_request(_prompt(5, seed=1), 4, tenant="tight_tpot")
    engine.run()
    snap = engine.metrics.snapshot()
    assert snap["serving_tenant_retired_total"
                "{tenant=tight_ttft,class=ttft_late}"] == 1
    assert snap["serving_tenant_retired_total"
                "{tenant=tight_tpot,class=tpot_late}"] == 1
    # all their tokens are badput, none goodput
    assert snap["serving_tenant_goodput_tokens_total"
                "{tenant=tight_ttft}"] == 0
    assert snap["serving_tenant_badput_tokens_total"
                "{tenant=tight_ttft}"] == 4
    # the per-tenant latency families saw the observations
    assert snap["serving_ttft_s_count{tenant=tight_ttft}"] == 1
    assert snap["serving_tpot_s_count{tenant=tight_tpot}"] == 1
    assert snap["serving_queue_delay_s_count{tenant=tight_ttft}"] == 1
    assert engine.journey(r1).state == "finished"
    assert engine.journey(r2).state == "finished"


# ---------------------------------------------------------------- slo_burn
def _record(step, queue_depth=0):
    return StepRecord(step=step, t_start=float(step), t_end=step + 1.0,
                      admitted=0, prefills=0, batch=0, finished=0,
                      preemptions=0, queue_depth=queue_depth,
                      pages_in_use=0)


def test_slo_burn_fires_once_per_onset_and_rearms():
    cfg = WatchdogConfig(slo_burn_window_steps=4, slo_burn_threshold=0.5,
                         slo_burn_min_retired=2)
    wd = Watchdog(cfg)
    feed = lambda step, v, r: wd.on_step(  # noqa: E731
        _record(step), {"tenant_slo": {"batch": (v, r)}})
    assert feed(0, 0, 1) == []          # below min_retired
    fired = feed(1, 2, 3)               # 2/3 violations >= 0.5: onset
    assert [a.rule for a in fired] == ["slo_burn"]
    assert fired[0].data["tenant"] == "batch"
    assert feed(2, 3, 4) == []          # still burning: latched, quiet
    # a healthy stretch re-arms (fraction in the window drops below the
    # threshold), then a second onset fires again
    assert feed(3, 3, 8) == []
    assert feed(4, 3, 12) == []
    assert feed(5, 3, 16) == []         # window now all-healthy deltas
    fired = feed(6, 15, 24)             # 12/20 in-window: second onset
    assert [a.rule for a in fired] == ["slo_burn"]
    assert wd.fired_total["slo_burn"] == 2
    # per-tenant isolation: a second tenant's burn is its own onset
    # (batch stays latched and quiet)
    fired = wd.on_step(_record(7), {"tenant_slo": {
        "batch": (15, 24), "vip": (4, 4)}})
    assert [(a.rule, a.data["tenant"]) for a in fired] == [("slo_burn",
                                                           "vip")]


def test_slo_burn_rearms_for_sparse_tenants():
    # the latch must not be held forever by a tenant whose healthy
    # traffic is too sparse to reach min_retired per window: a FULL
    # zero-violation window re-arms, and a later burn fires again
    cfg = WatchdogConfig(slo_burn_window_steps=3, slo_burn_threshold=0.5,
                         slo_burn_min_retired=4)
    wd = Watchdog(cfg)
    feed = lambda step, v, r: wd.on_step(  # noqa: E731
        _record(step), {"tenant_slo": {"t": (v, r)}})
    assert [a.rule for a in feed(0, 4, 4)] == ["slo_burn"]  # onset
    assert feed(1, 4, 5) == []  # sparse, still violations in window
    assert feed(2, 4, 5) == []
    assert feed(3, 4, 6) == []  # window now full + zero violations:
    fired = feed(4, 8, 10)      # re-armed, second burn fires
    assert [a.rule for a in fired] == ["slo_burn"]
    assert wd.fired_total["slo_burn"] == 2


def test_engine_slo_burn_fires_once_and_stamps_instant(model):
    engine = _engine(
        model, max_batch=2,
        tenants={"victim": TenantSLO(ttft_p99_s=1e-9, tpot_p99_s=1e-9)},
        watchdog=WatchdogConfig(slo_burn_window_steps=16,
                                slo_burn_min_retired=4))
    for i in range(6):
        engine.add_request(_prompt(4, seed=i), 2, tenant="victim")
    engine.run()
    alerts = engine.alerts()
    assert [a.rule for a in alerts] == ["slo_burn"]  # exactly once
    assert alerts[0].data["tenant"] == "victim"
    snap = engine.metrics.snapshot()
    assert snap["serving_alerts_total{rule=slo_burn}"] == 1
    doc = engine.export_chrome_trace()
    instants = [e for e in doc["traceEvents"]
                if e["ph"] == "i" and e["name"] == "alert:slo_burn"]
    assert len(instants) == 1 and instants[0]["s"] == "g"


def test_clean_run_fires_no_slo_burn(model):
    engine = _engine(model, tenants={
        "interactive": TenantSLO(ttft_p99_s=1e6, tpot_p99_s=1e6)})
    for i in range(4):
        engine.add_request(_prompt(4, seed=i), 4, tenant="interactive")
    engine.run()
    assert engine.alerts() == []
    snap = engine.metrics.snapshot()
    assert all(v == 0 for k, v in snap.items()
               if k.startswith("serving_alerts_total"))


def test_slo_burn_config_validation():
    with pytest.raises(ValueError, match="slo_burn_threshold"):
        Watchdog(WatchdogConfig(slo_burn_threshold=1.5))
    with pytest.raises(ValueError, match="slo_burn_min_retired"):
        Watchdog(WatchdogConfig(slo_burn_min_retired=0))


# -------------------------------------------------------------- invariants
def test_sync_free_and_compile_counts_with_tenants_and_journeys_on(model):
    # the acceptance pin: the SyncTally certification formula (one token
    # fetch per decode step + one per completed prefill) and the
    # compile counts are UNCHANGED with tenants + journeys + the
    # burn-rate watchdog ON — the tenant label never enters a traced
    # program
    engine = _engine(model, tenants={
        "interactive": TenantSLO(ttft_p99_s=1e6, tpot_p99_s=1e6)})
    assert engine.config.enable_tracing and engine.config.enable_watchdogs
    for i in range(3):
        engine.add_request(_prompt(4, seed=i), 4,
                           tenant="interactive" if i % 2 else "default")
    with SyncTally() as tally:
        engine.run()
    snap = engine.metrics.snapshot()
    fetches = int(snap["serving_decode_steps"]
                  + snap["serving_prefills_total"])
    assert tally.count == fetches, (tally.events, fetches)
    assert engine.compile_counts == {"prefill": 1, "decode": 1}
    assert len(engine.journeys()) == 3  # journeys really on


def test_outputs_bit_identical_tenants_on_vs_off(model):
    prompts = [_prompt(5, seed=i) for i in range(3)]

    def run(tenants, tags):
        engine = _engine(model, tenants=tenants)
        rids = [engine.add_request(p, 5, tenant=t)
                for p, t in zip(prompts, tags)]
        outs = engine.run()
        return [outs[r] for r in rids], engine.compile_counts

    base, cc_off = run(None, ["default"] * 3)
    tagged, cc_on = run({"interactive": TenantSLO(1e6, 1e6),
                         "batch": TenantSLO(1e6, 1e6)},
                        ["interactive", "batch", "interactive"])
    for a, b in zip(base, tagged):
        assert np.array_equal(a, b)
    assert cc_on == cc_off


def test_obs_off_tenant_and_journey_surfaces_return_none(model):
    engine = _engine(model, enable_tracing=False,
                     tenants={"interactive": TenantSLO(1e6, 1e6)})
    rid = engine.add_request(_prompt(5), 4, tenant="interactive")
    engine.run()
    # the obs-off contract: None / empty, never a raise
    assert engine.journey(rid) is None
    assert engine.journeys() == []
    assert engine.tenant_report() is None
    assert engine._journeys is None and engine._tenants is None
    rec = engine.flight_record()
    assert rec["tenants"] == {} and rec["journeys"] == []
    validate_flight_record(rec)


def test_tenant_validation_and_adhoc_seeding(model):
    with pytest.raises(ValueError, match="tenant name"):
        _engine(model, tenants={"bad{name": TenantSLO(1.0, 1.0)})
    with pytest.raises(ValueError, match="TenantSLO"):
        _engine(model, tenants={"ok": (1.0, 1.0)})
    with pytest.raises(ValueError, match="ttft_p99_s"):
        _engine(model, tenants={"ok": TenantSLO(-1.0, 1.0)})
    engine = _engine(model)
    with pytest.raises(ValueError, match="tenant name"):
        engine.add_request(_prompt(4), 4, tenant="a,b")
    with pytest.raises(ValueError, match="tenant name"):
        check_tenant_name("")
    # an ad-hoc (undeclared) tenant seeds its families on first sight
    snap = engine.metrics.snapshot()
    assert "serving_tenant_goodput_tokens_total{tenant=adhoc}" not in snap
    engine.add_request(_prompt(4), 4, tenant="adhoc")
    snap = engine.metrics.snapshot()
    assert snap["serving_tenant_goodput_tokens_total{tenant=adhoc}"] == 0
    assert snap["serving_tenant_retired_total"
                "{tenant=adhoc,class=failed}"] == 0


def test_tenant_families_pre_seeded_at_construction(model):
    engine = _engine(model, tenants={
        "interactive": TenantSLO(1e6, 1e6), "batch": TenantSLO(1e6, 1e6)})
    snap = engine.metrics.snapshot()
    for t in ("default", "interactive", "batch"):
        assert snap[f"serving_tenant_goodput_tokens_total{{tenant={t}}}"] \
            == 0
        assert snap[f"serving_tenant_badput_tokens_total{{tenant={t}}}"] \
            == 0
        for cls in CLASSES:
            assert snap[f"serving_tenant_retired_total"
                        f"{{tenant={t},class={cls}}}"] == 0
        for hist in ("ttft_s", "tpot_s", "queue_delay_s"):
            assert snap[f"serving_{hist}_count{{tenant={t}}}"] == 0
            assert snap[f"serving_{hist}_p99{{tenant={t}}}"] == 0


# ------------------------------------------------------ exposition + wire
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'                    # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'  # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' -?[0-9.e+Inf]+$')


def _scrape_parse(text):
    """A strict mini scrape parser: every non-comment line must match
    the exposition sample grammar, label keys must be sorted, and each
    # TYPE must appear at most once per metric name."""
    typed = {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE"):
            _, _, name, typ = ln.split()
            assert name not in typed, f"duplicate TYPE for {name}"
            typed[name] = typ
            continue
        assert _SAMPLE_RE.match(ln), f"unparseable sample line: {ln!r}"
        if "{" in ln:
            keys = re.findall(r'[{,]([a-zA-Z_][a-zA-Z0-9_]*)="', ln)
            assert keys == sorted(keys), f"unsorted labels: {ln!r}"
    return typed


def test_prometheus_multilabel_scrape_parses_live_and_dump(model,
                                                           tmp_path):
    engine = _engine(model, tenants={"batch": TenantSLO(1e6, 1e6)})
    engine.add_request(_prompt(5), 4, tenant="batch")
    engine.run()
    # live path: the full exposition incl. tenant family buckets
    text = engine.metrics.prometheus()
    typed = _scrape_parse(text)
    assert typed["serving_tenant_goodput_tokens_total"] == "counter"
    assert typed["serving_tenant_retired_total"] == "counter"
    assert typed["serving_ttft_s"] == "histogram"
    assert 'serving_ttft_s_bucket{le="+Inf",tenant="batch"}' in text
    assert 'serving_queue_delay_s_bucket{le="+Inf",tenant="batch"}' \
        in text
    assert 'serving_tenant_retired_total{class="in_slo",tenant="batch"}' \
        " 1" in text
    # dump path: same renderer over the flight record's gauges
    dump = tmp_path / "dump.json"
    engine.dump_flight_record(dump)
    assert obs_main(["--flight-record", str(dump), "--prometheus"]) == 0


def test_label_values_escaped_in_exposition():
    text = prometheus_text({'weird{path=a"b\\c}': 1.0})
    assert 'weird{path="a\\"b\\\\c"} 1' in text


def test_flight_record_v2_with_v1_backcompat(model, tmp_path):
    engine = _engine(model, tenants={"batch": TenantSLO(1e6, 1e6)})
    engine.add_request(_prompt(5), 4, tenant="batch")
    engine.run()
    rec = engine.flight_record()
    assert rec["schema"] == FLIGHT_RECORD_SCHEMA
    validate_flight_record(rec)
    assert rec["tenants"]["batch"]["goodput_tokens"] == 4
    assert rec["tenants"]["batch"]["slo"] == {"ttft_p99_s": 1e6,
                                              "tpot_p99_s": 1e6}
    assert [validate_journey(j) for j in rec["journeys"]]
    # json round trip stays valid
    validate_flight_record(json.loads(json.dumps(rec)))
    # v1 dumps (no tenant/journey sections) stay readable
    v1 = {k: v for k, v in rec.items() if k not in ("tenants", "journeys")}
    v1["schema"] = FLIGHT_RECORD_SCHEMA_V1
    validate_flight_record(v1)
    # ... but a v2 record missing its sections does not
    broken = dict(rec)
    del broken["journeys"]
    with pytest.raises(ValueError, match="journeys"):
        validate_flight_record(broken)
    # and a corrupt journey inside the ring is named
    bad = dict(rec, journeys=[{"schema": "nope"}])
    with pytest.raises(ValueError, match="journey schema"):
        validate_flight_record(bad)


def test_chrome_export_grows_tenant_tracks(model):
    engine = _engine(model, tenants={"batch": TenantSLO(1e6, 1e6)})
    engine.add_request(_prompt(5, seed=0), 4, tenant="batch")
    engine.add_request(_prompt(5, seed=1), 4)
    engine.run()
    doc = engine.export_chrome_trace()
    json.loads(json.dumps(doc))
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {"tenant batch", "tenant default"} <= names
    retires = [e for e in doc["traceEvents"]
               if e.get("cat") == "tenant" and e["ph"] == "i"]
    assert len(retires) == 2
    assert all(e["name"] == "retire:finished" and "tokens" in e["args"]
               for e in retires)


def test_tenant_table_renders(model):
    engine = _engine(model, tenants={"batch": TenantSLO(1e6, 1e6)})
    engine.add_request(_prompt(5), 4, tenant="batch")
    engine.run()
    table = tenant_table(engine.tenant_report())
    assert "batch" in table and "default" in table
    assert "100.0%" in table  # everything finished in_slo
    assert "goodput" in table and "ttft_p99" in table


def test_obs_cli_tenant_table_and_journey_views(model, tmp_path, capsys):
    engine = _engine(model, tenants={"batch": TenantSLO(1e6, 1e6)})
    rid = engine.add_request(_prompt(5), 4, tenant="batch")
    engine.run()
    dump = tmp_path / "dump.json"
    engine.dump_flight_record(dump)

    assert obs_main(["--flight-record", str(dump), "--tenant-table"]) == 0
    out = capsys.readouterr().out
    assert "batch" in out and "goodput" in out

    assert obs_main(["--flight-record", str(dump),
                     "--journey", str(rid)]) == 0
    out = capsys.readouterr().out
    assert f"journey rid={rid}" in out and "first_token" in out

    # a rid outside the ring is bad usage, naming the retained set
    assert obs_main(["--flight-record", str(dump),
                     "--journey", "99999"]) == 2
    assert "not in the dump's journey ring" in capsys.readouterr().out

    # the default pretty-print grows the tenant section
    assert obs_main(["--flight-record", str(dump)]) == 0
    out = capsys.readouterr().out
    assert "tenants (" in out and "journeys retained:" in out

    # --tenant-table on a v1 (pre-tenant) dump is bad usage, explained
    rec = json.loads(dump.read_text())
    v1 = {k: v for k, v in rec.items() if k not in ("tenants", "journeys")}
    v1["schema"] = FLIGHT_RECORD_SCHEMA_V1
    old = tmp_path / "v1.json"
    old.write_text(json.dumps(v1))
    assert obs_main(["--flight-record", str(old), "--tenant-table"]) == 2
    assert "no tenant section" in capsys.readouterr().out
    # --journey on a v1 dump names the real reason, not a fake eviction
    assert obs_main(["--flight-record", str(old), "--journey", "0"]) == 2
    assert "no journey ring" in capsys.readouterr().out
    # ... but the other views still read it (back-compat)
    assert obs_main(["--flight-record", str(old)]) == 0
    capsys.readouterr()
