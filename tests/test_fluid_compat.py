"""paddle.fluid compat namespace (SURVEY §2.1 #12) — 1.x-style code runs
against the TPU execution paths unchanged."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fluid


@pytest.fixture()
def _static():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_fluid_style_training_program(_static):
    paddle.seed(3)
    prog, sprog = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, sprog):
        x = fluid.data("x", [4, 8], "float32")
        y = fluid.data("y", [4, 1], "int64")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 3)
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(pred, y))
        fluid.optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    rs = np.random.RandomState(0)
    feed = {"x": rs.rand(4, 8).astype(np.float32),
            "y": rs.randint(0, 3, (4, 1))}
    losses = [float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
              for _ in range(6)]
    assert losses[-1] < losses[0]


def test_fluid_layer_spellings():
    a = paddle.to_tensor(np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32))
    b = paddle.ones([2, 2])
    np.testing.assert_allclose(
        np.asarray(fluid.layers.elementwise_add(a, b, act="relu")._value),
        np.asarray(a._value) + 1.0)
    np.testing.assert_allclose(
        float(fluid.layers.reduce_mean(a).numpy()), 2.5)
    np.testing.assert_allclose(
        np.asarray(fluid.layers.reduce_sum(a, dim=1, keep_dim=True)._value),
        [[3.0], [7.0]])
    img = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    np.testing.assert_allclose(
        np.asarray(fluid.layers.pool2d(img, 2, "max", 2)._value),
        [[[[5.0, 7.0], [13.0, 15.0]]]])
    np.testing.assert_allclose(
        float(fluid.layers.pool2d(img, global_pooling=True,
                                  pool_type="avg").numpy().ravel()[0]), 7.5)
    fc_out = fluid.layers.fill_constant([2, 2], "float32", 3.0)
    np.testing.assert_allclose(np.asarray(fc_out._value), 3.0)


def test_fluid_optimizer_regularization_maps_to_weight_decay():
    m = paddle.nn.Linear(4, 4)
    opt = fluid.optimizer.MomentumOptimizer(
        learning_rate=0.1, momentum=0.9,
        regularization=fluid.regularizer.L2DecayRegularizer(0.01),
        parameter_list=m.parameters())
    assert opt._weight_decay == pytest.approx(0.01)
    x = paddle.ones([2, 4])
    m(x).sum().backward()
    opt.step()
    opt.clear_grad()


def test_fluid_initializer_aliases():
    assert fluid.initializer.Xavier is fluid.initializer.XavierInitializer
    w = paddle.nn.Linear(
        4, 4, weight_attr=paddle.ParamAttr(
            initializer=fluid.initializer.Constant(0.5)))
    np.testing.assert_allclose(np.asarray(w.weight._value), 0.5)


def test_fluid_io_save_load_params_combined(tmp_path, _static):
    paddle.seed(5)
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = fluid.data("x", [2, 4], "float32")
        out = fluid.layers.fc(x, 3)
    exe = fluid.Executor()
    feed = {"x": np.ones((2, 4), np.float32)}
    before = exe.run(prog, feed=feed, fetch_list=[out])[0]
    names = fluid.io.save_params(exe, str(tmp_path), main_program=prog,
                                 filename="__params__")
    assert (tmp_path / "__params__").exists() and names
    # clobber, then reload
    for p in prog.captured_params():
        p.set_value(np.zeros(p.shape, np.float32))
    fluid.io.load_params(exe, str(tmp_path), main_program=prog,
                         filename="__params__")
    after = exe.run(prog, feed=feed, fetch_list=[out])[0]
    np.testing.assert_allclose(after, before, rtol=1e-6)


def test_fluid_positional_optimizer_args():
    """1.x code passes hyperparameters POSITIONALLY — they must land on the
    right parameters, not on regularization/grad_clip."""
    m = paddle.nn.Linear(2, 2)
    opt = fluid.optimizer.MomentumOptimizer(0.1, 0.9,
                                            parameter_list=m.parameters())
    assert opt._momentum == pytest.approx(0.9)
    assert opt._weight_decay in (None, 0.0)
    opt2 = fluid.optimizer.AdamOptimizer(0.001, 0.9, 0.999, 1e-8,
                                         parameter_list=m.parameters())
    assert opt2._beta1 == 0.9 and opt2._beta2 == 0.999
    assert opt2._weight_decay in (None, 0.0) and opt2._grad_clip is None


def test_fluid_cross_entropy_takes_probabilities():
    probs = paddle.to_tensor(np.asarray([[0.7, 0.2, 0.1],
                                         [0.1, 0.8, 0.1]], np.float32))
    label = paddle.to_tensor(np.asarray([[0], [1]], np.int64))
    out = fluid.layers.cross_entropy(probs, label)
    assert list(out.shape) == [2, 1]  # per-example, not reduced
    np.testing.assert_allclose(out.numpy().ravel(),
                               [-np.log(0.7), -np.log(0.8)], rtol=1e-5)


def test_fluid_expand_is_tile_and_split_last_dim():
    x = paddle.to_tensor(np.asarray([[1.0, 2.0, 3.0]], np.float32))
    tiled = fluid.layers.expand(x, [2, 2])
    assert list(tiled.shape) == [2, 6]  # tile, NOT broadcast-to-shape
    a, b = fluid.layers.split(paddle.ones([4, 8]), 2)
    assert list(a.shape) == [4, 4]  # fluid splits the LAST dim by default
    c, d = fluid.layers.split(paddle.ones([4, 8]), 2, dim=0)
    assert list(c.shape) == [2, 8]


def test_fluid_dropout_downgrade_in_infer():
    x = paddle.ones([1000])
    # train: kept values stay UNSCALED (downgrade_in_infer default)
    y = fluid.layers.dropout(x, 0.5)
    vals = np.unique(np.asarray(y._value))
    assert set(np.round(vals, 6)).issubset({0.0, 1.0})
    # infer: activations scaled by (1-p)
    z = fluid.layers.dropout(x, 0.5, is_test=True)
    np.testing.assert_allclose(np.asarray(z._value), 0.5)


def test_fluid_elementwise_mid_axis_broadcast():
    x = paddle.ones([2, 3, 4, 5])
    bias = paddle.to_tensor(np.arange(3, dtype=np.float32))
    out = fluid.layers.elementwise_add(x, bias, axis=1)
    assert list(out.shape) == [2, 3, 4, 5]
    np.testing.assert_allclose(np.asarray(out._value)[0, 2], 3.0)


def test_sequence_pad_truncating_maxlen(_static):
    from paddle_tpu.static.nn import sequence_pad

    padded, lens = sequence_pad(
        [np.ones((5, 2), np.float32), np.ones((2, 2), np.float32)],
        0.0, maxlen=3)
    assert list(padded.shape) == [2, 3, 2]
    np.testing.assert_array_equal(np.asarray(lens._value), [3, 2])


def test_fluid_layers_batch2_semantics():
    x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    # 1.x flatten is ALWAYS 2-D at `axis`
    assert fluid.layers.flatten(x, 2).shape == [6, 4]
    assert fluid.layers.flatten(x).shape == [2, 12]
    v, i = fluid.layers.topk(x, 2)
    assert v.shape == [2, 3, 2]
    assert fluid.layers.argmax(x).shape == [3, 4]  # 1.x default axis=0
    assert fluid.layers.squeeze(paddle.ones([1, 3, 1]), [0, 2]).shape == [3]
    assert fluid.layers.unsqueeze(paddle.ones([3]), [0, 2]).shape == [1, 3, 1]
    p = fluid.layers.pad(paddle.ones([2, 2]), [1, 1, 0, 0], 9.0)
    assert p.shape == [4, 2]
    assert float(np.asarray(p._value)[0, 0]) == 9.0
    assert fluid.layers.uniform_random([2, 3]).shape == [2, 3]
    assert fluid.layers.gaussian_random([4]).shape == [4]


def test_fluid_dygraph_guard_and_to_variable():
    with fluid.dygraph.guard():
        v = fluid.dygraph.to_variable(np.arange(4, dtype=np.float32))
        assert v.shape == [4]
        lin = paddle.nn.Linear(4, 2)
        assert np.isfinite(np.asarray(lin(v)._value)).all()


def test_fluid_namespace_batch2():
    """fluid.{backward,clip,metrics,DataFeeder,dygraph.Linear/Embedding,
    save_dygraph} — the 1.x surface migration guides lean on."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid

    lin = fluid.dygraph.Linear(4, 3, act="relu")
    out = lin(paddle.to_tensor(np.random.rand(2, 4).astype("float32")))
    assert tuple(out.shape) == (2, 3) and float(out.numpy().min()) >= 0

    emb = fluid.dygraph.Embedding([10, 5], padding_idx=0)
    e = emb(paddle.to_tensor(np.array([0, 3], "int64")))
    assert np.allclose(e.numpy()[0], 0)  # padding row zeroed

    m = fluid.metrics.Precision()
    m.update(np.array([0.9, 0.2, 0.8]), np.array([1, 0, 0]))
    assert m.eval() == 0.5
    r = fluid.metrics.Recall()
    r.update(np.array([0.9, 0.2]), np.array([1, 1]))
    assert r.eval() == 0.5

    fd = fluid.DataFeeder(
        feed_list=[type("V", (object,), {"name": "x"})()])
    feed = fd.feed([(np.ones(3),), (np.zeros(3),)])
    assert feed["x"].shape == (2, 3)

    assert isinstance(fluid.clip.GradientClipByGlobalNorm(1.0),
                      paddle.nn.ClipGradByGlobalNorm)
    assert fluid.in_dygraph_mode()
    import pytest as _pytest

    with _pytest.raises(NotImplementedError, match="custom_op"):
        fluid.load_op_library("x.so")


def test_fluid_save_load_dygraph(tmp_path):
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid

    net = paddle.nn.Linear(3, 2)
    path = str(tmp_path / "m")
    fluid.dygraph.save_dygraph(net.state_dict(), path)
    params, opt = fluid.dygraph.load_dygraph(path)
    assert opt is None
    np.testing.assert_allclose(
        np.asarray(params["weight"] if "weight" in params
                   else list(params.values())[0]),
        net.state_dict()[list(net.state_dict().keys())[0]].numpy())


def test_fluid_name_scope_and_install_check():
    """Code-review regressions (reproduced): name_scope must not crash and
    install_check keeps the reference's module call shape."""
    import paddle_tpu.fluid as fluid

    with fluid.name_scope("encoder"):
        name = fluid.unique_name.generate("w")
    assert name.startswith("encoder/w")
    fluid.install_check.run_check()  # the documented spelling


def test_fluid_core_and_slim_shims():
    import paddle_tpu.fluid as fluid

    assert fluid.core.VarDesc.VarType.FP32 == 5
    assert not fluid.core.is_compiled_with_cuda()
    assert fluid.core.get_cuda_device_count() == 0
    assert isinstance(fluid.core.globals(), dict)
    assert fluid.core.LoDTensor.__name__ == "LoDTensor"
    assert hasattr(fluid.contrib.slim, "QAT") or hasattr(
        fluid.contrib.slim, "quant_post_static") or True  # module resolves
    assert fluid.contrib.slim.__name__ == "paddle_tpu.quantization"
