"""N-replica fleet router: prefix-affinity routing, ledger-weighted
admission, fault drill, and the trace-driven simulator.

Coverage, one layer per block:

- digest parity: ``prefix_digest`` chains vs ``cached_prefix_tokens``
  through ``gossip_digests`` — device index AND the host spill tier —
  plus the prefix/determinism properties the router's affinity math
  assumes.
- routing: affinity strictly beats round-robin on the same trace with
  the prefill-token arithmetic pinned EXACTLY (conservation against
  tokens_saved, 2x hit count, saved-diff == prefill-diff), and a
  warm-but-full replica spills to the least-loaded survivor BEFORE
  anything is shed.
- admission: the slo_burn golden — exactly one weight gain per onset,
  gauge + weight_changes agree, and goodput + badput still reconcile
  with serving_tokens_total fleet-wide.
- equivalence: a 1-replica fleet is bit-identical to the bare engine,
  and the SyncTally certification + per-replica compile counts are
  UNCHANGED with the router on (routing never touches a device value).
- faults: ``route_fail`` sheds with a validate_journey-clean router
  journey; ``replica_down`` re-homes clean waiters to survivors as
  spills, fails in-flight requests, and drops the replica gauge —
  every journey on every book stays schema-clean.
- simulator: ``replay_classes`` reproduces the live fleet's per-tenant
  retirement-class counts EXACTLY from the journey dump, the what-if
  projection is sane, and the CLI round-trips a dump file.

Everything runs on the shared virtual clock — sleep-free, deterministic.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import SyncTally
from paddle_tpu.obs import TenantSLO, WatchdogConfig, validate_journey
from paddle_tpu.serving import (FaultInjector, FleetConfig, FleetRouter,
                                ServingConfig, ServingEngine, prefix_digest)
from paddle_tpu.serving.fleet_sim import main as sim_main
from paddle_tpu.serving.fleet_sim import replay_classes, simulate
from paddle_tpu.serving.scheduler import FAILED, SHED
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.fleet


class VirtualClock:
    """Integer-stepped fake clock shared by every replica: 1.0 s per
    read, so latency fields are exact float arithmetic."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


@pytest.fixture(scope="module")
def model():
    paddle.seed(41)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=48, dropout=0.0))
    m.eval()
    return m


_ENG = dict(max_batch=2, num_pages=20, page_size=4, max_prompt_len=8)


def _fleet(model, num_replicas=3, eng=None, injector=None, **fleet_kw):
    kw = dict(_ENG)
    kw.update(eng or {})
    cfg = FleetConfig(num_replicas=num_replicas,
                      engine=ServingConfig(**kw), **fleet_kw)
    return FleetRouter(model, cfg, clock=VirtualClock(),
                       fault_injector=injector)


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 97, (n,)).astype(np.int32)


# ------------------------------------------------------------ digest parity
def test_prefix_digest_properties():
    a = _prompt(8, seed=1)
    b = _prompt(8, seed=2)
    da = prefix_digest(a, 4)
    assert len(da) == 2  # one chained digest per FULL page
    assert prefix_digest(a, 4) == da  # deterministic
    assert prefix_digest(a[:4], 4) == da[:1]  # prefix property: a
    # chain's digests are its own prefixes' digests
    assert prefix_digest(b, 4)[0] != da[0]
    assert prefix_digest(a[:3], 4) == ()  # partial pages never digest


def test_digest_parity_with_cached_prefix_tokens(model):
    # the router-side affinity count and the cache-side probe must agree
    # EXACTLY — both derive from one key helper, and this pin is what
    # makes digest disagreement impossible by construction
    eng = ServingEngine(model, ServingConfig(**_ENG),
                        clock=VirtualClock())
    warm = _prompt(8, seed=1)
    eng.add_request(warm, 3)
    eng.run()
    gossip = eng.cache.gossip_digests()
    n = 0
    for d in prefix_digest(warm, 4):
        if d not in gossip:
            break
        n += 1
    assert n * 4 == eng.cache.cached_prefix_tokens(warm) == 8
    cold = _prompt(8, seed=9)
    assert not any(d in gossip for d in prefix_digest(cold, 4))


def test_digest_parity_covers_host_tier(model):
    # a prefix chain spilled to the host tier must still gossip — the
    # router would otherwise route a warm request to a cold replica
    rng = np.random.RandomState(29)
    system = rng.randint(0, 97, (16,)).astype(np.int32)
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=14, page_size=4, max_prompt_len=32,
        host_tier_bytes=1 << 20), clock=VirtualClock())
    eng.add_request(np.concatenate([system, [1, 2, 3]]).astype(np.int32), 4)
    eng.run()
    for _ in range(2):  # cold whales sweep the system pages to the tier
        eng.add_request(rng.randint(0, 97, (22,)).astype(np.int32), 2)
    eng.run()
    assert eng.cache.match_prefix(system) == []  # gone from the device
    cached = eng.cache.cached_prefix_tokens(system)
    assert cached == 16  # ...but fully served from the host tier
    gossip = eng.cache.gossip_digests()
    n = 0
    for d in prefix_digest(system, 4):
        if d not in gossip:
            break
        n += 1
    assert n * 4 == cached


# ----------------------------------------------------------------- config
def test_fleet_config_validation(model):
    with pytest.raises(ValueError, match="num_replicas"):
        FleetConfig(num_replicas=0).validate()
    with pytest.raises(ValueError, match="routing"):
        FleetConfig(routing="random").validate()
    with pytest.raises(ValueError, match="gossip_every"):
        FleetConfig(gossip_every=0).validate()
    with pytest.raises(ValueError, match="weight_gain"):
        FleetConfig(weight_gain=1.0).validate()
    fleet = _fleet(model, num_replicas=1)
    with pytest.raises(ValueError, match="1-D"):
        fleet.submit(np.zeros((2, 2), np.int32), 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        fleet.submit(_prompt(4), 0)
    with pytest.raises(ValueError, match="max_prompt_len"):
        fleet.submit(_prompt(9), 4)
    with pytest.raises(ValueError, match="tenant name"):
        fleet.submit(_prompt(4), 4, tenant="a,b")


# ---------------------------------------------------------------- routing
def test_affinity_beats_round_robin_exact_prefill_tokens(model):
    # acceptance pin (a): the SAME trace through both policies — two
    # warm families A/B, then a second wave of repeats. Affinity homes
    # every repeat on its warm replica; round-robin's rotation lands 2
    # of the 4 repeats on cold replicas and pays their full prefill.
    A, B = _prompt(8, seed=1), _prompt(8, seed=2)

    def run_policy(routing):
        fleet = _fleet(model, num_replicas=3, routing=routing)
        w1 = [fleet.submit(A, 3), fleet.submit(B, 3)]
        fleet.run()
        w2 = [fleet.submit(A, 3), fleet.submit(A, 3),
              fleet.submit(B, 3), fleet.submit(B, 3)]
        fleet.run()
        snap = fleet.metrics.snapshot()  # BEFORE the next fleet resets
        assert all(fleet.status(r) == "finished" for r in w1 + w2)
        return fleet, snap

    aff, aff_snap = run_policy("affinity")
    # wave 2 all warm: routed (not spilled), 8 gossiped warm tokens each
    w2_routes = sorted(aff.routes.items())[-4:]
    assert [(kind, tok) for _, (_, kind, tok) in w2_routes] == \
        [("routed", 8)] * 4
    assert aff_snap["serving_fleet_prefix_affinity_hits_total"] == 4
    assert aff_snap["serving_fleet_spills_total"] == 0
    assert aff_snap["serving_prefix_hits"] == 4

    rr, rr_snap = run_policy("round_robin")
    # rotation: wave 1 warms r0/r1; wave 2 [A->r2 cold, A->r0 warm,
    # B->r1 warm, B->r2 cold] — half the hits, never counted as
    # router affinity (round-robin ignores gossip by construction)
    assert rr_snap["serving_fleet_prefix_affinity_hits_total"] == 0
    assert rr_snap["serving_prefix_hits"] == 2

    aff_fill = aff_snap["serving_prefill_tokens_total"]
    rr_fill = rr_snap["serving_prefill_tokens_total"]
    aff_saved = aff_snap["serving_prefix_tokens_saved"]
    rr_saved = rr_snap["serving_prefix_tokens_saved"]
    assert aff_fill < rr_fill  # the headline: strictly fewer tokens
    # exact arithmetic: 48 prompt tokens either way — what one policy
    # saves the other prefills, and affinity saves exactly twice as
    # much (4 warm hits vs 2, same tokens saved per hit)
    assert aff_fill + aff_saved == rr_fill + rr_saved == 6 * 8
    assert aff_saved == 2 * rr_saved
    assert rr_fill - aff_fill == rr_saved > 0


def test_spillover_before_shed(model):
    # warm-but-full never sheds while a survivor has room: the order is
    # routed (warm) -> spilled (least-loaded) -> pending -> shed, and
    # the shed victim is always the NEWCOMER
    fleet = _fleet(model, num_replicas=2, max_replica_load=1,
                   max_pending=1)
    A = _prompt(8, seed=1)
    fleet.submit(A, 3)
    fleet.run()  # r0 is now the warm replica
    r1 = fleet.submit(A, 4)   # warm, room -> routed to r0
    r2 = fleet.submit(A, 4)   # warm replica full -> spill to r1
    r3 = fleet.submit(A, 4)   # both full -> router pending
    r4 = fleet.submit(A, 4)   # pending full -> shed the newcomer
    assert fleet.routes[r1][0:2] == (0, "routed")
    assert fleet.routes[r2][0:2] == (1, "spilled")
    assert fleet.status(r3) == "pending"
    assert fleet.status(r4) == SHED
    snap = fleet.metrics.snapshot()
    assert snap["serving_fleet_spills_total"] == 1
    assert snap["serving_shed"] == 1
    fleet.run()  # r3 dispatches once a replica frees; nothing else sheds
    assert fleet.status(r1) == fleet.status(r2) == \
        fleet.status(r3) == "finished"
    retired = fleet.pop_retired()
    assert retired[r4].state == SHED
    shed_j = [j for j in fleet.journeys() if j.rid == r4]
    assert len(shed_j) == 1 and shed_j[0].state == SHED
    wire = validate_journey(shed_j[0].to_wire())
    assert [h["kind"] for h in wire["hops"]] == \
        ["enqueue", "shed", "retire"]
    assert wire["hops"][1]["reason"] == "router_queue_full"
    assert fleet.metrics.snapshot()["serving_fleet_spills_total"] == 1


# -------------------------------------------------------------- admission
def test_burn_weighted_admission_golden(model):
    # acceptance pin (b): a tenant burning an unmeetable SLO gains
    # admission weight EXACTLY once per onset (the watchdog's edge
    # trigger is the dedupe), the gauge tracks it, and the fleet's
    # goodput/badput books still reconcile to the token counter
    fleet = _fleet(
        model, num_replicas=1,
        eng=dict(tenants={"victim": TenantSLO(ttft_p99_s=1e-9,
                                              tpot_p99_s=1e-9)},
                 watchdog=WatchdogConfig(slo_burn_window_steps=16,
                                         slo_burn_min_retired=4)))
    assert fleet.weight("victim") == 1.0
    for i in range(6):
        fleet.submit(_prompt(4, seed=i), 2, tenant="victim")
    fleet.run()
    assert [a.rule for a in fleet.alerts()] == ["slo_burn"]
    assert [(t, w) for _, t, w in fleet.weight_changes] == \
        [("victim", 2.0)]  # one onset -> one gain, not one per alert read
    assert fleet.weight("victim") == 2.0
    assert fleet.weight("default") == 1.0
    snap = fleet.metrics.snapshot()
    assert snap["serving_fleet_tenant_weight{tenant=victim}"] == 2.0
    assert snap["serving_fleet_tenant_weight{tenant=default}"] == 1.0
    # the ledger the weight is justified by still balances exactly
    good = sum(v for k, v in snap.items()
               if k.startswith("serving_tenant_goodput_tokens_total"))
    bad = sum(v for k, v in snap.items()
              if k.startswith("serving_tenant_badput_tokens_total"))
    assert good + bad == snap["serving_tokens_total"] > 0


def test_weighted_drain_orders_pending_by_tenant_weight(model):
    # with the burning tenant's weight raised, its pending requests
    # dispatch before earlier-arrived default ones — stable FIFO within
    # a weight class
    fleet = _fleet(model, num_replicas=1, max_replica_load=1)
    first = fleet.submit(_prompt(4, seed=0), 2)  # occupies the replica
    d1 = fleet.submit(_prompt(4, seed=1), 2)               # pending
    v1 = fleet.submit(_prompt(4, seed=2), 2, tenant="vip")  # pending
    fleet._actuate_weight("vip")  # as a live slo_burn alert would
    fleet.run()
    assert all(fleet.status(r) == "finished" for r in (first, d1, v1))
    # vip overtook the earlier default arrival at dispatch time
    order = sorted((rid, fleet.routes[rid]) for rid in (d1, v1))
    assert v1 > d1  # arrived later...
    vip_j = [j for j in fleet.journeys() if j.rid == v1][0]
    d_j = [j for j in fleet.journeys() if j.rid == d1][0]
    assert vip_j.admitted_t < d_j.admitted_t  # ...served earlier
    assert order  # routes recorded for both


# ------------------------------------------------------------ equivalence
def test_one_replica_fleet_bit_identical_to_bare_engine(model):
    prompts = [_prompt(5 + i % 3, seed=i) for i in range(3)]

    def outputs(build):
        box = build()
        rids = [box[1](p, 4) for p in prompts]
        outs = box[0].run()
        return [outs[r] for r in rids]

    bare = outputs(lambda: (lambda e: (e, e.add_request))(
        ServingEngine(model, ServingConfig(**_ENG),
                      clock=VirtualClock())))
    routed = outputs(lambda: (lambda f: (f, f.submit))(
        _fleet(model, num_replicas=1)))
    for a, b in zip(bare, routed):
        assert np.array_equal(a, b)


def test_sync_free_and_compile_counts_with_router_on(model):
    # the SyncTally certification formula (one token fetch per decode
    # step + one per completed prefill) holds FLEET-WIDE — routing,
    # gossip, and weighted drain never touch a device value — and every
    # replica stays at one compile per program (zero retraces)
    fleet = _fleet(model, num_replicas=3)
    A = _prompt(8, seed=1)
    for i in range(3):
        fleet.submit(_prompt(6, seed=i), 3)
    with SyncTally() as tally:
        fleet.run()
        fleet.submit(A, 3)
        fleet.submit(A, 3)  # a warm wave exercises the affinity path
        fleet.run()
    snap = fleet.metrics.snapshot()
    fetches = int(snap["serving_decode_steps"]
                  + snap["serving_prefills_total"])
    assert tally.count == fetches, (tally.events, fetches)
    for eng in fleet.replicas:
        assert eng.compile_counts == {"prefill": 1, "decode": 1}


# ------------------------------------------------------------------ faults
@pytest.mark.faults
def test_route_fail_sheds_with_clean_journey(model):
    inj = FaultInjector().arm("route_fail", step=0, times=1)
    fleet = _fleet(model, num_replicas=2, injector=inj)
    shed_rid = fleet.submit(_prompt(6, seed=0), 3)  # consumed by the arm
    ok_rid = fleet.submit(_prompt(6, seed=1), 3)
    assert fleet.status(shed_rid) == SHED
    fleet.run()
    assert fleet.status(ok_rid) == "finished"
    assert fleet.pop_retired()[shed_rid].state == SHED
    j = [j for j in fleet.journeys() if j.rid == shed_rid][0]
    wire = validate_journey(j.to_wire())
    assert wire["state"] == SHED and wire["tokens"] == 0
    assert wire["hops"][1]["reason"] == "route_fail"
    assert fleet.metrics.snapshot()["serving_shed"] == 1


@pytest.mark.faults
def test_replica_down_drains_waiters_to_survivors(model):
    # the drill: replica 0 dies at step 2 — its in-flight request
    # retires FAILED, its clean waiter re-homes to the survivor as a
    # spill under the SAME rid, the gauge drops, and every journey on
    # every book (including the dead replica's non-terminal half of the
    # re-homed pair) stays schema-clean
    inj = FaultInjector().arm("replica_down", step=2, rid=0)
    fleet = _fleet(model, num_replicas=2, max_replica_load=4,
                   eng=dict(max_batch=1), injector=inj)
    rids = [fleet.submit(_prompt(6, seed=i), 6) for i in range(4)]
    # cold least-loaded placement alternates: r0 gets rids[0] (running)
    # + rids[2] (waiting), r1 gets rids[1] + rids[3]
    assert [fleet.routes[r][0] for r in rids] == [0, 1, 0, 1]
    fleet.run()
    snap = fleet.metrics.snapshot()
    assert snap["serving_fleet_replicas"] == 1
    assert snap["serving_failed"] == 1
    assert snap["serving_fleet_spills_total"] == 1
    assert fleet.status(rids[0]) == FAILED  # in-flight on the dead replica
    for r in rids[1:]:
        assert fleet.status(r) == "finished"
    assert fleet.routes[rids[2]][0:2] == (1, "spilled")  # re-homed
    wires = [validate_journey(j.to_wire()) for j in fleet.journeys()]
    halves = sorted((w["state"] is None) for w in wires
                    if w["rid"] == rids[2])
    assert halves == [False, True]  # dead-replica half stays
    # non-terminal; the survivor's carries the real retirement
    dead_half = [w for w in wires
                 if w["rid"] == rids[2] and w["state"] is None][0]
    spill_hops = [h for h in dead_half["hops"] if h["kind"] == "spilled"]
    assert spill_hops and spill_hops[0]["reason"] == "replica_down"
    assert [w["state"] for w in wires if w["rid"] == rids[0]] == [FAILED]


# --------------------------------------------------------------- simulator
def test_simulator_replay_reproduces_live_classes(model, tmp_path, capsys):
    # acceptance pin (c): re-classifying the journey dump through a
    # fresh ledger reproduces the live per-tenant retirement-class
    # counts EXACTLY — including the router's own shed retirements
    fleet = _fleet(
        model, num_replicas=2, max_replica_load=1, max_pending=1,
        eng=dict(tenants={
            "interactive": TenantSLO(ttft_p99_s=1e6, tpot_p99_s=1e6),
            "batch": TenantSLO(ttft_p99_s=1e-9, tpot_p99_s=1e-9)}))
    for i in range(3):
        fleet.submit(_prompt(6, seed=i), 3, tenant="interactive")
        fleet.submit(_prompt(6, seed=10 + i), 3, tenant="batch")
    fleet.run()
    live = fleet.retirement_class_counts()
    assert sum(sum(row.values()) for row in live.values()) == 6
    dump = fleet.journey_dump()
    replay = replay_classes(dump, slos=dict(fleet.config.engine.tenants))
    for tenant, row in live.items():
        if any(row.values()):
            assert replay[tenant] == row
        else:  # zero-traffic tenants never appear in a dump
            assert tenant not in replay
    assert any(v for row in replay.values() for v in row.values())
    # the what-if projection: every served request replays, queueing is
    # non-negative, and fewer slots can only lengthen the makespan
    one = simulate(dump, replicas=1, slots=1)
    two = simulate(dump, replicas=2, slots=2)
    assert one["served"] == two["served"] > 0
    assert one["makespan_s"] >= two["makespan_s"]
    assert all(row["queue_delay_max_s"] >= 0.0
               for row in one["tenants"].values())
    # the CLI round-trips a dump file
    path = tmp_path / "dump.json"
    path.write_text(json.dumps(dump))
    assert sim_main([str(path), "--replicas", "2", "--slots", "2",
                     "--slo", "batch=0.000000001:0.000000001",
                     "--weight", "batch=2.0"]) == 0
    out = capsys.readouterr().out
    assert "replayed retirement classes" in out and "what-if" in out


def test_chrome_export_merges_one_track_per_replica(model, tmp_path):
    fleet = _fleet(model, num_replicas=2)
    fleet.submit(_prompt(6, seed=0), 3)
    fleet.submit(_prompt(6, seed=1), 3)
    fleet.run()
    path = tmp_path / "fleet.json"
    doc = fleet.export_chrome_trace(path)
    assert {e["pid"] for e in doc["traceEvents"]} == {1, 2}
    names = sorted(e["args"]["name"] for e in doc["traceEvents"]
                   if e.get("ph") == "M" and e["name"] == "process_name")
    assert names == ["paddle_tpu.serving/replica0",
                     "paddle_tpu.serving/replica1"]
    assert json.loads(path.read_text())["traceEvents"]
