"""Second parity batch: vision transforms, incubate ops/optimizers, device,
distribution registry, io worker info, fleet/distributed exports."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def test_submodule_surfaces_complete():
    import ast
    import os

    def get_all(path):
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        return [e.value for e in node.value.elts
                                if isinstance(e, ast.Constant)]

    ref = "/root/reference/python/paddle/"
    for sub, mp in [("vision.transforms", "vision/transforms/__init__.py"),
                    ("vision.models", "vision/models/__init__.py"),
                    ("optimizer.lr", "optimizer/lr.py"),
                    ("io", "io/__init__.py"),
                    ("distribution", "distribution/__init__.py"),
                    ("jit", "jit/__init__.py"),
                    ("distributed", "distributed/__init__.py"),
                    ("distributed.fleet", "distributed/fleet/__init__.py"),
                    ("utils", "utils/__init__.py"),
                    ("incubate", "incubate/__init__.py"),
                    ("device", "device/__init__.py")]:
        names = get_all(os.path.join(ref, mp))
        mod = paddle_tpu
        for part in sub.split("."):
            mod = getattr(mod, part)
        missing = sorted(n for n in names if not hasattr(mod, n))
        assert missing == [], (sub, missing)


import paddle_tpu  # noqa: E402


def test_segment_ops_match_manual():
    from paddle_tpu import incubate

    data = Tensor(np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]], np.float32))
    ids = Tensor(np.array([0, 0, 1, 1]))
    np.testing.assert_allclose(
        np.asarray(incubate.segment_sum(data, ids)._value), [[4, 6], [12, 14]])
    np.testing.assert_allclose(
        np.asarray(incubate.segment_mean(data, ids)._value), [[2, 3], [6, 7]])
    np.testing.assert_allclose(
        np.asarray(incubate.segment_max(data, ids)._value), [[3, 4], [7, 8]])
    np.testing.assert_allclose(
        np.asarray(incubate.segment_min(data, ids)._value), [[1, 2], [5, 6]])
    # empty segment -> 0 (reference convention), not -inf
    ids2 = Tensor(np.array([0, 0, 2, 2]))
    out = np.asarray(incubate.segment_max(data, ids2)._value)
    np.testing.assert_allclose(out[1], [0, 0])


def test_graph_send_recv():
    from paddle_tpu import incubate

    x = Tensor(np.array([[1.], [2.], [4.]], np.float32))
    src = Tensor(np.array([0, 1, 2, 0]))
    dst = Tensor(np.array([1, 2, 1, 0]))
    out = np.asarray(incubate.graph_send_recv(x, src, dst, "sum")._value)
    np.testing.assert_allclose(out, [[1.], [5.], [2.]])
    out = np.asarray(incubate.graph_send_recv(x, src, dst, "mean")._value)
    np.testing.assert_allclose(out, [[1.], [2.5], [2.]])


def test_softmax_mask_fuse_upper_triangle_is_causal():
    from paddle_tpu import incubate

    x = Tensor(np.zeros((1, 1, 4, 4), np.float32))
    out = np.asarray(incubate.softmax_mask_fuse_upper_triangle(x)._value)
    np.testing.assert_allclose(out[0, 0, 0], [1, 0, 0, 0], atol=1e-6)
    np.testing.assert_allclose(out[0, 0, 3], [0.25] * 4, atol=1e-6)


def test_lookahead_and_model_average():
    from paddle_tpu import nn
    from paddle_tpu.incubate import LookAhead, ModelAverage

    paddle.seed(0)
    m = nn.Linear(4, 4)
    inner = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    opt = LookAhead(inner, alpha=0.5, k=2)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    w0 = np.asarray(m.weight._value).copy()
    for _ in range(4):
        loss = (m(x) * m(x)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert not np.allclose(np.asarray(m.weight._value), w0)

    ma = ModelAverage(0.5, parameters=m.parameters())
    w_pre = np.asarray(m.weight._value).copy()
    ma.step()
    ma.apply()
    w_avg = np.asarray(m.weight._value).copy()
    ma.restore()
    np.testing.assert_allclose(np.asarray(m.weight._value), w_pre)
    np.testing.assert_allclose(w_avg, w_pre, rtol=1e-5)  # 1-step avg == current


def test_device_module():
    from paddle_tpu import device

    assert device.is_compiled_with_cuda() is False
    assert device.get_cudnn_version() is None
    assert "cpu" in device.get_all_device_type()
    assert device.get_available_device()
    assert isinstance(device.XPUPlace(0), paddle.TPUPlace)


def test_vision_transform_classes_run():
    from paddle_tpu.vision import transforms as T

    img = (np.random.RandomState(0).rand(3, 16, 16) * 255).astype(np.uint8)
    pipeline = T.Compose([
        T.ColorJitter(0.2, 0.2, 0.2, 0.1), T.RandomRotation(15),
        T.RandomResizedCrop(8), T.Grayscale(3)])
    out = pipeline(img)
    assert out.shape == (3, 8, 8)
    np.testing.assert_array_equal(T.hflip(img), img[:, :, ::-1])
    np.testing.assert_array_equal(T.vflip(img), img[:, ::-1])
    assert T.center_crop(img, 8).shape == (3, 8, 8)
    assert T.pad(img, 2).shape == (3, 20, 20)


@pytest.mark.slow
def test_voc2012_and_vgg_variants():
    from paddle_tpu.vision.datasets import VOC2012
    from paddle_tpu.vision.models import vgg11, vgg13

    ds = VOC2012(synthetic_size=4)
    img, mask = ds[0]
    assert img.shape == (3, 64, 64) and mask.shape == (64, 64)
    assert mask.max() >= 1
    m = vgg11(num_classes=10)
    n_convs = sum(1 for lyr in m.sublayers()
                  if type(lyr).__name__ == "Conv2D")
    assert n_convs == 8  # VGG-A has 8 conv layers


def test_get_worker_info_inside_workers():
    from paddle_tpu.io import DataLoader, get_worker_info

    assert get_worker_info() is None  # main process

    class DS:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            from paddle_tpu.io import get_worker_info as gwi

            info = gwi()
            assert info is not None and info.num_workers == 2
            return np.asarray([info.id], np.int64)

    dl = DataLoader(DS(), batch_size=2, num_workers=2, use_shared_memory=False)
    seen = [np.asarray(b[0] if isinstance(b, (list, tuple)) else b)
            for b in dl]
    assert len(seen) == 4


def test_program_translator_disables_to_static():
    from paddle_tpu import jit

    calls = []

    @jit.to_static
    def f(x):
        calls.append(1)
        return x * 2

    x = paddle.to_tensor(np.ones((2,), np.float32))
    jit.ProgramTranslator().enable(False)
    try:
        out = f(x)
        np.testing.assert_allclose(np.asarray(out._value), 2.0)
    finally:
        jit.ProgramTranslator().enable(True)


def test_distributed_split_and_entries():
    from paddle_tpu import distributed as dist

    x = paddle.to_tensor(np.ones((2, 8), np.float32))
    out = dist.split(x, (8, 4), operation="linear", axis=1)
    assert list(out.shape) == [2, 4]
    emb = dist.split(paddle.to_tensor(np.array([[1, 2]], np.int64)),
                     (16, 6), operation="embedding")
    assert list(emb.shape) == [1, 2, 6]
    assert "count_filter" in dist.CountFilterEntry(3).attr()
    assert dist.ParallelMode.TENSOR_PARALLEL == 1
    with pytest.raises(ValueError):
        dist.ProbabilityEntry(2.0)


def test_utils_helpers():
    from paddle_tpu import utils

    @utils.deprecated(update_to="paddle.new_api", since="2.0")
    def old():
        return 7

    with pytest.warns(DeprecationWarning):
        assert old() == 7
    utils.require_version("0.0.1")
    with pytest.raises(RuntimeError):
        utils.require_version("99.0.0")


def test_multiplicative_decay():
    from paddle_tpu.optimizer.lr import MultiplicativeDecay

    sched = MultiplicativeDecay(1.0, lambda e: 0.5)
    lrs = []
    for _ in range(3):
        lrs.append(sched())
        sched.step()
    assert lrs[0] == pytest.approx(1.0)
    assert lrs[1] == pytest.approx(0.5)
    assert lrs[2] == pytest.approx(0.25)
