"""FleetExecutor actor-runtime tests (reference analog:
fleet_executor/test/{compute_interceptor_test.cc, interceptor_pipeline_test.cc,
source_interceptor_test.cc})."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet_executor import (
    FleetExecutor, MessageBus, TaskNode,
)


def _chain(n_micro, fns, buffer_size=2, ranks=None):
    """source -> compute... -> sink chain."""
    nodes = [TaskNode(0, rank=0, max_run_times=n_micro, type="Source",
                      run_fn=lambda i: i)]
    for k, fn in enumerate(fns, start=1):
        r = ranks[k] if ranks else 0
        nodes.append(TaskNode(k, rank=r, max_run_times=n_micro, type="Compute",
                              run_fn=fn))
    nodes.append(TaskNode(len(fns) + 1, rank=ranks[-1] if ranks else 0,
                          max_run_times=n_micro, type="Sink"))
    for a, b in zip(nodes, nodes[1:]):
        a.add_downstream_task(b.task_id, buffer_size)
        b.add_upstream_task(a.task_id, buffer_size)
    return nodes


def test_source_compute_sink_chain():
    nodes = _chain(6, [lambda x: x * 2, lambda x: x + 1])
    exe = FleetExecutor(nodes)
    results = exe.run()
    assert results == [i * 2 + 1 for i in range(6)]


def test_credit_backpressure_limits_inflight():
    """With buffer_size=1 the source can never run ahead by more than one
    micro-batch (the reference's flow-control invariant)."""
    inflight, peak = [0], [0]

    def slow_stage(x):
        inflight[0] += 1
        peak[0] = max(peak[0], inflight[0])
        import time

        time.sleep(0.005)
        inflight[0] -= 1
        return x

    nodes = _chain(8, [slow_stage], buffer_size=1)
    results = FleetExecutor(nodes).run()
    assert results == list(range(8))
    assert peak[0] <= 1


def test_multi_carrier_cross_rank():
    """Stages on different ranks (carriers) exchanging via the bus."""
    nodes = _chain(5, [lambda x: x + 10, lambda x: x * 3],
                   ranks={1: 0, 2: 1, -1: 1})
    exe = FleetExecutor(nodes)
    results = exe.run()
    assert results == [(i + 10) * 3 for i in range(5)]
    assert len(exe.carriers) == 2


def test_amplifier_gradient_accumulation():
    """Amplifier forwards downstream only every N runs (grad-accum fan-in)."""
    from paddle_tpu.distributed.fleet_executor import AmplifierInterceptor

    acc = []

    def accumulate(x):
        acc.append(x)
        return sum(acc)

    n_micro = 6
    src = TaskNode(0, max_run_times=n_micro, type="Source", run_fn=lambda i: 1)
    amp = TaskNode(1, max_run_times=n_micro, type="Amplifier",
                   run_fn=accumulate, send_down_per_steps=3)
    sink = TaskNode(2, max_run_times=n_micro // 3, type="Sink")
    src.add_downstream_task(1, 8)
    amp.add_upstream_task(0, 8)
    amp.add_downstream_task(2, 8)
    sink.add_upstream_task(1, 8)

    exe = FleetExecutor([src, amp, sink])
    assert isinstance(exe.carriers[0]._interceptors[1], AmplifierInterceptor)
    results = exe.run()
    assert results == [3, 6]  # partial sums after 3 and 6 accumulations


def test_amplifier_run_per_steps_fanout():
    """run_per_steps=2: each upstream payload is executed twice."""
    seen = []

    def record(x):
        seen.append(x)
        return x

    src = TaskNode(0, max_run_times=3, type="Source", run_fn=lambda i: i)
    amp = TaskNode(1, max_run_times=6, type="Amplifier", run_fn=record,
                   run_per_steps=2)
    sink = TaskNode(2, max_run_times=6, type="Sink")
    src.add_downstream_task(1, 4)
    amp.add_upstream_task(0, 4)
    amp.add_downstream_task(2, 8)
    sink.add_upstream_task(1, 8)
    results = FleetExecutor([src, amp, sink]).run()
    assert seen == [0, 0, 1, 1, 2, 2]
    assert results == [0, 0, 1, 1, 2, 2]


def test_pipeline_with_jit_stages():
    """Host-driven 2-stage model pipeline: each stage is a jitted step."""
    import jax
    import jax.numpy as jnp

    w1 = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)
    w2 = jnp.asarray(np.random.RandomState(1).randn(8, 2), jnp.float32)

    @jax.jit
    def stage1(x):
        return jnp.tanh(x @ w1)

    @jax.jit
    def stage2(h):
        return h @ w2

    batches = [np.random.RandomState(i).randn(3, 4).astype("float32")
               for i in range(4)]
    nodes = _chain(4, [lambda x: stage1(x), lambda h: stage2(h)])
    # source feeds real data
    nodes[0].run_fn = lambda i: jnp.asarray(batches[i])
    results = FleetExecutor(nodes).run()
    for i, out in enumerate(results):
        expect = np.tanh(batches[i] @ np.asarray(w1)) @ np.asarray(w2)
        np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)


def test_remote_message_bus_over_tcp():
    """Two FleetExecutors (disjoint local_ranks) exchanging over the TCP bus —
    the multi-host path (reference: message_bus.cc brpc channel)."""
    nodes_spec = lambda: _chain(4, [lambda x: x + 100], ranks={1: 1, -1: 1})

    bus_a, bus_b = MessageBus(), MessageBus()
    exe_a = FleetExecutor(nodes_spec(), bus=bus_a, local_ranks={0})
    exe_b = FleetExecutor(nodes_spec(), bus=bus_b, local_ranks={1})
    srv_a, port_a = bus_a.serve()
    srv_b, port_b = bus_b.serve()
    bus_a.register_remote(1, f"127.0.0.1:{port_b}")
    bus_b.register_remote(0, f"127.0.0.1:{port_a}")

    import threading

    results = {}

    def run_b():
        results["b"] = exe_b.run(timeout=30)

    tb = threading.Thread(target=run_b)
    tb.start()
    exe_a.run(timeout=30)  # rank 0 holds only the source
    tb.join(timeout=35)
    assert results["b"] == [i + 100 for i in range(4)]
    srv_a.shutdown(); srv_b.shutdown()
    bus_a.close(); bus_b.close()
