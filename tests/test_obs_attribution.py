"""Goodput attribution layer: per-phase step accounting, live MFU /
roofline drift, anomaly watchdogs, and the black-box flight recorder.

Five layers of coverage:

- attribution exactness: per-phase times sum to the step's wall time on a
  virtual clock (exact — the PhaseAccumulator mark construction), and the
  phase vocabulary matches what the step actually did.
- roofline math: MFU / bandwidth-utilization / drift goldens on the
  tracker alone, then the engine-level gauges computed from the engine's
  OWN hlocheck audits (no second lowering) under ``debug_checks``.
- watchdogs: every rule fired deterministically exactly once (synthetic
  step feeds for the windowed rules, live engines for queue_stall and
  pallas_fallback) and quiescent on a clean run; zero added host syncs
  (the SyncTally formula is byte-identical with attribution + watchdogs
  ON, pinned here as in bench and the demo).
- flight recorder: ring bound, dump schema, the automatic dumps on
  request failure (every ``-m faults`` scenario doubles as a recorder
  test), on engine-fatal exceptions (the step ring flushed BEFORE the
  re-raise — the satellite fix), and on the stuck-engine backstop
  (a ``pool_exhausted`` preemption livelock).
- surfaces: Chrome counter tracks + alert instants schema, labeled-family
  pre-seeding and Prometheus rendering, CLI exit codes 0/1/2.

Everything runs on a virtual clock — sleep-free, deterministic.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import SyncTally
from paddle_tpu.obs import (ALERT_RULES, PHASES, PhaseAccumulator,
                            RooflineTracker, StepRecord, Watchdog,
                            WatchdogConfig, validate_flight_record)
from paddle_tpu.obs.__main__ import main as obs_main
from paddle_tpu.serving import FaultInjector, ServingConfig, ServingEngine
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.utils import monitor

pytestmark = pytest.mark.obs


class VirtualClock:
    """Integer-stepped fake engine clock: 1.0 s per read, so phase sums
    are EXACT float arithmetic (no rounding slop to hide behind)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


@pytest.fixture(scope="module")
def model():
    paddle.seed(29)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=48, dropout=0.0))
    m.eval()
    return m


def _engine(model, clock=None, fault_injector=None, **overrides):
    kw = dict(max_batch=2, num_pages=20, page_size=4, max_prompt_len=8)
    kw.update(overrides)
    return ServingEngine(model, ServingConfig(**kw),
                         clock=clock or VirtualClock(),
                         fault_injector=fault_injector)


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 97, (n,)).astype(np.int32)


def _record(step, queue_depth=0, admitted=0, batch=0, chunks=0):
    return StepRecord(step=step, t_start=float(step), t_end=step + 1.0,
                      admitted=admitted, prefills=0, batch=batch,
                      finished=0, preemptions=0, queue_depth=queue_depth,
                      pages_in_use=0, chunks=chunks)


# ------------------------------------------------------- phase attribution
def test_phase_accumulator_marks_and_exact_sum():
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    acc = PhaseAccumulator(clock)
    t0 = acc.begin()
    assert acc.open and t0 == 1.0
    assert acc.mark("admit") == 1.0
    assert acc.mark("decode", t=5.0) == 3.0
    assert acc.mark("decode", t=6.0) == 1.0  # accumulates, not replaces
    t_end, phases = acc.finish(t=10.0)
    assert not acc.open
    assert phases == {"admit": 1.0, "decode": 4.0, "other": 4.0}
    assert sum(phases.values()) == t_end - t0


def test_engine_phase_times_sum_to_step_wall_time_exactly(model):
    engine = _engine(model)
    for i in range(3):
        engine.add_request(_prompt(5, seed=i), 6)
    engine.run()
    records = engine.timeline.records()
    assert records
    for rec in records:
        assert sum(rec.phase_s.values()) == rec.duration, rec
        assert set(rec.phase_s) <= set(PHASES)
    # a decoding step attributes decode time; admission work is visible
    assert any(rec.phase_s.get("decode", 0) > 0 for rec in records)
    assert any(rec.phase_s.get("prefill", 0) > 0 for rec in records)
    assert all(rec.phase_s.get("admit", 0) > 0 for rec in records)


def test_phase_family_histograms_fed_and_pre_seeded(model):
    engine = _engine(model)
    snap = engine.metrics.snapshot()
    # presence before the first step, for every phase label
    for phase in PHASES:
        assert snap[f"serving_step_phase_s_count{{phase={phase}}}"] == 0
    engine.add_request(_prompt(5), 4)
    engine.run()
    snap = engine.metrics.snapshot()
    assert snap["serving_step_phase_s_count{phase=decode}"] > 0
    assert snap["serving_step_phase_s_p99{phase=decode}"] > 0
    # prometheus renders the family as real labeled bucket series (the
    # label-set renderer emits sorted k="v" pairs: le before phase)
    prom = engine.metrics.prometheus()
    assert '_bucket{le="' in prom and ',phase="decode"}' in prom
    assert "# TYPE serving_step_phase_s histogram" in prom


# ------------------------------------------------------------ roofline math
def test_roofline_tracker_goldens():
    rt = RooflineTracker(peak_flops_per_s=100.0, peak_hbm_bytes_per_s=1000.0)
    rt.on_program("decode", flops=100.0, hbm_bytes=1000.0)
    assert rt.predicted_step_s("decode") == 1.0  # both roofs bind at 1 s
    assert rt.predicted_step_s("unknown") is None
    rt.on_call("decode", 2.0)
    g = rt.gauges()
    # 100 flops in 2 s = 50 flops/s against a 100 flops/s peak
    assert g["mfu"] == pytest.approx(0.5)
    assert g["hbm_bw_util"] == pytest.approx(0.5)
    assert g["drift"]["decode"] == pytest.approx(2.0)


def test_roofline_kernel_ab_measured_vs_banked():
    rt = RooflineTracker(banked_kernels={"paged_decode": 1.5})
    assert rt.gauges()["kernels"]["paged_decode"] == {"predicted": 1.5}
    rt.on_kernel_call("paged_decode", 1.0, pallas=True)
    assert "measured" not in rt.gauges()["kernels"]["paged_decode"]
    rt.on_kernel_call("paged_decode", 3.0, pallas=False)
    entry = rt.gauges()["kernels"]["paged_decode"]
    # composite mean 3 s / kernel mean 1 s = 3x measured vs 1.5x banked
    assert entry["measured"] == pytest.approx(3.0)
    assert entry["drift"] == pytest.approx(2.0)


def test_engine_mfu_and_drift_from_own_audits(model):
    engine = _engine(model, debug_checks=True)
    snap = engine.metrics.snapshot()
    assert snap["serving_mfu"] == 0  # pre-seeded presence
    assert snap["serving_hbm_bw_util"] == 0
    assert snap["serving_cost_model_drift{program=decode}"] == 0
    assert snap["serving_cost_model_drift{program=prefill[8]}"] == 0
    for i in range(2):
        engine.add_request(_prompt(5, seed=i), 5)
    engine.run()
    snap = engine.metrics.snapshot()
    # the gauges divide measured dispatch time by the flops/HBM model the
    # engine's own first-trace hlocheck audits hold — both sides known
    assert set(engine.hlo_audits) == {"prefill[8]", "decode"}
    assert snap["serving_mfu"] > 0
    assert snap["serving_hbm_bw_util"] > 0
    assert snap["serving_cost_model_drift{program=decode}"] > 0
    assert snap["serving_cost_model_drift{program=prefill[8]}"] > 0


def test_mfu_stays_zero_without_audits(model):
    # no debug_checks -> no hlocheck audits -> no prediction side: the
    # gauges stay at their seeded zeros instead of inventing numbers
    engine = _engine(model)
    engine.add_request(_prompt(5), 4)
    engine.run()
    snap = engine.metrics.snapshot()
    assert snap["serving_mfu"] == 0
    assert snap["serving_cost_model_drift{program=decode}"] == 0


# --------------------------------------------------------------- watchdogs
def test_watchdog_retrace_and_fallback_rules_edge_trigger():
    wd = Watchdog(WatchdogConfig(warmup_steps=2))
    # a retrace during warmup only moves the baseline
    assert wd.on_step(_record(0), {"retraces": 1}) == []
    assert wd.on_step(_record(1), {"retraces": 1}) == []
    fired = wd.on_step(_record(2), {"retraces": 2})
    assert [a.rule for a in fired] == ["retrace_after_warmup"]
    # persisting at the new total stays quiet; growth fires again
    assert wd.on_step(_record(3), {"retraces": 2}) == []
    fired = wd.on_step(_record(4), {"retraces": 3, "fallbacks": 1})
    assert sorted(a.rule for a in fired) == ["pallas_fallback",
                                             "retrace_after_warmup"]
    assert wd.fired_total["retrace_after_warmup"] == 2


def test_watchdog_acceptance_collapse_latches():
    cfg = WatchdogConfig(acceptance_floor=0.5, acceptance_min_proposed=8,
                         acceptance_window_steps=4)
    wd = Watchdog(cfg)
    # 8 proposed / 1 accepted inside the window -> collapse, fired ONCE
    assert wd.on_step(_record(0), {"proposed": 4, "accepted": 1}) == []
    fired = wd.on_step(_record(1), {"proposed": 8, "accepted": 1})
    assert [a.rule for a in fired] == ["spec_acceptance_collapse"]
    assert wd.on_step(_record(2), {"proposed": 12, "accepted": 1}) == []
    # a healthy window re-arms, a second collapse fires again
    for step, (p, a) in enumerate([(24, 13), (36, 25), (48, 37),
                                   (60, 49)], start=3):
        assert wd.on_step(_record(step), {"proposed": p, "accepted": a}) \
            == []
    fired = wd.on_step(_record(9), {"proposed": 120, "accepted": 49})
    assert [a.rule for a in fired] == ["spec_acceptance_collapse"]


def test_watchdog_thrash_and_stall_rules():
    cfg = WatchdogConfig(thrash_window_steps=4, thrash_events=6,
                         stall_steps=3)
    wd = Watchdog(cfg)
    assert wd.on_step(_record(0), {"evictions": 3}) == []
    fired = wd.on_step(_record(1), {"evictions": 4, "spills": 2})
    assert [a.rule for a in fired] == ["eviction_thrash"]
    # the window cleared: the same totals don't re-fire
    assert wd.on_step(_record(2), {"evictions": 4, "spills": 2}) == []
    # queue stall: 3 consecutive no-progress steps with waiters
    assert wd.on_step(_record(3, queue_depth=2), {}) == []
    assert wd.on_step(_record(4, queue_depth=2), {}) == []
    fired = wd.on_step(_record(5, queue_depth=2), {})
    assert [a.rule for a in fired] == ["queue_stall"]
    # a persisting stall does NOT re-fire (edge, not level)
    assert wd.on_step(_record(6, queue_depth=2), {}) == []
    # progress resets the streak; a NEW stall episode fires again
    assert wd.on_step(_record(7, queue_depth=2, admitted=1), {}) == []
    for step in (8, 9):
        assert wd.on_step(_record(step, queue_depth=1), {}) == []
    assert [a.rule for a in
            wd.on_step(_record(10, queue_depth=1), {})] == ["queue_stall"]


def test_engine_queue_stall_fires_once_and_counts(model):
    engine = _engine(model, watchdog=WatchdogConfig(stall_steps=3))
    engine.add_request(_prompt(5), 4)
    engine.admit_paused = True  # wedge: queued work, no admission
    for _ in range(6):
        engine.step()
    alerts = engine.alerts()
    assert [a.rule for a in alerts] == ["queue_stall"]  # exactly once
    snap = engine.metrics.snapshot()
    assert snap["serving_alerts_total{rule=queue_stall}"] == 1
    # the firing renders as a global instant on the engine track
    doc = engine.export_chrome_trace()
    instants = [e for e in doc["traceEvents"]
                if e["ph"] == "i" and e["name"] == "alert:queue_stall"]
    assert len(instants) == 1 and instants[0]["s"] == "g"


def test_engine_pallas_fallback_watchdog_fires(model):
    engine = _engine(model)
    engine.add_request(_prompt(5), 3)
    engine.step()
    # simulate a dispatch degrading mid-serve: the kernel layer counts
    # the pre-seeded gauge, the watchdog sees the delta next boundary
    monitor.stat_add("serving_pallas_fallback_total", 1)
    engine.run()
    assert [a.rule for a in engine.alerts()] == ["pallas_fallback"]
    assert engine.metrics.snapshot()[
        "serving_alerts_total{rule=pallas_fallback}"] == 1


def test_clean_run_is_quiescent_and_families_pre_seeded(model):
    engine = _engine(model)
    snap = engine.metrics.snapshot()
    for rule in ALERT_RULES:  # presence before anything happens
        assert snap[f"serving_alerts_total{{rule={rule}}}"] == 0
    for i in range(3):
        engine.add_request(_prompt(5, seed=i), 5)
    engine.run()
    assert engine.alerts() == []
    snap = engine.metrics.snapshot()
    assert all(v == 0 for k, v in snap.items()
               if k.startswith("serving_alerts_total"))


def test_attribution_and_watchdogs_add_zero_host_syncs(model):
    # the acceptance pin: the SyncTally certification formula (one token
    # fetch per decode step + one per completed prefill) is UNCHANGED
    # with attribution + watchdogs ON — they are clock reads and host
    # dict lookups only
    engine = _engine(model)
    assert engine.config.enable_tracing and engine.config.enable_watchdogs
    for i in range(3):
        engine.add_request(_prompt(4, seed=i), 4)
    with SyncTally() as tally:
        engine.run()
    snap = engine.metrics.snapshot()
    fetches = int(snap["serving_decode_steps"]
                  + snap["serving_prefills_total"])
    assert tally.count == fetches, (tally.events, fetches)
    assert engine.timeline.records()[-1].phase_s  # attribution really on


def test_obs_off_surfaces_are_none_and_watchdog_off(model):
    engine = _engine(model, enable_tracing=False)
    assert engine._attr is None and engine._watchdog is None
    assert engine.alerts() == []
    engine.add_request(_prompt(5), 3)
    engine.run()
    rec = engine.flight_record()
    assert rec["steps"] == []  # documented: no ring with tracing off


# ---------------------------------------------------------- flight recorder
def test_flight_record_schema_ring_bound_and_dump(model, tmp_path):
    engine = _engine(model, flight_record_steps=4, debug_checks=True)
    for i in range(3):
        engine.add_request(_prompt(5, seed=i), 6)
    engine.run()
    assert len(engine.timeline) > 4
    path = tmp_path / "dump.json"
    rec = engine.dump_flight_record(path)
    validate_flight_record(rec)
    assert rec["reason"] == "manual"
    assert len(rec["steps"]) == 4  # the ring bound
    # the newest records, with their attribution riding along
    assert rec["steps"][-1]["step"] == engine.timeline.last.step
    assert rec["steps"][-1]["phase_s"]
    assert set(rec["programs"]) == {"prefill[8]", "decode"}
    assert rec["requests"] and rec["requests"][-1]["state"] == "finished"
    loaded = validate_flight_record(json.loads(path.read_text()))
    assert loaded["steps"] == json.loads(json.dumps(rec))["steps"]


def test_fault_failure_auto_dumps_flight_record(model, tmp_path):
    path = tmp_path / "auto.json"
    inj = FaultInjector().arm("decode_fail", step=2)
    engine = _engine(model, fault_injector=inj,
                     flight_record_path=str(path))
    for i in range(2):
        engine.add_request(_prompt(5, seed=i), 6)
    engine.run()
    assert engine.last_flight_record is not None
    assert engine.last_flight_record["reason"] == "request-failure"
    loaded = validate_flight_record(json.loads(path.read_text()))
    assert any(r["state"] == "failed" for r in loaded["requests"])


def test_engine_fatal_flushes_partial_step_into_ring(model):
    # the satellite fix: a step dying mid-body used to vanish — now the
    # open attribution closes into a partial StepRecord (extra names the
    # fatal) and the flight record dumps BEFORE the re-raise
    engine = _engine(model)
    engine.add_request(_prompt(5), 6)
    engine.step()
    n_before = len(engine.timeline)

    def boom(*args, **kwargs):
        raise RuntimeError("induced decode failure")

    engine._decode_jit = boom
    with pytest.raises(RuntimeError, match="induced decode failure"):
        engine.step()
    records = engine.timeline.records()
    assert len(records) == n_before + 1
    fatal = records[-1]
    assert fatal.extra["fatal"].startswith("RuntimeError")
    assert sum(fatal.phase_s.values()) == fatal.duration  # still exact
    rec = engine.last_flight_record
    assert rec is not None and rec["reason"] == "engine-fatal: RuntimeError"
    validate_flight_record(rec)
    assert rec["steps"][-1]["extra"]["fatal"].startswith("RuntimeError")


def test_engine_fatal_after_step_body_keeps_completed_record(model):
    # the debug sweep (check_invariants) runs AFTER _step returned: the
    # attribution is closed and the full step stats are built but not
    # yet appended — a fatal there must flush THAT record (real counts,
    # extra names the fatal), not silently drop the step that broke the
    # engine
    engine = _engine(model, debug_checks=True)
    engine.add_request(_prompt(5), 6)
    engine.step()
    n_before = len(engine.timeline)

    def boom():
        raise RuntimeError("induced invariant failure")

    engine.cache.check_invariants = boom
    with pytest.raises(RuntimeError, match="induced invariant failure"):
        engine.step()
    records = engine.timeline.records()
    assert len(records) == n_before + 1
    fatal = records[-1]
    assert fatal.extra["fatal"].startswith("RuntimeError")
    assert fatal.batch == 1  # the completed step's REAL counts survive
    assert sum(fatal.phase_s.values()) == fatal.duration
    assert engine._step_stats is None  # no stale handoff for a later step
    rec = engine.last_flight_record
    assert rec is not None and rec["reason"] == "engine-fatal: RuntimeError"
    assert rec["steps"][-1]["extra"]["fatal"].startswith("RuntimeError")


def test_pool_exhausted_livelock_dumps_on_stuck_backstop(model, tmp_path):
    # a pool_exhausted fault armed every step preempts the victim before
    # it ever decodes: admit -> prefill -> preempt forever. The stuck-
    # engine backstop fires, and the black box captures the preemption
    # storm that explains it.
    path = tmp_path / "stuck.json"
    inj = FaultInjector().arm("pool_exhausted", times=-1)
    engine = _engine(model, fault_injector=inj,
                     flight_record_path=str(path))
    engine.add_request(_prompt(5), 6)
    with pytest.raises(RuntimeError, match="exceeded"):
        engine.run(max_steps=6)
    rec = validate_flight_record(json.loads(path.read_text()))
    assert rec["reason"] == "stuck-engine"
    assert sum(s["preemptions"] for s in rec["steps"]) >= 5
    assert engine.last_flight_record["reason"] == "stuck-engine"


# ------------------------------------------------------ exporters + CLI
def test_chrome_counter_tracks_schema(model):
    engine = _engine(model)
    engine.add_request(_prompt(5), 4)
    engine.run()
    doc = engine.export_chrome_trace()
    json.loads(json.dumps(doc))
    counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert {e["name"] for e in counters} == {"pages_in_use", "batch",
                                             "queue_depth"}
    # one sample per track per retained step, single numeric series each
    assert len(counters) == 3 * len(engine.timeline)
    for ev in counters:
        assert ev["pid"] == 1 and ev["ts"] >= 0.0
        assert list(ev["args"]) == [ev["name"]]
        assert isinstance(ev["args"][ev["name"]], (int, float))
    # engine spans carry the attribution alongside the counters
    spans = [e for e in doc["traceEvents"]
             if e.get("cat") == "engine" and e["ph"] == "X"]
    assert all("phases" in e["args"] for e in spans)


def test_obs_cli_exit_codes(model, tmp_path, capsys):
    clean = tmp_path / "clean.json"
    engine = _engine(model)
    engine.add_request(_prompt(5), 4)
    engine.run()
    engine.dump_flight_record(clean)

    assert obs_main(["--flight-record", str(clean)]) == 0
    out = capsys.readouterr().out
    assert "flight record" in out and "alerts (0)" in out

    assert obs_main(["--flight-record", str(clean), "--prometheus"]) == 0
    out = capsys.readouterr().out
    assert "# TYPE serving_tokens_total counter" in out
    assert 'serving_alerts_total{rule="queue_stall"} 0' in out
    # dump typing matches the live ServingMetrics.prometheus() typing:
    # suffix-less counters (COUNTER_STATS) must not export as gauges
    assert "# TYPE serving_failed counter" in out
    assert "# TYPE serving_prefix_hits counter" in out

    assert obs_main(["--flight-record", str(clean),
                     "--latency-table"]) == 0
    out = capsys.readouterr().out
    assert "ttft" in out and "tpot" in out

    # findings: a dump that recorded alerts exits 1
    dirty = tmp_path / "dirty.json"
    stalled = _engine(model, watchdog=WatchdogConfig(stall_steps=2))
    stalled.add_request(_prompt(5), 4)
    stalled.admit_paused = True
    for _ in range(3):
        stalled.step()
    stalled.dump_flight_record(dirty)
    assert obs_main(["--flight-record", str(dirty)]) == 1
    assert "queue_stall" in capsys.readouterr().out

    # ... and so does a fatal/failure-reason dump with no alerts
    auto = tmp_path / "auto.json"
    engine.dump_flight_record(auto, reason="request-failure")
    assert obs_main(["--flight-record", str(auto)]) == 1
    capsys.readouterr()

    # bad usage / unreadable input
    assert obs_main(["--flight-record", str(tmp_path / "nope.json")]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("{\"schema\": \"wrong\"}")
    assert obs_main(["--flight-record", str(bad)]) == 2
    assert obs_main([]) == 2
    assert obs_main(["--no-such-flag"]) == 2
    capsys.readouterr()
    # --prometheus with no dump reads the live registry (this process),
    # with the SAME counter typing as the dump path — no type-flap
    # between a live scrape and a dump scrape of one process
    assert obs_main(["--prometheus"]) == 0
    out = capsys.readouterr().out
    assert "serving_" in out
    assert "# TYPE serving_tokens_total counter" in out
