"""Launch CLI + elastic tests (reference test model: test_fleet_launch_*.sh,
test_fleet_elastic_manager.py — SURVEY.md §4/6,7)."""
import os
import socket
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import json, os, sys
out = os.environ["TEST_OUT_DIR"]
rank = os.environ.get("PADDLE_TRAINER_ID", "?")
keep = {k: v for k, v in os.environ.items() if k.startswith("PADDLE_")}
with open(os.path.join(out, f"rank{rank}.json"), "w") as f:
    json.dump(keep, f)
"""

FLAKY_WORKER = """
import os, sys
if int(os.environ.get("PADDLE_RESTART_COUNT", "0")) == 0:
    sys.exit(7)
open(os.path.join(os.environ["TEST_OUT_DIR"], "ok"), "w").write("1")
"""


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _run_launch(args, script_body, tmp_path, name, extra_env=None, timeout=60):
    script = tmp_path / f"{name}.py"
    script.write_text(script_body)
    env = dict(os.environ, TEST_OUT_DIR=str(tmp_path), PYTHONPATH=REPO)
    env.update(extra_env or {})
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--log_dir", str(tmp_path / "log")] + args + [str(script)]
    return subprocess.run(cmd, env=env, cwd=REPO, timeout=timeout,
                          capture_output=True, text=True)


def test_single_node_two_procs(tmp_path):
    r = _run_launch(["--nnodes", "1", "--nproc_per_node", "2"], WORKER, tmp_path, "w")
    assert r.returncode == 0, r.stdout + r.stderr
    import json

    e0 = json.load(open(tmp_path / "rank0.json"))
    e1 = json.load(open(tmp_path / "rank1.json"))
    assert e0["PADDLE_TRAINERS_NUM"] == "2" and e1["PADDLE_TRAINERS_NUM"] == "2"
    assert e0["PADDLE_TRAINER_ID"] == "0" and e1["PADDLE_TRAINER_ID"] == "1"
    eps = e0["PADDLE_TRAINER_ENDPOINTS"].split(",")
    assert len(eps) == 2 and e1["PADDLE_CURRENT_ENDPOINT"] == eps[1]


def test_two_node_rendezvous(tmp_path):
    port = _free_port()
    master = f"127.0.0.1:{port}"
    script = tmp_path / "w.py"
    script.write_text(WORKER)
    env = dict(os.environ, TEST_OUT_DIR=str(tmp_path), PYTHONPATH=REPO)
    procs = []
    for rank in range(2):
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--nnodes", "2", "--master", master, "--rank", str(rank),
               "--log_dir", str(tmp_path / "log"), str(script)]
        procs.append(subprocess.Popen(cmd, env=env, cwd=REPO))
        time.sleep(0.3)  # let rank 0 bind the store first
    for p in procs:
        assert p.wait(timeout=60) == 0
    import json

    e0 = json.load(open(tmp_path / "rank0.json"))
    e1 = json.load(open(tmp_path / "rank1.json"))
    assert e0["PADDLE_TRAINERS_NUM"] == "2"
    assert {e0["PADDLE_TRAINER_ID"], e1["PADDLE_TRAINER_ID"]} == {"0", "1"}
    assert e0["PADDLE_MASTER"] == e1["PADDLE_MASTER"]
    assert e0["PADDLE_TRAINER_ENDPOINTS"] == e1["PADDLE_TRAINER_ENDPOINTS"]


def test_restart_on_failure(tmp_path):
    r = _run_launch(["--nnodes", "1", "--max_restart", "2"], FLAKY_WORKER, tmp_path, "f")
    assert r.returncode == 0, r.stdout + r.stderr
    assert (tmp_path / "ok").exists()


def test_failure_exhausts_restarts(tmp_path):
    r = _run_launch(["--nnodes", "1", "--max_restart", "1"],
                    "import sys; sys.exit(7)", tmp_path, "bad")
    assert r.returncode == 7


ELASTIC_WORKER = """
import json, os, sys, time
out = os.environ["TEST_OUT_DIR"]
world = int(os.environ["PADDLE_TRAINERS_NUM"])
if world < 3:
    time.sleep(600)  # hold until the scale-up restart kills us
with open(os.path.join(out, f"done{os.environ['PADDLE_TRAINER_ID']}"), "w") as f:
    f.write(os.environ["PADDLE_TRAINER_ENDPOINTS"])
"""


def test_elastic_scale_up(tmp_path):
    port = _free_port()
    master = f"127.0.0.1:{port}"
    script = tmp_path / "w.py"
    script.write_text(ELASTIC_WORKER)
    env = dict(os.environ, TEST_OUT_DIR=str(tmp_path), PYTHONPATH=REPO)

    def node(rank):
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--nnodes", "2:3", "--master", master, "--rank", str(rank),
               "--log_dir", str(tmp_path / "log"), str(script)]
        return subprocess.Popen(cmd, env=env, cwd=REPO)

    procs = [node(0), node(1)]
    time.sleep(8)  # let gen-0 (2-node world) deploy and start sleeping
    procs.append(node(2))  # scale up — triggers restart into a 3-node world
    try:
        for p in procs:
            assert p.wait(timeout=90) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    dones = sorted(f.name for f in tmp_path.glob("done*"))
    assert dones == ["done0", "done1", "done2"]


KILL_RECOVER_WORKER = """
import os, sys, time
out = os.environ["TEST_OUT_DIR"]
world = int(os.environ["PADDLE_TRAINERS_NUM"])
if world >= 3:
    time.sleep(600)  # gen-0 (3-node world): hold until a node dies
with open(os.path.join(out,
          f"recovered{os.environ['PADDLE_TRAINER_ID']}"), "w") as f:
    f.write(str(world))
"""


@pytest.mark.slow
def test_elastic_kill_node_and_recover(tmp_path):
    """VERDICT r3 item 8: kill one pod mid-run; the elastic manager must see
    the stale heartbeat, signal a restart, and the surviving nodes finish in a
    smaller (still >= np_min) world (reference: manager.py:130 scale-down +
    ELASTIC_EXIT_CODE relaunch protocol)."""
    port = _free_port()
    master = f"127.0.0.1:{port}"
    script = tmp_path / "w.py"
    script.write_text(KILL_RECOVER_WORKER)
    env = dict(os.environ, TEST_OUT_DIR=str(tmp_path), PYTHONPATH=REPO)

    def node(rank):
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--nnodes", "2:3", "--master", master, "--rank", str(rank),
               "--log_dir", str(tmp_path / "log"), str(script)]
        # own process group so killing the launcher's whole tree is possible
        return subprocess.Popen(cmd, env=env, cwd=REPO, start_new_session=True)

    procs = [node(0), node(1), node(2)]
    try:
        time.sleep(10)  # let gen-0 (3-node world) deploy and start sleeping
        os.killpg(os.getpgid(procs[2].pid), 9)  # kill node 2: launcher + worker
        for p in procs[:2]:
            assert p.wait(timeout=120) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), 9)
                except ProcessLookupError:
                    pass
    recovered = sorted(f.name for f in tmp_path.glob("recovered*"))
    assert len(recovered) == 2, recovered
    worlds = {f.read_text() for f in tmp_path.glob("recovered*")}
    assert worlds == {"2"}, worlds


class _FakeMaster:
    def __init__(self):
        self.hb = {}

    def start_heartbeat(self, rank, interval=2.0):
        self.hb[rank] = time.time()

    def stop_heartbeat(self):
        pass

    def alive_peers(self, nmax, stale_after=10.0):
        now = time.time()
        return [r for r, ts in sorted(self.hb.items()) if now - ts < stale_after]


def test_elastic_manager_match_and_watch():
    from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus

    m = _FakeMaster()
    em = ElasticManager(m, node_rank=0, np_min=2, np_max=4, timeout=0.5,
                        stale_after=5.0)
    assert em.enabled
    m.hb = {0: time.time(), 1: time.time()}
    assert em.match()
    assert em.watch() == ElasticStatus.COMPLETED
    # scale up: new peer appears
    m.hb[2] = time.time()
    assert em.watch() == ElasticStatus.RESTART
    assert em.watch() == ElasticStatus.COMPLETED
    # node death below np_min: HOLD then EXIT after timeout
    m.hb = {0: time.time()}
    assert em.watch() == ElasticStatus.HOLD
    time.sleep(0.6)
    assert em.watch() == ElasticStatus.EXIT
    assert not em.match()
