"""Static pipeline (device_guard) tests — D15 (reference:
PipelineOptimizer fluid/optimizer.py:4323 + SectionWorker device_worker.h:620).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.static.pipeline import (
    PipelineCompiledProgram,
    split_program_by_device,
)


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _build_pipelined(seed=5):
    paddle.seed(seed)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [8, 6], "float32")
        label = static.data("label", [8], "int64")
        with static.device_guard("stage:0"):
            h = nn.functional.relu(nn.Linear(6, 16)(x))
        with static.device_guard("stage:1"):
            logits = nn.Linear(16, 4)(h)
            loss = nn.functional.cross_entropy(logits, label)
    return main, loss


def test_split_by_device_guard():
    main, loss = _build_pipelined()
    segs = split_program_by_device(main)
    assert len(segs) == 2
    assert segs[0][0] == "stage:0" and segs[1][0] == "stage:1"
    # the ln/relu ops landed in stage 0, CE in stage 1
    assert any(op.type.endswith("relu") for op in segs[0][1])
    assert any("cross_entropy" in op.type for op in segs[1][1])


def test_pipeline_trains_and_matches_plain_executor():
    xv = np.random.RandomState(0).rand(8, 6).astype(np.float32)
    yv = np.random.RandomState(0).randint(0, 4, (8,)).astype(np.int64)

    # plain single-program run (reference: non-pipelined baseline)
    main_ref, loss_ref = _build_pipelined()
    with static.program_guard(main_ref):
        opt_r = paddle.optimizer.SGD(0.2)
        opt_r.minimize(loss_ref)
    exe = static.Executor()
    ref_losses = [float(exe.run(main_ref, feed={"x": xv, "label": yv},
                                fetch_list=[loss_ref])[0]) for _ in range(4)]

    # pipelined: 2 stages x 2 micro-batches, grad accumulation
    main_p, loss_p = _build_pipelined()
    pipe = PipelineCompiledProgram(main_p, loss_p,
                                   optimizer=paddle.optimizer.SGD(0.2),
                                   num_microbatches=2)
    pipe_losses = [pipe.run({"x": xv, "label": yv}) for _ in range(4)]
    # same init/data: micro-batched accumulation == full-batch step for
    # mean-CE + SGD (linear in grads), so the loss curves must match
    assert pipe_losses == pytest.approx(ref_losses, rel=1e-4), (
        pipe_losses, ref_losses)


def test_pipeline_rejects_single_stage_and_bad_batch():
    paddle.seed(1)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 3], "float32")
        y = nn.Linear(3, 2)(x)
    with pytest.raises(Exception, match="device_guard"):
        PipelineCompiledProgram(main, y, num_microbatches=2)

    main2, loss2 = _build_pipelined()
    pipe = PipelineCompiledProgram(main2, loss2,
                                   optimizer=paddle.optimizer.SGD(0.1),
                                   num_microbatches=3)
    with pytest.raises(Exception, match="micro"):
        pipe.run({"x": np.zeros((8, 6), np.float32),
                  "label": np.zeros((8,), np.int64)})
