"""M1 milestone: LeNet on (synthetic) MNIST via paddle.Model.fit converges.

Reference config: BASELINE.json configs[0] — 'MNIST LeNet via paddle.Model.fit'.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import Model, nn
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet
from paddle_tpu.vision.transforms import Normalize


@pytest.mark.slow
def test_lenet_mnist_convergence():
    transform = Normalize(mean=[127.5], std=[127.5])
    train = MNIST(mode="train", transform=transform, synthetic_size=512)
    test = MNIST(mode="test", transform=transform, synthetic_size=128)

    model = Model(LeNet())
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), Accuracy())

    model.fit(train, epochs=2, batch_size=64, verbose=0)
    res = model.evaluate(test, batch_size=64, verbose=0)
    # synthetic blobs are separable: should be well above chance after 2 epochs
    assert res["acc"] > 0.5, res


@pytest.mark.slow
def test_model_save_load(tmp_path):
    model = Model(LeNet())
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    x = np.random.rand(4, 1, 28, 28).astype(np.float32)
    y = np.random.randint(0, 10, (4, 1))
    model.train_batch([x], [y])
    p = str(tmp_path / "ckpt")
    model.save(p)

    model2 = Model(LeNet())
    opt2 = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model2.parameters())
    model2.prepare(opt2, nn.CrossEntropyLoss())
    model2.load(p)
    sd1 = model.network.state_dict()
    sd2 = model2.network.state_dict()
    for k in sd1:
        assert np.allclose(sd1[k].numpy(), sd2[k].numpy()), k


@pytest.mark.slow
def test_train_batch_reduces_loss():
    model = Model(LeNet())
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    x = np.random.rand(32, 1, 28, 28).astype(np.float32)
    y = np.random.randint(0, 10, (32, 1))
    losses = [model.train_batch([x], [y])[0] for _ in range(10)]
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_predict():
    model = Model(LeNet())
    model.prepare(None, None)
    x = np.random.rand(4, 1, 28, 28).astype(np.float32)
    out = model.predict_batch([paddle.to_tensor(x)])
    assert out.shape == [4, 10]


def test_summary():
    info = paddle.summary(LeNet())
    assert info["total_params"] > 60000


@pytest.mark.slow
def test_model_fit_in_static_mode():
    """Reference Model dispatches to a StaticGraphAdapter under
    enable_static (hapi/model.py:248); here the whole-step jit IS the
    compiled static execution — fit/evaluate must work and learn."""
    import numpy as np

    import paddle_tpu as paddle

    paddle.enable_static()
    try:
        paddle.seed(11)
        net = paddle.nn.Sequential(paddle.nn.Flatten(),
                                   paddle.nn.Linear(784, 10))
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.Adam(parameters=net.parameters()),
                      paddle.nn.CrossEntropyLoss(),
                      paddle.metric.Accuracy())
        ds = paddle.vision.datasets.MNIST(mode="train", synthetic_size=192)
        model.fit(ds, epochs=2, batch_size=32, verbose=0)
        res = model.evaluate(ds, batch_size=64, verbose=0)
        assert res["acc"] > 0.3  # synthetic blobs learn fast
        # static mode restored after every entry point
        assert paddle.in_static_mode()
    finally:
        paddle.disable_static()
