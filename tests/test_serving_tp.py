"""Tensor-parallel sharded serving (ServingConfig(tensor_parallel=N)).

The contract under test: sharding is INVISIBLE except for speed — every
serving invariant the single-chip engine pins must survive the Megatron
weight split + heads-sharded paged KV pool:

- **Bit-identical outputs** TP=2 and TP=4 vs TP=1 (token streams, not
  logits bits): greedy, sampling (the (seed, rid, token) PRNG fold),
  prefix-cache hits, chunked prefill, and both preemption modes.
- **Compile-once unchanged**: same ``compile_counts`` as TP=1 — the
  sharded programs compile once per prefill bucket + once for decode.
- **Sync-free certification unchanged**: SyncTally == decode steps +
  completed prefills, the exact single-chip formula.
- **CollectiveBudget certification**: under ``debug_checks`` every
  sharded program audits to exactly 2 all-reduces per block + 1 for the
  logits (byte-capped, the serving/tp.py declaration) — and the
  zero-budget variant raises NAMING the offending collective.
- **KV-pool shard math**: each device owns [num_pages, page_size,
  heads/N, head_dim] per layer; logical page ids/tables are unsharded.

Runs entirely on the conftest-forced 8-device CPU mesh — a virtual-mesh
proof, no chips needed. Sharded CPU compiles are the cost center here,
so tests share engines where coverage allows (the module-scope
debug-audited engine feeds three tests) and single-bucket configs are
used wherever a second pad bucket adds no coverage.
"""
import itertools

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import SyncTally
from paddle_tpu.analysis.hlocheck import (SINGLE_CHIP,
                                          CollectiveBudgetError, run_step)
from paddle_tpu.serving import ServingConfig, ServingEngine
from paddle_tpu.serving import scheduler as sched_mod
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.tp

HIDDEN, LAYERS, HEADS, VOCAB = 32, 2, 4, 97


@pytest.fixture(scope="module")
def model():
    if len(jax.devices()) < 4:
        pytest.skip("needs the conftest 8-device CPU mesh")
    paddle.seed(23)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=VOCAB, hidden_size=HIDDEN, num_layers=LAYERS,
        num_heads=HEADS, max_seq_len=48, dropout=0.0))
    m.eval()
    return m


def _prompts(seed, lens):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, (n,)).astype(np.int32) for n in lens]


def _engine(model, tp=1, **kw):
    # align rids across the engines being compared: the sampling PRNG
    # folds (seed, rid, token), so parity needs identical rids (the
    # test_serving_chunked idiom)
    sched_mod._rid_counter = itertools.count(9000)
    kw.setdefault("num_pages", 24)
    kw.setdefault("max_prompt_len", 8)  # one pad bucket unless a test
    # spans two — every extra bucket is an extra sharded CPU compile
    return ServingEngine(model, ServingConfig(
        max_batch=2, page_size=4, tensor_parallel=tp, **kw))


def _drive(model, tp, prompts, budgets, **kw):
    eng = _engine(model, tp, **kw)
    rids = [eng.add_request(p, b) for p, b in zip(prompts, budgets)]
    outs = eng.run()
    return [outs[r] for r in rids], eng


# ------------------------------------------------------------------ parity
def test_greedy_parity_compile_counts_and_sync_free_tp2_tp4(model):
    """THE acceptance gate: greedy outputs bit-identical across TP
    degrees, compile_counts pinned IDENTICAL to TP=1 (one trace per
    bucket + one decode), and the sync-free certification formula —
    SyncTally == decode steps + completed prefills — byte-identical to
    single-chip (the token fetch reads one replicated output: still one
    sync per step boundary)."""
    prompts = _prompts(0, (3, 12, 7, 5))  # spans both buckets [8, 16]
    budgets = [6, 5, 7, 6]
    ref, e1 = _drive(model, 1, prompts, budgets, max_prompt_len=16)
    for tp in (2, 4):
        eng = _engine(model, tp, max_prompt_len=16)
        rids = [eng.add_request(p, b) for p, b in zip(prompts, budgets)]
        pre = eng.metrics.snapshot()
        with SyncTally() as tally:
            outs = eng.run()
        for i, rid in enumerate(rids):
            assert np.array_equal(ref[i], outs[rid]), \
                f"TP={tp} request {i} diverged"
        assert eng.compile_counts == e1.compile_counts == \
            {"prefill": 2, "decode": 1}
        snap = eng.metrics.snapshot()
        fetches = int(snap["serving_decode_steps"]
                      - pre["serving_decode_steps"]
                      + snap["serving_prefills_total"]
                      - pre["serving_prefills_total"])
        assert tally.count == fetches, (tp, tally.count, fetches,
                                        tally.events[:10])


def test_sampling_parity_tp2(model):
    prompts = _prompts(1, (4, 7, 6))
    kw = dict(do_sample=True, temperature=0.8, top_k=20, top_p=0.95,
              seed=5)
    ref, _ = _drive(model, 1, prompts, [7, 6, 5], **kw)
    outs, _ = _drive(model, 2, prompts, [7, 6, 5], **kw)
    assert all(np.array_equal(a, b) for a, b in zip(ref, outs))


def test_prefix_hit_parity_tp2(model):
    """Cache hits map LOGICAL page ids — per-shard pools hold each head
    slice's bytes, so a TP=2 hit serves exactly the KV a TP=2 cold
    prefill would recompute."""
    system = _prompts(2, (4,))[0]  # exactly 1 whole page
    chats = [np.concatenate([system, t])
             for t in _prompts(3, (3, 3, 3))]

    def seq(tp):
        eng = _engine(model, tp, num_pages=32)
        outs = []
        for p in chats:  # sequential: later bursts hit the index
            rid = eng.add_request(p, 5)
            outs.append(eng.run()[rid])
        return outs, eng

    ref, _ = seq(1)
    outs, eng = seq(2)
    assert all(np.array_equal(a, b) for a, b in zip(ref, outs))
    snap = eng.metrics.snapshot()
    assert snap["serving_prefix_hits"] == len(chats) - 1
    assert snap["serving_prefix_tokens_saved"] >= 4 * (len(chats) - 1)


@pytest.mark.slow  # re-tiered 2026-08 (PR 20): tier-1 crossed its 870 s
# budget; chunked parity stays pinned at TP=1 (test_serving_chunked) and
# greedy/sampling TP parity stays tier-1 above
def test_chunked_parity_tp2(model):
    whale = np.arange(1, 14, dtype=np.int32)
    prompts = [whale] + _prompts(4, (3, 6))
    kw = dict(chunk_size=4, max_prompt_len=16)
    ref, e1 = _drive(model, 1, prompts, [6, 5, 6], **kw)
    outs, e2 = _drive(model, 2, prompts, [6, 5, 6], **kw)
    assert all(np.array_equal(a, b) for a, b in zip(ref, outs))
    # chunks pad into the existing bucket set under TP too
    assert e2.compile_counts == e1.compile_counts


@pytest.mark.parametrize("mode", ["recompute", "swap"])
def test_preemption_parity_tp2(model, mode):
    """A 6-usable-page pool forces mid-decode preemption; both modes
    replay/resume bit-identically under TP=2 (swap: the per-shard
    gather/scatter round-trips every head shard's bytes exactly)."""
    prompts = _prompts(5, (3, 8, 7, 5))
    kw = dict(preemption_mode=mode, num_pages=7)
    ref, e1 = _drive(model, 1, prompts, [8] * 4, **kw)
    outs, e2 = _drive(model, 2, prompts, [8] * 4, **kw)
    assert e2.metrics.snapshot()["serving_preemptions_total"] >= 1
    assert all(np.array_equal(a, b) for a, b in zip(ref, outs))
    if mode == "swap":
        # the sharded swap movers compile once each, like single-chip
        assert e2.cache.compile_counts["swap_gather"] == 1
        assert e2.cache.compile_counts["swap_scatter"] == 1


def test_chunked_swap_preemption_parity_tp2(model):
    """The compound case: a whale mid-chunked-prefill swapped out and
    resumed — prefilled_tokens ride the per-shard swap handles."""
    whale = np.arange(2, 10, dtype=np.int32)
    prompts = [whale] + _prompts(6, (7, 5))
    kw = dict(chunk_size=4, preemption_mode="swap", num_pages=7)
    ref, _ = _drive(model, 1, prompts, [8, 8, 8], **kw)
    outs, e2 = _drive(model, 2, prompts, [8, 8, 8], **kw)
    assert all(np.array_equal(a, b) for a, b in zip(ref, outs))


# ---------------------------------------------------------- certifications
@pytest.fixture(scope="module")
def debug_engine(model):
    """ONE debug-audited TP=2 engine shared by the certification tests —
    each sharded program costs an extra AOT compile to audit, so the
    audits are paid once for the module."""
    eng = _engine(model, 2, debug_checks=True, max_prompt_len=16)
    for p, b in zip(_prompts(8, (3, 12)), (4, 3)):
        eng.add_request(p, b)
    eng.run()
    return eng


def test_debug_checks_certifies_declared_budgets_tp2(debug_engine):
    """Every sharded program (both prefill buckets + decode) hlo-audits
    under debug_checks to EXACTLY the declared collectives — 2 all-reduces
    per block + 1 for the logits, byte volumes matching the budget
    formula — with every donated pool shard aliased; the census feeds the
    serving_tp_* gauges."""
    eng = debug_engine
    audits = eng.hlo_audits
    assert set(audits) == {"prefill[8]", "prefill[16]", "decode"}
    expect_ar = 2 * LAYERS + 1
    for label, r in audits.items():
        assert r.counts() == {"all-reduce": expect_ar}, label
        b, s = eng._step_shape(label)
        assert r.collective_bytes == \
            (2 * LAYERS * b * s * HIDDEN + b * s * VOCAB) * 4, label
        assert r.host_transfers == (), label
        assert r.donated_leaves == 2 * LAYERS == r.aliased_leaves, label
    snap = eng.metrics.snapshot()
    assert snap["serving_tp_degree"] == 2
    assert snap["serving_tp_collective_ops_per_step"] == expect_ar
    # bytes/token is bucket-independent here: payloads scale with tokens
    assert snap["serving_tp_collective_bytes_per_token"] == \
        (2 * LAYERS * HIDDEN + VOCAB) * 4


def test_zero_budget_variant_raises_naming_the_collective(model):
    """The acceptance gate's negative half: the SAME sharded engine held
    to the single-chip (zero) budget must raise at the first audited
    program, naming the offending all-reduce instruction."""
    eng = _engine(model, 2, debug_checks=True)
    eng._step_budget = lambda label: SINGLE_CHIP  # the zero-budget variant
    eng.add_request(_prompts(9, (4,))[0], 3)
    with pytest.raises(CollectiveBudgetError) as ei:
        eng.run()
    msg = str(ei.value)
    assert "all-reduce" in msg and "budget of 0" in msg
    assert "%all-reduce" in msg  # the HLO instruction is named


def test_report_reenforcement_against_zero_budget_raises(debug_engine):
    """Same property off the recorded report (no engine surgery): a clean
    TP audit re-enforced at SINGLE_CHIP raises; at its declared budget it
    is idempotent."""
    report = debug_engine.hlo_audits["decode"]
    report.enforce(debug_engine._step_budget("decode"))  # idempotent
    with pytest.raises(CollectiveBudgetError):
        report.enforce(SINGLE_CHIP)


def test_registry_tp2_steps_certify_including_chunk(model):
    """The hlocheck registry's sharded variants certify against their
    declared budgets — notably engine_prefill_chunk's TP twin (the
    ROADMAP follow-up this PR closes) and the donated per-shard swap
    scatter."""
    chunk = run_step("tp2_engine_prefill_chunk")
    assert chunk.counts() == {"all-reduce": 2 * LAYERS + 1}
    scatter = run_step("tp2_swap_scatter")
    assert scatter.collectives == ()
    assert scatter.donated_leaves == scatter.aliased_leaves > 0


# ------------------------------------------------------------- shard math
def test_kv_pool_and_param_shard_math(model):
    """Each device owns [num_pages, page_size, heads/N, head_dim] per
    layer — the global (logical) pool shape is unchanged, page tables
    stay host-side ints. Megatron param placement: qkv column shards,
    row-parallel biases live on device 0 only (the psum adds them
    exactly once), embeddings replicated. Construction-only: no step
    ever compiles here."""
    hd = HIDDEN // HEADS
    for tp in (2, 4):
        eng = _engine(model, tp)
        for layer in eng.cache.pools:
            for pool in layer.values():
                assert pool.shape == (24, 4, HEADS, hd)  # logical
                shards = pool.addressable_shards
                assert len(shards) == tp
                assert all(s.data.shape == (24, 4, HEADS // tp, hd)
                           for s in shards)
        assert eng.cache.page_table.shape == (2, 12)  # host, unsharded
    eng = _engine(model, 2)
    p = eng._p
    qkv = next(v for k, v in p.items() if k.endswith("qkv_proj.weight"))
    assert qkv.addressable_shards[0].data.shape == (HIDDEN,
                                                    3 * HIDDEN // 2)
    fc2 = next(v for k, v in p.items() if k.endswith("fc2.bias"))
    assert fc2.shape == (2, HIDDEN)  # stacked: device 0 real, rest zero
    assert np.asarray(fc2.addressable_shards[1].data).max() == 0.0
    wte = next(v for k, v in p.items() if k.endswith("wte.weight"))
    assert wte.addressable_shards[0].data.shape == (VOCAB, HIDDEN)


# ------------------------------------------------------------- validation
def test_validation_errors_and_gauge_seeding(model):
    with pytest.raises(ValueError, match="tensor_parallel -1"):
        ServingEngine(model, ServingConfig(tensor_parallel=-1))
    with pytest.raises(ValueError, match="num_heads"):
        _engine(model, 3)  # 4 heads % 3 != 0
    with pytest.raises(ValueError, match="device"):
        _engine(model, 16)  # wider than the forced 8-device mesh
    # PT003/PT008 contract: the serving_tp_* gauges are visible at zero
    # before any audit, and tp_degree reflects the config from
    # construction
    from paddle_tpu.serving.metrics import ServingMetrics

    snap = ServingMetrics().snapshot()
    for k in ("serving_tp_degree", "serving_tp_collective_ops_per_step",
              "serving_tp_collective_bytes_per_token"):
        assert snap[k] == 0, k
    assert _engine(model, 2).metrics.snapshot()["serving_tp_degree"] == 2
