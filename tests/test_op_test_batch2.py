"""OpTest batch 2: conv/pool/norm/embedding/elementwise/reduce coverage
(reference test strategy SURVEY §4.1 — numpy-reference per-op tests)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.utils.op_test import OpTest


def _np_conv2d(x, w, pad=0):
    n, cin, h, ww = x.shape
    cout, _, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh, ow = xp.shape[2] - kh + 1, xp.shape[3] - kw + 1
    out = np.zeros((n, cout, oh, ow), np.float64)
    for b in range(n):
        for co in range(cout):
            for i in range(oh):
                for j in range(ow):
                    out[b, co, i, j] = np.sum(
                        xp[b, :, i:i + kh, j:j + kw] * w[co])
    return out.astype("float32")


class TestConv2dOp(OpTest):
    def setUp(self):
        self.op = F.conv2d
        self.inputs = {
            "x": np.random.rand(2, 3, 6, 6).astype("float32"),
            "weight": np.random.rand(4, 3, 3, 3).astype("float32"),
        }
        self.attrs = {"padding": 1}
        self.ref = lambda x, weight, padding: _np_conv2d(x, weight, padding)

    def test_output(self):
        self.check_output(rtol=1e-4, atol=1e-4)

    def test_grad(self):
        self.check_grad(["x", "weight"], rtol=2e-2, atol=1e-2, delta=1e-2)


class TestMaxPool2dOp(OpTest):
    def setUp(self):
        self.op = F.max_pool2d
        self.inputs = {"x": np.random.rand(2, 3, 8, 8).astype("float32")}
        self.attrs = {"kernel_size": 2, "stride": 2}

        def ref(x, kernel_size, stride):
            n, c, h, w = x.shape
            return x.reshape(n, c, h // 2, 2, w // 2, 2).max((3, 5))

        self.ref = ref

    def test_output(self):
        self.check_output()


class TestAvgPool2dOp(OpTest):
    def setUp(self):
        self.op = F.avg_pool2d
        self.inputs = {"x": np.random.rand(2, 3, 8, 8).astype("float32")}
        self.attrs = {"kernel_size": 2, "stride": 2}

        def ref(x, kernel_size, stride):
            n, c, h, w = x.shape
            return x.reshape(n, c, h // 2, 2, w // 2, 2).mean((3, 5))

        self.ref = ref

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestBatchNormInference(OpTest):
    def setUp(self):
        c = 4
        self.op = F.batch_norm
        self.inputs = {
            "x": np.random.rand(2, c, 5, 5).astype("float32"),
            "running_mean": np.random.rand(c).astype("float32"),
            "running_var": (np.random.rand(c) + 0.5).astype("float32"),
            "weight": np.random.rand(c).astype("float32"),
            "bias": np.random.rand(c).astype("float32"),
        }
        self.attrs = {"training": False, "epsilon": 1e-5}

        def ref(x, running_mean, running_var, weight, bias, training,
                epsilon):
            sh = (1, -1, 1, 1)
            return (x - running_mean.reshape(sh)) / np.sqrt(
                running_var.reshape(sh) + epsilon) * weight.reshape(sh) \
                + bias.reshape(sh)

        self.ref = ref

    def test_output(self):
        self.check_output(rtol=1e-4, atol=1e-5, check_static=False)


class TestEmbeddingOp(OpTest):
    def setUp(self):
        self.op = F.embedding
        self.inputs = {
            "x": np.random.randint(0, 10, (3, 4)).astype("int64"),
            "weight": np.random.rand(10, 6).astype("float32"),
        }
        self.attrs = {}
        self.ref = lambda x, weight: weight[x]

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["weight"])


class TestElementwiseFamily(OpTest):
    def setUp(self):
        self.op = paddle.divide
        self.inputs = {
            "x": np.random.rand(3, 4).astype("float32") + 1,
            "y": np.random.rand(3, 4).astype("float32") + 1,
        }
        self.attrs = {}
        self.ref = lambda x, y: x / y

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x", "y"])


class TestBroadcastAdd(OpTest):
    def setUp(self):
        self.op = paddle.add
        self.inputs = {
            "x": np.random.rand(3, 4).astype("float32"),
            "y": np.random.rand(4).astype("float32"),
        }
        self.attrs = {}
        self.ref = lambda x, y: x + y

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x", "y"])


class TestReduceSumKeepdim(OpTest):
    def setUp(self):
        self.op = paddle.sum
        self.inputs = {"x": np.random.rand(2, 3, 4).astype("float32")}
        self.attrs = {"axis": [0, 2], "keepdim": True}
        self.ref = lambda x, axis, keepdim: x.sum(tuple(axis), keepdims=True)

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestTransposeReshapeChain(OpTest):
    def setUp(self):
        def chain(x):
            return paddle.reshape(paddle.transpose(x, [0, 2, 1]), [2, -1])

        self.op = chain
        self.inputs = {"x": np.random.rand(2, 3, 4).astype("float32")}
        self.attrs = {}
        self.ref = lambda x: x.transpose(0, 2, 1).reshape(2, -1)

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestCrossEntropyOp(OpTest):
    def setUp(self):
        n, c = 6, 5
        logits = np.random.rand(n, c).astype("float32")
        labels = np.random.randint(0, c, n).astype("int64")
        self.op = F.cross_entropy
        self.inputs = {"input": logits, "label": labels}
        self.attrs = {"reduction": "mean"}

        def ref(input, label, reduction):
            e = np.exp(input - input.max(-1, keepdims=True))
            p = e / e.sum(-1, keepdims=True)
            return -np.log(p[np.arange(len(label)), label]).mean()

        self.ref = ref

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["input"])


class TestLogSumExp(OpTest):
    def setUp(self):
        self.op = paddle.logsumexp
        self.inputs = {"x": np.random.rand(3, 5).astype("float32")}
        self.attrs = {"axis": 1}

        def ref(x, axis):
            m = x.max(axis, keepdims=True)
            return (np.log(np.exp(x - m).sum(axis)) + m.squeeze(axis))

        self.ref = ref

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestSquaredL2AndClipChain(OpTest):
    def setUp(self):
        def f(x):
            return paddle.sum(paddle.multiply(paddle.clip(x, 0.2, 0.8),
                                              paddle.clip(x, 0.2, 0.8)))

        self.op = f
        self.inputs = {"x": np.random.rand(20).astype("float32")}
        self.attrs = {}
        self.ref = lambda x: (np.clip(x, 0.2, 0.8) ** 2).sum()

    def test_output(self):
        self.check_output()
