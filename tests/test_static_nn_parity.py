"""static + static.nn parity batch tests: append_backward/gradients through
the whole-program jit, py_func callbacks, EMA, serialization round-trips,
sequence ops over the padded+lengths policy, nce/crf/row_conv."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.static import nn as snn


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_append_backward_and_gradients_numerics(static_mode):
    paddle.seed(0)
    prog, start = static.Program(), static.Program()
    with static.program_guard(prog, start):
        x = static.data("x", [4, 3], "float32")
        lin = paddle.nn.Linear(3, 2)
        y = lin(x)
        loss = (y * y).mean()
        pairs = static.append_backward(loss)
        gx, = static.gradients(loss, [x])
    exe = static.Executor()
    feed = {"x": np.ones((4, 3), np.float32)}
    outs = exe.run(prog, feed=feed, fetch_list=[loss, pairs[0][1], gx])
    W = np.asarray(lin.weight._value)
    b = np.asarray(lin.bias._value)
    yv = feed["x"] @ W + b
    dx_ref = (2 * yv / yv.size) @ W.T
    dW_ref = feed["x"].T @ (2 * yv / yv.size)
    np.testing.assert_allclose(np.asarray(outs[2]), dx_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]), dW_ref, rtol=1e-5)


def test_py_func_forward_and_backward():
    # dygraph/traced form: py_func is a host callback either way; under
    # static mode it records an op and returns a symbolic Variable instead
    import jax
    import jax.numpy as jnp

    def host_sq(a):
        return a * a

    def host_sq_grad(a, g):
        return 2.0 * a * g

    def f(a):
        out_decl = Tensor(jnp.zeros(a.shape, a.dtype))
        return static.py_func(host_sq, Tensor(a), out_decl,
                              backward_func=host_sq_grad)._value

    x = jnp.asarray(np.arange(4, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(f(x)), np.arange(4) ** 2)
    g = jax.grad(lambda a: jnp.sum(f(a)))(x)
    np.testing.assert_allclose(np.asarray(g), 2 * np.arange(4), rtol=1e-6)


def test_ema_apply_restore():
    paddle.seed(1)
    lin = paddle.nn.Linear(3, 3)
    prog = static.default_main_program()
    ema = static.ExponentialMovingAverage(0.5)
    w0 = np.asarray(lin.weight._value).copy()
    ema.update(parameters=[lin.weight])
    lin.weight._value = lin.weight._value + 1.0
    ema.update(parameters=[lin.weight])
    cur = np.asarray(lin.weight._value).copy()
    with ema.apply():
        applied = np.asarray(lin.weight._value)
        assert not np.allclose(applied, cur)
    np.testing.assert_allclose(np.asarray(lin.weight._value), cur)


def test_program_state_roundtrip(tmp_path, static_mode):
    paddle.seed(2)
    prog, start = static.Program(), static.Program()
    with static.program_guard(prog, start):
        x = static.data("x", [2, 3], "float32")
        lin = paddle.nn.Linear(3, 2)
        y = lin(x)
    path = str(tmp_path / "model")
    static.save(prog, path)
    orig = np.asarray(lin.weight._value).copy()
    lin.weight._value = lin.weight._value * 0 + 7.0
    static.load(prog, path)
    np.testing.assert_allclose(np.asarray(lin.weight._value), orig)
    state = static.load_program_state(path)
    assert lin.weight.name in state


def test_sequence_ops_padded_policy():
    seqs = [np.arange(3, dtype=np.float32).reshape(3, 1),
            np.arange(5, dtype=np.float32).reshape(5, 1)]
    padded, lens = snn.sequence_pad([Tensor(s) for s in seqs], 0.0)
    assert list(padded.shape) == [2, 5, 1]
    np.testing.assert_array_equal(np.asarray(lens._value), [3, 5])

    pooled = snn.sequence_pool(padded, "average", length=lens)
    np.testing.assert_allclose(np.asarray(pooled._value).ravel(),
                               [1.0, 2.0], rtol=1e-6)
    last = snn.sequence_last_step(padded, length=lens)
    np.testing.assert_allclose(np.asarray(last._value).ravel(), [2.0, 4.0])
    mx = snn.sequence_pool(padded, "max", length=lens)
    np.testing.assert_allclose(np.asarray(mx._value).ravel(), [2.0, 4.0])

    rev = snn.sequence_reverse(padded, length=lens)
    np.testing.assert_allclose(np.asarray(rev._value)[0, :3, 0], [2, 1, 0])
    np.testing.assert_allclose(np.asarray(rev._value)[0, 3:, 0], [0, 0])

    sm = snn.sequence_softmax(padded, length=lens)
    s = np.asarray(sm._value)
    np.testing.assert_allclose(s.sum(1).ravel(), 1.0, rtol=1e-5)
    assert (s[0, 3:] == 0).all()

    rows = snn.sequence_unpad(padded, lens)
    assert [r.shape[0] for r in rows] == [3, 5]
    np.testing.assert_allclose(np.asarray(rows[0]._value), seqs[0])


def test_sequence_conv_context_window():
    x = Tensor(np.arange(6, dtype=np.float32).reshape(1, 6, 1))
    paddle.seed(3)
    out = snn.sequence_conv(x, num_filters=2, filter_size=3)
    assert list(out.shape) == [1, 6, 2]


def test_nce_loss_shape_and_finite():
    paddle.seed(4)
    x = Tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
    y = Tensor(np.random.RandomState(1).randint(0, 50, (8, 1)))
    loss = snn.nce(x, y, num_total_classes=50, num_neg_samples=5)
    assert list(loss.shape) == [8, 1]
    assert np.isfinite(np.asarray(loss._value)).all()


def test_crf_decoding_shapes():
    pot = Tensor(np.random.RandomState(5).randn(2, 6, 4).astype(np.float32))
    trans = Tensor(np.random.RandomState(6).randn(4, 4).astype(np.float32))
    path = snn.crf_decoding(pot, transition=trans)
    assert list(path.shape) == [2, 6]
    assert np.asarray(path._value).max() < 4


def test_row_conv_lookahead():
    x = Tensor(np.ones((1, 4, 2), np.float32))
    out = snn.row_conv(x, future_context_size=2)
    assert list(out.shape) == [1, 4, 2]


def test_spectral_norm_unit_sigma():
    w = Tensor((np.random.RandomState(7).randn(8, 8) * 3).astype(np.float32))
    wn = snn.spectral_norm(w, power_iters=30)
    sigma = np.linalg.svd(np.asarray(wn._value), compute_uv=False)[0]
    assert sigma == pytest.approx(1.0, rel=1e-2)


def test_static_surface_complete():
    import ast

    def get_all(path):
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        return [e.value for e in node.value.elts
                                if isinstance(e, ast.Constant)]

    for sub, mp in [("static", "static/__init__.py"),
                    ("static.nn", "static/nn/__init__.py")]:
        names = get_all(f"/root/reference/python/paddle/{mp}")
        mod = paddle
        for part in sub.split("."):
            mod = getattr(mod, part)
        missing = sorted(n for n in names if not hasattr(mod, n))
        assert missing == [], (sub, missing)


def test_ipu_analog_strategy(static_mode):
    strat = static.IpuStrategy()
    strat.set_graph_config(num_ipus=4, micro_batch_size=2)
    strat.set_pipelining_config(enable_pipelining=True, batches_per_step=4)
    prog = static.default_main_program()
    compiled = static.IpuCompiledProgram(prog, ipu_strategy=strat).compile()
    assert compiled._ipu_strategy.num_ipus == 4

    captured = []

    def op():
        from paddle_tpu.static.program import current_device

        captured.append(current_device())

    try:
        from paddle_tpu.static.program import current_device  # noqa: F401

        with static.ipu_shard_guard(index=1):
            op()
        assert captured and "1" in str(captured[0])
    except ImportError:
        with static.ipu_shard_guard(index=1):
            pass  # guard enters/exits cleanly even without the probe
