"""paddle.vision.ops numeric tests vs torchvision reference
(reference analog: tests/unittests/test_nms_op.py, test_roi_align_op.py,
test_yolo_box_op.py, test_deformable_conv_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def _iou_np(a, b):
    ax1, ay1, ax2, ay2 = a
    bx1, by1, bx2, by2 = b
    ix = max(0.0, min(ax2, bx2) - max(ax1, bx1))
    iy = max(0.0, min(ay2, by2) - max(ay1, by1))
    inter = ix * iy
    ua = (ax2 - ax1) * (ay2 - ay1) + (bx2 - bx1) * (by2 - by1) - inter
    return inter / max(ua, 1e-10)


def _nms_np(boxes, scores, thresh):
    order = np.argsort(-scores)
    kept = []
    for i in order:
        if all(_iou_np(boxes[i], boxes[j]) <= thresh for j in kept):
            kept.append(i)
    return np.array(kept)


@pytest.mark.slow
def test_nms_matches_greedy_reference():
    rs = np.random.RandomState(0)
    base = rs.rand(40, 2) * 50
    boxes = np.concatenate([base, base + 5 + rs.rand(40, 2) * 20], 1).astype("float32")
    scores = rs.rand(40).astype("float32")
    kept = V.nms(paddle.to_tensor(boxes), 0.4,
                 scores=paddle.to_tensor(scores)).numpy()
    ref = _nms_np(boxes, scores, 0.4)
    np.testing.assert_array_equal(kept, ref)


def test_nms_category_aware_and_topk():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [0, 0, 10, 10]],
                     "float32")
    scores = np.array([0.9, 0.8, 0.7], "float32")
    cats = np.array([0, 0, 1])
    kept = V.nms(paddle.to_tensor(boxes), 0.5, paddle.to_tensor(scores),
                 category_idxs=paddle.to_tensor(cats), top_k=5).numpy()
    # box 1 suppressed by box 0 (same class); box 2 kept (other class)
    np.testing.assert_array_equal(np.sort(kept), [0, 2])


def _roi_align_np(x, boxes, batch_idx, out_size, ratio=2, aligned=True):
    """Direct numpy port of the RoIAlign definition (bilinear samples
    averaged per bin)."""
    R = boxes.shape[0]
    C, H, W = x.shape[1:]
    out = np.zeros((R, C, out_size, out_size), "float64")
    off = 0.5 if aligned else 0.0
    for r in range(R):
        img = x[batch_idx[r]]
        x1, y1, x2, y2 = boxes[r] - off
        rw = max(x2 - x1, 1e-3 if aligned else 1.0)
        rh = max(y2 - y1, 1e-3 if aligned else 1.0)
        bw, bh = rw / out_size, rh / out_size
        for oy in range(out_size):
            for ox in range(out_size):
                acc = np.zeros(C)
                for sy in range(ratio):
                    for sx in range(ratio):
                        yy = y1 + bh * (oy + (sy + 0.5) / ratio)
                        xx = x1 + bw * (ox + (sx + 0.5) / ratio)
                        y0 = int(np.clip(np.floor(yy), 0, H - 1))
                        x0 = int(np.clip(np.floor(xx), 0, W - 1))
                        y1i = min(y0 + 1, H - 1)
                        x1i = min(x0 + 1, W - 1)
                        wy1 = np.clip(yy - y0, 0, 1)
                        wx1 = np.clip(xx - x0, 0, 1)
                        acc += ((1 - wy1) * (1 - wx1) * img[:, y0, x0]
                                + (1 - wy1) * wx1 * img[:, y0, x1i]
                                + wy1 * (1 - wx1) * img[:, y1i, x0]
                                + wy1 * wx1 * img[:, y1i, x1i])
                out[r, :, oy, ox] = acc / (ratio * ratio)
    return out


def test_roi_align_matches_numpy_reference():
    rs = np.random.RandomState(1)
    x = rs.randn(2, 3, 16, 16).astype("float32")
    boxes = np.array([[1.0, 1.0, 9.0, 9.0], [2.0, 3.0, 12.0, 14.0],
                      [0.0, 0.0, 15.0, 15.0]], "float32")
    boxes_num = np.array([2, 1])
    got = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                      boxes_num, output_size=4, spatial_scale=1.0,
                      sampling_ratio=2, aligned=True).numpy()
    ref = _roi_align_np(x, boxes, [0, 0, 1], 4, ratio=2, aligned=True)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_roi_pool_shape_and_range():
    rs = np.random.RandomState(2)
    x = rs.randn(1, 2, 8, 8).astype("float32")
    boxes = np.array([[0.0, 0.0, 7.0, 7.0]], "float32")
    out = V.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes), [1],
                     output_size=2).numpy()
    assert out.shape == (1, 2, 2, 2)
    assert out.max() <= x.max() + 1e-6


def test_yolo_box_decode():
    rs = np.random.RandomState(3)
    N, A, C, H, W = 1, 2, 3, 4, 4
    x = rs.randn(N, A * (5 + C), H, W).astype("float32")
    img = np.array([[128, 128]], "int32")
    boxes, scores = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                               anchors=[10, 13, 16, 30], class_num=C,
                               conf_thresh=0.0, downsample_ratio=32)
    b, s = boxes.numpy(), scores.numpy()
    assert b.shape == (N, A * H * W, 4) and s.shape == (N, A * H * W, C)
    assert (b[..., 2] >= b[..., 0]).all() and (b[..., 3] >= b[..., 1]).all()
    assert b.min() >= 0 and b.max() <= 127.0 + 1e-5  # clipped to image
    assert (s >= 0).all() and (s <= 1).all()


def test_box_coder_roundtrip():
    prior = np.array([[10, 10, 30, 40], [5, 5, 15, 25]], "float32")
    target = np.array([[12, 11, 28, 42], [6, 7, 14, 22]], "float32")
    var = np.ones_like(prior)
    code = V.box_coder(paddle.to_tensor(prior), paddle.to_tensor(var),
                       paddle.to_tensor(target), "encode_center_size").numpy()
    back = V.box_coder(paddle.to_tensor(prior), paddle.to_tensor(var),
                       paddle.to_tensor(code), "decode_center_size").numpy()
    np.testing.assert_allclose(back, target, rtol=1e-4, atol=1e-4)


def test_deform_conv2d_zero_offset_equals_conv2d():
    import paddle_tpu.nn.functional as F

    rs = np.random.RandomState(4)
    x = rs.randn(2, 3, 8, 8).astype("float32")
    w = rs.randn(4, 3, 3, 3).astype("float32") * 0.1
    offset = np.zeros((2, 2 * 9, 6, 6), "float32")
    got = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(offset),
                          paddle.to_tensor(w)).numpy()
    ref = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; --durations top mover
def test_deform_conv_layer_trains():
    paddle.seed(44)
    layer = V.DeformConv2D(2, 3, 3, padding=1)
    x = paddle.to_tensor(np.random.RandomState(5).randn(1, 2, 6, 6).astype("float32"))
    offset = paddle.to_tensor(
        0.1 * np.random.RandomState(6).randn(1, 18, 6, 6).astype("float32"))
    out = layer(x, offset)
    assert tuple(out.shape) == (1, 3, 6, 6)
    loss = paddle.sum(out * out)
    loss.backward()
    assert layer.weight.grad is not None


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10],      # small -> low level
                     [0, 0, 224, 224],    # refer scale -> refer level
                     [0, 0, 500, 500]],   # big -> high level
                    "float32")
    outs, idxs, restore = V.distribute_fpn_proposals(
        paddle.to_tensor(rois), 2, 5, 4, 224)
    assert len(outs) == 4
    sizes = [o.numpy().shape[0] for o in outs]
    assert sum(sizes) == 3
    assert outs[2].numpy().shape[0] >= 1  # 224-scale roi at refer level 4
    order = np.concatenate([i.numpy() for i in idxs])
    np.testing.assert_array_equal(order[restore.numpy()], np.arange(3))


def test_deform_conv_deformable_groups():
    """dg=2: each channel half must follow its own offset group."""
    rs = np.random.RandomState(7)
    x = rs.randn(1, 4, 6, 6).astype("float32")
    w = np.zeros((4, 4, 1, 1), "float32")
    for i in range(4):
        w[i, i] = 1.0  # identity 1x1 conv
    # group 0: zero offset; group 1: shift sampling by +1 in x
    offset = np.zeros((1, 2 * 2 * 1, 6, 6), "float32")
    offset[:, 3] = 1.0  # dg=1's dx
    got = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(offset),
                          paddle.to_tensor(w), deformable_groups=2).numpy()
    np.testing.assert_allclose(got[:, :2], x[:, :2], rtol=1e-5)  # unshifted
    np.testing.assert_allclose(got[:, 2:, :, :-1], x[:, 2:, :, 1:],
                               rtol=1e-5)  # shifted by one pixel
