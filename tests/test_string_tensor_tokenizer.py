"""StringTensor-lite + faster_tokenizer op (VERDICT r3 item 9).

Ground truth: HuggingFace transformers.BertTokenizer (the canonical BERT
wordpiece implementation) run offline on a local vocab fixture.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text import (
    BertTokenizerLite,
    FasterTokenizer,
    StringTensor,
    faster_tokenizer,
    to_map_tensor,
    to_string_tensor,
)

_VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
          "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over",
          "lazy", "dog", "un", "##want", "##able", "runn", "##ing", ",", ".",
          "!", "?", "hello", "world", "中", "国"]
VOCAB = {t: i for i, t in enumerate(_VOCAB)}


def test_string_tensor_basics():
    st = to_string_tensor(["a b", "c"], name="txt")
    assert st.shape == [2] and st.dtype == "pstring" and st.place == "cpu"
    assert st[0] == "a b" and list(st) == ["a b", "c"]
    assert st.numpy().dtype == object
    vt = to_map_tensor(VOCAB, name="vocab")
    assert vt["the"] == 5 and "fox" in vt and len(vt) == len(VOCAB)
    assert vt.get_map_tensor()["[CLS]"] == 2


def test_wordpiece_greedy_longest_match():
    tok = BertTokenizerLite(VOCAB)
    # "jumped" -> jump + ##ed ; "unwanted" -> un + ##want + ##ed
    assert tok.tokenize("jumped") == [VOCAB["jump"], VOCAB["##ed"]]
    assert tok.tokenize("unwanted") == [VOCAB["un"], VOCAB["##want"],
                                        VOCAB["##ed"]]
    # unknown word -> [UNK] (whole word, not partial pieces)
    assert tok.tokenize("zzz") == [VOCAB["[UNK]"]]
    # CJK chars split to singles
    assert tok.tokenize("中国") == [VOCAB["中"], VOCAB["国"]]


def test_faster_tokenizer_op_batch_and_pairs():
    texts = to_string_tensor(["The quick brown fox", "hello world!"])
    ids, tt = faster_tokenizer(VOCAB, texts)
    ids, tt = ids.numpy(), tt.numpy()
    assert ids.shape == tt.shape and ids.dtype == np.int32
    # row 0: [CLS] the quick brown fox [SEP]
    np.testing.assert_array_equal(
        ids[0], [VOCAB["[CLS]"], VOCAB["the"], VOCAB["quick"],
                 VOCAB["brown"], VOCAB["fox"], VOCAB["[SEP]"]])
    # row 1 right-padded with [PAD]=0
    assert ids[1, -1] == VOCAB["[PAD]"]
    assert (tt == 0).all()  # single sequences: all segment 0

    ids2, tt2 = faster_tokenizer(VOCAB, ["hello"], ["world"])
    row, seg = ids2.numpy()[0], tt2.numpy()[0]
    np.testing.assert_array_equal(
        row, [VOCAB["[CLS]"], VOCAB["hello"], VOCAB["[SEP]"],
              VOCAB["world"], VOCAB["[SEP]"]])
    np.testing.assert_array_equal(seg, [0, 0, 0, 1, 1])


def test_faster_tokenizer_truncation_and_padding():
    ids, _ = faster_tokenizer(VOCAB, ["the quick brown fox jumped over"],
                              max_seq_len=5, pad_to_max_seq_len=True)
    row = ids.numpy()[0]
    assert row.shape == (5,)
    assert row[0] == VOCAB["[CLS]"] and row[-1] == VOCAB["[SEP]"]


@pytest.mark.slow
def test_faster_tokenizer_layer_feeds_bert():
    from paddle_tpu.text import BertModel
    from paddle_tpu.text.bert import BertConfig

    layer = FasterTokenizer(VOCAB)
    ids, tt = layer(StringTensor(["the lazy dog", "hello world"]))
    paddle.seed(0)
    bert = BertModel(BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                                num_heads=2, intermediate_size=64,
                                max_position_embeddings=32))
    out = bert(ids, token_type_ids=tt)
    seq_out = out[0] if isinstance(out, (tuple, list)) else out
    assert np.isfinite(np.asarray(seq_out._value)).all()


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; --durations top mover
def test_matches_huggingface_bert_tokenizer(tmp_path):
    transformers = pytest.importorskip("transformers")
    vocab_file = tmp_path / "vocab.txt"
    vocab_file.write_text("\n".join(_VOCAB))
    hf = transformers.BertTokenizer(str(vocab_file), do_lower_case=True)
    ours = BertTokenizerLite(VOCAB)
    for text in ["The QUICK brown fox jumped!", "unwanted running, dogs?",
                 "hello 中国 world.", "Jumps over the lazy dog"]:
        hf_ids = hf.encode(text)  # includes [CLS]/[SEP]
        our_ids, _ = ours.encode(text)
        assert our_ids == hf_ids, (text, our_ids, hf_ids)
    # pair encoding + segment ids
    enc = hf(text="hello world", text_pair="the fox", return_token_type_ids=True)
    our_ids, our_tt = ours.encode("hello world", "the fox")
    assert our_ids == enc["input_ids"]
    assert our_tt == enc["token_type_ids"]
