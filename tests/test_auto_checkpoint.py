"""Auto-checkpoint tests (reference: fluid/incubate/checkpoint/
auto_checkpoint.py:71 + its unittests — crash mid-range, relaunch, resume
from the last completed epoch with weights restored). Also covers the new
(src,dst)-addressed in-graph p2p ops (send_v2/recv_v2, D5 depth)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate import auto_checkpoint as ac


@pytest.fixture(autouse=True)
def _clean():
    ac.reset()
    yield
    ac.reset()


def _make(seed):
    paddle.seed(seed)
    m = nn.Linear(4, 2)
    opt = paddle.optimizer.Momentum(0.1, parameters=m.parameters())
    return m, opt


def test_train_epoch_range_resumes_after_crash(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_JOB_ID", "job7")
    x = np.random.RandomState(0).rand(8, 4).astype(np.float32)
    y = np.random.RandomState(0).randint(0, 2, (8,))

    def epoch_step(m, opt):
        loss = nn.functional.cross_entropy(m(paddle.to_tensor(x)),
                                           paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()

    # run 1: "crashes" after completing epoch 2. The break skips epoch 2's
    # post-yield snapshot, so the durable state is epoch 1 — a fresh process
    # must redo epoch 2 (at-least-once semantics, same as the reference).
    m1, opt1 = _make(1)
    ac.register(model=m1, optimizer=opt1)
    done = []
    for epoch in ac.train_epoch_range(6, dirname=str(tmp_path)):
        epoch_step(m1, opt1)
        done.append(epoch)
        if epoch == 2:
            break  # simulated crash
    assert done == [0, 1, 2]

    ac.reset()
    m2, opt2 = _make(99)  # different init: restore must overwrite it
    ac.register(model=m2, optimizer=opt2)
    resumed = list(ac.train_epoch_range(6, dirname=str(tmp_path)))
    assert resumed == [2, 3, 4, 5]

    # run 3: everything completed -> nothing to do
    ac.reset()
    m3, opt3 = _make(5)
    ac.register(model=m3, optimizer=opt3)
    assert list(ac.train_epoch_range(6, dirname=str(tmp_path))) == []


def test_restore_actually_loads_weights(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_JOB_ID", "jobw")
    m1, opt1 = _make(3)
    ac.register(model=m1)
    for epoch in ac.train_epoch_range(1, dirname=str(tmp_path)):
        m1.weight.set_value(np.full((4, 2), 7.0, np.float32))
    ac.reset()
    m2, _ = _make(42)
    ac.register(model=m2)
    rng = ac.train_epoch_range(5, dirname=str(tmp_path))
    assert rng.restored_epoch == 0
    np.testing.assert_allclose(m2.weight.numpy(), 7.0)


def test_send_v2_recv_v2_pair_addressed():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed import ops as cops

    devs = np.asarray(jax.devices()[:4])
    mesh = Mesh(devs, ("pp",))
    vals = np.arange(4, dtype=np.float32) + 1  # rank r holds r+1

    f = jax.jit(jax.shard_map(
        lambda v: cops.send_v2(v, "pp", dst=3, src=1),
        mesh=mesh, in_specs=P("pp"), out_specs=P("pp")))
    out = np.asarray(f(jnp.asarray(vals)))
    assert out[3] == 2.0  # rank 3 received rank 1's value
    assert out[0] == 0.0 and out[1] == 0.0 and out[2] == 0.0  # others: zeros

    g = jax.jit(jax.shard_map(
        lambda v: cops.p2p_exchange(v, "pp", [(0, 1), (2, 3)]),
        mesh=mesh, in_specs=P("pp"), out_specs=P("pp")))
    out2 = np.asarray(g(jnp.asarray(vals)))
    assert out2[1] == 1.0 and out2[3] == 3.0
    assert out2[0] == 0.0 and out2[2] == 0.0

    # recv_v2: explicit dst + the default-dst convention (src+1)
    h = jax.jit(jax.shard_map(
        lambda v: cops.recv_v2(v, "pp", src=2, dst=0),
        mesh=mesh, in_specs=P("pp"), out_specs=P("pp")))
    out3 = np.asarray(h(jnp.asarray(vals)))
    assert out3[0] == 3.0 and (out3[1:] == 0.0).all()
    h2 = jax.jit(jax.shard_map(
        lambda v: cops.recv_v2(v, "pp", src=3),  # default dst = (3+1)%4 = 0
        mesh=mesh, in_specs=P("pp"), out_specs=P("pp")))
    out4 = np.asarray(h2(jnp.asarray(vals)))
    assert out4[0] == 4.0 and (out4[1:] == 0.0).all()
