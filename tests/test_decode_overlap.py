"""Decode overlap triad (PR 18): the double-buffered page-DMA pipeline in
the ragged kernel, the hlocheck async-collective overlap census, and the
quantized logits all-reduce.

- **Overlap census on hand-built HLO**: sync-only programs report 0/N
  with byte counts identical to their async compilation, a ``-start``
  immediately followed by its ``-done`` counts as NOT overlapped (and
  fails a ``min_overlap_frac`` budget), fully interleaved programs count
  every in-flight instruction, and XLA's variadic combiner-merged form
  charges the result half of the tuple — so byte caps hold across
  sync/async/combined compilation of the same traffic.
- **Pipelined kernel parity**: chunked double-buffered staging (chunk <
  pages_per_seq) stays within float tolerance of the jitted composite in
  interpret mode for decode/verify x fp32/int8, and the chunk ==
  pages_per_seq path is BIT-identical to the default single-buffer
  gather; tuned-table dict schema + stale-chunk validation.
- **Quantized psum**: numeric parity vs the exact f32 psum (shared-scale
  int8 codes can never overflow the int8 accumulator), zero-input safe.
- **Engine level (TP=2 on the conftest CPU mesh)**: overlap-scheduler on
  + quantized off is bit-identical to the baseline sharded engine; the
  quantized logits all-reduce certifies at 2L+2 all-reduces with the
  census bytes UNDER the f32 budget's cap (the measurable shrink), at
  bounded greedy divergence (mean common-prefix >= 0.5); the
  ``serving_tp_collective_overlap_frac`` gauge is pre-seeded and fed at
  the first-trace audit.
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle

pytestmark = pytest.mark.overlap

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.analysis import hlocheck  # noqa: E402
from paddle_tpu.analysis.hlocheck import (  # noqa: E402
    CollectiveBudget, CollectiveOverlapError, HloAuditReport, census)
from paddle_tpu.kernels import paged_attention as pa  # noqa: E402
from paddle_tpu.kernels import ragged_paged_attention as rp  # noqa: E402
from paddle_tpu.serving import ServingConfig, ServingEngine  # noqa: E402
from paddle_tpu.serving import scheduler as sched_mod  # noqa: E402
from paddle_tpu.serving.tp import TPContext, quantized_psum  # noqa: E402
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM  # noqa: E402

# ------------------------------------------------- hand-built HLO fixtures
_SYNC = """
ENTRY %main {
  %p0 = f32[4,8] parameter(0)
  %mul = f32[4,8] multiply(%p0, %p0)
  %ar.1 = f32[4,8] all-reduce(%mul), replica_groups={}
  %add = f32[4,8] add(%ar.1, %p0)
  %ar.2 = f32[4,8] all-reduce(%add), replica_groups={}
  ROOT %out = f32[4,8] add(%ar.2, %mul)
}
"""

_ASYNC_OVERLAPPED = """
ENTRY %main {
  %p0 = f32[4,8] parameter(0)
  %ars.1 = (f32[4,8], f32[4,8]) all-reduce-start(%p0), replica_groups={}
  %mul = f32[4,8] multiply(%p0, %p0)
  %ard.1 = f32[4,8] all-reduce-done(f32[4,8] %ars.1)
  %ars.2 = (f32[4,8], f32[4,8]) all-reduce-start(%ard.1), replica_groups={}
  %mul2 = f32[4,8] multiply(%mul, %mul)
  %mul3 = f32[4,8] multiply(%mul2, %mul)
  %ard.2 = f32[4,8] all-reduce-done(f32[4,8] %ars.2)
  ROOT %out = f32[4,8] add(%ard.2, %mul3)
}
"""

_ASYNC_SERIALIZED = """
ENTRY %main {
  %p0 = f32[4,8] parameter(0)
  %ars = (f32[4,8], f32[4,8]) all-reduce-start(%p0), replica_groups={}
  %ard = f32[4,8] all-reduce-done(f32[4,8] %ars)
  ROOT %out = f32[4,8] add(%ard, %p0)
}
"""

# XLA's all-reduce combiner merged two collectives (f32 + sub-byte s8
# payloads) into ONE variadic async pair: the start's tuple carries the
# operand AND result halves
_ASYNC_VARIADIC = """
ENTRY %main {
  %p0 = f32[4,8] parameter(0)
  %p1 = s8[16] parameter(1)
  %ars = (f32[4,8], s8[16], f32[4,8], s8[16]) all-reduce-start(%p0, %p1), replica_groups={}
  %mul = f32[4,8] multiply(%p0, %p0)
  %ard = (f32[4,8], s8[16]) all-reduce-done((f32[4,8], s8[16]) %ars)
  ROOT %out = f32[4,8] add(%mul, %p0)
}
"""


def _report(name, text):
    colls, hosts = census(text)
    return HloAuditReport(name=name, collectives=colls,
                          host_transfers=hosts)


def test_census_sync_only_reports_zero_overlap():
    r = _report("sync", _SYNC)
    assert len(r.collectives) == 2
    assert all(not c.is_async and c.overlap == 0 for c in r.collectives)
    assert r.async_collectives == 0
    assert r.overlapped_collectives == 0
    assert r.overlap_frac == 0.0
    assert "overlap n/a (sync)" in r.summary()
    assert "compiled sync" in r.overlap_summary()


def test_census_async_fully_overlapped():
    r = _report("async", _ASYNC_OVERLAPPED)
    assert [c.is_async for c in r.collectives] == [True, True]
    # first pair hides the one multiply, second pair hides two
    assert [c.overlap for c in r.collectives] == [1, 2]
    assert r.async_collectives == 2
    assert r.overlapped_collectives == 2
    assert r.overlap_frac == 1.0
    assert "overlap 2/2 async" in r.summary()
    assert "2/2 async collective(s) overlapped" in r.overlap_summary()


def test_census_start_immediately_done_is_not_overlapped():
    """The async FORM alone buys nothing: a -start whose -done is the
    very next instruction hid zero compute and must count that way."""
    r = _report("serialized", _ASYNC_SERIALIZED)
    (c,) = r.collectives
    assert c.is_async and c.overlap == 0
    assert r.overlap_frac == 0.0
    # ...and it fails an overlap-demanding budget, naming the op
    with pytest.raises(CollectiveOverlapError) as ei:
        r.enforce(CollectiveBudget(all_reduce=1, min_overlap_frac=1.0))
    assert "0/1" in str(ei.value) and "all-reduce-start" in str(ei.value)


def test_census_min_overlap_frac_is_vacuous_for_sync_programs():
    """CPU backends compile collectives sync — the SAME overlap-demanding
    budget the tp2 registry entries declare must pass there, so the
    certification runs anywhere (and bites only where async pairs
    exist)."""
    budget = CollectiveBudget(all_reduce=2, min_overlap_frac=1.0)
    _report("sync", _SYNC).enforce(budget)  # must not raise
    # zero-collective programs pass too
    HloAuditReport(name="empty").enforce(
        CollectiveBudget(min_overlap_frac=1.0))
    # and a fully overlapped async program passes the same budget
    _report("async", _ASYNC_OVERLAPPED).enforce(budget)


def test_census_variadic_combiner_merged_form():
    """The merged start charges the RESULT half of its tuple — bytes the
    sync form(s) would report — with sub-byte-accurate s8 widths, and
    still tracks overlap until its (tuple-typed) done."""
    r = _report("variadic", _ASYNC_VARIADIC)
    (c,) = r.collectives
    assert c.is_async
    assert c.nbytes == 4 * 8 * 4 + 16  # f32[4,8] + s8[16], result half
    assert c.overlap == 1  # the one multiply before the done
    assert r.overlap_frac == 1.0


def test_census_bytes_and_counts_identical_sync_vs_async():
    """One budget certifies one traffic pattern regardless of how the
    backend compiled it: counts() and collective_bytes agree between the
    sync program and its async compilation, so a byte cap written
    against either holds for both."""
    sync = _report("s", _SYNC)
    async_ = _report("a", _ASYNC_OVERLAPPED)
    assert sync.counts() == async_.counts() == {"all-reduce": 2}
    assert sync.collective_bytes == async_.collective_bytes == 2 * 128
    cap = CollectiveBudget(all_reduce=2, max_collective_bytes=256)
    sync.enforce(cap)
    async_.enforce(cap)


def test_cli_overlap_view_and_child_forwarding(monkeypatch, capsys):
    """--overlap prints the per-collective view in-process, and a step
    respawned onto a forced CPU mesh carries the flag into the child
    command line (the child prints the view for us)."""
    rep = _report("engine_decode", _ASYNC_OVERLAPPED)
    monkeypatch.setattr(hlocheck, "run_step", lambda name: rep)
    rc = hlocheck.main(["--step", "engine_decode", "--overlap"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "2/2 async collective(s) overlapped" in out
    assert "overlap=2" in out

    import subprocess

    recorded = {}

    class _Done:
        returncode, stdout = 0, b""

    def fake_run(cmd, **kw):
        recorded["cmd"] = cmd
        return _Done()

    monkeypatch.setattr(subprocess, "run", fake_run)
    spec = hlocheck.StepSpec("fake", "doc", lambda: None, min_devices=99)
    hlocheck._run_in_subprocess(spec, overlap=True)
    assert "--overlap" in recorded["cmd"]
    hlocheck._run_in_subprocess(spec, overlap=False)
    assert "--overlap" not in recorded["cmd"]


# ------------------------------------------------ pipelined kernel parity
def _composite(q, kp, vp, tab, ctx, k_scale=None, v_scale=None,
               scale=None):
    from paddle_tpu.kernels.attention import sdpa

    s = q.shape[2]
    if k_scale is not None:
        k_all = pa.paged_gather_quant(kp, k_scale, tab, q.dtype)
        v_all = pa.paged_gather_quant(vp, v_scale, tab, q.dtype)
    else:
        k_all = pa.paged_gather(kp, tab)
        v_all = pa.paged_gather(vp, tab)
    mask = pa.ragged_mask(ctx, k_all.shape[2], s)
    return sdpa(q, k_all, v_all, mask=mask, scale=scale)


def _args(seed, b, h, s, d, ps, pps, npages, ctx_vals, quant=False):
    rng = np.random.RandomState(seed)
    if quant:
        kp = jnp.asarray(rng.randint(-127, 128, (npages, ps, h, d)),
                         jnp.int8)
        vp = jnp.asarray(rng.randint(-127, 128, (npages, ps, h, d)),
                         jnp.int8)
        kw = dict(
            k_scale=jnp.asarray(np.abs(rng.randn(npages, h)) + 0.1,
                                jnp.float32),
            v_scale=jnp.asarray(np.abs(rng.randn(npages, h)) + 0.1,
                                jnp.float32))
    else:
        kp = jnp.asarray(rng.randn(npages, ps, h, d), jnp.float32)
        vp = jnp.asarray(rng.randn(npages, ps, h, d), jnp.float32)
        kw = {}
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    tab = jnp.asarray(
        rng.choice(npages, (b, pps), replace=False).astype(np.int32))
    ctx = jnp.asarray(ctx_vals, jnp.int32)
    return (q, kp, vp, tab, ctx), kw


# (batch, heads, s, head_dim, page_size, pages_per_seq, npages, ctx):
# decode (1 query) and spec-verify (K+1 queries) — the two shapes the
# pipeline serves on the decode hot path
_PIPE_SHAPES = {
    "decode": (2, 2, 1, 8, 4, 4, 16, [5, 9]),
    "verify": (3, 4, 5, 16, 4, 8, 40, [10, 3, 17]),
}


@pytest.mark.parametrize("quant", [False, True], ids=["fp32", "int8"])
@pytest.mark.parametrize("mode", sorted(_PIPE_SHAPES))
def test_pipelined_chunks_match_composite(mode, quant):
    """Every chunking of the page row — including the 1-page chunk, the
    deepest pipeline — stays within fp32-accumulation tolerance of the
    composite: the online-softmax fold re-orders the reduction, so the
    pin is tight allclose, not bit-equality (that's the chunk == pps
    test below). Page accounting is exact: identical tables, ctx
    lengths, and output shape for every chunk."""
    shape = _PIPE_SHAPES[mode]
    pps = shape[5]
    args, kw = _args(3 + int(quant), *shape, quant=quant)
    ref = jax.jit(lambda *a: _composite(*a, **kw))(*args)
    for chunk in [c for c in (1, 2, 4) if c < pps] + [pps]:
        out = jax.jit(lambda *a, c=chunk: rp.ragged_paged_attention(
            *a, interpret=True, pipeline_chunk=c, **kw))(*args)
        assert out.shape == ref.shape
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5,
            err_msg=f"{mode}/{'int8' if quant else 'fp32'} chunk={chunk}")


def test_single_chunk_is_bit_identical_to_default():
    """chunk == pages_per_seq is the exact pre-pipeline path: same DMA
    plan, same op-for-op compute — bit-identical to calling without the
    knob (the tier-1 ragged suite's bit-identity pins ride this path)."""
    args, kw = _args(9, 2, 2, 1, 8, 4, 4, 16, [5, 9])
    base = jax.jit(lambda *a: rp.ragged_paged_attention(
        *a, interpret=True, **kw))(*args)
    pinned = jax.jit(lambda *a: rp.ragged_paged_attention(
        *a, interpret=True, pipeline_chunk=4, **kw))(*args)
    assert np.array_equal(np.asarray(base), np.asarray(pinned))


def test_bad_pipeline_chunk_falls_back_to_single_chunk():
    """A chunk that doesn't divide the call's page count (e.g. a tuned
    entry from a different window) must not crash or change numbers —
    the launch falls back to the exact single-chunk plan."""
    args, kw = _args(9, 2, 2, 1, 8, 4, 4, 16, [5, 9])
    base = jax.jit(lambda *a: rp.ragged_paged_attention(
        *a, interpret=True, **kw))(*args)
    for bad in (3, 0, -2, 8):
        out = jax.jit(lambda *a, c=bad: rp.ragged_paged_attention(
            *a, interpret=True, pipeline_chunk=c, **kw))(*args)
        assert np.array_equal(np.asarray(base), np.asarray(out)), bad


def test_tuned_dict_schema_and_stale_chunk_validation(monkeypatch):
    from paddle_tpu.analysis.kernelcheck import validate_ragged_tuned

    # dict schema: block_heads + pipeline_chunk resolved from the table
    monkeypatch.setattr(rp, "_tuned_table", lambda: {
        "16,8,128": {"block_heads": 4, "pipeline_chunk": 8,
                     "pages_per_seq": 32},
        "32,8,128": 2,  # legacy bare-int schema still resolves
    })
    assert rp.block_heads_for(16, 8, 128) == 4
    assert rp.pipeline_chunk_for(16, 8, 128, 32) == 8
    # the tuned chunk still divides a 24-page call (usable), but a
    # 20-page call can't mis-tile — fall back to the exact single chunk
    assert rp.pipeline_chunk_for(16, 8, 128, 24) == 8
    assert rp.pipeline_chunk_for(16, 8, 128, 20) == 20
    assert rp.block_heads_for(32, 8, 128) == 2
    assert rp.pipeline_chunk_for(32, 8, 128, 16) == 16  # legacy: no knob

    ok = {"16,8,128": {"block_heads": 4, "pipeline_chunk": 8,
                       "pages_per_seq": 32}}
    assert validate_ragged_tuned(ok) == []
    stale = {"16,8,128": {"block_heads": 4, "pipeline_chunk": 5,
                          "pages_per_seq": 32}}
    errs = validate_ragged_tuned(stale)
    assert errs and "stale" in errs[0]
    unknown = {"16,8,128": {"block_heads": 4, "pipeline_speed": 9}}
    assert validate_ragged_tuned(unknown)
    # a chunk with no divisibility anchor is unverifiable -> rejected
    anchorless = {"16,8,128": {"block_heads": 4, "pipeline_chunk": 8}}
    assert validate_ragged_tuned(anchorless)


def test_vmem_model_prices_double_buffered_staging():
    """chunk < pages_per_seq stages TWO buffers of chunk pages per pool:
    the dispatch-gate working set must price exactly that (the x2 the
    kernelcheck scratch certification matches), and chunk ==
    pages_per_seq must reproduce the pre-pipeline single-buffer number."""
    d, total_kv, nq, bh, pps = 128, 512, 1, 1, 32
    single = rp._vmem_working_set(d, total_kv, nq, bh, pps, False)
    pinned = rp._vmem_working_set(d, total_kv, nq, bh, pps, False,
                                  pipeline_chunk=pps)
    assert single == pinned
    chunked = rp._vmem_working_set(d, total_kv, nq, bh, pps, False,
                                   pipeline_chunk=8)
    per_page_kv = (total_kv // pps)
    # staging shrinks 32 pages -> 2 x 8 pages per pool (K and V, fp32)
    expected_delta = 2 * (total_kv - 2 * 8 * per_page_kv) * bh * d * 4
    assert single - chunked == expected_delta


# ------------------------------------------------------- quantized psum
def test_quantized_psum_parity_and_safety():
    if len(jax.devices()) < 4:
        pytest.skip("needs the conftest 8-device CPU mesh")
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]), ("tp",))
    f = jax.jit(shard_map(
        lambda xs: quantized_psum(xs[0], "tp"), mesh=mesh,
        in_specs=(P("tp", None, None),), out_specs=P()))

    x = np.random.RandomState(0).randn(4, 8, 97).astype(np.float32) * 3
    out, exact = np.asarray(f(jnp.asarray(x))), x.sum(0)
    rel = np.abs(out - exact).max() / np.abs(exact).max()
    assert rel < 0.05, rel
    # greedy decisions survive: the argmax rows agree
    assert (out.argmax(-1) == exact.argmax(-1)).mean() >= 0.9
    # all-zero input: the step guard keeps it NaN-free and exact
    z = np.asarray(f(jnp.zeros((4, 8, 97), np.float32)))
    assert np.all(z == 0)
    # overflow safety: identical extreme shards sum WITHOUT int8 wrap
    # (the shared step is sum(absmax)/(127-n), so accumulated codes are
    # provably < 127) — a naive absmax/127 scale wraps here
    e = np.full((4, 8, 97), 1e4, np.float32)
    oe = np.asarray(f(jnp.asarray(e)))
    assert np.all(oe > 0), "int8 accumulator wrapped"
    assert np.abs(oe - e.sum(0)).max() / 4e4 < 0.05


# --------------------------------------------------- engine level (TP=2)
HIDDEN, LAYERS, HEADS, VOCAB = 32, 2, 4, 97


@pytest.fixture(scope="module")
def model():
    if len(jax.devices()) < 2:
        pytest.skip("needs the conftest 8-device CPU mesh")
    paddle.seed(31)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=VOCAB, hidden_size=HIDDEN, num_layers=LAYERS,
        num_heads=HEADS, max_seq_len=48, dropout=0.0))
    m.eval()
    return m


def _drive(model, prompts, budgets, **kw):
    sched_mod._rid_counter = itertools.count(9000)
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8,
        tensor_parallel=2, **kw))
    rids = [eng.add_request(p, b) for p, b in zip(prompts, budgets)]
    outs = eng.run()
    return [outs[r] for r in rids], eng


def _prompts(seed, lens):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, (n,)).astype(np.int32) for n in lens]


def test_budget_shapes_quantized_and_overlap():
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=HIDDEN,
                    num_layers=LAYERS, num_heads=HEADS)
    plain = TPContext(2, cfg).step_budget(batch=2, seq=1)
    assert plain.all_reduce == 2 * LAYERS + 1
    assert plain.min_overlap_frac == 0.0
    ov = TPContext(2, cfg, overlap_scheduler=True).step_budget(2, 1)
    assert ov.all_reduce == 2 * LAYERS + 1
    assert ov.min_overlap_frac == 1.0
    q = TPContext(2, cfg, quantized_logits=True).step_budget(2, 1)
    assert q.all_reduce == 2 * LAYERS + 2
    f32_logits, q_logits = 2 * 1 * VOCAB * 4, 2 * 1 * VOCAB * 1 + 4
    assert plain.max_collective_bytes - q.max_collective_bytes == \
        f32_logits - q_logits


def test_overlap_on_quantized_off_is_bit_identical(model):
    """tp_overlap_scheduler changes WHEN collectives run, never what
    they compute — and is a declared no-op on backends without the
    scheduler (CPU) — so the token streams must match the baseline
    sharded engine bit for bit. tp_quantized_logits=False must too: the
    quantized branch never traces."""
    prompts, budgets = _prompts(4, (3, 6)), [6, 5]
    ref, _ = _drive(model, prompts, budgets)
    outs, eng = _drive(model, prompts, budgets,
                       tp_overlap_scheduler=True,
                       tp_quantized_logits=False)
    assert all(np.array_equal(a, b) for a, b in zip(ref, outs))
    assert eng.compile_counts == {"prefill": 1, "decode": 1}


def test_quantized_logits_census_divergence_and_gauges(model):
    """The acceptance pins in one sharded debug_checks engine: the
    quantized decode program audits at 2L+2 all-reduces with census
    bytes UNDER the f32 budget's cap (the measurable bytes/token
    shrink), greedy outputs diverge boundedly (mean common-prefix >=
    0.5 vs quantized-off), zero retraces, and the overlap/bytes gauges
    are pre-seeded then fed at the first-trace audit."""
    prompts, budgets = _prompts(4, (3, 6)), [6, 5]
    ref, _ = _drive(model, prompts, budgets)
    outs, eng = _drive(model, prompts, budgets, debug_checks=True,
                       tp_overlap_scheduler=True,
                       tp_quantized_logits=True)

    # bounded greedy divergence (the kvq idiom: loose bound, tight
    # measurement — these toy streams measure 1.0 most seeds)
    fracs = []
    for a, b in zip(ref, outs):
        common = 0
        for x, y in zip(a, b):
            if x != y:
                break
            common += 1
        fracs.append(common / len(a))
    assert np.mean(fracs) >= 0.5, f"divergence too high: {fracs}"

    # the compiled census: exactly 2L+2 all-reduces, bytes under the
    # unquantized budget's cap — the shrink is measured, not assumed
    report = eng.hlo_audits["decode"]
    assert report.counts() == {"all-reduce": 2 * LAYERS + 2}
    f32_cap = TPContext(2, model.cfg).step_budget(
        batch=2, seq=1).max_collective_bytes
    assert report.collective_bytes < f32_cap
    assert eng.compile_counts == {"prefill": 1, "decode": 1}
    assert all(g.retraces == 0 for g in eng.guards.values())

    # gauges: seeded names present; bytes/token fed and under the f32
    # cap per token; overlap_frac fed (0.0 — CPU compiles these sync)
    snap = eng.metrics.snapshot()
    assert "serving_tp_collective_overlap_frac" in snap
    bpt = snap["serving_tp_collective_bytes_per_token"]
    assert 0 < bpt < f32_cap / 2
    assert snap["serving_tp_collective_overlap_frac"] == 0.0


def test_registry_quantized_logits_step_certifies():
    """The tp2_engine_decode_qlogits REGISTRY entry certifies end to end
    on this process's mesh (conftest forces 8 CPU devices): budget
    2L+2, int8 logits payload counted bit-accurately, overlap contract
    declared."""
    if len(jax.devices()) < 2:
        pytest.skip("needs the conftest 8-device CPU mesh")
    report = hlocheck.run_step("tp2_engine_decode_qlogits")
    assert report.counts() == {"all-reduce": 2 * 2 + 2}
    sync_bytes = hlocheck.run_step("tp2_engine_decode").collective_bytes
    assert report.collective_bytes < sync_bytes
