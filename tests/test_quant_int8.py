"""INT8 execution path (VERDICT r4 missing #4): PTQ scales are CONSUMED by
an int8 runtime — weights stored int8, dots/convs accumulate in int32 on the
MXU, accuracy within tolerance of fp32, measured size reduction — plus the
KL/mse/hist calibration algorithms.

Reference: slim/quantization/post_training_quantization.py (algo dispatch),
quantization_pass.py (QuantizationFreezePass).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.quantization import (
    PostTrainingQuantization, convert_to_int8, load_quantized_model)
from paddle_tpu.quantization.int8 import (
    HistogramObserver, compute_hist_scale, compute_kl_scale,
    compute_mse_scale)


def _small_convnet():
    paddle.seed(11)
    return paddle.nn.Sequential(
        paddle.nn.Conv2D(3, 8, 3, padding=1),
        paddle.nn.ReLU(),
        paddle.nn.Conv2D(8, 8, 3, stride=2, padding=1),
        paddle.nn.ReLU(),
        paddle.nn.Flatten(),
        paddle.nn.Linear(8 * 8 * 8, 10),
    )


def _calib_batches(n=4, bs=4):
    rng = np.random.RandomState(0)
    return [rng.rand(bs, 3, 16, 16).astype("float32") * 2 - 1
            for _ in range(n)]


@pytest.mark.slow
def test_int8_execution_accuracy_and_size():
    model = _small_convnet()
    model.eval()
    fp32_weight_bytes = sum(
        s.weight.numpy().nbytes for s in
        [model._sub_layers[k] for k in ("0", "2", "5")])
    x = _calib_batches(1)[0]
    ref = model(paddle.to_tensor(x)).numpy()

    ptq = PostTrainingQuantization(model=model,
                                   data_loader=_calib_batches(),
                                   algo="abs_max")
    ptq.quantize()
    n = ptq.convert_to_int8()
    assert n == 3  # two convs + one linear now execute int8

    got = model(paddle.to_tensor(x)).numpy()
    # int8 is lossy; the deploy gate is relative error on the logits
    denom = np.abs(ref).max()
    rel = np.abs(got - ref).max() / denom
    assert rel < 0.08, f"int8 relative error {rel:.4f}"

    # measured size reduction: int8 codebooks vs the model's REAL fp32
    # weights (captured before quantization swapped them out)
    int8_bytes = sum(v["weight_int8"].nbytes for v in ptq.scales.values())
    assert int8_bytes * 4 == fp32_weight_bytes
    assert int8_bytes > 0


@pytest.mark.slow
def test_int8_dot_actually_int8():
    import jax

    model = _small_convnet()
    model.eval()
    ptq = PostTrainingQuantization(model=model,
                                   data_loader=_calib_batches(2))
    ptq.quantize()
    ptq.convert_to_int8()

    from paddle_tpu.core import tape as tape_mod
    from paddle_tpu.core.tensor import Tensor

    def fwd(xv):
        with tape_mod.no_grad():
            return model(Tensor(xv))._value

    jaxpr = str(jax.make_jaxpr(fwd)(np.zeros((1, 3, 16, 16), np.float32)))
    # the compiled program must carry real int8 operands into the
    # dot/conv with int32 accumulation — not a dequantized float mimic
    assert "i8[" in jaxpr, "no int8 tensors in the traced program"
    assert "preferred_element_type=int32" in jaxpr, (
        "no int32-accumulating MXU op in the traced program")


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; --durations top mover
def test_quant_sidecar_roundtrip(tmp_path):
    model = _small_convnet()
    model.eval()
    ptq = PostTrainingQuantization(model=model,
                                   data_loader=_calib_batches(2))
    ptq.quantize()
    path = str(tmp_path / "qmodel")
    ptq.save_quantized_model(path, input_spec=[
        paddle.static.InputSpec([1, 3, 16, 16], "float32")])
    ptq.convert_to_int8()
    x = _calib_batches(1)[0][:1]
    ref = model(paddle.to_tensor(x)).numpy()

    # a fresh float architecture + the sidecar reproduces the int8 model:
    # the .quant artifact is CONSUMED, not decorative. The fresh model has
    # DIFFERENT random weights — the sidecar's state_dict must win.
    paddle.seed(999)
    fresh = paddle.nn.Sequential(
        paddle.nn.Conv2D(3, 8, 3, padding=1), paddle.nn.ReLU(),
        paddle.nn.Conv2D(8, 8, 3, stride=2, padding=1), paddle.nn.ReLU(),
        paddle.nn.Flatten(), paddle.nn.Linear(8 * 8 * 8, 10))
    fresh.eval()
    n = load_quantized_model(fresh, path)
    assert n == 3
    got = fresh(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_kl_scale_clips_heavy_tail():
    # activations: bulk gaussian + a few huge outliers. abs_max keeps the
    # outlier range (wasting resolution); KL/mse/hist clip it.
    rng = np.random.RandomState(3)
    bulk = rng.randn(20000).astype(np.float32)
    outliers = np.array([40.0, -45.0, 50.0], np.float32)
    ob = HistogramObserver()
    ob.observe(np.concatenate([bulk, outliers]))

    abs_max = ob.amax
    kl = compute_kl_scale(ob.hist, ob.amax)
    mse = compute_mse_scale(ob.hist, ob.amax)
    hist = compute_hist_scale(ob.hist, ob.amax, percent=0.999)
    for name, s in (("KL", kl), ("hist", hist)):
        assert 0 < s < abs_max * 0.6, (
            f"{name} scale {s:.2f} failed to clip the outlier tail "
            f"(abs_max {abs_max:.2f})")
    # mse balances clip error vs resolution — with few huge outliers the
    # clip penalty dominates, so it only tightens, it does not hard-clip
    assert 0 < mse <= abs_max

    # and the clipped scale quantizes the bulk with LOWER error
    def quant_err(s):
        q = np.clip(np.round(bulk / s * 127), -127, 127) * s / 127
        return float(((bulk - q) ** 2).mean())

    assert quant_err(kl) < quant_err(abs_max)
    assert quant_err(mse) < quant_err(abs_max)


def test_ptq_kl_algo_end_to_end():
    model = _small_convnet()
    model.eval()
    ptq = PostTrainingQuantization(model=model,
                                   data_loader=_calib_batches(),
                                   algo="KL")
    ptq.quantize()
    for rec in ptq.scales.values():
        assert rec["act_scale"] > 0
    ptq.convert_to_int8()
    x = _calib_batches(1)[0]
    ref_model = _small_convnet()
    ref_model.eval()
    ref = ref_model(paddle.to_tensor(x)).numpy()
    got = model(paddle.to_tensor(x)).numpy()
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.08, f"KL-calibrated int8 relative error {rel:.4f}"
