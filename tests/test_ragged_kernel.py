"""Unified ragged paged-attention kernel (kernels/ragged_paged_attention).

- interpret-mode BIT-IDENTITY vs the jitted composite (gather + ragged-
  masked sdpa) for all four serving modes — prefill, chunked-prefill
  tail, decode, spec K+1 verify — in fp32 AND int8 (dequant fused into
  the page gather), incl. head_dim 64 and tuned block_heads
- the eligibility gate (single source of truth with the dispatch and the
  kernelcheck coverage report)
- ragged_tuned.json validation at LOAD (the flash_tuned discipline)
- engine-level: kernel path FORCED ON via FLAGS_ragged_interpret —
  outputs bit-identical to the composite engine, compile_counts equal,
  sync-free certification unchanged, zero fallbacks; kernel A/B gauges
  seeded from the bank; ineligible (CPU, flag off) stays composite with
  the fallback gauge at zero
- the flash seq-%512 pad-or-fallback satellite (kernels/attention.py)
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import monitor
from paddle_tpu.utils.flags import set_flags

pytestmark = pytest.mark.ragged

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.kernels import paged_attention as pa  # noqa: E402
from paddle_tpu.kernels import ragged_paged_attention as rp  # noqa: E402


@pytest.fixture
def ragged_interpret():
    set_flags({"FLAGS_ragged_interpret": True})
    yield
    set_flags({"FLAGS_ragged_interpret": False})


# ------------------------------------------------------ kernel-level parity
def _composite(q, kp, vp, tab, ctx, k_scale=None, v_scale=None,
               scale=None):
    from paddle_tpu.kernels.attention import sdpa

    s = q.shape[2]
    if k_scale is not None:
        k_all = pa.paged_gather_quant(kp, k_scale, tab, q.dtype)
        v_all = pa.paged_gather_quant(vp, v_scale, tab, q.dtype)
    else:
        k_all = pa.paged_gather(kp, tab)
        v_all = pa.paged_gather(vp, tab)
    mask = pa.ragged_mask(ctx, k_all.shape[2], s)
    return sdpa(q, k_all, v_all, mask=mask, scale=scale)


def _args(seed, b, h, s, d, ps, pps, npages, ctx_vals, quant=False):
    rng = np.random.RandomState(seed)
    if quant:
        kp = jnp.asarray(rng.randint(-127, 128, (npages, ps, h, d)),
                         jnp.int8)
        vp = jnp.asarray(rng.randint(-127, 128, (npages, ps, h, d)),
                         jnp.int8)
        kw = dict(
            k_scale=jnp.asarray(np.abs(rng.randn(npages, h)) + 0.1,
                                jnp.float32),
            v_scale=jnp.asarray(np.abs(rng.randn(npages, h)) + 0.1,
                                jnp.float32))
    else:
        kp = jnp.asarray(rng.randn(npages, ps, h, d), jnp.float32)
        vp = jnp.asarray(rng.randn(npages, ps, h, d), jnp.float32)
        kw = {}
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    tab = jnp.asarray(
        rng.choice(npages, (b, pps), replace=False).astype(np.int32))
    ctx = jnp.asarray(ctx_vals, jnp.int32)
    return (q, kp, vp, tab, ctx), kw


# (mode, batch, heads, s, head_dim, page_size, pages_per_seq, num_pages,
#  ctx_lens) — every serving contract: cold prefill (ctx 0), chunk tail
# (ctx mid-prompt), decode (s=1), spec verify (s=K+1), ragged ctx mixes
_MODES = [
    ("prefill", 1, 2, 8, 8, 4, 4, 16, [0]),
    ("chunk", 1, 2, 8, 8, 4, 4, 16, [4]),
    ("decode", 2, 2, 1, 8, 4, 4, 16, [5, 9]),
    ("verify", 3, 4, 5, 16, 4, 8, 40, [10, 3, 17]),
]


@pytest.mark.parametrize("quant", [False, True], ids=["fp32", "int8"])
@pytest.mark.parametrize("mode", [m[0] for m in _MODES])
def test_interpret_bit_identical_to_composite(mode, quant):
    spec = next(m for m in _MODES if m[0] == mode)
    # deterministic seed (hash() is salted per process — a failing run
    # must be reproducible from the test id alone)
    seed = [m[0] for m in _MODES].index(mode) * 2 + int(quant) + 1
    args, kw = _args(seed, *spec[1:], quant=quant)
    ref = jax.jit(lambda *a: _composite(*a, **kw))(*args)
    out = jax.jit(lambda *a: rp.ragged_paged_attention(
        *a, interpret=True, **kw))(*args)
    assert np.array_equal(np.asarray(out), np.asarray(ref)), \
        f"{mode}/{'int8' if quant else 'fp32'} diverged from composite"


def test_interpret_bit_identical_head_dim_64_and_block_heads():
    """The head_dim-64 coverage gap closed for real, and the tuned
    block_heads knob changes the launch config without changing a bit."""
    args, kw = _args(11, 2, 4, 1, 64, 4, 4, 16, [7, 12])
    ref = jax.jit(lambda *a: _composite(*a))(*args)
    for bh in (1, 2, 4):
        out = jax.jit(lambda *a, _bh=bh: rp.ragged_paged_attention(
            *a, interpret=True, block_heads=_bh))(*args)
        assert np.array_equal(np.asarray(out), np.asarray(ref)), \
            f"block_heads={bh} diverged"


def test_scale_override_matches_composite():
    args, _ = _args(13, 2, 2, 1, 8, 4, 4, 16, [5, 9])
    ref = jax.jit(lambda *a: _composite(*a, scale=0.25))(*args)
    out = jax.jit(lambda *a: rp.ragged_paged_attention(
        *a, scale=0.25, interpret=True))(*args)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


# --------------------------------------------------------- eligibility gate
def test_ragged_kernel_eligible_gates():
    ok, why = rp.ragged_kernel_eligible(128, 32, 16, 1, num_heads=8)
    assert ok and why == ""
    # int8, head_dim 64, unaligned widths, multi-token: all served
    for kw in (dict(quantized=True), dict(num_query_tokens=5),
               dict(num_query_tokens=64)):
        ok, why = rp.ragged_kernel_eligible(64, 30, 16, num_heads=8, **kw)
        assert ok, (kw, why)
    ok, why = rp.ragged_kernel_eligible(128, 32, 16, flags_on=False)
    assert not ok and "FLAGS_use_pallas_kernels" in why
    ok, why = rp.ragged_kernel_eligible(128, 32, 16, on_tpu=False)
    assert not ok and "FLAGS_ragged_interpret" in why
    ok, why = rp.ragged_kernel_eligible(128, 32, 16, on_tpu=False,
                                        interpret=True)
    assert ok  # the interpreter sanctions the CPU backend
    ok, why = rp.ragged_kernel_eligible(128, 4096, 512)
    assert not ok and "VMEM" in why


def test_validate_ragged_tuned():
    from paddle_tpu.analysis.kernelcheck import validate_ragged_tuned

    assert validate_ragged_tuned({"16,8,128": 4, "16,16,64": 1}) == []
    errors = validate_ragged_tuned({
        "16,8,128": 3,       # does not divide num_heads
        "16,8": 2,           # unparseable key
        "16,8,64": 0,        # non-positive
        "16,8,96": "2",      # non-int value
        "-4,8,64": 2,        # negative page size
    })
    msgs = "\n".join(errors)
    assert "does not divide num_heads" in msgs
    assert "page_size,num_heads,head_dim" in msgs
    assert "positive int" in msgs and "must be positive" in msgs


def test_shipped_ragged_tuned_table_is_valid():
    from paddle_tpu.analysis.kernelcheck import validate_ragged_tuned

    table = rp._tuned_table()  # raises on a bad shipped table
    assert validate_ragged_tuned(table) == []


def test_ragged_tuned_load_rejects_bad_entry(tmp_path, monkeypatch):
    bad = tmp_path / "ragged_tuned.json"
    bad.write_text(json.dumps({"16,8,128": 3}))
    monkeypatch.setattr(rp, "_TUNED_PATH", str(bad))
    monkeypatch.setattr(rp, "_TUNED", None)
    with pytest.raises(ValueError, match="does not divide"):
        rp._tuned_table()
    monkeypatch.setattr(rp, "_TUNED", None)  # don't poison the cache
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"4,2,8": 2}))
    monkeypatch.setattr(rp, "_TUNED_PATH", str(good))
    assert rp.block_heads_for(4, 2, 8) == 2
    assert rp.block_heads_for(16, 8, 128) == 1  # untuned default
    monkeypatch.setattr(rp, "_TUNED", None)


# ------------------------------------------------------------- engine level
def _mk_engine(kv="float32", spec=None, **over):
    from paddle_tpu.serving import ServingConfig, ServingEngine
    from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(7)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=61, hidden_size=16, num_layers=2, num_heads=2,
        max_seq_len=64, dropout=0.0))
    model.eval()
    cfg = dict(max_batch=2, num_pages=32, page_size=4, max_prompt_len=16,
               kv_dtype=kv, spec=spec)
    cfg.update(over)
    return ServingEngine(model, ServingConfig(**cfg))


def _drive(eng, budget=10):
    rng = np.random.RandomState(3)
    rids = [eng.add_request(rng.randint(0, 61, (n,)).astype(np.int32),
                            budget) for n in (5, 9)]
    outs = eng.run()
    return [outs[r] for r in rids]


def test_engine_kernel_on_bit_identical_and_sync_free(ragged_interpret):
    """The whole serving loop with EVERY attention dispatch through the
    unified kernel (interpret mode): outputs bit-identical to the
    composite engine, compile counts equal, the sync-free certification
    formula unchanged, zero fallbacks."""
    from paddle_tpu.analysis import SyncTally
    from paddle_tpu.serving.spec import SpecConfig

    set_flags({"FLAGS_ragged_interpret": False})
    base = _mk_engine(spec=SpecConfig(method="ngram", depth=2))
    off = _drive(base)
    cc_off = dict(base.compile_counts)

    set_flags({"FLAGS_ragged_interpret": True})
    eng = _mk_engine(spec=SpecConfig(method="ngram", depth=2))
    rng = np.random.RandomState(3)
    rids = [eng.add_request(rng.randint(0, 61, (n,)).astype(np.int32), 10)
            for n in (5, 9)]
    pre = eng.metrics.snapshot()
    with SyncTally() as tally:
        outs = eng.run()
    on = [outs[r] for r in rids]
    for a, b in zip(off, on):
        assert np.array_equal(a, b), "kernel-on output diverged"
    assert dict(eng.compile_counts) == cc_off
    snap = eng.metrics.snapshot()
    fetches = int(snap["serving_decode_steps"] - pre["serving_decode_steps"]
                  + snap["serving_prefills_total"]
                  - pre["serving_prefills_total"])
    assert tally.count == fetches, (
        f"kernel-on loop not sync-free: {tally.count} syncs vs "
        f"{fetches} sanctioned fetches")
    assert snap["serving_pallas_fallback_total"] == 0
    assert snap["serving_analysis_retraces_total"] == 0


@pytest.mark.slow  # re-tiered 2026-08 (PR 20): tier-1 crossed its 870 s
# budget; the fp32 engine-level bit-identity pin above keeps the
# kernel-on path hot in tier-1, int8 interpret numerics stay pinned too
def test_engine_kernel_on_int8_bit_identical(ragged_interpret):
    """The int8 pool — the config the old dispatch BANNED from the
    kernel — served through the fused-dequant gather, bit-identical to
    the quantized composite engine."""
    set_flags({"FLAGS_ragged_interpret": False})
    off = _drive(_mk_engine(kv="int8"))
    set_flags({"FLAGS_ragged_interpret": True})
    eng = _mk_engine(kv="int8")
    on = _drive(eng)
    for a, b in zip(off, on):
        assert np.array_equal(a, b), "int8 kernel-on output diverged"
    assert eng.metrics.snapshot()["serving_pallas_fallback_total"] == 0


def test_engine_ineligible_stays_composite_with_zero_fallbacks():
    """CPU without the interpret flag: the gate (not a fallback) routes
    to the composite — the fallback gauge stays at its pre-seeded zero
    and the A/B predicted gauges are seeded from the bank."""
    eng = _mk_engine()
    assert eng._decode_pallas_eligible is False
    _drive(eng, budget=4)
    snap = eng.metrics.snapshot()
    assert snap["serving_pallas_fallback_total"] == 0
    # the banked unified-kernel predictions seed the A/B gauges
    pred = snap.get("serving_kernel_speedup_predicted{kernel=ragged_paged}")
    assert pred is not None and pred > 1.0
    assert snap.get(
        "serving_kernel_speedup_predicted{kernel=ragged_paged_q8}") > 1.0
    # measured legs absent until both dispatch paths have samples
    assert snap.get(
        "serving_kernel_speedup_measured{kernel=ragged_paged}", 0.0) == 0.0


def test_engine_ab_keys_follow_kv_dtype():
    eng = _mk_engine()
    assert eng._kernel_ab_name == "ragged_paged"
    eng8 = _mk_engine(kv="int8")
    assert eng8._kernel_ab_name == "ragged_paged_q8"


# ------------------------------------------- flash %512 pad-or-fallback
def test_flash_route_and_pad_edge():
    from paddle_tpu.kernels import flash_attention as fa

    shape = (1, 8, 1024, 128)
    assert fa.flash_route(shape, shape, causal=True) == "direct"
    s640 = (1, 8, 640, 128)
    assert fa.flash_route(s640, s640, causal=True) == "pad"
    assert fa.pad_seq_to_block(640) == 1024
    assert fa.flash_route(s640, s640, causal=False) == ""
    assert fa.edge_missed(s640, s640)
    tiny = (1, 8, 64, 128)
    assert fa.flash_route(tiny, tiny, causal=True) == ""
    assert not fa.edge_missed(tiny, tiny)  # sub-kernel, not an edge
    # cross-attention and >2x pad blowups don't pad
    assert fa.flash_route((1, 8, 640, 128), (1, 8, 1280, 128),
                          causal=True) == ""


def test_sdpa_pad_route_counts_gauge_and_is_exact(monkeypatch):
    """Force the TPU gates on CPU: the 640 causal dispatch takes the pad
    route (counted on serving_flash_pad_total), the padded flash raises
    on the CPU backend, and the logged fallback serves the exact
    composite — no silent fast-path loss anywhere on the way."""
    from paddle_tpu.kernels import attention as at

    monkeypatch.setattr(at, "_on_tpu", lambda: True)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 2, 640, 64), jnp.float32)
    before_pad = monitor.stat_get("serving_flash_pad_total", 0)
    out = at.sdpa(q, q, q, is_causal=True)
    assert monitor.stat_get("serving_flash_pad_total", 0) == before_pad + 1
    ref = at.sdpa_reference(q, q, q, is_causal=True)
    assert np.allclose(np.asarray(out), np.asarray(ref))
    # non-causal 640: no route — the loudly-counted composite fallback
    before_edge = monitor.stat_get("serving_flash_edge_fallback_total", 0)
    at.sdpa(q, q, q, is_causal=False)
    assert monitor.stat_get("serving_flash_edge_fallback_total", 0) \
        == before_edge + 1


def test_flash_edge_gauges_pre_seeded():
    from paddle_tpu.serving.metrics import ServingMetrics

    snap = ServingMetrics().snapshot()
    assert snap["serving_flash_pad_total"] == 0
    assert snap["serving_flash_edge_fallback_total"] == 0
    prom = ServingMetrics().prometheus()
    assert "# TYPE serving_flash_pad_total counter" in prom
    assert "# TYPE serving_flash_edge_fallback_total counter" in prom
