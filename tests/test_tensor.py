"""Core Tensor + op tests (reference pattern: OpTest numpy-reference checks,
python/paddle/fluid/tests/unittests/op_test.py:292)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_dtypes():
    t = paddle.to_tensor([1, 2, 3])
    assert t.dtype == "int64" or t.dtype == "int32"
    t = paddle.to_tensor([1.0, 2.0])
    assert t.dtype == "float32"
    t = paddle.to_tensor(np.zeros((2, 3), np.float64))
    assert t.dtype == "float64"
    t = paddle.to_tensor([1.0], dtype="bfloat16")
    assert t.dtype == "bfloat16"


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([4]).numpy().sum() == 4
    assert paddle.full([2, 2], 7).numpy()[0, 0] == 7
    assert paddle.arange(5).tolist() == [0, 1, 2, 3, 4]
    assert paddle.eye(3).numpy().trace() == 3
    assert paddle.linspace(0, 1, 5).shape == [5]
    x = paddle.to_tensor([[1.0, 2], [3, 4]])
    assert np.allclose(paddle.tril(x).numpy(), np.tril(x.numpy()))
    assert paddle.ones_like(x).shape == [2, 2]


def test_arithmetic_matches_numpy():
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(3, 4).astype(np.float32) + 0.5
    ta, tb = paddle.to_tensor(a), paddle.to_tensor(b)
    assert np.allclose((ta + tb).numpy(), a + b)
    assert np.allclose((ta - tb).numpy(), a - b)
    assert np.allclose((ta * tb).numpy(), a * b)
    assert np.allclose((ta / tb).numpy(), a / b, rtol=1e-5)
    assert np.allclose((ta ** 2).numpy(), a ** 2)
    assert np.allclose((ta @ tb.t()).numpy(), a @ b.T, rtol=1e-5)
    assert np.allclose((2.0 - ta).numpy(), 2.0 - a)
    assert np.allclose((1.0 / tb).numpy(), 1.0 / b, rtol=1e-5)
    # scalar ops preserve dtype
    assert (ta + 1).dtype == "float32"


def test_reductions():
    a = np.random.rand(3, 4, 5).astype(np.float32)
    t = paddle.to_tensor(a)
    assert np.allclose(paddle.sum(t).numpy(), a.sum(), rtol=1e-5)
    assert np.allclose(paddle.mean(t, axis=1).numpy(), a.mean(1), rtol=1e-5)
    assert np.allclose(paddle.max(t, axis=[0, 2]).numpy(), a.max((0, 2)))
    assert np.allclose(paddle.prod(t, axis=0).numpy(), a.prod(0), rtol=1e-4)
    assert np.allclose(t.std(unbiased=True).numpy(), a.std(ddof=1), rtol=1e-4)
    assert np.allclose(paddle.logsumexp(t, axis=-1).numpy(),
                       np.log(np.exp(a).sum(-1)), rtol=1e-5)


def test_manipulation():
    a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    t = paddle.to_tensor(a)
    assert t.reshape([4, 6]).shape == [4, 6]
    assert t.reshape([0, -1]).shape == [2, 12]  # 0 = copy dim
    assert t.transpose([2, 0, 1]).shape == [4, 2, 3]
    assert paddle.concat([t, t], axis=1).shape == [2, 6, 4]
    assert paddle.stack([t, t]).shape == [2, 2, 3, 4]
    parts = paddle.split(t, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    parts = paddle.split(t, [1, -1], axis=1)
    assert parts[1].shape == [2, 2, 4]
    assert t.unsqueeze(0).shape == [1, 2, 3, 4]
    assert t.unsqueeze(0).squeeze(0).shape == [2, 3, 4]
    assert t.flatten().shape == [24]
    assert t.flatten(1).shape == [2, 12]
    assert paddle.tile(t, [2, 1, 1]).shape == [4, 3, 4]
    assert paddle.flip(t, axis=0).numpy()[0, 0, 0] == a[1, 0, 0]
    assert paddle.roll(t, 1, axis=0).numpy()[0, 0, 0] == a[1, 0, 0]


def test_indexing():
    a = np.arange(20, dtype=np.float32).reshape(4, 5)
    t = paddle.to_tensor(a)
    assert np.allclose(t[1].numpy(), a[1])
    assert np.allclose(t[1:3, 2:].numpy(), a[1:3, 2:])
    assert np.allclose(t[paddle.to_tensor([0, 2])].numpy(), a[[0, 2]])
    mask = t > 10
    assert np.allclose(t[mask].numpy(), a[a > 10])
    t2 = t.clone()
    t2[0] = 0.0
    assert t2.numpy()[0].sum() == 0


def test_gather_scatter():
    a = np.random.rand(5, 3).astype(np.float32)
    t = paddle.to_tensor(a)
    idx = paddle.to_tensor([0, 2, 4])
    assert np.allclose(paddle.gather(t, idx).numpy(), a[[0, 2, 4]])
    upd = paddle.ones([3, 3])
    out = paddle.scatter(t, idx, upd)
    assert np.allclose(out.numpy()[[0, 2, 4]], 1.0)


def test_search_sort():
    a = np.random.rand(4, 6).astype(np.float32)
    t = paddle.to_tensor(a)
    assert np.allclose(paddle.argmax(t, axis=1).numpy(), a.argmax(1))
    v, i = paddle.topk(t, 3, axis=1)
    ref = np.sort(a, 1)[:, ::-1][:, :3]
    assert np.allclose(v.numpy(), ref, rtol=1e-6)
    s = paddle.sort(t, axis=1, descending=True)
    assert np.allclose(s.numpy(), np.sort(a, 1)[:, ::-1])


def test_logic():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([3.0, 2.0, 1.0])
    assert (a == b).tolist() == [False, True, False]
    assert (a < b).tolist() == [True, False, False]
    assert bool(paddle.allclose(a, a))
    assert bool(paddle.equal_all(a, a))


def test_linalg():
    a = np.random.rand(3, 3).astype(np.float32)
    spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    t = paddle.to_tensor(spd)
    L = paddle.cholesky(t)
    assert np.allclose((L @ L.t()).numpy(), spd, atol=1e-4)
    assert np.allclose(paddle.inv(t).numpy(), np.linalg.inv(spd), atol=1e-4)
    assert abs(float(paddle.det(t)) - np.linalg.det(spd)) < 1e-2
    n = paddle.norm(paddle.to_tensor(a))
    assert abs(float(n) - np.linalg.norm(a)) < 1e-4


def test_einsum():
    a = np.random.rand(2, 3).astype(np.float32)
    b = np.random.rand(3, 4).astype(np.float32)
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    assert np.allclose(out.numpy(), a @ b, rtol=1e-5)


def test_random_reproducible():
    paddle.seed(7)
    a = paddle.randn([4, 4]).numpy()
    paddle.seed(7)
    b = paddle.randn([4, 4]).numpy()
    assert np.allclose(a, b)
    assert paddle.randint(0, 10, [100]).numpy().max() < 10
    assert paddle.randperm(10).numpy().sum() == 45


def test_cast_and_dtype_promo():
    t = paddle.to_tensor([1.5, 2.5])
    assert t.astype("int32").dtype == "int32"
    assert t.astype("bfloat16").dtype == "bfloat16"
    assert paddle.cast(t, "float64").dtype == "float64"


def test_inplace_ops():
    t = paddle.to_tensor([1.0, 2.0])
    t.add_(paddle.to_tensor([1.0, 1.0]))
    assert t.tolist() == [2.0, 3.0]
    t.scale_(2.0)
    assert t.tolist() == [4.0, 6.0]
    t.zero_()
    assert t.tolist() == [0.0, 0.0]


def test_linalg_eigh_lu_lstsq_family():
    """Round-3 linalg additions (reference: python/paddle/tensor/linalg.py
    eigh/eigvalsh/lu/lstsq/cholesky_solve/cov/corrcoef)."""
    rng = np.random.RandomState(0)
    A = rng.randn(4, 4).astype(np.float32)
    S = (A + A.T) / 2
    w, v = paddle.linalg.eigh(paddle.to_tensor(S))
    recon = np.asarray(v._value) @ np.diag(np.asarray(w._value)) @ np.asarray(v._value).T
    np.testing.assert_allclose(recon, S, atol=1e-4)
    np.testing.assert_allclose(
        np.sort(np.asarray(paddle.linalg.eigvalsh(paddle.to_tensor(S))._value)),
        np.sort(np.asarray(w._value)), rtol=1e-5)

    lu_packed, piv = paddle.linalg.lu(paddle.to_tensor(A))
    assert tuple(lu_packed.shape) == (4, 4)
    assert int(np.asarray(piv._value).min()) >= 1  # paddle 1-based pivots

    b = rng.randn(4, 2).astype(np.float32)
    sol, _, rank, _ = paddle.linalg.lstsq(paddle.to_tensor(A), paddle.to_tensor(b))
    np.testing.assert_allclose(np.asarray(sol._value),
                               np.linalg.lstsq(A, b, rcond=None)[0], atol=1e-3)
    assert int(rank) == 4

    P = S @ S.T + 4 * np.eye(4, dtype=np.float32)
    L = np.linalg.cholesky(P).astype(np.float32)
    x = paddle.linalg.cholesky_solve(paddle.to_tensor(b), paddle.to_tensor(L))
    np.testing.assert_allclose(P @ np.asarray(x._value), b, atol=1e-3)

    np.testing.assert_allclose(
        np.asarray(paddle.linalg.cov(paddle.to_tensor(A))._value), np.cov(A),
        rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(paddle.linalg.corrcoef(paddle.to_tensor(A))._value),
        np.corrcoef(A), rtol=1e-4)

    # UPLO selects one triangle (numpy/paddle semantics), no symmetrization
    tri = np.array([[1.0, 100.0], [0.0, 1.0]], np.float32)
    w_l = np.asarray(paddle.linalg.eigvalsh(paddle.to_tensor(tri), UPLO="L")._value)
    np.testing.assert_allclose(np.sort(w_l), [1.0, 1.0], atol=1e-5)
    with pytest.raises(NotImplementedError):
        paddle.linalg.lu(paddle.to_tensor(A), pivot=False)


def test_scalar_comparison_respects_tensor_dtype():
    """Python-scalar comparisons cast to the tensor's dtype (float64 safe)."""
    t64 = paddle.to_tensor(np.float64(0.1))
    assert bool(paddle.equal(t64, 0.1))
    t32 = paddle.to_tensor(np.float32(0.5))
    assert bool(paddle.equal(t32, 0.5))


def test_register_hook_and_activation_methods():
    """Tensor.register_hook observes/replaces incoming grads (reference:
    varbase_patch_methods register_hook); sigmoid/softmax/gradient methods."""
    import numpy as np

    import paddle_tpu as paddle

    t = paddle.to_tensor(np.ones(3, "float32"))
    t.stop_gradient = False
    seen = []
    handle = t.register_hook(lambda g: seen.append(g.numpy().copy()))
    paddle.sum(t.softmax().sigmoid()).backward()
    assert len(seen) == 1 and seen[0].shape == (3,)
    np.testing.assert_allclose(t.gradient(), t.grad.numpy())
    handle.remove()
    t.clear_grad()
    # replacing hook doubles the grad; removed observer no longer fires
    t.register_hook(lambda g: g * 2)
    paddle.sum(t * 3).backward()
    np.testing.assert_allclose(t.grad.numpy(), [6, 6, 6])
    assert len(seen) == 1


def test_register_hook_sees_accumulated_grad():
    """Code-review regression (reproduced): hooks run ONCE on the final
    accumulated gradient, not per contribution — clip(2)+clip(3) != clip(5)."""
    import numpy as np

    import paddle_tpu as paddle

    t = paddle.to_tensor(np.ones(3, "float32"))
    t.stop_gradient = False
    calls = []

    def clip_hook(g):
        calls.append(g.numpy().copy())
        return paddle.clip(g, -2.5, 2.5)

    t.register_hook(clip_hook)
    loss = paddle.add(paddle.sum(t * 2.0), paddle.sum(t * 3.0))
    loss.backward()
    assert len(calls) == 1           # once per backward
    np.testing.assert_allclose(calls[0], [5, 5, 5])   # accumulated value
    np.testing.assert_allclose(t.grad.numpy(), [2.5, 2.5, 2.5])
