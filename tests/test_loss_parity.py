"""Loss-curve parity: framework GPT vs the independent numpy implementation
(VERDICT r3 item 9; reference pattern test_dist_base.py:782 — same init, same
data, per-step loss agreement).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

from numpy_gpt import NumpyGPT


def _build(seed=13):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=61, hidden_size=16, num_layers=2, num_heads=2,
                    max_seq_len=8, dropout=0.0)
    model = GPTForCausalLM(cfg)
    params = {k: np.asarray(v.numpy(), np.float64)
              for k, v in model.named_parameters()}
    return model, cfg, params


@pytest.mark.slow
def test_single_step_grads_match_numpy():
    """The numpy backward is validated against the framework's autodiff on one
    step — every parameter's gradient, not just the loss."""
    model, cfg, params = _build()
    ref = NumpyGPT(params, cfg.num_layers, cfg.num_heads)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (2, 8))
    labels = rng.randint(0, cfg.vocab_size, (2, 8))

    loss_np, grads_np = ref.loss_and_grads(ids, labels)
    loss_fw = model(paddle.to_tensor(ids.astype(np.int32)),
                    labels=paddle.to_tensor(labels.astype(np.int32)))
    loss_fw.backward()
    assert float(loss_fw) == pytest.approx(loss_np, rel=1e-5)
    for name, p in model.named_parameters():
        gf = np.asarray(p.grad.numpy(), np.float64)
        gn = grads_np[name]
        np.testing.assert_allclose(
            gf, gn, rtol=2e-4, atol=2e-6,
            err_msg=f"grad mismatch for {name}")


@pytest.mark.slow
def test_loss_curve_parity_50_steps():
    """Train 50 SGD steps from the same init on the same batches; the loss
    sequences must agree step for step."""
    model, cfg, params = _build(seed=4)
    ref = NumpyGPT(params, cfg.num_layers, cfg.num_heads)
    opt = paddle.optimizer.SGD(0.5, parameters=model.parameters())
    rng = np.random.RandomState(7)

    data = [(rng.randint(0, cfg.vocab_size, (2, 8)),
             rng.randint(0, cfg.vocab_size, (2, 8))) for _ in range(4)]
    fw_losses, np_losses = [], []
    for step in range(50):
        ids, labels = data[step % len(data)]  # memorizable: loss must fall
        loss = model(paddle.to_tensor(ids.astype(np.int32)),
                     labels=paddle.to_tensor(labels.astype(np.int32)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        fw_losses.append(float(loss))
        l_np, g_np = ref.loss_and_grads(ids, labels)
        ref.sgd_step(g_np, 0.5)
        np_losses.append(l_np)

    np.testing.assert_allclose(fw_losses, np_losses, rtol=2e-3, atol=2e-4)
    # and training actually learned something in both
    assert fw_losses[-1] < fw_losses[0]
    assert np_losses[-1] < np_losses[0]
