"""jit.to_static, AMP, recompute, GradScaler tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_to_static_layer_matches_eager():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([3, 4])
    eager = net(x).numpy()
    traced = paddle.jit.to_static(net)
    out = traced.forward_traced(x)
    assert np.allclose(out.numpy(), eager, rtol=1e-5)
    # second call hits the jit cache
    out2 = traced.forward_traced(x)
    assert np.allclose(out2.numpy(), eager, rtol=1e-5)


def test_to_static_function():
    @paddle.jit.to_static
    def f(a, b):
        return a * 2 + b

    x, y = paddle.randn([3]), paddle.randn([3])
    assert np.allclose(f(x, y).numpy(), x.numpy() * 2 + y.numpy(), rtol=1e-6)


def test_to_static_bn_buffer_update():
    bn = nn.BatchNorm1D(4)
    net = nn.Sequential(bn)
    traced = paddle.jit.to_static(net)
    x = paddle.randn([16, 4, 8])
    traced.forward_traced(x)
    assert not np.allclose(bn._mean.numpy(), 0.0)


def test_auto_cast_o1():
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        a = paddle.randn([4, 4])
        b = paddle.randn([4, 4])
        c = paddle.matmul(a, b)  # white op -> bf16
        assert c.dtype == "bfloat16"
        d = a + b  # not white -> stays fp32
        assert d.dtype == "float32"
    c2 = paddle.matmul(a, b)
    assert c2.dtype == "float32"


def test_auto_cast_custom_lists():
    with paddle.amp.auto_cast(custom_white_list=["add"], level="O1"):
        a = paddle.randn([2])
        assert (a + a).dtype == "bfloat16"


def test_amp_decorate_o2():
    net = nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    net, opt = paddle.amp.decorate(net, opt, level="O2", dtype="bfloat16")
    assert net.weight.dtype == "bfloat16"
    assert opt._multi_precision


def test_grad_scaler_fp16_flow():
    net = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
    x = paddle.randn([8, 4])
    loss = net(x).sum()
    scaled = scaler.scale(loss)
    assert float(scaled.numpy()) == pytest.approx(float(loss.numpy()) * 1024.0, rel=1e-5)
    scaled.backward()
    w_before = net.weight.numpy().copy()
    scaler.step(opt)
    assert not np.allclose(net.weight.numpy(), w_before)


def test_grad_scaler_skips_inf():
    net = nn.Linear(2, 1)
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0, decr_every_n_nan_or_inf=1)
    net.weight.grad = paddle.to_tensor(np.asarray([[np.inf], [1.0]], dtype=np.float32))
    net.bias.grad = paddle.to_tensor(np.asarray([1.0], dtype=np.float32))
    w_before = net.weight.numpy().copy()
    scaler.step(opt)
    assert np.allclose(net.weight.numpy(), w_before)  # skipped
    assert scaler._scale == 2.0  # decreased


def test_recompute_in_jit():
    """recompute inside a jitted step gives identical grads."""
    import jax

    from paddle_tpu.distributed.fleet import recompute

    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 4))
    params, _ = net.functional_state()
    x = np.random.rand(2, 4).astype(np.float32)

    def loss_plain(pv):
        out, _ = net.functional_call(pv, {}, paddle.to_tensor(x))
        return float(out.sum().numpy()) if False else out.sum()._value

    def loss_rc(pv):
        from paddle_tpu.core import tape

        with tape.no_grad():
            all_p = dict(pv)
            saved = {k: t._value for k, t in params.items()}
            for k, v in all_p.items():
                params[k]._value = v
            try:
                out = recompute(net, paddle.to_tensor(x))
            finally:
                for k, t in params.items():
                    t._value = saved[k]
            return out.sum()._value

    pv = {k: v._value for k, v in params.items()}
    from paddle_tpu.core import tape

    def lp(p):
        with tape.no_grad():
            saved = {k: t._value for k, t in params.items()}
            for k, v in p.items():
                params[k]._value = v
            try:
                out = net(paddle.to_tensor(x))
            finally:
                for k, t in params.items():
                    t._value = saved[k]
            return out.sum()._value

    g1 = jax.jit(jax.grad(lp))(pv)
    g2 = jax.jit(jax.grad(loss_rc))(pv)
    for k in g1:
        assert np.allclose(np.asarray(g1[k]), np.asarray(g2[k]), rtol=1e-5), k


def test_jit_save_load(tmp_path):
    net = nn.Linear(4, 2)
    p = str(tmp_path / "m")
    paddle.jit.save(net, p)
    obj = paddle.jit.load(p)
    assert "state_dict" in obj
    assert np.allclose(obj["state_dict"]["weight"].numpy(), net.weight.numpy())
