"""nn layer tests (reference: per-layer unittests in tests/unittests/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def test_linear():
    l = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    y = l(x)
    assert y.shape == [2, 3]
    ref = x.numpy() @ l.weight.numpy() + l.bias.numpy()
    assert np.allclose(y.numpy(), ref, rtol=1e-5)


def test_conv2d_shape_and_value():
    conv = nn.Conv2D(3, 8, 3, padding=1)
    x = paddle.randn([2, 3, 16, 16])
    y = conv(x)
    assert y.shape == [2, 8, 16, 16]
    conv_s = nn.Conv2D(3, 8, 3, stride=2)
    assert conv_s(x).shape == [2, 8, 7, 7]
    # depthwise
    dw = nn.Conv2D(8, 8, 3, padding=1, groups=8)
    assert dw(y).shape == [2, 8, 16, 16]


def test_conv2d_matches_manual():
    conv = nn.Conv2D(1, 1, 2, bias_attr=False)
    x = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3))
    w = conv.weight.numpy()[0, 0]
    y = conv(x).numpy()[0, 0]
    a = x.numpy()[0, 0]
    ref = np.array([[np.sum(a[i:i+2, j:j+2] * w) for j in range(2)] for i in range(2)])
    assert np.allclose(y, ref, rtol=1e-5)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(4)
    x = paddle.randn([8, 4, 5, 5]) * 3 + 1
    bn.train()
    y = bn(x)
    yv = y.numpy()
    assert abs(yv.mean()) < 1e-4
    assert abs(yv.std() - 1) < 1e-2
    # running stats updated
    assert not np.allclose(bn._mean.numpy(), 0.0)
    bn.eval()
    y2 = bn(x)
    assert y2.shape == [8, 4, 5, 5]


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([2, 4, 8]) * 5 + 2
    y = ln(x).numpy()
    assert np.allclose(y.mean(-1), 0, atol=1e-4)
    assert np.allclose(y.std(-1), 1, atol=1e-2)


def test_embedding():
    emb = nn.Embedding(10, 6)
    idx = paddle.to_tensor([[1, 2], [3, 4]])
    y = emb(idx)
    assert y.shape == [2, 2, 6]
    assert np.allclose(y.numpy()[0, 0], emb.weight.numpy()[1])


def test_dropout_modes():
    d = nn.Dropout(0.5)
    x = paddle.ones([1000])
    d.train()
    y = d(x)
    kept = (y.numpy() != 0).mean()
    assert 0.3 < kept < 0.7
    assert np.allclose(y.numpy()[y.numpy() != 0], 2.0)  # upscale_in_train
    d.eval()
    assert np.allclose(d(x).numpy(), 1.0)


def test_pools():
    x = paddle.randn([2, 3, 8, 8])
    assert nn.MaxPool2D(2, 2)(x).shape == [2, 3, 4, 4]
    assert nn.AvgPool2D(2, 2)(x).shape == [2, 3, 4, 4]
    assert nn.AdaptiveAvgPool2D((1, 1))(x).shape == [2, 3, 1, 1]
    ref = x.numpy().mean((2, 3), keepdims=True)
    assert np.allclose(nn.AdaptiveAvgPool2D((1, 1))(x).numpy(), ref, rtol=1e-5)


def test_activations():
    x = paddle.to_tensor([-2.0, 0.0, 2.0])
    assert np.allclose(nn.ReLU()(x).numpy(), [0, 0, 2])
    assert np.allclose(nn.Sigmoid()(x).numpy(), 1 / (1 + np.exp([2.0, 0, -2])), rtol=1e-5)
    assert nn.GELU()(x).shape == [3]
    assert np.allclose(nn.LeakyReLU(0.1)(x).numpy(), [-0.2, 0, 2], rtol=1e-5)
    sm = nn.Softmax()(paddle.randn([2, 5]))
    assert np.allclose(sm.numpy().sum(-1), 1.0, rtol=1e-5)


def test_sequential_and_containers():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([3, 4])
    assert m(x).shape == [3, 2]
    assert len(m) == 3
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(list(ll[0].parameters())) == 2


def test_state_dict_roundtrip():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = m.state_dict()
    assert len(sd) == 4
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict(sd)
    for (k1, v1), (k2, v2) in zip(m.state_dict().items(), m2.state_dict().items()):
        assert np.allclose(v1.numpy(), v2.numpy())


def test_parameters_traversal():
    m = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 2))
    names = [n for n, _ in m.named_parameters()]
    assert names == ["0.weight", "0.bias", "1.weight", "1.bias"]
    assert len(m.parameters()) == 4


def test_layer_backward_through_model():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([3, 4])
    loss = m(x).sum()
    loss.backward()
    for p in m.parameters():
        assert p.grad is not None, p.name
        assert p.grad.shape == p.shape


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 5, 16])
    y = mha(x, x, x)
    assert y.shape == [2, 5, 16]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 5, 16])
    assert enc(x).shape == [2, 5, 16]
    loss = enc(x).sum()
    loss.backward()


def test_lstm():
    lstm = nn.LSTM(4, 8, num_layers=2)
    x = paddle.randn([3, 6, 4])  # [batch, time, feat]
    out, (h, c) = lstm(x)
    assert out.shape == [3, 6, 8]
    assert h.shape == [2, 3, 8]
    assert c.shape == [2, 3, 8]
    out.sum().backward()


def test_gru_bidirectional():
    gru = nn.GRU(4, 8, direction="bidirectional")
    x = paddle.randn([2, 5, 4])
    out, h = gru(x)
    assert out.shape == [2, 5, 16]
    assert h.shape == [2, 2, 8]


def test_losses():
    logits = paddle.randn([4, 10])
    labels = paddle.to_tensor([1, 2, 3, 4])
    loss = nn.CrossEntropyLoss()(logits, labels)
    assert loss.shape == []
    ref = -np.log(np.exp(logits.numpy())[np.arange(4), labels.numpy()]
                  / np.exp(logits.numpy()).sum(1))
    assert np.allclose(float(loss), ref.mean(), rtol=1e-5)
    assert nn.MSELoss()(paddle.randn([3]), paddle.randn([3])).shape == []
    x = paddle.rand([4])
    y = paddle.to_tensor([0.0, 1.0, 0.0, 1.0])
    assert float(nn.BCELoss()(x, y)) > 0


def test_functional_interpolate():
    x = paddle.randn([1, 3, 4, 4])
    y = F.interpolate(x, scale_factor=2, mode="nearest")
    assert y.shape == [1, 3, 8, 8]


def test_initializers():
    from paddle_tpu.nn import initializer as I

    w = I.XavierUniform()((100, 100), "float32")
    limit = np.sqrt(6 / 200)
    assert abs(np.asarray(w)).max() <= limit + 1e-6
    w = I.KaimingNormal()((64, 32), "float32")
    assert abs(np.asarray(w).std() - np.sqrt(2 / 64)) < 0.02
    w = I.Constant(3.0)((5,), "float32")
    assert np.allclose(np.asarray(w), 3.0)


def test_weight_attr_and_custom_init():
    attr = nn.ParamAttr(initializer=nn.initializer.Constant(0.5), learning_rate=0.1)
    l = nn.Linear(3, 3, weight_attr=attr)
    assert np.allclose(l.weight.numpy(), 0.5)
    assert l.weight.optimize_attr["learning_rate"] == 0.1
    l2 = nn.Linear(3, 3, bias_attr=False)
    assert l2.bias is None


def test_avg_pool_exclusive_semantics():
    import torch

    x = np.random.RandomState(5).randn(1, 1, 6, 6).astype("float32")
    # exclusive=False == torch count_include_pad=True
    got = F.avg_pool2d(paddle.to_tensor(x), 3, stride=2, padding=1,
                       exclusive=False).numpy()
    ref = torch.nn.functional.avg_pool2d(torch.tensor(x), 3, stride=2, padding=1,
                                         count_include_pad=True).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    # exclusive=True == count_include_pad=False
    got_ex = F.avg_pool2d(paddle.to_tensor(x), 3, stride=2, padding=1,
                          exclusive=True).numpy()
    ref_ex = torch.nn.functional.avg_pool2d(torch.tensor(x), 3, stride=2,
                                            padding=1,
                                            count_include_pad=False).numpy()
    np.testing.assert_allclose(got_ex, ref_ex, rtol=1e-6)
    assert not np.allclose(got, got_ex)


def test_adaptive_pool_non_divisible_matches_torch():
    import torch

    x = np.random.RandomState(6).randn(2, 3, 5, 7).astype("float32")
    got = F.adaptive_avg_pool2d(paddle.to_tensor(x), (3, 2)).numpy()
    ref = torch.nn.functional.adaptive_avg_pool2d(torch.tensor(x), (3, 2)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    gotm = F.adaptive_max_pool2d(paddle.to_tensor(x), (3, 2)).numpy()
    refm = torch.nn.functional.adaptive_max_pool2d(torch.tensor(x), (3, 2)).numpy()
    np.testing.assert_allclose(gotm, refm, rtol=1e-5, atol=1e-6)
