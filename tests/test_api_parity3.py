"""Cross-namespace __all__ parity gates (round 4): every public name in the
reference module's __all__ must resolve in ours. Complements
test_api_parity*.py (root/nn/functional/sparse) with the remaining
namespaces."""
import ast
import functools
import os

import pytest

import paddle_tpu as paddle

_REF = "/root/reference/python/paddle"


def _ref_all(relpath):
    path = os.path.join(_REF, relpath)
    names = []
    tree = ast.parse(open(path).read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "__all__":
                    try:
                        names += ast.literal_eval(node.value)
                    except Exception:
                        pass
    return names


_CASES = [
    ("optimizer", "optimizer/__init__.py"),
    ("optimizer.lr", "optimizer/lr.py"),
    ("nn", "nn/__init__.py"),
    ("nn.functional", "nn/functional/__init__.py"),
    ("distributed", "distributed/__init__.py"),
    ("distributed.fleet", "distributed/fleet/__init__.py"),
    ("vision", "vision/__init__.py"),
    ("vision.ops", "vision/ops.py"),
    ("vision.transforms", "vision/transforms/__init__.py"),
    ("linalg", "linalg.py"),
    ("signal", "signal.py"),
    ("fft", "fft.py"),
    ("distribution", "distribution/__init__.py"),
    ("sparse", "sparse/__init__.py"),
    ("static", "static/__init__.py"),
    ("static.nn", "static/nn/__init__.py"),
    ("profiler", "profiler/__init__.py"),
    ("utils", "utils/__init__.py"),
    ("incubate", "incubate/__init__.py"),
    ("io", "io/__init__.py"),
    ("metric", "metric/__init__.py"),
    ("amp", "amp/__init__.py"),
    ("autograd", "autograd/__init__.py"),
    ("text", "text/__init__.py"),
    ("jit", "jit/__init__.py"),
    ("callbacks", "callbacks.py"),
    ("hub", "hub.py"),
]


@pytest.mark.parametrize("mod,relpath", _CASES,
                         ids=[c[0] for c in _CASES])
def test_namespace_all_parity(mod, relpath):
    ours = functools.reduce(getattr, mod.split("."), paddle)
    missing = sorted(n for n in _ref_all(relpath) if not hasattr(ours, n))
    assert missing == [], f"paddle.{mod} missing: {missing}"


def test_full_coverage_report_is_clean():
    """tools/gen_api_coverage.py resolves 100% of the audited reference
    namespaces; run it to regenerate API_COVERAGE.md after API changes."""
    import importlib.util
    import os
    import sys

    spec = importlib.util.spec_from_file_location(
        "gen_api_coverage",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     "gen_api_coverage.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    total_ref = total_have = 0
    gaps = {}
    for rel in mod._TOP_MODULES:
        names = sorted(set(mod._collect(rel)))
        if not names:
            continue
        dotted = (rel[:-3] if rel.endswith(".py") else rel).replace("/", ".")
        ours = mod._ours(dotted)
        missing = [n for n in names
                   if ours is None or not hasattr(ours, n)]
        total_ref += len(names)
        total_have += len(names) - len(missing)
        if missing:
            gaps[dotted or "paddle"] = missing
    assert gaps == {}, f"coverage regressions: {gaps}"
    assert total_ref >= 1330  # audit scope only grows
