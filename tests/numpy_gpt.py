"""Independent numpy GPT reference — forward AND hand-derived backward.

Loss-curve parity harness (VERDICT r3 item 9; reference pattern:
test_dist_base.py:782 compares loss sequences between independent runs). This
implementation shares NO code with paddle_tpu: pure numpy, explicit backprop,
plain SGD. Training the framework's GPTForCausalLM from the same init on the
same batches must reproduce these losses step for step.

Architecture mirror of paddle_tpu.text.gpt.GPTForCausalLM (dropout=0, tied
embeddings): wte+wpe -> N x [ln1 -> causal MHA -> residual -> ln2 -> gelu MLP
-> residual] -> ln_f -> logits = h @ wte.T -> mean CE.
"""
from __future__ import annotations

import numpy as np

_EPS = 1e-5


def gelu(x):
    c = np.sqrt(2.0 / np.pi)
    return 0.5 * x * (1.0 + np.tanh(c * (x + 0.044715 * x**3)))


def dgelu(x):
    c = np.sqrt(2.0 / np.pi)
    t = np.tanh(c * (x + 0.044715 * x**3))
    dt = (1 - t**2) * c * (1 + 3 * 0.044715 * x**2)
    return 0.5 * (1 + t) + 0.5 * x * dt


def ln_fwd(x, w, b):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    inv = 1.0 / np.sqrt(var + _EPS)
    xhat = (x - mu) * inv
    return xhat * w + b, (xhat, inv)


def ln_bwd(dy, cache, w):
    xhat, inv = cache
    dxhat = dy * w
    dw = (dy * xhat).reshape(-1, xhat.shape[-1]).sum(0)
    db = dy.reshape(-1, dy.shape[-1]).sum(0)
    m = dxhat.mean(-1, keepdims=True)
    mx = (dxhat * xhat).mean(-1, keepdims=True)
    dx = inv * (dxhat - m - xhat * mx)
    return dx, dw, db


class NumpyGPT:
    def __init__(self, params: dict, n_layers: int, n_heads: int):
        # params: name -> np array, same names as GPTForCausalLM
        self.p = {k: np.asarray(v, np.float64) for k, v in params.items()}
        self.L = n_layers
        self.H = n_heads

    # ------------------------------------------------------------- forward
    def loss_and_grads(self, ids: np.ndarray, labels: np.ndarray):
        p = self.p
        g = {k: np.zeros_like(v) for k, v in p.items()}
        B, S = ids.shape
        h = p["gpt.wte.weight"].shape[1]
        H = self.H
        hd = h // H
        scale = 1.0 / np.sqrt(hd)

        x = p["gpt.wte.weight"][ids] + p["gpt.wpe.weight"][np.arange(S)][None]
        caches = []
        for l in range(self.L):
            pre = f"gpt.blocks.{l}."
            a, c_ln1 = ln_fwd(x, p[pre + "ln1.weight"], p[pre + "ln1.bias"])
            qkv = a @ p[pre + "attn.qkv_proj.weight"] + p[pre + "attn.qkv_proj.bias"]
            qkv_r = qkv.reshape(B, S, 3, H, hd).transpose(2, 0, 3, 1, 4)
            q, k, v = qkv_r[0], qkv_r[1], qkv_r[2]  # [B,H,S,hd]
            s_mat = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
            causal = np.tril(np.ones((S, S), bool))
            s_mat = np.where(causal, s_mat, -1e30)
            s_mat -= s_mat.max(-1, keepdims=True)
            e = np.exp(s_mat)
            probs = e / e.sum(-1, keepdims=True)
            o = np.einsum("bhqk,bhkd->bhqd", probs, v)
            o_merged = o.transpose(0, 2, 1, 3).reshape(B, S, h)
            attn_out = o_merged @ p[pre + "attn.out_proj.weight"] + \
                p[pre + "attn.out_proj.bias"]
            x1 = x + attn_out
            a2, c_ln2 = ln_fwd(x1, p[pre + "ln2.weight"], p[pre + "ln2.bias"])
            u = a2 @ p[pre + "mlp.fc1.weight"] + p[pre + "mlp.fc1.bias"]
            gu = gelu(u)
            mlp_out = gu @ p[pre + "mlp.fc2.weight"] + p[pre + "mlp.fc2.bias"]
            x2 = x1 + mlp_out
            caches.append((x, a, c_ln1, q, k, v, probs, o_merged, x1, a2,
                           c_ln2, u, gu))
            x = x2

        hf, c_lnf = ln_fwd(x, p["gpt.ln_f.weight"], p["gpt.ln_f.bias"])
        logits = hf @ p["gpt.wte.weight"].T  # [B,S,V] tied head
        zmax = logits.max(-1, keepdims=True)
        ez = np.exp(logits - zmax)
        lse = np.log(ez.sum(-1)) + zmax[..., 0]
        N = B * S
        tgt = np.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        loss = float((lse - tgt).mean())

        # ------------------------------------------------------------ backward
        soft = ez / ez.sum(-1, keepdims=True)
        dlogits = soft
        np.add.at(dlogits, (np.arange(B)[:, None], np.arange(S)[None], labels),
                  -1.0)
        dlogits /= N
        g["gpt.wte.weight"] += np.einsum("bsv,bsh->vh", dlogits, hf)
        dhf = dlogits @ p["gpt.wte.weight"]
        dx, dw, db = ln_bwd(dhf, c_lnf, p["gpt.ln_f.weight"])
        g["gpt.ln_f.weight"] += dw
        g["gpt.ln_f.bias"] += db

        for l in reversed(range(self.L)):
            pre = f"gpt.blocks.{l}."
            (x_in, a, c_ln1, q, k, v, probs, o_merged, x1, a2, c_ln2, u,
             gu) = caches[l]
            # mlp branch
            dmlp = dx  # residual: x2 = x1 + mlp_out
            g[pre + "mlp.fc2.weight"] += np.einsum("bsf,bsh->fh", gu, dmlp)
            g[pre + "mlp.fc2.bias"] += dmlp.reshape(-1, h).sum(0)
            dgu = dmlp @ p[pre + "mlp.fc2.weight"].T
            du = dgu * dgelu(u)
            g[pre + "mlp.fc1.weight"] += np.einsum("bsh,bsf->hf", a2, du)
            g[pre + "mlp.fc1.bias"] += du.reshape(-1, du.shape[-1]).sum(0)
            da2 = du @ p[pre + "mlp.fc1.weight"].T
            dx1_ln, dw, db = ln_bwd(da2, c_ln2, p[pre + "ln2.weight"])
            g[pre + "ln2.weight"] += dw
            g[pre + "ln2.bias"] += db
            dx1 = dx + dx1_ln
            # attention branch: x1 = x_in + attn_out
            dattn = dx1
            g[pre + "attn.out_proj.weight"] += np.einsum(
                "bsh,bso->ho", o_merged, dattn)
            g[pre + "attn.out_proj.bias"] += dattn.reshape(-1, h).sum(0)
            do_merged = dattn @ p[pre + "attn.out_proj.weight"].T
            B_, S_ = do_merged.shape[:2]
            do = do_merged.reshape(B_, S_, self.H, -1).transpose(0, 2, 1, 3)
            dprobs = np.einsum("bhqd,bhkd->bhqk", do, v)
            dv = np.einsum("bhqk,bhqd->bhkd", probs, do)
            dS = probs * (dprobs - (dprobs * probs).sum(-1, keepdims=True))
            scale_l = 1.0 / np.sqrt(q.shape[-1])
            dq = np.einsum("bhqk,bhkd->bhqd", dS, k) * scale_l
            dk = np.einsum("bhqk,bhqd->bhkd", dS, q) * scale_l
            dqkv_r = np.stack([dq, dk, dv])  # [3,B,H,S,hd]
            dqkv = dqkv_r.transpose(1, 3, 0, 2, 4).reshape(B_, S_, -1)
            g[pre + "attn.qkv_proj.weight"] += np.einsum("bsh,bst->ht", a, dqkv)
            g[pre + "attn.qkv_proj.bias"] += dqkv.reshape(-1, dqkv.shape[-1]).sum(0)
            da = dqkv @ p[pre + "attn.qkv_proj.weight"].T
            dx_ln, dw, db = ln_bwd(da, c_ln1, p[pre + "ln1.weight"])
            g[pre + "ln1.weight"] += dw
            g[pre + "ln1.bias"] += db
            dx = dx1 + dx_ln

        # embedding backward
        np.add.at(g["gpt.wte.weight"], ids.reshape(-1),
                  dx.reshape(-1, dx.shape[-1]))
        g["gpt.wpe.weight"][:dx.shape[1]] += dx.sum(0)
        return loss, g

    def sgd_step(self, grads, lr):
        for k in self.p:
            self.p[k] -= lr * grads[k]
