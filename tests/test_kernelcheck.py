"""kernelcheck: static certification of Pallas kernels.

- the registered in-tree kernel families certify (VMEM, tiling, race
  proof, roofline banked + composite diff) on CPU, no TPU required
- two deliberately defective fixture kernels are flagged: a colliding
  output index_map (write race) and an over-VMEM block config
- interpret-mode numerics smoke: certified kernels match their (jitted)
  composite references bit-for-bit on CPU (ULP-bounded where the lowering
  genuinely differs — see the test comments)
- the dispatch-coverage report names the int8 decode path as kernel-less
- the Pallas-fallback gauge + trace event satellite
- flash_tuned.json tiling validation at load and at autotune-bank time
- KERNELCHECK_CERTS module declarations cross-check the live registry
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import kernelcheck as kc
from paddle_tpu.utils import monitor

pytestmark = pytest.mark.kernelcheck

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


# certify each registry entry at most once per session — tracing the
# library kernels is the dominant cost, every test below reads the result
_RUNS: dict = {}


def _run(name):
    if name not in _RUNS:
        _RUNS[name] = kc.run_kernel(name)
    return _RUNS[name]


FAST_FAMILIES = ("fused_layernorm_fwd", "fused_layernorm_dx", "fused_adam",
                 "paged_decode", "ragged_paged", "ragged_paged_q8",
                 "ragged_paged_verify", "ragged_paged_prefill")


# ------------------------------------------------------------ certification
@pytest.mark.parametrize("name", FAST_FAMILIES)
def test_registry_kernel_certifies(name):
    report, record = _run(name)
    assert report.ok, [str(f) for f in report.all_findings()]
    assert len(report.calls) == 1
    assert report.vmem_bytes > 0
    assert report.vmem_bytes <= report.calls[0].vmem_cap
    # the banked roofline record carries the full contract
    assert record["flops"] > 0 and record["hbm_bytes"] > 0
    assert record["intensity"] == round(
        record["flops"] / record["hbm_bytes"], 3)
    assert record["composite"]["flops"] > 0
    assert record["predicted_speedup"] is not None


def test_flash_and_splash_certify_with_declared_revisits():
    """The attention kernels revisit their output across the KV grid dim
    (online-softmax accumulation) — legal exactly because their budgets
    declare allow_output_revisits."""
    for name in ("flash_fwd", "splash_fwd"):
        report, record = _run(name)
        assert report.ok, (name, [str(f) for f in report.all_findings()])
        assert sum(c.output_revisits for c in report.calls) > 0, name
        assert record["predicted_speedup"] > 1.0, name


def test_paged_decode_certifies_the_int8_flip():
    """PR 11 certified the int8 SKIP as a declared constraint; the
    unified ragged kernel inverts it — int8 decode is now
    kernel-ELIGIBLE, certified on the legacy paged certificate so the
    coverage flip can never silently regress."""
    report, _ = _run("paged_decode")
    assert report.ok
    spec = kc.REGISTRY["paged_decode"].build()
    names = {c[0]: c[1] for c in spec["constraints"]}
    assert names["int8_served_by_unified_kernel"] is True
    assert names["decode_kernel_eligible"] is True


def test_ragged_entries_resolve_data_dependent_output_map():
    """The unified kernel's output index map reads the prefetched
    cu_q_lens (data-dependent) — and certifies with ZERO race findings:
    the budget declares allow_data_dependent_outputs AND the builder's
    index_args let kernelcheck evaluate the map at the canonical runtime
    values and run the real injectivity proof. Resolved, not
    suppressed."""
    for name in ("ragged_paged", "ragged_paged_q8", "ragged_paged_verify",
                 "ragged_paged_prefill"):
        report, record = _run(name)
        assert report.ok, (name, [str(f) for f in report.all_findings()])
        races = [f for f in report.all_findings() if f.kind == "race"]
        assert races == [], (name, [str(f) for f in races])
        assert record["predicted_speedup"] > 1.0, name
    # WITHOUT index_args the same kernel fails closed (error) or warns
    # under the declaration — the resolve path is the index_args
    spec = kc.REGISTRY["ragged_paged"].build()
    undeclared = kc.certify(spec["fn"], spec["args"], name="ragged_paged",
                            budget=kc.KernelBudget())
    assert any(f.kind == "race" and f.severity == "error"
               and "allow_data_dependent_outputs" in f.message
               for f in undeclared.errors)
    declared = kc.certify(spec["fn"], spec["args"], name="ragged_paged",
                          budget=spec["budget"])
    warns = [f for f in declared.all_findings()
             if f.kind == "race" and f.severity == "warn"]
    assert warns and "index_args" in warns[0].message
    resolved = kc.certify(spec["fn"], spec["args"], name="ragged_paged",
                          budget=spec["budget"],
                          index_args=spec["index_args"])
    assert not [f for f in resolved.all_findings() if f.kind == "race"]


def test_ragged_q8_fused_dequant_speedup_banked():
    """The int8 entry's roofline captures WHY the fused dequant matters:
    the kernel moves int8 codes (+ tiny scales) where the composite
    materializes the dequantized f32 gather — the banked predicted
    speedup is the int8-decode headline."""
    _, rec = _run("ragged_paged_q8")
    _, rec_f32 = _run("ragged_paged")
    assert rec["hbm_bytes"] < rec_f32["hbm_bytes"] / 2
    assert rec["predicted_speedup"] > rec_f32["predicted_speedup"]


# -------------------------------------------------------- defect fixtures
def _fixture_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def _racy_call(x):
    """Deliberate write race: grid point i writes output block i % 2 —
    block 0 REAPPEARS at i=2 after the map moved away at i=1."""
    from jax.experimental import pallas as pl

    return pl.pallas_call(  # lint: disable=PT011
        _fixture_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i % 2, 0)),
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32))(x)


def test_race_fixture_flagged():
    x = jax.ShapeDtypeStruct((32, 128), jnp.float32)
    report = kc.certify(_racy_call, (x,), name="racy")
    assert not report.ok
    races = [f for f in report.errors if f.kind == "race"]
    assert races and "REAPPEARS" in races[0].message
    assert "write race" in races[0].message


def _revisit_call(x):
    """Every grid point maps to output block 0 — the accumulation idiom,
    an error unless the budget declares it."""
    from jax.experimental import pallas as pl

    return pl.pallas_call(  # lint: disable=PT011
        _fixture_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32))(x)


def test_undeclared_revisit_flagged_and_declarable():
    x = jax.ShapeDtypeStruct((32, 128), jnp.float32)
    report = kc.certify(_revisit_call, (x,), name="revisit")
    assert not report.ok
    assert any("allow_output_revisits" in f.message for f in report.errors)
    sanctioned = kc.certify(
        _revisit_call, (x,), name="revisit",
        budget=kc.KernelBudget(allow_output_revisits=True))
    assert sanctioned.ok
    assert sanctioned.calls[0].output_revisits == 3


def _over_vmem_call(x):
    """One 64 MiB f32 block — 4x the v5e VMEM, before double-buffering."""
    from jax.experimental import pallas as pl

    return pl.pallas_call(  # lint: disable=PT011
        _fixture_kernel,
        grid=(2,),
        in_specs=[pl.BlockSpec((8192, 2048), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8192, 2048), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((16384, 2048), jnp.float32))(x)


def test_over_vmem_fixture_flagged():
    # ShapeDtypeStructs only — nothing this size ever materializes
    x = jax.ShapeDtypeStruct((16384, 2048), jnp.float32)
    report = kc.certify(_over_vmem_call, (x,), name="whale")
    assert not report.ok
    vmem = [f for f in report.errors if f.kind == "vmem"]
    assert vmem and "VMEM working set" in vmem[0].message
    assert "exceeds" in vmem[0].message
    # 2 blocks x 64 MiB x 2 (pipeline double buffer)
    assert report.vmem_bytes == 2 * 8192 * 2048 * 4 * 2


def _misaligned_call(x):
    from jax.experimental import pallas as pl

    return pl.pallas_call(  # lint: disable=PT011
        _fixture_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 100), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 400), jnp.float32))(x)


def test_tiling_lane_misalignment_flagged():
    x = jax.ShapeDtypeStruct((32, 400), jnp.float32)
    report = kc.certify(_misaligned_call, (x,), name="misaligned")
    tiling = [f for f in report.errors if f.kind == "tiling"]
    assert tiling, [str(f) for f in report.all_findings()]
    assert any("128-lane" in f.message for f in tiling)


def test_dispatch_constraint_failure_flagged():
    x = jax.ShapeDtypeStruct((32, 128), jnp.float32)
    report = kc.certify(
        _revisit_call, (x,), name="gated",
        budget=kc.KernelBudget(allow_output_revisits=True),
        constraints=(("the_%512_rule", False,
                      "s=640 must take the composite path"),))
    assert not report.ok
    assert any(f.kind == "dispatch" and "the_%512_rule" in f.message
               for f in report.errors)


def test_untraceable_kernel_is_the_finding():
    """A kernel entry that cannot even trace (the paged-decode x64 bug's
    shape) certifies as a trace-kind violation, not a checker crash."""
    def broken(x):
        raise TypeError("mosaic legalization failed")

    report = kc.certify(broken, (jax.ShapeDtypeStruct((8,), jnp.float32),),
                        name="broken")
    assert not report.ok
    assert any(f.kind == "trace" and "composite fallback" in f.message
               for f in report.errors)


# ------------------------------------------------- interpret-mode numerics
# The reference is the registry's own composite, JITTED: interpret-mode
# pallas runs under jit, and eager-vs-jit constant folding alone costs
# thousands of ULPs on a reduction. Jit-to-jit, layernorm is bitwise.
def test_fused_layernorm_interpret_matches_composite_bitwise():
    from paddle_tpu.kernels import fused_layernorm as fl

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 256), jnp.float32)
    g = jnp.asarray(rng.randn(256), jnp.float32)
    b = jnp.asarray(rng.randn(256), jnp.float32)
    y = fl.fused_layer_norm(x, g, b, 1e-5, interpret=True)
    spec = kc.REGISTRY["fused_layernorm_fwd"].build()
    ref, _, _ = jax.jit(spec["composite"])(x, g, b)
    assert np.array_equal(np.asarray(y), np.asarray(ref))


def test_fused_adam_interpret_matches_composite_bitwise():
    from paddle_tpu.kernels import fused_optimizer as fo

    rng = np.random.RandomState(1)
    n = 1 << 16
    p, g, m, v = (jnp.asarray(rng.randn(n), jnp.float32) for _ in range(4))
    v = jnp.abs(v)
    lr, bc1, bc2 = (jnp.asarray(s, jnp.float32)
                    for s in (1e-3, 0.9, 0.999))
    out = fo.fused_adam_update(p, g, m, v, lr, bc1, bc2, beta1=0.9,
                               beta2=0.999, eps=1e-8, interpret=True)
    spec = kc.REGISTRY["fused_adam"].build()
    ref = jax.jit(spec["composite"])(p, g, m, v, lr, bc1, bc2)
    # m/v are bitwise; p's div-by-(sqrt+eps) lowers differently inside the
    # pallas interpreter (measured max 8 ULP on 116/65536 elements)
    assert np.array_equal(np.asarray(out[1]), np.asarray(ref[1]))
    assert np.array_equal(np.asarray(out[2]), np.asarray(ref[2]))
    np.testing.assert_array_max_ulp(np.asarray(out[0]), np.asarray(ref[0]),
                                    maxulp=8)


# ------------------------------------------------------- dispatch coverage
def test_coverage_int8_decode_and_head_dim_64_now_covered():
    """The two kernel-less findings PR 11's coverage report named —
    int8 decode and head_dim 64 — are CLOSED by the unified kernel, and
    the seq-%512 flash edge routes through the causal pad instead of
    silently falling off."""
    cov = kc.coverage_report()
    # nothing on the serving paged path is kernel-less anymore
    assert not any("paged" in k for k in cov["kernel_less"]), \
        cov["kernel_less"]
    by_config = {(r["family"], r["config"]): r for r in cov["rows"]}
    hot = by_config[("paged_decode",
                     "platform=tpu pallas_flag=on kv_dtype=float32")]
    assert hot["path"] == "pallas" and not hot["blocked_by"]
    q8 = by_config[("paged_decode",
                    "platform=tpu pallas_flag=on kv_dtype=int8")]
    assert q8["path"] == "pallas" and not q8["blocked_by"]
    d64 = by_config[("paged_decode",
                     "platform=tpu pallas_flag=on kv_dtype=float32 "
                     "head_dim=64")]
    assert d64["path"] == "pallas" and not d64["blocked_by"]
    cpu = by_config[("paged_decode",
                     "platform=cpu pallas_flag=on kv_dtype=float32")]
    assert cpu["path"] == "composite"
    assert "FLAGS_ragged_interpret" in cpu["blocked_by"]
    # the multi-token modes ride the same predicate, both dtypes
    for kv in ("float32", "int8"):
        for mode in ("verify[K+1=5]", "prefill[64]"):
            r = by_config[("ragged_paged",
                           f"platform=tpu pallas_flag=on kv_dtype={kv} "
                           f"mode={mode}")]
            assert r["path"] == "pallas", r
    # the %512 edge: causal pads to the block, non-causal is a
    # loudly-counted composite — neither is silent anymore
    pad = by_config[("flash_prefill",
                     "platform=tpu pallas_flag=on seq=640 causal")]
    assert pad["path"] == "pallas[padded]"
    nc = by_config[("flash_prefill",
                    "platform=tpu pallas_flag=on seq=640 non-causal")]
    assert nc["path"] == "composite[counted]"
    assert "serving_flash_edge_fallback_total" in nc["blocked_by"]
    assert not any("flash" in k and "640" in k for k in cov["kernel_less"])


def test_coverage_predicate_is_the_runtime_gate():
    """The coverage rows come from decode_kernel_eligible — now the
    unified ragged_kernel_eligible gate the dispatch calls, so the table
    can't drift. The PR 11 gates it retired (head_dim % 128, page-table
    width alignment, the int8 ban) stay retired."""
    from paddle_tpu.kernels import paged_attention as pa

    ok, why = pa.decode_kernel_eligible(128, 32, 16)
    assert ok and why == ""
    # the two closed coverage gaps — eligible now
    ok, why = pa.decode_kernel_eligible(64, 32, 16)
    assert ok and why == ""
    ok, why = pa.decode_kernel_eligible(128, 32, 16, quantized=True)
    assert ok and why == ""
    # unaligned page-table widths no longer fall off the fast path
    ok, _ = pa.decode_kernel_eligible(128, 30, 16)
    assert ok
    # the remaining honest gates
    ok, why = pa.decode_kernel_eligible(128, 32, 16, flags_on=False)
    assert not ok and "FLAGS_use_pallas_kernels" in why
    ok, why = pa.decode_kernel_eligible(128, 32, 16, on_tpu=False)
    assert not ok and "FLAGS_ragged_interpret" in why
    ok, why = pa.decode_kernel_eligible(128, 4096, 512)  # 2M-token ctx
    assert not ok and "VMEM" in why
    ok, why = pa.decode_kernel_eligible(128, 32, 16, num_query_tokens=0)
    assert not ok and "num_query_tokens" in why


# -------------------------------------------------- flash_tuned validation
def test_validate_flash_tuned():
    assert kc.validate_flash_tuned({"1024,128": 512, "2048,64": 1024}) == []
    errors = kc.validate_flash_tuned({
        "1024,128": 500,      # not a 128 multiple
        "1000,64": 512,       # does not tile seq
        "512,64": 1024,       # block exceeds seq
        "bogus": 512,         # unparseable key
        "1024,96": 512,       # head_dim off the 64 tile
        "1024,64": "512",     # non-int value
    })
    msgs = "\n".join(errors)
    assert "128-lane" in msgs and "does not tile" in msgs
    assert "exceeds seq" in msgs and "seq,head_dim" in msgs
    assert "head_dim 96" in msgs and "positive int" in msgs


def test_shipped_flash_tuned_table_is_valid():
    from paddle_tpu.kernels import flash_attention as fa

    table = fa._tuned_table()  # raises on a misaligned shipped table
    assert kc.validate_flash_tuned(table) == []


def test_flash_tuned_load_rejects_misaligned(tmp_path, monkeypatch):
    from paddle_tpu.kernels import flash_attention as fa

    bad = tmp_path / "flash_tuned.json"
    bad.write_text(json.dumps({"1024,64": 500}))
    monkeypatch.setattr(fa, "_TUNED_PATH", str(bad))
    monkeypatch.setattr(fa, "_TUNED", None)
    with pytest.raises(ValueError, match="tiling constraints"):
        fa._tuned_table()
    monkeypatch.setattr(fa, "_TUNED", None)  # don't poison the cache


def test_autotune_refuses_to_bank_misaligned(monkeypatch):
    """tools/flash_autotune.py validates before writing — the same
    validator, so the load site can never see a table the bank site
    accepted."""
    from paddle_tpu.analysis.kernelcheck import validate_flash_tuned

    assert validate_flash_tuned({"1024,64": 500})  # what main() raises on


# ------------------------------------------------- fallback gauge + events
def test_pallas_fallback_counts_gauge_and_calls_hook(monkeypatch):
    from paddle_tpu.kernels import paged_attention as pa
    from paddle_tpu.kernels import ragged_paged_attention as rp

    calls = []
    monkeypatch.setattr(pa, "_use_ragged_kernel",
                        lambda *a, **k: (True, True))

    def boom(*a, **k):
        raise RuntimeError("mosaic says no")

    monkeypatch.setattr(rp, "ragged_paged_attention", boom)
    monkeypatch.setattr(pa, "fallback_hook",
                        lambda exc, sig: calls.append((exc, sig)))
    q = jnp.zeros((1, 2, 1, 8), jnp.float32)
    pool = jnp.zeros((4, 2, 2, 8), jnp.float32)
    table = jnp.zeros((1, 2), jnp.int32)
    ctx = jnp.zeros((1,), jnp.int32)
    before = monitor.stats_with_prefix("serving_").get(
        "serving_pallas_fallback_total", 0)
    out = pa.paged_attention(q, pool, pool, table, ctx)
    assert out.shape == (1, 2, 1, 8)  # the composite path served
    after = monitor.stats_with_prefix("serving_")[
        "serving_pallas_fallback_total"]
    assert after == before + 1
    assert calls == [("RuntimeError", "q(1, 2, 1, 8) pool(4, 2, 2, 8)")]


def test_engine_stamps_fallback_trace_event():
    from paddle_tpu.obs.export import _INSTANTS
    from paddle_tpu.serving.engine import ServingConfig, ServingEngine
    from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

    assert "pallas_fallback" in _INSTANTS  # renders as a Chrome instant
    paddle.seed(11)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=61, hidden_size=16, num_layers=1, num_heads=2,
        max_seq_len=16, dropout=0.0))
    model.eval()
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=8, page_size=4, max_prompt_len=8))
    from paddle_tpu.kernels import paged_attention as pa

    eng._tracer.begin(7)
    eng._active[0] = True
    eng._rids[0] = 7
    # drive the INSTALLED module-level hook, not the method: this is the
    # exact call the kernel fallback site makes
    pa.fallback_hook("ValueError", "q(2, 2, 1, 8) pool(8, 4, 2, 8)")
    ev = eng._tracer.get(7).last("pallas_fallback")
    assert ev is not None
    assert ev.arg("exc") == "ValueError"
    assert "pool(8, 4, 2, 8)" in ev.arg("signature")
    # the gauge is pre-seeded: visible at zero before any fallback
    assert eng.metrics.snapshot()["serving_pallas_fallback_total"] == 0
    assert ("# TYPE serving_pallas_fallback_total counter"
            in eng.metrics.prometheus())
    # the hook holds only a weakref: dropping the engine must not leak it
    # (its KV pools) through the module global, and a post-mortem
    # fallback is a safe no-op
    import gc
    import weakref

    alive = weakref.ref(eng)
    del eng
    gc.collect()
    assert alive() is None, "module-level hook pinned the dropped engine"
    pa.fallback_hook("ValueError", "q(2, 2, 1, 8) pool(8, 4, 2, 8)")


# --------------------------------------------- registry <-> module certs
def test_kernelcheck_certs_declarations_match_registry():
    """Every pallas-kernel module's KERNELCHECK_CERTS names live registry
    entries, and every registry entry is declared by exactly one module —
    PT011's declaration can't go stale in either direction."""
    from paddle_tpu.kernels import (flash_attention, fused_layernorm,
                                    fused_optimizer, paged_attention,
                                    ragged_paged_attention)

    declared = []
    for mod in (flash_attention, fused_layernorm, fused_optimizer,
                paged_attention, ragged_paged_attention):
        certs = mod.KERNELCHECK_CERTS
        assert certs, mod.__name__
        declared.extend(certs)
    assert sorted(declared) == sorted(kc.REGISTRY)
    assert len(declared) == len(set(declared))


# ----------------------------------------------------------- bank + drift
def test_bank_and_drift_detection():
    _, rec = _run("fused_adam")
    records = {"fused_adam": rec}
    banked = json.loads(json.dumps(records))  # round-trip like the file
    assert kc.diff_banked(records, banked) == []
    banked["fused_adam"]["flops"] += 1
    drift = kc.diff_banked(records, banked)
    assert any(f.kind == "drift" and f.severity == "error"
               and "flops" in f.message for f in drift)
    missing = kc.diff_banked({"fused_adam": rec, "new_kernel": rec}, banked)
    assert any("--bank" in f.message for f in missing)
    # composite re-measurements drift only as warnings
    banked = json.loads(json.dumps(records))
    banked["fused_adam"]["composite"]["flops"] *= 2
    drift = kc.diff_banked(records, banked)
    assert drift and all(f.severity == "warn" for f in drift)


# ----------------------------------------------------------------- CLI
def test_cli_inprocess(tmp_path, capsys):
    assert kc.main(["--list-kernels"]) == 0
    assert "paged_decode" in capsys.readouterr().out
    assert kc.main(["--kernel", "bogus"]) == 2
    capsys.readouterr()
    profile = tmp_path / "kernelcheck.json"
    rc = kc.main(["--kernel", "fused_adam", "--kernel",
                  "fused_layernorm_fwd", "--bank", "--no-coverage",
                  "--profile", str(profile)])
    out = capsys.readouterr().out
    assert rc == 0 and profile.exists()
    assert "banked 2 roofline record(s)" in out
    banked = json.loads(profile.read_text())
    assert set(banked) == {"fused_adam", "fused_layernorm_fwd"}
    assert banked["fused_adam"]["flops"] == 14 * (1 << 16)


def test_cli_coverage_and_violation_exit(tmp_path, capsys):
    """A drifted bank fails the default sweep loudly (the PR 6 contract);
    the coverage table shows the int8/head_dim-64 flips and — the
    unified-kernel acceptance — NO kernel-less production section (every
    TPU-flags-on serving config reaches a kernel or a counted
    fallback)."""
    profile = tmp_path / "kernelcheck.json"
    bad = {name: {"grid": [], "vmem_bytes": 0, "flops": -1,
                  "hbm_bytes": 0} for name in kc.REGISTRY}
    profile.write_text(json.dumps(bad))
    rc = kc.main(["--profile", str(profile)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "drifted from the banked contract" in out
    assert "kernel-less production configs" not in out
    assert "kv_dtype=int8" in out  # the flipped row still prints, as pallas
