"""kernelcheck: static certification of Pallas kernels.

- the registered in-tree kernel families certify (VMEM, tiling, race
  proof, roofline banked + composite diff) on CPU, no TPU required
- two deliberately defective fixture kernels are flagged: a colliding
  output index_map (write race) and an over-VMEM block config
- interpret-mode numerics smoke: certified kernels match their (jitted)
  composite references bit-for-bit on CPU (ULP-bounded where the lowering
  genuinely differs — see the test comments)
- the dispatch-coverage report names the int8 decode path as kernel-less
- the Pallas-fallback gauge + trace event satellite
- flash_tuned.json tiling validation at load and at autotune-bank time
- KERNELCHECK_CERTS module declarations cross-check the live registry
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import kernelcheck as kc
from paddle_tpu.utils import monitor

pytestmark = pytest.mark.kernelcheck

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


# certify each registry entry at most once per session — tracing the
# library kernels is the dominant cost, every test below reads the result
_RUNS: dict = {}


def _run(name):
    if name not in _RUNS:
        _RUNS[name] = kc.run_kernel(name)
    return _RUNS[name]


FAST_FAMILIES = ("fused_layernorm_fwd", "fused_layernorm_dx", "fused_adam",
                 "paged_decode")


# ------------------------------------------------------------ certification
@pytest.mark.parametrize("name", FAST_FAMILIES)
def test_registry_kernel_certifies(name):
    report, record = _run(name)
    assert report.ok, [str(f) for f in report.all_findings()]
    assert len(report.calls) == 1
    assert report.vmem_bytes > 0
    assert report.vmem_bytes <= report.calls[0].vmem_cap
    # the banked roofline record carries the full contract
    assert record["flops"] > 0 and record["hbm_bytes"] > 0
    assert record["intensity"] == round(
        record["flops"] / record["hbm_bytes"], 3)
    assert record["composite"]["flops"] > 0
    assert record["predicted_speedup"] is not None


def test_flash_and_splash_certify_with_declared_revisits():
    """The attention kernels revisit their output across the KV grid dim
    (online-softmax accumulation) — legal exactly because their budgets
    declare allow_output_revisits."""
    for name in ("flash_fwd", "splash_fwd"):
        report, record = _run(name)
        assert report.ok, (name, [str(f) for f in report.all_findings()])
        assert sum(c.output_revisits for c in report.calls) > 0, name
        assert record["predicted_speedup"] > 1.0, name


def test_paged_decode_certifies_the_int8_skip():
    """The quantized pool's kernel-lessness is a DECLARED dispatch
    constraint on the paged certificate, not a docstring aside."""
    report, _ = _run("paged_decode")
    assert report.ok
    spec = kc.REGISTRY["paged_decode"].build()
    names = {c[0]: c[1] for c in spec["constraints"]}
    assert names["int8_skip_is_declared"] is True
    assert names["decode_kernel_eligible"] is True


# -------------------------------------------------------- defect fixtures
def _fixture_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def _racy_call(x):
    """Deliberate write race: grid point i writes output block i % 2 —
    block 0 REAPPEARS at i=2 after the map moved away at i=1."""
    from jax.experimental import pallas as pl

    return pl.pallas_call(  # lint: disable=PT011
        _fixture_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i % 2, 0)),
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32))(x)


def test_race_fixture_flagged():
    x = jax.ShapeDtypeStruct((32, 128), jnp.float32)
    report = kc.certify(_racy_call, (x,), name="racy")
    assert not report.ok
    races = [f for f in report.errors if f.kind == "race"]
    assert races and "REAPPEARS" in races[0].message
    assert "write race" in races[0].message


def _revisit_call(x):
    """Every grid point maps to output block 0 — the accumulation idiom,
    an error unless the budget declares it."""
    from jax.experimental import pallas as pl

    return pl.pallas_call(  # lint: disable=PT011
        _fixture_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32))(x)


def test_undeclared_revisit_flagged_and_declarable():
    x = jax.ShapeDtypeStruct((32, 128), jnp.float32)
    report = kc.certify(_revisit_call, (x,), name="revisit")
    assert not report.ok
    assert any("allow_output_revisits" in f.message for f in report.errors)
    sanctioned = kc.certify(
        _revisit_call, (x,), name="revisit",
        budget=kc.KernelBudget(allow_output_revisits=True))
    assert sanctioned.ok
    assert sanctioned.calls[0].output_revisits == 3


def _over_vmem_call(x):
    """One 64 MiB f32 block — 4x the v5e VMEM, before double-buffering."""
    from jax.experimental import pallas as pl

    return pl.pallas_call(  # lint: disable=PT011
        _fixture_kernel,
        grid=(2,),
        in_specs=[pl.BlockSpec((8192, 2048), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8192, 2048), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((16384, 2048), jnp.float32))(x)


def test_over_vmem_fixture_flagged():
    # ShapeDtypeStructs only — nothing this size ever materializes
    x = jax.ShapeDtypeStruct((16384, 2048), jnp.float32)
    report = kc.certify(_over_vmem_call, (x,), name="whale")
    assert not report.ok
    vmem = [f for f in report.errors if f.kind == "vmem"]
    assert vmem and "VMEM working set" in vmem[0].message
    assert "exceeds" in vmem[0].message
    # 2 blocks x 64 MiB x 2 (pipeline double buffer)
    assert report.vmem_bytes == 2 * 8192 * 2048 * 4 * 2


def _misaligned_call(x):
    from jax.experimental import pallas as pl

    return pl.pallas_call(  # lint: disable=PT011
        _fixture_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 100), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 400), jnp.float32))(x)


def test_tiling_lane_misalignment_flagged():
    x = jax.ShapeDtypeStruct((32, 400), jnp.float32)
    report = kc.certify(_misaligned_call, (x,), name="misaligned")
    tiling = [f for f in report.errors if f.kind == "tiling"]
    assert tiling, [str(f) for f in report.all_findings()]
    assert any("128-lane" in f.message for f in tiling)


def test_dispatch_constraint_failure_flagged():
    x = jax.ShapeDtypeStruct((32, 128), jnp.float32)
    report = kc.certify(
        _revisit_call, (x,), name="gated",
        budget=kc.KernelBudget(allow_output_revisits=True),
        constraints=(("the_%512_rule", False,
                      "s=640 must take the composite path"),))
    assert not report.ok
    assert any(f.kind == "dispatch" and "the_%512_rule" in f.message
               for f in report.errors)


def test_untraceable_kernel_is_the_finding():
    """A kernel entry that cannot even trace (the paged-decode x64 bug's
    shape) certifies as a trace-kind violation, not a checker crash."""
    def broken(x):
        raise TypeError("mosaic legalization failed")

    report = kc.certify(broken, (jax.ShapeDtypeStruct((8,), jnp.float32),),
                        name="broken")
    assert not report.ok
    assert any(f.kind == "trace" and "composite fallback" in f.message
               for f in report.errors)


# ------------------------------------------------- interpret-mode numerics
# The reference is the registry's own composite, JITTED: interpret-mode
# pallas runs under jit, and eager-vs-jit constant folding alone costs
# thousands of ULPs on a reduction. Jit-to-jit, layernorm is bitwise.
def test_fused_layernorm_interpret_matches_composite_bitwise():
    from paddle_tpu.kernels import fused_layernorm as fl

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 256), jnp.float32)
    g = jnp.asarray(rng.randn(256), jnp.float32)
    b = jnp.asarray(rng.randn(256), jnp.float32)
    y = fl.fused_layer_norm(x, g, b, 1e-5, interpret=True)
    spec = kc.REGISTRY["fused_layernorm_fwd"].build()
    ref, _, _ = jax.jit(spec["composite"])(x, g, b)
    assert np.array_equal(np.asarray(y), np.asarray(ref))


def test_fused_adam_interpret_matches_composite_bitwise():
    from paddle_tpu.kernels import fused_optimizer as fo

    rng = np.random.RandomState(1)
    n = 1 << 16
    p, g, m, v = (jnp.asarray(rng.randn(n), jnp.float32) for _ in range(4))
    v = jnp.abs(v)
    lr, bc1, bc2 = (jnp.asarray(s, jnp.float32)
                    for s in (1e-3, 0.9, 0.999))
    out = fo.fused_adam_update(p, g, m, v, lr, bc1, bc2, beta1=0.9,
                               beta2=0.999, eps=1e-8, interpret=True)
    spec = kc.REGISTRY["fused_adam"].build()
    ref = jax.jit(spec["composite"])(p, g, m, v, lr, bc1, bc2)
    # m/v are bitwise; p's div-by-(sqrt+eps) lowers differently inside the
    # pallas interpreter (measured max 8 ULP on 116/65536 elements)
    assert np.array_equal(np.asarray(out[1]), np.asarray(ref[1]))
    assert np.array_equal(np.asarray(out[2]), np.asarray(ref[2]))
    np.testing.assert_array_max_ulp(np.asarray(out[0]), np.asarray(ref[0]),
                                    maxulp=8)


# ------------------------------------------------------- dispatch coverage
def test_coverage_names_int8_decode_kernel_less():
    cov = kc.coverage_report()
    assert any("kv_dtype=int8" in k and "paged_decode" in k
               for k in cov["kernel_less"])
    by_config = {(r["family"], r["config"]): r for r in cov["rows"]}
    hot = by_config[("paged_decode",
                     "platform=tpu pallas_flag=on kv_dtype=float32")]
    assert hot["path"] == "pallas" and not hot["blocked_by"]
    q8 = by_config[("paged_decode",
                    "platform=tpu pallas_flag=on kv_dtype=int8")]
    assert q8["path"] == "composite" and "int8" in q8["blocked_by"]
    cpu = by_config[("paged_decode",
                     "platform=cpu pallas_flag=on kv_dtype=float32")]
    assert cpu["path"] == "composite"
    # the %512 composite-fallback rule, certified statically
    assert any(r["family"] == "flash_prefill" and "seq=640" in r["config"]
               and r["path"] == "composite" for r in cov["rows"])


def test_coverage_predicate_is_the_runtime_gate():
    """The coverage rows come from decode_kernel_eligible — the SAME
    predicate _use_pallas_decode calls, so the table can't drift."""
    from paddle_tpu.kernels import paged_attention as pa

    ok, why = pa.decode_kernel_eligible(128, 32, 16)
    assert ok and why == ""
    ok, why = pa.decode_kernel_eligible(64, 32, 16)
    assert not ok and "% 128" in why
    ok, why = pa.decode_kernel_eligible(128, 30, 16)
    assert not ok and "pages_per_block" in why
    ok, why = pa.decode_kernel_eligible(128, 32, 16, quantized=True)
    assert not ok and "int8" in why


# -------------------------------------------------- flash_tuned validation
def test_validate_flash_tuned():
    assert kc.validate_flash_tuned({"1024,128": 512, "2048,64": 1024}) == []
    errors = kc.validate_flash_tuned({
        "1024,128": 500,      # not a 128 multiple
        "1000,64": 512,       # does not tile seq
        "512,64": 1024,       # block exceeds seq
        "bogus": 512,         # unparseable key
        "1024,96": 512,       # head_dim off the 64 tile
        "1024,64": "512",     # non-int value
    })
    msgs = "\n".join(errors)
    assert "128-lane" in msgs and "does not tile" in msgs
    assert "exceeds seq" in msgs and "seq,head_dim" in msgs
    assert "head_dim 96" in msgs and "positive int" in msgs


def test_shipped_flash_tuned_table_is_valid():
    from paddle_tpu.kernels import flash_attention as fa

    table = fa._tuned_table()  # raises on a misaligned shipped table
    assert kc.validate_flash_tuned(table) == []


def test_flash_tuned_load_rejects_misaligned(tmp_path, monkeypatch):
    from paddle_tpu.kernels import flash_attention as fa

    bad = tmp_path / "flash_tuned.json"
    bad.write_text(json.dumps({"1024,64": 500}))
    monkeypatch.setattr(fa, "_TUNED_PATH", str(bad))
    monkeypatch.setattr(fa, "_TUNED", None)
    with pytest.raises(ValueError, match="tiling constraints"):
        fa._tuned_table()
    monkeypatch.setattr(fa, "_TUNED", None)  # don't poison the cache


def test_autotune_refuses_to_bank_misaligned(monkeypatch):
    """tools/flash_autotune.py validates before writing — the same
    validator, so the load site can never see a table the bank site
    accepted."""
    from paddle_tpu.analysis.kernelcheck import validate_flash_tuned

    assert validate_flash_tuned({"1024,64": 500})  # what main() raises on


# ------------------------------------------------- fallback gauge + events
def test_pallas_fallback_counts_gauge_and_calls_hook(monkeypatch):
    from paddle_tpu.kernels import paged_attention as pa

    calls = []
    monkeypatch.setattr(pa, "_use_pallas_decode", lambda *a: True)

    def boom(*a, **k):
        raise RuntimeError("mosaic says no")

    monkeypatch.setattr(pa, "_pallas_decode", boom)
    monkeypatch.setattr(pa, "fallback_hook",
                        lambda exc, sig: calls.append((exc, sig)))
    q = jnp.zeros((1, 2, 1, 8), jnp.float32)
    pool = jnp.zeros((4, 2, 2, 8), jnp.float32)
    table = jnp.zeros((1, 2), jnp.int32)
    ctx = jnp.zeros((1,), jnp.int32)
    before = monitor.stats_with_prefix("serving_").get(
        "serving_pallas_fallback_total", 0)
    out = pa.paged_attention(q, pool, pool, table, ctx)
    assert out.shape == (1, 2, 1, 8)  # the composite path served
    after = monitor.stats_with_prefix("serving_")[
        "serving_pallas_fallback_total"]
    assert after == before + 1
    assert calls == [("RuntimeError", "q(1, 2, 1, 8) pool(4, 2, 2, 8)")]


def test_engine_stamps_fallback_trace_event():
    from paddle_tpu.obs.export import _INSTANTS
    from paddle_tpu.serving.engine import ServingConfig, ServingEngine
    from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

    assert "pallas_fallback" in _INSTANTS  # renders as a Chrome instant
    paddle.seed(11)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=61, hidden_size=16, num_layers=1, num_heads=2,
        max_seq_len=16, dropout=0.0))
    model.eval()
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=8, page_size=4, max_prompt_len=8))
    from paddle_tpu.kernels import paged_attention as pa

    eng._tracer.begin(7)
    eng._active[0] = True
    eng._rids[0] = 7
    # drive the INSTALLED module-level hook, not the method: this is the
    # exact call the kernel fallback site makes
    pa.fallback_hook("ValueError", "q(2, 2, 1, 8) pool(8, 4, 2, 8)")
    ev = eng._tracer.get(7).last("pallas_fallback")
    assert ev is not None
    assert ev.arg("exc") == "ValueError"
    assert "pool(8, 4, 2, 8)" in ev.arg("signature")
    # the gauge is pre-seeded: visible at zero before any fallback
    assert eng.metrics.snapshot()["serving_pallas_fallback_total"] == 0
    assert ("# TYPE serving_pallas_fallback_total counter"
            in eng.metrics.prometheus())
    # the hook holds only a weakref: dropping the engine must not leak it
    # (its KV pools) through the module global, and a post-mortem
    # fallback is a safe no-op
    import gc
    import weakref

    alive = weakref.ref(eng)
    del eng
    gc.collect()
    assert alive() is None, "module-level hook pinned the dropped engine"
    pa.fallback_hook("ValueError", "q(2, 2, 1, 8) pool(8, 4, 2, 8)")


# --------------------------------------------- registry <-> module certs
def test_kernelcheck_certs_declarations_match_registry():
    """Every pallas-kernel module's KERNELCHECK_CERTS names live registry
    entries, and every registry entry is declared by exactly one module —
    PT011's declaration can't go stale in either direction."""
    from paddle_tpu.kernels import (flash_attention, fused_layernorm,
                                    fused_optimizer, paged_attention)

    declared = []
    for mod in (flash_attention, fused_layernorm, fused_optimizer,
                paged_attention):
        certs = mod.KERNELCHECK_CERTS
        assert certs, mod.__name__
        declared.extend(certs)
    assert sorted(declared) == sorted(kc.REGISTRY)
    assert len(declared) == len(set(declared))


# ----------------------------------------------------------- bank + drift
def test_bank_and_drift_detection():
    _, rec = _run("fused_adam")
    records = {"fused_adam": rec}
    banked = json.loads(json.dumps(records))  # round-trip like the file
    assert kc.diff_banked(records, banked) == []
    banked["fused_adam"]["flops"] += 1
    drift = kc.diff_banked(records, banked)
    assert any(f.kind == "drift" and f.severity == "error"
               and "flops" in f.message for f in drift)
    missing = kc.diff_banked({"fused_adam": rec, "new_kernel": rec}, banked)
    assert any("--bank" in f.message for f in missing)
    # composite re-measurements drift only as warnings
    banked = json.loads(json.dumps(records))
    banked["fused_adam"]["composite"]["flops"] *= 2
    drift = kc.diff_banked(records, banked)
    assert drift and all(f.severity == "warn" for f in drift)


# ----------------------------------------------------------------- CLI
def test_cli_inprocess(tmp_path, capsys):
    assert kc.main(["--list-kernels"]) == 0
    assert "paged_decode" in capsys.readouterr().out
    assert kc.main(["--kernel", "bogus"]) == 2
    capsys.readouterr()
    profile = tmp_path / "kernelcheck.json"
    rc = kc.main(["--kernel", "fused_adam", "--kernel",
                  "fused_layernorm_fwd", "--bank", "--no-coverage",
                  "--profile", str(profile)])
    out = capsys.readouterr().out
    assert rc == 0 and profile.exists()
    assert "banked 2 roofline record(s)" in out
    banked = json.loads(profile.read_text())
    assert set(banked) == {"fused_adam", "fused_layernorm_fwd"}
    assert banked["fused_adam"]["flops"] == 14 * (1 << 16)


def test_cli_coverage_and_violation_exit(tmp_path, capsys):
    """A drifted bank fails the default sweep loudly (the PR 6 contract);
    the coverage table prints the kernel-less int8 finding either way."""
    profile = tmp_path / "kernelcheck.json"
    bad = {name: {"grid": [], "vmem_bytes": 0, "flops": -1,
                  "hbm_bytes": 0} for name in kc.REGISTRY}
    profile.write_text(json.dumps(bad))
    rc = kc.main(["--profile", str(profile)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "drifted from the banked contract" in out
    assert "kernel-less production configs" in out
    assert "kv_dtype=int8" in out
