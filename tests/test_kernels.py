"""Pallas kernel numerics, validated on CPU via interpret mode.

Reference analog: the FMHA correctness tests around
operators/fused/fused_attention_op.cu — here against the composite
`sdpa_reference` (kernels/attention.py) which is itself parity-tested through
the model suites.
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.kernels.attention import sdpa_reference  # noqa: E402
from paddle_tpu.kernels.flash_attention import _splash  # noqa: E402


def _qkv(b, h, s_q, s_k, d, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda s: jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))  # noqa: E731
    return mk(s_q), jnp.asarray(rng.randn(b, h, s_k, d).astype(np.float32)), \
        jnp.asarray(rng.randn(b, h, s_k, d).astype(np.float32))


def test_splash_causal_matches_reference_square():
    b, h, s, d = 1, 2, 256, 128
    q, k, v = _qkv(b, h, s, s, d)
    scale = 1.0 / d ** 0.5
    out = _splash(q, k, v, scale, interpret=True)
    ref = sdpa_reference(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_splash_causal_rectangular_bottom_right_aligned():
    """s_q < s_k: the causal diagonal must align bottom-right (query i sees
    keys up to i + s_k - s_q), matching sdpa_reference's tril(k=s_k-s_q)."""
    b, h, s_q, s_k, d = 1, 2, 128, 256, 128
    q, k, v = _qkv(b, h, s_q, s_k, d, seed=1)
    scale = 1.0 / d ** 0.5
    out = _splash(q, k, v, scale, interpret=True)
    ref = sdpa_reference(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_splash_custom_vjp_grad_fast():
    """Fast-tier coverage of the hand-written _splash custom_vjp backward
    (round 5: the library kernel's internal vjp lowered under global x64 and
    failed Mosaic; _splash_fwd/_splash_bwd re-trace under x64-off). Small
    shape so the interpret-mode backward stays cheap."""
    b, h, s, d = 1, 2, 128, 64
    q, k, v = _qkv(b, h, s, s, d, seed=5)
    scale = 1.0 / d ** 0.5

    def f_splash(q, k, v):
        return jnp.sum(_splash(q, k, v, scale, True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(sdpa_reference(q, k, v, is_causal=True) ** 2)

    g_s = jax.grad(f_splash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gs, gr in zip(g_s, g_r):
        np.testing.assert_allclose(np.asarray(gs), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3)


@pytest.mark.slow
def test_splash_grad_matches_reference():
    b, h, s, d = 1, 1, 256, 128
    q, k, v = _qkv(b, h, s, s, d, seed=2)
    scale = 1.0 / d ** 0.5

    def f_splash(q, k, v):
        return jnp.sum(_splash(q, k, v, scale, interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(sdpa_reference(q, k, v, is_causal=True) ** 2)

    g_s = jax.grad(f_splash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gs, gr in zip(g_s, g_r):
        np.testing.assert_allclose(np.asarray(gs), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3)
