"""Optimizer numeric tests (closed-form references)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as opt


def _make_param(val):
    p = nn.Parameter(np.asarray(val, dtype=np.float32))
    return p


def _set_grad(p, g):
    p.grad = paddle.to_tensor(np.asarray(g, dtype=np.float32))


def test_sgd_step():
    p = _make_param([1.0, 2.0])
    o = opt.SGD(learning_rate=0.1, parameters=[p])
    _set_grad(p, [1.0, 1.0])
    o.step()
    assert np.allclose(p.numpy(), [0.9, 1.9])


def test_momentum():
    p = _make_param([1.0])
    o = opt.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
    _set_grad(p, [1.0])
    o.step()
    assert np.allclose(p.numpy(), [0.9])  # v=1, p-=0.1*1
    _set_grad(p, [1.0])
    o.step()
    # v = 0.9*1 + 1 = 1.9; p = 0.9 - 0.19
    assert np.allclose(p.numpy(), [0.71], atol=1e-6)


def test_adam_first_step_is_lr():
    p = _make_param([1.0])
    o = opt.Adam(learning_rate=0.01, parameters=[p])
    _set_grad(p, [0.5])
    o.step()
    # bias-corrected first step ≈ lr * sign(g)
    assert np.allclose(p.numpy(), [1.0 - 0.01], atol=1e-5)


def test_adamw_decoupled_decay():
    p = _make_param([1.0])
    o = opt.AdamW(learning_rate=0.01, weight_decay=0.1, parameters=[p])
    _set_grad(p, [0.0])
    o.step()
    # grad 0: only decay 1*(1-0.01*0.1) then adam update ~0
    assert np.allclose(p.numpy(), [0.999], atol=1e-5)


def test_weight_decay_l2_coupled():
    p = _make_param([1.0])
    o = opt.SGD(learning_rate=0.1, weight_decay=0.5, parameters=[p])
    _set_grad(p, [0.0])
    o.step()
    # g_eff = 0 + 0.5*1; p = 1 - 0.1*0.5
    assert np.allclose(p.numpy(), [0.95])


def test_grad_clip_global_norm():
    p1, p2 = _make_param([3.0]), _make_param([4.0])
    clip = nn.ClipGradByGlobalNorm(1.0)
    o = opt.SGD(learning_rate=1.0, parameters=[p1, p2], grad_clip=clip)
    _set_grad(p1, [3.0])
    _set_grad(p2, [4.0])
    o.step()
    # gnorm=5 -> scale 0.2 -> grads 0.6, 0.8
    assert np.allclose(p1.numpy(), [2.4], atol=1e-5)
    assert np.allclose(p2.numpy(), [3.2], atol=1e-5)


def test_multi_precision_master_weights():
    p = nn.Parameter(np.asarray([1.0], dtype=np.float32))
    p._value = p._value.astype("bfloat16")
    o = opt.Adam(learning_rate=0.01, parameters=[p], multi_precision=True)
    _set_grad(p, [0.5])
    o.step()
    slots = o._slots[id(p)]
    assert "master_weight" in slots
    assert str(slots["master_weight"].dtype) == "float32"
    assert p.dtype == "bfloat16"


def test_lr_schedulers():
    s = opt.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    vals = []
    for _ in range(5):
        vals.append(round(s(), 5))
        s.step()
    assert vals == [0.1, 0.1, 0.05, 0.05, 0.025]

    w = opt.lr.LinearWarmup(0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    assert w() == pytest.approx(0.0)
    for _ in range(4):
        w.step()
    assert w() == pytest.approx(0.1)

    c = opt.lr.CosineAnnealingDecay(0.1, T_max=10)
    c.step(10)
    assert c() == pytest.approx(0.0, abs=1e-6)


def test_scheduler_in_optimizer():
    p = _make_param([1.0])
    sched = opt.lr.StepDecay(0.1, step_size=1, gamma=0.1)
    o = opt.SGD(learning_rate=sched, parameters=[p])
    assert o.get_lr() == pytest.approx(0.1)
    sched.step()
    assert o.get_lr() == pytest.approx(0.01)


def test_functional_update_matches_eager():
    pv = np.random.rand(4).astype(np.float32)
    gv = np.random.rand(4).astype(np.float32)
    # eager
    p = _make_param(pv.copy())
    o = opt.Adam(learning_rate=0.01, parameters=[p])
    _set_grad(p, gv)
    o.step()
    # functional
    o2 = opt.Adam(learning_rate=0.01)
    import jax.numpy as jnp

    params = {"w": jnp.asarray(pv)}
    st = o2.functional_init(params)
    new_p, _ = o2.functional_update(params, {"w": jnp.asarray(gv)}, st)
    assert np.allclose(p.numpy(), np.asarray(new_p["w"]), atol=1e-6)


def test_state_dict_roundtrip():
    p = _make_param([1.0, 2.0])
    p.name = "w0"
    o = opt.Adam(learning_rate=0.01, parameters=[p])
    _set_grad(p, [0.1, 0.2])
    o.step()
    sd = o.state_dict()
    o2 = opt.Adam(learning_rate=0.01, parameters=[p])
    o2.set_state_dict(sd)
    assert o2._step_count == 1
    assert np.allclose(
        np.asarray(o2._slots[id(p)]["moment1"]), np.asarray(o._slots[id(p)]["moment1"])
    )


def test_lamb_and_lars_run():
    for cls in (opt.Lamb, opt.LarsMomentum, opt.RMSProp, opt.Adagrad, opt.Adadelta,
                opt.Adamax):
        p = _make_param(np.random.rand(3).astype(np.float32))
        o = cls(learning_rate=0.01, parameters=[p])
        before = p.numpy().copy()
        _set_grad(p, [0.5, 0.5, 0.5])
        o.step()
        assert not np.allclose(p.numpy(), before), cls.__name__


def test_decayed_adagrad_ftrl_dpsgd_converge():
    """The fluid-era optimizer tail (reference fluid/optimizer.py
    DecayedAdagrad/Ftrl/Dpsgd) minimizes a quadratic."""
    import numpy as np

    import paddle_tpu as paddle

    for cls, kw in [
        (paddle.optimizer.DecayedAdagrad, dict(learning_rate=0.5)),
        (paddle.optimizer.Ftrl, dict(learning_rate=0.5)),
        (paddle.optimizer.Dpsgd,
         dict(learning_rate=0.2, clip=5.0, batch_size=1.0, sigma=1e-6)),
    ]:
        w = paddle.to_tensor(np.array([3.0, -2.0], "float32"))
        w.stop_gradient = False
        opt = cls(parameters=[w], **kw)
        for _ in range(60):
            loss = paddle.sum(paddle.multiply(w, w))
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss.numpy()) < 0.05, cls.__name__
    # fluid aliases exist with 1.x signatures
    import paddle_tpu.fluid.optimizer as fo

    o = fo.FtrlOptimizer(0.1, parameter_list=[w])
    assert isinstance(o, paddle.optimizer.Ftrl)
