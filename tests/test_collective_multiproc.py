"""Multi-process collective tests — the reference pattern
(/root/reference/python/paddle/fluid/tests/unittests/test_collective_base.py:32):
fork N OS processes with crafted PADDLE_TRAINER_ID/PADDLE_MASTER envs, run a
small per-rank program, check numpy equality in the parent.

Exercises the honest (src, dst)-keyed p2p transport over the TCPStore
(VERDICT r2 item 3) plus the store-backed barrier and scatter(src=).
"""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.slow


_WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    os.environ["JAX_PLATFORMS"] = "cpu"
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.core.tensor import Tensor

    rank = int(os.environ["PADDLE_TRAINER_ID"])

    # ---- p2p: rank0 sends two FIFO messages to rank1; rank1 replies ----
    a0 = np.arange(6, dtype=np.float32).reshape(2, 3)
    a1 = a0 * 10.0
    if rank == 0:
        dist.send(Tensor(a0), dst=1)
        dist.send(Tensor(a1), dst=1)
        back = Tensor(np.zeros((2, 3), np.float32))
        dist.recv(back, src=1)
        assert np.allclose(back.numpy(), a0 + a1), "reply mismatch"
    else:
        m1 = Tensor(np.zeros((2, 3), np.float32))
        m2 = Tensor(np.zeros((2, 3), np.float32))
        dist.recv(m1, src=0)
        dist.recv(m2, src=0)
        assert np.allclose(m1.numpy(), a0), "FIFO order violated (first msg)"
        assert np.allclose(m2.numpy(), a1), "FIFO order violated (second msg)"
        dist.send(Tensor(m1.numpy() + m2.numpy()), dst=0)

    # ---- barrier: both ranks must arrive ----
    dist.barrier()

    # ---- scatter(src=1): rank1's rows land per-rank ----
    rows = [np.full((3,), 100.0 + r, np.float32) for r in range(2)]
    out = Tensor(np.zeros((3,), np.float32))
    if rank == 1:
        dist.scatter(out, rows, src=1)
    else:
        dist.scatter(out, None, src=1)
    assert np.allclose(out.numpy(), 100.0 + rank), f"scatter row {rank} wrong"

    dist.barrier()
    print(f"rank {rank} OK", flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_p2p_two_process():
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_STORE_MASTER": f"127.0.0.1:{port}",
            "PADDLE_DISTRIBUTED_BACKEND": "store",
            "JAX_PLATFORMS": "cpu",
            "PADDLE_P2P_TIMEOUT": "60",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode(errors="replace"))
        assert p.returncode == 0, f"rank {rank} failed:\n{outs[-1]}"
    assert "rank 0 OK" in outs[0]
    assert "rank 1 OK" in outs[1]


def test_recv_wrong_src_raises_inproc():
    """recv must refuse to deliver a message from a different source."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.core.tensor import Tensor

    dist.init_parallel_env()
    t = Tensor(np.ones((2,), np.float32))
    dist.send(t, dst=1)  # channel 0->1
    got = Tensor(np.zeros((2,), np.float32))
    with pytest.raises(RuntimeError, match="no message pending"):
        dist.recv(got, src=1)  # channel 1->0 is empty: must NOT deliver 0->1
    # and the correct channel still delivers in order
    back = Tensor(np.zeros((2,), np.float32))
    from paddle_tpu.distributed import collective as C

    C._local_p2p[(C._world_group().id, 1, 0)].append(np.full((2,), 5.0, np.float32))
    dist.recv(back, src=1)
    assert np.allclose(back.numpy(), 5.0)


def test_reduce_only_dst_row():
    """reduce(dst=2): row 2 gets the sum, other rows keep their values."""
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    vals = [np.full((3,), float(i), np.float32) for i in range(8)]
    t = dist.collective.scatter_ranks(vals)
    before = np.asarray(t._value).copy()
    dist.reduce(t, dst=2)
    out = np.asarray(t._value)
    assert np.allclose(out[2], 28.0)
    for r in range(8):
        if r != 2:
            assert np.allclose(out[r], before[r]), f"row {r} was clobbered"
