"""Hierarchical Scope (survey #17) + structured error codes (#29) tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import errors
from paddle_tpu.static.scope import Scope, scope_guard


def test_scope_hierarchy_lookup():
    root = Scope()
    root.set("w", 1.0)
    kid = root.new_scope()
    kid.set("b", 2.0)
    # find_var walks ancestors (reference Scope::FindVar)
    assert kid.get("w") == 1.0
    assert kid.get("b") == 2.0
    with pytest.raises(errors.NotFoundError):
        root.get("b")  # parent does NOT see child vars
    assert root.find_var("b") is None
    assert kid.find_var("w").name == "w"
    # var() creates locally; handles read/write through the scope
    h = kid.var("x")
    assert not h.is_initialized()
    h.set_tensor(np.ones(3))
    assert kid.get("x").shape == (3,)
    kid2 = root.new_scope()
    root.drop_kids()
    assert root.local_var_names() == ["w"]


def test_scope_guard_and_executor_fetch_persistence():
    from paddle_tpu import static

    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            y = paddle.sum(x)
        sc = Scope()
        exe = static.Executor()
        with scope_guard(sc):
            res = exe.run(main, feed={"x": np.ones((2, 2), np.float32)},
                          fetch_list=[y], scope=sc)
        assert float(res[0]) == 4.0
        # the fetch persisted into the scope under the var's name
        assert float(np.asarray(sc.get(y.name))) == 4.0
    finally:
        paddle.disable_static()


def test_program_debug_string_and_dot():
    """DebugString/graphviz analogs (reference: fluid/graphviz.py +
    ir/graph_viz_pass.cc)."""
    from paddle_tpu import nn, static

    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 4], "float32")
            with static.device_guard("stage:1"):
                y = nn.functional.relu(nn.Linear(4, 3)(x))
        s = static.program_to_string(main)
        assert "block 0" in s and "relu" in s and "x:float32[2, 4]" in s
        assert "device=stage:1" in s
        dot = static.program_to_dot(main)
        assert dot.startswith("digraph") and dot.rstrip().endswith("}")
        assert "relu" in dot and "palegreen" in dot  # device-tagged op colored
        assert dot.count("->") >= 4  # var->op and op->var edges present
    finally:
        paddle.disable_static()


def test_error_taxonomy_codes_and_builtin_compat():
    with pytest.raises(ValueError) as ei:
        raise errors.InvalidArgumentError("bad axis", axis=7, ndim=2)
    assert "(INVALID_ARGUMENT)" in str(ei.value)
    assert "axis=7" in str(ei.value)
    assert isinstance(ei.value, errors.PaddleError)

    with pytest.raises(NotImplementedError):
        raise errors.UnimplementedError("no such kernel")
    with pytest.raises(MemoryError):
        raise errors.ResourceExhaustedError("HBM full", requested="1GB")

    errors.enforce(True, "never")
    with pytest.raises(errors.PreconditionNotMetError):
        errors.enforce(False, "must init first",
                       error=errors.PreconditionNotMetError)
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce_eq(1, 2)
    assert errors.enforce_not_none(5) == 5
    with pytest.raises(errors.NotFoundError):
        errors.enforce_not_none(None, "missing table")
