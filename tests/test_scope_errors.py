"""Hierarchical Scope (survey #17) + structured error codes (#29) tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import errors
from paddle_tpu.static.scope import Scope, scope_guard


def test_scope_hierarchy_lookup():
    root = Scope()
    root.set("w", 1.0)
    kid = root.new_scope()
    kid.set("b", 2.0)
    # find_var walks ancestors (reference Scope::FindVar)
    assert kid.get("w") == 1.0
    assert kid.get("b") == 2.0
    with pytest.raises(errors.NotFoundError):
        root.get("b")  # parent does NOT see child vars
    assert root.find_var("b") is None
    assert kid.find_var("w").name == "w"
    # var() creates locally; handles read/write through the scope
    h = kid.var("x")
    assert not h.is_initialized()
    h.set_tensor(np.ones(3))
    assert kid.get("x").shape == (3,)
    kid2 = root.new_scope()
    root.drop_kids()
    assert root.local_var_names() == ["w"]


def test_scope_guard_and_executor_fetch_persistence():
    from paddle_tpu import static

    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            y = paddle.sum(x)
        sc = Scope()
        exe = static.Executor()
        with scope_guard(sc):
            res = exe.run(main, feed={"x": np.ones((2, 2), np.float32)},
                          fetch_list=[y], scope=sc)
        assert float(res[0]) == 4.0
        # the fetch persisted into the scope under the var's name
        assert float(np.asarray(sc.get(y.name))) == 4.0
    finally:
        paddle.disable_static()


def test_error_taxonomy_codes_and_builtin_compat():
    with pytest.raises(ValueError) as ei:
        raise errors.InvalidArgumentError("bad axis", axis=7, ndim=2)
    assert "(INVALID_ARGUMENT)" in str(ei.value)
    assert "axis=7" in str(ei.value)
    assert isinstance(ei.value, errors.PaddleError)

    with pytest.raises(NotImplementedError):
        raise errors.UnimplementedError("no such kernel")
    with pytest.raises(MemoryError):
        raise errors.ResourceExhaustedError("HBM full", requested="1GB")

    errors.enforce(True, "never")
    with pytest.raises(errors.PreconditionNotMetError):
        errors.enforce(False, "must init first",
                       error=errors.PreconditionNotMetError)
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce_eq(1, 2)
    assert errors.enforce_not_none(5) == 5
    with pytest.raises(errors.NotFoundError):
        errors.enforce_not_none(None, "missing table")
