"""Fused LayerNorm Pallas kernel — interpret-mode validation of forward
AND backward against the plain XLA layer_norm math."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.fused_layernorm import (
    fused_layer_norm,
    maybe_fused_layer_norm,
)


def _ref(x, g, b, eps=1e-5):
    mu = x.astype(np.float32).mean(-1, keepdims=True)
    var = x.astype(np.float32).var(-1, keepdims=True)
    return ((x - mu) / np.sqrt(var + eps) * g + b).astype(x.dtype)


@pytest.mark.parametrize("shape", [(64, 128), (8, 16, 256)])
def test_fused_ln_forward_matches_reference(shape):
    rng = np.random.RandomState(0)
    x = rng.randn(*shape).astype(np.float32)
    g = (rng.rand(shape[-1]) + 0.5).astype(np.float32)
    b = (rng.randn(shape[-1]) * 0.1).astype(np.float32)
    y = fused_layer_norm(jnp.asarray(x), jnp.asarray(g), jnp.asarray(b),
                         1e-5, True)
    np.testing.assert_allclose(np.asarray(y), _ref(x, g, b), rtol=1e-5,
                               atol=1e-6)


def test_fused_ln_backward_matches_xla_grads():
    rng = np.random.RandomState(1)
    x = rng.randn(64, 128).astype(np.float32)
    g = (rng.rand(128) + 0.5).astype(np.float32)
    b = (rng.randn(128) * 0.1).astype(np.float32)
    w = rng.randn(64, 128).astype(np.float32)  # non-uniform cotangent

    def fused_loss(x, g, b):
        return jnp.sum(fused_layer_norm(x, g, b, 1e-5, True) * w)

    def xla_loss(x, g, b):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        y = (x - mu) * jax.lax.rsqrt(var + 1e-5) * g + b
        return jnp.sum(y * w)

    gx, gg, gb = jax.grad(fused_loss, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(g), jnp.asarray(b))
    rx, rg, rb = jax.grad(xla_loss, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(g), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(rg), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb), rtol=1e-4,
                               atol=1e-4)


def test_fused_ln_bf16_dtype_preserved():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(64, 128), jnp.bfloat16)
    g = jnp.ones(128, jnp.bfloat16)
    b = jnp.zeros(128, jnp.bfloat16)
    y = fused_layer_norm(x, g, b, 1e-5, True)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        _ref(np.asarray(x, np.float32), np.ones(128, np.float32),
             np.zeros(128, np.float32)), rtol=3e-2, atol=3e-2)


def test_maybe_fused_ln_gates():
    from paddle_tpu.utils import flags

    x = jnp.zeros((64, 128), jnp.float32)
    g = jnp.ones(128)
    b = jnp.zeros(128)
    # cpu backend (conftest): XLA path
    assert maybe_fused_layer_norm(x, g, b, 1e-5) is None
    # non-tileable widths / few rows must gate off regardless of backend
    assert maybe_fused_layer_norm(jnp.zeros((64, 100)), jnp.ones(100),
                                  jnp.zeros(100), 1e-5) is None
    assert maybe_fused_layer_norm(jnp.zeros((4, 128)), g, b, 1e-5) is None
    flags.set_flags({"FLAGS_use_fused_layernorm": False})
    try:
        assert maybe_fused_layer_norm(x, g, b, 1e-5) is None
    finally:
        flags.set_flags({"FLAGS_use_fused_layernorm": True})


def test_layer_norm_functional_unchanged_on_cpu():
    """nn.functional.layer_norm numerics are identical (gate is off on
    CPU, and when on-TPU the kernel matches — forward test above)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(64, 128).astype(np.float32))
    w = paddle.to_tensor((rng.rand(128) + 0.5).astype(np.float32))
    b = paddle.to_tensor((rng.randn(128) * 0.1).astype(np.float32))
    out = F.layer_norm(x, 128, weight=w, bias=b)
    np.testing.assert_allclose(
        np.asarray(out._value),
        _ref(x.numpy(), w.numpy(), b.numpy()), rtol=1e-5, atol=1e-6)
