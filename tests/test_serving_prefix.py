"""Automatic prefix caching: refcounted page sharing + copy-on-write.

Pins the PR's contract end to end:

- allocator refcount invariants (decref by one holder keeps a shared page
  resident; double decref still raises; accounting drains to zero),
- whole-page content matching and shared admission (unique-page cost),
- COW fires only for the non-last writer; the sole holder writes in place,
- preemption (recompute AND swap) of a request holding shared pages never
  frees pages another request still references,
- LRU eviction reclaims refcount-0 cached pages only when the allocator
  would otherwise fail, purging their index entries (the recycled-page
  stale-KV regression),
- greedy outputs bit-identical with `enable_prefix_caching` on vs off and
  hit vs cold miss; a shared-prefix pair reduces prefilled tokens by at
  least the whole-page-rounded shared length,
- engine + cache compile counts stable across hit/miss/COW/eviction paths
  (prefix caching never changes pool or table shapes),
- multi-bucket prefill: the bucket set is the only source of new compiles,
- the jitted swap gather/scatter path: byte-exact roundtrip, one trace
  each across swap events of different page counts.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.serving import (FaultInjector, PagedCacheConfig,
                                PagedKVCache, PageAllocator, ServingConfig,
                                ServingEngine)
from paddle_tpu.serving.engine import prefill_buckets
from paddle_tpu.serving.kv_cache import NULL_PAGE
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM


def _cache(num_pages=9, page_size=4, max_batch=3, pages_per_seq=4,
           caching=True):
    return PagedKVCache(PagedCacheConfig(
        num_layers=1, num_heads=1, head_dim=4, num_pages=num_pages,
        page_size=page_size, max_batch=max_batch,
        pages_per_seq=pages_per_seq, enable_prefix_caching=caching))


# ----------------------------------------------------- allocator refcounts
def test_allocator_refcount_share_and_drain():
    a = PageAllocator(8)  # 7 usable
    (p,) = a.alloc(1)
    assert a.refcount(p) == 1 and a.pages_in_use == 1
    assert a.incref(p) == 2
    # decref by ONE holder keeps the page resident for the other
    assert a.decref(p) == 1
    assert a.pages_in_use == 1 and p not in a._free
    assert a.decref(p) == 0
    assert a.pages_in_use == 0 and a.num_free == 7
    # double decref raises (free-list pages have no holders)
    with pytest.raises(ValueError):
        a.decref(p)
    with pytest.raises(ValueError):
        a.free([p])
    with pytest.raises(ValueError):
        a.incref(p)  # no live holders: revival goes through take_cached


def test_allocator_hold_parks_reclaimable_not_free():
    a = PageAllocator(4)
    (p,) = a.alloc(1)
    assert a.decref(p, hold=True) == 0
    assert a.num_reclaimable == 1 and a.pages_in_use == 0
    assert p not in a._free
    # alloc never taps the reclaimable pool silently
    assert a.alloc(3) is None and a.num_reclaimable == 1
    # a cache hit revives it at refcount 1; eviction reclaims it to free
    a.take_cached(p)
    assert a.refcount(p) == 1 and a.num_reclaimable == 0
    a.decref(p, hold=True)
    assert a.reclaim_lru() == p
    assert a.num_free == 3 and a.reclaim_lru() is None


# ------------------------------------------------- matching + shared admit
def test_admit_shares_cached_whole_pages_only():
    c = _cache(num_pages=12, page_size=4)
    prompt = np.arange(10, dtype=np.int32)  # 2 full pages + 2 tokens
    assert c.admit(0, 10, tokens=prompt)
    assert c.cached_tokens(0) == 0  # cold
    c.register_prefix(0, prompt)  # indexes pages 0 and 1 (full), not 2
    donor_pages = list(c._slot_pages[0])
    used = c.allocator.pages_in_use

    assert c.admit(1, 10, tokens=prompt)
    assert c.cached_tokens(1) == 8  # whole-page granularity
    pages1 = c._slot_pages[1]
    assert pages1[:2] == donor_pages[:2]  # shared by table mapping
    assert pages1[2] != donor_pages[2]    # the partial page is private
    # sharing cost only ONE unique page
    assert c.allocator.pages_in_use == used + 1
    assert c.allocator.refcount(donor_pages[0]) == 2
    assert c.shared_page_count() == 2
    c.check_invariants()

    # releasing ONE holder keeps the shared pages resident for the other
    c.release(1)
    assert c.allocator.refcount(donor_pages[0]) == 1
    assert (c.page_table[0, :3] == donor_pages).all()
    c.check_invariants()


def test_release_parks_indexed_pages_reclaimable():
    c = _cache()
    prompt = np.arange(8, dtype=np.int32)
    assert c.admit(0, 8, tokens=prompt)
    c.register_prefix(0, prompt)
    pages = list(c._slot_pages[0])
    c.release(0)
    # refcount-0 indexed pages park reclaimable (warm cache), in-use drains
    assert c.allocator.pages_in_use == 0
    assert c.allocator.num_reclaimable == 2
    # a new identical prompt re-hits the SAME pages without allocation
    assert c.admit(1, 8, tokens=prompt)
    assert c._slot_pages[1][:1] == pages[:1]
    assert c.cached_tokens(1) == 7  # full hit capped at prompt_len - 1
    c.check_invariants()


# ------------------------------------------------------------ copy-on-write
def test_cow_triggers_only_for_the_non_last_writer():
    c = _cache(num_pages=12, page_size=4)
    prompt = np.arange(8, dtype=np.int32)  # exactly 2 full pages
    assert c.admit(0, 8, tokens=prompt)
    c.register_prefix(0, prompt)
    donor_last = c._slot_pages[0][-1]

    # donor still RUNNING: the full-prompt hit must privatize the last
    # page before the tail write (another holder exists) -> COW copy
    assert c.admit(1, 8, tokens=prompt)
    assert c.cow_copies == 1
    assert c._slot_pages[1][0] == c._slot_pages[0][0]  # first page shared
    assert c._slot_pages[1][1] != donor_last           # last page copied
    assert c.allocator.refcount(donor_last) == 1       # donor's alone again
    c.check_invariants()

    # all holders gone: the LAST writer takes the cached page in place
    c.release(0)
    c.release(1)
    assert c.admit(2, 8, tokens=prompt)
    assert c.cow_copies == 1, "sole holder must not copy"
    assert c._slot_pages[2][1] == donor_last
    assert c.cached_tokens(2) == 7
    c.check_invariants()


def test_cow_admission_is_all_or_nothing():
    # pool sized so the COW page itself cannot be allocated: admission
    # must fail cleanly with every claim rolled back
    c = _cache(num_pages=5, page_size=4, pages_per_seq=4)  # 4 usable
    prompt = np.arange(8, dtype=np.int32)
    assert c.admit(0, 8, tokens=prompt)  # 2 pages
    c.register_prefix(0, prompt)
    assert c.admit(1, 7, tokens=np.arange(100, 107, dtype=np.int32))  # 2 more
    # full hit on slot 0's chain while it still runs: needs 1 COW page,
    # pool is dry and nothing is reclaimable
    before = c.allocator.pages_in_use
    assert not c.admit(2, 8, tokens=prompt)
    assert c.allocator.pages_in_use == before
    assert c.cow_copies == 0
    assert c.allocator.refcount(c._slot_pages[0][0]) == 1  # claim undone
    c.check_invariants()


# ----------------------------------------------- preemption refcount safety
@pytest.mark.parametrize("mode", ["release", "swap"])
def test_preempting_shared_holder_never_frees_other_holders_pages(mode):
    c = _cache(num_pages=12, page_size=4)
    prompt = np.arange(12, dtype=np.int32)  # 3 full pages
    assert c.admit(0, 12, tokens=prompt)
    c.register_prefix(0, prompt)
    assert c.admit(1, 12, tokens=prompt)  # shares 2, COWs the third
    shared = c._slot_pages[0][:2]
    assert c._slot_pages[1][:2] == shared

    # preempt the DONOR (recompute drops its pages; swap copies them out)
    if mode == "swap":
        handle = c.swap_out(0)
        assert handle.n_pages == 3
    else:
        c.release(0)
    # the survivor's mapped pages are untouched and still refcounted
    assert all(c.allocator.refcount(p) == 1 for p in shared)
    assert (c.page_table[1, :3] == c._slot_pages[1]).all()
    c.check_invariants()
    c.release(1)
    assert c.allocator.pages_in_use == 0
    c.check_invariants()


# --------------------------------------------------- LRU eviction + staleness
def test_lru_eviction_only_when_allocator_would_fail():
    c = _cache(num_pages=6, page_size=4, pages_per_seq=4,
               max_batch=4)  # 5 usable pages
    a_prompt = np.arange(8, dtype=np.int32)
    assert c.admit(0, 8, tokens=a_prompt)
    c.register_prefix(0, a_prompt)
    c.release(0)  # 2 reclaimable, 3 free
    assert c.allocator.num_reclaimable == 2

    # fits in the free list: NO eviction, the warm cache survives
    assert c.admit(1, 12, tokens=np.arange(50, 62, dtype=np.int32))
    assert c.evictions == 0 and c.allocator.num_reclaimable == 2

    # next admission overflows the free list: LRU pages are reclaimed and
    # their index entries purged BEFORE the allocator is allowed to fail
    assert c.admit(2, 8, tokens=np.arange(80, 88, dtype=np.int32))
    assert c.evictions == 2
    assert c.allocator.num_reclaimable == 0
    assert c._key_to_page == {} and c._page_key == {}
    c.check_invariants()

    # the evicted chain is gone: the same prompt is now a cold miss
    c.release(1)
    c.release(2)
    assert c.admit(3, 8, tokens=a_prompt)
    assert c.cached_tokens(3) == 0
    c.check_invariants()


def test_recycled_page_never_serves_stale_kv():
    """Regression (swap/stale-bytes satellite): a page freed by swap_out or
    eviction and recycled into a new request must never be reachable
    through the prefix index — a hit on it would splice stale KV into the
    new request through the ragged mask's unmasked prefix."""
    model = _toy_model(seed=31)
    common = np.arange(1, 9, dtype=np.int32)
    other = np.arange(40, 48, dtype=np.int32)

    engine = ServingEngine(model, ServingConfig(
        max_batch=1, num_pages=5, page_size=4, max_prompt_len=8,
        preemption_mode="swap"))
    r1 = engine.add_request(common, 4)
    out1 = engine.run()[r1]
    # churn the tiny pool: the cached pages of r1 must be evicted to admit
    # this disjoint request (4 usable pages, 2 cached + 3 needed)
    engine.add_request(other, 4)
    engine.run()
    assert engine.cache.evictions > 0
    # free-list pages must not be index-reachable
    engine.cache.check_invariants()
    # the original prompt again: whatever the cache state, output is
    # bit-identical to the first run (stale pages would corrupt it)
    r3 = engine.add_request(common, 4)
    out3 = engine.run()[r3]
    np.testing.assert_array_equal(out1, out3)
    assert engine.cache.allocator.pages_in_use == 0


def test_doomed_allocation_never_purges_the_warm_cache():
    # an admission that cannot succeed even after full eviction must fail
    # with NO state change — evicting the warm cache for a request that
    # gets rejected anyway would make the next hit a pointless cold miss
    c = _cache(num_pages=4, page_size=4, max_batch=2, pages_per_seq=4)
    prompt = np.arange(8, dtype=np.int32)
    assert c.admit(0, 8, tokens=prompt)  # 2 of the 3 usable pages
    c.register_prefix(0, prompt)
    c.release(0)  # 1 free + 2 reclaimable
    assert not c.admit(1, 16, tokens=np.arange(30, 46, dtype=np.int32))
    assert c.evictions == 0 and c.allocator.num_reclaimable == 2
    assert len(c.match_prefix(prompt)) == 2, "warm chain must survive"
    c.check_invariants()


def test_recycled_page_id_cannot_resurrect_stale_chain_links():
    """The linked-key index must survive page-id recycling: after chain
    A's head is evicted and its PAGE ID becomes chain B's head, a prompt
    of B's first block + A's second block must not match A's orphaned
    child entry (keys link by never-reused registration serial, not by
    recyclable page id — a page-id link would splice A's KV under B's
    prefix)."""
    c = _cache(num_pages=4, page_size=4, max_batch=2, pages_per_seq=4)
    blk_a1 = np.arange(0, 4, dtype=np.int32)
    blk_a2 = np.arange(4, 8, dtype=np.int32)
    chain_a = np.concatenate([blk_a1, blk_a2])
    assert c.admit(0, 8, tokens=chain_a)
    c.register_prefix(0, chain_a)
    a_head, a_child = c._slot_pages[0]
    c.release(0)  # both parked reclaimable; head is LRU-oldest

    # chain B needs 2 pages, free list holds 1: evicts ONLY a_head, and
    # the recycled id becomes B's head page
    blk_b1 = np.arange(50, 54, dtype=np.int32)
    chain_b = np.concatenate([blk_b1, np.arange(60, 64, dtype=np.int32)])
    assert c.admit(1, 8, tokens=chain_b)
    assert c.evictions == 1
    assert a_head in c._slot_pages[1], "eviction must recycle A's head id"
    c.register_prefix(1, chain_b)
    c.release(1)
    assert a_child in c._page_key  # orphaned but parked: purges on evict

    # the spliced prompt matches only B's head — never A's orphaned child
    spliced = np.concatenate([blk_b1, blk_a2])
    assert c.match_prefix(spliced) == [a_head]
    c.check_invariants()


# ------------------------------------------------------- jitted swap path
def test_swap_gather_scatter_compile_once_across_sizes():
    import jax.numpy as jnp

    c = _cache(num_pages=12, page_size=4, max_batch=3, pages_per_seq=4)
    rng = np.random.RandomState(3)
    k = rng.rand(*np.shape(np.asarray(c.pools[0]["k_pool"]))).astype(
        np.float32)
    v = rng.rand(*k.shape).astype(np.float32)
    c.pools = [{"k_pool": jnp.asarray(k), "v_pool": jnp.asarray(v)}]

    assert c.admit(0, 6)   # 2 pages
    assert c.admit(1, 12)  # 3 pages
    p0, p1 = list(c._slot_pages[0]), list(c._slot_pages[1])
    h0 = c.swap_out(0)
    h1 = c.swap_out(1)  # DIFFERENT n_pages: same trace (padded width)
    assert h0.n_pages == 2 and h1.n_pages == 3
    np.testing.assert_array_equal(h0.k[0], k[p0])
    np.testing.assert_array_equal(h1.v[0], v[p1])

    assert c.swap_in(0, h1)  # restore across sizes, fresh page ids
    assert c.swap_in(1, h0)
    q0, q1 = c._slot_pages[0], c._slot_pages[1]
    kk = np.asarray(c.pools[0]["k_pool"])
    vv = np.asarray(c.pools[0]["v_pool"])
    np.testing.assert_array_equal(kk[q0], k[p1])
    np.testing.assert_array_equal(vv[q1], v[p0])
    # one trace each across four swap events of two different sizes
    assert c.compile_counts["swap_gather"] == 1
    assert c.compile_counts["swap_scatter"] == 1


# ------------------------------------------------------------- engine e2e
def _toy_model(seed=29):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _reference(model, prompt, budget):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=budget)
    return np.asarray(out._value)[0]


def _shared_prefix_prompts(rng, n, system_len=12, tail_len=3):
    system = rng.randint(0, 97, (system_len,)).astype(np.int32)
    return [np.concatenate([system, rng.randint(0, 97, (tail_len,))
                            .astype(np.int32)]) for _ in range(n)]


def test_prefix_hit_is_bit_identical_and_saves_prefill_tokens():
    model = _toy_model()
    rng = np.random.RandomState(0)
    # 12-token shared system prompt = 3 whole pages of 4
    prompts = _shared_prefix_prompts(rng, 3)
    budgets = [5, 6, 4]

    def drive(enable):
        engine = ServingEngine(model, ServingConfig(
            max_batch=1, num_pages=32, page_size=4, max_prompt_len=16,
            enable_prefix_caching=enable))
        outs = {}
        for p, b in zip(prompts, budgets):  # sequential: r2+ hit r1's pages
            rid = engine.add_request(p, b)
            outs[rid] = engine.run()[rid]
        return engine, list(outs.values()), engine.metrics.snapshot()

    eng_on, outs_on, snap_on = drive(True)
    eng_off, outs_off, snap_off = drive(False)

    # bit-identical on vs off, and vs the single-request reference
    for i, (a, b) in enumerate(zip(outs_on, outs_off)):
        np.testing.assert_array_equal(a, b, err_msg=f"request {i} diverged")
        np.testing.assert_array_equal(a, _reference(model, prompts[i],
                                                    budgets[i]))

    # requests 2 and 3 each reused >= the whole-page-rounded shared length
    assert snap_on["serving_prefix_hits"] == 2
    assert snap_on["serving_prefix_misses"] == 1
    shared_rounded = 12  # 12-token system prompt on page_size 4
    assert snap_on["serving_prefix_tokens_saved"] >= 2 * shared_rounded
    saved = (snap_off["serving_prefill_tokens_total"]
             - snap_on["serving_prefill_tokens_total"])
    assert saved >= 2 * shared_rounded
    assert snap_on["serving_prefills_total"] == \
        snap_off["serving_prefills_total"] == len(prompts)
    assert eng_on.cache.allocator.pages_in_use == 0
    eng_on.cache.check_invariants()


@pytest.mark.slow  # re-tiered 2026-08 (PR 20): tier-1 crossed its 870 s
# budget; prefix_hit_is_bit_identical keeps the hit path hot in tier-1
def test_full_prompt_hit_and_concurrent_share_parity():
    model = _toy_model(seed=41)
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, 97, (8,)).astype(np.int32)  # exactly 2 pages
    ref = _reference(model, prompt, 6)

    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=32, page_size=4, max_prompt_len=8))
    r1 = engine.add_request(prompt, 6)
    out1 = engine.run()[r1]
    # full-prompt hit against the warm (reclaimable) chain: in-place take
    r2 = engine.add_request(prompt, 6)
    out2 = engine.run()[r2]
    snap = engine.metrics.snapshot()
    assert snap["serving_prefix_hits"] == 1
    assert snap["serving_prefix_tokens_saved"] == 7  # capped at len - 1
    assert snap["serving_prefix_cow_copies"] == 0

    # two CONCURRENT identical prompts: the second must COW the last page
    r3 = engine.add_request(prompt, 6)
    r4 = engine.add_request(prompt, 6)
    outs = engine.run()
    assert engine.metrics.snapshot()["serving_prefix_cow_copies"] == 1
    for out in (out1, out2, outs[r3], outs[r4]):
        np.testing.assert_array_equal(ref, out)
    assert engine.cache.allocator.pages_in_use == 0
    engine.cache.check_invariants()


def test_compile_counts_stable_across_hit_miss_cow_evict():
    model = _toy_model(seed=43)
    rng = np.random.RandomState(1)
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=10, page_size=4, max_prompt_len=8))
    prompts = _shared_prefix_prompts(rng, 4, system_len=4, tail_len=2)

    # warmup: miss, hits, concurrent COW, and pool churn forcing eviction
    for p in prompts[:2]:
        engine.add_request(p, 4)
    engine.run()
    warm = dict(engine.compile_counts)
    cache_warm = dict(engine.cache.compile_counts)
    assert warm == {"prefill": 1, "decode": 1}  # one bucket at max 8

    for p in prompts[2:]:
        engine.add_request(p, 4)
    engine.add_request(rng.randint(0, 97, (8,)).astype(np.int32), 6)
    engine.add_request(rng.randint(0, 97, (7,)).astype(np.int32), 6)
    engine.run()
    assert engine.cache.evictions > 0 or \
        engine.cache.allocator.num_reclaimable > 0

    # hit/miss/COW/eviction never retrace: pool and table shapes are fixed
    assert engine.compile_counts == warm
    assert engine.cache.compile_counts["swap_gather"] == \
        cache_warm["swap_gather"]
    assert engine.cache.allocator.pages_in_use == 0
    engine.cache.check_invariants()


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; the
# one-compile-per-bucket invariant stays pinned tier-1 by test_serving_chunked's 3-bucket matrix,
# test_serving_tp's compile_counts pins, and the serving demo's bucket assert
def test_multi_bucket_prefill_compiles_once_per_bucket():
    assert prefill_buckets(8) == [8]
    assert prefill_buckets(32) == [8, 16, 32]
    assert prefill_buckets(48) == [8, 16, 32, 48]
    assert prefill_buckets(6) == [6]

    model = _toy_model(seed=47)
    rng = np.random.RandomState(5)
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=48, page_size=4, max_prompt_len=32,
        enable_prefix_caching=False))  # isolate the bucket dimension
    assert engine.prefill_buckets == [8, 16, 32]

    def serve(n):
        p = rng.randint(0, 97, (n,)).astype(np.int32)
        rid = engine.add_request(p, 3)
        np.testing.assert_array_equal(_reference(model, p, 3),
                                      engine.run()[rid])

    serve(3)   # bucket 8
    assert engine.compile_counts["prefill"] == 1
    serve(12)  # bucket 16
    assert engine.compile_counts["prefill"] == 2
    serve(30)  # bucket 32
    assert engine.compile_counts["prefill"] == 3
    # every further prompt reuses its bucket: the set is the ONLY source
    # of prefill compiles, and decode never retraces
    for n in (2, 8, 9, 16, 17, 31, 32, 5):
        serve(n)
    assert engine.compile_counts == {"prefill": 3, "decode": 1}


def test_prefix_cache_accounting_drains_after_fault_suite():
    model = _toy_model(seed=53)
    rng = np.random.RandomState(9)
    prompts = _shared_prefix_prompts(rng, 4, system_len=8, tail_len=2)
    inj = (FaultInjector()
           .arm("prefill_fail", step=0, rid=None)
           .arm("decode_fail", step=2, rid=None)
           .arm("pool_exhausted", step=3))
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=16, page_size=4, max_prompt_len=16),
        fault_injector=inj)
    rids = [engine.add_request(p, 5) for p in prompts]
    outs = engine.run()
    assert len(inj.fired) == 3
    survivors = [r for r in rids if engine.status(r) == "finished"]
    assert survivors and set(outs) == set(survivors)
    for rid, p in zip(rids, prompts):
        if rid in outs:
            np.testing.assert_array_equal(_reference(model, p, 5),
                                          outs[rid])
    # faulted, preempted, and finished alike: page accounting drains to
    # zero while the warm cache stays structurally sound
    assert engine.cache.allocator.pages_in_use == 0
    engine.cache.check_invariants()


def test_sampling_parity_with_prefix_hits():
    # hit-path tail prefill must not shift the (seed, rid, token) PRNG
    # stream: sampled outputs are identical with caching on vs off
    import itertools

    from paddle_tpu.serving import scheduler as sched_mod

    model = _toy_model(seed=59)
    rng = np.random.RandomState(11)
    prompts = _shared_prefix_prompts(rng, 3, system_len=8, tail_len=3)

    def drive(enable):
        sched_mod._rid_counter = itertools.count(7000)
        engine = ServingEngine(model, ServingConfig(
            max_batch=1, num_pages=32, page_size=4, max_prompt_len=16,
            do_sample=True, temperature=0.7, top_k=12, seed=3,
            enable_prefix_caching=enable))
        outs = []
        for p in prompts:
            rid = engine.add_request(p, 6)
            outs.append(engine.run()[rid])
        return outs, engine.metrics.snapshot()

    saved = sched_mod._rid_counter
    try:
        outs_on, snap_on = drive(True)
        outs_off, _ = drive(False)
    finally:
        sched_mod._rid_counter = saved
    assert snap_on["serving_prefix_hits"] == 2
    for a, b in zip(outs_on, outs_off):
        np.testing.assert_array_equal(a, b)
