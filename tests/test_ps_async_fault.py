"""Async PS communicator + worker-kill fault recovery (VERDICT r3 item 7).

Reference analog: the brpc AsyncCommunicator
(paddle/fluid/distributed/ps/service/communicator/communicator.h:1) and the
fleet fault-tolerance contract: servers hold authoritative state, so a
killed trainer re-joins by reconnecting and pulling — no barrier, no loss
of table state.

The fault test: 2 async workers train a CTR-style embedding regression
against in-process PS shards; worker 1 is SIGKILLed mid-run and restarted;
both finish and the model converges.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.ps import PsClient, PsServer
from paddle_tpu.distributed.ps import runtime as ps_runtime
from paddle_tpu.distributed.ps.communicator import AsyncCommunicator
from paddle_tpu.distributed.ps.role_maker import PaddleCloudRoleMaker

pytestmark = pytest.mark.slow


# ------------------------------------------------------------ unit-level
def _cluster(n_servers=2, n_workers=1):
    servers = [PsServer(port=0, n_workers=n_workers, host="127.0.0.1").start()
               for _ in range(n_servers)]
    eps = [f"127.0.0.1:{s.port}" for s in servers]
    client = PsClient(eps)
    return servers, client, eps


def test_async_communicator_merges_and_sends():
    servers, client, _ = _cluster()
    try:
        client.create_dense("w", 4, "sgd", 1.0,
                            init=np.zeros(4, np.float32))
        comm = AsyncCommunicator(client, send_interval=0.001).start()
        for _ in range(8):  # 8 queued grads of 1.0
            comm.push_dense("w", np.ones(4, np.float32))
        comm.flush()
        comm.stop()
        # every queued grad applied (merged sends, same math): w = -8
        np.testing.assert_allclose(client.pull_dense("w"), -8.0, rtol=1e-6)
        assert comm.merged_grads == 8
        # merging actually batched: fewer RPC rounds than grads
        assert comm.sent_batches <= 8
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_async_communicator_merges_sparse_duplicate_ids():
    servers, client, _ = _cluster()
    try:
        client.create_sparse("emb", 4, "sgd", 1.0, seed=0)
        # materialize rows first so the update is observable
        base = client.pull_sparse("emb", np.asarray([1, 2]))
        comm = AsyncCommunicator(client, send_interval=0.05).start()
        # enqueue BEFORE the first drain tick so both land in one merge
        comm.push_sparse("emb", np.asarray([1, 2]),
                         np.ones((2, 4), np.float32))
        comm.push_sparse("emb", np.asarray([2]),
                         np.ones((1, 4), np.float32))
        comm.flush()
        comm.stop()
        after = client.pull_sparse("emb", np.asarray([1, 2]))
        np.testing.assert_allclose(after[0], base[0] - 1.0, rtol=1e-5)
        np.testing.assert_allclose(after[1], base[1] - 2.0, rtol=1e-5)
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_async_communicator_retries_transient_failures():
    servers, client, _ = _cluster()
    try:
        client.create_dense("w", 2, "sgd", 1.0, init=np.zeros(2, np.float32))
        comm = AsyncCommunicator(client, send_interval=0.001, retry=3,
                                 retry_backoff=0.01)
        fails = {"n": 2}
        real = client.push_dense

        def flaky(name, grad, apply_now=True):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise ConnectionError("injected transient failure")
            return real(name, grad, apply_now)

        client.push_dense = flaky
        comm.start()
        comm.push_dense("w", np.ones(2, np.float32))
        comm.flush()
        comm.stop()
        np.testing.assert_allclose(client.pull_dense("w"), -1.0, rtol=1e-6)
        assert fails["n"] == 0  # both injected failures consumed by retries
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_the_ps_async_mode_converges(monkeypatch):
    servers, client, eps = _cluster()
    try:
        monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", ",".join(eps))
        ps_runtime.set_role(PaddleCloudRoleMaker())
        monkeypatch.setattr(ps_runtime, "_client", client)
        paddle.seed(7)

        class SparseNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = ps_runtime.DistEmbedding("v2", 50, 8, lr=0.2)
                self.fc = nn.Linear(8, 1)

            def forward(self, ids):
                return self.fc(paddle.mean(self.emb(ids), axis=1))

        net = SparseNet()
        the_ps = ps_runtime.ThePS(net, dense_optimizer="sgd", dense_lr=0.1,
                                  mode="async")
        rs = np.random.RandomState(0)
        ids = rs.randint(0, 50, (16, 3))
        target = (ids.mean(axis=1, keepdims=True) / 25.0 - 1.0).astype(
            "float32")
        losses = []
        for _ in range(25):
            pred = net(paddle.to_tensor(ids))
            loss = paddle.mean((pred - paddle.to_tensor(target)) ** 2)
            loss.backward()
            the_ps.step()  # non-blocking enqueue
            losses.append(float(loss.numpy()))
        the_ps.flush()
        the_ps.stop()
        # async staleness still converges (bounded-staleness SGD)
        assert losses[-1] < losses[0] * 0.6, losses
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_rejoining_worker_init_cannot_clobber_live_tables():
    """create_dense with init on an EXISTING table must not overwrite it —
    a restarted first worker would otherwise reset trained state."""
    servers, client, _ = _cluster()
    try:
        client.create_dense("w", 4, "sgd", 1.0, init=np.zeros(4, np.float32))
        client.push_dense("w", np.ones(4, np.float32))  # w = -1 (trained)
        # the same worker restarts and re-registers with a FRESH init
        client.create_dense("w", 4, "sgd", 1.0, init=np.full(4, 7.0, np.float32))
        np.testing.assert_allclose(client.pull_dense("w"), -1.0, rtol=1e-6)
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_server_snapshot_and_restart_recovery(tmp_path):
    """Kill a SERVER, start a fresh one, load the snapshot: weights AND
    optimizer accumulators AND lazy-init seeds recover — the
    save_persistables/load_persistables fault path (reference brpc
    Save/Load RPC). A parallel 'survivor' cluster that never died provides
    the ground-truth trajectory."""
    servers, client, eps = _cluster(n_servers=2)
    ref_servers, ref_client, _ = _cluster(n_servers=2)  # never killed
    snap_dir = str(tmp_path / "snap")
    ids = np.asarray([1, 5, 9, 12])
    g = np.tile(np.asarray([0.5, -1.0, 0.25, 2.0], np.float32), (4, 1))
    try:
        for c in (client, ref_client):
            c.create_dense("w", 6, "adagrad", 0.1,
                           init=np.arange(6, dtype=np.float32))
            c.create_sparse("emb", 4, "adagrad", 0.05, seed=3)
            c.pull_sparse("emb", ids)
            c.push_sparse("emb", ids, g)  # builds adagrad G sums
            c.push_dense("w", np.ones(6, np.float32))
        n = client.save_tables(snap_dir)
        assert n >= 3
    finally:
        client.stop_servers()
        client.close()
        for s in servers:
            s.stop()

    # fresh servers on NEW ports — nothing in memory
    servers2 = [PsServer(port=0, n_workers=1, host="127.0.0.1").start()
                for _ in range(2)]
    client2 = PsClient([f"127.0.0.1:{s.port}" for s in servers2])
    try:
        client2.load_tables(snap_dir)
        client2._sparse_dims["emb"] = 4  # client-side dim registry
        np.testing.assert_array_equal(client2.pull_dense("w"),
                                      ref_client.pull_dense("w"))
        np.testing.assert_array_equal(client2.pull_sparse("emb", ids),
                                      ref_client.pull_sparse("emb", ids))
        # optimizer ACCUMULATORS recovered: the next adagrad step on the
        # restored cluster matches the survivor exactly (G sums persisted —
        # a reset would take a far larger step)
        for c in (client2, ref_client):
            c.push_sparse("emb", ids, g)
            c.push_dense("w", np.ones(6, np.float32))
        np.testing.assert_allclose(client2.pull_sparse("emb", ids),
                                   ref_client.pull_sparse("emb", ids),
                                   rtol=1e-6)
        np.testing.assert_allclose(client2.pull_dense("w"),
                                   ref_client.pull_dense("w"), rtol=1e-6)
        # lazy-init SEED recovered: an id never materialized before the
        # snapshot initializes identically on both clusters
        fresh = np.asarray([77])
        np.testing.assert_array_equal(client2.pull_sparse("emb", fresh),
                                      ref_client.pull_sparse("emb", fresh))
    finally:
        client2.stop_servers()
        client2.close()
        ref_client.stop_servers()
        ref_client.close()
        for s in servers2 + ref_servers:
            s.stop()


def test_snapshot_rejects_mismatched_server_count(tmp_path):
    servers, client, _ = _cluster(n_servers=2)
    snap_dir = str(tmp_path / "snap2")
    try:
        client.create_dense("w", 2, "sgd", 0.1, init=np.zeros(2, np.float32))
        client.save_tables(snap_dir)
    finally:
        client.stop_servers()
        client.close()
        for s in servers:
            s.stop()
    one = [PsServer(port=0, n_workers=1, host="127.0.0.1").start()]
    c1 = PsClient([f"127.0.0.1:{one[0].port}"])
    try:
        with pytest.raises(RuntimeError, match="shard"):
            c1.load_tables(snap_dir)  # saved as 2 shards; loud, not silent
    finally:
        c1.stop_servers()
        c1.close()
        for s in one:
            s.stop()


# ------------------------------------------------------------ fault test
_FAULT_WORKER = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax; jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.ps import runtime as ps_runtime
    from paddle_tpu.distributed.ps.role_maker import PaddleCloudRoleMaker

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    steps = int(os.environ["FAULT_STEPS"])
    step_sleep = float(os.environ["FAULT_STEP_SLEEP"])
    ps_runtime.set_role(PaddleCloudRoleMaker())
    ps_runtime.init_worker()
    paddle.seed(100 + rank)

    class SparseNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = ps_runtime.DistEmbedding("fvocab", 50, 8, lr=0.2)
            self.fc = nn.Linear(8, 1)
        def forward(self, ids):
            return self.fc(paddle.mean(self.emb(ids), axis=1))

    net = SparseNet()
    # barrier=False: a RESTARTED worker must re-join without a rendezvous
    # (create_* is idempotent; servers hold the authoritative state)
    the_ps = ps_runtime.ThePS(net, dense_optimizer="sgd", dense_lr=0.05,
                              mode="async", barrier=False)
    rs = np.random.RandomState(0)  # same fixture on every worker
    ids = rs.randint(0, 50, (16, 3))
    target = (ids.mean(axis=1, keepdims=True) / 25.0 - 1.0).astype("float32")
    import time
    progress_path = os.environ.get("FAULT_PROGRESS_FILE")
    losses = []
    for i in range(steps):
        pred = net(paddle.to_tensor(ids))
        loss = paddle.mean((pred - paddle.to_tensor(target)) ** 2)
        loss.backward()
        the_ps.step()
        losses.append(float(loss.numpy()))
        if progress_path:
            with open(progress_path, "w") as pf:
                pf.write(str(i + 1))
        time.sleep(step_sleep)
    the_ps.flush()
    the_ps.stop()
    print("RESULT " + json.dumps({"rank": rank, "first": losses[0],
                                  "last": losses[-1]}))
""")


def _spawn_worker(rank, eps, steps, step_sleep=0.02, progress_file=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "TRAINING_ROLE": "TRAINER",
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": "2",
        "PADDLE_PSERVERS_IP_PORT_LIST": ",".join(eps),
        "FAULT_STEPS": str(steps),
        "FAULT_STEP_SLEEP": str(step_sleep),
        "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    })
    if progress_file:
        env["FAULT_PROGRESS_FILE"] = progress_file
    return subprocess.Popen([sys.executable, "-c", _FAULT_WORKER], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def _wait_for_progress(path, min_steps, timeout=120):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with open(path) as f:
                if int(f.read().strip() or 0) >= min_steps:
                    return
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    raise TimeoutError(f"worker never reached step {min_steps}")


def test_async_trainer_survives_worker_kill_and_restart(tmp_path):
    """Kill worker 1 mid-run (SIGKILL), restart it; training converges."""
    servers = [PsServer(port=0, n_workers=2, host="127.0.0.1").start()
               for _ in range(2)]
    eps = [f"127.0.0.1:{s.port}" for s in servers]
    admin = PsClient(eps)
    progress = str(tmp_path / "w1_progress")
    try:
        w0 = _spawn_worker(0, eps, steps=60)
        # worker 1 sleeps longer per step so the kill always lands MID-RUN:
        # we kill only after its progress file shows real training steps
        w1 = _spawn_worker(1, eps, steps=400, step_sleep=0.1,
                           progress_file=progress)
        _wait_for_progress(progress, min_steps=5)
        os.kill(w1.pid, signal.SIGKILL)
        w1.wait()
        assert w1.returncode != 0  # actually died mid-run
        # servers must still be serving: admin client can pull
        assert admin.pull_dense is not None
        # restart worker 1: rejoins WITHOUT barrier, resumes from server state
        w1b = _spawn_worker(1, eps, steps=30)
        out0, err0 = w0.communicate(timeout=240)
        out1, err1 = w1b.communicate(timeout=240)
        assert w0.returncode == 0, err0.decode()[-2000:]
        assert w1b.returncode == 0, err1.decode()[-2000:]
        r0 = json.loads(out0.decode().split("RESULT ")[1])
        r1 = json.loads(out1.decode().split("RESULT ")[1])
        # converged despite the kill: both workers' final loss way down
        assert r0["last"] < r0["first"] * 0.5, r0
        # the restarted worker started from ALREADY-TRAINED server state
        assert r1["first"] < 1.0 and r1["last"] <= r1["first"] * 1.5, r1
    finally:
        admin.stop_servers()
        admin.close()
        for s in servers:
            s.stop()
