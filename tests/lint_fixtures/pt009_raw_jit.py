"""PT009 fixture: raw jax.jit in serving/ escapes the CompileGuard
registry — no compile budget, no retrace explanation, no hlocheck audit.
Linted as if it lived under serving/."""
import functools

import jax


def decode_step(params, state):
    return state


raw = jax.jit(decode_step, donate_argnums=(1,))

partial_raw = functools.partial(jax.jit, donate_argnums=(1,))(decode_step)


@jax.jit
def other_step(x):
    return x


sanctioned = jax.jit(decode_step)  # lint: disable=PT009

from jax import jit  # noqa: E402 — the bare-import respelling fires too

import jax as j  # noqa: E402 — the alias itself is fine...

aliased = j.jit(decode_step)  # ...but its .jit use fires
