"""PT007 fixture: mutable default argument."""


def queue_request(req, queue=[]):  # finding: shared across every call
    queue.append(req)
    return queue


def tally(name, counts={}):  # lint: disable=PT007
    counts[name] = counts.get(name, 0) + 1
    return counts


def keyword_only(*, seen=set()):  # finding: kw-only defaults count too
    return seen


def good(req, queue=None):
    queue = [] if queue is None else queue
    queue.append(req)
    return queue
