"""PT003 fixture: counter incremented without pre-seeding in _SEEDED."""
from paddle_tpu.utils import monitor

PREFIX = "serving_"

_SEEDED = ("rejected", "expired")


class Metrics:
    def reset(self):
        for k in _SEEDED:
            monitor.stat_set(PREFIX + k, 0)

    def on_rejected(self):
        monitor.stat_add(PREFIX + "rejected", 1)  # seeded: not a finding

    def on_shed(self):
        monitor.stat_add(PREFIX + "shed", 1)  # finding: never seeded

    def on_timeout(self):
        monitor.stat_add("serving_timeouts", 1)  # finding: literal name

    def on_legacy(self):
        monitor.stat_add(PREFIX + "legacy", 1)  # lint: disable=PT003
