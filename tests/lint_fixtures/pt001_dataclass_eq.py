"""PT001 fixture: dataclass with array fields and no eq=False."""
from dataclasses import dataclass

import numpy as np


@dataclass
class BadHandle:  # finding: generated __eq__ compares arrays elementwise
    n_pages: int
    k: np.ndarray
    v: np.ndarray


@dataclass  # lint: disable=PT001
class SuppressedHandle:
    k: np.ndarray


@dataclass(eq=False)
class GoodHandle:
    k: np.ndarray


@dataclass(frozen=True)
class NoArrays:  # no array field: not a finding
    n_pages: int
    name: str
