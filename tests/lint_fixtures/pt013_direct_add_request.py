"""PT013 fixture: a direct ServingEngine.add_request call inside a
fleet module (linted as if it lived at serving/fleet_rogue.py) — the
admission bypass the rule exists to close — plus the pragma-suppressed
twin, the router's sanctioned dispatch idiom."""


def rogue_dispatch(engine, prompt):
    # bypasses weighted admission, affinity placement, fleet counters
    return engine.add_request(prompt, 8)


def sanctioned_dispatch(engine, prompt, rid):
    return engine.add_request(prompt, 8, rid=rid)  # lint: disable=PT013
