"""PT004 fixture: time.time() in serving/ instead of the engine clock."""
import time


def sweep_deadlines(requests):
    now = time.time()  # finding: bypasses the pluggable clock
    return [r for r in requests if r.deadline and now >= r.deadline]


def sweep_suppressed(requests):
    now = time.time()  # lint: disable=PT004
    return [r for r in requests if r.deadline and now >= r.deadline]


def sweep_good(engine, requests):
    now = engine.now()  # pluggable clock + fault skew: not a finding
    return now, requests
