"""PT017 fixture: wire ``.exchange(...)`` calls that omit the
``rid=``/``step=`` journey-join context. The fixture is linted AS IF it
lived at serving/pt017.py; its intentional positives are what the rule
test pins. ``rid=None`` is the sanctioned no-request spelling (gossip),
and a ``**kwargs`` splat is assumed to forward the caller's context."""


def gossip(transport, peer, frames):
    return transport.exchange(peer, frames)  # finding: no rid/step


def fetch(transport, donor, frames, step):
    # finding: rid missing even though step is threaded
    return transport.exchange(donor, frames, step=step)


def rehome(transport, peer, frames, rid):
    # finding: step missing even though rid is threaded
    return transport.exchange(peer, frames, rid=rid)


def fetch_suppressed(transport, donor, frames):
    return transport.exchange(donor, frames)  # lint: disable=PT017


def good(transport, peer, frames, rid, step, kwargs):
    a = transport.exchange(peer, frames, step=step, rid=rid)
    b = transport.exchange(peer, frames, step=step, rid=None)  # gossip
    c = transport.exchange(peer, frames, **kwargs)  # splat forwards it
    return a, b, c
