"""PT010 fixture: shard_map entry points in serving/ outside the
registered tensor-parallel wrapper — the bare import, an aliased import,
and the attribute respelling all fire; the pragma-suppressed twin is the
sanctioned serving/tp.py idiom (its wrapped steps are registered with
declared CollectiveBudgets in the hlocheck registry)."""
from jax.experimental.shard_map import shard_map
from jax.experimental.shard_map import shard_map as smap

import jax.experimental.shard_map as sm_mod


def rogue_attribute(fn, mesh, specs):
    return sm_mod.shard_map(fn, mesh=mesh, in_specs=specs, out_specs=specs)


def sanctioned(fn, mesh, specs):
    from jax.experimental.shard_map import shard_map  # lint: disable=PT010
    return shard_map(fn, mesh=mesh, in_specs=specs, out_specs=specs)
