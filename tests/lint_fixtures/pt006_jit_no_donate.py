"""PT006 fixture: jit of pool-sized args without donate_argnums."""
import jax


def scatter(pools, idx, vals):
    return [pl.at[idx].set(vals) for pl in pools]


def gather(pools, idx):
    return [pl[idx] for pl in pools]


def lookup(table, idx):
    return table[idx]


scatter_bad = jax.jit(scatter)  # finding: every .at[] write copies the pool
scatter_good = jax.jit(scatter, donate_argnums=(0,))
gather_read_only = jax.jit(gather)  # lint: disable=PT006
lookup_jit = jax.jit(lookup)  # no pool-sized arg: not a finding
