"""PT006 fixture: jit of pool-sized args without donate_argnums.

(Spelled with the bare ``jit`` import so THIS fixture stays about
donation: PT006 polices the missing donate_argnums on any jit spelling,
while the raw-jit-in-serving finding — PT009, which also flags this very
import — is fixtured separately and pragma'd here.)
"""
from jax import jit  # lint: disable=PT009


def scatter(pools, idx, vals):
    return [pl.at[idx].set(vals) for pl in pools]


def gather(pools, idx):
    return [pl[idx] for pl in pools]


def lookup(table, idx):
    return table[idx]


scatter_bad = jit(scatter)  # finding: every .at[] write copies the pool
scatter_good = jit(scatter, donate_argnums=(0,))
gather_read_only = jit(gather)  # lint: disable=PT006
lookup_jit = jit(lookup)  # no pool-sized arg: not a finding
