"""PT011 fixture: pallas_call in a module with NO KERNELCHECK_CERTS
declaration — the attribute launch and the bare import both fire; the
pragma-suppressed twin is the sanctioned-uncertified escape hatch. (A
module that DOES declare KERNELCHECK_CERTS is covered by linting the real
kernels/fused_layernorm.py in test_analysis.py.)"""
from jax.experimental import pallas as pl
from jax.experimental.pallas import pallas_call


def uncertified_launch(kernel, x, out_shape):
    return pl.pallas_call(kernel, out_shape=out_shape)(x)


def uncertified_bare(kernel, x, out_shape):
    return pallas_call(kernel, out_shape=out_shape)(x)


def sanctioned(kernel, x, out_shape):
    return pl.pallas_call(kernel, out_shape=out_shape)(x)  # lint: disable=PT011
