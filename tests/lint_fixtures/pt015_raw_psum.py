"""PT015 fixture: raw psum in serving/ outside tp.py — the attribute and
from-import (aliased) forms fire; the pragma'd twin and non-psum lax
usage stay quiet."""
import jax
from jax import lax
from jax.lax import psum
from jax.lax import psum as raw_sum


def rogue_reduce(x):
    y = lax.psum(x, "tp")
    z = jax.lax.psum(y, "tp")
    return y + z + psum(x, "tp") + raw_sum(x, "tp")


def sanctioned(x):
    return lax.psum(x, "tp")  # lint: disable=PT015


def fine(x):
    return lax.stop_gradient(x) + jax.lax.exp(x)
