"""PT014 fixture: raw serialization/transport primitives in a serving
module that is NOT wire.py (linted as if at serving/sidechannel.py) —
ad-hoc framing that forks the versioned wire schema — plus the
pragma-suppressed twins of the same calls."""
import pickle
import socket
import struct
from pickle import loads  # noqa: F401


def rogue_page_bytes(page):
    return pickle.dumps(page)


def rogue_peer_read():
    return socket.socket()


def rogue_frame(serial):
    return struct.pack("<Q", serial)


def suppressed_twin(page, serial):
    blob = pickle.dumps(page)  # lint: disable=PT014
    return blob + struct.pack("<Q", serial)  # lint: disable=PT014
