"""PT008 fixture: gauge written (stat_set/stat_max) without pre-seeding."""
from paddle_tpu.utils import monitor

PREFIX = "serving_"

_SEEDED = ("queue_depth", "page_pool_peak")


class Metrics:
    def reset(self):
        for k in _SEEDED:
            monitor.stat_set(PREFIX + k, 0)  # Name, not Constant: exempt

    def on_state(self, depth, active):
        monitor.stat_set(PREFIX + "queue_depth", depth)  # seeded: clean
        monitor.stat_set(PREFIX + "active_requests", active)  # finding
        monitor.stat_set("serving_utilization", 0.5)  # finding: literal
        monitor.stat_max(PREFIX + "depth_peak", depth)  # finding: stat_max

    def on_peak(self, pages):
        monitor.stat_max(PREFIX + "page_pool_peak", pages)  # seeded: clean

    def on_legacy(self, v):
        monitor.stat_set(PREFIX + "legacy", v)  # lint: disable=PT008
