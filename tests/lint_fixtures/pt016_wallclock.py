"""PT016 fixture: nondeterminism sources (wall clock, global RNG,
id()-keyed ordering) in serving/ outside the sanctioned modules. The
fixture is linted AS IF it lived at serving/pt016.py; its intentional
positives are what the rule test pins. time.time() is deliberately
absent — that arm of the fence is PT004's."""
import random
import time

import numpy as np


def stamp(events):
    t = time.monotonic()  # finding: wall clock outside the engine clock
    return [(t, e) for e in events]


def jitter():
    return random.random() + np.random.rand()  # finding: global RNGs


def shuffle(requests):
    random.shuffle(requests)  # finding: global RNG state
    return sorted(requests, key=id)  # finding: allocator-address order


def dedup(requests):
    seen = {}
    for r in requests:
        seen[id(r)] = r  # finding: id()-keyed table
    return seen


def stamp_suppressed(events):
    t = time.monotonic()  # lint: disable=PT016
    return [(t, e) for e in events]


def good(engine, requests, seed):
    now = engine.now()  # the pluggable clock: not a finding
    rng = np.random.RandomState(seed)  # seeded constructor: fine
    local = random.Random(seed)  # seeded instance: fine
    order = sorted(requests, key=lambda r: r.rid)  # stable key: fine
    return now, rng.rand(), local.random(), order
