"""PT012 fixture: labeled stat families (``base{label=value}`` and
multi-label ``base{a=,b=}`` names, f-string formatted) written without a
``_FAMILIES`` declaration — or with label keys disagreeing with it —
the names PT003/PT008 cannot resolve statically."""
from paddle_tpu.utils import monitor

PREFIX = "serving_"
_SEEDED = ("good_total",)
_FAMILIES = {"known_total": "rule", "known_ml_total": ("tenant", "class")}


def rogue_fstring(rule):
    # base "rogue_total" is in neither _FAMILIES nor _SEEDED: fires
    monitor.stat_add(PREFIX + f"rogue_total{{rule={rule}}}", 1)


def rogue_literal():
    # a braced literal is PT012's too (PT003 defers names containing {)
    monitor.stat_set(PREFIX + "rogue_gauge{kernel=paged_decode}", 1.0)


def rogue_inline_prefix(rule):
    # the prefix carried inline in the f-string instead of PREFIX +
    monitor.stat_max(f"serving_rogue_peak{{rule={rule}}}", 2.0)


def registered(rule):
    # declared in _FAMILIES: clean
    monitor.stat_add(PREFIX + f"known_total{{rule={rule}}}", 1)


def seeded_scalar():
    # plain seeded scalar: PT003's domain, not PT012's
    monitor.stat_add(PREFIX + "good_total", 1)


def suppressed(rule):
    # the same defect, pragma-sanctioned
    monitor.stat_add(PREFIX + f"rogue2_total{{rule={rule}}}", 1)  # lint: disable=PT012


def rogue_multilabel(tenant, cls):
    # multi-label family in neither registry: fires
    monitor.stat_add(PREFIX + f"rogue_ml_total{{tenant={tenant},class={cls}}}", 1)


def registered_multilabel(tenant, cls):
    # declared with matching ordered keys: clean
    monitor.stat_add(PREFIX + f"known_ml_total{{tenant={tenant},class={cls}}}", 1)


def wrong_key(rule):
    # the base IS declared — but the written label key disagrees, so the
    # registry key can never match the seeded member: fires
    monitor.stat_add(PREFIX + f"known_total{{tenant={rule}}}", 1)


def wrong_order(tenant, cls):
    # declared keys in the wrong ORDER build a different registry key
    # than seed_family created: fires
    monitor.stat_add(PREFIX + f"known_ml_total{{class={cls},tenant={tenant}}}", 1)
