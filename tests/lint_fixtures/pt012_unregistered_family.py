"""PT012 fixture: labeled stat families (``base{label=value}`` names,
f-string formatted) written without a ``_FAMILIES`` declaration — the
names PT003/PT008 cannot resolve statically."""
from paddle_tpu.utils import monitor

PREFIX = "serving_"
_SEEDED = ("good_total",)
_FAMILIES = {"known_total": "rule"}


def rogue_fstring(rule):
    # base "rogue_total" is in neither _FAMILIES nor _SEEDED: fires
    monitor.stat_add(PREFIX + f"rogue_total{{rule={rule}}}", 1)


def rogue_literal():
    # a braced literal is PT012's too (PT003 defers names containing {)
    monitor.stat_set(PREFIX + "rogue_gauge{kernel=paged_decode}", 1.0)


def rogue_inline_prefix(rule):
    # the prefix carried inline in the f-string instead of PREFIX +
    monitor.stat_max(f"serving_rogue_peak{{rule={rule}}}", 2.0)


def registered(rule):
    # declared in _FAMILIES: clean
    monitor.stat_add(PREFIX + f"known_total{{rule={rule}}}", 1)


def seeded_scalar():
    # plain seeded scalar: PT003's domain, not PT012's
    monitor.stat_add(PREFIX + "good_total", 1)


def suppressed(rule):
    # the same defect, pragma-sanctioned
    monitor.stat_add(PREFIX + f"rogue2_total{{rule={rule}}}", 1)  # lint: disable=PT012
