"""PT002 fixture: per-layer host .at[].set loop over a stacked pool."""


def swap_in_bad(self, pages, k_all, v_all):
    for i, pl in enumerate(self.pools):  # finding: O(pool) copy per layer
        pl["k_pool"] = pl["k_pool"].at[pages].set(k_all[i])
        pl["v_pool"] = pl["v_pool"].at[pages].set(v_all[i])


def swap_in_suppressed(self, pages, k_all, v_all):
    for i, pl in enumerate(self.pools):  # lint: disable=PT002
        pl["k_pool"] = pl["k_pool"].at[pages].set(k_all[i])


def swap_in_good(self, pages, k_all, v_all):
    # one jitted scatter over the stacked view: traced once, no host loop
    self.pools = self._scatter_jit(self.pools, pages, k_all, v_all)


def unrelated_loop(items, table):
    for it in items:  # not over a pool: not a finding
        table = table.at[it].set(0)
    return table
