"""PT005 fixture: host-sync call inside a step()/decode hot path."""
import jax
import numpy as np


def step(self):
    toks = self._decode_jit(self.pools)
    toks = np.asarray(toks)  # finding: device->host sync every step
    ctx = jax.device_get(self.ctx)  # finding
    last = toks[0].item()  # finding
    return toks, ctx, last


def decode_loop(self):
    toks = np.asarray(self._decode_jit(self.pools))  # lint: disable=PT005
    return toks


def admit(self, prompt):
    # not a hot-path function name: not a finding
    return np.asarray(prompt)
