"""FS abstraction tests (reference analog: tests/unittests/test_fs_interface.py,
test_fleet_localfs_client.py)."""
import os

import pytest

from paddle_tpu.distributed.fleet import LocalFS
from paddle_tpu.distributed.fleet.fs import (
    ExecuteError, FSFileExistsError, FSFileNotExistsError, _handle_errors,
)


def test_localfs_roundtrip(tmp_path):
    fs = LocalFS()
    d = str(tmp_path / "a" / "b")
    fs.mkdirs(d)
    assert fs.is_dir(d) and fs.is_exist(d)
    f = os.path.join(d, "x.txt")
    fs.touch(f)
    assert fs.is_file(f)
    with pytest.raises(FSFileExistsError):
        fs.touch(f, exist_ok=False)
    dirs, files = fs.ls_dir(d)
    assert files == ["x.txt"] and dirs == []

    dst = os.path.join(str(tmp_path), "y.txt")
    fs.mv(f, dst)
    assert fs.is_file(dst) and not fs.is_exist(f)
    with pytest.raises(FSFileNotExistsError):
        fs.mv(f, dst)

    fs.touch(f)
    with pytest.raises(FSFileExistsError):
        fs.mv(dst, f, overwrite=False)
    fs.mv(dst, f, overwrite=True)

    up = str(tmp_path / "copy.txt")
    fs.upload(f, up)
    assert fs.is_file(up)
    fs.delete(up)
    assert not fs.is_exist(up)
    fs.delete(d)
    assert not fs.is_exist(d)


def test_handle_errors_retries_then_raises():
    calls = []

    class Flaky:
        _time_out = 0.5

        @_handle_errors()
        def sometimes(self, fail_times):
            calls.append(1)
            if len(calls) <= fail_times:
                raise OSError("transient")
            return "ok"

    assert Flaky().sometimes(2) == "ok"
    assert len(calls) == 3

    calls.clear()

    class AlwaysFail:
        _time_out = 0.3

        @_handle_errors()
        def boom(self):
            raise OSError("nope")

    with pytest.raises(ExecuteError):
        AlwaysFail().boom()
