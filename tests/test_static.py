"""Static-graph tests (reference: static executor stack, survey §3.1)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_program_build_and_run():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 3], "float32")
        w = paddle.to_tensor(np.random.rand(3, 2).astype(np.float32))
        y = paddle.matmul(x, w)
        out = paddle.sum(y)
    assert len(main.all_ops()) == 2
    exe = static.Executor()
    xv = np.random.rand(4, 3).astype(np.float32)
    res = exe.run(main, feed={"x": xv}, fetch_list=[out, y])
    assert np.allclose(res[0], (xv @ w.numpy()).sum(), rtol=1e-5)
    assert np.allclose(res[1], xv @ w.numpy(), rtol=1e-5)


@pytest.mark.slow
def test_static_layers():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 1, 28, 28], "float32")
        from paddle_tpu.vision.models import LeNet

        net = LeNet()
        logits = net(x)
    exe = static.Executor()
    out = exe.run(main, feed={"x": np.random.rand(2, 1, 28, 28).astype(np.float32)},
                  fetch_list=[logits])
    assert out[0].shape == (2, 10)


def test_static_minimize_trains():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [16, 8], "float32")
        label = static.data("label", [16], "int64")
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
        logits = net(x)
        loss = nn.functional.cross_entropy(logits, label)
        opt = paddle.optimizer.Adam(1e-2)
        opt.minimize(loss)
    exe = static.Executor()
    xv = np.random.rand(16, 8).astype(np.float32)
    yv = np.random.randint(0, 4, (16,))
    losses = []
    for _ in range(10):
        res = exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])
        losses.append(float(res[0]))
    assert losses[-1] < losses[0]


def test_static_dygraph_parity():
    """Same weights -> same loss in both modes (the CPU-parity pattern §4.2)."""
    paddle.disable_static()
    paddle.seed(5)
    net = nn.Sequential(nn.Linear(6, 12), nn.Tanh(), nn.Linear(12, 3))
    xv = np.random.rand(4, 6).astype(np.float32)
    dy_out = net(paddle.to_tensor(xv)).numpy()

    paddle.enable_static()
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 6], "float32")
        out = net(x)
    exe = static.Executor()
    st_out = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
    assert np.allclose(dy_out, st_out, rtol=1e-5)


def test_program_clone_for_test():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 4], "float32")
        y = paddle.sum(x * 2)
        opt = paddle.optimizer.SGD(0.1)
        opt.minimize(y)
    test_prog = main.clone(for_test=True)
    assert test_prog._minimize_spec is None
    assert main._minimize_spec is not None


def test_static_nn_helpers():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("img", [2, 3, 8, 8], "float32")
        c = static.nn.conv2d(x, 4, 3, padding=1, act="relu")
        flat = c.flatten(1)
        fc = static.nn.fc(flat, 10)
    exe = static.Executor()
    out = exe.run(main, feed={"img": np.random.rand(2, 3, 8, 8).astype(np.float32)},
                  fetch_list=[fc])
    assert out[0].shape == (2, 10)
