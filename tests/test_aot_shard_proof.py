"""North-star scale proof (VERDICT r4 missing #2): the 6.7B GPT hybrid
config AOT-compiles under dp x mp x ZeRO shardings on a virtual v5p mesh and
fits HBM — per-device buffer accounting from XLA's own memory_analysis.

Reference analog: the full-size GPT fixture of the reference's auto-parallel
tests (python/paddle/fluid/tests/unittests/auto_parallel_gpt_model.py:1).
"""
import os
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

import aot_shard_proof  # noqa: E402


@pytest.mark.slow
def test_gpt_6_7b_v5p8_shards_compiles_and_fits():
    # subprocess with its own 8-dev CPU mesh (run_one clears the axon path)
    res = aot_shard_proof.run_one("6.7b-v5p8-mp4-zero3-remat", timeout=1500)
    assert res["n_params"] > 6.5e9, res["n_params"]
    pd = res["per_device_bytes"]
    # mp=4 divides the param bytes: full fp32 copy would be ~27 GB
    assert pd["params"] < 8e9, pd
    # Adam m+v follow the param sharding
    assert 1.8 * pd["params"] < pd["opt_state"] < 2.2 * pd["params"], pd
    # XLA compiled it and reported a real temp arena
    assert pd["temp_xla"] > 0 and res["flops_per_device_step"] > 1e12
    # remat-adjusted activation estimate fits the v5p HBM budget
    est = res["remat_estimate"]
    assert est is not None and est["fits_hbm"], est


@pytest.mark.slow
def test_gpt_1_3b_v5p8_fits_without_remat_credit():
    res = aot_shard_proof.run_one("1.3b-v5p8-dp-zero1", timeout=900)
    assert res["fits_hbm"], res["per_device_gb"]  # conservative bound fits
    pd = res["per_device_bytes"]
    # ZeRO-1: params replicated, opt slots sharded over the 2-way axis
    assert pd["opt_state"] < 1.2 * pd["params"], pd
