"""Round-4 regression tests for VERDICT r3 confirmed bugs (weak #2-5, #8)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


# ---------------------------------------------------------------- gumbel_softmax
def test_gumbel_softmax_soft_is_distribution():
    x = paddle.to_tensor(np.random.randn(4, 10).astype(np.float32))
    y = F.gumbel_softmax(x, temperature=0.5, hard=False)
    out = y.numpy()
    assert np.allclose(out.sum(-1), 1.0, atol=1e-5)
    assert (out > 0).all() and not np.allclose(out.max(-1), 1.0)


def test_gumbel_softmax_hard_is_one_hot():
    x = paddle.to_tensor(np.random.randn(8, 16).astype(np.float32))
    y = F.gumbel_softmax(x, temperature=1.0, hard=True)
    out = y.numpy()
    # forward must be exactly one-hot (VERDICT r3 weak #2: was returning soft)
    assert set(np.unique(out)) <= {0.0, 1.0}
    assert np.array_equal(out.sum(-1), np.ones(8, np.float32))


def test_gumbel_softmax_hard_has_soft_gradient():
    x = paddle.to_tensor(np.random.randn(3, 5).astype(np.float32),
                         stop_gradient=False)
    y = F.gumbel_softmax(x, temperature=1.0, hard=True)
    y.sum().backward()
    g = x.grad.numpy()
    # straight-through: gradient flows (a pure one-hot has zero grad a.e.)
    assert np.abs(g).sum() > 0 or np.allclose(g, 0, atol=1e-12)
    # the ST gradient of sum(one_hot + y - sg(y)) == grad of sum(softmax) == 0
    # per row; more discriminating: weight rows differently
    x2 = paddle.to_tensor(np.random.randn(3, 5).astype(np.float32),
                          stop_gradient=False)
    w = paddle.to_tensor(np.arange(5, dtype=np.float32))
    y2 = F.gumbel_softmax(x2, temperature=1.0, hard=True)
    (y2 * w).sum().backward()
    assert np.abs(x2.grad.numpy()).sum() > 1e-6


# ---------------------------------------------------------------- resize
def test_resize_bilinear_matches_torch():
    torch = pytest.importorskip("torch")
    from paddle_tpu.vision.transforms import Resize

    img = np.random.rand(3, 17, 23).astype(np.float32)
    out = Resize((8, 12), interpolation="bilinear")(img)
    ref = torch.nn.functional.interpolate(
        torch.from_numpy(img)[None], size=(8, 12), mode="bilinear",
        align_corners=False,
    )[0].numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_resize_bicubic_close_to_torch():
    torch = pytest.importorskip("torch")
    from paddle_tpu.vision.transforms import Resize

    img = np.random.rand(3, 16, 16).astype(np.float32)
    out = Resize((32, 32), interpolation="bicubic")(img)
    ref = torch.nn.functional.interpolate(
        torch.from_numpy(img)[None], size=(32, 32), mode="bicubic",
        align_corners=False,
    )[0].numpy()
    # torch uses a=-0.75 too; interior should match tightly
    np.testing.assert_allclose(out[:, 4:-4, 4:-4], ref[:, 4:-4, 4:-4],
                               rtol=1e-3, atol=1e-3)


def test_resize_int_size_matches_shorter_edge():
    from paddle_tpu.vision.transforms import Resize

    img = np.random.rand(3, 20, 40).astype(np.float32)
    out = Resize(10)(img)
    assert out.shape == (3, 10, 20)
    out2 = Resize(10)(np.random.rand(3, 40, 20).astype(np.float32))
    assert out2.shape == (3, 20, 10)


def test_resize_nearest_and_uint8_roundtrip():
    from paddle_tpu.vision.transforms import Resize

    img = (np.random.rand(1, 8, 8) * 255).astype(np.uint8)
    out = Resize((4, 4), interpolation="nearest")(img)
    assert out.dtype == np.uint8 and out.shape == (1, 4, 4)
    outb = Resize((16, 16), interpolation="bilinear")(img)
    assert outb.dtype == np.uint8


def test_normalize_to_rgb_flips_channels():
    from paddle_tpu.vision.transforms import Normalize

    img = np.stack([np.full((2, 2), 1.0), np.full((2, 2), 2.0),
                    np.full((2, 2), 3.0)]).astype(np.float32)
    out = Normalize(mean=[0, 0, 0], std=[1, 1, 1], to_rgb=True)(img)
    assert out[0, 0, 0] == 3.0 and out[2, 0, 0] == 1.0


# ---------------------------------------------------------------- executor cache
def test_executor_cache_keyed_on_serial_not_id():
    from paddle_tpu import static

    paddle.enable_static()
    try:
        exe = static.Executor()
        results, serials = [], []
        for scale in (1.0, 3.0):
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data("x", [2, 2], "float32")
                y = x * scale
            out = exe.run(prog, feed={"x": np.ones((2, 2), np.float32)},
                          fetch_list=[y])[0]
            results.append(out[0, 0])
            serials.append(prog._exec_serial)
        # serials are process-unique (id() is not, after GC): distinct programs
        # can never alias a cache entry even if their ids collide
        assert serials[0] != serials[1]
        assert {k[0] for k in exe._cache} == set(serials)
        assert results[0] == 1.0 and results[1] == 3.0
        # re-running the same program hits the existing entry (serial is stable)
        assert len(exe._cache) == 2
    finally:
        paddle.disable_static()


# ---------------------------------------------------------------- pallas gate
def test_flash_gate_single_source():
    from paddle_tpu.kernels.flash_attention import _block, supports_shape

    # seq 640 passes %128 but NOT %block(640)=512 — must be gated out
    assert not supports_shape((1, 8, 640, 64), (1, 8, 640, 64))
    assert supports_shape((1, 8, 512, 64), (1, 8, 512, 64))
    assert supports_shape((1, 8, 256, 128), (1, 8, 256, 128))
    assert not supports_shape((1, 8, 512, 80), (1, 8, 512, 80))  # head_dim
    assert not supports_shape((1, 8, 64, 64), (1, 8, 64, 64))  # too short
    assert _block(640) == 512 and _block(256) == 256


# ---------------------------------------------------------------- ADVICE items
def test_flops_matches_reference_mac_convention():
    from paddle_tpu import nn

    net = nn.Linear(16, 8)
    # reference count_linear: total_mul(=16*8) * out elements w/o batch? —
    # convention: in*out MACs per row, no doubling
    assert paddle.flops(net, [2, 16]) == 2 * 16 * 8


def test_asp_prunes_conv_weights():
    from paddle_tpu import incubate as inc
    from paddle_tpu import nn
    from paddle_tpu.incubate import asp

    paddle.seed(3)
    m = nn.Sequential(nn.Conv2D(4, 8, 3), nn.Flatten(), nn.Linear(8 * 6 * 6, 4))
    asp.prune_model(m)
    conv_w = m.sublayers()[0].weight.numpy()
    # conv weight [8, 4, 3, 3] is pruned via the flattened 2-D path
    assert asp.calculate_density(conv_w) == pytest.approx(0.5, abs=0.02)
    asp.reset_excluded_layers()


def test_sparse_maxpool_keeps_negative_stored_values():
    from paddle_tpu import sparse as sp

    d = np.zeros((1, 2, 2, 2, 1), np.float32)
    d[0, 0, 0, 0, 0] = -3.0  # all stored values in the window are negative
    idx = np.stack(np.nonzero(d != 0))
    x = sp.sparse_coo_tensor(idx, d[d != 0], d.shape)
    y = sp.MaxPool3D(2)(x)
    vals = np.asarray(y.values().numpy())
    # max over stored support only: -3.0, NOT 0 from implicit zeros
    assert y.nnz() == 1 and vals[0] == -3.0


def test_lookahead_first_sync_pulls_toward_initial_weights():
    from paddle_tpu import nn
    from paddle_tpu.incubate import LookAhead

    paddle.seed(5)
    m = nn.Linear(4, 4)
    w0 = np.asarray(m.weight._value).copy()
    inner = paddle.optimizer.SGD(learning_rate=0.5, parameters=m.parameters())
    opt = LookAhead(inner, alpha=0.5, k=1)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    m(x).sum().backward()  # dL/dW = 2 (batch of ones) -> fast = w0 - 1.0
    opt.step()
    w_after = np.asarray(m.weight._value)
    # sync: w = slow0 + alpha*(fast - slow0) = w0 - 0.5. The old lazy init
    # made the first sync a no-op and returned fast = w0 - 1.0.
    np.testing.assert_allclose(w_after, w0 - 0.5, rtol=1e-5)


def test_ema_constant_decay_without_thres_steps():
    from paddle_tpu import static

    lin = paddle.nn.Linear(2, 2)
    ema = static.ExponentialMovingAverage(0.5)
    w0 = np.asarray(lin.weight._value).copy()
    ema.update(parameters=[lin.weight])  # shadow initialized to w0
    lin.weight._value = lin.weight._value + 2.0
    ema.update(parameters=[lin.weight])
    # shadow = 0.5*w0 + 0.5*(w0+2) = w0 + 1 — the old warm-up ramp gave
    # d=(1+1)/(10+1)=0.18 -> w0+1.63
    ema.apply(need_restore=False)
    np.testing.assert_allclose(np.asarray(lin.weight._value), w0 + 1.0,
                               rtol=1e-5)
    ema.restore()


def test_ema_thres_steps_ramp():
    from paddle_tpu import static

    lin = paddle.nn.Linear(2, 2)
    ema = static.ExponentialMovingAverage(0.999, thres_steps=0)
    w0 = np.asarray(lin.weight._value).copy()
    ema.update(parameters=[lin.weight])  # shadow initialized to w0
    lin.weight._value = lin.weight._value + 1.0
    ema.update(parameters=[lin.weight])
    # d = min(0.999, (0+1)/(0+10)) = 0.1 -> shadow = 0.1*w0 + 0.9*(w0+1)
    ema.apply(need_restore=False)
    np.testing.assert_allclose(np.asarray(lin.weight._value), w0 + 0.9,
                               rtol=1e-5)
    ema.restore()


def test_splash_auto_select_policy():
    from paddle_tpu.kernels.flash_attention import _want_splash
    from paddle_tpu.utils import flags

    try:
        assert _want_splash(True, 4096, 4096) is True  # long causal: splash
        assert _want_splash(True, 1024, 1024) is False  # measured even at 1k
        assert _want_splash(False, 8192, 8192) is False  # non-causal: dense
        assert _want_splash(True, 4096, 2048) is False  # cross-attn: dense
        flags.set_flags({"FLAGS_use_splash_attention": True})
        assert _want_splash(True, 512, 512) is True  # explicit force wins
        flags.set_flags({"FLAGS_use_splash_attention": False})
        assert _want_splash(True, 8192, 8192) is False
    finally:
        flags.set_flags({"FLAGS_use_splash_attention": "auto"})


def test_sdpa_composite_on_cpu_still_correct():
    from paddle_tpu.kernels.attention import sdpa, sdpa_reference
    import jax.numpy as jnp

    q = jnp.asarray(np.random.randn(1, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(np.random.randn(1, 2, 16, 8).astype(np.float32))
    v = jnp.asarray(np.random.randn(1, 2, 16, 8).astype(np.float32))
    np.testing.assert_allclose(np.asarray(sdpa(q, k, v, is_causal=True)),
                               np.asarray(sdpa_reference(q, k, v, is_causal=True)),
                               rtol=1e-5, atol=1e-5)
