"""OpTest batch 3: activation tail, cumulative/linalg ops, multi-output
grads (reference test strategy SURVEY §4.1)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.utils.op_test import OpTest


class TestEluOp(OpTest):
    def setUp(self):
        self.op = F.elu
        self.inputs = {"x": (np.random.rand(12) * 4 - 2).astype("float32")}
        self.attrs = {"alpha": 1.5}
        self.ref = lambda x, alpha: np.where(x > 0, x,
                                             alpha * (np.exp(x) - 1))

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestSoftplusOp(OpTest):
    def setUp(self):
        self.op = F.softplus
        self.inputs = {"x": (np.random.rand(10) * 6 - 3).astype("float32")}
        self.attrs = {}
        self.ref = lambda x: np.log1p(np.exp(x))

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestHardswishOp(OpTest):
    def setUp(self):
        self.op = F.hardswish
        self.inputs = {"x": (np.random.rand(20) * 10 - 5).astype("float32")}
        self.attrs = {}
        self.ref = lambda x: x * np.clip(x + 3, 0, 6) / 6

    def test_output(self):
        self.check_output()


class TestSeluOp(OpTest):
    def setUp(self):
        self.op = F.selu
        self.inputs = {"x": (np.random.rand(10) * 2 - 1).astype("float32")}
        self.attrs = {}
        scale, alpha = 1.0507009873554805, 1.6732632423543772
        self.ref = lambda x: scale * np.where(
            x > 0, x, alpha * (np.exp(x) - 1))

    def test_output(self):
        self.check_output()


class TestCumsumOp(OpTest):
    def setUp(self):
        self.op = paddle.cumsum
        self.inputs = {"x": np.random.rand(3, 5).astype("float32")}
        self.attrs = {"axis": 1}
        self.ref = lambda x, axis: x.cumsum(axis)

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestCumprodOp(OpTest):
    def setUp(self):
        self.op = paddle.cumprod
        self.inputs = {"x": (np.random.rand(4, 3) + 0.5).astype("float32")}
        self.attrs = {"dim": 0}
        self.ref = lambda x, dim: x.cumprod(dim)

    def test_output(self):
        self.check_output()


class TestPreluOp(OpTest):
    def setUp(self):
        self.op = F.prelu
        self.inputs = {
            "x": (np.random.rand(2, 3, 4) * 2 - 1).astype("float32"),
            "weight": np.full(3, 0.2, "float32"),
        }
        self.attrs = {}

        def ref(x, weight):
            w = weight.reshape(1, -1, 1)
            return np.where(x > 0, x, x * w)

        self.ref = ref

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x", "weight"])


class TestStackGrad(OpTest):
    def setUp(self):
        def op(a, b):
            return paddle.stack([a, b], axis=0)

        self.op = op
        self.inputs = {
            "a": np.random.rand(3, 4).astype("float32"),
            "b": np.random.rand(3, 4).astype("float32"),
        }
        self.attrs = {}
        self.ref = lambda a, b: np.stack([a, b])

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["a", "b"])


class TestSplitMultiOutputGrad(OpTest):
    def setUp(self):
        def op(x):
            a, b = paddle.split(x, 2, axis=1)
            return a, b

        self.op = op
        self.inputs = {"x": np.random.rand(3, 8).astype("float32")}
        self.attrs = {}
        self.ref = lambda x: (x[:, :4], x[:, 4:])

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestMatmulBatchedOp(OpTest):
    def setUp(self):
        self.op = paddle.matmul
        self.inputs = {
            "x": np.random.rand(2, 3, 4).astype("float32"),
            "y": np.random.rand(2, 4, 5).astype("float32"),
        }
        self.attrs = {}
        self.ref = lambda x, y: x @ y

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x", "y"])


class TestNormOp(OpTest):
    def setUp(self):
        self.op = paddle.linalg.norm
        self.inputs = {"x": np.random.rand(4, 5).astype("float32")}
        self.attrs = {"p": 2, "axis": 1}
        self.ref = lambda x, p, axis: np.linalg.norm(x, p, axis)

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"], atol=1e-3)


class TestLogCumsumExpStyleChain(OpTest):
    def setUp(self):
        def op(x):
            return paddle.log(paddle.cumsum(paddle.exp(x), axis=0))

        self.op = op
        self.inputs = {"x": np.random.rand(4, 3).astype("float32")}
        self.attrs = {}
        self.ref = lambda x: np.log(np.exp(x).cumsum(0))

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestPadOp(OpTest):
    def setUp(self):
        self.op = F.pad
        self.inputs = {"x": np.random.rand(2, 3).astype("float32")}
        self.attrs = {"pad": [1, 2], "value": 0.5}

        def ref(x, pad, value):
            return np.pad(x, ((0, 0), (1, 2)), constant_values=value)

        self.ref = ref

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])
