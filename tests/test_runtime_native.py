"""Native C++ runtime tests: blocking queue + TCPStore (built with g++ via ctypes)."""
import threading

import numpy as np
import pytest

from paddle_tpu.runtime import build_native


@pytest.fixture(scope="module")
def native_lib():
    lib = build_native()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def test_native_queue_roundtrip(native_lib):
    from paddle_tpu.runtime.blocking_queue import BlockingQueue

    q = BlockingQueue(capacity=4)
    assert q._native is not None, "native queue should be active after build"
    q.put({"x": 1})
    q.put([1, 2, 3])
    assert q.get() == {"x": 1}
    assert q.get() == [1, 2, 3]
    q.close()


def test_native_queue_blocking_and_threads(native_lib):
    from paddle_tpu.runtime.blocking_queue import BlockingQueue

    q = BlockingQueue(capacity=2)
    results = []

    def consumer():
        for _ in range(20):
            results.append(q.get())

    t = threading.Thread(target=consumer)
    t.start()
    for i in range(20):
        q.put(i)
    t.join(timeout=10)
    assert results == list(range(20))
    q.close()


def test_dataloader_uses_native_queue(native_lib):
    import paddle_tpu as paddle
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __getitem__(self, i):
            return np.full((2,), i, dtype=np.float32)

        def __len__(self):
            return 16

    dl = DataLoader(DS(), batch_size=4, num_workers=2)
    batches = list(dl)
    assert len(batches) == 4
    assert np.allclose(batches[0].numpy()[:, 0], [0, 1, 2, 3])


def test_tcp_store(native_lib):
    from paddle_tpu.runtime.tcp_store import TCPStore

    port = 29731
    master = TCPStore("127.0.0.1", port, is_master=True)
    worker = TCPStore("127.0.0.1", port, is_master=False)

    master.set("hello", b"world")
    assert worker.get("hello") == b"world"
    assert worker.add("counter", 3) == 3
    assert master.add("counter", 4) == 7
    worker.set("barrier/0", b"1")
    master.wait(["barrier/0"])  # returns because key exists


def test_tcp_store_wait_blocks_until_set(native_lib):
    from paddle_tpu.runtime.tcp_store import TCPStore

    port = 29741
    master = TCPStore("127.0.0.1", port, is_master=True)
    worker = TCPStore("127.0.0.1", port, is_master=False)

    def setter():
        import time

        time.sleep(0.2)
        master.set("late_key", b"v")

    t = threading.Thread(target=setter)
    t.start()
    worker.wait("late_key")
    assert worker.get("late_key") == b"v"
    t.join()
