"""paddle_tpu.serving — paged KV cache, scheduler, and engine invariants,
plus regression tests for the PR's satellite fixes (executor stale-runner
eviction across CompiledProgram/clone aliases; pdmodel dead-output name
reuse; fetch-of-fused-var diagnostics; axis_medium host mapping).

The e2e tests pin the serving contract from the ISSUE: with a fixed
max_batch/page pool the jitted prefill and decode steps each compile exactly
once across a run where requests join and leave (compile_counts increments
inside the traced python bodies, i.e. once per compilation), and every
request's greedy output is bit-identical to single-request generate().
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.serving import (EngineOverloaded, PagedCacheConfig,
                                PagedKVCache, PageAllocator, Request,
                                Scheduler, ServingConfig, ServingEngine)
from paddle_tpu.serving.kv_cache import NULL_PAGE
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM


# ------------------------------------------------------- page allocator
def test_allocator_alloc_free_invariants():
    a = PageAllocator(8)  # 7 usable; page 0 reserved
    assert a.num_usable == 7 and a.num_free == 7
    got = a.alloc(3)
    assert len(got) == 3 and NULL_PAGE not in got
    assert a.num_free == 4 and a.pages_in_use == 3
    # all-or-nothing: an unservable request changes nothing
    assert a.alloc(5) is None
    assert a.num_free == 4 and a.pages_in_use == 3
    a.free(got)
    assert a.num_free == 7 and a.pages_in_use == 0
    # double free and foreign pages raise
    with pytest.raises(ValueError):
        a.free(got)
    with pytest.raises(ValueError):
        a.free([NULL_PAGE])
    with pytest.raises(ValueError):
        a.alloc(-1)


def test_allocator_reserves_null_page():
    a = PageAllocator(4)
    pages = a.alloc(3)
    assert sorted(pages) == [1, 2, 3]  # page 0 never handed out
    assert a.alloc(1) is None
    with pytest.raises(ValueError):
        PageAllocator(1)  # nothing usable


def _cache(num_pages=9, page_size=4, max_batch=2, pages_per_seq=4):
    return PagedKVCache(PagedCacheConfig(
        num_layers=1, num_heads=1, head_dim=4, num_pages=num_pages,
        page_size=page_size, max_batch=max_batch,
        pages_per_seq=pages_per_seq))


def test_cache_admit_grow_release():
    c = _cache()
    assert c.admit(0, num_tokens=6)  # 2 pages of 4
    row = c.page_table[0]
    assert (row[:2] != NULL_PAGE).all() and (row[2:] == NULL_PAGE).all()
    # growing within the current page allocates nothing
    used = c.allocator.pages_in_use
    assert c.grow(0, 8) and c.allocator.pages_in_use == used
    assert c.grow(0, 9) and c.allocator.pages_in_use == used + 1
    with pytest.raises(ValueError):
        c.grow(0, c.cfg.max_tokens_per_seq + 1)
    with pytest.raises(ValueError):
        c.admit(0, 1)  # already admitted
    c.release(0)
    assert c.allocator.pages_in_use == 0
    assert (c.page_table[0] == NULL_PAGE).all()


def test_cache_admit_is_all_or_nothing():
    c = _cache(num_pages=4)  # 3 usable
    assert c.admit(0, 12)  # takes all 3
    assert not c.admit(1, 1)
    assert c.utilization() == 1.0
    c.release(0)
    assert c.admit(1, 1)
    assert 0 < c.utilization() < 1


def test_cache_swap_roundtrip_preserves_kv_bytes():
    import jax.numpy as jnp

    c = _cache(num_pages=9, page_size=4)
    assert c.admit(0, 6)  # 2 pages
    pages_before = list(c._slot_pages[0])
    rng = np.random.RandomState(7)
    k = np.asarray(c.pools[0]["k_pool"]).copy()
    v = np.asarray(c.pools[0]["v_pool"]).copy()
    k[pages_before] = rng.rand(2, 4, 1, 4)
    v[pages_before] = rng.rand(2, 4, 1, 4)
    c.pools = [{"k_pool": jnp.asarray(k), "v_pool": jnp.asarray(v)}]

    h = c.swap_out(0)
    assert h.n_pages == 2 and h.nbytes > 0
    assert c.allocator.pages_in_use == 0
    assert (c.page_table[0] == NULL_PAGE).all()
    with pytest.raises(ValueError):
        c.swap_out(0)  # nothing resident any more

    # land the restore on DIFFERENT page ids than it left from
    assert c.admit(1, 3)
    assert c.swap_in(0, h)
    pages_after = c._slot_pages[0]
    assert pages_after != pages_before
    np.testing.assert_array_equal(
        np.asarray(c.pools[0]["k_pool"])[pages_after], k[pages_before])
    np.testing.assert_array_equal(
        np.asarray(c.pools[0]["v_pool"])[pages_after], v[pages_before])
    with pytest.raises(ValueError):
        c.swap_in(0, h)  # slot occupied


def test_cache_swap_in_is_all_or_nothing():
    c = _cache(num_pages=4)  # 3 usable
    assert c.admit(0, 12)  # all 3 pages
    h = c.swap_out(0)
    assert c.admit(1, 5)  # 2 pages: only 1 left for the 3-page handle
    used = c.allocator.pages_in_use
    assert not c.swap_in(0, h)
    assert c.allocator.pages_in_use == used  # no partial grant
    c.release(1)
    assert c.swap_in(0, h)
    assert c.allocator.pages_in_use == 3


# ------------------------------------------------------------ scheduler
def _req(n, budget=4):
    return Request(prompt=np.arange(n, dtype=np.int32),
                   max_new_tokens=budget)


def test_scheduler_fifo_head_of_line_admission():
    c = _cache(num_pages=6, max_batch=3)  # 5 usable pages
    s = Scheduler(c, max_batch=3)
    big = _req(12)    # needs 3 pages
    small = _req(2)   # needs 1 page
    tiny = _req(1)
    s.add(big)
    s.add(small)
    s.add(tiny)
    admitted = s.admit()
    # FIFO into slots 0,1,2 in arrival order
    assert [r.rid for r in admitted] == [big.rid, small.rid, tiny.rid]
    assert [r.slot for r in admitted] == [0, 1, 2]
    assert s.queue_depth == 0


def test_scheduler_head_of_line_blocks_out_of_order_admission():
    c = _cache(num_pages=5, max_batch=2)  # 4 usable pages
    s = Scheduler(c, max_batch=2)
    first = _req(12)   # 3 pages
    second = _req(8)   # 2 pages — cannot fit alongside first
    third = _req(1)    # 1 page — WOULD fit, but must not jump the queue
    s.add(first)
    s.add(second)
    s.add(third)
    admitted = s.admit()
    assert [r.rid for r in admitted] == [first.rid]
    assert s.queue_depth == 2 and s.waiting[0] is second


def test_scheduler_rejects_never_fitting_request():
    c = _cache(num_pages=4, pages_per_seq=4)
    s = Scheduler(c, max_batch=2)
    with pytest.raises(ValueError):
        s.add(_req(12, budget=8))  # 20 tokens > 3 usable pages * 4


def test_scheduler_preempts_youngest_and_recomputes():
    c = _cache(num_pages=5, max_batch=2, pages_per_seq=4)  # 4 usable
    s = Scheduler(c, max_batch=2)
    old, young = _req(8, budget=6), _req(4, budget=6)
    s.add(old)
    s.add(young)
    assert len(s.admit()) == 2  # 2 + 1 pages
    young.generated.append(7)  # decoded one token already
    # old needs page 3 of 4 for token 9; pool is out -> young must yield
    old.generated.extend([1, 2, 3])
    preempted = s.ensure_decode_pages()
    assert [(r.rid, slot) for r, slot in preempted] == [(young.rid, 1)]
    assert young.state == "waiting" and young.generated == [] \
        and young.preemptions == 1
    assert s.waiting[0] is young  # requeued at the FRONT
    assert s.preemption_count == 1
    # the survivor got its page
    assert old.slot == 0 and c.allocator.pages_in_use == 3


def test_scheduler_no_spurious_preempt_at_page_boundary():
    # tokens_resident exactly fills the slot's pages: the pending decode
    # step writes INSIDE the last page (position tokens_resident - 1), so
    # no new page is needed — asking for tokens_resident + 1 used to make
    # a lone request preempt ITSELF against a full pool
    c = _cache(num_pages=2, page_size=4, max_batch=1)  # 1 usable page
    s = Scheduler(c, max_batch=1)
    req = _req(3, budget=1)
    s.add(req)
    assert len(s.admit()) == 1
    req.generated.append(5)  # tokens_resident = 4 = page_size
    assert s.ensure_decode_pages() == []
    assert s.preemption_count == 0 and req.slot == 0


def test_scheduler_victim_prefers_requests_that_decoded():
    c = _cache(num_pages=9, page_size=4, max_batch=3, pages_per_seq=4)
    s = Scheduler(c, max_batch=3)
    a, b, f = _req(4, budget=6), _req(4, budget=6), _req(4, budget=6)
    for r in (a, b, f):
        s.add(r)
    assert len(s.admit()) == 3
    a.generated, b.generated = [1, 2], [3, 4]
    f.generated, f.fresh = [5], True  # prefilled this step, no decode yet
    # youngest-first would sacrifice f's fresh prefill; the policy spares
    # it and preempts the youngest request that already decoded
    assert s.pick_victim() is b
    # when EVERY candidate is fresh, fall back to plain youngest-first
    a.fresh = b.fresh = True
    assert s.pick_victim() is f


def test_scheduler_bounded_queue_reject_and_shed():
    c = _cache(num_pages=9, max_batch=1)
    s = Scheduler(c, max_batch=1, max_waiting=2, shed_policy="reject")
    r1, r2, r3 = _req(2), _req(2), _req(2)
    assert s.add(r1) is None and s.add(r2) is None
    with pytest.raises(EngineOverloaded):
        s.add(r3)
    assert list(s.waiting) == [r1, r2]

    s2 = Scheduler(c, max_batch=1, max_waiting=2, shed_policy="shed-oldest")
    q1, q2, q3 = _req(2), _req(2), _req(2)
    s2.add(q1)
    s2.add(q2)
    shed = s2.add(q3)
    assert shed is q1 and q1.state == "shed"
    assert list(s2.waiting) == [q2, q3]  # FIFO intact for survivors

    with pytest.raises(ValueError):
        Scheduler(c, max_batch=1, shed_policy="drop-newest")
    with pytest.raises(ValueError):
        Scheduler(c, max_batch=1, preemption_mode="migrate")


# ------------------------------------------------------------ engine e2e
def _toy_model(seed=11):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=48, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _reference(model, prompt, budget):
    out = model.generate(Tensor(np.asarray(prompt)[None]),
                         max_new_tokens=budget)
    return np.asarray(out._value)[0]


def test_engine_e2e_churn_parity_and_single_compile():
    model = _toy_model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 97, (n,)).astype(np.int32)
               for n in (3, 7, 5, 2, 6, 4)]
    budgets = [5, 8, 3, 9, 4, 6]

    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=20, page_size=4, max_prompt_len=8))

    snaps = []
    rids = [engine.add_request(p, b)
            for p, b in zip(prompts[:4], budgets[:4])]
    for _ in range(5):  # requests finish and new ones join mid-stream
        engine.step()
        snaps.append(engine.metrics.snapshot())
    rids += [engine.add_request(p, b)
             for p, b in zip(prompts[4:], budgets[4:])]
    while not engine.scheduler.all_done:
        engine.step()
        snaps.append(engine.metrics.snapshot())
    outputs = dict(engine._finished)

    # per-request parity with the single-batch generate() loop
    for i, rid in enumerate(rids):
        ref = _reference(model, prompts[i], budgets[i])
        np.testing.assert_array_equal(ref, outputs[rid],
                                      err_msg=f"request {i} diverged")
    # ONE compilation each for prefill and decode across all the churn
    assert engine.compile_counts == {"prefill": 1, "decode": 1}

    # observability: metrics were live during the run
    totals = [s.get("serving_tokens_total", 0) for s in snaps]
    assert totals == sorted(totals), "token counter must be monotonic"
    assert totals[-1] == sum(budgets)
    assert any(s.get("serving_queue_depth", 0) > 0 for s in snaps), \
        "with max_batch=2 and 4 queued requests the queue must back up"
    assert any(s.get("serving_page_utilization", 0) > 0 for s in snaps)
    assert any(s.get("serving_tokens_per_sec", 0) > 0 for s in snaps)
    assert snaps[-1]["serving_decode_steps"] > 0
    # pool fully drains when every request retires
    assert engine.cache.allocator.pages_in_use == 0


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 budget; page-pressure preemption stays pinned
# tier-1 by the faults suite's pool_exhausted scenarios and test_serving_tp's preemption-parity pair
def test_engine_preemption_under_page_pressure():
    model = _toy_model(seed=13)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 97, (n,)).astype(np.int32)
               for n in (6, 5, 4)]
    budgets = [10, 9, 8]
    # pool sized so concurrent decodes run out of pages mid-stream
    engine = ServingEngine(model, ServingConfig(
        max_batch=3, num_pages=8, page_size=4, max_prompt_len=8))
    rids = [engine.add_request(p, b) for p, b in zip(prompts, budgets)]
    outputs = engine.run()
    assert engine.scheduler.preemption_count > 0, \
        "pool of 7 usable pages must preempt (needs 11 pages peak)"
    assert engine.metrics.snapshot()["serving_preemptions_total"] > 0
    for i, rid in enumerate(rids):  # greedy recompute is deterministic
        np.testing.assert_array_equal(
            _reference(model, prompts[i], budgets[i]), outputs[rid])


def test_engine_run_returns_only_this_calls_completions():
    model = _toy_model(seed=17)
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=24, page_size=4, max_prompt_len=8))
    p = np.arange(1, 5, dtype=np.int32)
    r1 = engine.add_request(p, 3)
    out1 = engine.run()
    assert set(out1) == {r1}
    r2 = engine.add_request(p + 1, 3)
    out2 = engine.run()
    assert set(out2) == {r2}, "run() must not replay earlier completions"
    # finished requests leave the per-request bookkeeping immediately…
    assert engine._requests == {}
    # …and pop_finished drains the retained outputs (server memory bound)
    drained = engine.pop_finished()
    assert set(drained) == {r1, r2}
    assert engine.pop_finished() == {}
    np.testing.assert_array_equal(drained[r1], out1[r1])


def test_engine_rejects_oversized_requests():
    model = _toy_model()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=16, page_size=4, max_prompt_len=8))
    with pytest.raises(ValueError):
        engine.add_request(np.zeros(9, np.int32), 4)  # prompt > bucket
    with pytest.raises(ValueError):
        engine.add_request(np.zeros(4, np.int32), 0)  # no budget
    with pytest.raises(ValueError):
        engine.add_request(np.zeros((2, 2), np.int32), 4)  # not 1-D
    with pytest.raises(ValueError):
        # empty prompt would sample from a padding position's logits
        engine.add_request(np.zeros(0, np.int32), 4)


def test_sampling_recompute_preemption_reproduces_tokens():
    # PRNG keys derive from (engine seed, rid, token index) — a pure
    # function of request identity — so a RECOMPUTE-preempted *sampling*
    # request replays its original tokens instead of silently resampling
    from paddle_tpu.serving import scheduler as sched_mod

    model = _toy_model(seed=23)
    prompts = [np.random.RandomState(i).randint(0, 97, (n,)).astype(np.int32)
               for i, n in enumerate((6, 5, 4))]
    budgets = [10, 9, 8]

    def drive(num_pages):
        # align rids across the two engines: the key streams are rid-keyed
        sched_mod._rid_counter = itertools.count(9000)
        engine = ServingEngine(model, ServingConfig(
            max_batch=3, num_pages=num_pages, page_size=4, max_prompt_len=8,
            do_sample=True, temperature=0.8, top_k=20, seed=5))
        rids = [engine.add_request(p, b) for p, b in zip(prompts, budgets)]
        return engine, rids, engine.run()

    saved_counter = sched_mod._rid_counter
    try:
        calm, rids_a, outs_a = drive(num_pages=24)  # pool ample: no preempt
        tight, rids_b, outs_b = drive(num_pages=8)  # pool dry: preempt+replay
    finally:
        sched_mod._rid_counter = saved_counter
    assert rids_a == rids_b
    assert calm.scheduler.preemption_count == 0
    assert tight.scheduler.preemption_count > 0
    for ra, rb in zip(rids_a, rids_b):
        np.testing.assert_array_equal(
            outs_a[ra], outs_b[rb],
            err_msg="recomputed sampling request resampled different tokens")


def test_prefix_counters_pre_seeded_in_registry():
    # dashboards key on presence: a snapshot taken before the first hit/
    # miss/COW must already carry the prefix-cache counters as zeros
    model = _toy_model(seed=31)
    engine = ServingEngine(model, ServingConfig(
        max_batch=1, num_pages=8, page_size=4, max_prompt_len=8))
    snap = engine.metrics.snapshot()
    for k in ("prefix_hits", "prefix_misses", "prefix_tokens_saved",
              "prefix_shared_pages", "prefix_cached_pages",
              "prefix_cow_copies", "prefix_evictions"):
        assert snap.get("serving_" + k) == 0, k


def test_stuck_engine_report_is_actionable():
    model = _toy_model(seed=19)
    engine = ServingEngine(model, ServingConfig(
        max_batch=1, num_pages=16, page_size=4, max_prompt_len=8))
    engine.add_request(np.arange(1, 5, dtype=np.int32), 8)
    engine.add_request(np.arange(2, 6, dtype=np.int32), 8)
    with pytest.raises(RuntimeError) as ei:
        engine.run(max_steps=0)
    msg = str(ei.value)
    # the bare "...exceeded N steps" of PR 1 named nothing — a stuck-engine
    # report must say what is queued, what is active, and who holds pages
    for needle in ("queue_depth=", "active rids", "pages_in_use="):
        assert needle in msg, msg


# ----------------------------------------- satellite: executor eviction
def _build_prog(static):
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4])
        w = paddle.to_tensor(np.random.rand(4, 4).astype("float32"))
        y = paddle.matmul(x, w)
    return prog, y


def test_compiled_program_shares_underlying_serial():
    from paddle_tpu import static
    from paddle_tpu.static.executor import CompiledProgram

    static.enable_static()
    try:
        prog, y = _build_prog(static)
        cp = CompiledProgram(prog)
        exe = static.Executor()
        xv = np.random.rand(2, 4).astype("float32")
        exe.run(prog, feed={"x": xv}, fetch_list=[y])
        exe.run(cp, feed={"x": xv}, fetch_list=[y])
        # the serial is stamped on the underlying Program, never the wrapper
        assert "_exec_serial" not in cp.__dict__
        serials = {k[0] for k in exe._cache}
        assert serials == {prog._exec_serial}
    finally:
        static.disable_static()


def test_clone_alias_runners_co_evict_on_pass_bump():
    from paddle_tpu import static
    from paddle_tpu.static.executor import CompiledProgram
    from paddle_tpu.static.passes import new_pass

    static.enable_static()
    try:
        prog, y = _build_prog(static)
        clone = prog.clone()
        cp = CompiledProgram(prog)
        exe = static.Executor()
        xv = np.random.rand(2, 4).astype("float32")
        # distinct feed keys would collide; same key set -> same cache key
        # except for the serial, so give the clone a different fetch shape
        exe.run(prog, feed={"x": xv}, fetch_list=[y])
        exe.run(clone, feed={"x": xv}, fetch_list=[y])
        exe.run(cp, feed={"x": xv}, fetch_list=[y])
        v0 = getattr(prog.global_block, "_version", 0)
        assert {k[1] for k in exe._cache} == {v0}
        new_pass("fuse_gemm_epilogue").apply(prog)  # bumps the shared block
        exe.run(prog, feed={"x": xv}, fetch_list=[y])
        # the clone's (and wrapper's) stale pre-pass runners co-evicted:
        # nothing in the cache references the old block version
        assert {k[1] for k in exe._cache} == {v0 + 1}
    finally:
        static.disable_static()


def test_dead_program_serial_pruned_from_block_groups():
    import gc

    from paddle_tpu import static

    static.enable_static()
    try:
        exe = static.Executor()
        prog, y = _build_prog(static)
        xv = np.random.rand(2, 4).astype("float32")
        exe.run(prog, feed={"x": xv}, fetch_list=[y])
        serial = prog._exec_serial
        assert any(serial in g for g in exe._block_serials.values())
        # the cached runner closes over the program tape, so the program
        # can only die once its entries are evicted (e.g. a version bump)
        exe._cache.clear()
        del prog, y
        gc.collect()
        # the finalizer must then drop the serial from its co-eviction
        # group — otherwise every discarded Program leaks a _block_serials
        # entry for the life of the executor
        assert not any(serial in g for g in exe._block_serials.values())
    finally:
        static.disable_static()


# --------------------------------------- satellite: fetch of a fused var
def test_fetch_of_fused_away_var_names_the_pass():
    from paddle_tpu import static
    from paddle_tpu.static.passes import new_pass

    static.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4])
            w = paddle.to_tensor(np.random.rand(4, 8).astype("float32"))
            b = paddle.to_tensor(np.random.rand(8).astype("float32"))
            y = paddle.matmul(x, w)  # interior: consumed by the fusion
            out = y + b
        ctx = new_pass("fuse_gemm_epilogue").apply(prog)
        assert ctx.attrs["fused_gemm_epilogue"] >= 1
        exe = static.Executor()
        xv = np.random.rand(2, 4).astype("float32")
        (ov,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
        assert ov.shape == (2, 8)
        with pytest.raises(ValueError, match="fuse_gemm_epilogue"):
            exe.run(prog, feed={"x": xv}, fetch_list=[y])
    finally:
        static.disable_static()


# ------------------------------------- satellite: pdmodel BN name reuse
def _bn_op(x="h", y="y"):
    # inference-style batch_norm: MeanOut/VarianceOut REUSE the input
    # names, as real Paddle exports do
    return {"type": "batch_norm",
            "inputs": {"X": [x], "Scale": ["bn_s"], "Bias": ["bn_b"],
                       "Mean": ["bn_m"], "Variance": ["bn_v"]},
            "outputs": {"Y": [y], "MeanOut": ["bn_m"],
                        "VarianceOut": ["bn_v"], "SavedMean": ["sm"],
                        "SavedVariance": ["sv"]},
            "attrs": {"epsilon": 1e-5}}


def test_pdmodel_passes_ignore_dead_output_name_reuse():
    from paddle_tpu.inference.pdmodel import apply_inference_passes

    ops = [
        {"type": "relu", "inputs": {"X": ["x"]}, "outputs": {"Out": ["h"]},
         "attrs": {}},
        _bn_op(),
        {"type": "dropout", "inputs": {"X": ["y"]},
         "outputs": {"Out": ["o"]},
         "attrs": {"dropout_implementation": "upscale_in_train",
                   "dropout_prob": 0.5}},
    ]
    live = {"x", "bn_s", "bn_b", "bn_m", "bn_v"}
    new_ops, fetch, stats = apply_inference_passes(ops, ["o"],
                                                   live_names=live)
    # the dead MeanOut/VarianceOut rewrites must NOT disable the passes
    assert "skipped" not in stats
    assert stats["delete_dropout"] == 1
    assert fetch == ["y"]
    assert [op["type"] for op in new_ops] == ["relu", "batch_norm"]


def test_pdmodel_passes_fold_conv_bn_despite_dead_reuse():
    from paddle_tpu.inference.pdmodel import apply_inference_passes

    ops = [
        {"type": "conv2d",
         "inputs": {"Input": ["x"], "Filter": ["w"]},
         "outputs": {"Output": ["c"]}, "attrs": {}},
        _bn_op(x="c"),
    ]
    params = {"w": np.random.rand(3, 2, 1, 1).astype(np.float32),
              "bn_s": np.ones(3, np.float32),
              "bn_b": np.zeros(3, np.float32),
              "bn_m": np.zeros(3, np.float32),
              "bn_v": np.ones(3, np.float32)}
    live = {"x"} | set(params)
    new_ops, _, stats = apply_inference_passes(ops, ["y"], live_names=live,
                                               params=params)
    assert stats.get("conv_bn_fuse") == 1, \
        "the headline conv+BN fold must fire on a real-export-shaped BN"
    assert [op["type"] for op in new_ops] == ["conv2d", "elementwise_add"]


def test_pdmodel_passes_still_bail_on_live_reuse():
    from paddle_tpu.inference.pdmodel import apply_inference_passes

    # the reused name IS read downstream -> folding is unsound -> bail
    ops = [
        {"type": "assign", "inputs": {"X": ["x"]},
         "outputs": {"Out": ["y"]}, "attrs": {}},
        {"type": "relu", "inputs": {"X": ["x"]},
         "outputs": {"Out": ["x"]}, "attrs": {}},
        {"type": "elementwise_add", "inputs": {"X": ["y"], "Y": ["x"]},
         "outputs": {"Out": ["out"]}, "attrs": {}},
    ]
    same, _, stats = apply_inference_passes(ops, ["out"], live_names={"x"})
    assert same is ops and stats.get("skipped") == "in-place var-name reuse"
    # a fetched rewrite is live even with no downstream op
    ops2 = [{"type": "relu", "inputs": {"X": ["z"]},
             "outputs": {"Out": ["x"]}, "attrs": {}}]
    _, _, stats2 = apply_inference_passes(ops2, ["x"],
                                          live_names={"x", "z"})
    assert stats2.get("skipped") == "in-place var-name reuse"


def test_pdmodel_passes_bail_on_pre_overwrite_copy():
    from paddle_tpu.inference.pdmodel import apply_inference_passes

    # assign copies x BEFORE the in-place overwrite; alias folding would
    # rewrite y's reader to read post-overwrite x — must bail even though
    # no op reads x after the overwrite
    ops = [
        {"type": "assign", "inputs": {"X": ["x"]},
         "outputs": {"Out": ["y"]}, "attrs": {}},
        {"type": "relu", "inputs": {"X": ["x"]},
         "outputs": {"Out": ["x"]}, "attrs": {}},
        {"type": "sigmoid", "inputs": {"X": ["y"]},
         "outputs": {"Out": ["out"]}, "attrs": {}},
    ]
    same, _, stats = apply_inference_passes(ops, ["out"], live_names={"x"})
    assert same is ops and stats.get("skipped") == "in-place var-name reuse"


# --------------------------------------- satellite: axis_medium mapping
def test_axis_medium_checks_actual_hosts_not_span():
    from paddle_tpu.distributed.auto_parallel.cluster import Cluster

    c = Cluster(accelerator_type="v5p", n_hosts=2, chips_per_host=6)
    # span 4 <= 6, but group {4, 6} straddles hosts 0 and 1
    assert c.axis_medium(2, stride=2) == "dcn"
    # contiguous tilings that align with hosts stay ICI
    assert c.axis_medium(6, stride=1) == "ici"
    assert c.axis_medium(2, stride=6) == "dcn"
    # explicit groups win over the synthesized tiling
    assert c.axis_medium(2, stride=2, groups=[[0, 2], [1, 3]]) == "ici"
    assert c.axis_medium(2, stride=2, groups=[[4, 6]]) == "dcn"
    # an empty enumeration (span overruns the cluster) fails CLOSED
    assert c.axis_medium(4, stride=4) == "dcn"


def test_mapper_placement_uses_actual_groups():
    from paddle_tpu.distributed.auto_parallel.cluster import Cluster
    from paddle_tpu.distributed.auto_parallel.mapper import map_mesh

    c = Cluster(accelerator_type="v5p", n_hosts=2, chips_per_host=6)
    ids, placement = map_mesh(c, {"dp": 2, "mp": 6},
                              comm_bytes={"mp": 2.0, "dp": 1.0})
    # mp (innermost, stride 1, size 6) tiles each host exactly -> ici;
    # dp pairs rank r with r+6 across hosts -> dcn
    assert placement["mp"] == "ici"
    assert placement["dp"] == "dcn"
