"""Meta-optimizer stack + strategy compiler tests (reference:
meta_optimizers/{gradient_merge,localsgd,dgc}_optimizer.py +
base/strategy_compiler.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.fleet.meta_optimizers import (
    DGCMomentumOptimizer,
    GradientMergeOptimizer,
    StrategyCompiler,
    create_meta_optimizer,
)
from paddle_tpu.distributed.fleet.distributed_strategy import DistributedStrategy


def _model_and_data(seed=11):
    paddle.seed(seed)
    m = nn.Linear(8, 4)
    x = np.random.RandomState(0).rand(16, 8).astype(np.float32)
    y = np.random.RandomState(0).randint(0, 4, (16,))
    return m, x, y


def _train_steps(model, opt, x, y, n):
    losses = []
    for _ in range(n):
        loss = nn.functional.cross_entropy(model(paddle.to_tensor(x)),
                                           paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def test_gradient_merge_matches_plain_on_constant_batch():
    m1, x, y = _model_and_data()
    plain = paddle.optimizer.SGD(0.2, parameters=m1.parameters())
    ref = _train_steps(m1, plain, x, y, 2)

    m2, _, _ = _model_and_data()
    gm = GradientMergeOptimizer(
        paddle.optimizer.SGD(0.2, parameters=m2.parameters()), k_steps=2)
    merged = _train_steps(m2, gm, x, y, 4)
    # identical grads within a window: steps 0,1 see init params; step 2 sees
    # the post-update params = plain step 1
    assert merged[0] == pytest.approx(merged[1], rel=1e-6)
    assert merged[2] == pytest.approx(ref[1], rel=1e-5)


def test_gradient_merge_minimize_path_honors_merging():
    """minimize() must route through the wrapper's step(), not the inner
    optimizer's (regression: __getattr__ used to delegate minimize)."""
    m, x, y = _model_and_data()
    gm = GradientMergeOptimizer(
        paddle.optimizer.SGD(0.2, parameters=m.parameters()), k_steps=2)
    w0 = m.weight.numpy().copy()
    loss = nn.functional.cross_entropy(m(paddle.to_tensor(x)),
                                       paddle.to_tensor(y))
    gm.minimize(loss)
    # first micro-step accumulates only: params unchanged
    np.testing.assert_array_equal(m.weight.numpy(), w0)
    loss = nn.functional.cross_entropy(m(paddle.to_tensor(x)),
                                       paddle.to_tensor(y))
    gm.minimize(loss)
    assert not np.allclose(m.weight.numpy(), w0)  # k-th step applies


def test_dgc_sparsifies_but_still_learns():
    m, x, y = _model_and_data()
    dgc = DGCMomentumOptimizer(
        paddle.optimizer.Momentum(0.1, parameters=m.parameters()),
        sparsity=0.75)
    losses = _train_steps(m, dgc, x, y, 25)
    assert losses[-1] < losses[0]


def test_strategy_compiler_conflicts_and_wiring():
    s = DistributedStrategy()
    s.lamb = True
    s.lars = True  # loser of the (lamb, lars) exclusion
    s.gradient_merge = True
    s.gradient_merge_configs = {"k_steps": 2, "avg": True}
    with pytest.warns(UserWarning, match="lars conflicts"):
        flags, applied, disabled = StrategyCompiler().compile(s)
    assert disabled == ["lars"] and "lamb" in applied

    m, _, _ = _model_and_data()
    base = paddle.optimizer.SGD(0.1, parameters=m.parameters())
    with pytest.warns(UserWarning):
        opt = create_meta_optimizer(base, s)
    assert isinstance(opt, GradientMergeOptimizer)
    from paddle_tpu.optimizer.optimizers import Lamb

    assert isinstance(opt.inner, Lamb)
    assert opt._meta_report == {"applied": ["lamb", "gradient_merge"],
                                "disabled": ["lars"]}


def test_fleet_distributed_optimizer_applies_meta_stack_once():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.fleet_base import fleet as f

    f._is_initialized = False
    f._hcg = None
    s = fleet.DistributedStrategy()
    s.gradient_merge = True
    s.gradient_merge_configs = {"k_steps": 2, "avg": True}
    try:
        f.init(is_collective=True, strategy=s)
        m, _, _ = _model_and_data()
        dopt = f.distributed_optimizer(
            paddle.optimizer.SGD(0.1, parameters=m.parameters()))
        inner = getattr(dopt, "_inner_opt", dopt)
        assert isinstance(inner, GradientMergeOptimizer)
        # exactly ONE layer of wrapping (double-apply regression check)
        assert not isinstance(inner.inner, GradientMergeOptimizer)
        assert inner._meta_report["applied"] == ["gradient_merge"]
    finally:
        # restore the singleton so later tests don't inherit this strategy
        f._is_initialized = False
        f._hcg = None
        f._user_defined_strategy = DistributedStrategy()
