"""DataLoader / Dataset / metric / save-load tests."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import (BatchSampler, DataLoader, Dataset, DistributedBatchSampler,
                           TensorDataset)


class RangeDS(Dataset):
    def __init__(self, n=20):
        self.n = n

    def __getitem__(self, i):
        return np.full((3,), i, dtype=np.float32), np.asarray(i, dtype=np.int64)

    def __len__(self):
        return self.n


def test_dataloader_basic():
    dl = DataLoader(RangeDS(20), batch_size=4)
    batches = list(dl)
    assert len(batches) == 5
    x, y = batches[0]
    assert x.shape == [4, 3]
    assert y.shape == [4]
    assert np.allclose(x.numpy()[:, 0], y.numpy())


def test_dataloader_shuffle_drop_last():
    dl = DataLoader(RangeDS(10), batch_size=3, shuffle=True, drop_last=True)
    batches = list(dl)
    assert len(batches) == 3
    all_idx = np.concatenate([b[1].numpy() for b in batches])
    assert len(set(all_idx.tolist())) == 9


def test_dataloader_workers():
    dl = DataLoader(RangeDS(32), batch_size=4, num_workers=2)
    batches = list(dl)
    assert len(batches) == 8
    # order preserved despite threading
    assert batches[0][1].numpy()[0] == 0
    assert batches[7][1].numpy()[-1] == 31


def test_tensor_dataset_and_random_split():
    from paddle_tpu.io import random_split

    x = paddle.randn([10, 4])
    y = paddle.arange(10)
    ds = TensorDataset([x, y])
    assert len(ds) == 10
    a, b = random_split(ds, [7, 3])
    assert len(a) == 7 and len(b) == 3


def test_distributed_batch_sampler():
    ds = RangeDS(20)
    s0 = DistributedBatchSampler(ds, batch_size=2, num_replicas=4, rank=0)
    s1 = DistributedBatchSampler(ds, batch_size=2, num_replicas=4, rank=1)
    i0 = [i for b in s0 for i in b]
    i1 = [i for b in s1 for i in b]
    assert len(i0) == 5 and len(i1) == 5
    assert not set(i0) & set(i1)


def test_accuracy_metric():
    from paddle_tpu.metric import Accuracy

    m = Accuracy()
    pred = paddle.to_tensor([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = paddle.to_tensor([1, 0, 0])
    correct = m.compute(pred, label)
    m.update(correct)
    assert m.accumulate() == pytest.approx(2 / 3)


def test_precision_recall_auc():
    from paddle_tpu.metric import Auc, Precision, Recall

    preds = np.asarray([0.9, 0.8, 0.2, 0.1])
    labels = np.asarray([1, 0, 1, 0])
    p = Precision()
    p.update(preds, labels)
    assert p.accumulate() == pytest.approx(0.5)
    r = Recall()
    r.update(preds, labels)
    assert r.accumulate() == pytest.approx(0.5)
    a = Auc()
    a.update(np.asarray([0.9, 0.7, 0.3, 0.1]), np.asarray([1, 1, 0, 0]))
    assert a.accumulate() == pytest.approx(1.0, abs=0.01)


def test_save_load_roundtrip(tmp_path):
    from paddle_tpu import nn

    m = nn.Linear(4, 3)
    path = str(tmp_path / "model.pdparams")
    paddle.save(m.state_dict(), path)
    sd = paddle.load(path)
    assert np.allclose(sd["weight"].numpy(), m.weight.numpy())
    m2 = nn.Linear(4, 3)
    m2.set_state_dict(sd)
    assert np.allclose(m2.weight.numpy(), m.weight.numpy())


def test_save_load_nested(tmp_path):
    obj = {"a": paddle.to_tensor([1.0, 2.0]), "b": [paddle.ones([2]), {"c": 3}],
           "scalar": 5}
    path = str(tmp_path / "obj.pd")
    paddle.save(obj, path)
    back = paddle.load(path)
    assert np.allclose(back["a"].numpy(), [1, 2])
    assert back["b"][1]["c"] == 3
    assert back["scalar"] == 5


def test_bfloat16_save_load(tmp_path):
    t = paddle.to_tensor([1.5, 2.5], dtype="bfloat16")
    # state-dict path: bf16 upcasts to PORTABLE fp32 (real Paddle has no
    # ml_dtypes; set_state_dict casts back to the param dtype on load)
    path = str(tmp_path / "bf16.pdparams")
    paddle.save({"t": t}, path)
    back = paddle.load(path)
    assert back["t"].dtype == "float32"
    np.testing.assert_array_equal(back["t"].numpy(), [1.5, 2.5])
    # nested (private) path: exact dtype round-trip
    path2 = str(tmp_path / "bf16.pd")
    paddle.save([t], path2)
    back2 = paddle.load(path2)
    assert back2[0].dtype == "bfloat16"
