"""Collective tests on the virtual 8-device CPU mesh (reference pattern:
test_collective_base.py — per-rank values in, numpy equality out)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


@pytest.fixture(scope="module", autouse=True)
def _env():
    dist.init_parallel_env()
    yield


def test_world_group():
    g = dist.get_group()
    assert g.nranks == 8


def test_all_reduce_sum():
    vals = [np.full((3,), float(i)) for i in range(8)]
    t = dist.collective.scatter_ranks(vals)
    dist.all_reduce(t)
    out = np.asarray(t._value)
    assert out.shape == (8, 3)
    for i in range(8):
        assert np.allclose(out[i], 28.0)  # sum 0..7


def test_all_reduce_max():
    vals = [np.full((2,), float(i)) for i in range(8)]
    t = dist.collective.scatter_ranks(vals)
    dist.all_reduce(t, op=dist.ReduceOp.MAX)
    assert np.allclose(np.asarray(t._value), 7.0)


def test_all_gather():
    vals = [np.full((2,), float(i)) for i in range(8)]
    t = dist.collective.scatter_ranks(vals)
    out = []
    dist.all_gather(out, t)
    assert len(out) == 8
    for i in range(8):
        assert np.allclose(out[i].numpy(), float(i))


def test_broadcast():
    vals = [np.full((2,), float(i)) for i in range(8)]
    t = dist.collective.scatter_ranks(vals)
    dist.broadcast(t, src=3)
    assert np.allclose(np.asarray(t._value), 3.0)


def test_reduce_scatter():
    # each rank contributes rows 0..7; rank i should end with sum of row i
    vals = [np.arange(8, dtype=np.float32).reshape(8, 1) + i for i in range(8)]
    t = dist.collective.scatter_ranks(vals)
    out_t = paddle.zeros([8, 1, 1])
    dist.reduce_scatter(out_t, t)
    out = np.asarray(out_t._value)
    # row r = sum_i (r + i) = 8r + 28
    for r in range(8):
        assert np.allclose(out[r], 8 * r + 28)


def test_in_graph_ops_shard_map():
    """The c_* op lowerings inside shard_map (static-graph comm op analog)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed import ops as cops

    mesh = dist.global_mesh()  # 1-D 'dp' over 8 devices
    x = jnp.arange(8.0)

    def f(xl):
        s = cops.c_allreduce_sum(jnp.sum(xl), "dp")
        g = cops.c_allgather(xl, "dp")
        idx = cops.axis_index("dp")
        return s * jnp.ones_like(xl), g[None] * 1.0, idx[None].astype(jnp.float32)

    fm = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("dp"),
                               out_specs=(P("dp"), P("dp"), P("dp"))))
    s, g, idx = fm(x)
    assert np.allclose(np.asarray(s), 28.0)
    assert np.allclose(np.asarray(g)[0], np.arange(8.0))
    assert np.allclose(np.asarray(idx), np.arange(8.0))


def test_ppermute_ring():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed import ops as cops

    mesh = dist.global_mesh()
    x = jnp.arange(8.0)
    f = jax.jit(jax.shard_map(lambda v: cops.send_next(v, "dp"), mesh=mesh,
                              in_specs=P("dp"), out_specs=P("dp")))
    out = np.asarray(f(x))
    assert np.allclose(out, np.roll(np.arange(8.0), 1))


def test_vocab_parallel_ce():
    """c_softmax_with_cross_entropy matches the dense reference."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed import ops as cops

    devs = np.asarray(jax.devices()[:4])
    mesh = Mesh(devs, ("mp",))
    b, v = 6, 32
    logits = np.random.randn(b, v).astype(np.float32)
    labels = np.random.randint(0, v, (b,))

    f = jax.jit(jax.shard_map(
        lambda lg, lb: cops.c_softmax_with_cross_entropy(lg, lb, "mp"),
        mesh=mesh, in_specs=(P(None, "mp"), P()), out_specs=P(),
    ))
    loss = np.asarray(f(jnp.asarray(logits), jnp.asarray(labels)))
    # dense reference
    ref = -np.log(
        np.exp(logits)[np.arange(b), labels] / np.exp(logits).sum(-1)
    )
    assert np.allclose(loss, ref, rtol=1e-4)


def test_vocab_parallel_embedding_op():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed import ops as cops

    devs = np.asarray(jax.devices()[:4])
    mesh = Mesh(devs, ("mp",))
    table = np.random.randn(16, 8).astype(np.float32)
    ids = np.random.randint(0, 16, (5,))
    f = jax.jit(jax.shard_map(
        lambda t, i: cops.c_embedding(i, t, "mp"),
        mesh=mesh, in_specs=(P("mp", None), P()), out_specs=P(),
    ))
    out = np.asarray(f(jnp.asarray(table), jnp.asarray(ids)))
    assert np.allclose(out, table[ids], rtol=1e-5)
