"""KV-cache decoding + sampling tests (reference: fused_multi_transformer
CacheKV generation path; top_k_op / top_p_sampling samplers)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
                    max_seq_len=48, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.mark.slow
def test_cached_forward_matches_full_forward(tiny_gpt):
    """Prefill + cached one-token steps must reproduce the uncached logits —
    the cache is an optimization, not an approximation."""
    m = tiny_gpt
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 97, (2, 10)).astype("int32")

    full = np.asarray(m(Tensor(ids))._value)  # [2, 10, 97]

    caches = m.gpt.init_cache(2, max_len=16)
    logits_p, caches = m(Tensor(ids[:, :6]), caches=caches, pos=0)
    np.testing.assert_allclose(np.asarray(logits_p._value), full[:, :6],
                               rtol=2e-4, atol=2e-4)
    pos = 6
    for t in range(6, 10):
        step, caches = m(Tensor(ids[:, t:t + 1]), caches=caches, pos=pos)
        np.testing.assert_allclose(np.asarray(step._value)[:, 0], full[:, t],
                                   rtol=2e-4, atol=2e-4)
        pos += 1


@pytest.mark.slow
def test_greedy_generate_matches_stepwise_argmax(tiny_gpt):
    m = tiny_gpt
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 97, (2, 5)).astype("int32")
    out = np.asarray(m.generate(Tensor(ids), max_new_tokens=6)._value)
    assert out.shape == (2, 11)
    assert (out[:, :5] == ids).all()

    # uncached argmax roll-forward must agree with the cached scan loop
    cur = ids
    for _ in range(6):
        logits = np.asarray(m(Tensor(cur))._value)
        nxt = logits[:, -1].argmax(-1).astype("int32")
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, cur)


def test_generate_eos_padding(tiny_gpt):
    """Rows that hit eos keep emitting pad_token_id."""
    m = tiny_gpt
    rng = np.random.RandomState(2)
    ids = rng.randint(0, 97, (1, 4)).astype("int32")
    # force eos = the greedy first token so the row finishes immediately
    first = np.asarray(m.generate(Tensor(ids), max_new_tokens=1)._value)[0, -1]
    out = np.asarray(m.generate(Tensor(ids), max_new_tokens=5,
                                eos_token_id=int(first),
                                pad_token_id=96)._value)
    assert out[0, 4] == first
    assert (out[0, 5:] == 96).all()


def test_sampling_respects_top_k():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.text.generation import sample_logits

    logits = jnp.asarray(np.array([[5.0, 4.0, 3.0, -2.0, -3.0]] * 64))
    toks = sample_logits(logits, jax.random.key(0), temperature=1.0, top_k=2)
    assert set(np.asarray(toks).tolist()) <= {0, 1}

    toks_p = sample_logits(logits, jax.random.key(1), top_p=0.5)
    # p=0.5: token 0 alone carries ~0.64 mass -> nucleus is {0}
    assert set(np.asarray(toks_p).tolist()) == {0}


def test_sampled_generate_runs_and_varies(tiny_gpt):
    m = tiny_gpt
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 97, (2, 4)).astype("int32")
    a = np.asarray(m.generate(Tensor(ids), max_new_tokens=8, do_sample=True,
                              temperature=1.5, seed=0)._value)
    b = np.asarray(m.generate(Tensor(ids), max_new_tokens=8, do_sample=True,
                              temperature=1.5, seed=1)._value)
    assert a.shape == b.shape == (2, 12)
    assert (a != b).any()  # different seeds give different samples
