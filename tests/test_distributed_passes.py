"""Distributed-pass tests — the reference pattern (dist_pass_test_base.py):
build a program, snapshot it, apply the pass, assert the recorded rewrite AND
numeric equivalence/effect against the un-passed program.
"""
import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.distributed.passes import PassManager, new_pass


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _build_train_program(seed=3, lr=0.1, opt_cls=None):
    paddle.seed(seed)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [8, 6], "float32")
        label = static.data("label", [8], "int64")
        net = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4))
        logits = net(x)
        loss = nn.functional.cross_entropy(logits, label)
        opt = (opt_cls or paddle.optimizer.SGD)(lr)
        opt.minimize(loss)
    return main, loss


def _data(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.rand(8, 6).astype(np.float32),
            rng.randint(0, 4, (8,)).astype(np.int64))


def test_gradient_merge_pass_numerics():
    """k=2 gradient merge on a constant batch == plain SGD at half the step
    count (grads identical within an accumulation window)."""
    xv, yv = _data()

    main_ref, loss_ref = _build_train_program()
    exe = static.Executor()
    ref_losses = [float(exe.run(main_ref, feed={"x": xv, "label": yv},
                                fetch_list=[loss_ref])[0]) for _ in range(2)]

    main_gm, loss_gm = _build_train_program()
    ctx = new_pass("auto_parallel_gradient_merge", {"k_steps": 2}).apply(main_gm)
    assert ctx.attrs["gradient_merge"] == {"k_steps": 2, "avg": True}
    assert main_gm._gradient_merge == {"k_steps": 2, "avg": True}

    exe2 = static.Executor()
    gm_losses = [float(exe2.run(main_gm, feed={"x": xv, "label": yv},
                                fetch_list=[loss_gm])[0]) for _ in range(4)]
    # steps 0,1 see the initial params; step 2 sees params after one update
    assert gm_losses[0] == pytest.approx(gm_losses[1], rel=1e-6)
    assert gm_losses[2] == pytest.approx(ref_losses[1], rel=1e-5)


def test_gradient_merge_counter_state():
    main, loss = _build_train_program()
    new_pass("auto_parallel_gradient_merge", {"k_steps": 3}).apply(main)
    exe = static.Executor()
    xv, yv = _data()
    for i in range(4):
        exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])
        count = int(np.asarray(main._gm_ref["s"][0]))
        assert count == (i + 1) % 3, f"step {i}: count={count}"


def test_sharding_pass_layout_and_parity():
    """Stage-1 sharding: optimizer slots land sharded over the axis; losses
    match the un-passed program exactly (GSPMD layout must not change math)."""
    xv, yv = _data()

    main_ref, loss_ref = _build_train_program(opt_cls=paddle.optimizer.Adam)
    exe = static.Executor()
    ref_losses = [float(exe.run(main_ref, feed={"x": xv, "label": yv},
                                fetch_list=[loss_ref])[0]) for _ in range(3)]

    mesh = Mesh(np.asarray(jax.devices()), ("sharding",))
    main_sh, loss_sh = _build_train_program(opt_cls=paddle.optimizer.Adam)
    ctx = new_pass("auto_parallel_sharding",
                   {"mesh": mesh, "stage": 1}).apply(main_sh)
    assert ctx.attrs["sharding"]["stage"] == 1
    assert main_sh._dist_attrs["axis"] == "sharding"

    exe2 = static.Executor()
    sh_losses = [float(exe2.run(main_sh, feed={"x": xv, "label": yv},
                                fetch_list=[loss_sh])[0]) for _ in range(3)]
    assert sh_losses == pytest.approx(ref_losses, rel=2e-5)

    # the [16] bias / [6,16] weight slots: at least one slot actually sharded
    slots = main_sh._opt_state_ref["s"]["slots"]
    leaves = jax.tree_util.tree_leaves(slots)
    assert any(
        isinstance(l.sharding, NamedSharding) and "sharding" in str(l.sharding.spec)
        for l in leaves
    ), [getattr(l, "sharding", None) for l in leaves]


def test_sharding_pass_stage3_params():
    mesh = Mesh(np.asarray(jax.devices()), ("sharding",))
    main, loss = _build_train_program()
    new_pass("auto_parallel_sharding", {"mesh": mesh, "stage": 3}).apply(main)
    assert main._dist_attrs["param_specs"], "stage 3 must record param specs"
    exe = static.Executor()
    xv, yv = _data()
    l0 = float(exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])[0])
    for _ in range(5):
        l1 = float(exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])[0])
    assert np.isfinite(l1) and l1 < l0


def test_pass_manager_chains_and_amp_idempotent():
    mesh = Mesh(np.asarray(jax.devices()), ("sharding",))
    main, loss = _build_train_program()
    pm = PassManager([
        new_pass("auto_mixed_precision"),
        new_pass("auto_parallel_sharding", {"mesh": mesh, "stage": 1}),
        new_pass("auto_parallel_gradient_merge", {"k_steps": 2}),
    ])
    ctx = pm.apply(main)
    assert ctx.attrs["applied_passes"] == [
        "auto_mixed_precision", "auto_parallel_sharding",
        "auto_parallel_gradient_merge"]
    # idempotency (VERDICT r2 weak #8): re-applying AMP must not double-wrap
    amp_ops = [op for b in main.blocks for op in b.ops if "amp" in op.attrs]
    fns_before = [op.fn for op in amp_ops]
    new_pass("auto_mixed_precision").apply(main)
    assert [op.fn for op in amp_ops] == fns_before
    exe = static.Executor()
    xv, yv = _data()
    l = float(exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])[0])
    assert np.isfinite(l)
