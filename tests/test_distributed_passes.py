"""Distributed-pass tests — the reference pattern (dist_pass_test_base.py):
build a program, snapshot it, apply the pass, assert the recorded rewrite AND
numeric equivalence/effect against the un-passed program.
"""
import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.distributed.passes import PassManager, new_pass


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _build_train_program(seed=3, lr=0.1, opt_cls=None):
    paddle.seed(seed)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [8, 6], "float32")
        label = static.data("label", [8], "int64")
        net = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4))
        logits = net(x)
        loss = nn.functional.cross_entropy(logits, label)
        opt = (opt_cls or paddle.optimizer.SGD)(lr)
        opt.minimize(loss)
    return main, loss


def _data(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.rand(8, 6).astype(np.float32),
            rng.randint(0, 4, (8,)).astype(np.int64))


def test_gradient_merge_pass_numerics():
    """k=2 gradient merge on a constant batch == plain SGD at half the step
    count (grads identical within an accumulation window)."""
    xv, yv = _data()

    main_ref, loss_ref = _build_train_program()
    exe = static.Executor()
    ref_losses = [float(exe.run(main_ref, feed={"x": xv, "label": yv},
                                fetch_list=[loss_ref])[0]) for _ in range(2)]

    main_gm, loss_gm = _build_train_program()
    ctx = new_pass("auto_parallel_gradient_merge", {"k_steps": 2}).apply(main_gm)
    assert ctx.attrs["gradient_merge"] == {"k_steps": 2, "avg": True}
    assert main_gm._gradient_merge == {"k_steps": 2, "avg": True}

    exe2 = static.Executor()
    gm_losses = [float(exe2.run(main_gm, feed={"x": xv, "label": yv},
                                fetch_list=[loss_gm])[0]) for _ in range(4)]
    # steps 0,1 see the initial params; step 2 sees params after one update
    assert gm_losses[0] == pytest.approx(gm_losses[1], rel=1e-6)
    assert gm_losses[2] == pytest.approx(ref_losses[1], rel=1e-5)


def test_gradient_merge_counter_state():
    main, loss = _build_train_program()
    new_pass("auto_parallel_gradient_merge", {"k_steps": 3}).apply(main)
    exe = static.Executor()
    xv, yv = _data()
    for i in range(4):
        exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])
        count = int(np.asarray(main._gm_ref["s"][0]))
        assert count == (i + 1) % 3, f"step {i}: count={count}"


def test_sharding_pass_layout_and_parity():
    """Stage-1 sharding: optimizer slots land sharded over the axis; losses
    match the un-passed program exactly (GSPMD layout must not change math)."""
    xv, yv = _data()

    main_ref, loss_ref = _build_train_program(opt_cls=paddle.optimizer.Adam)
    exe = static.Executor()
    ref_losses = [float(exe.run(main_ref, feed={"x": xv, "label": yv},
                                fetch_list=[loss_ref])[0]) for _ in range(3)]

    mesh = Mesh(np.asarray(jax.devices()), ("sharding",))
    main_sh, loss_sh = _build_train_program(opt_cls=paddle.optimizer.Adam)
    ctx = new_pass("auto_parallel_sharding",
                   {"mesh": mesh, "stage": 1}).apply(main_sh)
    assert ctx.attrs["sharding"]["stage"] == 1
    assert main_sh._dist_attrs["axis"] == "sharding"

    exe2 = static.Executor()
    sh_losses = [float(exe2.run(main_sh, feed={"x": xv, "label": yv},
                                fetch_list=[loss_sh])[0]) for _ in range(3)]
    assert sh_losses == pytest.approx(ref_losses, rel=2e-5)

    # the [16] bias / [6,16] weight slots: at least one slot actually sharded
    slots = main_sh._opt_state_ref["s"]["slots"]
    leaves = jax.tree_util.tree_leaves(slots)
    assert any(
        isinstance(l.sharding, NamedSharding) and "sharding" in str(l.sharding.spec)
        for l in leaves
    ), [getattr(l, "sharding", None) for l in leaves]


def test_sharding_pass_stage3_params():
    mesh = Mesh(np.asarray(jax.devices()), ("sharding",))
    main, loss = _build_train_program()
    new_pass("auto_parallel_sharding", {"mesh": mesh, "stage": 3}).apply(main)
    assert main._dist_attrs["param_specs"], "stage 3 must record param specs"
    exe = static.Executor()
    xv, yv = _data()
    l0 = float(exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])[0])
    for _ in range(5):
        l1 = float(exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])[0])
    assert np.isfinite(l1) and l1 < l0


def test_recompute_pass_tags_and_parity():
    """Recompute must not change numerics — only the remat schedule."""
    xv, yv = _data()
    main_ref, loss_ref = _build_train_program()
    exe = static.Executor()
    ref = [float(exe.run(main_ref, feed={"x": xv, "label": yv},
                         fetch_list=[loss_ref])[0]) for _ in range(3)]

    main_rc, loss_rc = _build_train_program()
    ctx = new_pass("auto_parallel_recompute", {"policy": "dots"}).apply(main_rc)
    assert ctx.attrs["recompute"]["policy"] == "dots"
    assert ctx.attrs["recompute"]["n_forward_ops"] > 0
    fwd_ops = [op for b in main_rc.blocks for op in b.ops
               if op.attrs.get("recompute")]
    assert fwd_ops, "forward ops must be tagged"
    exe2 = static.Executor()
    rc = [float(exe2.run(main_rc, feed={"x": xv, "label": yv},
                         fetch_list=[loss_rc])[0]) for _ in range(3)]
    assert rc == pytest.approx(ref, rel=1e-6)


def test_amp_o1_pass_program_diff_and_numerics():
    xv, yv = _data()
    main, loss = _build_train_program()
    ref_main, ref_loss = _build_train_program()
    ctx = new_pass("auto_parallel_amp").apply(main)
    assert ctx.attrs["amp"] == {"level": "O1", "dtype": "bfloat16",
                                "n_ops": ctx.attrs["amp"]["n_ops"]}
    assert ctx.attrs["amp"]["n_ops"] > 0
    tagged = [op.attrs["amp"] for b in main.blocks for op in b.ops
              if "amp" in op.attrs]
    assert "bfloat16" in tagged  # linear ops compute in bf16
    exe, exe_ref = static.Executor(), static.Executor()
    for _ in range(3):
        l_amp = float(exe.run(main, feed={"x": xv, "label": yv},
                              fetch_list=[loss])[0])
        l_ref = float(exe_ref.run(ref_main, feed={"x": xv, "label": yv},
                                  fetch_list=[ref_loss])[0])
    # bf16 matmuls: close to fp32 but not bit-identical
    assert l_amp == pytest.approx(l_ref, rel=0.05)
    assert np.isfinite(l_amp)


def test_fp16_pass_loss_scaling_protocol():
    """fp16 O2: scale applied, update skipped on overflow, scale shrinks."""
    xv, yv = _data()
    main, loss = _build_train_program(opt_cls=paddle.optimizer.Adam)
    new_pass("auto_parallel_fp16", {
        "dtype": "float16", "init_loss_scaling": 1024.0,
        "incr_every_n_steps": 2, "decr_every_n_nan_or_inf": 1,
    }).apply(main)
    assert main._loss_scaling["enabled"]
    exe = static.Executor()
    l0 = float(exe.run(main, feed={"x": xv, "label": yv},
                       fetch_list=[loss])[0])
    assert np.isfinite(l0)
    scale0 = float(np.asarray(main._ls_ref["s"][0]))
    assert scale0 == 1024.0  # one good step: not yet grown (incr_every=2)
    exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])
    assert float(np.asarray(main._ls_ref["s"][0])) == 2048.0  # grew after 2

    # poison batch -> inf loss: update must be SKIPPED and scale halved
    params_before = [np.asarray(p._value).copy()
                     for p in main.captured_params() if not p.stop_gradient]
    bad_x = np.full((8, 6), 1e30, np.float32)
    l_bad = exe.run(main, feed={"x": bad_x, "label": yv},
                    fetch_list=[loss])[0]
    params_after = [np.asarray(p._value)
                    for p in main.captured_params() if not p.stop_gradient]
    for b, a in zip(params_before, params_after):
        np.testing.assert_array_equal(b, a)
    assert float(np.asarray(main._ls_ref["s"][0])) == 1024.0  # halved


def test_bf16_fp16_pass_disables_scaling():
    main, loss = _build_train_program()
    new_pass("auto_parallel_fp16", {"dtype": "bfloat16"}).apply(main)
    assert not main._loss_scaling["enabled"]  # bf16 needs no overflow guard
    exe = static.Executor()
    xv, yv = _data()
    l = float(exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])[0])
    assert np.isfinite(l)


def test_fuse_all_reduce_pass_numeric_parity():
    """Flat-bucket fused update must be numerically identical (Adam)."""
    xv, yv = _data()
    main_ref, loss_ref = _build_train_program(opt_cls=paddle.optimizer.Adam)
    exe = static.Executor()
    ref = [float(exe.run(main_ref, feed={"x": xv, "label": yv},
                         fetch_list=[loss_ref])[0]) for _ in range(4)]

    main_f, loss_f = _build_train_program(opt_cls=paddle.optimizer.Adam)
    ctx = new_pass("fuse_all_reduce", {"size_mb": 32}).apply(main_f)
    assert ctx.attrs["fuse_all_reduce"]["size_mb"] == 32
    exe2 = static.Executor()
    fused = [float(exe2.run(main_f, feed={"x": xv, "label": yv},
                            fetch_list=[loss_f])[0]) for _ in range(4)]
    assert fused == pytest.approx(ref, rel=1e-5)
    # the optimizer state must actually live on flat buckets
    assert main_f._fuse_plan is not None
    slot_keys = list(main_f._opt_state_ref["s"]["slots"].keys())
    assert all(k.startswith("bucket") for k in slot_keys), slot_keys
    # 4 params (2 layers x w,b) packed into one 32MB bucket
    assert len(main_f._fuse_plan["buckets"]) == 1


def test_fuse_all_reduce_composes_with_gradient_merge():
    xv, yv = _data()
    main_ref, loss_ref = _build_train_program(opt_cls=paddle.optimizer.Adam)
    new_pass("auto_parallel_gradient_merge", {"k_steps": 2}).apply(main_ref)
    exe = static.Executor()
    ref = [float(exe.run(main_ref, feed={"x": xv, "label": yv},
                         fetch_list=[loss_ref])[0]) for _ in range(4)]

    main_f, loss_f = _build_train_program(opt_cls=paddle.optimizer.Adam)
    PassManager([
        new_pass("auto_parallel_gradient_merge", {"k_steps": 2}),
        new_pass("fuse_all_reduce", {"size_mb": 32}),
    ]).apply(main_f)
    exe2 = static.Executor()
    fused = [float(exe2.run(main_f, feed={"x": xv, "label": yv},
                            fetch_list=[loss_f])[0]) for _ in range(4)]
    assert fused == pytest.approx(ref, rel=1e-5)


def test_fuse_all_reduce_skips_non_elementwise_opt():
    import warnings as _w

    main, loss = _build_train_program(
        opt_cls=lambda lr: paddle.optimizer.Lamb(learning_rate=lr))
    new_pass("fuse_all_reduce", {"size_mb": 32}).apply(main)
    exe = static.Executor()
    xv, yv = _data()
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        l = float(exe.run(main, feed={"x": xv, "label": yv},
                          fetch_list=[loss])[0])
    assert np.isfinite(l)
    assert main._fuse_plan is None  # Lamb trust ratio is per-param: unfused
    assert any("not elementwise" in str(w.message) for w in rec)


def test_apply_strategy_passes_routes_flags():
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.passes import apply_strategy_passes

    strategy = DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs = {"level": "O1", "dtype": "bfloat16"}
    strategy.recompute = True
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 2, "avg": True}
    main, loss = _build_train_program()
    ctx = apply_strategy_passes(main, strategy)
    assert set(ctx.attrs["applied_passes"]) >= {
        "auto_parallel_amp", "auto_parallel_recompute",
        "auto_parallel_gradient_merge", "fuse_all_reduce"}
    exe = static.Executor()
    xv, yv = _data()
    l = float(exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])[0])
    assert np.isfinite(l)


def test_strategy_compiler_warns_on_unwired_flags():
    import warnings as _w

    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.fleet.meta_optimizers import StrategyCompiler

    strategy = DistributedStrategy()
    strategy.fp16_allreduce = True
    strategy.heter_ccl_mode = True
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        _, applied, disabled = StrategyCompiler().compile(strategy)
    msgs = [str(w.message) for w in rec]
    assert any("fp16_allreduce" in m for m in msgs)
    assert any("heter_ccl_mode" in m for m in msgs)
    assert "fp16_allreduce" in disabled and "heter_ccl_mode" in disabled


def test_pass_manager_chains_and_amp_idempotent():
    mesh = Mesh(np.asarray(jax.devices()), ("sharding",))
    main, loss = _build_train_program()
    pm = PassManager([
        new_pass("auto_mixed_precision"),
        new_pass("auto_parallel_sharding", {"mesh": mesh, "stage": 1}),
        new_pass("auto_parallel_gradient_merge", {"k_steps": 2}),
    ])
    ctx = pm.apply(main)
    assert ctx.attrs["applied_passes"] == [
        "auto_mixed_precision", "auto_parallel_sharding",
        "auto_parallel_gradient_merge"]
    # idempotency (VERDICT r2 weak #8): re-applying AMP must not double-wrap
    amp_ops = [op for b in main.blocks for op in b.ops if "amp" in op.attrs]
    fns_before = [op.fn for op in amp_ops]
    new_pass("auto_mixed_precision").apply(main)
    assert [op.fn for op in amp_ops] == fns_before
    exe = static.Executor()
    xv, yv = _data()
    l = float(exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])[0])
    assert np.isfinite(l)
