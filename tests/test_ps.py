"""Parameter-server stack tests.

Reference test analog: tests/unittests/test_dist_base.py (subprocess
pserver/trainer cluster) + table unit tests (memory_sparse_table_test.cc).
Here servers run in-process threads (single-host substitute, same as the
reference's local-cluster pattern).
"""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.ps import (
    DenseTable, PsClient, PsServer, SparseTable,
)
from paddle_tpu.distributed.ps import runtime as ps_runtime
from paddle_tpu.distributed.ps.role_maker import PaddleCloudRoleMaker
from paddle_tpu.runtime import native


def test_dense_table_sgd_adagrad():
    t = DenseTable(4, optimizer="sgd", lr=0.1)
    t.assign(np.ones(4, np.float32))
    t.push_grad(np.full(4, 2.0, np.float32))
    t.push_grad(np.full(4, 1.0, np.float32))  # accumulates
    norm = t.apply()
    np.testing.assert_allclose(t.read(), 1.0 - 0.1 * 3.0, rtol=1e-6)
    assert norm == pytest.approx(6.0)  # |(3,3,3,3)|
    ta = DenseTable(2, optimizer="adagrad", lr=0.5)
    ta.assign(np.zeros(2, np.float32))
    ta.push_grad(np.array([2.0, -2.0], np.float32))
    ta.apply()
    np.testing.assert_allclose(ta.read(), [-0.5, 0.5], rtol=1e-4)


def test_sparse_table_lazy_init_and_update():
    t = SparseTable(8, optimizer="sgd", lr=0.1, seed=3)
    rows = t.pull(np.array([5, 9, 5]))
    assert rows.shape == (3, 8)
    np.testing.assert_allclose(rows[0], rows[2])  # same id, same row
    assert t.size() == 2
    g = np.ones((2, 8), np.float32)
    before = t.pull(np.array([5, 9]))
    t.push_grad(np.array([5, 9]), g)
    after = t.pull(np.array([5, 9]))
    np.testing.assert_allclose(after, before - 0.1, rtol=1e-5)
    ids, emb = t.export()
    assert set(ids.tolist()) == {5, 9} and emb.shape == (2, 8)


def test_native_tables_are_used():
    # the C++ core should be available in this image (g++ baked in)
    assert native.lib is not None or native.build() is not None


@pytest.fixture
def ps_cluster():
    servers = [PsServer(port=0, n_workers=1).start() for _ in range(2)]
    eps = [f"127.0.0.1:{s.port}" for s in servers]
    client = PsClient(eps)
    yield servers, client, eps
    try:
        client.close()
    finally:
        for s in servers:
            s.stop()


def test_client_server_dense_sparse(ps_cluster):
    _, client, _ = ps_cluster
    client.create_dense("w", 6, optimizer="sgd", lr=0.5,
                        init=np.arange(6, dtype=np.float32))
    np.testing.assert_allclose(client.pull_dense("w"), np.arange(6))
    client.push_dense("w", np.ones(6, np.float32), apply_now=True)
    np.testing.assert_allclose(client.pull_dense("w"), np.arange(6) - 0.5)

    client.create_sparse("emb", 4, optimizer="sgd", lr=1.0, seed=0)
    ids = np.array([0, 1, 2, 3, 101, 202])  # shards across both servers
    rows = client.pull_sparse("emb", ids)
    assert rows.shape == (6, 4)
    client.push_sparse("emb", ids, np.ones((6, 4), np.float32))
    rows2 = client.pull_sparse("emb", ids)
    np.testing.assert_allclose(rows2, rows - 1.0, rtol=1e-5)
    assert client.sparse_size("emb") == 6


def test_barrier_blocks_until_all_workers():
    server = PsServer(port=0, n_workers=2).start()
    c1 = PsClient([f"127.0.0.1:{server.port}"])
    c2 = PsClient([f"127.0.0.1:{server.port}"])
    order = []

    def w1():
        c1.barrier()
        order.append("released")

    th = threading.Thread(target=w1)
    th.start()
    th.join(timeout=0.3)
    assert th.is_alive() and not order  # blocked until second worker arrives
    c2.barrier()
    th.join(timeout=5)
    assert order == ["released"]
    c1.close()
    c2.close()
    server.stop()


def test_ssd_sparse_table_spills_and_compacts(tmp_path):
    """SSD table (reference ssd_sparse_table.cc): rows beyond the RAM cache
    spill to disk and come back bit-exact; save() compacts append history."""
    from paddle_tpu.distributed.ps import SsdSparseTable

    t = SsdSparseTable(dim=4, path=str(tmp_path / "emb.bin"), cache_rows=8,
                       lr=0.5, seed=3)
    ids = np.arange(32)
    first = t.pull(ids)  # 32 rows through an 8-row cache: 24 spilled
    assert t.size() == 32
    assert t.hot_rows() <= 8
    again = t.pull(ids)
    np.testing.assert_array_equal(first, again)  # spilled rows round-trip

    # updates hit spilled rows correctly
    t.push_grad(np.array([0, 31]), np.ones((2, 4), np.float32))
    np.testing.assert_allclose(t.pull(np.array([0]))[0], first[0] - 0.5)
    np.testing.assert_allclose(t.pull(np.array([31]))[0], first[31] - 0.5)

    # compaction dedups the append-only history but preserves values
    import os

    before = os.path.getsize(tmp_path / "emb.bin")
    t.save()
    after = os.path.getsize(tmp_path / "emb.bin")
    assert after == 32 * 4 * 4 <= before
    np.testing.assert_allclose(t.pull(np.array([31]))[0], first[31] - 0.5)

    # empty pull, checkpoint copy doesn't move the live store, adagrad honored
    assert t.pull(np.array([], np.int64)).shape == (0, 4)
    t.save(str(tmp_path / "ckpt.bin"))
    assert os.path.exists(tmp_path / "ckpt.bin")
    t.push_grad(np.array([5]), np.ones((1, 4), np.float32))  # appends to live
    assert os.path.getsize(tmp_path / "ckpt.bin") == 32 * 4 * 4  # untouched
    t.close()

    ta = SsdSparseTable(dim=2, path=str(tmp_path / "ada.bin"), cache_rows=2,
                        optimizer="adagrad", lr=1.0, seed=0)
    r0 = ta.pull(np.array([1]))[0].copy()
    ta.push_grad(np.array([1]), np.full((1, 2), 2.0, np.float32))
    # adagrad first step: w -= lr * g / (sqrt(g^2) + eps) ~= lr * sign(g)
    np.testing.assert_allclose(ta.pull(np.array([1]))[0], r0 - 1.0, atol=1e-4)
    # accumulator survives a spill round-trip: second identical step is smaller
    ta.pull(np.arange(10, 14))  # force eviction of id 1
    ta.push_grad(np.array([1]), np.full((1, 2), 2.0, np.float32))
    np.testing.assert_allclose(ta.pull(np.array([1]))[0],
                               r0 - 1.0 - 1.0 / np.sqrt(2), atol=1e-3)
    ta.close()


def test_ssd_sparse_table_restart_and_ctr_compose(tmp_path):
    """save() must round-trip across a process restart (offset index sidecar)
    and the table must compose with CtrAccessor (export/erase contract)."""
    from paddle_tpu.distributed.ps import CtrAccessor, SsdSparseTable

    path = str(tmp_path / "emb.bin")
    t = SsdSparseTable(dim=4, path=path, cache_rows=4, lr=0.5, seed=1)
    vals = t.pull(np.arange(12))
    t.push_grad(np.array([3]), np.ones((1, 4), np.float32))
    trained = t.pull(np.array([3]))[0].copy()
    t.save()
    t.close()

    t2 = SsdSparseTable(dim=4, path=path, cache_rows=4, lr=0.5, seed=999)
    assert t2.size() == 12  # restart recovered the saved rows
    np.testing.assert_allclose(t2.pull(np.array([3]))[0], trained)
    np.testing.assert_allclose(t2.pull(np.array([7]))[0], vals[7])

    acc = CtrAccessor(t2)
    acc.update(np.array([3, 7]), shows=[10, 10])
    removed = acc.shrink(1.0)  # evict everything never shown
    assert removed == 10 and t2.size() == 2
    with pytest.raises(ValueError):
        SsdSparseTable(dim=4, path=str(tmp_path / "z.bin"), cache_rows=0)
    t2.close()


def test_ctr_accessor_decay_and_shrink():
    """CTR accessor (reference ctr_accessor.cc + MemorySparseTable::Shrink):
    show/click scores decay per pass; shrink evicts low-score features from
    the native table."""
    from paddle_tpu.distributed.ps import CtrAccessor, SparseTable

    t = SparseTable(dim=4, seed=0)
    acc = CtrAccessor(t, show_coeff=1.0, click_coeff=10.0, decay_rate=0.5)
    hot, cold = np.array([1, 2]), np.array([100, 200, 300])
    t.pull(np.concatenate([hot, cold]))  # materialize 5 rows
    assert t.size() == 5
    acc.update(hot, shows=[5, 5], clicks=[1, 2])
    acc.update(cold, shows=[1, 1, 1])
    assert acc.score(2) == 5 + 20
    acc.decay()
    assert acc.score(2) == pytest.approx((5 + 20) / 2)
    # evict everything under score 2.0: the three cold features (score 0.5)
    removed = acc.shrink(2.0)
    assert removed == 3
    assert t.size() == 2
    ids, _ = t.export()
    assert set(ids.tolist()) == {1, 2}
    # erased ids re-materialize fresh on next pull (lazy init)
    t.pull(np.array([100]))
    assert t.size() == 3


def test_geo_sgd_two_workers_merge_deltas(ps_cluster, monkeypatch):
    """Geo-SGD (reference the_one_ps.py:816 geo mode): two workers train
    locally, each sync pushes its local delta; after both sync, the server
    holds init + delta_a + delta_b and both workers converge to it."""
    servers, client, eps = ps_cluster
    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", ",".join(eps))
    ps_runtime.set_role(PaddleCloudRoleMaker())
    monkeypatch.setattr(ps_runtime, "_client", client)

    import jax.numpy as jnp

    def make_model(seed):
        paddle.seed(seed)
        return nn.Linear(4, 3)

    # worker A registers (first worker initializes tables)
    m_a = make_model(7)
    init_w = m_a.weight.numpy().copy()
    geo_a = ps_runtime.GeoSGD(m_a, k_steps=2)
    # worker B shares the same tables (same client here; role still worker 0,
    # so pass init too — create_dense is idempotent on existing tables)
    m_b = make_model(7)
    geo_b = ps_runtime.GeoSGD(m_b, k_steps=2)

    # both trained locally: A adds +0.5 to its weight, B adds +0.25
    m_a.weight._value = m_a.weight._value + 0.5
    geo_a.step()  # count 1: no sync
    assert not np.allclose(client.pull_dense(
        [n for n, _ in geo_a._dense][0]).reshape(m_a.weight.shape),
        init_w + 0.5)
    geo_a.step()  # count 2: sync -> pushes +0.5 delta
    m_b.weight._value = m_b.weight._value + 0.25
    geo_b.sync()  # explicit sync -> pushes +0.25 delta
    # server now holds init + 0.75; B pulled it at sync
    np.testing.assert_allclose(m_b.weight.numpy(), init_w + 0.75, rtol=1e-5)
    geo_a.sync()
    np.testing.assert_allclose(m_a.weight.numpy(), m_b.weight.numpy(), rtol=1e-5)


def test_ps_end_to_end_embedding_regression(ps_cluster, monkeypatch):
    """Async-SGD: DistEmbedding + dense linear head, loss decreases."""
    servers, client, eps = ps_cluster
    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", ",".join(eps))
    ps_runtime.set_role(PaddleCloudRoleMaker())
    monkeypatch.setattr(ps_runtime, "_client", client)

    paddle.seed(31)

    class SparseNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = ps_runtime.DistEmbedding("vocab", 50, 8, lr=0.2)
            self.fc = nn.Linear(8, 1)

        def forward(self, ids):
            h = self.emb(ids)
            return self.fc(paddle.mean(h, axis=1))

    net = SparseNet()
    the_ps = ps_runtime.ThePS(net, dense_optimizer="sgd", dense_lr=0.1)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 50, (16, 3))
    target = (ids.mean(axis=1, keepdims=True) / 25.0 - 1.0).astype("float32")

    losses = []
    for _ in range(15):
        pred = net(paddle.to_tensor(ids))
        loss = paddle.mean((pred - paddle.to_tensor(target)) ** 2)
        loss.backward()
        the_ps.step()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5, losses
    assert client.sparse_size("vocab") <= 50


def test_sparse_table_text_dump_roundtrip(tmp_path):
    """Reference PS dump interop (memory_sparse_table.cc SaveLocalFS):
    `<dir>/<table_id>/part-NNN-00000` with `"key w... [acc...]"` lines.
    mode 0 resumes the adagrad trajectory exactly; mode 3 (weights-only,
    the save-for-inference param) reloads with reset accumulators; a
    hand-written reference-style file loads too."""
    t = SparseTable(4, optimizer="adagrad", lr=0.1, seed=7)
    ids = np.array([3, 11, 42])
    t.pull(ids)
    t.push_grad(ids, np.random.RandomState(0).rand(3, 4).astype(np.float32))
    want = t.pull(ids)
    _, _, want_acc = t.export_state()

    path = t.save_text(tmp_path, table_id=1, mode=0)
    assert path.endswith("part-000-00000")
    with open(path) as f:
        first = f.readline().split()
    assert len(first) == 1 + 2 * 4  # key + weights + accumulators

    t2 = SparseTable(4, optimizer="adagrad", lr=0.1, seed=99)
    t2.pull(np.array([777]))  # stale row a restore must clear
    assert t2.load_text(tmp_path, table_id=1) == 3
    assert t2.size() == 3  # clear=True erased the stale id 777
    np.testing.assert_allclose(t2.pull(ids), want, rtol=1e-6)
    _, _, acc2 = t2.export_state()
    np.testing.assert_allclose(np.sort(acc2, 0), np.sort(want_acc, 0),
                               rtol=1e-6)

    # weights-only dump: loads, accumulators reset
    t.save_text(tmp_path / "inf", table_id=0, mode=3)
    t3 = SparseTable(4, optimizer="adagrad", lr=0.1, seed=5)
    t3.load_text(tmp_path / "inf", table_id=0)
    np.testing.assert_allclose(t3.pull(ids), want, rtol=1e-6)

    # a reference-shaped file written by hand parses
    ref_dir = tmp_path / "ref" / "2"
    ref_dir.mkdir(parents=True)
    (ref_dir / "part-000-00000").write_text(
        "7 0.5 -0.25 1.0 2.0\n100 1 2 3 4 0.1 0.2 0.3 0.4\n")
    t4 = SparseTable(4, optimizer="adagrad", lr=0.1)
    assert t4.load_text(tmp_path / "ref", table_id=2) == 2
    np.testing.assert_allclose(t4.pull(np.array([7]))[0],
                               [0.5, -0.25, 1.0, 2.0])


def test_dense_table_text_dump_roundtrip(tmp_path):
    """Dense analog of the sparse dump (memory_dense_table.cc Save):
    one line per element, `weight [acc]` columns."""
    t = DenseTable(6, optimizer="adagrad", lr=0.1)
    t.assign(np.arange(6, dtype=np.float32))
    t.push_grad(np.ones(6, np.float32))
    t.apply()
    want, want_acc = t.read(), t.read_acc()

    t.save_text(tmp_path, table_id=7)
    t2 = DenseTable(6, optimizer="adagrad", lr=0.1)
    assert t2.load_text(tmp_path, table_id=7) == 6
    np.testing.assert_allclose(t2.read(), want, rtol=1e-6)
    np.testing.assert_allclose(t2.read_acc(), want_acc, rtol=1e-6)

    # size-mismatched dump refuses loudly
    t3 = DenseTable(4)
    with pytest.raises(ValueError, match="table size"):
        t3.load_text(tmp_path, table_id=7)

    # multi-slot accessor dumps (e.g. adam_d2sum 'weight avg_w acc') refuse
    # instead of silently mis-assigning columns
    d2 = tmp_path / "d2sum" / "0"
    d2.mkdir(parents=True)
    (d2 / "part-000").write_text("1.0 0.5 0.25\n" * 6)
    with pytest.raises(ValueError, match="columns"):
        DenseTable(6).load_text(tmp_path / "d2sum", table_id=0)
