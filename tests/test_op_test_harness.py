"""Op tests written against the OpTest harness (reference test strategy
SURVEY §4.1: numpy-reference op tests via op_test.py). Each class declares
inputs/attrs + numpy reference; check_output exercises eager AND static
paths, check_grad compares tape grads to finite differences."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.utils.op_test import OpTest


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


class TestMatmulOp(OpTest):
    def setUp(self):
        self.op = paddle.matmul
        self.inputs = {
            "x": np.random.rand(4, 6).astype("float32"),
            "y": np.random.rand(6, 5).astype("float32"),
        }
        self.attrs = {}
        self.ref = lambda x, y: x @ y

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x", "y"])


class TestMatmulTransposed(OpTest):
    def setUp(self):
        self.op = paddle.matmul
        self.inputs = {
            "x": np.random.rand(5, 4).astype("float32"),
            "y": np.random.rand(5, 3).astype("float32"),
        }
        self.attrs = {"transpose_x": True}
        self.ref = lambda x, y, transpose_x: x.T @ y

    def test_output(self):
        self.check_output()


class TestSoftmaxOp(OpTest):
    def setUp(self):
        self.op = F.softmax
        self.inputs = {"x": np.random.rand(3, 7).astype("float32")}
        self.attrs = {"axis": -1}
        self.ref = lambda x, axis: _np_softmax(x, axis)

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestGeluOp(OpTest):
    def setUp(self):
        self.op = F.gelu
        self.inputs = {"x": (np.random.rand(4, 5) * 2 - 1).astype("float32")}
        self.attrs = {}
        from scipy.special import erf as _erf  # scipy is available via jax deps

        self.ref = lambda x: 0.5 * x * (1 + _erf(x / np.sqrt(2)))

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestLayerNormOp(OpTest):
    def setUp(self):
        x = np.random.rand(4, 8).astype("float32")
        self.op = F.layer_norm
        self.inputs = {"x": x}
        self.attrs = {"normalized_shape": [8], "epsilon": 1e-5}

        def ref(x, normalized_shape, epsilon):
            mu = x.mean(-1, keepdims=True)
            var = x.var(-1, keepdims=True)
            return (x - mu) / np.sqrt(var + epsilon)

        self.ref = ref

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["x"], rtol=2e-2, atol=1e-3)


class TestLogSoftmaxOp(OpTest):
    def setUp(self):
        self.op = F.log_softmax
        self.inputs = {"x": np.random.rand(3, 6).astype("float32")}
        self.attrs = {"axis": -1}
        self.ref = lambda x, axis: np.log(_np_softmax(x, axis))

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestSigmoidOp(OpTest):
    def setUp(self):
        self.op = F.sigmoid
        self.inputs = {"x": (np.random.rand(10) * 4 - 2).astype("float32")}
        self.attrs = {}
        self.ref = lambda x: 1 / (1 + np.exp(-x))

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestReduceMeanOp(OpTest):
    def setUp(self):
        self.op = paddle.mean
        self.inputs = {"x": np.random.rand(4, 6).astype("float32")}
        self.attrs = {"axis": 1}
        self.ref = lambda x, axis: x.mean(axis)

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"])


class TestClipOp(OpTest):
    def setUp(self):
        self.op = paddle.clip
        self.inputs = {"x": (np.random.rand(20) * 2 - 1).astype("float32")}
        self.attrs = {"min": -0.4, "max": 0.6}
        self.ref = lambda x, min, max: np.clip(x, min, max)

    def test_output(self):
        self.check_output()


class TestBf16ToleranceSweep(OpTest):
    """bf16 runs with the relaxed per-dtype tolerance (reference runs each
    op per dtype with per-dtype thresholds)."""

    def setUp(self):
        import jax.numpy as jnp  # noqa: F401 — ensures bf16 numpy interop

        x32 = np.random.rand(4, 4).astype("float32")
        self.op = F.softmax
        import ml_dtypes

        self.inputs = {"x": x32.astype(ml_dtypes.bfloat16)}
        self.attrs = {"axis": -1}
        self.ref = lambda x, axis: _np_softmax(np.asarray(x, np.float32), axis)

    def test_output(self):
        self.check_output(atol=1e-2)


class TestHarnessCatchesWrongRef(OpTest):
    """The harness must actually fail on a wrong reference."""

    def setUp(self):
        self.op = F.relu
        self.inputs = {"x": (np.random.rand(8) - 0.5).astype("float32")}
        self.attrs = {}
        self.ref = lambda x: x  # wrong on purpose

    def test_output_fails(self):
        with self.assertRaises(AssertionError):
            self.check_output()
