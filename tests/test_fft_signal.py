"""paddle.fft / paddle.signal numeric tests vs numpy reference
(reference test analog: tests/unittests/test_fft.py, test_signal.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


rng = np.random.RandomState(7)


@pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
def test_fft_ifft_roundtrip(norm):
    x = rng.randn(3, 16).astype("float32")
    y = paddle.fft.fft(paddle.to_tensor(x), norm=norm).numpy()
    np.testing.assert_allclose(y, np.fft.fft(x, norm=norm), rtol=1e-4, atol=1e-5)
    back = paddle.fft.ifft(paddle.to_tensor(y), norm=norm).numpy()
    np.testing.assert_allclose(back.real, x, rtol=1e-4, atol=1e-5)


def test_rfft_irfft():
    x = rng.randn(4, 32).astype("float64")
    y = paddle.fft.rfft(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(y, np.fft.rfft(x), rtol=1e-9, atol=1e-10)
    back = paddle.fft.irfft(paddle.to_tensor(y), n=32).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-9, atol=1e-10)


def test_fft2_fftn():
    x = rng.randn(2, 8, 8).astype("float64")
    np.testing.assert_allclose(
        paddle.fft.fft2(paddle.to_tensor(x)).numpy(), np.fft.fft2(x), rtol=1e-9,
        atol=1e-9)
    np.testing.assert_allclose(
        paddle.fft.fftn(paddle.to_tensor(x)).numpy(), np.fft.fftn(x), rtol=1e-9,
        atol=1e-9)


def test_hfft_ihfft():
    x = rng.randn(10).astype("float64")
    np.testing.assert_allclose(
        paddle.fft.hfft(paddle.to_tensor(x)).numpy(), np.fft.hfft(x), rtol=1e-9,
        atol=1e-9)
    np.testing.assert_allclose(
        paddle.fft.ihfft(paddle.to_tensor(x)).numpy(), np.fft.ihfft(x),
        rtol=1e-9, atol=1e-9)


def test_freq_shift_helpers():
    np.testing.assert_allclose(paddle.fft.fftfreq(8, 0.5).numpy(),
                               np.fft.fftfreq(8, 0.5).astype("float32"))
    np.testing.assert_allclose(paddle.fft.rfftfreq(8, 0.5).numpy(),
                               np.fft.rfftfreq(8, 0.5).astype("float32"))
    x = rng.randn(9)
    np.testing.assert_allclose(paddle.fft.fftshift(paddle.to_tensor(x)).numpy(),
                               np.fft.fftshift(x))
    np.testing.assert_allclose(paddle.fft.ifftshift(paddle.to_tensor(x)).numpy(),
                               np.fft.ifftshift(x))


def test_frame_overlap_add_inverse():
    x = rng.randn(2, 40).astype("float32")
    f = paddle.signal.frame(paddle.to_tensor(x), frame_length=8, hop_length=8)
    assert f.numpy().shape == (2, 8, 5)
    y = paddle.signal.overlap_add(f, hop_length=8)
    np.testing.assert_allclose(y.numpy(), x, rtol=1e-6)


def test_stft_istft_roundtrip():
    x = rng.randn(1, 256).astype("float64")
    n_fft, hop = 64, 16
    win = np.hanning(n_fft).astype("float64")
    spec = paddle.signal.stft(paddle.to_tensor(x), n_fft, hop_length=hop,
                              window=paddle.to_tensor(win))
    assert spec.numpy().shape == (1, n_fft // 2 + 1, (256 // hop) + 1)
    back = paddle.signal.istft(spec, n_fft, hop_length=hop,
                               window=paddle.to_tensor(win), length=256)
    # edges lose energy with a hann window; compare the interior
    np.testing.assert_allclose(back.numpy()[0, n_fft:-n_fft],
                               x[0, n_fft:-n_fft], rtol=1e-6, atol=1e-8)


def test_frame_axis0_matches_reference_layout():
    # reference doc: frame(arange(8), 4, 2, axis=0) -> [[0..3],[2..5],[4..7]]
    x = paddle.to_tensor(np.arange(8).astype("float64"))
    y = paddle.signal.frame(x, 4, 2, axis=0).numpy()
    np.testing.assert_array_equal(
        y, np.array([[0, 1, 2, 3], [2, 3, 4, 5], [4, 5, 6, 7]], "float64"))
    y1 = paddle.signal.frame(x, 4, 2, axis=-1).numpy()
    np.testing.assert_array_equal(y1, y.T)
    back = paddle.signal.overlap_add(paddle.to_tensor(y), 4, axis=0).numpy()
    # non-overlapping hop=frame_length reconstructs when hop=4
    x2 = paddle.to_tensor(np.arange(8).astype("float64"))
    f2 = paddle.signal.frame(x2, 4, 4, axis=0)
    np.testing.assert_array_equal(
        paddle.signal.overlap_add(f2, 4, axis=0).numpy(), x2.numpy())
    with pytest.raises(ValueError):
        paddle.signal.frame(paddle.to_tensor(np.zeros((2, 8))), 4, 2, axis=1)


@pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
def test_hfftn_ihfftn_norms(norm):
    # real even signal -> rfftn spectrum; hfftn(ihfftn(x)) == x for every norm
    x = rng.randn(4, 10)
    spec = paddle.fft.ihfftn(paddle.to_tensor(x), norm=norm)
    back = paddle.fft.hfftn(spec, s=(4, 10), norm=norm).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-9, atol=1e-10)
    # 1d consistency: hfftn over last axis == hfft
    h1 = paddle.fft.hfftn(paddle.to_tensor(x[0]), axes=(0,), norm=norm).numpy()
    np.testing.assert_allclose(h1, np.fft.hfft(x[0], norm=norm), rtol=1e-9,
                               atol=1e-9)


def test_fft_gradients_flow():
    """ADVICE r1: fft/signal must be differentiable (reference fft has grad
    kernels)."""
    x = paddle.to_tensor(np.random.rand(4, 32).astype("float32"),
                         stop_gradient=False)
    y = paddle.abs(paddle.fft.rfft(x)).sum()
    y.backward()
    assert x.grad is not None and np.abs(x.grad.numpy()).sum() > 0


def test_stft_gradients_flow():
    x = paddle.to_tensor(np.random.rand(256).astype("float32"),
                         stop_gradient=False)
    loss = paddle.abs(paddle.signal.stft(x, n_fft=64)).sum()
    loss.backward()
    assert x.grad is not None and np.abs(x.grad.numpy()).sum() > 0


def test_frame_validates_inputs():
    with pytest.raises(ValueError):
        paddle.signal.frame(paddle.to_tensor(np.zeros(10, "float32")), 32, 8)
    with pytest.raises(ValueError):
        paddle.signal.frame(paddle.to_tensor(np.zeros(64, "float32")), 16, 0)
