"""pdmodel exporter <-> loader round-trip (VERDICT r4 missing #3 / weak #7).

Three layers of gate:
  1. Enumeration: every wire op type the exporter can write is readable by
     the loader (EXPORTED_OP_TYPES vs the loader op map) — drift breaks CI.
  2. Real-model round-trips: ResNet50 and a BERT-shaped encoder export via
     static/pdmodel_export.py, reload via inference/pdmodel.py, and match
     the source program numerically.
  3. Control-flow + detection tail: while / conditional_block+select_input
     programs synthesized on the real wire format run under lax control
     flow; yolo_box / multiclass_nms3 match reference semantics.
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.inference.pdmodel import (
    PdModelProgram, _make_op_map, load_pdmodel, parse_program_desc)
from paddle_tpu.static import pdmodel_export as pe
from paddle_tpu.static.pdmodel_export import (
    BlockIdx, save_inference_model_pdmodel)


# ------------------------------------------------------------- 1. enumeration
def test_every_exported_op_type_is_loadable():
    loader_ops = set(_make_op_map()) | {"feed", "fetch", "while",
                                        "conditional_block"}
    missing = pe.EXPORTED_OP_TYPES - loader_ops
    assert not missing, (
        f"exporter can write op types the loader cannot read: {missing}")


def test_emitter_keys_have_declared_types():
    # canary: every emitted "type" literal in the module source is declared
    import re

    src = open(pe.__file__.rstrip("c")).read()
    emitted = set(re.findall(r'"type": "([a-z0-9_]+)"', src))
    # _unary/_binary emitters take the type from their argument
    emitted |= {m for m in re.findall(r'_(?:unary|binary)\("([a-z0-9_]+)"\)',
                                      src)}
    assert emitted <= pe.EXPORTED_OP_TYPES, (
        emitted - pe.EXPORTED_OP_TYPES)


# ------------------------------------------------------- 2. real-model trips
@pytest.mark.slow
def test_resnet50_roundtrip_numerical_identity():
    static.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [1, 3, 64, 64])
            from paddle_tpu.vision.models import resnet50

            m = resnet50(num_classes=10)
            m.eval()
            out = m(x)
        xv = np.random.RandomState(0).rand(1, 3, 64, 64).astype("float32")
        (ref,) = static.Executor().run(prog, feed={"x": xv},
                                       fetch_list=[out])
        d = tempfile.mkdtemp()
        save_inference_model_pdmodel(os.path.join(d, "r50"), [x], [out],
                                     program=prog)
        got = load_pdmodel(os.path.join(d, "r50")).run({"x": xv})[0]
        np.testing.assert_allclose(np.asarray(got), ref, atol=1e-5)
    finally:
        static.disable_static()


def test_bert_shaped_roundtrip_numerical_identity():
    static.enable_static()
    try:
        paddle.seed(3)
        prog = static.Program()
        with static.program_guard(prog):
            ids = static.data("ids", [2, 16], "int64")
            emb = paddle.nn.Embedding(100, 32)
            enc = paddle.nn.TransformerEncoderLayer(
                32, 4, 64, dropout=0.0, activation="gelu")
            ln = paddle.nn.LayerNorm(32)
            out = ln(enc(emb(ids)))
        iv = np.random.RandomState(1).randint(0, 100, (2, 16)).astype("int64")
        (ref,) = static.Executor().run(prog, feed={"ids": iv},
                                       fetch_list=[out])
        d = tempfile.mkdtemp()
        save_inference_model_pdmodel(os.path.join(d, "bert"), [ids], [out],
                                     program=prog)
        got = load_pdmodel(os.path.join(d, "bert")).run({"ids": iv})[0]
        np.testing.assert_allclose(np.asarray(got), ref, atol=2e-5)
    finally:
        static.disable_static()


# --------------------------------------------- 3. control flow on the wire
def _wire_program(blocks):
    """blocks: list of (vars, ops) -> ProgramDesc bytes via the exporter's
    own wire primitives (the format both sides implement from the spec)."""
    out = b""
    for i, (vars_b, ops) in enumerate(blocks):
        parent = -1 if i == 0 else 0
        out += pe._lfield(1, pe._block_bytes(
            [pe._var_bytes(*v) for v in vars_b],
            [pe._op_bytes(o) for o in ops], idx=i, parent=parent))
    return out


def _feed_fetch(feed_names, fetch_names, shapes, dtypes):
    vars_b = [("feed", pe._VT_FEED_MINIBATCH), ("fetch", pe._VT_FETCH_LIST)]
    for n, s, dt in zip(feed_names, shapes, dtypes):
        vars_b.append((n, pe._VT_LOD_TENSOR, np.dtype(dt), s))
    ops = [{"type": "feed", "inputs": {"X": ["feed"]},
            "outputs": {"Out": [n]}, "attrs": {"col": i}}
           for i, n in enumerate(feed_names)]
    tail = [{"type": "fetch", "inputs": {"X": [n]},
             "outputs": {"Out": ["fetch"]}, "attrs": {"col": i}}
            for i, n in enumerate(fetch_names)]
    return vars_b, ops, tail


def test_while_loop_on_wire():
    # while i < n: x = x * 2; i = i + 1   (reference: while_op.cc semantics)
    vars_b, head, tail = _feed_fetch(["x", "i", "n"],
                                     ["x"],
                                     [(4,), (1,), (1,)],
                                     ["float32", "float32", "float32"])
    main_ops = head + [
        {"type": "less_than", "inputs": {"X": ["i"], "Y": ["n"]},
         "outputs": {"Out": ["cond"]}, "attrs": {}},
        {"type": "while",
         "inputs": {"X": ["x", "i", "n"], "Condition": ["cond"]},
         "outputs": {"Out": ["x", "i"], "StepScopes": ["_scopes"]},
         "attrs": {"sub_block": BlockIdx(1)}},
    ] + tail
    sub_ops = [
        {"type": "scale", "inputs": {"X": ["x"]}, "outputs": {"Out": ["x"]},
         "attrs": {"scale": 2.0, "bias": 0.0, "bias_after_scale": True}},
        {"type": "increment", "inputs": {"X": ["i"]},
         "outputs": {"Out": ["i"]}, "attrs": {"step": 1.0}},
        {"type": "less_than", "inputs": {"X": ["i"], "Y": ["n"]},
         "outputs": {"Out": ["cond"]}, "attrs": {}},
    ]
    blob = _wire_program([(vars_b, main_ops), ([], sub_ops)])
    pm = PdModelProgram(blob, None)
    x = np.ones(4, np.float32)
    (out,) = pm.run({"x": x, "i": np.zeros(1, np.float32),
                     "n": np.full(1, 3.0, np.float32)})
    np.testing.assert_allclose(np.asarray(out), x * 8.0)  # 3 doublings


def test_conditional_block_select_input_on_wire():
    # if cond: y = x * 10 else: y = x + 1 — paddle lowers this to two
    # conditional_blocks + logical_not + select_input (reference:
    # conditional_block_op.cc + select_input_op.cc)
    vars_b, head, tail = _feed_fetch(["x", "cond"], ["y"],
                                     [(3,), (1,)], ["float32", "bool"])
    main_ops = head + [
        {"type": "logical_not", "inputs": {"X": ["cond"]},
         "outputs": {"Out": ["ncond"]}, "attrs": {}},
        {"type": "conditional_block",
         "inputs": {"Cond": ["cond"], "Input": ["x"]},
         "outputs": {"Out": ["yt"], "Scope": ["_s1"]},
         "attrs": {"sub_block": BlockIdx(1)}},
        {"type": "conditional_block",
         "inputs": {"Cond": ["ncond"], "Input": ["x"]},
         "outputs": {"Out": ["yf"], "Scope": ["_s2"]},
         "attrs": {"sub_block": BlockIdx(2)}},
        {"type": "select_input",
         "inputs": {"X": ["yf", "yt"], "Mask": ["cond"]},
         "outputs": {"Out": ["y"]}, "attrs": {}},
    ] + tail
    sub_true = [{"type": "scale", "inputs": {"X": ["x"]},
                 "outputs": {"Out": ["yt"]},
                 "attrs": {"scale": 10.0, "bias": 0.0,
                           "bias_after_scale": True}}]
    sub_false = [{"type": "scale", "inputs": {"X": ["x"]},
                  "outputs": {"Out": ["yf"]},
                  "attrs": {"scale": 1.0, "bias": 1.0,
                            "bias_after_scale": True}}]
    blob = _wire_program([(vars_b, main_ops), ([], sub_true), ([], sub_false)])
    pm = PdModelProgram(blob, None)
    x = np.array([1.0, 2.0, 3.0], np.float32)
    (y_true,) = pm.run({"x": x, "cond": np.array([True])})
    np.testing.assert_allclose(np.asarray(y_true), x * 10.0)
    (y_false,) = pm.run({"x": x, "cond": np.array([False])})
    np.testing.assert_allclose(np.asarray(y_false), x + 1.0)


# ------------------------------------------------------- 3b. detection tail
def test_multiclass_nms3_semantics():
    from paddle_tpu.inference.pdmodel import _multiclass_nms3

    # two overlapping boxes of class 1 (IoU > 0.5) + one separate class 2
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
                     np.float32)
    scores = np.zeros((1, 3, 3), np.float32)
    scores[0, 1, 0] = 0.9   # class 1, box 0
    scores[0, 1, 1] = 0.8   # class 1, box 1 — suppressed by box 0
    scores[0, 2, 2] = 0.7   # class 2, box 2
    op = {"inputs": {"BBoxes": ["b"], "Scores": ["s"]},
          "outputs": {"Out": ["o"], "Index": ["i"], "NmsRoisNum": ["n"]},
          "attrs": {"background_label": 0, "score_threshold": 0.1,
                    "nms_threshold": 0.5, "keep_top_k": 5,
                    "nms_top_k": 10, "normalized": True}}
    outs = _multiclass_nms3({"b": boxes, "s": scores}, op)
    out = np.asarray(outs["Out"])
    n = int(np.asarray(outs["NmsRoisNum"])[0])
    assert n == 2
    # rows sorted by score: (class 1, 0.9), (class 2, 0.7)
    np.testing.assert_allclose(out[0, :2], [1.0, 0.9], atol=1e-6)
    np.testing.assert_allclose(out[1, :2], [2.0, 0.7], atol=1e-6)
    np.testing.assert_allclose(out[0, 2:], [0, 0, 10, 10], atol=1e-6)
    assert (out[2:, 0] == -1).all()  # padding rows


def test_yolo_box_op_decodes():
    from paddle_tpu.inference.pdmodel import _yolo_box_op

    rng = np.random.RandomState(0)
    x = rng.randn(1, 2 * (5 + 3), 4, 4).astype(np.float32)  # 2 anchors, 3 cls
    img = np.array([[128, 128]], np.int32)
    op = {"inputs": {"X": ["x"], "ImgSize": ["im"]},
          "outputs": {"Boxes": ["b"], "Scores": ["s"]},
          "attrs": {"anchors": [10, 13, 16, 30], "class_num": 3,
                    "conf_thresh": 0.01, "downsample_ratio": 32,
                    "clip_bbox": True, "scale_x_y": 1.0}}
    outs = _yolo_box_op({"x": x, "im": img}, op)
    b = np.asarray(outs["Boxes"])
    s = np.asarray(outs["Scores"])
    assert b.shape == (1, 32, 4) and s.shape == (1, 32, 3)
    assert (b >= 0).all() and (b <= 127).all()  # clipped to image
    assert (s >= 0).all() and (s <= 1).all()


# ---------------------------------------------------- 3c. decoder-tail ops
def test_top_k_gather_increment_ops():
    op_map = _make_op_map()
    import jax.numpy as jnp

    env = {"x": jnp.asarray(np.array([[3.0, 1.0, 2.0]], np.float32))}
    outs = op_map["top_k_v2"](env, {
        "inputs": {"X": ["x"]}, "outputs": {"Out": ["v"], "Indices": ["i"]},
        "attrs": {"k": 2, "axis": -1, "largest": True}})
    np.testing.assert_allclose(np.asarray(outs["Out"]), [[3.0, 2.0]])
    np.testing.assert_array_equal(np.asarray(outs["Indices"]), [[0, 2]])

    env2 = {"x": jnp.asarray(np.arange(10.0, dtype=np.float32)),
            "idx": jnp.asarray(np.array([7, 2], np.int64))}
    outs2 = op_map["gather"](env2, {
        "inputs": {"X": ["x"], "Index": ["idx"]},
        "outputs": {"Out": ["o"]}, "attrs": {}})
    np.testing.assert_allclose(np.asarray(outs2["Out"]), [7.0, 2.0])
