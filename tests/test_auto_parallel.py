"""Auto-parallel tests (reference model: tests/unittests/auto_parallel/ —
SURVEY.md §4/5). Runs on the 8-device CPU mesh from conftest."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.auto_parallel import (
    ClusterSpec,
    CommCostModel,
    Engine,
    ProcessMesh,
    complete,
    plan_mesh,
    reshard,
    shard_tensor,
)


def test_process_mesh_basics():
    pm = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    assert pm.shape == [2, 4] and pm.ndim == 2 and pm.size == 8
    assert pm.get_dim_size("mp") == 4
    m = pm.jax_mesh()
    assert m.axis_names == ("dp", "mp")
    assert m.devices.shape == (2, 4)
    pm2 = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["dp", "mp"])
    assert pm == pm2
    with pytest.raises(ValueError):
        ProcessMesh(np.arange(4), dim_names=["a", "b"])


def test_shard_tensor_eager_layout():
    pm = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
    t = shard_tensor(np.ones((8, 16), np.float32), pm, ["x", "y"])
    sh = t._value.sharding
    assert isinstance(sh, NamedSharding)
    assert tuple(sh.spec) == ("x", "y")
    # each device holds an (4, 4) shard
    shard = t._value.addressable_shards[0]
    assert shard.data.shape == (4, 4)
    assert t._sharding_spec == ("x", "y")
    with pytest.raises(ValueError):
        shard_tensor(np.ones((4, 4)), pm, ["nope", None])


def test_reshard_changes_layout():
    pm = ProcessMesh(np.arange(8), dim_names=["x"])
    t = shard_tensor(np.arange(64, dtype=np.float32).reshape(8, 8), pm, ["x", None])
    assert t._value.addressable_shards[0].data.shape == (1, 8)
    r = reshard(t, pm, [None, "x"])
    assert r._value.addressable_shards[0].data.shape == (8, 1)
    np.testing.assert_array_equal(np.asarray(r._value),
                                  np.arange(64, dtype=np.float32).reshape(8, 8))


def test_completion_propagates_shardings():
    pm = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    mesh = pm.jax_mesh()

    def f(x, w):
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("dp", None)))
        w = jax.lax.with_sharding_constraint(
            w, NamedSharding(mesh, P(None, "mp")))
        return jnp.dot(x, w)

    x = np.ones((16, 32), np.float32)
    w = np.ones((32, 64), np.float32)
    res = complete(f, x, w)
    # GSPMD keeps the row-sharded x and column-sharded w; the output of
    # (dp,·)x(·,mp) propagates to (dp, mp)
    assert res["outputs"][0] == ("dp", "mp")


def test_planner_regimes():
    cl = ClusterSpec()
    # tiny model → pure data parallel
    pm = plan_mesh(8, n_params=10_000_000, cluster=cl)
    sizes = dict(zip(pm.dim_names, pm.shape))
    assert sizes["dp"] == 8 and sizes["mp"] == 1 and sizes["sharding"] == 1
    # model whose replicated opt state overflows but params fit → ZeRO/mp split
    pm = plan_mesh(8, n_params=30_000_000_000, cluster=cl)
    sizes = dict(zip(pm.dim_names, pm.shape))
    assert sizes["sharding"] * sizes["mp"] > 1
    assert pm.size == 8
    # comm cost model sanity: allreduce cost grows with bytes, dp=1 free
    cm = CommCostModel(cl)
    assert cm.all_reduce(1 << 30, 8) > cm.all_reduce(1 << 20, 8)
    assert cm.all_reduce(1 << 30, 1) == 0.0


def _tiny_gpt(seed=42):
    from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2, num_heads=2,
                    max_seq_len=8, dropout=0.0)
    return GPTForCausalLM(cfg), cfg


def test_complete_param_specs_infers_megatron_layout():
    """Annotate only the column weights + embedding; completion must infer the
    row-parallel fc2 and the 'mp' biases through the traced graph (the
    dist_matmul rule run in reverse — reference completion.py fixpoint)."""
    from paddle_tpu.distributed.auto_parallel import complete_param_specs

    m, cfg = _tiny_gpt()
    for name, p in m.named_parameters():
        if name.endswith("qkv_proj.weight") or name.endswith("fc1.weight"):
            p._sharding_spec = (None, "mp")
        if name.endswith("wte.weight"):
            p._sharding_spec = ("mp", None)
    ids = np.random.randint(0, 64, (2, 8)).astype(np.int32)
    specs = complete_param_specs(m, [ids])
    got = {k: tuple(v) for k, v in specs.items()}
    for blk in (0, 1):
        assert got[f"gpt.blocks.{blk}.mlp.fc2.weight"] == ("mp", None)
        assert got[f"gpt.blocks.{blk}.mlp.fc1.bias"] == ("mp",)
        assert got[f"gpt.blocks.{blk}.attn.qkv_proj.bias"] == ("mp",)


def test_partitioner_validates_and_relaxes():
    from paddle_tpu.distributed.auto_parallel import Partitioner
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "mp"))
    part = Partitioner(mesh)
    # divisible: kept
    assert tuple(part.validate_spec((8, 16), (None, "mp"))) == (None, "mp")
    # non-divisible dim: relaxed to replicated, not an error
    assert tuple(part.validate_spec((8, 6), (None, "mp"))) == (None, None)
    # unknown axis: relaxed
    assert tuple(part.validate_spec((8, 16), (None, "nope"))) == (None, None)


def test_resharder_cross_spec_and_noop():
    from paddle_tpu.distributed.auto_parallel import Resharder
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()), ("x",))
    r = Resharder()
    t = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    moved = r.apply(t, NamedSharding(mesh, P("x", None)))
    assert r.log[-1][0] == "device_put"
    again = r.apply(moved, NamedSharding(mesh, P("x", None)))
    assert r.log[-1][0] == "noop"
    np.testing.assert_array_equal(np.asarray(again._value),
                                  np.arange(64, dtype=np.float32).reshape(8, 8))


@pytest.mark.slow
def test_engine_completion_matches_manual_megatron_loss():
    """VERDICT r3 done-criterion: Engine.fit with partial annotations +
    completion produces exactly the same losses as apply_megatron_specs."""
    from paddle_tpu.distributed.fleet.meta_parallel import apply_megatron_specs

    pm = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (4, 8)).astype(np.int32)
    batches = [(ids, ids)] * 3

    def lm_loss(logits, labels):
        return nn.functional.cross_entropy(
            logits.reshape([-1, 64]), labels.reshape([-1]).astype("int64"))

    def run(annotate):
        m, cfg = _tiny_gpt(seed=7)
        annotate(m)
        opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
        eng = Engine(model=m, loss=lm_loss, optimizer=opt, process_mesh=pm)
        eng.prepare(inputs_spec=[jax.ShapeDtypeStruct((4, 8), np.int32)])
        hist = eng.fit(batches, epochs=1, log_freq=1)
        return hist["loss"]

    losses_manual = run(lambda m: apply_megatron_specs(m))

    def partial_annotations(m):
        for name, p in m.named_parameters():
            if name.endswith("qkv_proj.weight") or name.endswith("fc1.weight"):
                p._sharding_spec = (None, "mp")
            if name.endswith("wte.weight"):
                p._sharding_spec = ("mp", None)

    losses_completed = run(partial_annotations)
    assert losses_manual == pytest.approx(losses_completed, rel=1e-6), (
        losses_manual, losses_completed)


def test_cost_model_xla_analysis_grounds_flops():
    """CompCostModel.analyze reads XLA's own cost analysis — verify against
    the known FLOP count of a matmul (2*m*n*k)."""
    from paddle_tpu.distributed.auto_parallel.cost_model import CompCostModel

    m, k, n = 64, 128, 32
    comp = CompCostModel()
    res = comp.analyze(lambda a, b: jnp.dot(a, b),
                       np.zeros((m, k), np.float32), np.zeros((k, n), np.float32))
    assert res["flops"] == pytest.approx(2 * m * k * n, rel=0.01)
    assert res["bytes_accessed"] > 0
    assert res["time"] > 0


def test_planner_time_estimates_monotonic():
    """estimate_step_time: compute shrinks with dp; mp layouts cost extra comm
    on a small model (the trade the planner arbitrates)."""
    from paddle_tpu.distributed.auto_parallel.cost_model import ClusterSpec
    from paddle_tpu.distributed.auto_parallel.planner import estimate_step_time

    cl = ClusterSpec()
    pb = 4e8  # 100M fp32 params
    sb = pb * 4
    flops = 6 * 1e8 * 1e6  # 1M tokens/step
    t_dp8, _ = estimate_step_time(8, 1, 1, pb, sb, flops, 0.0, cl)
    t_dp1, _ = estimate_step_time(1, 1, 1, pb, sb, flops, 0.0, cl)
    assert t_dp8 < t_dp1  # dp splits compute
    t_mp8, mem_mp8 = estimate_step_time(1, 1, 8, pb, sb, flops, 0.0, cl)
    _, mem_dp8 = estimate_step_time(8, 1, 1, pb, sb, flops, 0.0, cl)
    assert mem_mp8 < mem_dp8  # mp trades memory...
    assert t_mp8 > t_dp8  # ...for activation allreduce time on a small model
    # ZeRO computes at the same per-chip FLOPs as dp (batch splits over both)
    t_sh8, _ = estimate_step_time(1, 8, 1, pb, sb, flops, 0.0, cl)
    assert t_sh8 < t_mp8  # sharding beats mp on a compute-dominated step


@pytest.mark.slow  # re-tiered 2026-08 (PR 8): tier-1 crossed its 870 s budget on the 1-core box; --durations top mover
def test_engine_fit_evaluate_predict(tmp_path):
    paddle.seed(42)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))
    opt = paddle.optimizer.Adam(learning_rate=5e-3, parameters=model.parameters())
    engine = Engine(model=model, loss=nn.CrossEntropyLoss(), optimizer=opt,
                    metrics=paddle.metric.Accuracy())

    rng = np.random.RandomState(0)
    xs = rng.randn(64, 16).astype(np.float32)
    ys = (xs[:, :4].argmax(-1)).astype(np.int64)  # learnable mapping
    batches = [(xs[i:i + 16], ys[i:i + 16]) for i in range(0, 64, 16)]

    hist = engine.fit(batches, epochs=30, log_freq=10)
    assert hist["loss"][-1] < hist["loss"][0] * 0.7

    res = engine.evaluate(batches)
    assert res["loss"] < 1.0
    assert res["acc"] > 0.5

    preds = engine.predict([(xs[:16],)])
    assert preds[0][0].shape == (16, 4)

    engine.save(str(tmp_path / "m"))
    engine2 = Engine(model=model, loss=nn.CrossEntropyLoss(), optimizer=opt)
    engine2.load(str(tmp_path / "m"))
