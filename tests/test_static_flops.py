"""hapi.static_flops: Program-based FLOP counting (reference:
hapi/static_flops.py); paddle.flops dispatches Programs to it."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static


def test_static_flops_counts_program():
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 1, 28, 28], "float32")
            w = paddle.to_tensor(
                np.random.randn(6, 1, 5, 5).astype("float32"))
            h = paddle.nn.functional.conv2d(x, w, padding=2)   # 2*6*28*28 out
            h = paddle.nn.functional.relu(h)
            h = paddle.flatten(h, 1)
            w2 = paddle.to_tensor(
                np.random.randn(6 * 28 * 28, 10).astype("float32") * 0.01)
            y = paddle.nn.functional.linear(h, w2)  # noqa: F841
        total = paddle.flops(main)
        conv_macs = (2 * 6 * 28 * 28) * (1 * 5 * 5)
        lin_macs = (2 * 10) * (6 * 28 * 28)
        relu = 2 * 6 * 28 * 28
        assert total == conv_macs + lin_macs + relu
        # print_detail path works
        assert paddle.flops(main, print_detail=True) == total
    finally:
        paddle.disable_static()


def test_flops_dynamic_still_works():
    from paddle_tpu.vision.models import LeNet

    n = paddle.flops(LeNet(), [1, 1, 28, 28])
    assert n > 0
