"""Functional/higher-order autodiff tests
(reference analog: tests/unittests/autograd/test_jvp_and_transpose.py etc.)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.incubate import autograd as A


def test_jvp_matches_finite_difference():
    def f(x):
        return paddle.sum(paddle.tanh(x) ** 2)

    x = paddle.to_tensor(np.array([0.3, -0.7, 1.2], np.float64))
    v = paddle.to_tensor(np.array([1.0, 0.5, -0.2], np.float64))
    _, tan = A.jvp(f, x, v)
    eps = 1e-6
    fd = (float(f(paddle.to_tensor(x.numpy() + eps * v.numpy())).numpy())
          - float(f(paddle.to_tensor(x.numpy() - eps * v.numpy())).numpy())) / (2 * eps)
    np.testing.assert_allclose(float(tan.numpy()), fd, rtol=1e-6)


def test_vjp_matches_backward():
    def f(x):
        return paddle.sum(x * x * x)

    xv = np.array([1.0, 2.0, 3.0], np.float64)
    _, g = A.vjp(f, paddle.to_tensor(xv))
    np.testing.assert_allclose(g.numpy(), 3 * xv ** 2, rtol=1e-10)


def test_jacobian_full_matrix():
    def f(x):
        return paddle.matmul(paddle.to_tensor(W), x)

    W = np.random.RandomState(0).randn(3, 4)
    x = paddle.to_tensor(np.random.RandomState(1).randn(4))
    J = A.Jacobian(f, x)
    assert J.shape == (3, 4)
    np.testing.assert_allclose(J.numpy(), W, rtol=1e-10)
    np.testing.assert_allclose(J[0].numpy(), W[0], rtol=1e-10)


def test_hessian_quadratic():
    Q = np.array([[2.0, 1.0], [1.0, 4.0]])

    def f(x):
        return 0.5 * paddle.sum(x * paddle.matmul(paddle.to_tensor(Q), x))

    x = paddle.to_tensor(np.array([0.5, -1.0]))
    H = A.Hessian(f, x)
    np.testing.assert_allclose(H.numpy(), Q, rtol=1e-8)


def test_multi_input_jacobian():
    def f(x, y):
        return x * y

    x = paddle.to_tensor(np.array([1.0, 2.0]))
    y = paddle.to_tensor(np.array([3.0, 4.0]))
    J = A.Jacobian(f, [x, y])
    expect = np.block([[np.diag([3.0, 4.0]), np.diag([1.0, 2.0])]])
    np.testing.assert_allclose(J.numpy(), expect, rtol=1e-10)


def test_prim_toggles():
    assert A.prim_enabled()
    A.disable_prim()
    assert not A.prim_enabled()
    A.enable_prim()
    assert A.prim_enabled()


def test_jacobian_is_batched():
    """reference semantics: leading dim excluded from differentiation."""
    import paddle_tpu as paddle
    from paddle_tpu.incubate.autograd import Jacobian

    x = paddle.to_tensor(np.random.rand(3, 4).astype("float32"))
    J = Jacobian(lambda x: x * x, x, is_batched=True)
    assert J.shape == (3, 4, 4)
    for b in range(3):
        expect = np.diag(2 * x.numpy()[b])
        np.testing.assert_allclose(J[b].numpy(), expect, rtol=1e-5)


def test_hessian_is_batched():
    import paddle_tpu as paddle
    from paddle_tpu.incubate.autograd import Hessian

    x = paddle.to_tensor(np.random.rand(2, 3).astype("float32"))
    H = Hessian(lambda x: (x * x).sum(), x, is_batched=True)
    assert H.shape == (2, 3, 3)
    np.testing.assert_allclose(H[0].numpy(), 2 * np.eye(3), atol=1e-5)
