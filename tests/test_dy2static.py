"""dy2static AST conversion tests (reference: dygraph_to_static —
ifelse_transformer.py / loop_transformer.py unittests pattern: same function,
python semantics vs converted-and-traced semantics must agree)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.dy2static import convert_control_flow


def test_tensor_if_both_signs():
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    g = convert_control_flow(f)
    pos = np.array([1.0, 2.0], np.float32)
    neg = np.array([-3.0, 1.0], np.float32)
    np.testing.assert_allclose(g(Tensor(pos)).numpy(), pos * 2)
    np.testing.assert_allclose(g(Tensor(neg)).numpy(), neg - 1)


def test_tensor_if_under_jit_tracing():
    import jax

    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    g = convert_control_flow(f)

    @jax.jit
    def traced(arr):
        from paddle_tpu.core import tape

        with tape.no_grad():
            return g(Tensor(arr))._value

    pos = np.array([1.0, 2.0], np.float32)
    neg = np.array([-3.0, 1.0], np.float32)
    np.testing.assert_allclose(np.asarray(traced(pos)), pos * 2)
    np.testing.assert_allclose(np.asarray(traced(neg)), neg - 1)  # same jit!


def test_python_if_untouched():
    def f(x, flag):
        if flag:
            return x + 1.0
        return x - 1.0

    g = convert_control_flow(f)
    x = Tensor(np.zeros(2, np.float32))
    np.testing.assert_allclose(g(x, True).numpy(), 1.0)
    np.testing.assert_allclose(g(x, False).numpy(), -1.0)


def test_var_assigned_one_branch_raises():
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            z = x  # noqa: F841 — y missing on this branch
        return y  # noqa: F821

    g = convert_control_flow(f)
    with pytest.raises(NameError, match="only one branch"):
        g(Tensor(np.ones(2, np.float32)))


def test_tensor_while_loop():
    def f(x):
        s = x * 0.0 + 1.0
        n = x * 0.0
        while (s < 100.0).all():
            s = s * 2.0
            n = n + 1.0
        return s, n

    g = convert_control_flow(f)
    s, n = g(Tensor(np.ones((), np.float32)))
    assert float(s) == 128.0 and float(n) == 7.0

    # python-int while untouched
    def h(x, k):
        while k > 0:
            x = x + 1.0
            k -= 1
        return x

    hh = convert_control_flow(h)
    assert float(hh(Tensor(np.zeros((), np.float32)), 3)) == 3.0


def test_for_range_conversion_python_and_tensor_bounds():
    def g(x, n):
        acc = x * 0.0
        for i in range(n):
            acc = acc + 1.0
        return acc

    cg = convert_control_flow(g)
    # python int bound: unchanged semantics
    assert float(cg(Tensor(np.zeros((), np.float32)), 4)) == 4.0
    # tensor bound under one jit trace
    import jax

    @jax.jit
    def traced(n_arr):
        from paddle_tpu.core import tape

        with tape.no_grad():
            return cg(Tensor(np.zeros((), np.float32)), Tensor(n_arr))._value

    assert float(np.asarray(traced(np.int32(5)))) == 5.0
    assert float(np.asarray(traced(np.int32(2)))) == 2.0  # same compiled fn

    # range(start, stop) + loop-var use inside the body
    def h(x, n):
        s = x * 0.0
        for i in range(1, n):
            s = s + i
        return s

    ch = convert_control_flow(h)
    assert float(ch(Tensor(np.zeros((), np.float32)), 4)) == 6.0  # 1+2+3

    # negative literal step stays python-correct
    def k(x):
        s = x * 0.0
        for i in range(3, 0, -1):
            s = s + i
        return s

    assert float(convert_control_flow(k)(Tensor(np.zeros((), np.float32)))) == 6.0


def test_for_range_python_edge_semantics():
    """Zero-iteration ranges keep python semantics (loop var untouched) and
    range args evaluate exactly once."""
    def f(x, i):
        for i in range(5, 5):
            x = x + 1.0
        return x, i

    cf = convert_control_flow(f)
    out, i = cf(Tensor(np.zeros((), np.float32)), 99)
    assert float(out) == 0.0 and i == 99  # untaken loop leaves i alone

    calls = []

    def side(v):
        calls.append(v)
        return v

    def g(x, n):
        s = x * 0.0
        for i in range(side(1), n):
            s = s + i
        return s

    cg = convert_control_flow(g)
    assert float(cg(Tensor(np.zeros((), np.float32)), 4)) == 6.0
    assert calls == [1], calls  # start expression evaluated once


def test_closure_and_globals_survive():
    scale = 3.0

    def outer():
        offset = 10.0

        def f(x):
            if x.sum() > 0:
                y = x * scale + offset
            else:
                y = x * scale - offset
            return y

        return f

    g = convert_control_flow(outer())
    np.testing.assert_allclose(
        g(Tensor(np.ones(2, np.float32))).numpy(), 13.0)
    np.testing.assert_allclose(
        g(Tensor(-np.ones(2, np.float32))).numpy(), -13.0)


def test_while_with_body_temp_variable():
    """A temp assigned only inside a tensor-while recomputes per iteration
    (not loop-carried) and is unbound after the loop."""
    def f(x):
        s = x * 0.0
        while (s < 5.0).all():
            t = x * 1.0  # body-local temp, no pre-loop init
            s = s + t
        return s

    g = convert_control_flow(f)
    assert float(g(Tensor(np.ones((), np.float32)))) == 5.0

    def h(x):
        s = x * 0.0
        while (s < 3.0).all():
            t = x * 1.0
            s = s + t
        return t  # read after the loop: must fail loudly, not return garbage

    gh = convert_control_flow(h)
    with pytest.raises((NameError, UnboundLocalError)):
        gh(Tensor(np.ones((), np.float32)))


def test_nested_tensor_ifs_convert():
    """Inner transforms synthesize returns; the outer if must still convert
    (regression: _has_flow_escape used to see them and bail)."""
    import jax

    def f(x):
        if x.sum() > 0.0:
            if x.max() > 10.0:
                y = x * 100.0
            else:
                y = x * 2.0
        else:
            y = x - 1.0
        return y

    g = convert_control_flow(f)

    @jax.jit
    def traced(arr):
        from paddle_tpu.core import tape

        with tape.no_grad():
            return g(Tensor(arr))._value

    np.testing.assert_allclose(np.asarray(traced(np.array([20.0], np.float32))), 2000.0)
    np.testing.assert_allclose(np.asarray(traced(np.array([2.0], np.float32))), 4.0)
    np.testing.assert_allclose(np.asarray(traced(np.array([-2.0], np.float32))), -3.0)


def test_python_untaken_branch_var_stays_unbound():
    def f(x, flag):
        if flag:
            y = x * 2.0
        else:
            z = x  # noqa: F841
        return y  # noqa: F821

    g = convert_control_flow(f)
    np.testing.assert_allclose(
        g(Tensor(np.ones(2, np.float32)), True).numpy(), 2.0)
    with pytest.raises((NameError, UnboundLocalError)):
        g(Tensor(np.ones(2, np.float32)), False)


def test_to_static_layer_with_convert_flag():
    from paddle_tpu import nn

    class Gate(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(2, 2)

        def forward(self, x):
            h = self.fc(x)
            if h.sum() > 0:
                out = h * 2.0
            else:
                out = h * -1.0
            return out

    paddle.seed(0)
    layer = Gate()
    paddle.jit.to_static(layer, convert_control_flow=True)
    x = paddle.to_tensor(np.ones((1, 2), np.float32))
    ref = layer.fc(x)
    expected = ref.numpy() * (2.0 if ref.numpy().sum() > 0 else -1.0)
    np.testing.assert_allclose(layer.forward_traced(x).numpy(), expected,
                               rtol=1e-6)


def test_to_static_with_convert_flag():
    @paddle.jit.to_static(convert_control_flow=True)
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x * -1.0
        return y

    pos = paddle.to_tensor(np.array([2.0], np.float32))
    neg = paddle.to_tensor(np.array([-2.0], np.float32))
    np.testing.assert_allclose(f(pos).numpy(), 4.0)
    np.testing.assert_allclose(f(neg).numpy(), 2.0)


def test_return_inside_branch_left_as_python_if():
    """A branch containing return is left untransformed: python-predicate use
    keeps working, and eager tensor predicates still work via concrete bool
    (only jit tracing of such a function fails, with jax's tracer error)."""
    def f(x, flag):
        if flag:
            return x * 2.0
        return x

    g = convert_control_flow(f)
    x = Tensor(np.array([3.0], np.float32))
    np.testing.assert_allclose(g(x, True).numpy(), 6.0)
    np.testing.assert_allclose(g(x, False).numpy(), 3.0)

    def h(x):
        if x.sum() > 0:
            return x * 2.0
        return x

    gh = convert_control_flow(h)  # conversion succeeds; if left in place
    np.testing.assert_allclose(gh(x).numpy(), 6.0)  # eager concrete bool ok

# --- tensor-dependent break/continue (reference:
# dygraph_to_static/break_continue_transformer.py) -------------------------

def _src_fn(code, name):
    """Compile from a real file so inspect.getsource works."""
    import tempfile, importlib.util, os, sys

    f = tempfile.NamedTemporaryFile("w", suffix=".py", delete=False)
    f.write(code)
    f.close()
    spec = importlib.util.spec_from_file_location("d2s_bc_mod_" + name, f.name)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return getattr(mod, name), f.name


_BC_CODE = """
import paddle_tpu as paddle


def f_break(x):
    s = paddle.zeros([], 'float32')
    for i in range(5):
        if s > 2.5:
            break
        s = s + paddle.sum(x)
    return s


def f_continue(x):
    s = paddle.zeros([], 'float32')
    for i in range(4):
        if paddle.sum(x) * float(i) == 3.0:
            continue
        s = s + 1.0
    return s


def f_while_break(x):
    s = paddle.zeros([], 'float32')
    n = paddle.zeros([], 'int32')
    while n < 100:
        s = s + paddle.sum(x)
        n = n + 1
        if s > 7.0:
            break
    return s, n


def f_python_break(x):
    s = 0.0
    for i in range(10):
        if i == 3:
            break
        s = s + 1.0
    return paddle.to_tensor(__import__('numpy').float32(s)) + paddle.sum(x) * 0
"""


def test_tensor_break_in_for_range():
    import os

    fn, path = _src_fn(_BC_CODE, "f_break")
    try:
        x = paddle.to_tensor(np.ones(3, "float32"))
        out = paddle.jit.to_static(fn)(x)
        assert float(out.numpy()) == 3.0  # stops once s > 2.5
    finally:
        os.unlink(path)


def test_tensor_continue_in_for_range():
    import os

    fn, path = _src_fn(_BC_CODE, "f_continue")
    try:
        x = paddle.to_tensor(np.ones(3, "float32"))
        out = paddle.jit.to_static(fn)(x)
        assert float(out.numpy()) == 3.0  # i==1 skipped
    finally:
        os.unlink(path)


def test_tensor_break_in_while():
    import os

    fn, path = _src_fn(_BC_CODE, "f_while_break")
    try:
        x = paddle.to_tensor(np.ones(3, "float32"))
        s, n = paddle.jit.to_static(fn)(x)
        assert float(s.numpy()) == 9.0 and int(n.numpy()) == 3
    finally:
        os.unlink(path)


def test_python_break_semantics_preserved():
    import os

    fn, path = _src_fn(_BC_CODE, "f_python_break")
    try:
        x = paddle.to_tensor(np.ones(3, "float32"))
        out = paddle.jit.to_static(fn)(x)
        assert float(out.numpy()) == 3.0
    finally:
        os.unlink(path)


_WITH_BREAK_CODE = """
import paddle_tpu as paddle


def f_with_break(x):
    s = paddle.zeros([], 'float32')
    for i in range(5):
        with paddle.no_grad():
            if s > 2.5:
                break
        s = s + paddle.sum(x)
    return s
"""


def test_tensor_break_inside_with_block():
    """Code-review regression (reproduced): break under `with`
    (no_grad/auto_cast) must convert like a bare break."""
    import os

    fn, path = _src_fn(_WITH_BREAK_CODE, "f_with_break")
    try:
        x = paddle.to_tensor(np.ones(3, "float32"))
        out = paddle.jit.to_static(fn)(x)
        assert float(out.numpy()) == 3.0
    finally:
        os.unlink(path)
