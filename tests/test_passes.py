"""Pass framework: pre/post program diff tests (reference pattern:
dist_pass_test_base.py — apply the pass, compare program structure AND
numerics against the un-passed program)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.static.passes import PassManager, new_pass


@pytest.fixture(autouse=True)
def _static_mode():
    static.enable_static()
    yield
    static.disable_static()


def _mlp_program(seed=5):
    paddle.seed(seed)
    rng = np.random.RandomState(seed)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 8])
        w1 = paddle.to_tensor(rng.rand(8, 16).astype("float32"))
        b1 = paddle.to_tensor(rng.rand(16).astype("float32"))
        w2 = paddle.to_tensor(rng.rand(16, 4).astype("float32"))
        b2 = paddle.to_tensor(rng.rand(4).astype("float32"))
        h = paddle.nn.functional.relu(paddle.matmul(x, w1) + b1)
        out = paddle.matmul(h, w2) + b2
    return prog, out


def test_new_pass_registry():
    p = new_pass("fuse_gemm_epilogue")
    assert p.name == "fuse_gemm_epilogue"
    with pytest.raises(ValueError):
        new_pass("no_such_pass")


def test_fuse_gemm_epilogue_rewrites_and_matches():
    prog, out = _mlp_program()
    x = np.random.rand(4, 8).astype("float32")
    exe = static.Executor()
    (before,) = exe.run(prog, feed={"x": x}, fetch_list=[out])

    types_before = [op.type for op in prog.global_block.ops]
    assert types_before == ["matmul", "add", "relu", "matmul", "add"]

    ctx = new_pass("fuse_gemm_epilogue").apply(prog)
    types_after = [op.type for op in prog.global_block.ops]
    # matmul+add+relu -> one op; trailing matmul+add -> one op
    assert types_after == ["fused_gemm_epilogue", "fused_gemm_epilogue"]
    assert ctx.attrs["fused_gemm_epilogue"] == 2
    assert prog.global_block.ops[0].attrs["epilogue"] == "relu"
    assert prog.global_block.ops[1].attrs["epilogue"] == "bias"

    exe2 = static.Executor()
    (after,) = exe2.run(prog, feed={"x": x}, fetch_list=[out])
    np.testing.assert_allclose(before, after, rtol=1e-6)


def test_fuse_skips_multi_use_outputs():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4])
        w = paddle.to_tensor(np.random.rand(4, 4).astype("float32"))
        b = paddle.to_tensor(np.random.rand(4).astype("float32"))
        y = paddle.matmul(x, w)
        z1 = y + b
        z2 = y * 2.0  # second consumer of the matmul output: fusion illegal
    new_pass("fuse_gemm_epilogue").apply(prog)
    assert [op.type for op in prog.global_block.ops][0] == "matmul"


def test_amp_o2_pass_bf16_compute_fp32_master():
    prog, out = _mlp_program()
    x = np.random.rand(4, 8).astype("float32")
    exe = static.Executor()
    (before,) = exe.run(prog, feed={"x": x}, fetch_list=[out])

    ctx = new_pass("auto_mixed_precision").apply(prog)
    assert ctx.attrs["amp_dtype"] == "bfloat16"
    mm_ops = [op for op in prog.global_block.ops if op.type == "matmul"]
    assert all(op.attrs.get("amp") == "bf16" for op in mm_ops)

    exe2 = static.Executor()
    (after,) = exe2.run(prog, feed={"x": x}, fetch_list=[out])
    # bf16 matmuls: close but not identical
    np.testing.assert_allclose(before, after, rtol=2e-2, atol=2e-2)
    assert not np.allclose(before, after, rtol=1e-7, atol=1e-7)
    # master weights untouched (fp32 on the captured params)
    for p in prog.captured_params():
        assert str(p._value.dtype) == "float32"


def test_amp_training_keeps_master_weights_fp32():
    """One minimize step through the AMP-passed program: params update in fp32."""
    paddle.seed(9)
    rng = np.random.RandomState(9)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [8, 4])
        label = static.data("label", [8], "int64")
        w = paddle.to_tensor(rng.rand(4, 3).astype("float32"), stop_gradient=False)
        b = paddle.to_tensor(np.zeros(3, "float32"), stop_gradient=False)
        logits = paddle.matmul(x, w) + b
        loss = paddle.nn.functional.cross_entropy(logits, label)
        opt = paddle.optimizer.SGD(learning_rate=0.5)
        opt.minimize(loss)
    new_pass("auto_mixed_precision").apply(prog)
    w0 = np.asarray(w._value).copy()
    exe = static.Executor()
    (lv,) = exe.run(prog, feed={"x": rng.rand(8, 4).astype("float32"),
                                "label": rng.randint(0, 3, (8,))},
                    fetch_list=[loss])
    assert np.isfinite(lv).all()
    assert str(w._value.dtype) == "float32"
    assert not np.allclose(np.asarray(w._value), w0)  # actually trained


def test_pass_manager_ordering():
    prog, out = _mlp_program()
    pm = PassManager([new_pass("fuse_gemm_epilogue"),
                      new_pass("auto_mixed_precision")])
    ctx = pm.apply(prog)
    assert ctx.attrs["applied_passes"] == ["fuse_gemm_epilogue",
                                           "auto_mixed_precision"]
    # fused ops picked up by the AMP whitelist
    assert all(op.attrs.get("amp") == "bf16"
               for op in prog.global_block.ops
               if op.type == "fused_gemm_epilogue")


def test_static_amp_namespace():
    """paddle.static.amp (reference static/amp/__init__.py re-exports):
    decorate(O1/O2), lists, guards, cast helpers route through the
    registered AMP passes."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import static

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("sx", [8, 6], "float32")
            y = static.data("sy", [8], "int64")
            net = paddle.nn.Sequential(paddle.nn.Linear(6, 16),
                                       paddle.nn.ReLU(),
                                       paddle.nn.Linear(16, 4))
            loss = paddle.nn.functional.cross_entropy(net(x), y)
            opt = static.amp.decorate(paddle.optimizer.SGD(0.1),
                                      use_pure_fp16=True, use_bf16=True)
            opt.minimize(loss)
        exe = static.Executor()
        rng = np.random.RandomState(0)
        feed = {"sx": rng.rand(8, 6).astype("float32"),
                "sy": rng.randint(0, 4, (8,)).astype("int64")}
        losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
                  for _ in range(4)]
        assert losses[-1] < losses[0]
        assert opt.get_loss_scaling() > 0
        with static.amp.fp16_guard():
            pass
        with static.amp.bf16.bf16_guard():
            pass
        lists = static.amp.AutoMixedPrecisionLists(
            custom_white_list=["gelu"])
        assert "gelu" in lists.white_list and "matmul" in lists.white_list
    finally:
        paddle.disable_static()


def test_fuse_attention_pattern():
    """fuse_attention: hand-rolled QK^T -> scale -> softmax -> .V collapses
    to one fused_attention node with identical numerics (reference
    fused_attention_op contract at the program level)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.static.passes import new_pass

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            q = static.data("aq", [2, 4, 8, 16], "float32")
            k = static.data("ak", [2, 4, 8, 16], "float32")
            v = static.data("av", [2, 4, 8, 16], "float32")
            scores = paddle.matmul(q, k, transpose_y=True) * 0.25
            probs = paddle.nn.functional.softmax(scores, axis=-1)
            out = paddle.matmul(probs, v)
        exe = static.Executor()
        rng = np.random.RandomState(0)
        feed = {n: rng.rand(2, 4, 8, 16).astype("float32")
                for n in ("aq", "ak", "av")}
        before = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])

        new_pass("fuse_attention").apply(main)
        types = [op.type for op in main.global_block.ops]
        assert "fused_attention" in types, types
        assert not any(t.split("/")[-1] == "softmax" for t in types)
        after = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])
        np.testing.assert_allclose(after, before, rtol=1e-6)
    finally:
        paddle.disable_static()


def test_fuse_feedforward_pattern():
    """fuse_feedforward: linear -> gelu -> linear collapses to one node,
    numerics preserved (reference fused_feedforward_op)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.static.passes import new_pass

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("ffx", [4, 16], "float32")
            h = paddle.nn.Linear(16, 64)(x)
            h = paddle.nn.functional.gelu(h)
            out = paddle.nn.Linear(64, 16)(h)
        exe = static.Executor()
        rng = np.random.RandomState(1)
        feed = {"ffx": rng.rand(4, 16).astype("float32")}
        before = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])

        new_pass("fuse_feedforward").apply(main)
        types = [op.type for op in main.global_block.ops]
        assert "fused_feedforward" in types, types
        after = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])
        np.testing.assert_allclose(after, before, rtol=1e-6)
    finally:
        paddle.disable_static()


def test_build_strategy_applies_fusion_passes():
    """reference: build_strategy.fuse_gemm_epilogue -> the pass actually
    runs when the program is wrapped in CompiledProgram."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import static

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("bsx", [4, 8], "float32")
            out = paddle.nn.functional.relu(
                paddle.matmul(x, paddle.ones([8, 8])) + 1.0)
        bs = static.BuildStrategy()
        bs.fuse_gemm_epilogue = True
        compiled = static.CompiledProgram(main, build_strategy=bs)
        assert any(op.type == "fused_gemm_epilogue"
                   for op in main.global_block.ops)
        exe = static.Executor()
        res = exe.run(compiled, feed={"bsx": np.ones((4, 8), "float32")},
                      fetch_list=[out])[0]
        np.testing.assert_allclose(np.asarray(res), np.full((4, 8), 9.0))
    finally:
        paddle.disable_static()


def test_pass_after_run_invalidates_executor_cache():
    """A pass applied AFTER the program has executed must recompile on the
    next run — the reference workflow (exe.run(startup); ...; apply pass;
    exe.run(main)) silently hit the stale pre-pass computation before the
    program-version cache key. Observable: square(x+300) is finite in
    fp32, inf once the fp16 pass casts it."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.distributed.passes import new_pass

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("cx", [4, 4], "float32")
            out = paddle.square(x + 300.0)
        exe = static.Executor()
        feed = {"cx": np.zeros((4, 4), "float32")}
        r1 = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])
        assert np.all(np.isfinite(r1))  # fp32: 9e4 fits

        # warm a CLONE alias too: it shares the tape, so the pass applied
        # through `main` must also invalidate the clone's cached runner
        test_prog = main.clone(for_test=True)
        rc1 = np.asarray(exe.run(test_prog, feed=feed, fetch_list=[out])[0])
        assert np.all(np.isfinite(rc1))

        new_pass("auto_parallel_fp16",
                 {"use_dynamic_loss_scaling": False}).apply(main)
        r2 = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])
        assert np.all(np.isinf(r2)), (
            "executor served the stale pre-pass computation: "
            f"{r2[0, 0]} (expected fp16 overflow -> inf)")
        rc2 = np.asarray(exe.run(test_prog, feed=feed, fetch_list=[out])[0])
        assert np.all(np.isinf(rc2)), "clone alias served stale computation"
        # stale pre-pass runners are evicted, not stranded
        assert all(k[1] >= 1 for k in exe._cache if k[0] ==
                   exe._program_serial(main))
    finally:
        paddle.disable_static()


def test_fusion_preserves_scope_attrs():
    """Pass composition: chain fusion must not strip the attrs OTHER passes
    consume — a fused op losing its device tag would land in the wrong
    pipeline stage; losing in_fp16_guard silently un-casts a guarded
    region. Tags propagate only when every fused part agrees."""
    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.static.passes import new_pass

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("fsx", [4, 16], "float32")
            with static.device_guard("tpu:1"), static.amp.fp16_guard():
                h = paddle.nn.Linear(16, 32)(x)
                h = paddle.nn.functional.gelu(h)
                out = paddle.nn.Linear(32, 8)(h)
        new_pass("fuse_feedforward").apply(main)
        fused = [op for op in main.global_block.ops
                 if op.type == "fused_feedforward"]
        assert fused, [op.type for op in main.global_block.ops]
        assert fused[0].attrs.get("device") == "tpu:1"
        assert fused[0].attrs.get("in_fp16_guard") is True

        # a chain spanning two stages REFUSES to fuse — an untagged fused op
        # would erase the pipeline cut (the splitter re-stages untagged ops)
        main2, startup2 = static.Program(), static.Program()
        with static.program_guard(main2, startup2):
            x2 = static.data("fsy", [4, 16], "float32")
            with static.device_guard("tpu:0"):
                h2 = paddle.nn.Linear(16, 32)(x2)
                h2 = paddle.nn.functional.gelu(h2)
            with static.device_guard("tpu:1"):
                out2 = paddle.nn.Linear(32, 8)(h2)
        new_pass("fuse_feedforward").apply(main2)
        types2 = [op.type for op in main2.global_block.ops]
        assert "fused_feedforward" not in types2, types2
    finally:
        paddle.disable_static()


def test_fp16_guard_region_scoped_o2():
    """reference fp16_utils.py:352 (_need_keep_fp32): with use_fp16_guard,
    ONLY ops inside fp16_guard() cast to fp16 — a numerically fragile op
    OUTSIDE the guard keeps fp32 and must not overflow. square((h+300)) is
    ~9e4 > fp16 max 65504: inf if cast, finite when the guard is honored."""
    import warnings

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import static

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("fx", [8, 6], "float32")
            net = paddle.nn.Linear(6, 16)
            with static.amp.fp16_guard():
                h = net(x)
            fragile = paddle.square(h + 300.0)
        static.amp.cast_model_to_fp16(main, use_fp16_guard=True)

        guarded = [op for op in main.global_block.ops
                   if op.attrs.get("in_fp16_guard")]
        assert guarded, "guard scope marked no ops"
        assert any(op.attrs.get("amp") == "float16" for op in guarded)
        sq = [op for op in main.global_block.ops if "square" in op.type]
        assert sq and all(op.attrs.get("amp") == "fp32" for op in sq)

        exe = static.Executor()
        out = exe.run(main, feed={"fx": np.random.RandomState(0)
                                  .rand(8, 6).astype("float32")},
                      fetch_list=[fragile])[0]
        assert np.all(np.isfinite(np.asarray(out))), \
            "fragile region outside fp16_guard overflowed — guard not honored"

        # guard flag on, but nothing guarded -> loud warning, program stays fp32
        main2, startup2 = static.Program(), static.Program()
        with static.program_guard(main2, startup2):
            x2 = static.data("fx2", [4, 6], "float32")
            _ = paddle.nn.Linear(6, 8)(x2)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            static.amp.cast_model_to_fp16(main2, use_fp16_guard=True)
        assert any("no op was" in str(x.message).lower()
                   or "fp16_guard" in str(x.message) for x in w)
        assert all(op.attrs.get("amp") != "float16"
                   for op in main2.global_block.ops)
    finally:
        paddle.disable_static()
