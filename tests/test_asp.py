"""ASP 2:4 structured-sparsity tests incl. the training-loop integration the
round-2 verdict flagged as missing (reference: python/paddle/incubate/asp/
+ test_asp_optimize.py — prune, decorate the optimizer, train, and the mask
must survive every update).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate import asp


@pytest.fixture(autouse=True)
def _clean_masks():
    asp.reset_excluded_layers()
    yield
    asp.reset_excluded_layers()


def test_mask_2_4_pattern():
    w = np.arange(1, 17, dtype=np.float32).reshape(4, 4)
    mask = asp.compute_mask_2_4(w)
    assert mask.sum() == 8  # exactly 2 of every 4 kept
    assert (mask.reshape(-1, 4).sum(axis=1) == 2).all()
    # keeps the largest-|w| pair
    assert mask[0].tolist() == [False, False, True, True]


def test_asp_training_loop_preserves_sparsity():
    paddle.seed(77)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    asp.prune_model(model)
    opt = asp.decorate(
        paddle.optimizer.Adam(5e-3, parameters=model.parameters()))

    # pruning actually zeroed half of each 2D weight
    for p in model.parameters():
        if p.ndim == 2:
            w = p.numpy()
            assert (np.abs(w.reshape(-1, 4)) > 0).sum(axis=1).max() <= 2

    rng = np.random.RandomState(0)
    xs = rng.randn(64, 16).astype(np.float32)
    ys = xs[:, :4].argmax(-1)
    losses = []
    for step in range(30):
        i = (step * 16) % 64
        x = paddle.to_tensor(xs[i:i + 16])
        y = paddle.to_tensor(ys[i:i + 16])
        loss = nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
        # the 2:4 mask survives EVERY optimizer step (reference
        # OptimizerWithSparsityGuarantee semantics)
        for p in model.parameters():
            if p.ndim == 2:
                nz = (np.abs(p.numpy().reshape(-1, 4)) > 0).sum(axis=1)
                assert nz.max() <= 2, f"step {step}: mask violated"
    assert losses[-1] < losses[0], losses
