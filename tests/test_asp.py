"""ASP 2:4 structured-sparsity tests incl. the training-loop integration the
round-2 verdict flagged as missing (reference: python/paddle/incubate/asp/
+ test_asp_optimize.py — prune, decorate the optimizer, train, and the mask
must survive every update).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate import asp


@pytest.fixture(autouse=True)
def _clean_masks():
    asp.reset_excluded_layers()
    yield
    asp.reset_excluded_layers()


def test_mask_2_4_pattern():
    w = np.arange(1, 17, dtype=np.float32).reshape(4, 4)
    mask = asp.compute_mask_2_4(w)
    assert mask.sum() == 8  # exactly 2 of every 4 kept
    assert (mask.reshape(-1, 4).sum(axis=1) == 2).all()
    # keeps the largest-|w| pair
    assert mask[0].tolist() == [False, False, True, True]


@pytest.mark.slow
def test_asp_training_loop_preserves_sparsity():
    paddle.seed(77)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    asp.prune_model(model)
    opt = asp.decorate(
        paddle.optimizer.Adam(5e-3, parameters=model.parameters()))

    # pruning actually zeroed half of each 2D weight
    for p in model.parameters():
        if p.ndim == 2:
            w = p.numpy()
            assert (np.abs(w.reshape(-1, 4)) > 0).sum(axis=1).max() <= 2

    rng = np.random.RandomState(0)
    xs = rng.randn(64, 16).astype(np.float32)
    ys = xs[:, :4].argmax(-1)
    losses = []
    for step in range(30):
        i = (step * 16) % 64
        x = paddle.to_tensor(xs[i:i + 16])
        y = paddle.to_tensor(ys[i:i + 16])
        loss = nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
        # the 2:4 mask survives EVERY optimizer step (reference
        # OptimizerWithSparsityGuarantee semantics)
        for p in model.parameters():
            if p.ndim == 2:
                nz = (np.abs(p.numpy().reshape(-1, 4)) > 0).sum(axis=1)
                assert nz.max() <= 2, f"step {step}: mask violated"
    assert losses[-1] < losses[0], losses


def test_mask_2d_algorithms_satisfy_row_and_col_constraints():
    rng = np.random.RandomState(0)
    w = rng.randn(8, 12).astype(np.float32)
    for algo in (asp.MaskAlgo.MASK_2D_GREEDY, asp.MaskAlgo.MASK_2D_BEST):
        mask = asp.create_mask(w, func_name=algo, n=2, m=4)
        assert asp.check_sparsity(w * mask, asp.CheckMethod.CHECK_2D, 2, 4)
        assert asp.calculate_density(w * mask) == pytest.approx(0.5, abs=1e-6)
    # best >= greedy in retained magnitude (its defining property)
    g = asp.create_mask(w, asp.MaskAlgo.MASK_2D_GREEDY, 2, 4)
    b = asp.create_mask(w, asp.MaskAlgo.MASK_2D_BEST, 2, 4)
    assert np.abs(w * b).sum() >= np.abs(w * g).sum() - 1e-6


def test_general_n_m_and_check_methods():
    rng = np.random.RandomState(1)
    w = rng.randn(4, 16).astype(np.float32)
    mask = asp.get_mask_1d(w, 1, 4)  # 1:4
    assert asp.check_mask_1d(w * mask, 1, 4)
    assert not asp.check_mask_1d(w, 1, 4)  # dense fails
    assert asp.CheckMethod.get_checking_method(
        asp.MaskAlgo.MASK_2D_BEST) == asp.CheckMethod.CHECK_2D


def test_excluded_layers_skip_pruning():
    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
    names = [n for n, _ in m.named_parameters()]
    asp.set_excluded_layers(param_names=[names[0]], model=m)
    asp.prune_model(m)
    w0 = m.sublayers()[0].weight.numpy() if hasattr(m.sublayers()[0], "weight") else None
    p0 = dict(m.named_parameters())[names[0]]
    assert asp.calculate_density(p0.numpy()) == 1.0  # untouched
    p2 = dict(m.named_parameters())[names[2]]
    assert asp.calculate_density(p2.numpy()) == pytest.approx(0.5, abs=0.01)
    asp.reset_excluded_layers()
