"""LoDTensor-lite / RaggedTensor (SURVEY §2.1 #30 — the ragged type that
closes the LoD round-trip; reference fluid/lod_tensor)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import LoDTensor, RaggedTensor, create_lod_tensor


def test_create_and_reference_accessors():
    # 3 sequences of lengths 2, 1, 3 over 2-d features
    data = [np.full((2, 4), 1.0, np.float32),
            np.full((1, 4), 2.0, np.float32),
            np.full((3, 4), 3.0, np.float32)]
    t = create_lod_tensor(data, [[2, 1, 3]])
    assert t.shape == [6, 4] and len(t) == 3
    assert t.recursive_sequence_lengths() == [[2, 1, 3]]
    assert t.lod() == [[0, 2, 3, 6]]  # offset form, reference Tensor.lod()
    np.testing.assert_array_equal(t[1].numpy(), np.full((1, 4), 2.0))
    np.testing.assert_array_equal(t[2].numpy(), np.full((3, 4), 3.0))
    assert RaggedTensor is LoDTensor


def test_padded_round_trip():
    rng = np.random.RandomState(0)
    vals = rng.randn(6, 3).astype(np.float32)
    t = LoDTensor(paddle.to_tensor(vals), [[2, 1, 3]])
    padded, lengths = t.to_padded(pad_value=-1.0)
    assert padded.shape == [3, 3, 3]
    np.testing.assert_array_equal(lengths.numpy(), [2, 1, 3])
    p = padded.numpy()
    np.testing.assert_array_equal(p[0, :2], vals[:2])
    assert (p[0, 2] == -1.0).all() and (p[1, 1:] == -1.0).all()
    back = LoDTensor.from_padded(padded, lengths)
    np.testing.assert_array_equal(back.numpy(), vals)
    assert back.recursive_sequence_lengths() == [[2, 1, 3]]


def test_two_level_lod():
    # 2 docs: doc0 has 2 sentences (lens 2,1), doc1 has 1 sentence (len 3)
    vals = np.arange(6, dtype=np.float32).reshape(6, 1)
    t = LoDTensor(paddle.to_tensor(vals), [[2, 1], [2, 1, 3]])
    assert t.lod() == [[0, 2, 3], [0, 2, 3, 6]]
    doc0 = t[0]
    assert isinstance(doc0, LoDTensor)
    assert doc0.recursive_sequence_lengths() == [[2, 1]]
    np.testing.assert_array_equal(doc0.numpy(), vals[:3])
    doc1 = t[1]
    np.testing.assert_array_equal(doc1.numpy(), vals[3:])


def test_set_lod_and_validation():
    vals = np.zeros((6, 2), np.float32)
    t = LoDTensor(paddle.to_tensor(vals), [[3, 3]])
    t.set_lod([[0, 2, 6]])
    assert t.recursive_sequence_lengths() == [[2, 4]]
    with pytest.raises(ValueError, match="dim0"):
        LoDTensor(paddle.to_tensor(vals), [[2, 2]])  # sums to 4 != 6
    with pytest.raises(ValueError, match="level-0"):
        LoDTensor(paddle.to_tensor(vals), [[3], [3, 3]])  # 3 != 2 seqs
    with pytest.raises(ValueError, match="depth"):
        LoDTensor(paddle.to_tensor(vals), [[6], [6], [6]])


def test_negative_index_and_bounds():
    vals = np.arange(12, dtype=np.float32).reshape(6, 2)
    t = LoDTensor(paddle.to_tensor(vals), [[2, 1, 3]])
    np.testing.assert_array_equal(t[-1].numpy(), vals[3:])  # last sequence
    np.testing.assert_array_equal(t[-3].numpy(), t[0].numpy())
    with pytest.raises(IndexError):
        t[3]
    with pytest.raises(IndexError):
        t[-4]


def test_negative_lengths_rejected():
    vals = np.zeros((6, 2), np.float32)
    with pytest.raises(ValueError, match="non-negative"):
        LoDTensor(paddle.to_tensor(vals), [[-1, 7]])
    t = LoDTensor(paddle.to_tensor(vals), [[3, 3]])
    with pytest.raises(ValueError, match="non-negative"):
        t.set_lod([[0, 4, 2, 6]])  # non-monotonic offsets


def test_truncating_maxlen_returns_consistent_pair():
    vals = np.arange(6, dtype=np.float32).reshape(6, 1)
    t = LoDTensor(paddle.to_tensor(vals), [[2, 1, 3]])
    padded, lengths = t.to_padded(maxlen=2)
    assert padded.shape == [3, 2, 1]
    np.testing.assert_array_equal(lengths.numpy(), [2, 1, 2])  # clamped
    back = LoDTensor.from_padded(padded, lengths)  # must not raise
    assert back.recursive_sequence_lengths() == [[2, 1, 2]]


def test_padded_feeds_sequence_mask_pipeline():
    """The intended TPU flow: ragged -> padded + lengths -> masked compute."""
    import paddle_tpu.nn.functional as F

    t = create_lod_tensor([np.ones((2, 4), np.float32),
                           np.ones((5, 4), np.float32)], [[2, 5]])
    padded, lengths = t.to_padded()
    mask = F.sequence_mask(lengths, maxlen=5, dtype="float32")
    s = (padded * paddle.unsqueeze(mask, -1)).sum()
    assert float(s.numpy()) == pytest.approx(7 * 4)
