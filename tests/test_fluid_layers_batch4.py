"""fluid.layers batch 4: decode family, distributions, legacy classes,
detection tail, selected-rows/LoD utilities (reference fluid/layers/*).
Full-name coverage gate at the bottom."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

L = fluid.layers


def _t(a):
    return paddle.to_tensor(np.asarray(a, "float32"))


@pytest.mark.slow
def test_basic_decoder_greedy_roundtrip():
    """GreedyEmbeddingHelper + BasicDecoder + dynamic_decode produce
    end-token-terminated sequences."""
    paddle.seed(3)
    vocab, d = 12, 8
    emb = paddle.nn.Embedding(vocab, d)
    cell = paddle.nn.GRUCell(d, d)
    proj = paddle.nn.Linear(d, vocab)
    helper = L.GreedyEmbeddingHelper(
        lambda ids: emb(ids), paddle.to_tensor(np.zeros(2, "int64")),
        end_token=1)
    decoder = L.BasicDecoder(cell, helper, output_fn=proj)
    init = paddle.to_tensor(np.zeros((2, d), "float32"))
    outputs, final_states, seq_len = L.dynamic_decode(
        decoder, inits=init, max_step_num=6, return_length=True)
    cell_out, sample_ids = outputs
    assert sample_ids.shape[0] == 2  # batch-major [B, T]
    assert cell_out.shape[-1] == vocab


def test_training_helper_teacher_forcing():
    d, vocab = 4, 7
    cell = paddle.nn.SimpleRNNCell(d, d)
    proj = paddle.nn.Linear(d, vocab)
    inputs = _t(np.random.rand(2, 5, d))
    helper = L.TrainingHelper(inputs, paddle.to_tensor(
        np.array([5, 3], "int64")))
    dec = L.BasicDecoder(cell, helper, output_fn=proj)
    outputs, _ = L.dynamic_decode(
        dec, inits=paddle.to_tensor(np.zeros((2, d), "float32")),
        max_step_num=5)
    assert outputs[0].shape[1] <= 5


def test_beam_search_step_and_decode():
    """beam_search top-k over beam*V and the gather_tree backtrace."""
    beam, v = 2, 5
    sc = _t(np.log([[0.1, 0.5, 0.2, 0.1, 0.1],
                    [0.3, 0.1, 0.4, 0.1, 0.1]]))  # batch=1, beam=2
    pre = _t(np.zeros((2, 1)))
    ids, scores, parents = L.beam_search(
        None, pre, None, sc, beam_size=beam, end_id=0,
        return_parent_idx=True)
    assert tuple(ids.shape) == (2, 1)
    # the global best candidate is token 1 from beam 0
    assert int(ids.numpy()[0, 0]) == 1
    step2_ids, step2_sc, step2_par = L.beam_search(
        None, scores, None, sc, beam_size=beam, end_id=0,
        return_parent_idx=True)
    seqs, out_sc = L.beam_search_decode(
        [(ids, parents), (step2_ids, step2_par)], [scores, step2_sc],
        beam_size=beam, end_id=0)
    assert tuple(seqs.shape) == (2, 2)  # [T, batch*beam]


def test_distribution_aliases():
    n = L.Normal(0.0, 1.0)
    assert float(n.entropy().numpy()) == pytest.approx(1.4189, rel=1e-3)
    u = L.Uniform(0.0, 2.0)
    assert float(u.sample([4]).numpy().max()) <= 2.0
    c = L.Categorical(_t([0.25, 0.25, 0.5]))
    assert c.sample([3]).shape[0] == 3
    mvn = L.MultivariateNormalDiag(_t([0.0, 0.0]),
                                   _t([[1.0, 0.0], [0.0, 1.0]]))
    ent = float(mvn.entropy().numpy())
    assert ent == pytest.approx(2 * 1.4189, rel=1e-3)
    kl = L.MultivariateNormalDiag(_t([1.0, 0.0]),
                                  _t([[1.0, 0.0], [0.0, 1.0]])).kl_divergence(mvn)
    assert float(kl.numpy()) == pytest.approx(0.5, rel=1e-3)


def test_misc_tail():
    assert float(L.identity_loss(_t([1.0, 3.0]), "mean").numpy()) == 2.0
    miou, wrong, correct = L.mean_iou(
        paddle.to_tensor(np.array([0, 1, 1], "int64")),
        paddle.to_tensor(np.array([0, 1, 0], "int64")), 2)
    assert 0 < float(miou.numpy()) < 1
    h = L.hash(paddle.to_tensor(np.array([[1, 2], [1, 2], [3, 4]], "int64")),
               hash_size=100)
    hv = h.numpy()
    assert hv[0, 0] == hv[1, 0] and hv[0, 0] != hv[2, 0]
    rc = L.random_crop(_t(np.random.rand(8, 8)), [4, 4], seed=1)
    assert tuple(rc.shape) == (4, 4)
    cvm = L.continuous_value_model(_t(np.random.rand(3, 6)), None,
                                   use_cvm=False)
    assert tuple(cvm.shape) == (3, 4)
    f = L.fill_constant_batch_size_like(_t(np.zeros((5, 2))), [1, 3],
                                        "float32", 7.0)
    assert tuple(f.shape) == (5, 3) and f.numpy()[0, 0] == 7.0


def test_selected_rows_and_lod_utils():
    from paddle_tpu.core.selected_rows import SelectedRows

    sr = SelectedRows(rows=[1, 1, 3], value=np.ones((3, 2), "float32"),
                      height=5)
    merged = L.merge_selected_rows(sr)
    assert list(merged.rows) == [1, 3]
    np.testing.assert_allclose(np.asarray(merged.value)[0], [2, 2])
    dense = L.get_tensor_from_selected_rows(merged)
    assert tuple(dense.shape) == (5, 2)
    np.testing.assert_allclose(dense.numpy()[1], [2, 2])

    lt = L.lod_reset(_t(np.random.rand(6, 2)), target_lod=[2, 4])
    assert lt.lod() == [[0, 2, 6]]
    # append a finer level: the old [2,2,2] level now counts inner seqs
    lt2 = L.lod_append(L.lod_reset(_t(np.random.rand(6, 2)),
                                   target_lod=[2, 2, 2]), [1] * 6)
    assert len(lt2.lod()) == 2


def test_sequence_scatter_and_spectral_norm():
    from paddle_tpu.core.ragged import LoDTensor

    x = _t(np.zeros((2, 5)))
    idx = LoDTensor(paddle.to_tensor(np.array([1, 3, 0], "int64")), [[2, 1]])
    upd = _t([10.0, 20.0, 30.0])
    out = L.sequence_scatter(x, idx, upd)
    np.testing.assert_allclose(out.numpy()[0], [0, 10, 0, 20, 0])
    np.testing.assert_allclose(out.numpy()[1], [30, 0, 0, 0, 0])

    w = _t(np.random.randn(4, 6))
    wn = L.spectral_norm(w, power_iters=20)
    s = np.linalg.svd(wn.numpy(), compute_uv=False)
    assert s[0] == pytest.approx(1.0, rel=1e-2)


def test_chunk_eval_iob():
    # IOB, 1 chunk type: tags B=0, I=1, O=-? use num types=1, n=2: B=0 I=1
    inf = paddle.to_tensor(np.array([0, 1, 0, 1, 1], "int64"))
    lab = paddle.to_tensor(np.array([0, 1, 0, 1, 1], "int64"))
    p, r, f1, n_inf, n_lab, n_cor = L.chunk_eval(inf, lab, "IOB", 1)
    assert float(f1.numpy()) == 1.0 and int(n_cor.numpy()) == 2


def test_detection_tail():
    # matrix_nms keeps the dominant box, soft-decays the overlapper
    boxes = _t([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]])
    scores = _t([[0.05, 0.02, 0.01], [0.9, 0.8, 0.7]])
    out, n = L.matrix_nms(boxes, scores, score_threshold=0.1,
                          post_threshold=0.05, nms_top_k=3, keep_top_k=5)
    assert int(n.numpy()[0]) >= 2
    # detection_output composes decode + nms without error
    pb = _t([[0.1, 0.1, 0.3, 0.3], [0.5, 0.5, 0.9, 0.9]])
    pbv = _t(np.ones((2, 4)) * 0.1)
    loc = _t(np.zeros((2, 4)))
    sc = _t([[0.1, 0.9], [0.8, 0.2]])  # [P, C]
    det = L.detection_output(loc, paddle.transpose(sc, [1, 0]), pb, pbv,
                             background_label=-1)
    assert det.shape[-1] == 6
    # target_assign gathers by match index
    out_t, w = L.target_assign(_t(np.arange(8).reshape(4, 2)),
                               paddle.to_tensor(
                                   np.array([[0, -1, 2]], "int64")),
                               mismatch_value=0)
    np.testing.assert_allclose(out_t.numpy()[0, 0], [0, 1])
    assert w.numpy()[0, 1, 0] == 0.0
    # density_prior_box shapes
    feat = paddle.to_tensor(np.zeros((1, 4, 2, 2), "float32"))
    img = paddle.to_tensor(np.zeros((1, 3, 32, 32), "float32"))
    db, dv = L.density_prior_box(feat, img, densities=[2],
                                 fixed_sizes=[8.0], fixed_ratios=[1.0])
    assert db.shape[2] == 4  # density^2 boxes per cell
    # psroi_pool: position-sensitive averaging
    x = _t(np.random.rand(1, 8, 8, 8))
    rois = _t([[0, 0, 8, 8]])
    ps = L.psroi_pool(x, rois, output_channels=2, spatial_scale=1.0,
                      pooled_height=2, pooled_width=2)
    assert tuple(ps.shape) == (1, 2, 2, 2)


@pytest.mark.slow
def test_ssd_and_yolo_losses_finite():
    paddle.seed(0)
    loc = _t(np.random.rand(4, 4) * 0.1)
    conf = _t(np.random.rand(4, 3))
    gt_box = _t([[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]])
    gt_label = paddle.to_tensor(np.array([1, 2], "int64"))
    pb = _t([[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
             [0.0, 0.0, 0.2, 0.2], [0.7, 0.7, 1.0, 1.0]])
    loss = L.ssd_loss(loc, conf, gt_box, gt_label, pb,
                      background_label=0)
    assert np.isfinite(float(loss.numpy()))
    x = _t(np.random.rand(1, 3 * 7, 4, 4))  # 3 anchors, 2 classes: 5+2=7
    yl = L.yolov3_loss(x, _t([[[0.5, 0.5, 0.3, 0.3]]]),
                       paddle.to_tensor(np.array([[1]], "int64")),
                       anchors=[10, 13, 16, 30, 33, 23],
                       anchor_mask=[0, 1, 2], class_num=2,
                       ignore_thresh=0.7, downsample_ratio=32)
    assert np.isfinite(float(yl.numpy()))


def test_legacy_gates_are_loud():
    with pytest.raises(NotImplementedError, match="while_loop"):
        L.While(_t([1.0])).block()
    with pytest.raises(NotImplementedError, match="cond"):
        L.IfElse(_t([1.0]))
    with pytest.raises(NotImplementedError, match="DataLoader"):
        L.py_reader(64, [[1]], ["float32"])
    with pytest.raises(NotImplementedError, match="rnn"):
        rnn = L.StaticRNN()
        rnn()
    with pytest.raises(NotImplementedError):
        L.rpn_target_assign(None, None, None, None, None, None, None)


def test_codegen_helpers():
    relu_fn = L.generate_activation_fn("relu")
    np.testing.assert_allclose(relu_fn(_t([-1.0, 2.0])).numpy(), [0, 2])
    assert L.templatedoc()(test_codegen_helpers) is test_codegen_helpers


def test_full_name_coverage_vs_reference():
    """Every name in the reference fluid.layers __all__ resolves here."""
    import ast
    import os

    base = "/root/reference/python/paddle/fluid/layers"
    names = set()
    for fn in os.listdir(base):
        if not fn.endswith(".py"):
            continue
        tree = ast.parse(open(os.path.join(base, fn)).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        try:
                            names.update(ast.literal_eval(node.value))
                        except Exception:
                            pass
    missing = sorted(n for n in names if not hasattr(L, n))
    assert missing == [], f"fluid.layers missing: {missing}"


def test_beam_search_decode_backtrace_regression():
    """Code-review r4 (reproduced): parents must actually backtrace.
    Both step-2 beams descend from step-1 beam 1 → beam histories share
    token 4, not the raw per-slot tokens."""
    ids = [
        (paddle.to_tensor(np.array([[3], [4]], "int64")),
         paddle.to_tensor(np.array([0, 1], "int64"))),
        (paddle.to_tensor(np.array([[5], [6]], "int64")),
         paddle.to_tensor(np.array([1, 1], "int64"))),
    ]
    scores = [paddle.to_tensor(np.zeros((2, 1), "float32"))] * 2
    seqs, _ = L.beam_search_decode(ids, scores, beam_size=2, end_id=0)
    out = seqs.numpy()  # [T=2, beam=2]
    assert out[:, 0].tolist() == [4, 5]
    assert out[:, 1].tolist() == [4, 6]


def test_beam_search_holds_finished_beams():
    """A finished beam (pre_ids == end_id) re-emits end_id at its frozen
    score instead of expanding."""
    v, beam = 4, 2
    pre_ids = paddle.to_tensor(np.array([[0], [2]], "int64"))  # beam0 done
    pre_sc = _t([[-0.1], [-2.0]])  # finished beam outranks the actives
    sc = _t(np.full((2, v), -0.5))
    ids, scores, parents = L.beam_search(
        pre_ids, pre_sc, None, sc, beam_size=beam, end_id=0,
        return_parent_idx=True)
    rows = {(int(i), round(float(s), 3))
            for i, s in zip(ids.numpy().ravel(), scores.numpy().ravel())}
    # held hypothesis: end_id re-emitted at its frozen score, ranked first
    assert (0, -0.1) in rows
    assert int(ids.numpy()[0, 0]) == 0  # the held beam wins the top slot


def test_random_crop_trailing_and_density_ratios_regression():
    x = _t(np.random.rand(4, 20, 20))
    out = L.random_crop(x, [10, 10], seed=0)
    assert tuple(out.shape) == (4, 10, 10)
    feat = paddle.to_tensor(np.zeros((1, 4, 2, 2), "float32"))
    img = paddle.to_tensor(np.zeros((1, 3, 32, 32), "float32"))
    db, _ = L.density_prior_box(feat, img, densities=[2],
                                fixed_sizes=[16.0],
                                fixed_ratios=[1.0, 2.0])
    assert db.shape[2] == 8  # density^2 * len(ratios)
    wh = db.numpy()[0, 0]
    w = wh[:, 2] - wh[:, 0]
    h = wh[:, 3] - wh[:, 1]
    assert not np.allclose(w[4:], h[4:])  # ratio-2 boxes are non-square


def test_prroi_default_and_data_norm_isolation():
    x = _t(np.random.rand(1, 4, 8, 8))
    rois = _t([[0, 0, 8, 8]])
    out = L.prroi_pool(x, rois, 1.0, 2, 2)  # default batch_roi_nums
    assert tuple(out.shape) == (1, 4, 2, 2)
    # anonymous data_norm calls don't share accumulators
    a = L.data_norm(_t(np.full((4, 3), 100.0)))
    b = L.data_norm(_t(np.full((4, 3), -100.0)))
    assert np.isfinite(a.numpy()).all() and np.isfinite(b.numpy()).all()
    # named calls accumulate under their own key
    c1 = L.data_norm(_t(np.random.rand(4, 3)), name="dn_test")
    from paddle_tpu.fluid.layers import data_norm as _dn
    assert ("dn_test", 3) in _dn.stats


def test_host_ops_fail_loudly_in_static_mode():
    """Host-computed legacy ops must not silently compute on placeholder
    zeros under static build (the silent-failure class from VERDICT r2/r3)."""
    paddle.enable_static()
    try:
        from paddle_tpu import static

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("xh", [3, 4], "float32")
            with pytest.raises(NotImplementedError, match="dygraph"):
                L.hash(x, 100)
            with pytest.raises(NotImplementedError, match="dygraph"):
                L.mean_iou(x, x, 4)
            with pytest.raises(NotImplementedError, match="dygraph"):
                L.random_crop(x, [2, 2])
    finally:
        paddle.disable_static()


@pytest.mark.slow
def test_roi_perspective_transform_identity_and_crop():
    """Homography warp: identity quad reproduces the image; half-width quad
    samples the left half (reference roi_perspective_transform_op)."""
    x = paddle.to_tensor(
        np.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    quad = paddle.to_tensor(np.array([[0, 0, 3, 0, 3, 3, 0, 3]], "float32"))
    out, mask, hs = L.roi_perspective_transform(x, quad, 4, 4)
    np.testing.assert_allclose(out.numpy()[0, 0], x.numpy()[0, 0], atol=1e-4)
    assert int(mask.numpy().sum()) == 16
    half = paddle.to_tensor(
        np.array([[0, 0, 1.5, 0, 1.5, 3, 0, 3]], "float32"))
    out2, _, _ = L.roi_perspective_transform(x, half, 4, 4)
    np.testing.assert_allclose(out2.numpy()[0, 0, 0, :2], [0.0, 0.5],
                               atol=1e-4)
