"""OpTest batch 6: norm family (group/instance/LRN), einsum, loss tail,
triangular/selection, vision-geometry ops (reference test strategy SURVEY
§4.1, op_test.py protocol: eager + static paths vs numpy reference,
finite-difference grad checks where differentiable)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from optest_batch_util import make_f32, make_mk

_mk = make_mk(globals())
_r = np.random.RandomState(11)
_f32 = make_f32(_r)


# ------------------------------------------------------------- norm family
def _group_norm_ref(x, num_groups, eps=1e-5):
    n, c, h, w = x.shape
    g = x.reshape(n, num_groups, c // num_groups, h, w)
    mu = g.mean(axis=(2, 3, 4), keepdims=True)
    var = g.var(axis=(2, 3, 4), keepdims=True)
    return ((g - mu) / np.sqrt(var + eps)).reshape(x.shape)


_mk("TestGroupNormOp",
    lambda x, num_groups: F.group_norm(x, num_groups,
                                       weight=paddle.ones([8]),
                                       bias=paddle.zeros([8])),
    lambda: {"x": _f32(2, 8, 4, 4)},
    lambda x, num_groups: _group_norm_ref(x, num_groups),
    attrs={"num_groups": 4}, grads=("x",), atol=1e-5)


def _instance_norm_ref(x, eps=1e-5):
    mu = x.mean(axis=(2, 3), keepdims=True)
    var = x.var(axis=(2, 3), keepdims=True)
    return (x - mu) / np.sqrt(var + eps)


_mk("TestInstanceNormOp", F.instance_norm,
    lambda: {"x": _f32(2, 3, 5, 5)},
    _instance_norm_ref, grads=("x",), atol=1e-5)


def _lrn_ref(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
    n, c, h, w = x.shape
    sq = x ** 2
    acc = np.zeros_like(x)
    half = size // 2
    for ci in range(c):
        lo, hi = max(0, ci - half), min(c, ci + half + 1)
        acc[:, ci] = sq[:, lo:hi].sum(axis=1)
    return x / (k + alpha * acc / size) ** beta


_mk("TestLocalResponseNormOp", F.local_response_norm,
    lambda: {"x": _f32(2, 7, 4, 4)},
    lambda x, size: _lrn_ref(x, size=size),
    attrs={"size": 5}, atol=1e-5)


# ------------------------------------------------------------------ einsum
_mk("TestEinsumMatmulOp",
    lambda x, y, equation: paddle.einsum(equation, x, y),
    lambda: {"x": _f32(3, 4), "y": _f32(4, 5)},
    lambda x, y, equation: np.einsum(equation, x, y),
    attrs={"equation": "ij,jk->ik"}, grads=("x", "y"), atol=1e-5)

_mk("TestEinsumBatchTraceOp",
    lambda x, equation: paddle.einsum(equation, x),
    lambda: {"x": _f32(4, 5, 5)},
    lambda x, equation: np.einsum(equation, x),
    attrs={"equation": "bii->b"}, grads=("x",), atol=1e-5)


# ----------------------------------------------------------- triangular etc
_mk("TestTrilOp", paddle.tril,
    lambda: {"x": _f32(4, 6)},
    lambda x, diagonal: np.tril(x, k=diagonal),
    attrs={"diagonal": -1}, grads=("x",))

_mk("TestTriuOp", paddle.triu,
    lambda: {"x": _f32(4, 6)},
    lambda x, diagonal: np.triu(x, k=diagonal),
    attrs={"diagonal": 1}, grads=("x",))

_mk("TestWhereOp", paddle.where,
    lambda: {"condition": (_r.rand(4, 5) > 0.5),
             "x": _f32(4, 5), "y": _f32(4, 5)},
    lambda condition, x, y: np.where(condition, x, y),
    grads=("x", "y"))

_mk("TestTileOp", paddle.tile,
    lambda: {"x": _f32(2, 3)},
    lambda x, repeat_times: np.tile(x, repeat_times),
    attrs={"repeat_times": (2, 2)}, grads=("x",))

_mk("TestExpandAsOp", paddle.expand_as,
    lambda: {"x": _f32(1, 4), "y": _f32(3, 4)},
    lambda x, y: np.broadcast_to(x, y.shape).copy(),
    grads=("x",))

_mk("TestStridedSliceOp", paddle.strided_slice,
    lambda: {"x": _f32(4, 8, 6)},
    lambda x, axes, starts, ends, strides: x[:, 1:7:2, ::3],
    attrs={"axes": [1, 2], "starts": [1, 0], "ends": [7, 6],
           "strides": [2, 3]}, grads=("x",))

_mk("TestHistogramOp", paddle.histogram,
    lambda: {"input": (_r.rand(100) * 10).astype("float32")},
    lambda input, bins, min, max: np.histogram(
        input, bins=bins, range=(min, max))[0].astype("int64"),
    attrs={"bins": 8, "min": 0, "max": 10})


# ---------------------------------------------------------------- loss tail
_mk("TestCosineSimilarityOp", F.cosine_similarity,
    lambda: {"x1": _f32(4, 8), "x2": _f32(4, 8)},
    lambda x1, x2, axis: (x1 * x2).sum(axis) /
    (np.sqrt((x1 ** 2).sum(axis)) * np.sqrt((x2 ** 2).sum(axis))),
    attrs={"axis": 1}, grads=("x1", "x2"), atol=1e-5)


def _nll_ref(input, label):
    return -input[np.arange(len(label)), label].mean()


_mk("TestNllLossOp", F.nll_loss,
    lambda: {"input": np.log(_r.rand(6, 4).astype("float32") + 0.1),
             "label": _r.randint(0, 4, (6,)).astype("int64")},
    _nll_ref, grads=("input",))

_mk("TestKlDivOp", F.kl_div,
    lambda: {"input": np.log(_r.rand(4, 5).astype("float32") + 0.1),
             "label": (_r.rand(4, 5).astype("float32") + 0.1)},
    lambda input, label: (label * (np.log(label) - input)).mean(),
    grads=("input",), atol=1e-5)

_mk("TestSmoothL1Op", F.smooth_l1_loss,
    lambda: {"input": _f32(4, 5, lo=-2, hi=2),
             "label": _f32(4, 5, lo=-2, hi=2)},
    lambda input, label: np.where(
        np.abs(input - label) < 1.0,
        0.5 * (input - label) ** 2,
        np.abs(input - label) - 0.5).mean(),
    grads=("input",), atol=1e-5)

_mk("TestBCEOp", F.binary_cross_entropy,
    lambda: {"input": (_r.rand(4, 5) * 0.8 + 0.1).astype("float32"),
             "label": _r.randint(0, 2, (4, 5)).astype("float32")},
    lambda input, label: (-(label * np.log(input)
                            + (1 - label) * np.log(1 - input))).mean(),
    grads=("input",), atol=1e-5)

_mk("TestMarginRankingOp", F.margin_ranking_loss,
    lambda: {"input": _f32(6), "other": _f32(6),
             "label": np.sign(_r.randn(6)).astype("float32")},
    lambda input, other, label: np.maximum(
        0.0, -label * (input - other)).mean(),
    grads=("input", "other"))

_mk("TestGluOp", F.glu,
    lambda: {"x": _f32(4, 8)},
    lambda x, axis: x[:, :4] / (1.0 + np.exp(-x[:, 4:])),
    attrs={"axis": 1}, grads=("x",), atol=1e-5)


# ------------------------------------------------------------ vision / geom
def _affine_grid_ref(theta, out_shape, align_corners=True):
    n, c, h, w = out_shape
    if align_corners:
        ys = np.linspace(-1, 1, h)
        xs = np.linspace(-1, 1, w)
    else:
        ys = (np.arange(h) * 2 + 1) / h - 1
        xs = (np.arange(w) * 2 + 1) / w - 1
    gy, gx = np.meshgrid(ys, xs, indexing="ij")
    base = np.stack([gx, gy, np.ones_like(gx)], axis=-1)  # [h, w, 3]
    out = np.einsum("hwk,njk->nhwj", base, theta)
    return out.astype("float32")


_mk("TestAffineGridOp", F.affine_grid,
    lambda: {"theta": _f32(2, 2, 3)},
    lambda theta, out_shape: _affine_grid_ref(theta, out_shape),
    attrs={"out_shape": [2, 3, 4, 5]}, grads=("theta",), atol=1e-5)


def _temporal_shift_ref(x, seg_num, shift_ratio=0.25):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    out = np.zeros_like(x5)
    out[:, :-1, :fold] = x5[:, 1:, :fold]          # shift left
    out[:, 1:, fold:2 * fold] = x5[:, :-1, fold:2 * fold]  # shift right
    out[:, :, 2 * fold:] = x5[:, :, 2 * fold:]
    return out.reshape(x.shape)


_mk("TestTemporalShiftOp", F.temporal_shift,
    lambda: {"x": _f32(4, 8, 3, 3)},
    lambda x, seg_num: _temporal_shift_ref(x, seg_num),
    attrs={"seg_num": 2}, grads=("x",))


def _fold_ref(x, output_sizes, kernel_sizes):
    # x: [n, c*kh*kw, L] -> [n, c, H, W] sum of patches (stride 1, no pad)
    n, ckk, L = x.shape
    kh, kw = kernel_sizes
    c = ckk // (kh * kw)
    H, W = output_sizes
    out = np.zeros((n, c, H, W), x.dtype)
    cols = x.reshape(n, c, kh, kw, L)
    li = 0
    for i in range(H - kh + 1):
        for j in range(W - kw + 1):
            out[:, :, i:i + kh, j:j + kw] += cols[:, :, :, :, li]
            li += 1
    return out


_mk("TestFoldOp", F.fold,
    lambda: {"x": _f32(2, 3 * 2 * 2, 9)},
    lambda x, output_sizes, kernel_sizes: _fold_ref(
        x, output_sizes, kernel_sizes),
    attrs={"output_sizes": [4, 4], "kernel_sizes": [2, 2]},
    grads=("x",), atol=1e-5)


def _unpool_inputs():
    x = _f32(1, 2, 4, 4)
    xt = paddle.to_tensor(x)
    out, idx = F.max_pool2d(xt, 2, stride=2, return_mask=True)
    return {"x": out.numpy(), "indices": idx.numpy().astype("int64")}


def _unpool_ref(x, indices, kernel_size):
    n, c, h, w = x.shape
    out = np.zeros((n, c, h * 2, w * 2), x.dtype)
    flat = out.reshape(n, c, -1)
    for ni in range(n):
        for ci in range(c):
            flat[ni, ci, indices[ni, ci].reshape(-1)] = \
                x[ni, ci].reshape(-1)
    return flat.reshape(out.shape)


_mk("TestMaxUnpool2dOp", F.max_unpool2d,
    lambda: _unpool_inputs(),
    lambda x, indices, kernel_size: _unpool_ref(x, indices, kernel_size),
    attrs={"kernel_size": 2})


if __name__ == "__main__":
    import unittest

    unittest.main()
