"""Fleetscope: cross-replica distributed tracing over the wire,
fleet-wide metrics aggregation, and the cluster flight recorder.

Coverage, one layer per block:

- span ids: FNV-1a determinism golden, fixed-width hex keys (a 64-bit
  int does not survive a float53 JSON viewer).
- wire extension: the optional span tail is v1-compatible — span-less
  frames are BYTE-identical to the pre-extension codec (hex golden),
  old readers (``decode_frame``) decode span-bearing frames, and
  ``decode_frame_span`` round-trips the id on all three frame kinds.
- scope: the bounded exchange-span ring (open/child/end), eviction
  semantics, per-rid query.
- chrome flows: the ``ph:"s"``/``ph:"f"`` flow-event schema, and the
  acceptance scenario — a lossy-channel page fetch with >=1 retry
  renders as ONE flow-linked span tree across two replica tracks with
  retry/backoff children, bit-identical across runs.
- fleet metrics: the merged scrape is one valid exposition with
  ``replica=`` on every sample, grammar-checked line by line on both
  the live (``fleet_metrics``) and dump (``from_fleet_record``) paths;
  the breaker gauge never skips a state across a full
  open -> half_open -> closed cycle.
- fleet record: ``paddle-tpu/fleet-record/v1`` validates, names the
  first offending key / corrupt replica, auto-dumps on replica_down
  and on a chaos-soak invariant failure, and round-trips through the
  ``--fleet-record`` / ``--span`` CLI views.
- off switch: ``FleetConfig(fleetscope=False)`` returns None surfaces,
  sends plain v1 frames, and is sync-free + compile-count + output
  bit-identical to fleetscope on.

Everything runs on the shared virtual clock — sleep-free, deterministic.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import SyncTally
from paddle_tpu.obs.fleetscope import (FLEET_RECORD_SCHEMA, FleetMetrics,
                                       FleetScope, flow_events,
                                       format_fleet_record,
                                       format_span_tree, span_id,
                                       span_key, validate_fleet_record)
from paddle_tpu.obs.journey import validate_journey
from paddle_tpu.serving import (FaultInjector, FleetConfig, FleetRouter,
                                ServingConfig)
from paddle_tpu.serving.channel import (ChannelConfig, SimChannel,
                                        Transport, TransportConfig)
from paddle_tpu.serving.chaos import (ChaosConfig, ChaosInvariantError,
                                      soak)
from paddle_tpu.serving.metrics import (BREAKER_STATE_VALUES,
                                        ServingMetrics)
from paddle_tpu.serving.wire import (decode_frame, decode_frame_span,
                                     encode_digests, encode_page,
                                     encode_rehome)
from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.utils import monitor

pytestmark = pytest.mark.fleetscope


class VirtualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


@pytest.fixture(scope="module")
def model():
    paddle.seed(41)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=48, dropout=0.0))
    m.eval()
    return m


_ENG = dict(max_batch=2, num_pages=20, page_size=4, max_prompt_len=8)


def _fleet(model, num_replicas=2, eng=None, injector=None, **fleet_kw):
    kw = dict(_ENG)
    kw.update(eng or {})
    cfg = FleetConfig(num_replicas=num_replicas,
                      engine=ServingConfig(**kw), **fleet_kw)
    return FleetRouter(model, cfg, clock=VirtualClock(),
                       fault_injector=injector)


def _lossless(seed=0, **kw):
    return Transport(SimChannel(ChannelConfig(seed=seed)),
                     TransportConfig(seed=seed, **kw))


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 97, (n,)).astype(np.int32)


def _lossy_fetch_fleet(model):
    """The acceptance scenario: warm replica 0, then overflow the same
    prompt so spills land on replica 1, whose page fetch rides a lossy
    wire that costs >= 1 retry (seed probed once, pinned forever)."""
    tr = Transport(SimChannel(ChannelConfig(seed=5, drop_rate=0.3,
                                            corrupt_rate=0.1)),
                   TransportConfig(seed=5, retries=8, timeout_s=0.5,
                                   breaker_threshold=100))
    fl = _fleet(model, num_replicas=2,
                eng=dict(host_tier_bytes=1 << 20),
                transport=tr, fetch_pages=True)
    warm = _prompt(8, seed=3)
    fl.submit(warm, 3)
    fl.run()
    rids = [fl.submit(warm, 3) for _ in range(5)]
    outs = fl.run()
    assert sorted(outs) == sorted(rids)
    return fl


# ------------------------------------------------------------ span ids
def test_span_id_deterministic_golden():
    # FNV-1a over (rid, serial): pinned so span ids survive refactors —
    # two builds watching the same exchange must agree on its id
    assert span_id(7, 1) == 0x08285707B4E2C825
    assert span_id(None, 1) == span_id(None, 1)
    assert span_id(None, 1) != span_id(None, 2)
    assert span_id(None, 1) == 0xF7CA12F84B11AE9D  # rid-less hashes -1
    assert span_id(0, 1) != span_id(None, 1)


def test_span_key_fixed_width_hex():
    assert span_key(span_id(7, 1)) == "08285707b4e2c825"
    for sid in (0, 1, (1 << 64) - 1, span_id(None, 3)):
        key = span_key(sid)
        assert len(key) == 16 and int(key, 16) == sid


# ------------------------------------------------------ wire extension
def test_wire_spanless_digest_frame_golden():
    # the pre-extension v1 bytes, pinned as hex: a reader (or writer)
    # that changes span-less frames breaks every deployed peer
    assert encode_digests({3, 17, 255}).hex() == (
        "5054575201021c000000030000000300000000000000110000000000"
        "0000ff000000000000008f58a15a")


def test_wire_span_extension_round_trip():
    from paddle_tpu.serving.kv_cache import SpilledPage

    rng = np.random.RandomState(0)
    page = SpilledPage(key=(3, (1, 2, 3)), serial=9,
                       k=rng.randn(2, 4, 2, 16).astype(np.float32),
                       v=rng.randn(2, 4, 2, 16).astype(np.float32),
                       k_scale=None, v_scale=None)
    sid = span_id(42, 7)
    frames = [encode_page(page, span=sid),
              encode_digests({1, 2}, span=sid),
              encode_rehome(5, _prompt(4), 3, None, "default", span=sid)]
    for f in frames:
        kind, value, got = decode_frame_span(f)
        assert got == sid
        # the old 2-tuple reader stays total over span-bearing frames
        old_kind, old_value = decode_frame(f)
        assert old_kind == kind
    # span=None is not "span 0": the tail is absent, bytes identical
    assert encode_digests({1, 2}, span=None) == encode_digests({1, 2})
    assert decode_frame_span(encode_digests({1, 2}))[2] is None


# ------------------------------------------------------------- scope
def test_fleetscope_ring_children_and_eviction():
    sc = FleetScope(capacity=2)
    a = sc.open(kind="page", src=0, dst=1, rid=11, step=3, t=1.0)
    sc.child(a, "attempt", 1.0, 1.5, ok=False, timeout=True)
    sc.child(a, "backoff", 1.5, 1.6, attempt=1)
    sc.end(a, t=2.0, ok=True, retries=1)
    rec = sc.records()[0]
    assert rec["span"] == span_key(a) and rec["rid"] == 11
    assert rec["ok"] is True and rec["retries"] == 1
    assert [c["kind"] for c in rec["children"]] == ["attempt", "backoff"]
    assert sc.spans_for(11) == [rec] and sc.spans_for(99) == []
    # ring bound: the oldest record falls off at capacity
    b = sc.open(kind="digests", src=0)
    c = sc.open(kind="digests", src=1)
    assert [r["span"] for r in sc.records()] == [span_key(b),
                                                 span_key(c)]
    # children/end on unknown (evicted) ids drop silently — these sit
    # on the transport's per-attempt path and must never raise
    sc.child(a, "attempt", 2.0, 2.1, ok=True)
    sc.end(a, t=2.2, ok=False)
    sc.end(b, t=3.0, ok=True)
    assert sc.records()[0]["ok"] is True


def test_flow_events_schema():
    sc = FleetScope()
    sid = sc.open(kind="page", src=0, dst=1, rid=4, t=2.0)
    sc.child(sid, "attempt", 2.0, 2.5, ok=True)
    sc.end(sid, t=2.5, ok=True)
    evs = flow_events(sc.records(), transport_pid=9)
    slices = [e for e in evs if e["ph"] == "X"]
    starts = [e for e in evs if e["ph"] == "s"]
    fins = [e for e in evs if e["ph"] == "f"]
    assert len(starts) == len(fins) == 1
    assert starts[0]["id"] == fins[0]["id"] == span_key(sid)
    assert starts[0]["pid"] == 1 and fins[0]["pid"] == 2  # src+1/dst+1
    assert fins[0]["bp"] == "e"  # bind to the enclosing recv slice
    assert {e["name"] for e in slices} == {"wire:page", "wire:attempt",
                                           "wire:page recv"}
    metas = [e for e in evs if e["ph"] == "M"]
    assert {m["pid"] for m in metas} == {1, 2}


# ------------------------------------------------- acceptance scenario
@pytest.fixture(scope="module")
def lossy_fleet(model):
    # built once and shared: the consumers below only read scope/trace/
    # record state (re-tiered 2026-08 (PR 20): a second fresh build here
    # helped push tier-1 past its 870 s budget)
    return _lossy_fetch_fleet(model)


def test_lossy_page_fetch_flow_linked_across_replicas(lossy_fleet):
    fl = lossy_fleet
    pages = [r for r in fl.scope.records() if r["kind"] == "page"]
    assert pages, "the pinned seed no longer drives a page fetch"
    retried = [r for r in pages if r["retries"] >= 1]
    assert retried, "the pinned seed no longer costs a retry"
    rec = retried[0]
    assert rec["src"] != rec["dst"] and rec["ok"] is True
    kinds = [c["kind"] for c in rec["children"]]
    assert "attempt" in kinds and "backoff" in kinds
    # ... and the whole tree renders flow-linked in the chrome trace:
    # one s/f pair under the span id, bridging two replica tracks
    doc = fl.export_chrome_trace()
    flows = {ph: [e for e in doc["traceEvents"]
                  if e.get("ph") == ph and e.get("id") == rec["span"]]
             for ph in ("s", "f")}
    assert len(flows["s"]) == 1 and len(flows["f"]) == 1
    assert flows["s"][0]["pid"] == rec["src"] + 1
    assert flows["f"][0]["pid"] == rec["dst"] + 1
    assert flows["s"][0]["pid"] != flows["f"][0]["pid"]
    # the journey carries the span ref as a v1-compatible hop extension
    hops = [h for j in fl.journey_dump() for h in j["hops"]
            if h.get("span") == rec["span"]]
    assert hops and all(h["kind"] == "wire_retry" for h in hops)
    for j in fl.journey_dump():
        validate_journey(j)
    # ... and the exchange shows up in the merged scrape
    text = fl.fleet_metrics().prometheus()
    assert 'serving_wire_rtt_s_count{peer="1",replica="0"}' in text
    assert 'serving_wire_attempts_count{peer="1",replica="0"}' in text


@pytest.mark.slow  # re-tiered 2026-08 (PR 20): two full lossy-fleet
# builds; the single-build flow-linked acceptance above stays tier-1
def test_acceptance_scenario_bit_identical_across_runs(model):
    # span ids hash (rid, serial), so "same run" means same rid state:
    # pin the process-global rid counter to the same start both times
    # (both modules bind the name at import, so patch both)
    import itertools

    import paddle_tpu.serving.fleet as fleet_mod
    import paddle_tpu.serving.scheduler as sched_mod

    saved = sched_mod._rid_counter

    def run():
        ctr = itertools.count(10_000)
        sched_mod._rid_counter = fleet_mod._rid_counter = ctr
        fl = _lossy_fetch_fleet(model)
        # serving_tokens_per_sec is the ONE wall-clock-timestamped
        # gauge (a perf_counter sliding window, predating fleetscope)
        # — everything else in the scrape must be bit-identical
        scrape = "\n".join(
            line for line in fl.fleet_metrics().prometheus().splitlines()
            if not line.startswith("serving_tokens_per_sec"))
        return (json.dumps(fl.export_chrome_trace(), sort_keys=True),
                scrape,
                json.dumps(fl.scope.records(), sort_keys=True))

    try:
        assert run() == run()
    finally:
        sched_mod._rid_counter = fleet_mod._rid_counter = saved


# ------------------------------------------------------ merged scrape
_SAMPLE = __import__("re").compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+=\"[^\"]*\""
    r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})? -?[0-9.+eE-]+$")
_TYPE = __import__("re").compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (gauge|counter|histogram)$")


def _check_exposition(text):
    typed = set()
    for line in text.splitlines():
        if line.startswith("# TYPE"):
            base = line.split()[2]
            assert base not in typed, f"duplicate TYPE for {base}"
            typed.add(base)
            assert _TYPE.match(line), line
        else:
            assert _SAMPLE.match(line), line
            if "{" in line:
                assert 'replica="' in line, line
    return typed


def test_merged_scrape_grammar_live_and_dump(model):
    fl = _fleet(model, num_replicas=2, transport=_lossless(seed=1))
    fl.submit(_prompt(5), 3)
    fl.run()
    live = fl.fleet_metrics().prometheus()
    typed = _check_exposition(live)
    assert "serving_breaker_state" in typed
    assert "serving_wire_bytes_total" in typed
    assert 'serving_breaker_state{peer="0",replica="1"} 0' in live
    # counter typing survives the merge (the one-TYPE-per-base pin)
    assert "# TYPE serving_wire_bytes_total counter" in live
    # the dump path renders through the SAME pipeline
    rec = fl.fleet_record()
    dumped = FleetMetrics.from_fleet_record(rec).prometheus()
    _check_exposition(dumped)
    assert 'serving_tokens_total{replica="0"}' in dumped
    assert 'serving_tokens_total{replica="1"}' in dumped


def test_breaker_full_cycle_gauge_never_skips_a_state():
    # satellite pin: the gauge must follow open -> half_open -> closed —
    # metering only the open edge made recovery invisible
    m = ServingMetrics()
    m.seed_wire_peers([0])
    seen = []
    orig = m.on_breaker_state
    m.on_breaker_state = lambda peer, state: (
        seen.append((peer, state)), orig(peer, state))[-1]
    inj = FaultInjector().arm("peer_timeout", rid=0, times=2)
    tr = Transport(SimChannel(ChannelConfig(seed=1)),
                   TransportConfig(seed=1, retries=0, timeout_s=0.5,
                                   breaker_threshold=2,
                                   breaker_reset_s=1.0))
    tr.attach(metrics=m, injector=inj)
    gauge = lambda: monitor.stat_get("serving_breaker_state{peer=0}")
    frames = [encode_digests({1})]
    assert gauge() == BREAKER_STATE_VALUES["closed"]  # pre-seeded
    assert tr.exchange(0, frames) is None  # failure 1: still closed
    assert gauge() == BREAKER_STATE_VALUES["closed"]
    assert tr.exchange(0, frames) is None  # failure 2: trips open
    assert gauge() == BREAKER_STATE_VALUES["open"]
    assert tr.exchange(0, frames) is None  # cooldown: blocked, still open
    assert gauge() == BREAKER_STATE_VALUES["open"]
    tr.t += 2.0  # past breaker_reset_s on the virtual timeline
    assert tr.exchange(0, frames) is not None  # probe succeeds
    assert gauge() == BREAKER_STATE_VALUES["closed"]
    assert [s for _, s in seen] == ["open", "half_open", "closed"]
    assert [s for _, _, s in tr.breaker_events] == ["open", "half_open",
                                                    "closed"]


# ------------------------------------------------------- fleet record
def test_fleet_record_validates_and_round_trips(model, tmp_path):
    fl = _fleet(model, num_replicas=2, transport=_lossless(seed=2))
    fl.submit(_prompt(5), 3)
    fl.run()
    path = tmp_path / "fleet.json"
    rec = fl.dump_fleet_record(path)
    assert rec["schema"] == FLEET_RECORD_SCHEMA
    assert fl.last_fleet_record is rec
    loaded = validate_fleet_record(json.loads(path.read_text()))
    assert len(loaded["replicas"]) == 2
    assert [r["reason"] for r in loaded["replicas"]] == \
        ["fleet: manual"] * 2
    # the pretty renderer survives the JSON round trip
    out = format_fleet_record(loaded)
    assert "fleet record paddle-tpu/fleet-record/v1" in out
    assert "breakers:" in out and "router: live=[0, 1]" in out
    for ex in loaded["exchanges"]:
        format_span_tree(ex)


def test_fleet_record_error_naming(model):
    fl = _fleet(model, num_replicas=2, transport=_lossless(seed=2))
    fl.submit(_prompt(5), 3)
    fl.run()
    good = fl.fleet_record()
    validate_fleet_record(good)
    with pytest.raises(ValueError, match="must be a dict"):
        validate_fleet_record([])
    with pytest.raises(ValueError, match="unknown fleet record schema"):
        validate_fleet_record(dict(good, schema="paddle-tpu/nope/v9"))
    bad = dict(good)
    del bad["router"]
    with pytest.raises(ValueError, match="missing key 'router'"):
        validate_fleet_record(bad)
    with pytest.raises(ValueError, match="key 'exchanges' must be list"):
        validate_fleet_record(dict(good, exchanges={}))
    # a corrupt BUNDLED record is named by replica index
    broken = dict(good, replicas=[{}] + good["replicas"][1:])
    with pytest.raises(ValueError, match="fleet record replica 0:"):
        validate_fleet_record(broken)
    with pytest.raises(ValueError, match="exchange 0 is not a span"):
        validate_fleet_record(dict(good, exchanges=[{"span": "x"}]))
    with pytest.raises(ValueError, match="alert 0 missing rule/replica"):
        validate_fleet_record(dict(good, alerts=[{"rule": "r"}]))


def test_replica_down_auto_dumps_fleet_record(model, tmp_path):
    path = tmp_path / "auto.json"
    inj = FaultInjector().arm("replica_down", rid=1, step=2)
    fl = _fleet(model, num_replicas=2, injector=inj,
                transport=_lossless(seed=3),
                fleet_record_path=str(path))
    for i in range(2):
        fl.submit(_prompt(5, seed=i), 3)
    fl.run()
    assert path.exists()
    rec = validate_fleet_record(json.loads(path.read_text()))
    assert rec["reason"] == "replica_down: replica 1"
    assert rec["router"]["down"] == [1]
    # no path configured -> the record is still kept in memory
    fl2 = _fleet(model, num_replicas=2,
                 injector=FaultInjector().arm("replica_down", rid=1,
                                              step=2),
                 transport=_lossless(seed=3))
    for i in range(2):
        fl2.submit(_prompt(5, seed=i), 3)
    fl2.run()
    assert fl2.last_fleet_record is not None
    assert fl2.last_fleet_record["reason"] == "replica_down: replica 1"


def test_chaos_invariant_auto_dumps_fleet_record(model, tmp_path):
    # rigged failure: a drain deadline the soak cannot meet
    path = tmp_path / "chaos.json"
    with pytest.raises(ChaosInvariantError, match="failed to drain"):
        soak(model, ChaosConfig(seed=0, max_steps=2, horizon=2,
                                fleet_record_path=str(path)))
    rec = validate_fleet_record(json.loads(path.read_text()))
    assert rec["reason"] == "chaos_invariant"
    assert len(rec["replicas"]) == 2
    # the soak CLI names the dump in its FAIL line (rc 1)
    import sys
    sys.path.insert(0, "tools")
    try:
        import chaos_soak
    finally:
        sys.path.pop(0)
    import paddle_tpu.serving.chaos as chaos_mod

    def rigged(model_, cfg):
        return soak(model_, ChaosConfig(
            seed=cfg.seed, max_steps=2, horizon=2,
            fleet_record_path=cfg.fleet_record_path))
    orig = chaos_mod.soak
    chaos_mod.soak = rigged
    try:
        rc = chaos_soak.main(["--seeds", "1",
                              "--fleet-record-dir", str(tmp_path)])
    finally:
        chaos_mod.soak = orig
    assert rc == 1
    validate_fleet_record(json.loads(
        (tmp_path / "chaos_fleet_record_seed0.json").read_text()))


# ---------------------------------------------------------------- CLI
def test_cli_fleet_record_views(lossy_fleet, tmp_path, capsys):
    from paddle_tpu.obs.__main__ import main as obs_main

    fl = lossy_fleet
    path = tmp_path / "fleet.json"
    fl.dump_fleet_record(path)
    # default view: the roll-up table; manual dump with no alerts -> 0
    assert obs_main(["--fleet-record", str(path)]) == 0
    out = capsys.readouterr().out
    assert "replica" in out and "breakers:" in out
    # --span renders every tree the ring kept for that rid
    rec = next(r for r in fl.scope.records() if r["rid"] is not None)
    assert obs_main(["--fleet-record", str(path),
                     "--span", str(rec["rid"])]) == 0
    out = capsys.readouterr().out
    assert f"span {rec['span']}" in out
    # bad rid: rc 2 naming the retained rids
    assert obs_main(["--fleet-record", str(path),
                     "--span", "424242"]) == 2
    assert "retained rids" in capsys.readouterr().out
    # --prometheus over the dump: the merged exposition
    assert obs_main(["--fleet-record", str(path),
                     "--prometheus"]) == 0
    assert 'replica="1"' in capsys.readouterr().out
    # bad path / contextless --span: rc 2 with a message
    assert obs_main(["--fleet-record", str(path) + ".nope"]) == 2
    assert "cannot read fleet record" in capsys.readouterr().out
    assert obs_main(["--span", "3"]) == 2
    assert "--fleet-record" in capsys.readouterr().out
    # flight-record-only views refuse the cluster input loudly
    assert obs_main(["--fleet-record", str(path), "--journey", "3"]) == 2
    assert "--flight-record" in capsys.readouterr().out


# ---------------------------------------------------------- off switch
def test_fleetscope_off_surfaces_quiet_and_v1_frames(model):
    fl = _fleet(model, num_replicas=2, transport=_lossless(seed=4),
                fleetscope=False)
    fl.submit(_prompt(5), 3)
    fl.run()
    assert fl.scope is None
    assert fl.spans(0) is None
    assert fl.transport.last.span is None  # frames went out span-less
    assert all("span" not in h for j in fl.journey_dump()
               for h in j["hops"])
    rec = fl.fleet_record()  # the recorder still works, ring empty
    assert rec["exchanges"] == []
    validate_fleet_record(rec)


def test_fleetscope_on_is_sync_free_and_compile_stable(model):
    def run(on):
        fl = _fleet(model, num_replicas=2, transport=_lossless(seed=6),
                    fleetscope=on)
        rids = [fl.submit(_prompt(5 + i % 3, seed=i), 4)
                for i in range(4)]
        with SyncTally() as tally:
            outs = fl.run()
        return ([outs[r] for r in rids], tally.count,
                [dict(eng.compile_counts) for eng in fl.replicas])
    on_out, on_tally, on_compiles = run(True)
    off_out, off_tally, off_compiles = run(False)
    for a, b in zip(on_out, off_out):
        assert np.array_equal(a, b)  # outputs: bit-identical
    assert on_tally == off_tally  # device syncs: identical
    assert on_compiles == off_compiles  # traces: identical
