"""nn.utils reparameterizations + distributed.utils cluster model
(reference: nn/utils/{weight_norm,spectral_norm}_hook.py,
transform_parameters.py; distributed/utils.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_weight_norm_and_remove():
    paddle.seed(0)
    lin = paddle.nn.Linear(4, 3)
    w_before = lin.weight.numpy().copy()
    paddle.nn.utils.weight_norm(lin, "weight", dim=0)
    assert "weight_g" in dict(lin.named_parameters())
    assert "weight" not in lin._parameters
    x = paddle.to_tensor(np.random.rand(2, 4).astype("float32"))
    out1 = lin(x)
    # forward reproduces the original weight (g initialized to ||v||)
    np.testing.assert_allclose(
        out1.numpy(),
        x.numpy() @ w_before + lin.bias.numpy(), rtol=1e-5, atol=1e-5)
    # v and g are the trainables now
    loss = paddle.sum(out1)
    loss.backward()
    assert lin.weight_g.grad is not None and lin.weight_v.grad is not None
    paddle.nn.utils.remove_weight_norm(lin, "weight")
    assert "weight" in lin._parameters
    np.testing.assert_allclose(lin.weight.numpy(), w_before, rtol=1e-5,
                               atol=1e-6)


def test_spectral_norm_hook():
    paddle.seed(0)
    lin = paddle.nn.Linear(6, 4)
    paddle.nn.utils.spectral_norm(lin, "weight", n_power_iterations=20)
    lin(paddle.to_tensor(np.random.rand(2, 6).astype("float32")))
    s = np.linalg.svd(lin.weight.numpy(), compute_uv=False)
    assert s[0] == pytest.approx(1.0, rel=5e-2)


def test_parameters_vector_roundtrip():
    lin = paddle.nn.Linear(3, 2)
    vec = paddle.nn.utils.parameters_to_vector(list(lin.parameters()))
    assert int(vec.shape[0]) == 3 * 2 + 2
    doubled = paddle.scale(vec, scale=2.0)
    paddle.nn.utils.vector_to_parameters(doubled, list(lin.parameters()))
    np.testing.assert_allclose(
        paddle.nn.utils.parameters_to_vector(
            list(lin.parameters())).numpy(), doubled.numpy(), rtol=1e-6)


def test_distributed_utils_cluster_model(tmp_path):
    import paddle_tpu.distributed.utils as du

    cluster, pod = du.get_cluster(
        ["10.0.0.1", "10.0.0.2"], "10.0.0.2",
        [["10.0.0.1:9000", "10.0.0.1:9001"],
         ["10.0.0.2:9000", "10.0.0.2:9001"]])
    assert cluster.trainers_nranks() == 4
    assert pod.rank == 1 and pod.trainers[0].rank == 2
    assert cluster.get_pod_by_id(0).addr == "10.0.0.1"
    assert len(du.find_free_ports(2)) == 2
    h = du.Hdfs()
    assert not h.is_valid()


@pytest.mark.slow
def test_start_and_watch_local_trainers(tmp_path):
    import time

    import paddle_tpu.distributed.utils as du

    cluster, pod = du.get_cluster(
        ["127.0.0.1"], "127.0.0.1", [["127.0.0.1:9100", "127.0.0.1:9101"]])
    script = tmp_path / "w.py"
    script.write_text("import os\nprint('rank', os.environ['PADDLE_TRAINER_ID'])\n")
    procs = du.start_local_trainers(cluster, pod, str(script), [],
                                    log_dir=str(tmp_path))
    while du.watch_local_trainers(procs, 2):
        time.sleep(0.2)
    logs = sorted(p.name for p in tmp_path.glob("workerlog.*"))
    assert logs == ["workerlog.0", "workerlog.1"]


def test_prim2orig_identity():
    from paddle_tpu.incubate.autograd import orig2prim, prim2orig

    assert prim2orig(None) is None and orig2prim("b") == "b"


def test_bilinear_initializer_kernel():
    w = paddle.nn.initializer.Bilinear()((1, 1, 4, 4))
    k = np.asarray(w)[0, 0]
    np.testing.assert_allclose(k, k.T, rtol=1e-6)  # separable symmetric
    assert k.max() == k[1, 1] or k.max() == k[2, 2]


def test_remove_weight_norm_then_train():
    """Review regression: after removal the restored parameter must be the
    tensor forward uses (the stale hook attribute must not shadow it)."""
    paddle.seed(1)
    lin = paddle.nn.Linear(4, 3)
    paddle.nn.utils.weight_norm(lin, "weight")
    paddle.nn.utils.remove_weight_norm(lin, "weight")
    assert lin.weight is lin._parameters["weight"]
    opt = paddle.optimizer.SGD(learning_rate=0.5,
                               parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    before = lin(x).numpy().copy()
    loss = paddle.sum(lin(x))
    loss.backward()
    opt.step()
    opt.clear_grad()
    after = lin(x).numpy()
    assert not np.allclose(before, after), "updates must reach forward"


def test_weight_norm_negative_dim_is_real_axis():
    """Review regression: dim=-1 is the LAST axis, not the dim=None
    whole-tensor sentinel — g must have per-slice shape."""
    lin = paddle.nn.Linear(4, 3)
    paddle.nn.utils.weight_norm(lin, "weight", dim=-1)
    assert int(np.prod(lin.weight_g.shape)) == 3  # one g per output column
    lin2 = paddle.nn.Linear(4, 3)
    paddle.nn.utils.weight_norm(lin2, "weight", dim=None)
    assert int(np.prod(lin2.weight_g.shape)) == 1  # whole-tensor norm


def test_set_global_initializer_takes_effect():
    """Review regression: set_global_initializer must actually drive
    parameter creation."""
    paddle.nn.initializer.set_global_initializer(
        paddle.nn.initializer.Constant(0.25),
        paddle.nn.initializer.Constant(-1.0))
    try:
        lin = paddle.nn.Linear(3, 2)
        np.testing.assert_allclose(lin.weight.numpy(), 0.25)
        np.testing.assert_allclose(lin.bias.numpy(), -1.0)
    finally:
        paddle.nn.initializer.set_global_initializer(None)
    lin2 = paddle.nn.Linear(3, 2)
    assert not np.allclose(lin2.weight.numpy(), 0.25)
